"""Neural-network ops.

Reference: src/operator/nn/ — convolution.cc (ConvolutionParam),
fully_connected.cc, pooling.cc, batch_norm.cc, layer_norm.cc, dropout-inl.h,
softmax.cc, activation.cc, leaky_relu.cc; cuDNN paths in
src/operator/nn/cudnn/.

TPU-native: conv → `lax.conv_general_dilated` (MXU-tiled by XLA, replacing
cuDNN algo selection); pooling → `lax.reduce_window`; norms/softmax →
jnp compositions that XLA fuses into the surrounding matmuls.  MXNet layout
convention (NCHW / NCW / NCDHW) is preserved at the API; XLA relayouts
internally for the MXU so no NHWC surface change is needed.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register


def _tup(v, n):
    if v is None:
        return (0,) * n
    if isinstance(v, int):
        return (v,) * n
    v = tuple(v)
    return v if len(v) == n else v + v[-1:] * (n - len(v))


# ---------------------------------------------------------------------------
# FullyConnected
# ---------------------------------------------------------------------------


@register("FullyConnected", aliases=["fully_connected"])
def _fully_connected(data, weight, bias=None, num_hidden=None, no_bias=False,
                     flatten=True):
    x = data.reshape(data.shape[0], -1) if flatten and data.ndim > 2 else data
    # weight layout: (num_hidden, in_units) — reference keeps cuBLAS row-major
    out = jnp.matmul(x, weight.T)
    if bias is not None and not no_bias:
        out = out + bias
    return out


# ---------------------------------------------------------------------------
# Convolution — MXNet NCHW layout; kernel layout OIHW
# ---------------------------------------------------------------------------

_CONV_DIMS = {1: ("NCH", "OIH", "NCH"), 2: ("NCHW", "OIHW", "NCHW"),
              3: ("NCDHW", "OIDHW", "NCDHW")}


@register("Convolution", aliases=["convolution"])
def _convolution(data, weight, bias=None, kernel=None, stride=None, dilate=None,
                 pad=None, num_filter=None, num_group=1, no_bias=False,
                 cudnn_tune=None, cudnn_off=False, workspace=1024, layout=None):
    n = len(kernel)
    stride = _tup(stride or 1, n)
    dilate = _tup(dilate or 1, n)
    pad = _tup(pad, n)
    spatial = "DHW"[-n:] if n != 2 else "HW"
    lhs_spec = "NC" + spatial
    rhs_spec = "OI" + spatial
    dn = lax.conv_dimension_numbers(data.shape, weight.shape,
                                    (lhs_spec, rhs_spec, lhs_spec))
    # no preferred_element_type: its transpose rule rejects the mixed
    # fp32-cotangent/bf16-operand combo under grad, and TPU bf16 convs
    # already accumulate in fp32 on the MXU
    out = lax.conv_general_dilated(
        data, weight, window_strides=stride,
        padding=[(p, p) for p in pad],
        rhs_dilation=dilate, dimension_numbers=dn,
        feature_group_count=num_group)
    out = out.astype(data.dtype)
    if bias is not None and not no_bias:
        out = out + bias.reshape((1, -1) + (1,) * n)
    return out


@register("Deconvolution", aliases=["deconvolution"])
def _deconvolution(data, weight, bias=None, kernel=None, stride=None,
                   dilate=None, pad=None, adj=None, num_filter=None,
                   num_group=1, no_bias=True, target_shape=None,
                   cudnn_tune=None, cudnn_off=False, workspace=1024, layout=None):
    n = len(kernel)
    stride = _tup(stride or 1, n)
    dilate = _tup(dilate or 1, n)
    pad = _tup(pad, n)
    adj = _tup(adj or 0, n)
    spatial = "DHW"[-n:] if n != 2 else "HW"
    lhs_spec = "NC" + spatial
    # weight layout for Deconvolution is (in, out/g, *kernel) = IOHW
    rhs_spec = "IO" + spatial
    dn = lax.conv_dimension_numbers(data.shape, weight.shape,
                                    (lhs_spec, rhs_spec, lhs_spec))
    # transposed conv: pad by effective-kernel-1 minus user pad, and run
    # the SPATIALLY FLIPPED kernel — Deconvolution is the transpose of
    # correlation, which this dilated-conv emulation only reproduces with
    # the flip (caught by the torch-oracle parity lane)
    weight = jnp.flip(weight, axis=tuple(range(2, 2 + n)))
    if num_group > 1:
        # (in, out/g, *k) -> (in/g, out, *k): lax feature_group_count
        # wants per-group input channels and GROUP-MAJOR output channels
        cin, og = weight.shape[0], weight.shape[1]
        w = weight.reshape((num_group, cin // num_group, og)
                           + tuple(kernel))
        weight = jnp.moveaxis(w, 0, 1).reshape(
            (cin // num_group, og * num_group) + tuple(kernel))
    eff = [(k - 1) * d + 1 for k, d in zip(kernel, dilate)]
    pads = [(e - 1 - p, e - 1 - p + a) for e, p, a in zip(eff, pad, adj)]
    out = lax.conv_general_dilated(
        data, weight, window_strides=(1,) * n, padding=pads,
        lhs_dilation=stride, rhs_dilation=dilate, dimension_numbers=dn,
        feature_group_count=num_group)
    if bias is not None and not no_bias:
        out = out + bias.reshape((1, -1) + (1,) * n)
    return out


# ---------------------------------------------------------------------------
# Pooling
# ---------------------------------------------------------------------------


@register("Pooling", aliases=["pooling"])
def _pooling(data, kernel=None, pool_type="max", global_pool=False,
             stride=None, pad=None, pooling_convention="valid",
             count_include_pad=True, cudnn_off=False, layout=None, p_value=2):
    n = data.ndim - 2
    if global_pool:
        axes = tuple(range(2, data.ndim))
        if pool_type == "max":
            return jnp.max(data, axis=axes, keepdims=True)
        return jnp.mean(data, axis=axes, keepdims=True)
    kernel = _tup(kernel, n)
    stride = _tup(stride or kernel, n)
    pad = _tup(pad, n)
    window = (1, 1) + kernel
    strides = (1, 1) + stride
    if pooling_convention == "full":
        # ceil-mode: pad right enough that ceil division is covered
        pads = [(0, 0), (0, 0)]
        for i in range(n):
            in_sz = data.shape[2 + i]
            out_sz = -(-(in_sz + 2 * pad[i] - kernel[i]) // stride[i]) + 1
            needed = (out_sz - 1) * stride[i] + kernel[i] - in_sz
            pads.append((pad[i], max(needed - pad[i], pad[i])))
    else:
        pads = [(0, 0), (0, 0)] + [(p, p) for p in pad]
    if pool_type == "max":
        init = -jnp.inf if jnp.issubdtype(data.dtype, jnp.floating) else jnp.iinfo(data.dtype).min
        return lax.reduce_window(data, init, lax.max, window, strides, pads)
    if pool_type in ("avg", "sum"):
        summed = lax.reduce_window(data, 0.0, lax.add, window, strides, pads)
        if pool_type == "sum":
            return summed
        if count_include_pad:
            denom = 1
            for k in kernel:
                denom *= k
            return summed / denom
        ones = jnp.ones_like(data)
        counts = lax.reduce_window(ones, 0.0, lax.add, window, strides, pads)
        return summed / counts
    if pool_type == "lp":
        powed = lax.reduce_window(jnp.abs(data) ** p_value, 0.0, lax.add,
                                  window, strides, pads)
        return powed ** (1.0 / p_value)
    raise ValueError("bad pool_type %r" % pool_type)


@register("AdaptiveAvgPooling2D", aliases=["_contrib_AdaptiveAvgPooling2D"])
def _adaptive_avg_pool(data, output_size=1):
    os = _tup(output_size, 2)
    n, c, h, w = data.shape
    # reduce via mean over equal bins (exact when divisible; BASELINE nets are)
    x = data.reshape(n, c, os[0], h // os[0], os[1], w // os[1])
    return jnp.mean(x, axis=(3, 5))


@register("BilinearResize2D", aliases=["_contrib_BilinearResize2D"])
def _bilinear_resize(data, height=None, width=None, scale_height=None,
                     scale_width=None, mode="size"):
    n, c, h, w = data.shape
    th = height or int(h * scale_height)
    tw = width or int(w * scale_width)
    return jax.image.resize(data, (n, c, th, tw), method="linear")


@register("UpSampling")
def _upsampling(data, scale=2, sample_type="nearest", num_args=1):
    n, c, h, w = data.shape
    if sample_type == "nearest":
        return jnp.repeat(jnp.repeat(data, scale, axis=2), scale, axis=3)
    return jax.image.resize(data, (n, c, h * scale, w * scale), method="linear")


# ---------------------------------------------------------------------------
# Activations
# ---------------------------------------------------------------------------


@register("Activation", aliases=["activation"])
def _activation(data, act_type="relu"):
    if act_type == "relu":
        return jax.nn.relu(data)
    if act_type == "sigmoid":
        return jax.nn.sigmoid(data)
    if act_type == "tanh":
        return jnp.tanh(data)
    if act_type == "softrelu":
        return jax.nn.softplus(data)
    if act_type == "softsign":
        return jax.nn.soft_sign(data)
    if act_type == "log_sigmoid":
        return jax.nn.log_sigmoid(data)
    if act_type == "mish":
        return data * jnp.tanh(jax.nn.softplus(data))
    raise ValueError("bad act_type %r" % act_type)


@register("LeakyReLU", aliases=["leaky_relu", "_npx_leaky_relu"])
def _leaky_relu(data, gamma=None, act_type="leaky", slope=0.25,
                lower_bound=0.125, upper_bound=0.334):
    if act_type == "leaky":
        return jnp.where(data >= 0, data, slope * data)
    if act_type == "prelu":
        g = gamma.reshape((1, -1) + (1,) * (data.ndim - 2)) if data.ndim > 2 else gamma
        return jnp.where(data >= 0, data, g * data)
    if act_type == "elu":
        return jnp.where(data >= 0, data, slope * (jnp.exp(data) - 1.0))
    if act_type == "selu":
        alpha, lam = 1.6732632423543772, 1.0507009873554805
        return lam * jnp.where(data >= 0, data, alpha * (jnp.exp(data) - 1.0))
    if act_type == "gelu":
        return jax.nn.gelu(data, approximate=False)
    if act_type == "gelu_tanh":
        return jax.nn.gelu(data, approximate=True)
    if act_type == "rrelu":  # eval-mode deterministic
        return jnp.where(data >= 0, data, data * (lower_bound + upper_bound) / 2)
    raise ValueError("bad act_type %r" % act_type)


@register("softmax", aliases=["Softmax"])
def _softmax(data, axis=-1, temperature=None, length=None, use_length=False,
             dtype=None):
    x = data if temperature in (None, 1.0) else data / temperature
    if use_length and length is not None:
        steps = jnp.arange(x.shape[axis])
        bshape = [1] * x.ndim
        bshape[axis] = x.shape[axis]
        mask = steps.reshape(bshape) < length.reshape(
            [x.shape[0]] + [1] * (x.ndim - 1))
        x = jnp.where(mask, x, -jnp.inf)
    out = jax.nn.softmax(x, axis=axis)
    if use_length and length is not None:
        out = jnp.where(jnp.isnan(out), 0.0, out)
    return out.astype(dtype or data.dtype)


@register("log_softmax")
def _log_softmax(data, axis=-1, temperature=None, dtype=None):
    x = data if temperature in (None, 1.0) else data / temperature
    return jax.nn.log_softmax(x, axis=axis).astype(dtype or data.dtype)


@register("softmin")
def _softmin(data, axis=-1, temperature=None, dtype=None):
    return _softmax(-data, axis=axis, temperature=temperature, dtype=dtype)


@register("softmax_cross_entropy")
def _softmax_cross_entropy(data, label):
    logp = jax.nn.log_softmax(data, axis=-1)
    onehot = jax.nn.one_hot(label.astype(jnp.int32), data.shape[-1],
                            dtype=logp.dtype)
    return -jnp.sum(logp * onehot)


@register("SoftmaxOutput", aliases=["softmax_output"])
def _softmax_output(data, label, grad_scale=1.0, ignore_label=-1.0,
                    multi_output=False, use_ignore=False, preserve_shape=False,
                    normalization="null", out_grad=False, smooth_alpha=0.0):
    # forward is plain softmax; the custom grad (p - onehot) comes out of the
    # VJP of cross-entropy at the Gluon/Module loss level.
    return jax.nn.softmax(data, axis=1 if multi_output else -1)


# ---------------------------------------------------------------------------
# Normalization
# ---------------------------------------------------------------------------


@register("BatchNorm", aliases=["batch_norm"], num_outputs=3,
          aux_writeback={1: 3, 2: 4})
def _batch_norm(data, gamma, beta, moving_mean, moving_var, eps=1e-5,
                momentum=0.9, fix_gamma=True, use_global_stats=False,
                output_mean_var=False, axis=1, cudnn_off=False,
                min_calib_range=None, max_calib_range=None, training=True):
    if output_mean_var:
        raise NotImplementedError("BatchNorm(output_mean_var=True)")
    ax = axis % data.ndim
    red = tuple(i for i in range(data.ndim) if i != ax)
    bshape = [1] * data.ndim
    bshape[ax] = data.shape[ax]
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    if training and not use_global_stats:
        x32 = data.astype(jnp.float32)
        mean = jnp.mean(x32, axis=red)
        var = jnp.var(x32, axis=red)
        new_mean = momentum * moving_mean + (1.0 - momentum) * mean
        new_var = momentum * moving_var + (1.0 - momentum) * var
    else:
        mean, var = moving_mean, moving_var
        new_mean, new_var = moving_mean, moving_var
    inv = lax.rsqrt(var + eps).astype(data.dtype)
    out = (data - mean.reshape(bshape).astype(data.dtype)) * \
        (inv * g.astype(data.dtype)).reshape(bshape) + \
        beta.astype(data.dtype).reshape(bshape)
    return out, new_mean, new_var


@register("LayerNorm", aliases=["layer_norm"])
def _layer_norm(data, gamma, beta, axis=-1, eps=1e-5, output_mean_var=False):
    x32 = data.astype(jnp.float32)
    mean = jnp.mean(x32, axis=axis, keepdims=True)
    var = jnp.var(x32, axis=axis, keepdims=True)
    inv = lax.rsqrt(var + eps)
    norm = ((x32 - mean) * inv).astype(data.dtype)
    ax = axis % data.ndim
    bshape = [1] * data.ndim
    bshape[ax] = data.shape[ax]
    return norm * gamma.reshape(bshape) + beta.reshape(bshape)


@register("GroupNorm")
def _group_norm(data, gamma, beta, num_groups=1, eps=1e-5):
    n, c = data.shape[:2]
    rest = data.shape[2:]
    x = data.reshape(n, num_groups, c // num_groups, *rest).astype(jnp.float32)
    red = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=red, keepdims=True)
    var = jnp.var(x, axis=red, keepdims=True)
    norm = ((x - mean) * lax.rsqrt(var + eps)).reshape(data.shape).astype(data.dtype)
    bshape = (1, c) + (1,) * len(rest)
    return norm * gamma.reshape(bshape) + beta.reshape(bshape)


@register("InstanceNorm")
def _instance_norm(data, gamma, beta, eps=1e-3):
    red = tuple(range(2, data.ndim))
    x32 = data.astype(jnp.float32)
    mean = jnp.mean(x32, axis=red, keepdims=True)
    var = jnp.var(x32, axis=red, keepdims=True)
    norm = ((x32 - mean) * lax.rsqrt(var + eps)).astype(data.dtype)
    bshape = (1, data.shape[1]) + (1,) * (data.ndim - 2)
    return norm * gamma.reshape(bshape) + beta.reshape(bshape)


@register("RMSNorm")
def _rms_norm(data, gamma, axis=-1, eps=1e-6):
    x32 = data.astype(jnp.float32)
    ms = jnp.mean(jnp.square(x32), axis=axis, keepdims=True)
    return (x32 * lax.rsqrt(ms + eps)).astype(data.dtype) * gamma


# ---------------------------------------------------------------------------
# Dropout — takes an RNG key array as first input (plumbed by nd wrapper)
# ---------------------------------------------------------------------------


@register("Dropout", aliases=["dropout"], needs_rng=True)
def _dropout(key, data, p=0.5, mode="training", axes=(), cudnn_off=False,
             training=True):
    if not training or p <= 0.0:
        return data
    shape = list(data.shape)
    for a in axes:
        shape[a] = 1
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, keep, tuple(shape)).astype(data.dtype)
    return data * mask / keep


# ---------------------------------------------------------------------------
# Attention (reference: src/operator/contrib/transformer.cc interleaved
# self-attention ops).  Composition form; the Pallas flash path plugs in at
# mxnet_tpu/parallel/attention.py for long sequences.
# ---------------------------------------------------------------------------


@register("multi_head_attention")
def _mha(q, k, v, mask=None, num_heads=1, scaled=True, causal=False,
         units=None):  # units: carried for ONNX export (scale = sqrt(units/heads))
    # q,k,v: (B, T, H*D), mask broadcastable to (B, H, Tq, Tk);
    # hot path = Pallas flash attention on TPU
    from .attention import attention_core
    B, Tq, HD = q.shape
    D = HD // num_heads
    qh = q.reshape(B, Tq, num_heads, D).transpose(0, 2, 1, 3)
    kh = k.reshape(B, -1, num_heads, D).transpose(0, 2, 1, 3)
    vh = v.reshape(B, -1, num_heads, D).transpose(0, 2, 1, 3)
    scale = (1.0 / D ** 0.5) if scaled else 1.0
    out = attention_core(qh, kh, vh, scale=scale, causal=causal, mask=mask)
    return out.transpose(0, 2, 1, 3).reshape(B, Tq, HD)


# ---------------------------------------------------------------------------
# CTC loss (reference: src/operator/nn/ctc_loss.cc — warp-ctc/cuDNN CTC).
# TPU-native: the alpha (forward-variable) recursion is a lax.scan over time
# with log-sum-exp accumulation — static shapes, differentiable by autodiff,
# no cuDNN dependency.  Blank label index 0 (MXNet blank_label='first').
# ---------------------------------------------------------------------------


@register("CTCLoss", aliases=["ctc_loss", "_contrib_CTCLoss", "_contrib_ctc_loss"])
def _ctc_loss(pred, label, data_lengths=None, label_lengths=None,
              blank_label="first"):
    """pred: (T, N, C) activations (softmax applied internally, like the
    reference).  blank_label='first': blank = class 0, labels 1..C-1,
    0-padded.  blank_label='last': blank = class C-1, labels 0..C-2,
    padded with -1 — remapped onto the 'first' layout by rolling the class
    axis so one recursion serves both."""
    T, N, C = pred.shape
    L = label.shape[1]
    S = 2 * L + 1
    logp = jax.nn.log_softmax(pred.astype(jnp.float32), axis=-1)
    label = label.astype(jnp.int32)
    if blank_label == "last":
        # move blank channel C-1 to the front and shift labels up by one
        logp = jnp.concatenate([logp[..., -1:], logp[..., :-1]], axis=-1)
        if label_lengths is None:
            lab_len = jnp.sum((label >= 0).astype(jnp.int32), axis=1)
        else:
            lab_len = label_lengths.astype(jnp.int32)
        label = jnp.where(label >= 0, label + 1, 0)
    elif label_lengths is None:
        # infer: count of non-zero entries (0 is blank ⇒ cannot be a label)
        lab_len = jnp.sum((label != 0).astype(jnp.int32), axis=1)
    else:
        lab_len = label_lengths.astype(jnp.int32)
    if data_lengths is None:
        seq_len = jnp.full((N,), T, jnp.int32)
    else:
        seq_len = data_lengths.astype(jnp.int32)

    # extended sequence: blank, l1, blank, l2, ..., blank  → shape (N, S)
    ext = jnp.zeros((N, S), jnp.int32)
    ext = ext.at[:, 1::2].set(label)
    # transition-2 allowed where ext[s] != blank and ext[s] != ext[s-2]
    ext_shift2 = jnp.pad(ext[:, :-2], ((0, 0), (2, 0)), constant_values=-1)
    allow2 = (ext != 0) & (ext != ext_shift2)          # (N, S)

    neg_inf = jnp.float32(-1e30)
    pos = jnp.arange(S)
    alpha0 = jnp.where(pos[None, :] < 2,
                       jnp.take_along_axis(logp[0], ext, axis=-1), neg_inf)
    alpha0 = jnp.where((pos[None, :] == 1) & (lab_len[:, None] == 0),
                       neg_inf, alpha0)

    def step(alpha, t):
        a1 = jnp.pad(alpha[:, :-1], ((0, 0), (1, 0)), constant_values=neg_inf)
        a2 = jnp.pad(alpha[:, :-2], ((0, 0), (2, 0)), constant_values=neg_inf)
        a2 = jnp.where(allow2, a2, neg_inf)
        m = jnp.maximum(jnp.maximum(alpha, a1), a2)
        new = m + jnp.log(jnp.exp(alpha - m) + jnp.exp(a1 - m) +
                          jnp.exp(a2 - m))
        new = new + jnp.take_along_axis(logp[t], ext, axis=-1)
        # past each sequence's length the alphas freeze
        new = jnp.where((t < seq_len)[:, None], new, alpha)
        return new, None

    alpha, _ = lax.scan(step, alpha0, jnp.arange(1, T))
    # final: logsumexp of positions 2*lab_len and 2*lab_len - 1
    last = 2 * lab_len
    a_last = jnp.take_along_axis(alpha, last[:, None], axis=1)[:, 0]
    a_prev = jnp.take_along_axis(alpha, jnp.maximum(last - 1, 0)[:, None],
                                 axis=1)[:, 0]
    a_prev = jnp.where(lab_len > 0, a_prev, neg_inf)
    m = jnp.maximum(a_last, a_prev)
    ll = m + jnp.log(jnp.exp(a_last - m) + jnp.exp(a_prev - m))
    return -ll


@register("LinearRegressionOutput", aliases=["linear_regression_output"])
def _linear_regression_output(data, label, grad_scale=1.0):
    # forward is identity; Module.backward injects the implicit l2 loss
    # gradient (pred - label) the reference computes in-op
    # (src/operator/regression_output.cc)
    return data


@register("MAERegressionOutput", aliases=["mae_regression_output"])
def _mae_regression_output(data, label, grad_scale=1.0):
    return data


@register("LogisticRegressionOutput", aliases=["logistic_regression_output"])
def _logistic_regression_output(data, label, grad_scale=1.0):
    return jax.nn.sigmoid(data)
