"""Spatial vision ops: GridGenerator/BilinearSampler/SpatialTransformer,
ROIPooling/ROIAlign, Correlation, im2col/col2im.

Reference: src/operator/spatial_transformer.cc (SpatialTransformerParam),
src/operator/bilinear_sampler.cc, src/operator/grid_generator.cc,
src/operator/roi_pooling.cc (ROIPoolingParam), src/operator/contrib/
roi_align.cc (ROIAlignParam), src/operator/correlation.cc,
src/operator/nn/im2col.h.

TPU-native: gather-based formulations with static shapes.  Bilinear
sampling = 4 gathers + lerp (vectorized over the batch with vmap);
ROI ops vmap over rois.  No scatter in the forward paths, so VJPs are
XLA-generated scatter-adds.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register, alias


def _bilinear_gather(img, x, y):
    """img: (C, H, W); x, y: (...) pixel coords → (C, ...) samples; zero
    padding outside."""
    C, H, W = img.shape
    x0 = jnp.floor(x)
    y0 = jnp.floor(y)
    dx = x - x0
    dy = y - y0

    def at(xi, yi):
        inb = (xi >= 0) & (xi <= W - 1) & (yi >= 0) & (yi <= H - 1)
        xc = jnp.clip(xi, 0, W - 1).astype(jnp.int32)
        yc = jnp.clip(yi, 0, H - 1).astype(jnp.int32)
        v = img[:, yc, xc]            # (C, ...)
        return jnp.where(inb, v, 0.0)

    w00 = (1 - dx) * (1 - dy)
    w01 = dx * (1 - dy)
    w10 = (1 - dx) * dy
    w11 = dx * dy
    return (at(x0, y0) * w00 + at(x0 + 1, y0) * w01 +
            at(x0, y0 + 1) * w10 + at(x0 + 1, y0 + 1) * w11)


@register("GridGenerator", differentiable=True)
def _grid_generator(data, transform_type="affine", target_shape=(0, 0)):
    """affine: data (B, 6) → grid (B, 2, H, W) of normalized [-1,1] coords;
    warp: data (B, 2, H, W) flow added to the identity grid."""
    H, W = target_shape
    ys = jnp.linspace(-1.0, 1.0, H)
    xs = jnp.linspace(-1.0, 1.0, W)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    if transform_type == "affine":
        theta = data.reshape(-1, 2, 3)
        base = jnp.stack([gx.ravel(), gy.ravel(),
                          jnp.ones(H * W, data.dtype)], axis=0)  # (3, HW)
        out = jnp.einsum("bij,jk->bik", theta, base)             # (B, 2, HW)
        return out.reshape(-1, 2, H, W)
    # warp: normalized flow displacement
    B = data.shape[0]
    Hd, Wd = data.shape[2], data.shape[3]
    ys = jnp.linspace(-1.0, 1.0, Hd)
    xs = jnp.linspace(-1.0, 1.0, Wd)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    ident = jnp.stack([gx, gy], axis=0)[None]                    # (1,2,H,W)
    flow = jnp.stack([data[:, 0] * 2.0 / jnp.maximum(Wd - 1, 1),
                      data[:, 1] * 2.0 / jnp.maximum(Hd - 1, 1)], axis=1)
    return ident + flow


@register("BilinearSampler")
def _bilinear_sampler(data, grid, cudnn_off=False):
    """data: (B, C, H, W), grid: (B, 2, Ho, Wo) in [-1, 1] (x, y).
    Reference: src/operator/bilinear_sampler.cc."""
    H, W = data.shape[2], data.shape[3]
    gx = (grid[:, 0] + 1.0) * (W - 1) / 2.0      # (B, Ho, Wo)
    gy = (grid[:, 1] + 1.0) * (H - 1) / 2.0
    return jax.vmap(_bilinear_gather)(data, gx, gy)


@register("SpatialTransformer")
def _spatial_transformer(data, loc, target_shape=(0, 0),
                         transform_type="affine",
                         sampler_type="bilinear", cudnn_off=False):
    grid = _grid_generator(loc, transform_type="affine",
                           target_shape=tuple(target_shape))
    return _bilinear_sampler(data, grid)


@register("ROIPooling")
def _roi_pooling(data, rois, pooled_size=(7, 7), spatial_scale=1.0):
    """data: (B, C, H, W); rois: (R, 5) [batch_idx, x1, y1, x2, y2] in
    image coords.  Max-pool each roi into pooled_size bins (reference:
    src/operator/roi_pooling.cc). Gather-based: static bin sampling grid
    (2x2 samples/bin, max-reduced) — XLA-friendly, no data-dependent
    shapes."""
    PH, PW = pooled_size
    H, W = data.shape[2], data.shape[3]

    def one_roi(roi):
        b = roi[0].astype(jnp.int32)
        x1, y1, x2, y2 = roi[1] * spatial_scale, roi[2] * spatial_scale, \
            roi[3] * spatial_scale, roi[4] * spatial_scale
        rw = jnp.maximum(x2 - x1 + 1.0, 1.0)
        rh = jnp.maximum(y2 - y1 + 1.0, 1.0)
        bw, bh = rw / PW, rh / PH
        # 2 samples per bin per axis, max-reduced ≈ exact max for small bins
        sx = x1 + (jnp.arange(PW)[:, None] + jnp.asarray([0.25, 0.75])) * bw
        sy = y1 + (jnp.arange(PH)[:, None] + jnp.asarray([0.25, 0.75])) * bh
        xx = sx.reshape(-1)                       # (PW*2,)
        yy = sy.reshape(-1)                       # (PH*2,)
        gx, gy = jnp.meshgrid(xx, yy, indexing="xy")  # (PH*2, PW*2)
        xi = jnp.clip(jnp.round(gx), 0, W - 1).astype(jnp.int32)
        yi = jnp.clip(jnp.round(gy), 0, H - 1).astype(jnp.int32)
        img = data[b]                             # (C, H, W)
        vals = img[:, yi, xi]                     # (C, PH*2, PW*2)
        vals = vals.reshape(img.shape[0], PH, 2, PW, 2)
        return jnp.max(vals, axis=(2, 4))         # (C, PH, PW)

    return jax.vmap(one_roi)(rois)


@register("_contrib_ROIAlign")
def _roi_align(data, rois, pooled_size=(7, 7), spatial_scale=1.0,
               sample_ratio=2, position_sensitive=False, aligned=False):
    """Average-pooled bilinear sampling (reference: contrib/roi_align.cc)."""
    PH, PW = pooled_size
    S = max(int(sample_ratio), 1)
    off = 0.5 if aligned else 0.0

    def one_roi(roi):
        b = roi[0].astype(jnp.int32)
        x1 = roi[1] * spatial_scale - off
        y1 = roi[2] * spatial_scale - off
        x2 = roi[3] * spatial_scale - off
        y2 = roi[4] * spatial_scale - off
        rw = jnp.maximum(x2 - x1, 1.0 if not aligned else 1e-6)
        rh = jnp.maximum(y2 - y1, 1.0 if not aligned else 1e-6)
        bw, bh = rw / PW, rh / PH
        ix = (jnp.arange(S) + 0.5) / S
        sx = x1 + (jnp.arange(PW)[:, None] + ix) * bw   # (PW, S)
        sy = y1 + (jnp.arange(PH)[:, None] + ix) * bh   # (PH, S)
        gx = sx.reshape(-1)
        gy = sy.reshape(-1)
        mx_, my_ = jnp.meshgrid(gx, gy, indexing="xy")  # (PH*S, PW*S)
        vals = _bilinear_gather(data[b], mx_, my_)      # (C, PH*S, PW*S)
        vals = vals.reshape(vals.shape[0], PH, S, PW, S)
        return jnp.mean(vals, axis=(2, 4))

    return jax.vmap(one_roi)(rois)


alias("_contrib_ROIAlign", "ROIAlign", "roi_align")


@register("Correlation", num_outputs=1)
def _correlation(data1, data2, kernel_size=1, max_displacement=1, stride1=1,
                 stride2=1, pad_size=0, is_multiply=True):
    """FlowNet correlation layer (reference: src/operator/correlation.cc).
    Simplified: kernel_size=1 patch correlation over a (2d+1)² displacement
    window, expressed as shifted elementwise products (XLA fuses the whole
    window loop)."""
    d = max_displacement
    B, C, H, W = data1.shape
    p1 = jnp.pad(data1, ((0, 0), (0, 0), (d, d), (d, d)))
    p2 = jnp.pad(data2, ((0, 0), (0, 0), (d, d), (d, d)))
    outs = []
    for dy in range(-d, d + 1, stride2):
        for dx in range(-d, d + 1, stride2):
            a = p1[:, :, d:d + H, d:d + W]
            b = p2[:, :, d + dy:d + dy + H, d + dx:d + dx + W]
            prod = a * b if is_multiply else -jnp.abs(a - b)
            outs.append(jnp.mean(prod, axis=1))
    return jnp.stack(outs, axis=1)


@register("im2col")
def _im2col(data, kernel=(1, 1), stride=(1, 1), dilate=(1, 1), pad=(0, 0)):
    """Reference: src/operator/nn/im2col.h. (B, C, H, W) →
    (B, C*kh*kw, L) patches."""
    kh, kw = kernel
    patches = lax.conv_general_dilated_patches(
        data, filter_shape=(kh, kw), window_strides=tuple(stride),
        padding=[(pad[0], pad[0]), (pad[1], pad[1])],
        rhs_dilation=tuple(dilate),
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    B, CKK, Ho, Wo = patches.shape
    return patches.reshape(B, CKK, Ho * Wo)

@register("_contrib_RROIAlign", aliases=["RROIAlign"])
def _rroi_align(data, rois, pooled_size=(7, 7), spatial_scale=1.0,
                sampling_ratio=2):
    """Rotated ROI align (reference: src/operator/contrib/rroi_align.cc,
    RRPN-style rois).  rois: (N, 6) = [batch, cx, cy, w, h, angle_deg];
    bins sample a rotated grid around (cx, cy), bilinear, mean-reduced."""
    PH, PW = pooled_size
    S = max(int(sampling_ratio), 1)

    def one_roi(roi):
        b = roi[0].astype(jnp.int32)
        cx = roi[1] * spatial_scale
        cy = roi[2] * spatial_scale
        rw = jnp.maximum(roi[3] * spatial_scale, 1.0)
        rh = jnp.maximum(roi[4] * spatial_scale, 1.0)
        theta = roi[5] * jnp.pi / 180.0
        ix = (jnp.arange(S) + 0.5) / S
        # bin-local sample coords, centered on the box
        lx = ((jnp.arange(PW)[:, None] + ix) / PW - 0.5).reshape(-1) * rw
        ly = ((jnp.arange(PH)[:, None] + ix) / PH - 0.5).reshape(-1) * rh
        gx, gy = jnp.meshgrid(lx, ly, indexing="xy")    # (PH*S, PW*S)
        c, s = jnp.cos(theta), jnp.sin(theta)
        sx = cx + gx * c - gy * s
        sy = cy + gx * s + gy * c
        vals = _bilinear_gather(data[b], sx, sy)        # (C, PH*S, PW*S)
        vals = vals.reshape(vals.shape[0], PH, S, PW, S)
        return jnp.mean(vals, axis=(2, 4))

    return jax.vmap(one_roi)(rois)
