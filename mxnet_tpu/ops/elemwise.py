"""Elementwise + broadcast binary/unary ops.

Reference: src/operator/tensor/elemwise_binary_broadcast_op_basic.cc
(broadcast_add ...), elemwise_unary_op_basic.cc, src/operator/mxnet_op.h.
On TPU these all lower to single fused XLA HLO ops — no kernels to write;
the op registry entry IS the implementation (SURVEY.md §2.1 "Dense op
kernels" row).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

# ---------------------------------------------------------------------------
# broadcast binary — MXNet exposes elemwise_* (same-shape) and broadcast_*
# (numpy broadcasting); XLA doesn't care, so both alias one impl.
# ---------------------------------------------------------------------------

_BINARY = {
    "broadcast_add": jnp.add,
    "broadcast_sub": jnp.subtract,
    "broadcast_mul": jnp.multiply,
    "broadcast_div": jnp.divide,
    "broadcast_mod": jnp.mod,
    "broadcast_power": jnp.power,
    "broadcast_maximum": jnp.maximum,
    "broadcast_minimum": jnp.minimum,
    "broadcast_hypot": jnp.hypot,
    "arctan2": jnp.arctan2,
}

_BINARY_ALIASES = {
    "broadcast_add": ["elemwise_add", "_plus", "_add"],
    "broadcast_sub": ["elemwise_sub", "_minus", "_sub"],
    "broadcast_mul": ["elemwise_mul", "_mul"],
    "broadcast_div": ["elemwise_div", "_div"],
    "broadcast_mod": ["_mod"],
    "broadcast_power": ["_power", "pow"],
    "broadcast_maximum": ["_maximum", "maximum"],
    "broadcast_minimum": ["_minimum", "minimum"],
}

for _name, _fn in _BINARY.items():
    register(_name, _fn, aliases=_BINARY_ALIASES.get(_name, ()))

_COMPARE = {
    "broadcast_equal": jnp.equal,
    "broadcast_not_equal": jnp.not_equal,
    "broadcast_greater": jnp.greater,
    "broadcast_greater_equal": jnp.greater_equal,
    "broadcast_lesser": jnp.less,
    "broadcast_lesser_equal": jnp.less_equal,
    "broadcast_logical_and": jnp.logical_and,
    "broadcast_logical_or": jnp.logical_or,
    "broadcast_logical_xor": jnp.logical_xor,
}

for _name, _fn in _COMPARE.items():
    # MXNet comparison ops return float (1.0/0.0), not bool
    def _mk(f):
        def cmp(a, b):
            res = f(a, b)
            want = a.dtype if jnp.issubdtype(a.dtype, jnp.floating) else jnp.float32
            return res.astype(want)
        return cmp
    register(_name, _mk(_fn), differentiable=False,
             aliases=[_name.replace("broadcast_", "")] if _name.startswith("broadcast_") else ())

# ---------------------------------------------------------------------------
# unary
# ---------------------------------------------------------------------------

_UNARY = {
    "negative": jnp.negative,
    "abs": jnp.abs,
    "sign": jnp.sign,
    "floor": jnp.floor,
    "ceil": jnp.ceil,
    "round": jnp.round,
    "rint": jnp.rint,
    "trunc": jnp.trunc,
    "fix": jnp.trunc,
    "exp": jnp.exp,
    "expm1": jnp.expm1,
    "log": jnp.log,
    "log10": jnp.log10,
    "log2": jnp.log2,
    "log1p": jnp.log1p,
    "sqrt": jnp.sqrt,
    "cbrt": jnp.cbrt,
    "square": jnp.square,
    "reciprocal": jnp.reciprocal,
    "rsqrt": lax.rsqrt,
    "sin": jnp.sin,
    "cos": jnp.cos,
    "tan": jnp.tan,
    "arcsin": jnp.arcsin,
    "arccos": jnp.arccos,
    "arctan": jnp.arctan,
    "sinh": jnp.sinh,
    "cosh": jnp.cosh,
    "tanh": jnp.tanh,
    "arcsinh": jnp.arcsinh,
    "arccosh": jnp.arccosh,
    "arctanh": jnp.arctanh,
    "erf": lax.erf,
    "erfinv": lax.erf_inv,
    "gamma": lambda x: jnp.exp(lax.lgamma(x)),
    "gammaln": lax.lgamma,
    "digamma": lax.digamma,
    "sigmoid": jax.nn.sigmoid,
    "softsign": lambda x: x / (1.0 + jnp.abs(x)),
    "relu": jax.nn.relu,   # custom grad: 0 at x==0, matching the reference
    "logical_not": lambda x: jnp.logical_not(x.astype(bool)).astype(x.dtype if jnp.issubdtype(x.dtype, jnp.floating) else jnp.float32),
    "identity": lambda x: x,
}

_UNARY_NONDIFF = {"sign", "floor", "ceil", "round", "rint", "trunc", "fix",
                  "logical_not"}

for _name, _fn in _UNARY.items():
    _al = {"identity": ["_copy", "_np_copy"],
           "gamma": ["_npx_gamma"]}.get(_name, [])
    register(_name, _fn, differentiable=_name not in _UNARY_NONDIFF,
             aliases=_al)


@register("clip")
def _clip(x, a_min=None, a_max=None):
    return jnp.clip(x, a_min, a_max)


@register("isnan", differentiable=False)
def _isnan(x):
    return jnp.isnan(x).astype(jnp.float32)


@register("isinf", differentiable=False)
def _isinf(x):
    return jnp.isinf(x).astype(jnp.float32)


@register("isfinite", differentiable=False)
def _isfinite(x):
    return jnp.isfinite(x).astype(jnp.float32)


@register("cast")
def _cast(x, dtype="float32"):
    d = jnp.bfloat16 if dtype == "bfloat16" else dtype
    return x.astype(d)


register("Cast", _cast)
register("amp_cast", _cast)


@register("where")
def _where(cond, a, b):
    return jnp.where(cond.astype(bool), a, b)


@register("smooth_l1")
def _smooth_l1(x, scalar=1.0):
    s2 = scalar * scalar
    absx = jnp.abs(x)
    return jnp.where(absx < 1.0 / s2, 0.5 * s2 * x * x, absx - 0.5 / s2)
