"""Long-tail ops: activations, bitwise, scalar-variant, sampling-free math.

Reference anchors: src/operator/leaky_relu.cc (LeakyReLU modes incl.
elu/selu/gelu via Activation), src/operator/tensor/elemwise_unary_op_basic.cc,
elemwise_binary_scalar_op*.cc (the _plus_scalar family), np elemwise tail,
src/operator/tensor/histogram.cc, src/operator/numpy/np_percentile_op.cc.

Everything is a one-line jnp/lax lowering — the value of this file is API
surface (MXNet name + signature + defaults), not kernels; XLA owns codegen.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register, alias

# ---------------------------------------------------------------------------
# activations tail
# ---------------------------------------------------------------------------


@register("gelu")
def _gelu(x, approximation="none"):
    return jax.nn.gelu(x, approximate=(approximation == "tanh"))


@register("selu")
def _selu(x):
    return jax.nn.selu(x)


@register("elu")
def _elu(x, alpha=1.0):
    return jax.nn.elu(x, alpha=alpha)


@register("softrelu", aliases=["softplus"])
def _softrelu(x):
    return jax.nn.softplus(x)


@register("hard_sigmoid")
def _hard_sigmoid(x, alpha=0.2, beta=0.5):
    return jnp.clip(alpha * x + beta, 0.0, 1.0)


@register("hard_swish")
def _hard_swish(x):
    return x * jnp.clip(x / 6.0 + 0.5, 0.0, 1.0)


@register("silu", aliases=["swish"])
def _silu(x):
    return x * jax.nn.sigmoid(x)


@register("mish")
def _mish(x):
    return x * jnp.tanh(jax.nn.softplus(x))


@register("prelu")
def _prelu(x, gamma):
    return jnp.where(x >= 0, x, gamma * x)


@register("rrelu", needs_rng=True)
def _rrelu(key, x, lower_bound=0.125, upper_bound=0.334, training=True):
    if training:
        slope = jax.random.uniform(key, x.shape, x.dtype,
                                   lower_bound, upper_bound)
    else:
        slope = (lower_bound + upper_bound) / 2.0
    return jnp.where(x >= 0, x, slope * x)


@register("log_sigmoid")
def _log_sigmoid(x):
    return jax.nn.log_sigmoid(x)


@register("masked_softmax")
def _masked_softmax(data, mask=None, axis=-1, temperature=1.0,
                    normalize=True):
    x = data / temperature
    if mask is not None:
        x = jnp.where(mask.astype(bool), x, -jnp.inf)
    out = jax.nn.softmax(x, axis=axis)
    if mask is not None:
        out = jnp.where(mask.astype(bool), out, 0.0)
    return out


@register("masked_log_softmax")
def _masked_log_softmax(data, mask=None, axis=-1, temperature=1.0):
    x = data / temperature
    if mask is not None:
        x = jnp.where(mask.astype(bool), x, -jnp.inf)
    return jax.nn.log_softmax(x, axis=axis)


# ---------------------------------------------------------------------------
# bitwise / integer
# ---------------------------------------------------------------------------

@register("bitwise_and")
def _bitwise_and(a, b):
    return jnp.bitwise_and(a, b)


@register("bitwise_or")
def _bitwise_or(a, b):
    return jnp.bitwise_or(a, b)


@register("bitwise_xor")
def _bitwise_xor(a, b):
    return jnp.bitwise_xor(a, b)


@register("bitwise_not", aliases=["invert"])
def _bitwise_not(a):
    return jnp.bitwise_not(a)


@register("bitwise_left_shift", aliases=["left_shift"])
def _left_shift(a, b):
    return jnp.left_shift(a, b)


@register("bitwise_right_shift", aliases=["right_shift"])
def _right_shift(a, b):
    return jnp.right_shift(a, b)


# ---------------------------------------------------------------------------
# math tail
# ---------------------------------------------------------------------------

@register("radians")
def _radians(x):
    return jnp.radians(x)


@register("degrees")
def _degrees(x):
    return jnp.degrees(x)


@register("rcbrt")
def _rcbrt(x):
    return 1.0 / jnp.cbrt(x)


@register("erfc")
def _erfc(x):
    return jax.scipy.special.erfc(x)


@register("gammainc")
def _gammainc(a, x):
    return jax.scipy.special.gammainc(a, x)


@register("gammaincc")
def _gammaincc(a, x):
    return jax.scipy.special.gammaincc(a, x)


@register("polygamma")
def _polygamma(x, n=0):
    return jax.scipy.special.polygamma(n, x)


@register("logaddexp")
def _logaddexp(a, b):
    return jnp.logaddexp(a, b)


@register("logsumexp")
def _logsumexp(data, axis=None, keepdims=False):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return jax.scipy.special.logsumexp(data, axis=ax, keepdims=keepdims)


@register("ldexp")
def _ldexp(a, b):
    return a * jnp.exp2(b)


@register("fmod")
def _fmod(a, b):
    return jnp.fmod(a, b)


@register("heaviside")
def _heaviside(a, b):
    # numpy: heaviside(nan, h) is nan; jnp.heaviside returns h there
    out = jnp.heaviside(a, b)
    if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating):
        out = jnp.where(jnp.isnan(a), jnp.nan, out)
    return out


@register("copysign")
def _copysign(a, b):
    return jnp.copysign(a, b)


@register("nextafter")
def _nextafter(a, b):
    return jnp.nextafter(a, b)


@register("hypot")
def _hypot(a, b):
    return jnp.hypot(a, b)


@register("sinc")
def _sinc(x):
    return jnp.sinc(x)


@register("i0")
def _i0(x):
    return jax.scipy.special.i0(x)


@register("trace_op", aliases=["trace"])
def _trace(data, offset=0, axis1=0, axis2=1):
    return jnp.trace(data, offset=offset, axis1=axis1, axis2=axis2)


@register("cross")
def _cross(a, b, axisa=-1, axisb=-1, axisc=-1):
    return jnp.cross(a, b, axisa=axisa, axisb=axisb, axisc=axisc)


@register("kron")
def _kron(a, b):
    return jnp.kron(a, b)


@register("interp")
def _interp(x, xp, fp, left=None, right=None):
    return jnp.interp(x, xp, fp, left=left, right=right)


@register("digitize", differentiable=False)
def _digitize(x, bins, right=False):
    return jnp.digitize(x, bins, right=right)


@register("lerp")
def _lerp(start, end, weight):
    return start + weight * (end - start)


# ---------------------------------------------------------------------------
# reductions / stats tail
# ---------------------------------------------------------------------------

@register("quantile")
def _quantile(a, q, axis=None, keepdims=False, interpolation="linear"):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return jnp.quantile(a, q, axis=ax, keepdims=keepdims,
                        method=interpolation)


@register("percentile")
def _percentile(a, q, axis=None, keepdims=False, interpolation="linear"):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return jnp.percentile(a, q, axis=ax, keepdims=keepdims,
                          method=interpolation)


@register("median")
def _median(a, axis=None, keepdims=False):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return jnp.median(a, axis=ax, keepdims=keepdims)


@register("std")
def _std(a, axis=None, ddof=0, keepdims=False):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return jnp.std(a, axis=ax, ddof=ddof, keepdims=keepdims)


@register("var")
def _var(a, axis=None, ddof=0, keepdims=False):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return jnp.var(a, axis=ax, ddof=ddof, keepdims=keepdims)


@register("ptp")
def _ptp(a, axis=None, keepdims=False):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return jnp.ptp(a, axis=ax, keepdims=keepdims)


@register("average")
def _average(a, weights=None, axis=None):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return jnp.average(a, axis=ax, weights=weights)


@register("histogram", aliases=["_histogram"],
          differentiable=False, num_outputs=2)
def _histogram(data, bin_cnt=10, range=None):
    """Reference: src/operator/tensor/histogram.cc. Static-shape: fixed
    bin_cnt; returns (counts, bin_edges)."""
    lo, hi = range if range is not None else (None, None)
    if lo is None:
        raise ValueError("histogram on TPU requires an explicit range= "
                         "(static shapes; the reference's auto-range needs "
                         "a host sync)")
    edges = jnp.linspace(lo, hi, bin_cnt + 1)
    idx = jnp.clip(((data - lo) / (hi - lo) * bin_cnt).astype(jnp.int32),
                   0, bin_cnt - 1)
    in_range = (data >= lo) & (data <= hi)
    counts = jnp.zeros((bin_cnt,), jnp.int32)
    counts = counts.at[idx.reshape(-1)].add(
        in_range.reshape(-1).astype(jnp.int32))
    return counts, edges


@register("nan_to_num")
def _nan_to_num(data, nan=0.0, posinf=None, neginf=None):
    return jnp.nan_to_num(data, nan=nan, posinf=posinf, neginf=neginf)


@register("cummax", differentiable=False)
def _cummax(a, axis=0):
    return lax.associative_scan(jnp.maximum, a, axis=axis)


@register("cummin", differentiable=False)
def _cummin(a, axis=0):
    return lax.associative_scan(jnp.minimum, a, axis=axis)


# ---------------------------------------------------------------------------
# indexing tail
# ---------------------------------------------------------------------------

@register("index_add")
def _index_add(data, index, value):
    return data.at[index.astype(jnp.int32)].add(value)


@register("index_update")
def _index_update(data, index, value):
    return data.at[index.astype(jnp.int32)].set(value)


@register("searchsorted", differentiable=False)
def _searchsorted(a, v, side="left"):
    return jnp.searchsorted(a, v, side=side)


@register("bincount", differentiable=False)
def _bincount(data, weights=None, minlength=0):
    if minlength <= 0:
        raise ValueError("bincount on TPU requires minlength= (static "
                         "output shape)")
    return jnp.bincount(data.astype(jnp.int32), weights=weights,
                        length=minlength)


@register("roll")
def _roll(data, shift=0, axis=None):
    sh = tuple(shift) if isinstance(shift, (list, tuple)) else shift
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return jnp.roll(data, sh, axis=ax)


@register("rot90")
def _rot90(data, k=1, axes=(0, 1)):
    return jnp.rot90(data, k=k, axes=tuple(axes))


@register("tril")
def _tril(data, k=0):
    return jnp.tril(data, k=k)


@register("triu")
def _triu(data, k=0):
    return jnp.triu(data, k=k)


@register("diagonal")
def _diagonal(data, offset=0, axis1=0, axis2=1):
    return jnp.diagonal(data, offset=offset, axis1=axis1, axis2=axis2)


@register("atleast_1d")
def _atleast_1d(x):
    return jnp.atleast_1d(x)


@register("atleast_2d")
def _atleast_2d(x):
    return jnp.atleast_2d(x)


@register("atleast_3d")
def _atleast_3d(x):
    return jnp.atleast_3d(x)


# ---------------------------------------------------------------------------
# windows / creation-style (static shape params)
# ---------------------------------------------------------------------------

@register("hanning", differentiable=False)
def _hanning(M=0, dtype="float32"):
    return jnp.hanning(M).astype(dtype)


@register("hamming", differentiable=False)
def _hamming(M=0, dtype="float32"):
    return jnp.hamming(M).astype(dtype)


@register("blackman", differentiable=False)
def _blackman(M=0, dtype="float32"):
    return jnp.blackman(M).astype(dtype)


# ---------------------------------------------------------------------------
# straight-through estimators (reference: src/operator/contrib/stes_op.cc)
# ---------------------------------------------------------------------------


@register("_contrib_round_ste", aliases=["round_ste"])
def _round_ste(data):
    """round() forward, identity gradient (quantization-aware training)."""
    return data + lax.stop_gradient(jnp.rint(data) - data)


@register("_contrib_sign_ste", aliases=["sign_ste"])
def _sign_ste(data):
    return data + lax.stop_gradient(jnp.sign(data) - data)
