"""mx.nd.image.* operators.

Reference: src/operator/image/image_random.cc + image_resize.cc
(_image_to_tensor, _image_normalize, _image_resize, _image_crop,
_image_flip_*, _image_adjust_lighting, _image_random_*) — the op-level
augmentation pipeline gluon.data.vision.transforms rides.

Layout: HWC or NHWC uint8/float input, like the reference.  Deterministic
ops are pure jnp; random_* draw through the registry's stateless rng
plumbing (needs_rng) so they are traceable under hybridized transforms.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register

__all__ = []

# ITU-R BT.601 luma weights (the reference's grayscale coefficients)
_LUMA = (0.299, 0.587, 0.114)


def _is_batch(x):
    return x.ndim == 4


@register("_image_to_tensor", aliases=["image_to_tensor"])
def _to_tensor(data):
    """HWC uint8 [0,255] -> CHW float32 [0,1] (reference: ToTensor)."""
    x = data.astype(jnp.float32) / 255.0
    if _is_batch(data):
        return x.transpose(0, 3, 1, 2)
    return x.transpose(2, 0, 1)


@register("_image_normalize", aliases=["image_normalize"])
def _normalize(data, mean=(0.0,), std=(1.0,)):
    """CHW float -> (x - mean) / std per channel (reference: Normalize)."""
    mean = jnp.asarray(mean, jnp.float32)
    std = jnp.asarray(std, jnp.float32)
    if _is_batch(data):
        return (data - mean.reshape(1, -1, 1, 1)) / std.reshape(1, -1, 1, 1)
    return (data - mean.reshape(-1, 1, 1)) / std.reshape(-1, 1, 1)


@register("_image_resize", aliases=["image_resize"])
def _resize(data, size=(0, 0), keep_ratio=False, interp=1):
    """HWC resize; size (w, h) like the reference."""
    if isinstance(size, int):
        size = (size, size)
    w, h = int(size[0]), int(size[1] if len(size) > 1 else size[0])
    method = "nearest" if interp == 0 else "linear"
    if _is_batch(data):
        out_shape = (data.shape[0], h, w, data.shape[3])
    else:
        out_shape = (h, w, data.shape[2])
    return jax.image.resize(data.astype(jnp.float32), out_shape,
                            method=method).astype(data.dtype)


@register("_image_crop", aliases=["image_crop"])
def _crop(data, x=0, y=0, width=1, height=1):
    if _is_batch(data):
        return data[:, y:y + height, x:x + width, :]
    return data[y:y + height, x:x + width, :]


@register("_image_flip_left_right", aliases=["image_flip_left_right"])
def _flip_lr(data):
    return jnp.flip(data, axis=-2)


@register("_image_flip_top_bottom", aliases=["image_flip_top_bottom"])
def _flip_tb(data):
    return jnp.flip(data, axis=-3)


@register("_image_adjust_lighting", aliases=["image_adjust_lighting"])
def _adjust_lighting(data, alpha=(0.0, 0.0, 0.0)):
    """AlexNet-style PCA lighting shift (reference: AdjustLighting)."""
    eigval = jnp.asarray([55.46, 4.794, 1.148], jnp.float32)
    eigvec = jnp.asarray([[-0.5675, 0.7192, 0.4009],
                          [-0.5808, -0.0045, -0.8140],
                          [-0.5836, -0.6948, 0.4203]], jnp.float32)
    alpha = jnp.asarray(alpha, jnp.float32)
    shift = (eigvec * alpha * eigval).sum(axis=1)
    return (data.astype(jnp.float32) + shift).astype(data.dtype)


def _blend(a, b, w):
    return (w * a.astype(jnp.float32)
            + (1.0 - w) * b.astype(jnp.float32))


def _grayscale(x):
    wts = jnp.asarray(_LUMA, jnp.float32)
    g = (x.astype(jnp.float32) * wts).sum(axis=-1, keepdims=True)
    return jnp.broadcast_to(g, x.shape)


def _brightness(x, w):
    return _blend(x, jnp.zeros_like(x, jnp.float32), w)


def _contrast(x, w):
    mean = _grayscale(x).mean()
    return _blend(x, jnp.full_like(x, mean, jnp.float32), w)


def _saturation(x, w):
    return _blend(x, _grayscale(x), w)


def _hue(x, w):
    """YIQ rotation (the reference's AdjustHue path)."""
    t_yiq = jnp.asarray([[0.299, 0.587, 0.114],
                         [0.596, -0.274, -0.321],
                         [0.211, -0.523, 0.311]], jnp.float32)
    t_rgb = jnp.asarray([[1.0, 0.956, 0.621],
                         [1.0, -0.272, -0.647],
                         [1.0, -1.107, 1.705]], jnp.float32)
    h = w * jnp.pi
    u, v = jnp.cos(h), jnp.sin(h)
    rot = jnp.asarray([[1.0, 0.0, 0.0],
                       [0.0, 0.0, 0.0],
                       [0.0, 0.0, 0.0]], jnp.float32)
    rot = rot.at[1, 1].set(u).at[1, 2].set(-v).at[2, 1].set(v).at[2, 2].set(u)
    m = t_rgb @ rot @ t_yiq
    return x.astype(jnp.float32) @ m.T


def _rand_w(key, frac):
    # clamp at 0: frac > 1 must brighten/flatten, never invert (the
    # reference samples jitter factors from [max(0, 1-frac), 1+frac])
    return jax.random.uniform(key, (), jnp.float32,
                              max(0.0, 1.0 - frac), 1.0 + frac)


@register("_image_random_brightness", aliases=["image_random_brightness"],
          differentiable=False, needs_rng=True)
def _random_brightness(key, data, min_factor=0.0, max_factor=0.0):
    w = jax.random.uniform(key, (), jnp.float32, min_factor, max_factor)
    return _brightness(data, w).astype(data.dtype)


@register("_image_random_contrast", aliases=["image_random_contrast"],
          differentiable=False, needs_rng=True)
def _random_contrast(key, data, min_factor=0.0, max_factor=0.0):
    w = jax.random.uniform(key, (), jnp.float32, min_factor, max_factor)
    return _contrast(data, w).astype(data.dtype)


@register("_image_random_saturation", aliases=["image_random_saturation"],
          differentiable=False, needs_rng=True)
def _random_saturation(key, data, min_factor=0.0, max_factor=0.0):
    w = jax.random.uniform(key, (), jnp.float32, min_factor, max_factor)
    return _saturation(data, w).astype(data.dtype)


@register("_image_random_hue", aliases=["image_random_hue"],
          differentiable=False, needs_rng=True)
def _random_hue(key, data, min_factor=0.0, max_factor=0.0):
    w = jax.random.uniform(key, (), jnp.float32, min_factor, max_factor)
    return _hue(data, w).astype(data.dtype)


@register("_image_random_color_jitter", aliases=["image_random_color_jitter"],
          differentiable=False, needs_rng=True)
def _random_color_jitter(key, data, brightness=0.0, contrast=0.0,
                         saturation=0.0, hue=0.0):
    kb, kc, ks, kh = jax.random.split(key, 4)
    x = data.astype(jnp.float32)
    if brightness > 0:
        x = _brightness(x, _rand_w(kb, brightness))
    if contrast > 0:
        x = _contrast(x, _rand_w(kc, contrast))
    if saturation > 0:
        x = _saturation(x, _rand_w(ks, saturation))
    if hue > 0:
        x = _hue(x, jax.random.uniform(kh, (), jnp.float32, -hue, hue))
    return x.astype(data.dtype)


@register("_image_random_lighting", aliases=["image_random_lighting"],
          differentiable=False, needs_rng=True)
def _random_lighting(key, data, alpha_std=0.05):
    alpha = jax.random.normal(key, (3,), jnp.float32) * alpha_std
    return _adjust_lighting(data, alpha)


@register("_image_random_flip_left_right",
          aliases=["image_random_flip_left_right"],
          differentiable=False, needs_rng=True)
def _random_flip_lr(key, data, p=0.5):
    return jnp.where(jax.random.bernoulli(key, p),
                     jnp.flip(data, axis=-2), data)


@register("_image_random_flip_top_bottom",
          aliases=["image_random_flip_top_bottom"],
          differentiable=False, needs_rng=True)
def _random_flip_tb(key, data, p=0.5):
    return jnp.where(jax.random.bernoulli(key, p),
                     jnp.flip(data, axis=-3), data)


# ---------------------------------------------------------------------------
# OpenCV-plugin parity ops (reference: plugin/opencv/cv_api.cc — _cvimread,
# _cvimdecode, _cvimresize, _cvcopyMakeBorder).  PIL plays OpenCV's role.
# ---------------------------------------------------------------------------


@register("_cvimdecode", aliases=["cvimdecode"], no_jit=True,
          differentiable=False)
def _cvimdecode(buf, flag=1, to_rgb=True):
    from .misc import _imdecode
    return _imdecode(buf, flag=flag, to_rgb=to_rgb)


@register("_cvimread", aliases=["cvimread"], no_jit=True,
          differentiable=False)
def _cvimread(filename="", flag=1, to_rgb=True):
    import numpy as np
    from PIL import Image
    if flag == 0:               # OpenCV IMREAD_GRAYSCALE
        arr = np.asarray(Image.open(filename).convert("L"), np.uint8)
        return jnp.asarray(arr[:, :, None])
    arr = np.asarray(Image.open(filename).convert("RGB"), np.uint8)
    if not to_rgb:              # OpenCV-native channel order is BGR
        arr = arr[:, :, ::-1]
    return jnp.asarray(arr.copy())


@register("_cvimresize", aliases=["cvimresize"], differentiable=False)
def _cvimresize(data, w=1, h=1, interp=1):
    method = "nearest" if interp == 0 else "linear"
    out_shape = (int(h), int(w), data.shape[2])
    return jax.image.resize(data.astype(jnp.float32), out_shape,
                            method=method).astype(data.dtype)


@register("_cvcopyMakeBorder", aliases=["copyMakeBorder_op"],
          differentiable=False)
def _cvcopy_make_border(data, top=0, bot=0, left=0, right=0, type=0,
                        value=0.0, values=()):
    """OpenCV border types: 0 constant, 1 replicate, 2 reflect,
    3 wrap, 4 reflect_101."""
    pads = ((top, bot), (left, right), (0, 0))
    if type == 0:
        if values:                 # per-channel constant fill
            chans = [jnp.pad(data[..., c], pads[:2],
                             constant_values=values[min(c, len(values) - 1)])
                     for c in range(data.shape[-1])]
            return jnp.stack(chans, axis=-1).astype(data.dtype)
        return jnp.pad(data, pads, constant_values=value).astype(data.dtype)
    mode = {1: "edge", 2: "symmetric", 3: "wrap", 4: "reflect"}.get(type)
    if mode is None:
        raise ValueError("unsupported border type %r" % (type,))
    return jnp.pad(data, pads, mode=mode).astype(data.dtype)
