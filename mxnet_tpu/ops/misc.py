"""Misc long-tail ops: ElementWiseSum, AMP helpers, shape-like ops, contrib
odds and ends.

Reference anchors: src/operator/tensor/elemwise_sum.cc (add_n),
src/operator/contrib/all_finite.cc, src/operator/tensor/amp_cast.cc
(amp_multicast), src/operator/tensor/matrix_op.cc (reshape_like,
broadcast_like, reverse), src/operator/tensor/indexing_op.cc
(choose_element_0index / fill_element_0index), src/operator/contrib/
(arange_like, index_array, allclose, quadratic, fft/ifft,
bipartite_matching, gradient multiplier), src/operator/numpy/np_diff_op.cc.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

__all__ = []


@register("add_n", aliases=["ElementWiseSum", "element_wise_sum"])
def _add_n(*args):
    out = args[0]
    for a in args[1:]:
        out = out + a
    return out


@register("all_finite", differentiable=False)
def _all_finite(data, init_output=True):
    return jnp.isfinite(data).all().reshape((1,)).astype(jnp.float32)


@register("multi_all_finite", differentiable=False)
def _multi_all_finite(*arrays, num_arrays=1, init_output=True):
    ok = jnp.array(True)
    for a in arrays:
        ok = jnp.logical_and(ok, jnp.isfinite(a).all())
    return ok.reshape((1,)).astype(jnp.float32)


@register("amp_multicast", num_outputs=-1)  # variable: one per input
def _amp_multicast(*args, num_outputs=1, cast_narrow=False):
    """Cast all inputs to the widest (or narrowest) float type among them
    (reference: amp_multicast in amp_cast.cc)."""
    dts = [a.dtype for a in args]
    order = [jnp.float16, jnp.bfloat16, jnp.float32, jnp.float64]

    def rank(d):
        for i, t in enumerate(order):
            if d == t:
                return i
        return len(order)
    target = (min if cast_narrow else max)(dts, key=rank)
    return tuple(a.astype(target) for a in args)


@register("cast_storage", differentiable=False)
def _cast_storage(data, stype="default"):
    """Dense backend: every stype materializes dense (the NDArray layer owns
    real CSR/RowSparse conversion — ndarray/sparse.py tostype)."""
    return data


@register("choose_element_0index", aliases=["pick_0index"],
          differentiable=False)
def _choose_element_0index(lhs, rhs):
    # pick lhs[i, rhs[i]] along the trailing axis (legacy pick)
    idx = rhs.astype(jnp.int32)
    return jnp.take_along_axis(lhs, idx[..., None], axis=-1)[..., 0]


@register("fill_element_0index", differentiable=False)
def _fill_element_0index(lhs, mhs, rhs):
    # lhs[i, rhs[i]] = mhs[i] (functional: returns the filled copy)
    idx = rhs.astype(jnp.int32)
    src = jnp.expand_dims(mhs, -1)
    return jnp.put_along_axis(lhs, idx[..., None], src, axis=-1,
                              inplace=False)


@register("reshape_like")
def _reshape_like(lhs, rhs, lhs_begin=None, lhs_end=None, rhs_begin=None,
                  rhs_end=None):
    if lhs_begin is None and rhs_begin is None:
        return lhs.reshape(rhs.shape)
    lb = 0 if lhs_begin is None else int(lhs_begin)
    le = lhs.ndim if lhs_end is None else int(lhs_end)
    rb = 0 if rhs_begin is None else int(rhs_begin)
    re_ = rhs.ndim if rhs_end is None else int(rhs_end)
    new_shape = lhs.shape[:lb] + rhs.shape[rb:re_] + lhs.shape[le:]
    return lhs.reshape(new_shape)


@register("broadcast_like")
def _broadcast_like(lhs, rhs, lhs_axes=None, rhs_axes=None):
    if lhs_axes is None and rhs_axes is None:
        return jnp.broadcast_to(lhs, rhs.shape)
    shape = list(lhs.shape)
    for la, ra in zip(lhs_axes, rhs_axes):
        shape[int(la)] = rhs.shape[int(ra)]
    return jnp.broadcast_to(lhs, tuple(shape))


@register("reverse", aliases=["_reverse"])
def _reverse(data, axis=0):
    axes = axis if isinstance(axis, (list, tuple)) else (axis,)
    for ax in axes:
        data = jnp.flip(data, int(ax))
    return data


@register("diff")
def _diff(a, n=1, axis=-1):
    return jnp.diff(a, n=int(n), axis=int(axis))


@register("_contrib_arange_like", aliases=["arange_like"],
          differentiable=False)
def _arange_like(data, start=0.0, step=1.0, repeat=1, axis=None):
    if axis is None:
        n = 1
        for s in data.shape:
            n *= s
        out = start + step * jnp.arange(n, dtype=jnp.float32)
        return out.reshape(data.shape)
    n = data.shape[int(axis)]
    return start + step * jnp.arange(n, dtype=jnp.float32)


@register("_contrib_div_sqrt_dim", aliases=["div_sqrt_dim"])
def _div_sqrt_dim(data):
    return data / jnp.sqrt(jnp.asarray(data.shape[-1], data.dtype))


@register("_contrib_gradientmultiplier", aliases=["gradientmultiplier"])
def _gradientmultiplier(data, scalar=1.0):
    """Identity forward, grad scaled by `scalar` (gradient-reversal layers
    use scalar=-1)."""
    s = jnp.asarray(scalar, data.dtype)

    @jax.custom_vjp
    def f(x):
        return x

    def fwd(x):
        return x, None

    def bwd(_, g):
        return (g * s,)
    f.defvjp(fwd, bwd)
    return f(data)


@register("_contrib_index_array", aliases=["index_array"],
          differentiable=False)
def _index_array(data, axes=None):
    shape = data.shape
    if axes is None:
        axes = tuple(range(len(shape)))
    else:
        axes = tuple(int(a) for a in axes)
    grids = [lax.broadcasted_iota(jnp.int64, shape, a) for a in axes]
    return jnp.stack(grids, axis=-1)


@register("_contrib_allclose", aliases=["allclose"], differentiable=False)
def _allclose(a, b, rtol=1e-05, atol=1e-08, equal_nan=True):
    return jnp.allclose(a, b, rtol=rtol, atol=atol,
                        equal_nan=equal_nan).reshape((1,)).astype(jnp.float32)


@register("_contrib_quadratic", aliases=["quadratic"])
def _quadratic(data, a=0.0, b=0.0, c=0.0):
    """The reference's tutorial op (src/operator/contrib/quadratic_op.cc)."""
    return a * data * data + b * data + c


@register("_contrib_fft", aliases=["fft"], differentiable=False)
def _fft(data, compute_size=128):
    """1-D FFT over the last axis; complex output packed [re, im] pairs on
    the last axis like the reference cuFFT wrapper."""
    z = jnp.fft.fft(data.astype(jnp.complex64), axis=-1)
    out = jnp.stack([z.real, z.imag], axis=-1)
    return out.reshape(data.shape[:-1] + (2 * data.shape[-1],))


@register("_contrib_ifft", aliases=["ifft"], differentiable=False)
def _ifft(data, compute_size=128):
    n = data.shape[-1] // 2
    pairs = data.reshape(data.shape[:-1] + (n, 2))
    z = lax.complex(pairs[..., 0], pairs[..., 1])
    return jnp.fft.ifft(z, axis=-1).real.astype(jnp.float32)


@register("_contrib_bipartite_matching", aliases=["bipartite_matching"],
          num_outputs=2, differentiable=False)
def _bipartite_matching(data, is_ascend=False, threshold=0.0, topk=-1):
    """Greedy bipartite matching over a (..., N, M) score matrix
    (reference: src/operator/contrib/bounding_box.cc BipartiteMatching).
    Returns (row->col match or -1, col->row match or -1)."""
    x = data
    lead = x.shape[:-2]
    N, M = x.shape[-2], x.shape[-1]
    xf = x.reshape((-1, N, M))
    big = jnp.asarray(jnp.inf, x.dtype)
    sign = 1.0 if is_ascend else -1.0
    k = N if topk in (-1, None) else min(int(topk), N)

    def one(mat):
        def body(_, carry):
            m, rowm, colm = carry
            flat = jnp.argmin(sign * m)
            i, j = flat // M, flat % M
            val = m[i, j]
            ok = (val > threshold) if not is_ascend else (val < big)
            rowm = jnp.where(ok, rowm.at[i].set(j), rowm)
            colm = jnp.where(ok, colm.at[j].set(i), colm)
            m = jnp.where(ok, m.at[i, :].set(sign * big), m)
            m = jnp.where(ok, m.at[:, j].set(sign * big), m)
            return m, rowm, colm
        rowm = jnp.full((N,), -1, jnp.float32)
        colm = jnp.full((M,), -1, jnp.float32)
        _, rowm, colm = lax.fori_loop(0, k, body, (mat, rowm, colm))
        return rowm, colm
    rows, cols = jax.vmap(one)(xf)
    return rows.reshape(lead + (N,)), cols.reshape(lead + (M,))


@register("_contrib_getnnz", aliases=["getnnz"], differentiable=False)
def _getnnz(data, axis=None):
    nz = (data != 0)
    if axis is None:
        return jnp.sum(nz).astype(jnp.int64).reshape(())
    return jnp.sum(nz, axis=int(axis)).astype(jnp.int64)


@register("_contrib_dynamic_reshape", aliases=["dynamic_reshape"],
          no_jit=True, differentiable=False)
def _dynamic_reshape(data, shape_like):
    """Reshape with a runtime shape TENSOR (dynamic-shape: eager-only)."""
    import numpy as np
    tgt = tuple(int(s) for s in np.asarray(shape_like))
    return jnp.reshape(data, tgt)


@register("_scatter_set_nd", differentiable=False)
def _scatter_set_nd(lhs, rhs, indices, shape=None):
    """lhs[indices] = rhs (functional copy; reference: _scatter_set_nd in
    indexing_op.cc — gather_nd's in-place writing dual)."""
    idx = tuple(indices[i].astype(jnp.int32) for i in range(indices.shape[0]))
    return lhs.at[idx].set(rhs)


@register("_rnn_param_concat")
def _rnn_param_concat(*args, dim=0, num_args=1):
    """Concat RNN parameter blobs into the fused layout (reference:
    src/operator/rnn.cc _rnn_param_concat)."""
    flat = [a.reshape(-1) if a.ndim != 1 else a for a in args]
    return jnp.concatenate(flat, axis=0)


@register("_onehot_encode", differentiable=False)
def _onehot_encode(indices, out_like):
    """Legacy onehot_encode(indices, out) (reference:
    src/operator/tensor/indexing_op.cc OneHotEncode)."""
    return jax.nn.one_hot(indices.astype(jnp.int32), out_like.shape[-1],
                          dtype=out_like.dtype)


@register("_copyto", aliases=["copyto_op"])
def _copyto(data):
    return data + 0  # fresh buffer; device move handled by the call layer


@register("_sparse_retain", aliases=["sparse_retain"], differentiable=False)
def _sparse_retain_op(data, indices):
    """Zero all rows except `indices` (dense view of the reference's
    row_sparse retain, src/operator/tensor/sparse_retain.cc)."""
    mask = jnp.zeros((data.shape[0],), bool).at[
        indices.astype(jnp.int32)].set(True)
    return jnp.where(mask.reshape((-1,) + (1,) * (data.ndim - 1)), data, 0)


@register("softmax_with_length")
def _softmax_with_length(data, length, axis=-1, temperature=1.0):
    """Softmax over the first `length` positions per row (reference:
    src/operator/nn/softmax.cc SoftmaxWithLength)."""
    ax = axis % data.ndim
    pos = jnp.arange(data.shape[ax])
    shape = [1] * data.ndim
    shape[ax] = -1
    mask = pos.reshape(shape) < jnp.expand_dims(length, ax)
    logits = jnp.where(mask, data / temperature, -jnp.inf)
    out = jax.nn.softmax(logits, axis=ax)
    return jnp.where(mask, out, 0.0)


@register("IdentityAttachKLSparseReg",
          aliases=["identity_attach_kl_sparse_reg"])
def _identity_kl_sparse_reg(data, sparseness_target=0.1, penalty=0.001,
                            momentum=0.9):
    """Identity forward; backward adds the KL sparsity-penalty gradient on
    the mean activation (reference: src/operator/regression_output...
    identity_attach_KL_sparse_reg.cc)."""
    rho = sparseness_target

    @jax.custom_vjp
    def f(x):
        return x

    def fwd(x):
        return x, jnp.clip(jnp.mean(x, axis=0), 1e-6, 1 - 1e-6)

    def bwd(rho_hat, g):
        reg = penalty * (-rho / rho_hat + (1 - rho) / (1 - rho_hat))
        return (g + reg[None, :].astype(g.dtype),)
    f.defvjp(fwd, bwd)
    return f(data)


@register("_contrib_count_sketch", aliases=["count_sketch"],
          differentiable=False)
def _count_sketch(data, h, s, out_dim=1, processing_batch_size=32):
    """Count-sketch projection (reference: src/operator/contrib/
    count_sketch.cc): out[:, h[j]] += s[j] * data[:, j]."""
    idx = h.reshape(-1).astype(jnp.int32)
    sign = s.reshape(-1).astype(data.dtype)
    signed = data * sign[None, :]
    out = jnp.zeros((data.shape[0], int(out_dim)), data.dtype)
    return out.at[:, idx].add(signed)


@register("_contrib_hawkesll", aliases=["hawkesll"], num_outputs=2,
          differentiable=False)
def _hawkesll(lda, alpha, beta, state, lags, marks, valid_length,
              max_time):
    """Hawkes-process log-likelihood over interarrival lags (reference:
    src/operator/contrib/hawkes_ll.cc).  Returns (loglik, final state)."""
    B, T = lags.shape

    def one(lda_i, state_i, lags_i, marks_i, vl_i, tmax):
        marks_i = marks_i.astype(jnp.int32)
        times = jnp.cumsum(lags_i)
        valid = jnp.arange(T) < vl_i

        def step(carry, t):
            ll, rem = carry
            k = marks_i[t]
            rem = rem * jnp.exp(-beta * lags_i[t])
            lam = lda_i[k] + alpha[k] * beta[k] * rem[k]
            v = valid[t]
            ll = ll + jnp.where(v, jnp.log(jnp.maximum(lam, 1e-30)), 0.0)
            rem = jnp.where(v, rem.at[k].add(1.0), rem)
            return (ll, rem), None
        (ll, rem), _ = lax.scan(step, (0.0, state_i), jnp.arange(T))
        # compensator: ∫₀ᵀ λ(t)dt — background + decayed window-start state
        # + each event's exponential-kernel mass inside the window
        comp = jnp.sum(lda_i) * tmax
        comp = comp + jnp.sum(alpha * state_i
                              * (1.0 - jnp.exp(-beta * tmax)))
        decay = 1.0 - jnp.exp(-beta[marks_i]
                              * jnp.maximum(tmax - times, 0.0))
        comp = comp + jnp.sum(jnp.where(valid, alpha[marks_i] * decay, 0.0))
        # state handed to the next window: decayed to tmax
        rem_out = rem * jnp.exp(-beta * jnp.maximum(tmax - times[-1], 0.0))
        return ll - comp, rem_out
    tmax = jnp.broadcast_to(jnp.asarray(max_time, jnp.float32), (B,))
    ll, rem = jax.vmap(one)(lda, state, lags, marks, valid_length, tmax)
    return ll, rem


@register("_image_imdecode", aliases=["imdecode_op"], no_jit=True,
          differentiable=False)
def _imdecode(buf, flag=1, to_rgb=True):
    """Host JPEG/PNG decode via PIL (reference: src/io/image_io.cc
    Imdecode — OpenCV there)."""
    import io as _io
    import numpy as np
    from PIL import Image
    raw = np.asarray(buf, np.uint8).tobytes()
    img = Image.open(_io.BytesIO(raw))
    if flag == 0:               # IMREAD_GRAYSCALE
        arr = np.asarray(img.convert("L"), np.uint8)
        return jnp.asarray(arr[:, :, None])
    arr = np.asarray(img.convert("RGB"), np.uint8)
    if not to_rgb:              # OpenCV-native BGR order
        arr = arr[:, :, ::-1].copy()
    return jnp.asarray(arr)


@register("_contrib_edge_id", aliases=["edge_id"], no_jit=True,
          differentiable=False)
def _edge_id(indptr, indices, u, v):
    """Edge ids of (u, v) pairs in a CSR adjacency, -1 when absent
    (reference: src/operator/contrib/dgl_graph.cc EdgeID over CSRNDArray;
    the CSR's data array holds edge ids — here the data INDEX is the id,
    matching mx.nd.contrib.edge_id's contract with data = arange).
    Host-side: graph queries are control-flow bound."""
    import numpy as np
    ip = np.asarray(indptr).astype(np.int64)
    ix = np.asarray(indices).astype(np.int64)
    uu = np.asarray(u).astype(np.int64).ravel()
    vv = np.asarray(v).astype(np.int64).ravel()
    out = np.full(uu.shape, -1.0, np.float32)
    for i, (a, b) in enumerate(zip(uu, vv)):
        lo, hi = ip[a], ip[a + 1]
        seg = ix[lo:hi]
        hits = np.nonzero(seg == b)[0]
        if hits.size:
            out[i] = float(lo + hits[0])
    return jnp.asarray(out)

@register("_contrib_index_copy", aliases=["index_copy"])
def _index_copy(old_tensor, index_vector, new_tensor):
    """Copy rows of new_tensor into old_tensor at index_vector
    (reference: src/operator/contrib/index_copy.cc).  Differentiable in
    both tensors: d(old) is zeroed at the copied rows, d(new) gathers
    them."""
    idx = index_vector.astype(jnp.int32)
    return old_tensor.at[idx].set(new_tensor.astype(old_tensor.dtype))


@register("_identity_with_attr_like_rhs")
def _identity_with_attr_like_rhs(lhs, rhs):
    """Identity on lhs; rhs only donates its attributes (storage/shape
    hints in the reference graph passes — no-op under XLA)."""
    del rhs
    return lhs
