"""Operator registry.

Reference: the nnvm op registry — include/mxnet/op_attr_types.h (FCompute,
FGradient, FInferShape), NNVM_REGISTER_OP in src/operator/**.

The rebuild's registry is a Python-side dict keyed by op name.  Each entry
carries:
  * ``fn`` — the op's implementation as a *pure, traceable JAX function*
    ``fn(*arrays, **params) -> array | tuple`` where ``params`` are static
    (hashable) keyword attributes.  This single function plays the role of
    FCompute<cpu>, FCompute<gpu> and the cuDNN/oneDNN paths at once: XLA
    lowers it per backend, and the MXU/fusion decisions belong to the
    compiler (SURVEY.md §7.0).
  * differentiability — gradients come from ``jax.vjp`` over ``fn`` (the role
    of FGradient); ops that are semantically non-differentiable are marked so
    the tape can skip/zero them.
  * aliases — MXNet exposes many ops under several names (`elemwise_add`,
    `broadcast_add`, `_plus`, ...).

Shape/dtype inference (FInferShape/FInferType) falls out of ``jax.eval_shape``
over ``fn`` and needs no per-op rule.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional, Sequence

import jax

__all__ = ["OpDef", "register", "get_op", "list_ops", "alias", "cached_jit"]

_REGISTRY: Dict[str, "OpDef"] = {}


class OpDef:
    __slots__ = ("name", "fn", "differentiable", "num_outputs", "doc",
                 "mutates_input", "needs_rng", "aux_writeback", "no_jit",
                 "_pos_params")

    def __init__(self, name: str, fn: Callable, differentiable: bool = True,
                 num_outputs: int = 1, doc: Optional[str] = None,
                 mutates_input: Optional[int] = None, needs_rng: bool = False,
                 aux_writeback: Optional[Dict[int, int]] = None,
                 no_jit: bool = False):
        self.name = name
        self.fn = fn
        self.differentiable = differentiable
        self.num_outputs = num_outputs
        self.doc = doc or (fn.__doc__ or "")
        # index of the input the op writes in place (e.g. fused optimizer
        # updates mutate the weight); dispatch writes back through the chunk.
        self.mutates_input = mutates_input
        # op's first positional arg is a PRNG key injected by the dispatcher
        self.needs_rng = needs_rng
        # {output_idx: input_idx}: outputs written in place into the given
        # inputs (BatchNorm moving stats = the reference's aux states) and
        # stripped from the visible return
        self.aux_writeback = aux_writeback
        # dynamic-output-shape ops (boolean_mask, np.unique-style) cannot be
        # traced: dispatch eagerly, outside the per-op jit cache
        self.no_jit = no_jit
        self._pos_params = None

    def pos_params(self):
        """[(name, has_default)] for the kernel's positional parameters
        (minus the injected rng key; stops at *args).  Drives the
        classic-API convention: a positional NON-tensor value whose slot
        HAS a default is an attr (nd.expand_dims(x, 0), nd.one_hot(i, 5),
        nd.reshape(x, (2, 3))); a slot without a default is a tensor
        operand (broadcast_add(x, 2.0) stays an array)."""
        if self._pos_params is None:
            import inspect
            info = []
            try:
                for p in inspect.signature(self.fn).parameters.values():
                    if p.kind not in (p.POSITIONAL_ONLY,
                                      p.POSITIONAL_OR_KEYWORD):
                        break
                    info.append((p.name, p.default is not p.empty))
            except (TypeError, ValueError):
                pass
            if self.needs_rng and info and info[0][0] == "key":
                info = info[1:]
            self._pos_params = tuple(info)
        return self._pos_params

    def split_pos_attrs(self, inputs, params, tensor_cls):
        """Classic-API positional attrs (one shared implementation for
        the nd and sym dispatchers): a plain value (number/tuple/list/
        str) in a slot whose kernel parameter HAS a default moves into
        `params` (mutated in place); defaultless slots keep plain
        numbers as tensor operands.  Raises on a positional/keyword
        duplicate.  Returns the remaining tensor inputs."""
        import numbers as _numbers
        if not any(isinstance(x, (_numbers.Number, tuple, list, str))
                   and not isinstance(x, tensor_cls) for x in inputs):
            return inputs
        info = self.pos_params()
        kept = []
        for i, x in enumerate(inputs):
            if isinstance(x, (_numbers.Number, tuple, list, str)) \
                    and not isinstance(x, tensor_cls) \
                    and i < len(info) and info[i][1]:
                name = info[i][0]
                if name in params:
                    raise TypeError(
                        "%s: got multiple values for %r (positional and "
                        "keyword)" % (self.name, name))
                params[name] = x
            else:
                kept.append(x)
        return tuple(kept)

    def __repr__(self):
        return "OpDef(%s)" % self.name


def register(name: str, fn: Optional[Callable] = None, *, differentiable: bool = True,
             num_outputs: int = 1, aliases: Sequence[str] = (),
             mutates_input: Optional[int] = None, needs_rng: bool = False,
             aux_writeback: Optional[Dict[int, int]] = None,
             no_jit: bool = False, replace: bool = False):
    """Register an op. Usable as decorator or direct call.

    ``replace=True`` is for deliberate re-registration (user kernel
    iteration via tpu_kernel.register); the built-in op modules must not
    overwrite each other silently — that has already masked a kernel
    regression once, so a same-module duplicate raises."""

    def _do(f: Callable) -> Callable:
        op = OpDef(name, f, differentiable=differentiable,
                   num_outputs=num_outputs, mutates_input=mutates_input,
                   needs_rng=needs_rng, aux_writeback=aux_writeback,
                   no_jit=no_jit)
        if name in _REGISTRY or any(a in _REGISTRY for a in aliases):
            if not replace:
                dup = name if name in _REGISTRY else \
                    next(a for a in aliases if a in _REGISTRY)
                raise ValueError(
                    "op %r is already registered (to %r); pass "
                    "replace=True only for deliberate user-kernel "
                    "re-registration" % (dup, _REGISTRY[dup].fn))
            # re-registration (user kernel iteration): drop the per-op jit
            # cache or dispatch keeps hitting the old fn via (name, params)
            _jitted.cache_clear()
        _REGISTRY[name] = op
        for a in aliases:
            _REGISTRY[a] = op
        return f

    if fn is None:
        return _do
    return _do(fn)


def alias(name: str, *names: str) -> None:
    op = _REGISTRY[name]
    for n in names:
        _REGISTRY[n] = op


def get_op(name: str) -> OpDef:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError("Operator %r is not registered (have %d ops)"
                       % (name, len(set(_REGISTRY.values())))) from None


def list_ops():
    """All registered op names (reference: MXListAllOpNames)."""
    return sorted(_REGISTRY.keys())


# ---------------------------------------------------------------------------
# Eager per-op jit cache — the rebuild's HOT LOOP 1 (SURVEY.md §3.2): an eager
# `mx.nd.dot` must hit a dict lookup, not a retrace.  jax.jit already caches
# compiled executables keyed on input avals; we additionally cache the jitted
# callable per (op, static-params) so eager dispatch does zero re-wrapping.
# ---------------------------------------------------------------------------

def _freeze(v: Any):
    if isinstance(v, (list, tuple)):
        return tuple(_freeze(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _freeze(x)) for k, x in v.items()))
    return v


@functools.lru_cache(maxsize=8192)
def _jitted(name: str, frozen_params) -> Callable:
    op = _REGISTRY[name]
    params = dict(frozen_params)
    # light-mode census (ISSUE 10): jax.jit keeps its C++ dispatch on
    # this hottest of paths; the registry still sees every op program's
    # (re)trace count and bracketed compile time as `op.<name>`
    from ..programs import register_program
    return register_program("op." + op.name,
                            functools.partial(op.fn, **params),
                            mode="light", specializing=True)


def cached_jit(name: str, params: Dict[str, Any]) -> Callable:
    if not params:          # hot path: most elementwise ops have no attrs
        return _jitted(name, ())
    return _jitted(name, tuple(sorted((k, _freeze(v)) for k, v in params.items())))
