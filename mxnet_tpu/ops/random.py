"""Random sampling ops.

Reference: src/operator/random/sample_op.cc (_random_uniform, _random_normal,
...), src/resource.cc (per-device cuRAND states seeded by mx.random.seed).

TPU-native: counter-based stateless RNG.  A process-global root key (set by
``mx.random.seed``) is folded with a monotonically increasing counter for
every sample op; the key is passed to the op as an ordinary array input so
the op stays pure/traceable.  This replaces the reference's per-device
ResourceManager kRandom states while keeping `mx.random.seed` determinism.
"""
from __future__ import annotations

import threading

import jax
import jax.numpy as jnp

from .registry import register

_state = threading.local()
_DEFAULT_SEED = 0


def _make_fold_in():
    from ..programs import register_program
    return register_program("random.fold_in", jax.random.fold_in,
                            mode="light")


_fold_in_jit = _make_fold_in()


def _root():
    if not hasattr(_state, "key"):
        _state.key = jax.random.PRNGKey(_DEFAULT_SEED)
        _state.counter = 0
    return _state


def seed(seed_val: int) -> None:
    st = _root()
    st.key = jax.random.PRNGKey(int(seed_val))
    st.counter = 0


def next_key() -> jax.Array:
    st = _root()
    # Inside a hybridize/jit trace the key must be a *traced input*, not a
    # baked-in constant (else every cached-graph call would replay the same
    # dropout mask).  trace_key_scope installs a holder whose key is a tracer;
    # we split it so successive ops in one trace draw distinct streams.
    holder = getattr(st, "trace_holder", None)
    if holder is not None:
        holder[0], sub = jax.random.split(holder[0])
        return sub
    st.counter += 1
    # JITTED fold_in with the counter as a traced ARRAY operand: the eager
    # threefry path runs dozens of un-fused scalar ops (~100ms+ per call on
    # CPU), and a Python-int counter would bake into the trace and recompile
    # per value.  One executable serves every counter.
    return _fold_in_jit(st.key, jnp.uint32(st.counter))


class trace_key_scope:
    """Route next_key() through a traced base key for the duration of a
    hybridized-graph trace (see gluon/block.py CachedOp)."""

    def __init__(self, key: jax.Array):
        self._holder = [key]

    def __enter__(self):
        st = _root()
        self._old = getattr(st, "trace_holder", None)
        st.trace_holder = self._holder
        return self

    def __exit__(self, *exc):
        _root().trace_holder = self._old
        return False


def _dt(dtype):
    if dtype in (None, "None"):
        return jnp.float32
    return jnp.bfloat16 if dtype == "bfloat16" else dtype


@register("_random_uniform", aliases=["random_uniform", "uniform"],
          differentiable=False, needs_rng=True)
def _uniform(key, low=0.0, high=1.0, shape=(), dtype=None):
    return jax.random.uniform(key, shape, _dt(dtype), minval=low, maxval=high)


@register("_random_normal", aliases=["random_normal", "normal"],
          differentiable=False, needs_rng=True)
def _normal(key, loc=0.0, scale=1.0, shape=(), dtype=None):
    return jax.random.normal(key, shape, _dt(dtype)) * scale + loc


@register("_random_gamma", aliases=["random_gamma"], differentiable=False, needs_rng=True)
def _gamma(key, alpha=1.0, beta=1.0, shape=(), dtype=None):
    return jax.random.gamma(key, alpha, shape, _dt(dtype)) * beta


@register("_random_exponential", aliases=["random_exponential"],
          differentiable=False, needs_rng=True)
def _exponential(key, lam=1.0, shape=(), dtype=None):
    return jax.random.exponential(key, shape, _dt(dtype)) / lam


@register("_random_poisson", aliases=["random_poisson"], differentiable=False, needs_rng=True)
def _poisson(key, lam=1.0, shape=(), dtype=None):
    return jax.random.poisson(key, lam, shape).astype(_dt(dtype))


@register("_random_randint", aliases=["random_randint"], differentiable=False, needs_rng=True)
def _randint(key, low=0, high=2, shape=(), dtype="int32"):
    return jax.random.randint(key, shape, low, high, dtype or jnp.int32)


@register("_random_bernoulli", aliases=["bernoulli"], differentiable=False, needs_rng=True)
def _bernoulli(key, prob=0.5, shape=(), dtype=None):
    return jax.random.bernoulli(key, prob, shape).astype(_dt(dtype))


@register("_sample_multinomial", aliases=["sample_multinomial", "multinomial"],
          differentiable=False, needs_rng=True)
def _multinomial(key, data, shape=(), get_prob=False, dtype="int32"):
    # data: (..., k) probabilities
    logits = jnp.log(jnp.maximum(data, 1e-37))
    n = 1
    for s in (shape if isinstance(shape, tuple) else (shape,)):
        n *= int(s) if s else 1
    out_shape = data.shape[:-1] + ((shape if isinstance(shape, tuple) else (shape,)) if shape else ())
    samp = jax.random.categorical(key, logits, axis=-1,
                                  shape=(n,) + data.shape[:-1])
    if data.ndim == 1:
        samp = samp.reshape(out_shape if shape else ())
    else:
        samp = jnp.moveaxis(samp, 0, -1).reshape(out_shape)
    samp = samp.astype(dtype or jnp.int32)
    if get_prob:
        # REINFORCE path: also return log-prob of each drawn sample
        logp = jnp.take_along_axis(
            jnp.broadcast_to(logits, samp.shape + (logits.shape[-1],)),
            samp[..., None].astype(jnp.int32), axis=-1)[..., 0]
        return samp, logp
    return samp


@register("shuffle", aliases=["_shuffle"], differentiable=False, needs_rng=True)
def _shuffle(key, data):
    return jax.random.permutation(key, data, axis=0)


@register("sample_normal_like", differentiable=False, needs_rng=True)
def _normal_like(key, data, loc=0.0, scale=1.0):
    return jax.random.normal(key, data.shape, data.dtype) * scale + loc


# ---------------------------------------------------------------------------
# distribution tail (reference: src/operator/random/sample_op.cc) — inverse-
# CDF transforms over uniform/gamma primitives; all counter-based stateless
# ---------------------------------------------------------------------------


def _u(key, shape, dtype):
    # uniform in (0, 1): open at 0 so log() stays finite
    return jax.random.uniform(key, shape, dtype, minval=jnp.finfo(dtype).tiny,
                              maxval=1.0)


@register("_random_negative_binomial",
          aliases=["random_negative_binomial", "negative_binomial"],
          differentiable=False, needs_rng=True)
def _negative_binomial(key, k=1, p=1.0, shape=(), dtype=None):
    """NB(k, p) == Poisson(Gamma(k, (1-p)/p)) (reference sampler)."""
    dt = _dt(dtype)
    k1, k2 = jax.random.split(key)
    lam = jax.random.gamma(k1, float(k), shape, jnp.float32) * \
        ((1.0 - p) / max(p, 1e-12))
    return jax.random.poisson(k2, lam, shape).astype(dt)


@register("_random_generalized_negative_binomial",
          aliases=["random_generalized_negative_binomial",
                   "generalized_negative_binomial"],
          differentiable=False, needs_rng=True)
def _gen_negative_binomial(key, mu=1.0, alpha=1.0, shape=(), dtype=None):
    """GNB(mu, alpha): Poisson with Gamma(1/alpha, mu*alpha) rate."""
    dt = _dt(dtype)
    if alpha == 0.0:
        return jax.random.poisson(key, mu, shape).astype(dt)
    k1, k2 = jax.random.split(key)
    r = 1.0 / alpha
    lam = jax.random.gamma(k1, r, shape, jnp.float32) * (mu * alpha)
    return jax.random.poisson(k2, lam, shape).astype(dt)


@register("_random_pareto", aliases=["random_pareto", "pareto"],
          differentiable=False, needs_rng=True)
def _pareto(key, a=1.0, shape=(), dtype=None):
    dt = _dt(dtype)
    return jnp.expm1(-jnp.log(_u(key, shape, jnp.float32)) / a).astype(dt)


@register("_random_rayleigh", aliases=["random_rayleigh", "rayleigh"],
          differentiable=False, needs_rng=True)
def _rayleigh(key, scale=1.0, shape=(), dtype=None):
    dt = _dt(dtype)
    u = _u(key, shape, jnp.float32)
    return (scale * jnp.sqrt(-2.0 * jnp.log(u))).astype(dt)


@register("_random_weibull", aliases=["random_weibull", "weibull"],
          differentiable=False, needs_rng=True)
def _weibull(key, a=1.0, shape=(), dtype=None):
    dt = _dt(dtype)
    u = _u(key, shape, jnp.float32)
    return jnp.power(-jnp.log(u), 1.0 / a).astype(dt)


@register("_random_logistic", aliases=["random_logistic", "logistic"],
          differentiable=False, needs_rng=True)
def _logistic(key, loc=0.0, scale=1.0, shape=(), dtype=None):
    dt = _dt(dtype)
    return (jax.random.logistic(key, shape, jnp.float32) * scale
            + loc).astype(dt)


@register("_random_gumbel", aliases=["random_gumbel", "gumbel"],
          differentiable=False, needs_rng=True)
def _gumbel(key, loc=0.0, scale=1.0, shape=(), dtype=None):
    dt = _dt(dtype)
    return (jax.random.gumbel(key, shape, jnp.float32) * scale
            + loc).astype(dt)


# ---------------------------------------------------------------------------
# sample_* family: per-row distribution parameters as TENSOR inputs
# (reference: src/operator/random/multisample_op.cc — each row of the
# parameter tensors draws `shape` samples)
# ---------------------------------------------------------------------------


def _persample(key, params, shape, draw):
    """params: tuple of same-shape tensors; returns shape params.shape+shape
    with draw(key, *scalar_params, sample_shape)."""
    ps = params[0].shape
    extra = tuple(shape) if isinstance(shape, (tuple, list)) else \
        ((int(shape),) if shape else ())
    out_shape = ps + extra
    return draw(key, params, out_shape, extra)


@register("_sample_uniform", aliases=["sample_uniform"],
          differentiable=False, needs_rng=True)
def _sample_uniform(key, low, high, shape=(), dtype=None):
    dt = _dt(dtype)

    def draw(key, params, out_shape, extra):
        low, high = params
        u = jax.random.uniform(key, out_shape, jnp.float32)
        lowb = low.reshape(low.shape + (1,) * len(extra))
        highb = high.reshape(high.shape + (1,) * len(extra))
        return (lowb + u * (highb - lowb)).astype(dt)
    return _persample(key, (low, high), shape, draw)


@register("_sample_normal", aliases=["sample_normal"],
          differentiable=False, needs_rng=True)
def _sample_normal(key, mu, sigma, shape=(), dtype=None):
    dt = _dt(dtype)

    def draw(key, params, out_shape, extra):
        mu, sigma = params
        z = jax.random.normal(key, out_shape, jnp.float32)
        mub = mu.reshape(mu.shape + (1,) * len(extra))
        sigb = sigma.reshape(sigma.shape + (1,) * len(extra))
        return (mub + z * sigb).astype(dt)
    return _persample(key, (mu, sigma), shape, draw)


@register("_sample_gamma", aliases=["sample_gamma"],
          differentiable=False, needs_rng=True)
def _sample_gamma(key, alpha, beta, shape=(), dtype=None):
    dt = _dt(dtype)

    def draw(key, params, out_shape, extra):
        alpha, beta = params
        ab = alpha.reshape(alpha.shape + (1,) * len(extra))
        bb = beta.reshape(beta.shape + (1,) * len(extra))
        g = jax.random.gamma(key, jnp.broadcast_to(ab, out_shape), out_shape,
                             jnp.float32)
        return (g * bb).astype(dt)
    return _persample(key, (alpha, beta), shape, draw)


@register("_sample_exponential", aliases=["sample_exponential"],
          differentiable=False, needs_rng=True)
def _sample_exponential(key, lam, shape=(), dtype=None):
    dt = _dt(dtype)

    def draw(key, params, out_shape, extra):
        (lam,) = params
        lamb = lam.reshape(lam.shape + (1,) * len(extra))
        e = jax.random.exponential(key, out_shape, jnp.float32)
        return (e / lamb).astype(dt)
    return _persample(key, (lam,), shape, draw)


@register("_sample_poisson", aliases=["sample_poisson"],
          differentiable=False, needs_rng=True)
def _sample_poisson(key, lam, shape=(), dtype=None):
    dt = _dt(dtype)

    def draw(key, params, out_shape, extra):
        (lam,) = params
        lamb = jnp.broadcast_to(lam.reshape(lam.shape + (1,) * len(extra)),
                                out_shape)
        return jax.random.poisson(key, lamb).astype(dt)
    return _persample(key, (lam,), shape, draw)


@register("_sample_negative_binomial", aliases=["sample_negative_binomial"],
          differentiable=False, needs_rng=True)
def _sample_negative_binomial(key, k, p, shape=(), dtype=None):
    dt = _dt(dtype)

    def draw(key, params, out_shape, extra):
        k_, p_ = params
        kb = jnp.broadcast_to(k_.reshape(k_.shape + (1,) * len(extra))
                              .astype(jnp.float32), out_shape)
        pb = jnp.broadcast_to(p_.reshape(p_.shape + (1,) * len(extra))
                              .astype(jnp.float32), out_shape)
        k1, k2 = jax.random.split(key)
        lam = jax.random.gamma(k1, kb, out_shape, jnp.float32) * \
            ((1.0 - pb) / jnp.maximum(pb, 1e-12))
        return jax.random.poisson(k2, lam).astype(dt)
    return _persample(key, (k, p), shape, draw)


@register("_sample_generalized_negative_binomial",
          aliases=["sample_generalized_negative_binomial"],
          differentiable=False, needs_rng=True)
def _sample_gen_negative_binomial(key, mu, alpha, shape=(), dtype=None):
    dt = _dt(dtype)

    def draw(key, params, out_shape, extra):
        mu_, al_ = params
        mub = jnp.broadcast_to(mu_.reshape(mu_.shape + (1,) * len(extra))
                               .astype(jnp.float32), out_shape)
        alb = jnp.broadcast_to(al_.reshape(al_.shape + (1,) * len(extra))
                               .astype(jnp.float32), out_shape)
        k1, k2 = jax.random.split(key)
        r = 1.0 / jnp.maximum(alb, 1e-12)
        lam = jax.random.gamma(k1, r, out_shape, jnp.float32) * (mub * alb)
        return jax.random.poisson(k2, lam).astype(dt)
    return _persample(key, (mu, alpha), shape, draw)


@register("_sample_unique_zipfian", aliases=["sample_unique_zipfian"],
          differentiable=False, needs_rng=True, no_jit=True,
          num_outputs=2)
def _sample_unique_zipfian(key, range_max=1, shape=()):
    """Unique zipfian draws for sampled softmax (reference:
    src/operator/random/unique_sample_op.cc).  Dynamic-unique ⇒ eager-only;
    returns (samples, expected-count-per-draw)."""
    import numpy as np
    n = 1
    for s in (shape if isinstance(shape, (tuple, list)) else (shape,)):
        n *= int(s) if s else 1
    seed = int(jax.random.randint(key, (), 0, 2**31 - 1))
    rng = np.random.RandomState(seed)
    log_range = np.log(range_max + 1.0)
    out, seen = [], set()
    trials = 0
    while len(out) < n:
        u = rng.rand()
        v = int(np.exp(u * log_range)) - 1
        v = min(max(v, 0), range_max - 1)
        trials += 1
        if v not in seen:
            seen.add(v)
            out.append(v)
    samples = np.asarray(out, np.int64)
    prob = np.log((samples + 2.0) / (samples + 1.0)) / log_range
    cnt = prob * trials
    return (jnp.asarray(samples),
            jnp.asarray(cnt.astype(np.float32)))


# ---------------------------------------------------------------------------
# *_like variants (reference: sample_op.cc _random_uniform_like & co) —
# draw with the template array's shape and dtype
# ---------------------------------------------------------------------------


@register("_random_uniform_like", aliases=["random_uniform_like"],
          differentiable=False, needs_rng=True)
def _uniform_like(key, data, low=0.0, high=1.0):
    return jax.random.uniform(key, data.shape, data.dtype,
                              minval=low, maxval=high)


@register("_random_normal_like", aliases=["random_normal_like"],
          differentiable=False, needs_rng=True)
def _random_normal_like(key, data, loc=0.0, scale=1.0):
    return jax.random.normal(key, data.shape, data.dtype) * scale + loc


@register("_random_exponential_like", aliases=["random_exponential_like"],
          differentiable=False, needs_rng=True)
def _exponential_like(key, data, lam=1.0):
    return jax.random.exponential(key, data.shape, data.dtype) / lam


@register("_random_gamma_like", aliases=["random_gamma_like"],
          differentiable=False, needs_rng=True)
def _gamma_like(key, data, alpha=1.0, beta=1.0):
    return jax.random.gamma(key, alpha, data.shape, data.dtype) * beta


@register("_random_poisson_like", aliases=["random_poisson_like"],
          differentiable=False, needs_rng=True)
def _poisson_like(key, data, lam=1.0):
    return jax.random.poisson(key, lam, data.shape).astype(data.dtype)


@register("_random_negative_binomial_like",
          aliases=["random_negative_binomial_like"],
          differentiable=False, needs_rng=True)
def _negative_binomial_like(key, data, k=1, p=1.0):
    k1, k2 = jax.random.split(key)
    lam = jax.random.gamma(k1, float(k), data.shape, jnp.float32) * \
        ((1.0 - p) / max(p, 1e-12))
    return jax.random.poisson(k2, lam, data.shape).astype(data.dtype)


@register("_random_generalized_negative_binomial_like",
          aliases=["random_generalized_negative_binomial_like"],
          differentiable=False, needs_rng=True)
def _gnb_like(key, data, mu=1.0, alpha=1.0):
    a = 1.0 / max(alpha, 1e-12)
    k1, k2 = jax.random.split(key)
    lam = jax.random.gamma(k1, a, data.shape, jnp.float32) * (mu * alpha)
    return jax.random.poisson(k2, lam, data.shape).astype(data.dtype)


# numpy-era samplers (reference: src/operator/numpy/random/*.cc)


@register("_npi_uniform", differentiable=False, needs_rng=True)
def _npi_uniform(key, low=0.0, high=1.0, size=(), dtype=None):
    return jax.random.uniform(key, size or (), _dt(dtype),
                              minval=low, maxval=high)


@register("_npi_normal", differentiable=False, needs_rng=True)
def _npi_normal(key, loc=0.0, scale=1.0, size=(), dtype=None):
    return jax.random.normal(key, size or (), _dt(dtype)) * scale + loc


@register("_npi_choice", differentiable=False, needs_rng=True, no_jit=True)
def _npi_choice(key, a, size=(), replace=True, p=None):
    return jax.random.choice(key, a, size or (), replace=replace, p=p)


@register("_npi_laplace", aliases=["random_laplace", "laplace"],
          differentiable=False, needs_rng=True)
def _npi_laplace(key, loc=0.0, scale=1.0, size=(), dtype=None):
    return jax.random.laplace(key, size or (), _dt(dtype)) * scale + loc


@register("_npi_beta", aliases=["random_beta", "beta"],
          differentiable=False, needs_rng=True)
def _npi_beta(key, a=1.0, b=1.0, size=(), dtype=None):
    return jax.random.beta(key, a, b, size or (), _dt(dtype))


@register("_npi_chisquare", aliases=["random_chisquare", "chisquare"],
          differentiable=False, needs_rng=True)
def _npi_chisquare(key, df=1.0, size=(), dtype=None):
    return jax.random.chisquare(key, df, shape=size or (), dtype=_dt(dtype))


@register("_npi_f", differentiable=False, needs_rng=True)
def _npi_f(key, dfnum=1.0, dfden=1.0, size=(), dtype=None):
    # NOTE: the user-visible legacy alias "random_f" belongs to the
    # legacy-convention _random_f kernel below (shape= kwarg), not here
    return jax.random.f(key, dfnum, dfden, shape=size or (),
                        dtype=_dt(dtype))


@register("_npi_standard_t", aliases=["random_standard_t", "standard_t"],
          differentiable=False, needs_rng=True)
def _npi_standard_t(key, df=1.0, size=(), dtype=None):
    return jax.random.t(key, df, shape=size or (), dtype=_dt(dtype))


@register("_npi_lognormal", aliases=["random_lognormal", "lognormal"],
          differentiable=False, needs_rng=True)
def _npi_lognormal(key, mean=0.0, sigma=1.0, size=(), dtype=None):
    return jnp.exp(jax.random.normal(key, size or (), _dt(dtype))
                   * sigma + mean)


@register("_npi_triangular", aliases=["random_triangular", "triangular"],
          differentiable=False, needs_rng=True)
def _npi_triangular(key, left=0.0, mode=0.5, right=1.0, size=(), dtype=None):
    dt = _dt(dtype)
    u = jax.random.uniform(key, size or (), dt)
    c = (mode - left) / (right - left)
    lo = left + jnp.sqrt(u * (right - left) * (mode - left))
    hi = right - jnp.sqrt((1 - u) * (right - left) * (right - mode))
    return jnp.where(u < c, lo, hi)


@register("_npi_permutation", differentiable=False, needs_rng=True)
def _npi_permutation(key, x):
    return jax.random.permutation(key, x, axis=0)


@register("_random_f", aliases=["random_f"], differentiable=False,
          needs_rng=True)
def _f_dist(key, dfnum=1.0, dfden=1.0, shape=(), dtype=None):
    """F(d1, d2) = (X1/d1)/(X2/d2) for chi-square X1, X2 (reference:
    np.random.f)."""
    dt = _dt(dtype)
    k1, k2 = jax.random.split(key)
    x1 = 2.0 * jax.random.gamma(k1, dfnum / 2.0, shape, jnp.float32)
    x2 = 2.0 * jax.random.gamma(k2, dfden / 2.0, shape, jnp.float32)
    return ((x1 / dfnum) / (x2 / dfden)).astype(dt)


@register("_random_geometric", aliases=["random_geometric"],
          differentiable=False, needs_rng=True)
def _geometric(key, p=0.5, shape=(), dtype=None):
    """Trials to first success, support {1, 2, ...} (np.random.geometric
    convention): ceil(log(U)/log(1-p))."""
    dt = _dt(dtype)
    u = _u(key, shape, jnp.float32)
    return jnp.ceil(jnp.log(u) / jnp.log1p(-p)).astype(dt)


@register("_random_power", aliases=["random_power"],
          differentiable=False, needs_rng=True)
def _power_dist(key, a=1.0, shape=(), dtype=None):
    """Power distribution on [0, 1]: U^(1/a) (np.random.power)."""
    dt = _dt(dtype)
    u = _u(key, shape, jnp.float32)
    return jnp.power(u, 1.0 / a).astype(dt)


@register("_npi_dirichlet", aliases=["random_dirichlet", "dirichlet"],
          differentiable=False, needs_rng=True)
def _npi_dirichlet(key, alpha=(1.0,), size=(), dtype=None):
    """np.random.dirichlet: normalized Gamma(alpha_i) draws."""
    dt = _dt(dtype)
    alpha = jnp.asarray(alpha, dt)
    return jax.random.dirichlet(key, alpha, shape=size or (), dtype=dt)


@register("_npi_standard_cauchy",
          aliases=["random_standard_cauchy", "standard_cauchy"],
          differentiable=False, needs_rng=True)
def _npi_standard_cauchy(key, size=(), dtype=None):
    return jax.random.cauchy(key, size or (), _dt(dtype))


@register("_npi_standard_gamma",
          aliases=["random_standard_gamma", "standard_gamma"],
          differentiable=False, needs_rng=True)
def _npi_standard_gamma(key, shape_param=1.0, size=(), dtype=None):
    return jax.random.gamma(key, shape_param, size or (), _dt(dtype))


@register("_npi_noncentral_chisquare",
          aliases=["random_noncentral_chisquare", "noncentral_chisquare"],
          differentiable=False, needs_rng=True)
def _npi_noncentral_chisquare(key, df=1.0, nonc=0.0, size=(), dtype=None):
    """Poisson-mixture construction: chi2(df + 2*K), K ~ Poisson(nonc/2)
    (the standard exact sampler; np.random.noncentral_chisquare)."""
    dt = _dt(dtype)
    k_key, c_key = jax.random.split(key)
    k = jax.random.poisson(k_key, nonc / 2.0, shape=size or ())
    return jax.random.chisquare(
        c_key, df + 2.0 * k.astype(jnp.float32), shape=size or (),
        dtype=dt)


@register("_npi_wald", aliases=["random_wald", "wald"],
          differentiable=False, needs_rng=True)
def _npi_wald(key, mean=1.0, scale=1.0, size=(), dtype=None):
    """Inverse Gaussian via the Michael-Schucany-Haas transform
    (np.random.wald)."""
    dt = _dt(dtype)
    n_key, u_key = jax.random.split(key)
    shape = size or ()
    v = jax.random.normal(n_key, shape, jnp.float32) ** 2
    x = (mean + (mean ** 2) * v / (2.0 * scale)
         - (mean / (2.0 * scale))
         * jnp.sqrt(4.0 * mean * scale * v + (mean * v) ** 2))
    u = jax.random.uniform(u_key, shape, jnp.float32)
    return jnp.where(u <= mean / (mean + x), x,
                     (mean ** 2) / x).astype(dt)


@register("_npi_logseries", aliases=["random_logseries", "logseries"],
          differentiable=False, needs_rng=True)
def _npi_logseries(key, p=0.5, size=(), dtype=None):
    """Kemp's exact two-uniform sampler for the log-series distribution
    (np.random.logseries): x = floor(1 + ln(v)/ln(1 - (1-p)^u))."""
    dt = dtype or "int32"
    shape = size or ()
    ku, kv = jax.random.split(key)
    u = jax.random.uniform(ku, shape, jnp.float32, 1e-7, 1.0)
    v = jax.random.uniform(kv, shape, jnp.float32, 1e-7, 1.0)
    q = 1.0 - jnp.power(1.0 - p, u)
    x = jnp.floor(1.0 + jnp.log(v) / jnp.log(q))
    return jnp.maximum(x, 1.0).astype(dt)


@register("_npi_vonmises", aliases=["random_vonmises", "vonmises"],
          differentiable=False, needs_rng=True)
def _npi_vonmises(key, mu=0.0, kappa=1.0, size=(), dtype=None):
    """Best-Fisher (1979) rejection sampler, vectorized with a fixed
    64-round accept mask (acceptance rate ~65%+ per round, so the
    probability of an unfilled lane after 64 rounds is < 1e-29)."""
    dt = _dt(dtype)
    shape = size or ()
    if kappa < 1e-6:
        # numpy semantics: kappa=0 is the uniform circular distribution
        # (the Best-Fisher rho would be 0/0)
        u = jax.random.uniform(key, shape, jnp.float32, 0.0, 1.0)
        theta = 2.0 * jnp.pi * u - jnp.pi
        return (jnp.mod(theta + mu + jnp.pi, 2.0 * jnp.pi)
                - jnp.pi).astype(dt)
    r = 1.0 + jnp.sqrt(1.0 + 4.0 * kappa ** 2)
    rho = (r - jnp.sqrt(2.0 * r)) / (2.0 * kappa)
    s = (1.0 + rho ** 2) / (2.0 * rho)

    def body(carry, k):
        out, done = carry
        k1, k2, k3 = jax.random.split(k, 3)
        u1 = jax.random.uniform(k1, shape, jnp.float32, 1e-7, 1.0)
        u2 = jax.random.uniform(k2, shape, jnp.float32, 1e-7, 1.0)
        u3 = jax.random.uniform(k3, shape, jnp.float32, 1e-7, 1.0)
        z = jnp.cos(jnp.pi * u1)
        f = (1.0 + s * z) / (s + z)
        c = kappa * (s - f)
        accept = (c * (2.0 - c) - u2 > 0) | (jnp.log(c / u2) + 1.0 - c >= 0)
        theta = jnp.sign(u3 - 0.5) * jnp.arccos(jnp.clip(f, -1.0, 1.0))
        out = jnp.where(done, out, jnp.where(accept, theta, out))
        done = done | accept
        return (out, done), None

    keys = jax.random.split(key, 64)
    init = (jnp.zeros(shape, jnp.float32), jnp.zeros(shape, bool))
    (theta, _done), _ = jax.lax.scan(body, init, keys)
    return (jnp.mod(theta + mu + jnp.pi, 2.0 * jnp.pi) - jnp.pi).astype(dt)


@register("_npi_zipf", aliases=["random_zipf", "zipf"],
          differentiable=False, needs_rng=True)
def _npi_zipf(key, a=2.0, size=(), dtype=None):
    """Devroye's rejection-inversion sampler for the Zipf distribution,
    vectorized with a fixed 64-round accept mask (acceptance rate is
    >= 1/2 for a > 1, so 64 rounds leave < 1e-19 unfilled)."""
    if not a > 1.0:
        raise ValueError("zipf: a must be > 1 (got %r)" % (a,))
    dt = dtype or "int32"
    shape = size or ()
    am1 = a - 1.0
    b = jnp.power(2.0, am1)

    def body(carry, k):
        out, done = carry
        k1, k2 = jax.random.split(k)
        u = jax.random.uniform(k1, shape, jnp.float32, 1e-7, 1.0)
        v = jax.random.uniform(k2, shape, jnp.float32)
        x = jnp.floor(jnp.power(u, -1.0 / am1))
        t = jnp.power(1.0 + 1.0 / x, am1)
        accept = (v * x * (t - 1.0) / (b - 1.0) <= t / b) & \
            (x >= 1.0) & jnp.isfinite(x)
        out = jnp.where(done, out, jnp.where(accept, x, out))
        done = done | accept
        return (out, done), None

    keys = jax.random.split(key, 64)
    init = (jnp.ones(shape, jnp.float32), jnp.zeros(shape, bool))
    (x, _done), _ = jax.lax.scan(body, init, keys)
    return x.astype(dt)
