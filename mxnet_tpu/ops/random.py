"""Random sampling ops.

Reference: src/operator/random/sample_op.cc (_random_uniform, _random_normal,
...), src/resource.cc (per-device cuRAND states seeded by mx.random.seed).

TPU-native: counter-based stateless RNG.  A process-global root key (set by
``mx.random.seed``) is folded with a monotonically increasing counter for
every sample op; the key is passed to the op as an ordinary array input so
the op stays pure/traceable.  This replaces the reference's per-device
ResourceManager kRandom states while keeping `mx.random.seed` determinism.
"""
from __future__ import annotations

import threading

import jax
import jax.numpy as jnp

from .registry import register

_state = threading.local()
_DEFAULT_SEED = 0


def _root():
    if not hasattr(_state, "key"):
        _state.key = jax.random.PRNGKey(_DEFAULT_SEED)
        _state.counter = 0
    return _state


def seed(seed_val: int) -> None:
    st = _root()
    st.key = jax.random.PRNGKey(int(seed_val))
    st.counter = 0


def next_key() -> jax.Array:
    st = _root()
    # Inside a hybridize/jit trace the key must be a *traced input*, not a
    # baked-in constant (else every cached-graph call would replay the same
    # dropout mask).  trace_key_scope installs a holder whose key is a tracer;
    # we split it so successive ops in one trace draw distinct streams.
    holder = getattr(st, "trace_holder", None)
    if holder is not None:
        holder[0], sub = jax.random.split(holder[0])
        return sub
    st.counter += 1
    return jax.random.fold_in(st.key, st.counter)


class trace_key_scope:
    """Route next_key() through a traced base key for the duration of a
    hybridized-graph trace (see gluon/block.py CachedOp)."""

    def __init__(self, key: jax.Array):
        self._holder = [key]

    def __enter__(self):
        st = _root()
        self._old = getattr(st, "trace_holder", None)
        st.trace_holder = self._holder
        return self

    def __exit__(self, *exc):
        _root().trace_holder = self._old
        return False


def _dt(dtype):
    if dtype in (None, "None"):
        return jnp.float32
    return jnp.bfloat16 if dtype == "bfloat16" else dtype


@register("_random_uniform", aliases=["random_uniform", "uniform"],
          differentiable=False, needs_rng=True)
def _uniform(key, low=0.0, high=1.0, shape=(), dtype=None):
    return jax.random.uniform(key, shape, _dt(dtype), minval=low, maxval=high)


@register("_random_normal", aliases=["random_normal", "normal"],
          differentiable=False, needs_rng=True)
def _normal(key, loc=0.0, scale=1.0, shape=(), dtype=None):
    return jax.random.normal(key, shape, _dt(dtype)) * scale + loc


@register("_random_gamma", aliases=["random_gamma"], differentiable=False, needs_rng=True)
def _gamma(key, alpha=1.0, beta=1.0, shape=(), dtype=None):
    return jax.random.gamma(key, alpha, shape, _dt(dtype)) * beta


@register("_random_exponential", aliases=["random_exponential"],
          differentiable=False, needs_rng=True)
def _exponential(key, lam=1.0, shape=(), dtype=None):
    return jax.random.exponential(key, shape, _dt(dtype)) / lam


@register("_random_poisson", aliases=["random_poisson"], differentiable=False, needs_rng=True)
def _poisson(key, lam=1.0, shape=(), dtype=None):
    return jax.random.poisson(key, lam, shape).astype(_dt(dtype))


@register("_random_randint", aliases=["random_randint"], differentiable=False, needs_rng=True)
def _randint(key, low=0, high=2, shape=(), dtype="int32"):
    return jax.random.randint(key, shape, low, high, dtype or jnp.int32)


@register("_random_bernoulli", aliases=["bernoulli"], differentiable=False, needs_rng=True)
def _bernoulli(key, prob=0.5, shape=(), dtype=None):
    return jax.random.bernoulli(key, prob, shape).astype(_dt(dtype))


@register("_sample_multinomial", aliases=["sample_multinomial", "multinomial"],
          differentiable=False, needs_rng=True)
def _multinomial(key, data, shape=(), get_prob=False, dtype="int32"):
    # data: (..., k) probabilities
    logits = jnp.log(jnp.maximum(data, 1e-37))
    n = 1
    for s in (shape if isinstance(shape, tuple) else (shape,)):
        n *= int(s) if s else 1
    out_shape = data.shape[:-1] + ((shape if isinstance(shape, tuple) else (shape,)) if shape else ())
    samp = jax.random.categorical(key, logits, axis=-1,
                                  shape=(n,) + data.shape[:-1])
    if data.ndim == 1:
        samp = samp.reshape(out_shape if shape else ())
    else:
        samp = jnp.moveaxis(samp, 0, -1).reshape(out_shape)
    samp = samp.astype(dtype or jnp.int32)
    if get_prob:
        # REINFORCE path: also return log-prob of each drawn sample
        logp = jnp.take_along_axis(
            jnp.broadcast_to(logits, samp.shape + (logits.shape[-1],)),
            samp[..., None].astype(jnp.int32), axis=-1)[..., 0]
        return samp, logp
    return samp


@register("shuffle", aliases=["_shuffle"], differentiable=False, needs_rng=True)
def _shuffle(key, data):
    return jax.random.permutation(key, data, axis=0)


@register("sample_normal_like", differentiable=False, needs_rng=True)
def _normal_like(key, data, loc=0.0, scale=1.0):
    return jax.random.normal(key, data.shape, data.dtype) * scale + loc
