"""numpy-semantics internal ops — the ``_npi_*`` namespace.

Reference: ``src/operator/numpy/`` (np_elemwise_broadcast_op.cc,
np_broadcast_reduce_op_value.cc, np_matrix_op.cc, np_insert_op*.cc, ...)
registering the ``_npi_*``/``_np_*`` internal ops that
``python/mxnet/numpy/multiarray.py`` dispatches to.

Semantics note (why these are DISTINCT ops, not aliases of the legacy
``mx.nd`` surface): the legacy ops carry MXNet conventions — comparisons
return float32, no int→float promotion, 1-d-minimum outputs — while the
``_npi_`` layer implements *NumPy* conventions: bool outputs for
comparisons/logic, NumPy dtype-promotion on mixed inputs, 0-d scalars.
jax.numpy already implements the NumPy rules, so each op here is a thin
pure function over jnp — XLA-traceable, jit-cached by the dispatcher,
and differentiable through ``jax.vjp`` where the math is.

Ops whose OUTPUT SHAPE depends on input *values* (unique, nonzero,
set ops, ...) are registered ``no_jit`` and computed eagerly — same
posture as the reference, which runs these on CPU with dynamic outputs.

Routing: ``mxnet_tpu/numpy/__init__.py`` dispatches its function surface
through these registered names via ``invoke`` so numpy calls hit the
per-op jit cache and the autograd tape like every other op.
"""
from __future__ import annotations

import numpy as _onp

import jax
import jax.numpy as jnp

from .registry import alias, register

__all__ = []  # everything is reached through the registry


def _reg(name, fn, differentiable=True, aliases=(), num_outputs=1,
         no_jit=False):
    fn.__name__ = name
    if not fn.__doc__:
        fn.__doc__ = ("numpy-semantics %s (reference: src/operator/numpy/ "
                      "%s registration)" % (name.replace("_npi_", ""), name))
    register(name, fn, differentiable=differentiable, aliases=aliases,
             num_outputs=num_outputs, no_jit=no_jit)


# ---------------------------------------------------------------------------
# unary elementwise
# ---------------------------------------------------------------------------

def _unary(jfn):
    def fn(a):
        return jfn(a)
    return fn


def _np_conjugate(a):
    # numpy promotes bool input to int8; jnp keeps bool
    out = jnp.conjugate(a)
    if out.dtype == jnp.bool_:
        out = out.astype(jnp.int8)
    return out


_UNARY_DIFF = {
    "absolute": jnp.absolute, "fabs": jnp.fabs, "negative": jnp.negative,
    "positive": jnp.positive, "conjugate": _np_conjugate,
    "exp": jnp.exp, "exp2": jnp.exp2, "expm1": jnp.expm1,
    "log": jnp.log, "log2": jnp.log2, "log10": jnp.log10, "log1p": jnp.log1p,
    "sqrt": jnp.sqrt, "cbrt": jnp.cbrt, "square": jnp.square,
    "reciprocal": jnp.reciprocal,
    "sin": jnp.sin, "cos": jnp.cos, "tan": jnp.tan,
    "arcsin": jnp.arcsin, "arccos": jnp.arccos, "arctan": jnp.arctan,
    "sinh": jnp.sinh, "cosh": jnp.cosh, "tanh": jnp.tanh,
    "arcsinh": jnp.arcsinh, "arccosh": jnp.arccosh, "arctanh": jnp.arctanh,
    "degrees": jnp.degrees, "radians": jnp.radians,
    "deg2rad": jnp.deg2rad, "rad2deg": jnp.rad2deg,
    "sinc": jnp.sinc, "i0": jnp.i0,
}

def _as_float_round(jfn):
    # numpy's round family PROMOTES integer/bool input to float output;
    # jnp passes ints through unchanged
    def fn(a):
        if not jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating):
            a = jnp.asarray(a).astype(jnp.float32)
        return jfn(a)
    return fn


_UNARY_NONDIFF = {
    "sign": jnp.sign, "signbit": jnp.signbit,
    "floor": _as_float_round(jnp.floor),
    "ceil": _as_float_round(jnp.ceil),
    "trunc": _as_float_round(jnp.trunc),
    "rint": jnp.rint,
    "fix": _as_float_round(jnp.trunc),  # np.fix == truncate toward zero
    "isnan": jnp.isnan, "isinf": jnp.isinf, "isfinite": jnp.isfinite,
    "isneginf": jnp.isneginf, "isposinf": jnp.isposinf,
    "logical_not": jnp.logical_not, "bitwise_not": jnp.bitwise_not,
    "invert": jnp.invert,
}

for _n, _f in _UNARY_DIFF.items():
    _reg("_npi_" + _n, _unary(_f))
for _n, _f in _UNARY_NONDIFF.items():
    _reg("_npi_" + _n, _unary(_f), differentiable=False)


def _npi_around(a, decimals=0):
    return jnp.round(a, decimals)


_reg("_npi_around", _npi_around, differentiable=False,
     aliases=["_npi_round", "_npi_round_"])


def _npi_nan_to_num(a, copy=True, nan=0.0, posinf=None, neginf=None):
    return jnp.nan_to_num(a, nan=nan, posinf=posinf, neginf=neginf)


_reg("_npi_nan_to_num", _npi_nan_to_num, differentiable=False)


def _npi_real(a):
    return jnp.real(a)


def _npi_imag(a):
    return jnp.imag(a)


_reg("_npi_real", _npi_real)
_reg("_npi_imag", _npi_imag)


# ---------------------------------------------------------------------------
# binary elementwise (numpy promotion; scalars arrive as arrays or params)
# ---------------------------------------------------------------------------

def _binary(jfn):
    def fn(a, b):
        return jfn(a, b)
    return fn


_BINARY_DIFF = {
    "add": jnp.add, "subtract": jnp.subtract, "multiply": jnp.multiply,
    "true_divide": jnp.true_divide, "power": jnp.power,
    "float_power": jnp.float_power,
    "arctan2": jnp.arctan2, "hypot": jnp.hypot,
    "logaddexp": jnp.logaddexp, "logaddexp2": jnp.logaddexp2,
    "maximum": jnp.maximum, "minimum": jnp.minimum,
    "fmax": jnp.fmax, "fmin": jnp.fmin,
    "copysign": jnp.copysign,
}

# ONE nan-propagating heaviside serves both the legacy "heaviside" op
# (ops/extra.py registration) and the _npi_ numpy layer
from .extra import _heaviside as _np_heaviside  # noqa: E402

_BINARY_NONDIFF = {
    "floor_divide": jnp.floor_divide, "remainder": jnp.remainder,
    "fmod": jnp.fmod, "nextafter": jnp.nextafter, "ldexp": jnp.ldexp,
    "heaviside": _np_heaviside,
    "gcd": jnp.gcd, "lcm": jnp.lcm,
    "bitwise_and": jnp.bitwise_and, "bitwise_or": jnp.bitwise_or,
    "bitwise_xor": jnp.bitwise_xor,
    "left_shift": jnp.left_shift, "right_shift": jnp.right_shift,
    "equal": jnp.equal, "not_equal": jnp.not_equal,
    "less": jnp.less, "less_equal": jnp.less_equal,
    "greater": jnp.greater, "greater_equal": jnp.greater_equal,
    "logical_and": jnp.logical_and, "logical_or": jnp.logical_or,
    "logical_xor": jnp.logical_xor,
}

for _n, _f in _BINARY_DIFF.items():
    _reg("_npi_" + _n, _binary(_f))
for _n, _f in _BINARY_NONDIFF.items():
    _reg("_npi_" + _n, _binary(_f), differentiable=False)


# scalar variants: how 2.x graphs encode `a + 2` / `2 / a` (reference:
# np_elemwise_broadcast_op.cc _npi_*_scalar / _npi_r*_scalar).  The
# scalar stays a PYTHON number so jax's weak typing reproduces numpy's
# array-scalar promotion; is_int preserves integer semantics.
def _scalar_variant(jfn, reflected):
    def fn(data, scalar=0.0, is_int=False):
        s = int(scalar) if bool(is_int) and float(scalar).is_integer() \
            else float(scalar)
        return jfn(s, data) if reflected else jfn(data, s)
    return fn


_NONCOMMUTATIVE = ("subtract", "true_divide", "power", "mod",
                   "floor_divide", "arctan2", "copysign", "ldexp",
                   "nextafter")

def _rldexp(data, scalar=0.0, is_int=False):
    # reference semantics: scalar * 2**data, defined for FLOAT exponents
    # too (jnp.ldexp rejects non-integer exponent dtypes)
    del is_int
    return float(scalar) * jnp.exp2(data)


for _n, _f in list(_BINARY_DIFF.items()) + list(_BINARY_NONDIFF.items()):
    _d = _n in _BINARY_DIFF
    _mx = "mod" if _n == "remainder" else _n
    # no_jit: the scalar is a static attr — a per-op jit would compile
    # one executable PER SCALAR VALUE (cache blowup for decaying-lr-style
    # loops); the plain jnp call is one dispatch anyway, and under an
    # outer jit/hybridize trace the kernel inlines with the scalar baked
    # in, exactly like the reference graph attr
    _reg("_npi_%s_scalar" % _mx, _scalar_variant(_f, False),
         differentiable=_d, no_jit=True,
         aliases=(("_npi_%s_scalar" % _n,) if _mx != _n else ()))
    if _mx in _NONCOMMUTATIVE and _mx != "ldexp":
        _reg("_npi_r%s_scalar" % _mx, _scalar_variant(_f, True),
             differentiable=_d, no_jit=True)

_reg("_npi_rldexp_scalar", _rldexp, no_jit=True)
alias("_npi_remainder", "_npi_mod")
_reg("_npi_rarctan2", _binary(lambda a, b: jnp.arctan2(b, a)))
_reg("_npi_rcopysign", _binary(lambda a, b: jnp.copysign(b, a)))
_reg("_npi_rldexp", lambda a, b: b * jnp.exp2(a))


def _npi_spacing(a):
    # SIGNED distance to the next representable value away from zero
    # (np.spacing(-1.0) == -eps)
    away = jnp.where(a >= 0, jnp.inf, -jnp.inf).astype(a.dtype)
    return jnp.nextafter(a, away) - a


_reg("_npi_spacing", _npi_spacing, differentiable=False)


def _npi_divmod(a, b):
    return jnp.divmod(a, b)


_reg("_npi_divmod", _npi_divmod, differentiable=False, num_outputs=2)


def _npi_modf(a):
    return jnp.modf(a)


_reg("_npi_modf", _npi_modf, differentiable=False, num_outputs=2)


def _npi_frexp(a):
    return jnp.frexp(a)


_reg("_npi_frexp", _npi_frexp, differentiable=False, num_outputs=2)


def _npi_isclose(a, b, rtol=1e-05, atol=1e-08, equal_nan=False):
    return jnp.isclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan)


def _npi_allclose(a, b, rtol=1e-05, atol=1e-08, equal_nan=False):
    return jnp.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan)


def _npi_array_equal(a, b):
    return jnp.array_equal(a, b)


def _npi_array_equiv(a, b):
    return jnp.array_equiv(a, b)


_reg("_npi_isclose", _npi_isclose, differentiable=False)
_reg("_npi_allclose", _npi_allclose, differentiable=False)
_reg("_npi_array_equal", _npi_array_equal, differentiable=False)
_reg("_npi_array_equiv", _npi_array_equiv, differentiable=False)


# ---------------------------------------------------------------------------
# reductions
# ---------------------------------------------------------------------------

def _red(jfn):
    def fn(a, axis=None, keepdims=False):
        return jfn(a, axis=axis, keepdims=keepdims)
    return fn


def _red_dtype(jfn):
    def fn(a, axis=None, dtype=None, keepdims=False):
        return jfn(a, axis=axis, dtype=dtype, keepdims=keepdims)
    return fn


def _red_ddof(jfn):
    def fn(a, axis=None, dtype=None, ddof=0, keepdims=False):
        return jfn(a, axis=axis, dtype=dtype, ddof=ddof, keepdims=keepdims)
    return fn


_reg("_npi_sum", _red_dtype(jnp.sum))
_reg("_npi_prod", _red_dtype(jnp.prod))
_reg("_npi_mean", _red_dtype(jnp.mean))
_reg("_npi_nansum", _red_dtype(jnp.nansum))
_reg("_npi_nanprod", _red_dtype(jnp.nanprod))
_reg("_npi_nanmean", _red_dtype(jnp.nanmean))
_reg("_npi_std", _red_ddof(jnp.std))
_reg("_npi_var", _red_ddof(jnp.var))
_reg("_npi_nanstd", _red_ddof(jnp.nanstd))
_reg("_npi_nanvar", _red_ddof(jnp.nanvar))
_reg("_npi_amax", _red(jnp.max), aliases=["_npi_max"])
_reg("_npi_amin", _red(jnp.min), aliases=["_npi_min"])
_reg("_npi_nanmax", _red(jnp.nanmax))
_reg("_npi_nanmin", _red(jnp.nanmin))
_reg("_npi_ptp", _red(jnp.ptp), differentiable=False)
_reg("_npi_all", _red(jnp.all), differentiable=False)
_reg("_npi_any", _red(jnp.any), differentiable=False)


def _npi_count_nonzero(a, axis=None, keepdims=False):
    return jnp.count_nonzero(a, axis=axis, keepdims=keepdims)


_reg("_npi_count_nonzero", _npi_count_nonzero, differentiable=False)


def _arg_red(jfn):
    def fn(a, axis=None, keepdims=False):
        out = jfn(a, axis=axis)
        if keepdims:
            out = jnp.expand_dims(
                out, tuple(range(a.ndim)) if axis is None else axis)
        return out
    return fn


_reg("_npi_argmax", _arg_red(jnp.argmax), differentiable=False)
_reg("_npi_argmin", _arg_red(jnp.argmin), differentiable=False)
_reg("_npi_nanargmax", _arg_red(jnp.nanargmax), differentiable=False)
_reg("_npi_nanargmin", _arg_red(jnp.nanargmin), differentiable=False)


def _cum(jfn):
    def fn(a, axis=None, dtype=None):
        return jfn(a, axis=axis, dtype=dtype)
    return fn


_reg("_npi_cumsum", _cum(jnp.cumsum))
_reg("_npi_cumprod", _cum(jnp.cumprod))
_reg("_npi_nancumsum", _cum(jnp.nancumsum))
_reg("_npi_nancumprod", _cum(jnp.nancumprod))


def _npi_median(a, axis=None, keepdims=False):
    return jnp.median(a, axis=axis, keepdims=keepdims)


def _npi_nanmedian(a, axis=None, keepdims=False):
    return jnp.nanmedian(a, axis=axis, keepdims=keepdims)


def _npi_percentile(a, q, axis=None, method="linear", keepdims=False):
    return jnp.percentile(a, jnp.asarray(q), axis=axis, method=method,
                          keepdims=keepdims)


def _npi_nanpercentile(a, q, axis=None, method="linear", keepdims=False):
    return jnp.nanpercentile(a, jnp.asarray(q), axis=axis, method=method,
                             keepdims=keepdims)


def _npi_quantile(a, q, axis=None, method="linear", keepdims=False):
    return jnp.quantile(a, jnp.asarray(q), axis=axis, method=method,
                        keepdims=keepdims)


def _npi_nanquantile(a, q, axis=None, method="linear", keepdims=False):
    return jnp.nanquantile(a, jnp.asarray(q), axis=axis, method=method,
                           keepdims=keepdims)


_reg("_npi_median", _npi_median)
_reg("_npi_nanmedian", _npi_nanmedian)
_reg("_npi_percentile", _npi_percentile)
_reg("_npi_nanpercentile", _npi_nanpercentile)
_reg("_npi_quantile", _npi_quantile)
_reg("_npi_nanquantile", _npi_nanquantile)


def _npi_average(a, weights=None, axis=None):
    if weights is None:
        return jnp.mean(a, axis=axis)
    return jnp.average(a, axis=axis, weights=weights)


_reg("_npi_average", _npi_average)


def _npi_trapz(y, x=None, dx=1.0, axis=-1):
    f = getattr(jnp, "trapezoid", None) or jnp.trapz
    if x is None:
        return f(y, dx=dx, axis=axis)
    return f(y, x, axis=axis)


_reg("_npi_trapz", _npi_trapz, aliases=["_npi_trapezoid"])


# ---------------------------------------------------------------------------
# shape manipulation
# ---------------------------------------------------------------------------

def _npi_reshape(a, newshape, order="C"):
    return jnp.reshape(a, newshape, order=order)


def _npi_ravel(a, order="C"):
    return jnp.ravel(a, order=order)


def _npi_transpose(a, axes=None):
    return jnp.transpose(a, axes)


def _npi_swapaxes(a, axis1, axis2):
    return jnp.swapaxes(a, axis1, axis2)


def _npi_moveaxis(a, source, destination):
    return jnp.moveaxis(a, source, destination)


def _npi_rollaxis(a, axis, start=0):
    return jnp.rollaxis(a, axis, start)


def _npi_expand_dims(a, axis):
    return jnp.expand_dims(a, axis)


def _npi_squeeze(a, axis=None):
    return jnp.squeeze(a, axis)


def _npi_broadcast_to(a, shape):
    return jnp.broadcast_to(a, shape)


def _npi_flip(a, axis=None):
    return jnp.flip(a, axis)


def _npi_fliplr(a):
    return jnp.fliplr(a)


def _npi_flipud(a):
    return jnp.flipud(a)


def _npi_roll(a, shift, axis=None):
    return jnp.roll(a, shift, axis)


def _npi_rot90(a, k=1, axes=(0, 1)):
    return jnp.rot90(a, k, axes)


for _n in ("reshape", "ravel", "transpose", "swapaxes", "moveaxis",
           "rollaxis", "expand_dims", "squeeze", "broadcast_to", "flip",
           "fliplr", "flipud", "roll", "rot90"):
    _reg("_npi_" + _n, globals()["_npi_" + _n])


def _npi_concatenate(*arrays, axis=0):
    return jnp.concatenate(arrays, axis=axis)


def _npi_stack(*arrays, axis=0):
    return jnp.stack(arrays, axis=axis)


def _npi_column_stack(*arrays):
    return jnp.column_stack(arrays)


def _npi_hstack(*arrays):
    return jnp.hstack(arrays)


def _npi_vstack(*arrays):
    return jnp.vstack(arrays)


def _npi_dstack(*arrays):
    return jnp.dstack(arrays)


_reg("_npi_concatenate", _npi_concatenate, aliases=["_npi_concat"])
_reg("_npi_stack", _npi_stack)
_reg("_npi_column_stack", _npi_column_stack)
_reg("_npi_hstack", _npi_hstack)
_reg("_npi_vstack", _npi_vstack)
_reg("_npi_dstack", _npi_dstack)


def _split_like(jfn):
    def fn(a, indices_or_sections, axis=0):
        return tuple(jfn(a, indices_or_sections, axis=axis))
    return fn


_reg("_npi_split", _split_like(jnp.split), num_outputs=-1)
_reg("_npi_array_split", _split_like(jnp.array_split), num_outputs=-1)


def _npi_hsplit(a, indices_or_sections):
    return tuple(jnp.hsplit(a, indices_or_sections))


def _npi_vsplit(a, indices_or_sections):
    return tuple(jnp.vsplit(a, indices_or_sections))


def _npi_dsplit(a, indices_or_sections):
    return tuple(jnp.dsplit(a, indices_or_sections))


_reg("_npi_hsplit", _npi_hsplit, num_outputs=-1)
_reg("_npi_vsplit", _npi_vsplit, num_outputs=-1)
_reg("_npi_dsplit", _npi_dsplit, num_outputs=-1)


def _npi_repeat(a, repeats, axis=None):
    return jnp.repeat(a, repeats, axis=axis)


def _npi_tile(a, reps):
    return jnp.tile(a, reps)


def _npi_append(arr, values, axis=None):
    return jnp.append(arr, values, axis=axis)


_reg("_npi_repeat", _npi_repeat)
_reg("_npi_tile", _npi_tile)
_reg("_npi_append", _npi_append)


def _npi_pad(a, pad_width, mode="constant", constant_values=0):
    if mode == "constant":
        return jnp.pad(a, pad_width, mode, constant_values=constant_values)
    return jnp.pad(a, pad_width, mode)


_reg("_npi_pad", _npi_pad)


def _npi_delete(arr, obj, axis=None):
    # static obj (int/slice/index list passed as attr) -> static out shape
    return jnp.delete(arr, obj if not isinstance(obj, list) else
                      jnp.asarray(obj), axis=axis)


def _npi_insert(arr, values, obj, axis=None):
    return jnp.insert(arr, obj if not isinstance(obj, list) else
                      jnp.asarray(obj), values, axis=axis)


_reg("_npi_delete", _npi_delete, no_jit=True, differentiable=False)
_reg("_npi_insert", _npi_insert, no_jit=True, differentiable=False)


def _npi_trim_zeros(filt, trim="fb"):
    return jnp.asarray(_onp.trim_zeros(_onp.asarray(filt), trim))


_reg("_npi_trim_zeros", _npi_trim_zeros, no_jit=True, differentiable=False)


# ---------------------------------------------------------------------------
# indexing / selection
# ---------------------------------------------------------------------------

def _npi_take(a, indices, axis=None, mode="clip"):
    return jnp.take(a, indices, axis=axis, mode=mode)


def _npi_take_along_axis(a, indices, axis):
    return jnp.take_along_axis(a, indices, axis=axis)


def _npi_compress(condition, a, axis=None):
    return jnp.asarray(_onp.compress(_onp.asarray(condition),
                                     _onp.asarray(a), axis=axis))


def _npi_extract(condition, arr):
    return jnp.asarray(_onp.extract(_onp.asarray(condition),
                                    _onp.asarray(arr)))


def _npi_choose(a, *choices, mode="clip"):
    return jnp.choose(a, list(choices), mode=mode)


def _npi_select(*args, default=0):
    n = len(args) // 2
    return jnp.select(list(args[:n]), list(args[n:]), default=default)


def _npi_where(condition, x, y):
    return jnp.where(condition, x, y)


_reg("_npi_take", _npi_take)
_reg("_npi_take_along_axis", _npi_take_along_axis)
_reg("_npi_compress", _npi_compress, no_jit=True, differentiable=False)
_reg("_npi_extract", _npi_extract, no_jit=True, differentiable=False)
_reg("_npi_choose", _npi_choose, differentiable=False)
_reg("_npi_select", _npi_select)
_reg("_npi_where", _npi_where)


def _npi_nonzero(a):
    return tuple(jnp.asarray(i) for i in _onp.nonzero(_onp.asarray(a)))


def _npi_flatnonzero(a):
    return jnp.asarray(_onp.flatnonzero(_onp.asarray(a)))


def _npi_argwhere(a):
    return jnp.asarray(_onp.argwhere(_onp.asarray(a)))


_reg("_npi_nonzero", _npi_nonzero, no_jit=True, differentiable=False,
     num_outputs=-1)
_reg("_npi_flatnonzero", _npi_flatnonzero, no_jit=True, differentiable=False)
_reg("_npi_argwhere", _npi_argwhere, no_jit=True, differentiable=False)


def _npi_searchsorted(a, v, side="left"):
    return jnp.searchsorted(a, v, side=side)


_reg("_npi_searchsorted", _npi_searchsorted, differentiable=False)


def _npi_unravel_index(indices, shape):
    return tuple(jnp.unravel_index(indices, shape))


def _npi_ravel_multi_index(*multi_index, dims, mode="clip"):
    return jnp.ravel_multi_index(multi_index, dims, mode=mode)


_reg("_npi_unravel_index", _npi_unravel_index, differentiable=False,
     num_outputs=-1)
_reg("_npi_ravel_multi_index", _npi_ravel_multi_index, differentiable=False)


def _npi_diag_indices_from(a):
    return tuple(jnp.diag_indices_from(a))


def _npi_tril_indices(n, k=0, m=None):
    return tuple(jnp.tril_indices(n, k, m))


def _npi_triu_indices(n, k=0, m=None):
    return tuple(jnp.triu_indices(n, k, m))


def _npi_indices(dimensions, dtype=None):
    return jnp.indices(tuple(dimensions),
                       dtype=dtype or jnp.int32)


_reg("_npi_diag_indices_from", _npi_diag_indices_from, differentiable=False,
     num_outputs=-1)
_reg("_npi_tril_indices", _npi_tril_indices, differentiable=False,
     num_outputs=2)
_reg("_npi_triu_indices", _npi_triu_indices, differentiable=False,
     num_outputs=2)
_reg("_npi_indices", _npi_indices, differentiable=False)


# ---------------------------------------------------------------------------
# linear algebra (numpy calling conventions; dense MXU work)
# ---------------------------------------------------------------------------

def _npi_dot(a, b):
    return jnp.dot(a, b)


def _npi_vdot(a, b):
    return jnp.vdot(a, b)


def _npi_inner(a, b):
    return jnp.inner(a, b)


def _npi_outer(a, b):
    return jnp.outer(a, b)


def _npi_matmul(a, b):
    return jnp.matmul(a, b)


def _npi_tensordot(a, b, axes=2):
    if isinstance(axes, list):
        axes = tuple(tuple(x) if isinstance(x, list) else x for x in axes)
    return jnp.tensordot(a, b, axes=axes)


def _npi_trace_np(a, offset=0, axis1=0, axis2=1):
    return jnp.trace(a, offset, axis1, axis2)


_reg("_npi_dot", _npi_dot)
_reg("_npi_vdot", _npi_vdot)
_reg("_npi_inner", _npi_inner)
_reg("_npi_outer", _npi_outer)
_reg("_npi_matmul", _npi_matmul)
_reg("_npi_tensordot", _npi_tensordot)
_reg("_npi_trace", _npi_trace_np)


# ---------------------------------------------------------------------------
# set operations (value-dependent shapes: eager numpy, reference posture)
# ---------------------------------------------------------------------------

def _npi_unique(a, return_index=False, return_inverse=False,
                return_counts=False, axis=None):
    out = _onp.unique(_onp.asarray(a), return_index=return_index,
                      return_inverse=return_inverse,
                      return_counts=return_counts, axis=axis)
    if isinstance(out, tuple):
        return tuple(jnp.asarray(o) for o in out)
    return jnp.asarray(out)


def _npi_isin(element, test_elements, invert=False):
    return jnp.isin(element, test_elements, invert=invert)


def _npi_in1d(ar1, ar2, invert=False):
    return jnp.isin(jnp.ravel(ar1), ar2, invert=invert)


def _npi_intersect1d(ar1, ar2):
    return jnp.asarray(_onp.intersect1d(_onp.asarray(ar1),
                                        _onp.asarray(ar2)))


def _npi_union1d(ar1, ar2):
    return jnp.asarray(_onp.union1d(_onp.asarray(ar1), _onp.asarray(ar2)))


def _npi_setdiff1d(ar1, ar2):
    return jnp.asarray(_onp.setdiff1d(_onp.asarray(ar1), _onp.asarray(ar2)))


def _npi_setxor1d(ar1, ar2):
    return jnp.asarray(_onp.setxor1d(_onp.asarray(ar1), _onp.asarray(ar2)))


_reg("_npi_unique", _npi_unique, no_jit=True, differentiable=False,
     num_outputs=-1)
_reg("_npi_isin", _npi_isin, differentiable=False)
_reg("_npi_in1d", _npi_in1d, differentiable=False)
for _n in ("intersect1d", "union1d", "setdiff1d", "setxor1d"):
    _reg("_npi_" + _n, globals()["_npi_" + _n], no_jit=True,
         differentiable=False)


# ---------------------------------------------------------------------------
# sorting
# ---------------------------------------------------------------------------

def _npi_sort(a, axis=-1, kind=None):
    return jnp.sort(a, axis=axis)


def _npi_argsort_np(a, axis=-1, kind=None):
    return jnp.argsort(a, axis=axis)


def _npi_lexsort(*keys, axis=-1):
    return jnp.lexsort(keys, axis=axis)


def _npi_partition(a, kth, axis=-1):
    return jnp.partition(a, kth, axis=axis)


def _npi_argpartition(a, kth, axis=-1):
    return jnp.argpartition(a, kth, axis=axis)


def _npi_msort(a):
    return jnp.sort(a, axis=0)


_reg("_npi_sort", _npi_sort)
_reg("_npi_argsort", _npi_argsort_np, differentiable=False)
_reg("_npi_lexsort", _npi_lexsort, differentiable=False)
_reg("_npi_partition", _npi_partition, differentiable=False)
_reg("_npi_argpartition", _npi_argpartition, differentiable=False)
_reg("_npi_msort", _npi_msort)


# ---------------------------------------------------------------------------
# math misc
# ---------------------------------------------------------------------------

def _npi_clip(a, a_min=None, a_max=None):
    return jnp.clip(a, a_min, a_max)


def _npi_interp_np(x, xp, fp, left=None, right=None):
    return jnp.interp(x, xp, fp, left=left, right=right)


def _npi_ediff1d(ary, to_end=None, to_begin=None):
    return jnp.ediff1d(ary, to_end=to_end, to_begin=to_begin)


def _npi_diff(a, n=1, axis=-1):
    return jnp.diff(a, n=n, axis=axis)


def _npi_gradient(f, *varargs, axis=None):
    out = jnp.gradient(f, *varargs, axis=axis)
    if isinstance(out, list):
        return tuple(out)
    return out


def _npi_convolve(a, v, mode="full"):
    return jnp.convolve(a, v, mode=mode)


def _npi_correlate(a, v, mode="valid"):
    return jnp.correlate(a, v, mode=mode)


def _npi_polyval(p, x):
    return jnp.polyval(p, x)


def _npi_corrcoef(x):
    return jnp.corrcoef(x)


def _npi_cov(m, rowvar=True, bias=False, ddof=None):
    return jnp.cov(m, rowvar=rowvar, bias=bias, ddof=ddof)


def _npi_histogram(a, weights=None, bins=10, range=None, density=False):
    h, e = jnp.histogram(a, bins=bins, range=range, weights=weights,
                         density=density)
    return h, e


def _npi_bincount(x, weights=None, minlength=0):
    # numpy semantics: out length = max(x)+1 (value-dependent) -> eager
    return jnp.asarray(_onp.bincount(_onp.asarray(x),
                                     None if weights is None
                                     else _onp.asarray(weights),
                                     minlength))


def _npi_digitize(x, bins, right=False):
    return jnp.digitize(x, bins, right=right)


_reg("_npi_clip", _npi_clip)
_reg("_npi_interp", _npi_interp_np)
_reg("_npi_ediff1d", _npi_ediff1d)
_reg("_npi_diff", _npi_diff)
_reg("_npi_gradient", _npi_gradient, num_outputs=-1)
_reg("_npi_convolve", _npi_convolve)
_reg("_npi_correlate", _npi_correlate)
_reg("_npi_polyval", _npi_polyval)
_reg("_npi_corrcoef", _npi_corrcoef)
_reg("_npi_cov", _npi_cov)
_reg("_npi_histogram", _npi_histogram, differentiable=False, num_outputs=2)
_reg("_npi_bincount", _npi_bincount, no_jit=True, differentiable=False)
_reg("_npi_digitize", _npi_digitize, differentiable=False)


# ---------------------------------------------------------------------------
# windows + creation-like
# ---------------------------------------------------------------------------

def _win(jfn):
    def fn(M, dtype=None):
        out = jfn(int(M))
        return out.astype(dtype) if dtype else out
    return fn


_reg("_npi_bartlett", _win(jnp.bartlett), differentiable=False)
_reg("_npi_kaiser",
     (lambda M, beta=0.0, dtype=None:
      jnp.kaiser(int(M), beta).astype(dtype)
      if dtype else jnp.kaiser(int(M), beta)),
     differentiable=False)
_reg("_npi_blackman_np", _win(jnp.blackman), differentiable=False,
     aliases=["_npi_blackman"])
_reg("_npi_hamming_np", _win(jnp.hamming), differentiable=False,
     aliases=["_npi_hamming"])
_reg("_npi_hanning_np", _win(jnp.hanning), differentiable=False,
     aliases=["_npi_hanning"])


def _npi_full_like(a, fill_value, dtype=None):
    return jnp.full_like(a, fill_value, dtype=dtype)


def _npi_empty_like(a, dtype=None):
    return jnp.empty_like(a, dtype=dtype)


def _npi_identity(n, dtype=None):
    return jnp.identity(int(n), dtype=dtype)


def _npi_tri(N, M=None, k=0, dtype=None):
    return jnp.tri(int(N), M if M is None else int(M), k,
                   dtype=dtype or jnp.float32)


def _npi_diagflat(v, k=0):
    return jnp.diagflat(v, k)


def _npi_vander(x, N=None, increasing=False):
    return jnp.vander(x, N, increasing=increasing)


def _npi_meshgrid(*xi, indexing="xy", sparse=False):
    return tuple(jnp.meshgrid(*xi, indexing=indexing, sparse=sparse))


def _npi_broadcast_arrays(*args):
    return tuple(jnp.broadcast_arrays(*args))


def _npi_logspace(start, stop, num=50, endpoint=True, base=10.0, dtype=None):
    return jnp.logspace(start, stop, int(num), endpoint=endpoint, base=base,
                        dtype=dtype)


def _npi_geomspace(start, stop, num=50, endpoint=True, dtype=None):
    return jnp.geomspace(start, stop, int(num), endpoint=endpoint,
                         dtype=dtype)


_reg("_npi_full_like", _npi_full_like, differentiable=False)
_reg("_npi_empty_like", _npi_empty_like, differentiable=False)
_reg("_npi_identity", _npi_identity, differentiable=False)
_reg("_npi_tri", _npi_tri, differentiable=False)
_reg("_npi_diagflat", _npi_diagflat)
_reg("_npi_vander", _npi_vander, differentiable=False)
_reg("_npi_meshgrid", _npi_meshgrid, differentiable=False, num_outputs=-1)
_reg("_npi_broadcast_arrays", _npi_broadcast_arrays, num_outputs=-1)
_reg("_npi_logspace", _npi_logspace, differentiable=False)
_reg("_npi_geomspace", _npi_geomspace, differentiable=False)


# ---------------------------------------------------------------------------
# numpy linalg (reference: src/operator/numpy/linalg/np_*.cc — _npi_svd,
# _npi_qr, _npi_solve, _npi_pinv, _npi_cholesky, _npi_eigvalsh, ...)
# ---------------------------------------------------------------------------


def _npi_svd(a, full_matrices=False):
    u, s, vh = jnp.linalg.svd(a, full_matrices=full_matrices)
    return u, s, vh


def _npi_qr(a):
    q, r = jnp.linalg.qr(a)
    return q, r


def _npi_solve(a, b):
    return jnp.linalg.solve(a, b)


def _npi_lstsq(a, b, rcond=None):
    x, res, rank, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
    return x, res, rank, sv


def _npi_pinv(a, rcond=1e-15):
    return jnp.linalg.pinv(a, rcond)


def _npi_cholesky(a, lower=True):
    out = jnp.linalg.cholesky(a)
    return out if lower else jnp.swapaxes(out, -1, -2)


def _npi_eigvalsh(a, UPLO="L"):
    return jnp.linalg.eigvalsh(a, UPLO=UPLO)


def _npi_eigh(a, UPLO="L"):
    w, v = jnp.linalg.eigh(a, UPLO=UPLO)
    return w, v


def _npi_matrix_rank(M, tol=None, hermitian=False):
    return jnp.linalg.matrix_rank(M, tol=tol)


def _npi_matrix_power(a, n):
    return jnp.linalg.matrix_power(a, int(n))


def _npi_multi_dot(*arrays):
    return jnp.linalg.multi_dot(list(arrays))


def _npi_tensorsolve(a, b, axes=None):
    return jnp.linalg.tensorsolve(a, b, axes=axes)


def _npi_tensorinv(a, ind=2):
    return jnp.linalg.tensorinv(a, ind=ind)


def _npi_cond(x, p=None):
    return jnp.linalg.cond(x, p=p)


_reg("_npi_svd", _npi_svd, num_outputs=3)
_reg("_npi_qr", _npi_qr, num_outputs=2)
_reg("_npi_solve", _npi_solve)
_reg("_npi_lstsq", _npi_lstsq, num_outputs=4, differentiable=False)
_reg("_npi_pinv", _npi_pinv)
_reg("_npi_cholesky", _npi_cholesky)
_reg("_npi_eigvalsh", _npi_eigvalsh)
_reg("_npi_eigh", _npi_eigh, num_outputs=2)
_reg("_npi_matrix_rank", _npi_matrix_rank, differentiable=False)
_reg("_npi_matrix_power", _npi_matrix_power)
_reg("_npi_multi_dot", _npi_multi_dot)
_reg("_npi_tensorsolve", _npi_tensorsolve)
_reg("_npi_tensorinv", _npi_tensorinv)
_reg("_npi_cond", _npi_cond, differentiable=False)


# ---------------------------------------------------------------------------
# 2.x symbol.json name parity: graphs serialized by the numpy-era reference
# carry _npi_* node op names for ops whose semantics our existing kernels
# already implement — pure ALIASES (no new impls), so loaded symbols
# resolve (symbol.py looks nodes up by registry name).
# ---------------------------------------------------------------------------


for _existing, _npi_names in [
        ("diag", ["_npi_diag"]),
        ("tril", ["_npi_tril"]),
        ("triu", ["_npi_triu"]),
        ("_eye", ["_npi_eye"]),
        ("_arange", ["_npi_arange"]),
        ("_zeros", ["_npi_zeros"]),
        ("_ones", ["_npi_ones"]),
        ("_full", ["_npi_full"]),
        ("_linspace", ["_npi_linspace"]),
        ("zeros_like_op", ["_npi_zeros_like"]),
        ("ones_like_op", ["_npi_ones_like"]),
        ("kron", ["_npi_kron"]),
        ("cross", ["_npi_cross"]),
        ("diagonal", ["_npi_diagonal"]),
        ("one_hot", ["_npi_one_hot"]),
        ("boolean_mask", ["_npi_boolean_mask"]),
        ("atleast_1d", ["_npi_atleast_1d"]),
        ("atleast_2d", ["_npi_atleast_2d"]),
        ("atleast_3d", ["_npi_atleast_3d"]),
        ("logsumexp", ["_npi_logsumexp"]),
        ("histogram", ["_npx_histogram"]),
        ("topk", ["_npx_topk"]),
        ("pick", ["_npx_pick"]),
        ("gather_nd", ["_npi_gather_nd", "_npx_gather_nd"]),
        ("scatter_nd", ["_npi_scatter_nd"]),
        ("sequence_mask", ["_npx_sequence_mask"]),
        ("shape_array", ["_npx_shape_array"]),
        ("Activation", ["_npx_activation"]),
        ("BatchNorm", ["_npx_batch_norm"]),
        ("Convolution", ["_npx_convolution"]),
        ("Deconvolution", ["_npx_deconvolution"]),
        ("Pooling", ["_npx_pooling"]),
        ("FullyConnected", ["_npx_fully_connected"]),
        ("Embedding", ["_npx_embedding"]),
        ("Dropout", ["_npx_dropout"]),
        ("LayerNorm", ["_npx_layer_norm"]),
        ("GroupNorm", ["_npx_group_norm"]),
        ("softmax", ["_npx_softmax"]),
        ("log_softmax", ["_npx_log_softmax"]),
        ("masked_softmax", ["_npx_masked_softmax"]),
        ("relu", ["_npx_relu"]),
        ("sigmoid", ["_npx_sigmoid"]),
        ("RNN", ["_npx_rnn"]),
        ("reshape", ["_npx_reshape"]),
        ("arange_like", ["_npi_arange_like"]),
        ("broadcast_like", ["_npi_broadcast_like"])]:
    try:
        alias(_existing, *_npi_names)
    except KeyError:
        pass   # alias table is best-effort across op-set evolution

# remaining 2.x internal spellings (early `_np_*` era + `_npx_*`
# extended names) onto the existing kernels — graph-loading parity only.
# Same best-effort guard as the table above (one mechanism, one place to
# extend).  NOT aliased: _npx_cond is the control-flow cond
# (control_flow.cc), unrelated to _npi_cond (linalg condition number) —
# better an unregistered-op error than a silently wrong dispatch.
for _existing, _names in [
        ("_npi_sort", ["_npx_sort"]),
        ("_npi_argsort", ["_npx_argsort"]),
        ("_npi_one_hot", ["_npx_one_hot"]),
        ("_npi_full_like", ["_np_full_like"]),
        ("_npi_zeros_like", ["_np_zeros_like"]),
        ("_npi_ones_like", ["_np_ones_like"]),
        ("_npi_transpose", ["_np_transpose"]),
        ("_npi_dot", ["_np_dot"]),
        ("_npi_sum", ["_np_sum"]),
        ("_npi_prod", ["_np_prod"]),
        ("_npi_reshape", ["_np_reshape"])]:
    try:
        alias(_existing, *_names)
    except KeyError:
        pass   # best-effort across op-set evolution


def _npx_nonzero(a):
    # 2.x npx.nonzero convention: ONE (N, ndim) int64 index tensor
    # (contrast _npi_nonzero, which returns ndim separate (N,) arrays).
    # np.argwhere IS this layout — call the argwhere kernel directly;
    # 0-d inputs keep one index column (the reference treats a scalar as
    # shape-(1,)); int64 unless x64 is off (jax truncates otherwise).
    _i64 = jnp.int64 if jax.config.read("jax_enable_x64") else jnp.int32
    if a.ndim == 0:
        a = a.reshape(1)
    return _npi_argwhere(a).astype(_i64)


_reg("_npx_nonzero", _npx_nonzero, no_jit=True, differentiable=False)


# ---------------------------------------------------------------------------
# numpy fft (reference: the mx.np surface tracks NumPy's np.fft module;
# on TPU these lower to XLA's FFT HLO, which runs on-device)
# ---------------------------------------------------------------------------

def _fftify(jfn, name, differentiable=True):
    def fn(a, n=None, axis=-1, norm=None):
        return jfn(a, n=n, axis=axis, norm=norm)
    fn.__name__ = name
    _reg(name, fn, differentiable=differentiable)


def _fftify_nd(jfn, name):
    def fn(a, s=None, axes=None, norm=None):
        if jfn in (jnp.fft.fft2, jnp.fft.ifft2, jnp.fft.rfft2,
                   jnp.fft.irfft2):
            return jfn(a, s=s, axes=axes if axes is not None else (-2, -1),
                       norm=norm)
        return jfn(a, s=s, axes=axes, norm=norm)
    fn.__name__ = name
    _reg(name, fn)


_fftify(jnp.fft.fft, "_npi_fft")
_fftify(jnp.fft.ifft, "_npi_ifft")
_fftify(jnp.fft.rfft, "_npi_rfft")
_fftify(jnp.fft.irfft, "_npi_irfft")
_fftify(jnp.fft.hfft, "_npi_hfft")
_fftify(jnp.fft.ihfft, "_npi_ihfft")
_fftify_nd(jnp.fft.fft2, "_npi_fft2")
_fftify_nd(jnp.fft.ifft2, "_npi_ifft2")
_fftify_nd(jnp.fft.rfft2, "_npi_rfft2")
_fftify_nd(jnp.fft.irfft2, "_npi_irfft2")
_fftify_nd(jnp.fft.fftn, "_npi_fftn")
_fftify_nd(jnp.fft.ifftn, "_npi_ifftn")
_fftify_nd(jnp.fft.rfftn, "_npi_rfftn")
_fftify_nd(jnp.fft.irfftn, "_npi_irfftn")


def _npi_fftfreq(n, d=1.0):
    return jnp.fft.fftfreq(int(n), d=d)


def _npi_rfftfreq(n, d=1.0):
    return jnp.fft.rfftfreq(int(n), d=d)


def _npi_fftshift(a, axes=None):
    return jnp.fft.fftshift(a, axes=axes)


def _npi_ifftshift(a, axes=None):
    return jnp.fft.ifftshift(a, axes=axes)


_reg("_npi_fftfreq", _npi_fftfreq, differentiable=False)
_reg("_npi_rfftfreq", _npi_rfftfreq, differentiable=False)
_reg("_npi_fftshift", _npi_fftshift)
_reg("_npi_ifftshift", _npi_ifftshift)


# ---------------------------------------------------------------------------
# numpy polynomial family (np.polyadd/... surface; polyval/vander above)
# ---------------------------------------------------------------------------

def _npi_polyadd(a1, a2):
    return jnp.polyadd(a1, a2)


def _npi_polysub(a1, a2):
    return jnp.polysub(a1, a2)


def _npi_polymul(a1, a2):
    return jnp.polymul(a1, a2)


def _npi_polydiv(u, v):
    q, r = jnp.polydiv(u, v)
    return q, r


def _npi_polyder(p, m=1):
    for _ in range(int(m)):
        p = jnp.polyder(p)
    return p


def _npi_polyint(p, m=1):
    for _ in range(int(m)):
        p = jnp.polyint(p)
    return p


def _npi_polyfit(x, y, deg):
    return jnp.polyfit(x, y, int(deg))


def _npi_roots(p):
    # strip_zeros=False keeps the output shape static (len(p)-1) so the
    # kernel stays jittable; numpy strips leading zeros instead
    return jnp.roots(p, strip_zeros=False)


def _npi_poly(seq):
    return jnp.poly(seq)


_reg("_npi_polyadd", _npi_polyadd)
_reg("_npi_polysub", _npi_polysub)
_reg("_npi_polymul", _npi_polymul)
_reg("_npi_polydiv", _npi_polydiv, num_outputs=2)
_reg("_npi_polyder", _npi_polyder)
_reg("_npi_polyint", _npi_polyint)
_reg("_npi_polyfit", _npi_polyfit)
_reg("_npi_roots", _npi_roots, differentiable=False)
_reg("_npi_poly", _npi_poly, differentiable=False)


# ---------------------------------------------------------------------------
# remaining numpy surface: unwrap (kaiser/spacing kernels already exist
# above — only their np-level bindings were missing)
# ---------------------------------------------------------------------------

def _npi_unwrap(p, discont=None, axis=-1, period=6.283185307179586):
    return jnp.unwrap(p, discont=discont, axis=axis, period=period)


_reg("_npi_unwrap", _npi_unwrap)


# ---------------------------------------------------------------------------
# special functions (beyond the reference: jax.scipy.special lowered to
# XLA — useful loss/statistics primitives with exact gradients on TPU)
# ---------------------------------------------------------------------------

def _specials():
    from jax.scipy import special as jsp
    table = {
        "_npx_betainc": (jsp.betainc, True),
        "_npx_zeta": (jsp.zeta, True),
        "_npx_ndtr": (jsp.ndtr, True),
        "_npx_ndtri": (jsp.ndtri, True),
        "_npx_log_ndtr": (jsp.log_ndtr, True),
        "_npx_logit": (jsp.logit, True),
        "_npx_expit": (jsp.expit, True),
        "_npx_xlogy": (jsp.xlogy, True),
        "_npx_xlog1py": (jsp.xlog1py, True),
        "_npx_entr": (jsp.entr, True),
        "_npx_rel_entr": (jsp.rel_entr, True),
        "_npx_kl_div": (jsp.kl_div, True),
        "_npx_i0e": (jsp.i0e, True),
        "_npx_i1": (jsp.i1, True),
        "_npx_i1e": (jsp.i1e, True),
    }
    for name, (jfn, diff) in table.items():
        def make(jfn=jfn):
            def fn(*args):
                return jfn(*args)
            return fn
        f = make()
        f.__doc__ = ("jax.scipy.special.%s lowered to XLA (beyond-"
                     "reference TPU primitive)" % jfn.__name__)
        _reg(name, f, differentiable=diff)


_specials()


def _more_specials():
    """Second special-function batch: registered defensively (only what
    this jax build provides) so the surface tracks jax.scipy.special."""
    from jax.scipy import special as jsp
    def _multigammaln(a, d=1):
        # d is the integration-space dimension: a static attr, not an
        # operand (jax requires it concrete)
        return jsp.multigammaln(a, int(d))
    if hasattr(jsp, "multigammaln"):
        _reg("_npx_multigammaln", _multigammaln)

    def _bernoulli(n=1):
        # jsp.bernoulli builds the first n+1 Bernoulli numbers with a
        # concrete-n Python loop: n is a static attr, not an operand
        return jsp.bernoulli(int(n))
    if hasattr(jsp, "bernoulli"):
        _reg("_npx_bernoulli", _bernoulli, differentiable=False)
    for name in ("betaln", "expi", "expn", "exp1",
                 "factorial", "gammasgn", "hyp1f1",
                 "poch", "spence"):
        jfn = getattr(jsp, name, None)
        if jfn is None:
            continue
        def make(jfn=jfn):
            def fn(*args):
                return jfn(*args)
            return fn
        f = make()
        f.__doc__ = ("jax.scipy.special.%s lowered to XLA (beyond-"
                     "reference TPU primitive)" % name)
        _reg("_npx_" + name, f)


_more_specials()


def _npi_histogram_bin_edges(a, bins=10, range=None):
    return jnp.histogram_bin_edges(a, bins=bins, range=range)


def _npi_real_if_close(a, tol=100.0):
    # numpy semantics: drop an imaginary part that is numerically zero.
    # The complex->real decision is value-dependent -> eager (no_jit).
    a = jnp.asarray(a)
    if not jnp.issubdtype(a.dtype, jnp.complexfloating):
        return a
    import numpy as _np2
    eps = _np2.finfo(a.dtype).eps
    if bool(jnp.all(jnp.abs(a.imag) < tol * eps)):
        return a.real
    return a


def _npi_matrix_transpose(a):
    return jnp.swapaxes(a, -2, -1)


def _npi_place_impl(a, mask, vals):
    # numpy.place: first N True positions take vals cyclically.  The
    # cyclic index depends on the mask's running count — computable with
    # static shapes via cumsum, so it stays jittable.
    vals = jnp.atleast_1d(vals).ravel()   # scalars/multi-d per numpy
    idx = (jnp.cumsum(mask.ravel().astype(jnp.int32)) - 1) % vals.size
    flat = jnp.where(mask.ravel(), vals[idx], a.ravel())
    return flat.reshape(a.shape)


def _npi_putmask_impl(a, mask, vals):
    # numpy.putmask: vals broadcast cyclically by POSITION (not by the
    # running mask count, unlike place)
    vals = jnp.atleast_1d(vals).ravel()
    idx = jnp.arange(a.size) % vals.size
    flat = jnp.where(mask.ravel(), vals[idx], a.ravel())
    return flat.reshape(a.shape)


_reg("_npi_histogram_bin_edges", _npi_histogram_bin_edges,
     differentiable=False)
_reg("_npi_real_if_close", _npi_real_if_close, no_jit=True,
     differentiable=False)
_reg("_npi_matrix_transpose", _npi_matrix_transpose)
_reg("_npi_place_impl", _npi_place_impl)
_reg("_npi_putmask_impl", _npi_putmask_impl)


def _stats():
    """jax.scipy.stats log-densities as registry kernels (npx.stats.*):
    exact-gradient loss/likelihood primitives lowered to XLA."""
    from jax.scipy import stats as jst
    table = [
        ("norm_pdf", jst.norm.pdf), ("norm_logpdf", jst.norm.logpdf),
        ("norm_cdf", jst.norm.cdf), ("norm_logcdf", jst.norm.logcdf),
        ("expon_logpdf", jst.expon.logpdf),
        ("gamma_logpdf", jst.gamma.logpdf),
        ("beta_logpdf", jst.beta.logpdf),
        ("t_logpdf", jst.t.logpdf),
        ("cauchy_logpdf", jst.cauchy.logpdf),
        ("laplace_logpdf", jst.laplace.logpdf),
        ("uniform_logpdf", jst.uniform.logpdf),
        ("poisson_pmf", jst.poisson.pmf),
        ("poisson_logpmf", jst.poisson.logpmf),
        ("bernoulli_logpmf", jst.bernoulli.logpmf),
    ]
    for name, jfn in table:
        def make(jfn=jfn):
            def fn(*args):
                return jfn(*args)
            return fn
        f = make()
        f.__doc__ = ("jax.scipy.stats %s lowered to XLA (beyond-reference "
                     "TPU primitive)" % name)
        _reg("_npx_stats_" + name, f)


_stats()
