"""Shape manipulation, linear algebra, indexing ops.

Reference: src/operator/tensor/matrix_op.cc (transpose/reshape/slice/concat/
stack/tile/repeat/clip/dot/batch_dot), indexing_op.cc (take/gather_nd/
scatter_nd/one_hot/Embedding), diag_op.cc, la_op.cc (linalg_*).

dot/batch_dot lower to `lax.dot_general` — the MXU path.  All shape ops are
free at XLA level (layout changes fused away).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

# ---------------------------------------------------------------------------
# matmul family (MXU)
# ---------------------------------------------------------------------------


@register("dot")
def _dot(a, b, transpose_a=False, transpose_b=False):
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
    if a.ndim == 1 and b.ndim == 1:
        return jnp.dot(a, b)
    # MXNet dot: contract last axis of a with first axis of b
    return jnp.tensordot(a, b, axes=([a.ndim - 1], [0]))


@register("batch_dot", aliases=["_npx_batch_dot"])
def _batch_dot(a, b, transpose_a=False, transpose_b=False):
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    return jnp.matmul(a, b)


@register("linalg_gemm2")
def _linalg_gemm2(a, b, transpose_a=False, transpose_b=False, alpha=1.0):
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    return alpha * jnp.matmul(a, b)


@register("linalg_syrk")
def _linalg_syrk(a, transpose=False, alpha=1.0):
    at = jnp.swapaxes(a, -1, -2)
    return alpha * (jnp.matmul(at, a) if transpose else jnp.matmul(a, at))


@register("linalg_potrf")
def _linalg_potrf(a):
    return jnp.linalg.cholesky(a)


@register("linalg_trsm")
def _linalg_trsm(a, b, transpose=False, rightside=False, lower=True, alpha=1.0):
    if transpose:
        a = jnp.swapaxes(a, -1, -2)
        lower = not lower
    sol = jax.scipy.linalg.solve_triangular(
        a, alpha * b if not rightside else jnp.swapaxes(alpha * b, -1, -2),
        lower=lower)
    return sol if not rightside else jnp.swapaxes(sol, -1, -2)


# ---------------------------------------------------------------------------
# shape ops
# ---------------------------------------------------------------------------


@register("transpose")
def _transpose(x, axes=None):
    return jnp.transpose(x, axes=axes)


@register("swapaxes", aliases=["SwapAxis"])
def _swapaxes(x, dim1=0, dim2=0):
    return jnp.swapaxes(x, dim1, dim2)


@register("reshape", aliases=["Reshape"])
def _reshape(x, shape=None, reverse=False):
    # op-form reshape (copy semantics under trace); view reshape is the
    # NDArray method.  Supports MXNet's 0 (=keep) / -1 (=infer) codes.
    out = []
    for i, d in enumerate(shape):
        out.append(x.shape[i] if d == 0 else int(d))
    return jnp.reshape(x, tuple(out))


@register("flatten", aliases=["Flatten"])
def _flatten(x):
    return jnp.reshape(x, (x.shape[0], -1)) if x.ndim > 1 else x


@register("expand_dims")
def _expand_dims(x, axis=0):
    return jnp.expand_dims(x, axis)


@register("squeeze")
def _squeeze(x, axis=None):
    return jnp.squeeze(x, axis=axis)


@register("broadcast_to")
def _broadcast_to(x, shape=None):
    tgt = tuple(x.shape[i] if s == 0 else s for i, s in enumerate(shape))
    return jnp.broadcast_to(x, tgt)


@register("broadcast_axis", aliases=["broadcast_axes"])
def _broadcast_axis(x, axis=None, size=None):
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    sizes = (size,) if isinstance(size, int) else tuple(size)
    tgt = list(x.shape)
    for a, s in zip(axes, sizes):
        tgt[a] = s
    return jnp.broadcast_to(x, tuple(tgt))


@register("concat", aliases=["Concat"])
def _concat(*xs, dim=1, num_args=None):
    return jnp.concatenate(xs, axis=dim)


@register("stack")
def _stack(*xs, axis=0, num_args=None):
    return jnp.stack(xs, axis=axis)


@register("split", aliases=["SliceChannel"], num_outputs=0)
def _split(x, num_outputs=2, axis=1, squeeze_axis=False):
    parts = jnp.split(x, num_outputs, axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts)


@register("split_v2", aliases=["_split_v2"], num_outputs=0)
def _split_v2(x, indices=(), axis=0, squeeze_axis=False, sections=0):
    if sections:
        parts = jnp.split(x, sections, axis=axis)
    else:
        parts = jnp.split(x, list(indices), axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts)


def _idx_slices(begin, end, step):
    step = step or (None,) * len(begin)
    return [slice(b, e, s) for b, e, s in zip(begin, end, step)]


@register("slice", aliases=["crop"])
def _slice(x, begin=(), end=(), step=()):
    return x[tuple(_idx_slices(begin, end, step))]


@register("slice_axis")
def _slice_axis(x, axis=0, begin=0, end=None):
    idx = [slice(None)] * x.ndim
    idx[axis] = slice(begin, end)
    return x[tuple(idx)]


@register("slice_like")
def _slice_like(x, like, axes=()):
    axes = axes or tuple(range(min(x.ndim, like.ndim)))
    idx = [slice(None)] * x.ndim
    for a in axes:
        idx[a] = slice(0, like.shape[a])
    return x[tuple(idx)]


@register("tile")
def _tile(x, reps=()):
    return jnp.tile(x, reps)


@register("repeat")
def _repeat(x, repeats=1, axis=None):
    return jnp.repeat(x, repeats, axis=axis)


@register("pad", aliases=["Pad"])
def _pad(x, mode="constant", pad_width=(), constant_value=0.0):
    pw = [(pad_width[2 * i], pad_width[2 * i + 1]) for i in range(len(pad_width) // 2)]
    if mode == "constant":
        return jnp.pad(x, pw, mode="constant", constant_values=constant_value)
    if mode == "edge":
        return jnp.pad(x, pw, mode="edge")
    if mode == "reflect":
        return jnp.pad(x, pw, mode="reflect")
    raise ValueError("bad pad mode %r" % mode)


@register("flip")
def _flip(x, axis=0):
    # "reverse" (multi-axis v1.x semantics) is owned by ops/misc.py
    return jnp.flip(x, axis=axis)


@register("diag")
def _diag(x, k=0):
    if x.ndim == 1:
        return jnp.diag(x, k=k)
    return jnp.diagonal(x, offset=k, axis1=-2, axis2=-1)


@register("zeros_like_op", aliases=["zeros_like"])
def _zeros_like(x):
    return jnp.zeros_like(x)


@register("ones_like_op", aliases=["ones_like"])
def _ones_like(x):
    return jnp.ones_like(x)


@register("space_to_depth")
def _space_to_depth(x, block_size=2):
    n, c, h, w = x.shape
    bs = block_size
    y = x.reshape(n, c, h // bs, bs, w // bs, bs)
    y = y.transpose(0, 3, 5, 1, 2, 4)
    return y.reshape(n, c * bs * bs, h // bs, w // bs)


@register("depth_to_space")
def _depth_to_space(x, block_size=2):
    n, c, h, w = x.shape
    bs = block_size
    y = x.reshape(n, bs, bs, c // (bs * bs), h, w)
    y = y.transpose(0, 3, 4, 1, 5, 2)
    return y.reshape(n, c // (bs * bs), h * bs, w * bs)


# ---------------------------------------------------------------------------
# indexing / gather / scatter
# ---------------------------------------------------------------------------


@register("take")
def _take(x, indices, axis=0, mode="clip"):
    idx = indices.astype(jnp.int32)
    if mode == "wrap":
        idx = jnp.mod(idx, x.shape[axis])
    else:
        idx = jnp.clip(idx, 0, x.shape[axis] - 1)
    return jnp.take(x, idx, axis=axis)


@register("pick")
def _pick(x, index, axis=-1, keepdims=False, mode="clip"):
    idx = jnp.clip(index.astype(jnp.int32), 0, x.shape[axis] - 1)
    out = jnp.take_along_axis(x, jnp.expand_dims(idx, axis % x.ndim), axis=axis)
    if not keepdims:
        out = jnp.squeeze(out, axis=axis)
    return out


@register("gather_nd")
def _gather_nd(x, indices):
    idx = indices.astype(jnp.int32)
    m = idx.shape[0]
    return x[tuple(idx[i] for i in range(m))]


@register("scatter_nd")
def _scatter_nd(data, indices, shape=None):
    idx = indices.astype(jnp.int32)
    m = idx.shape[0]
    out = jnp.zeros(shape, data.dtype)
    return out.at[tuple(idx[i] for i in range(m))].set(data)


@register("Embedding", aliases=["embedding"])
def _embedding(data, weight, input_dim=None, output_dim=None, dtype="float32",
               sparse_grad=False):
    # grads flow to `weight` as scatter-add via the gather VJP — the TPU
    # realization of the rowsparse-gradient path (SURVEY.md "Sparse kernels")
    idx = data.astype(jnp.int32)
    return jnp.take(weight, idx, axis=0)


@register("one_hot", differentiable=False)
def _one_hot(indices, depth=None, on_value=1.0, off_value=0.0, dtype="float32"):
    d = jnp.bfloat16 if dtype == "bfloat16" else dtype
    hot = jax.nn.one_hot(indices.astype(jnp.int32), depth)
    return (hot * (on_value - off_value) + off_value).astype(d)


@register("where_op")
def _where_op(cond, a, b):
    return jnp.where(cond.astype(bool), a, b)


@register("boolean_mask", aliases=["_contrib_boolean_mask"],
          differentiable=False, no_jit=True)
def _boolean_mask(data, index, axis=0):
    """Keep slices along `axis` whose index entry is non-zero (reference:
    src/operator/contrib/boolean_mask.cc).  Dynamic output shape, so
    no_jit and eager-only; the reference's backward is a sanctioned cut
    (use `take` with precomputed indices to train through a mask)."""
    return jnp.compress(index.reshape(-1).astype(bool), data,
                        axis=int(axis))


@register("sequence_mask", aliases=["SequenceMask"])
def _sequence_mask(data, sequence_length=None, use_sequence_length=False,
                   value=0.0, axis=0):
    if not use_sequence_length or sequence_length is None:
        return data
    seq_axis = axis
    steps = jnp.arange(data.shape[seq_axis])
    bshape = [1] * data.ndim
    bshape[seq_axis] = data.shape[seq_axis]
    steps = steps.reshape(bshape)
    batch_axis = 1 - seq_axis if data.ndim > 1 else 0
    lshape = [1] * data.ndim
    lshape[batch_axis] = data.shape[batch_axis]
    lens = sequence_length.reshape(lshape)
    return jnp.where(steps < lens, data, jnp.asarray(value, data.dtype))


@register("SequenceLast")
def _sequence_last(data, sequence_length=None, use_sequence_length=False, axis=0):
    if not use_sequence_length or sequence_length is None:
        idx = [slice(None)] * data.ndim
        idx[axis] = -1
        return data[tuple(idx)]
    last = (sequence_length.astype(jnp.int32) - 1)
    moved = jnp.moveaxis(data, axis, 0)
    return moved[last, jnp.arange(moved.shape[1])]


@register("SequenceReverse")
def _sequence_reverse(data, sequence_length=None, use_sequence_length=False, axis=0):
    if not use_sequence_length or sequence_length is None:
        return jnp.flip(data, axis=axis)
    moved = jnp.moveaxis(data, axis, 0)  # (T, B, ...)
    T = moved.shape[0]
    steps = jnp.arange(T)[:, None]
    lens = sequence_length.astype(jnp.int32)[None, :]
    src = jnp.where(steps < lens, lens - 1 - steps, steps)
    out = jnp.take_along_axis(
        moved, src.reshape(src.shape + (1,) * (moved.ndim - 2)).astype(jnp.int32),
        axis=0)
    return jnp.moveaxis(out, 0, axis)


@register("shape_array", differentiable=False)
def _shape_array(x):
    return jnp.asarray(x.shape, dtype=jnp.int64)


@register("size_array", differentiable=False)
def _size_array(x):
    return jnp.asarray([x.size], dtype=jnp.int64)


@register("_internal_getitem")
def _internal_getitem(x, key=None):
    """Basic-index read as a recorded op — used by NDArray.__getitem__ under
    autograd so the gradient chain survives (views carry no tape node)."""
    return x[key]

def _assign_slices(x, begin, end, step):
    idx = _idx_slices(begin, end, step)
    idx.extend([slice(None)] * (x.ndim - len(idx)))
    return tuple(idx)


@register("_slice_assign", aliases=["_crop_assign"])
def _slice_assign(lhs, rhs, begin=(), end=(), step=()):
    """lhs with lhs[begin:end:step] = rhs (reference:
    src/operator/tensor/matrix_op.cc _slice_assign — the recorded form of
    sliced writes).  begin/end/step are static attrs, so this stays
    jittable; differentiable in both operands (scatter vjp)."""
    idx = _assign_slices(lhs, begin, end, step)
    return lhs.at[idx].set(rhs.astype(lhs.dtype))


@register("_slice_assign_scalar", aliases=["_crop_assign_scalar"])
def _slice_assign_scalar(data, scalar=0.0, begin=(), end=(), step=()):
    idx = _assign_slices(data, begin, end, step)
    return data.at[idx].set(jnp.asarray(scalar, data.dtype))
