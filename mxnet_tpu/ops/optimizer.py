"""Fused optimizer-update ops.

Reference: src/operator/optimizer_op.cc (NNVM_REGISTER_OP(sgd_update),
sgd_mom_update, mp_sgd_update, adam_update, nag_mom_update, rmsprop_update,
rmspropalex_update, ftrl_update, signsgd_update, signum_update,
lamb_update_phase1/lamb_update_phase2) and src/operator/contrib/adamw.cc.

TPU-native: each update is one jitted XLA program that fuses the whole
elementwise chain (the reference needed hand-fused CUDA kernels for this;
XLA does it from the jnp composition).  In-place semantics use the registry's
mutates_input (weight) + aux_writeback (state buffers) so Python-level
NDArray handles update like the reference's mutable inputs.

All ops clip gradients first when clip_gradient > 0 and apply
rescale_grad — matching dmlc-param defaults.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register


def _prep(grad, rescale_grad, clip_gradient, wd=0.0, weight=None):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    if wd and weight is not None:
        g = g + wd * weight
    return g


@register("sgd_update", differentiable=False, mutates_input=0)
def _sgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
                clip_gradient=-1.0, lazy_update=True):
    g = _prep(grad, rescale_grad, clip_gradient, wd, weight)
    return weight - lr * g.astype(weight.dtype)


@register("sgd_mom_update", differentiable=False, num_outputs=2,
          mutates_input=0, aux_writeback={1: 2})
def _sgd_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                    rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True):
    g = _prep(grad, rescale_grad, clip_gradient, wd, weight)
    new_mom = momentum * mom - lr * g.astype(mom.dtype)
    return weight + new_mom.astype(weight.dtype), new_mom


@register("mp_sgd_update", differentiable=False, num_outputs=2,
          mutates_input=0, aux_writeback={1: 2})
def _mp_sgd_update(weight, grad, weight32, lr=0.01, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0, lazy_update=True):
    g = _prep(grad.astype(jnp.float32), rescale_grad, clip_gradient, wd,
              weight32)
    new_w32 = weight32 - lr * g
    return new_w32.astype(weight.dtype), new_w32


@register("mp_sgd_mom_update", differentiable=False, num_outputs=3,
          mutates_input=0, aux_writeback={1: 2, 2: 3})
def _mp_sgd_mom_update(weight, grad, mom, weight32, lr=0.01, momentum=0.0,
                       wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                       lazy_update=True):
    g = _prep(grad.astype(jnp.float32), rescale_grad, clip_gradient, wd,
              weight32)
    new_mom = momentum * mom - lr * g
    new_w32 = weight32 + new_mom
    return new_w32.astype(weight.dtype), new_mom, new_w32


@register("nag_mom_update", differentiable=False, num_outputs=2,
          mutates_input=0, aux_writeback={1: 2})
def _nag_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                    rescale_grad=1.0, clip_gradient=-1.0):
    g = _prep(grad, rescale_grad, clip_gradient, wd, weight)
    new_mom = momentum * mom + g.astype(mom.dtype)
    update = momentum * new_mom + g.astype(mom.dtype)
    return weight - lr * update.astype(weight.dtype), new_mom


@register("adam_update", differentiable=False, num_outputs=3,
          mutates_input=0, aux_writeback={1: 2, 2: 3})
def _adam_update(weight, grad, mean, var, lr=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                 lazy_update=True):
    g = _prep(grad, rescale_grad, clip_gradient, wd, weight).astype(mean.dtype)
    new_mean = beta1 * mean + (1.0 - beta1) * g
    new_var = beta2 * var + (1.0 - beta2) * g * g
    update = lr * new_mean / (jnp.sqrt(new_var) + epsilon)
    return weight - update.astype(weight.dtype), new_mean, new_var


@register("adamw_update", aliases=["_adamw_update", "_contrib_adamw_update"],
          differentiable=False, num_outputs=3, mutates_input=0,
          aux_writeback={1: 2, 2: 3})
def _adamw_update(weight, grad, mean, var, lr=0.001, beta1=0.9, beta2=0.999,
                  epsilon=1e-8, wd=0.0, eta=1.0, rescale_grad=1.0,
                  clip_gradient=-1.0):
    """Decoupled weight decay (reference: src/operator/contrib/adamw.cc)."""
    g = _prep(grad, rescale_grad, clip_gradient).astype(mean.dtype)
    new_mean = beta1 * mean + (1.0 - beta1) * g
    new_var = beta2 * var + (1.0 - beta2) * g * g
    update = eta * (lr * new_mean / (jnp.sqrt(new_var) + epsilon) +
                    wd * weight.astype(mean.dtype))
    return weight - update.astype(weight.dtype), new_mean, new_var


@register("rmsprop_update", differentiable=False, num_outputs=2,
          mutates_input=0, aux_writeback={1: 2})
def _rmsprop_update(weight, grad, n, lr=0.001, gamma1=0.9, epsilon=1e-8,
                    wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                    clip_weights=-1.0):
    g = _prep(grad, rescale_grad, clip_gradient, wd, weight).astype(n.dtype)
    new_n = (1.0 - gamma1) * g * g + gamma1 * n
    new_w = weight - (lr * g / jnp.sqrt(new_n + epsilon)).astype(weight.dtype)
    if clip_weights is not None and clip_weights > 0:
        new_w = jnp.clip(new_w, -clip_weights, clip_weights)
    return new_w, new_n


@register("rmspropalex_update", differentiable=False, num_outputs=4,
          mutates_input=0, aux_writeback={1: 2, 2: 3, 3: 4})
def _rmspropalex_update(weight, grad, n, g_buf, delta, lr=0.001, gamma1=0.95,
                        gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                        clip_gradient=-1.0, clip_weights=-1.0):
    """Centered RMSProp with momentum (Graves 2013; reference:
    rmspropalex_update)."""
    g = _prep(grad, rescale_grad, clip_gradient, wd, weight).astype(n.dtype)
    new_n = (1.0 - gamma1) * g * g + gamma1 * n
    new_g = (1.0 - gamma1) * g + gamma1 * g_buf
    new_delta = gamma2 * delta - lr * g / jnp.sqrt(new_n - new_g * new_g +
                                                   epsilon)
    new_w = weight + new_delta.astype(weight.dtype)
    if clip_weights is not None and clip_weights > 0:
        new_w = jnp.clip(new_w, -clip_weights, clip_weights)
    return new_w, new_n, new_g, new_delta


@register("ftrl_update", differentiable=False, num_outputs=3,
          mutates_input=0, aux_writeback={1: 2, 2: 3})
def _ftrl_update(weight, grad, z, n, lr=0.1, lamda1=0.01, beta=1.0, wd=0.0,
                 rescale_grad=1.0, clip_gradient=-1.0):
    g = _prep(grad, rescale_grad, clip_gradient).astype(z.dtype)
    new_n = n + g * g
    sigma = (jnp.sqrt(new_n) - jnp.sqrt(n)) / lr
    new_z = z + g - sigma * weight.astype(z.dtype)
    new_w = jnp.where(
        jnp.abs(new_z) <= lamda1, jnp.zeros_like(new_z),
        (jnp.sign(new_z) * lamda1 - new_z) /
        ((beta + jnp.sqrt(new_n)) / lr + wd))
    return new_w.astype(weight.dtype), new_z, new_n


@register("signsgd_update", differentiable=False, mutates_input=0)
def _signsgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
                    clip_gradient=-1.0):
    g = _prep(grad, rescale_grad, clip_gradient)
    return weight - lr * (jnp.sign(g) + wd * weight).astype(weight.dtype)


@register("signum_update", differentiable=False, num_outputs=2,
          mutates_input=0, aux_writeback={1: 2})
def _signum_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0):
    g = _prep(grad, rescale_grad, clip_gradient, wd, weight)
    new_mom = momentum * mom - (1.0 - momentum) * g.astype(mom.dtype)
    new_w = (1.0 - lr * wd_lh) * weight + lr * jnp.sign(new_mom).astype(weight.dtype)
    return new_w, new_mom


@register("lamb_update_phase1", differentiable=False, num_outputs=3,
          mutates_input=None, aux_writeback={1: 2, 2: 3})
def _lamb_phase1(grad, weight, mean, var, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, t=1, bias_correction=True, wd=0.0,
                 rescale_grad=1.0, clip_gradient=-1.0):
    """Phase 1 emits the raw update direction; phase 2 applies the trust
    ratio (reference: src/operator/optimizer_op.cc lamb_update_phase1)."""
    g = _prep(grad, rescale_grad, clip_gradient).astype(mean.dtype)
    new_mean = beta1 * mean + (1.0 - beta1) * g
    new_var = beta2 * var + (1.0 - beta2) * g * g
    if bias_correction:
        mean_hat = new_mean / (1.0 - beta1 ** t)
        var_hat = new_var / (1.0 - beta2 ** t)
    else:
        mean_hat, var_hat = new_mean, new_var
    update = mean_hat / (jnp.sqrt(var_hat) + epsilon) + \
        wd * weight.astype(mean.dtype)
    return update, new_mean, new_var


@register("lamb_update_phase2", differentiable=False, mutates_input=0)
def _lamb_phase2(weight, g_update, r1=None, r2=None, lr=0.01,
                 lower_bound=-1.0, upper_bound=-1.0):
    if r1 is None:
        r1 = jnp.sqrt(jnp.sum(jnp.square(weight.astype(jnp.float32))))
    if r2 is None:
        r2 = jnp.sqrt(jnp.sum(jnp.square(g_update.astype(jnp.float32))))
    if lower_bound is not None and lower_bound > 0:
        r1 = jnp.maximum(r1, lower_bound)
    if upper_bound is not None and upper_bound > 0:
        r1 = jnp.minimum(r1, upper_bound)
    ratio = jnp.where((r1 > 0) & (r2 > 0), r1 / r2, 1.0)
    return weight - (lr * ratio * g_update).astype(weight.dtype)


# -- rowsparse lazy updates ---------------------------------------------------
# Reference: src/operator/optimizer_op.cc (SGDUpdateRspImpl, SGDMomUpdateRspImpl,
# AdamUpdateRspImpl — "lazy update": only rows present in the gradient touch
# weight/state; absent rows skip wd decay and momentum/moment decay too).
# TPU-native: one jitted gather → elementwise chain → scatter; XLA fuses it.

@register("_sparse_sgd_update", differentiable=False, mutates_input=0)
def _sparse_sgd_update(weight, grad_data, grad_idx, lr=0.01, wd=0.0,
                       rescale_grad=1.0, clip_gradient=-1.0):
    rows = weight[grad_idx]
    g = _prep(grad_data.astype(rows.dtype), rescale_grad, clip_gradient, wd,
              rows)
    return weight.at[grad_idx].set(rows - lr * g)


@register("_sparse_sgd_mom_update", differentiable=False, num_outputs=2,
          mutates_input=0, aux_writeback={1: 3})
def _sparse_sgd_mom_update(weight, grad_data, grad_idx, mom, lr=0.01,
                           momentum=0.0, wd=0.0, rescale_grad=1.0,
                           clip_gradient=-1.0):
    rows = weight[grad_idx]
    mrows = mom[grad_idx]
    g = _prep(grad_data.astype(mrows.dtype), rescale_grad, clip_gradient, wd,
              rows)
    new_m = momentum * mrows - lr * g
    return (weight.at[grad_idx].set(rows + new_m.astype(weight.dtype)),
            mom.at[grad_idx].set(new_m))


@register("_sparse_adam_update", differentiable=False, num_outputs=3,
          mutates_input=0, aux_writeback={1: 3, 2: 4})
def _sparse_adam_update(weight, grad_data, grad_idx, mean, var, lr=0.001,
                        beta1=0.9, beta2=0.999, epsilon=1e-8, wd=0.0,
                        rescale_grad=1.0, clip_gradient=-1.0):
    rows = weight[grad_idx]
    m = mean[grad_idx]
    v = var[grad_idx]
    g = _prep(grad_data.astype(rows.dtype), rescale_grad, clip_gradient, wd,
              rows)
    new_m = beta1 * m + (1.0 - beta1) * g
    new_v = beta2 * v + (1.0 - beta2) * g * g
    new_w = rows - lr * new_m / (jnp.sqrt(new_v) + epsilon)
    return (weight.at[grad_idx].set(new_w),
            mean.at[grad_idx].set(new_m),
            var.at[grad_idx].set(new_v))


# ---------------------------------------------------------------------------
# optimizer tail (reference: src/operator/optimizer_op.cc ftml/mp_* rows,
# src/operator/contrib/optimizer_op.cc group_adagrad,
# src/operator/contrib/multi_*.cc and preloaded_multi_*.cc fused fleets)
# ---------------------------------------------------------------------------


@register("ftml_update", differentiable=False, num_outputs=4,
          mutates_input=0, aux_writeback={1: 2, 2: 3, 3: 4})
def _ftml_update(weight, grad, d, v, z, lr=0.0025, beta1=0.6, beta2=0.999,
                 epsilon=1e-8, t=1, wd=0.0, rescale_grad=1.0,
                 clip_grad=-1.0):
    g = _prep(grad, rescale_grad, clip_grad, wd, weight)
    v_new = beta2 * v + (1.0 - beta2) * g * g
    bias2 = 1.0 - beta2 ** t
    d_new = (1.0 - beta1 ** t) / lr * \
        (jnp.sqrt(v_new / bias2) + epsilon)
    sigma = d_new - beta1 * d
    z_new = beta1 * z + (1.0 - beta1) * g - sigma * weight
    w_new = -z_new / d_new
    return w_new.astype(weight.dtype), d_new, v_new, z_new


@register("mp_nag_mom_update", differentiable=False, num_outputs=3,
          mutates_input=0, aux_writeback={1: 2, 2: 3})
def _mp_nag_mom_update(weight, grad, mom, weight32, lr=0.01, momentum=0.0,
                       wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    g = _prep(grad.astype(jnp.float32), rescale_grad, clip_gradient, wd,
              weight32)
    new_mom = momentum * mom + g
    new_w32 = weight32 - lr * (g + momentum * new_mom)
    return new_w32.astype(weight.dtype), new_mom, new_w32


@register("mp_lamb_update_phase1", differentiable=False, num_outputs=3,
          aux_writeback={1: 2, 2: 3})
def _mp_lamb_phase1(grad, weight32, mean, var, beta1=0.9, beta2=0.999,
                    epsilon=1e-6, t=1, bias_correction=True, wd=0.0,
                    rescale_grad=1.0, clip_gradient=-1.0):
    g = _prep(grad.astype(jnp.float32), rescale_grad, clip_gradient)
    new_mean = beta1 * mean + (1.0 - beta1) * g
    new_var = beta2 * var + (1.0 - beta2) * g * g
    m, v = new_mean, new_var
    if bias_correction:
        m = m / (1.0 - beta1 ** t)
        v = v / (1.0 - beta2 ** t)
    g_update = m / (jnp.sqrt(v) + epsilon) + wd * weight32
    return g_update, new_mean, new_var


@register("mp_lamb_update_phase2", differentiable=False, num_outputs=2,
          mutates_input=0, aux_writeback={1: 4})
def _mp_lamb_phase2(weight, g_update, r1, r2, weight32, lr=0.01,
                    lower_bound=-1.0, upper_bound=-1.0):
    r1 = jnp.where(lower_bound >= 0, jnp.maximum(r1, lower_bound), r1)
    r1 = jnp.where(upper_bound >= 0, jnp.minimum(r1, upper_bound), r1)
    ratio = jnp.where(jnp.logical_and(r1 > 0, r2 > 0), r1 / r2, 1.0)
    new_w32 = weight32 - lr * ratio * g_update
    return new_w32.astype(weight.dtype), new_w32


@register("mp_adamw_update", aliases=["_mp_adamw_update"],
          differentiable=False, num_outputs=4,
          mutates_input=0, aux_writeback={1: 2, 2: 3, 3: 4})
def _mp_adamw_update(weight, grad, mean, var, weight32, rescale_grad,
                     lr=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                     wd=0.0, eta=1.0, clip_gradient=-1.0):
    # rescale_grad arrives as a TENSOR (loss-scale) like the reference
    g = grad.astype(jnp.float32) * rescale_grad.astype(jnp.float32)
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_mean = beta1 * mean + (1.0 - beta1) * g
    new_var = beta2 * var + (1.0 - beta2) * g * g
    # decoupled weight decay: wd OUTSIDE the lr factor (matches the fp32
    # _adamw_update above and the reference's mp_adamw_update)
    upd = lr * new_mean / (jnp.sqrt(new_var) + epsilon) + wd * weight32
    new_w32 = weight32 - eta * upd
    return new_w32.astype(weight.dtype), new_mean, new_var, new_w32


@register("_contrib_group_adagrad_update",
          aliases=["group_adagrad_update"], differentiable=False,
          num_outputs=2, mutates_input=0, aux_writeback={1: 2})
def _group_adagrad_update(weight, grad, history, lr=0.01, rescale_grad=1.0,
                          clip_gradient=-1.0, epsilon=1e-5):
    """Row-wise AdaGrad (reference: group_adagrad — Adagrad with one
    accumulator per embedding row)."""
    g = _prep(grad, rescale_grad, clip_gradient)
    sq = jnp.mean(g * g, axis=tuple(range(1, g.ndim)), keepdims=True) \
        if g.ndim > 1 else g * g
    new_h = history + sq
    return (weight - lr * g / (jnp.sqrt(new_h) + epsilon)).astype(
        weight.dtype), new_h


def _multi_pairs(arrays, stride):
    n = len(arrays) // stride
    return [tuple(arrays[i * stride + j] for j in range(stride))
            for i in range(n)]


def _scalar_list(v, n, default):
    if v is None:
        return (default,) * n
    if isinstance(v, (int, float)):
        return (float(v),) * n
    return tuple(float(x) for x in v)


@register("multi_sgd_update", differentiable=False, num_outputs=-1,
          aux_writeback=lambda p: {i: 2 * i
                                   for i in range(int(p.get("num_weights",
                                                            1)))})
def _multi_sgd_update(*arrays, lrs=None, wds=None, rescale_grad=1.0,
                      clip_gradient=-1.0, num_weights=1):
    """Fused SGD over many (weight, grad) pairs in ONE launch (reference:
    multi_sgd_update — kernel-launch amortization; here one XLA program).
    Outputs are written back in place via the registry's (callable)
    aux_writeback map keyed on num_weights."""
    lrs = _scalar_list(lrs, num_weights, 0.01)
    wds = _scalar_list(wds, num_weights, 0.0)
    outs = []
    for i, (w, g) in enumerate(_multi_pairs(list(arrays), 2)):
        gg = _prep(g, rescale_grad, clip_gradient, wds[i], w)
        outs.append(w - lrs[i] * gg.astype(w.dtype))
    return tuple(outs)


@register("multi_sgd_mom_update", differentiable=False, num_outputs=-1,
          aux_writeback=lambda p: {k: v for i in range(
              int(p.get("num_weights", 1)))
              for k, v in ((2 * i, 3 * i), (2 * i + 1, 3 * i + 2))})
def _multi_sgd_mom_update(*arrays, lrs=None, wds=None, momentum=0.0,
                          rescale_grad=1.0, clip_gradient=-1.0,
                          num_weights=1):
    lrs = _scalar_list(lrs, num_weights, 0.01)
    wds = _scalar_list(wds, num_weights, 0.0)
    outs = []
    for i, (w, g, m) in enumerate(_multi_pairs(list(arrays), 3)):
        gg = _prep(g, rescale_grad, clip_gradient, wds[i], w)
        new_m = momentum * m - lrs[i] * gg.astype(m.dtype)
        outs.append(w + new_m.astype(w.dtype))
        outs.append(new_m)
    return tuple(outs)


@register("multi_mp_sgd_update", differentiable=False, num_outputs=-1,
          aux_writeback=lambda p: {k: v for i in range(
              int(p.get("num_weights", 1)))
              for k, v in ((2 * i, 3 * i), (2 * i + 1, 3 * i + 2))})
def _multi_mp_sgd_update(*arrays, lrs=None, wds=None, rescale_grad=1.0,
                         clip_gradient=-1.0, num_weights=1):
    lrs = _scalar_list(lrs, num_weights, 0.01)
    wds = _scalar_list(wds, num_weights, 0.0)
    outs = []
    for i, (w, g, w32) in enumerate(_multi_pairs(list(arrays), 3)):
        gg = _prep(g.astype(jnp.float32), rescale_grad, clip_gradient,
                   wds[i], w32)
        new_w32 = w32 - lrs[i] * gg
        outs.append(new_w32.astype(w.dtype))
        outs.append(new_w32)
    return tuple(outs)


@register("multi_mp_sgd_mom_update", differentiable=False,
          num_outputs=-1,
          aux_writeback=lambda p: {k: v for i in range(
              int(p.get("num_weights", 1)))
              for k, v in ((3 * i, 4 * i), (3 * i + 1, 4 * i + 2),
                           (3 * i + 2, 4 * i + 3))})
def _multi_mp_sgd_mom_update(*arrays, lrs=None, wds=None, momentum=0.0,
                             rescale_grad=1.0, clip_gradient=-1.0,
                             num_weights=1):
    lrs = _scalar_list(lrs, num_weights, 0.01)
    wds = _scalar_list(wds, num_weights, 0.0)
    outs = []
    for i, (w, g, m, w32) in enumerate(_multi_pairs(list(arrays), 4)):
        gg = _prep(g.astype(jnp.float32), rescale_grad, clip_gradient,
                   wds[i], w32)
        new_m = momentum * m - lrs[i] * gg
        new_w32 = w32 + new_m
        outs.append(new_w32.astype(w.dtype))
        outs.append(new_m)
        outs.append(new_w32)
    return tuple(outs)


@register("multi_sum_sq", differentiable=False)
def _multi_sum_sq(*arrays, num_arrays=1):
    """Σx² per input array, stacked into one (N,) vector (reference:
    multi_sum_sq — the LARS norm pass)."""
    return jnp.stack([jnp.sum(a.astype(jnp.float32) * a) for a in arrays])


@register("multi_lars", differentiable=False)
def _multi_lars(lrs, weights_sum_sq, grads_sum_sq, wds, eta=0.001,
                eps=1e-8, rescale_grad=1.0):
    """LARS learning-rate adaptation over stacked per-layer norms
    (reference: multi_lars)."""
    w_norm = jnp.sqrt(weights_sum_sq)
    g_norm = jnp.sqrt(grads_sum_sq) * rescale_grad
    trust = jnp.where((w_norm > 0) & (g_norm > 0),
                      eta * w_norm / (g_norm + wds * w_norm + eps), 1.0)
    return lrs * trust


@register("preloaded_multi_sgd_update", differentiable=False,
          num_outputs=-1,
          aux_writeback=lambda p: {i: 2 * i for i in range(
              int(p.get("num_weights", 1)))})
def _preloaded_multi_sgd_update(*arrays, rescale_grad=1.0,
                                clip_gradient=-1.0, num_weights=1):
    """multi_sgd with lrs/wds as the trailing TENSOR inputs (reference:
    preloaded_multi_sgd_update — LARS feeds adapted lrs without a host
    roundtrip)."""
    lrs, wds = arrays[-2], arrays[-1]
    outs = []
    for i, (w, g) in enumerate(_multi_pairs(list(arrays[:-2]), 2)):
        # lr/wd are TENSOR elements (traced): apply arithmetically
        gg = _prep(g, rescale_grad, clip_gradient) + wds[i] * w
        outs.append(w - lrs[i] * gg.astype(w.dtype))
    return tuple(outs)


@register("preloaded_multi_sgd_mom_update", differentiable=False,
          num_outputs=-1,
          aux_writeback=lambda p: {k: v for i in range(
              int(p.get("num_weights", 1)))
              for k, v in ((2 * i, 3 * i), (2 * i + 1, 3 * i + 2))})
def _preloaded_multi_sgd_mom_update(*arrays, momentum=0.0, rescale_grad=1.0,
                                    clip_gradient=-1.0, num_weights=1):
    lrs, wds = arrays[-2], arrays[-1]
    outs = []
    for i, (w, g, m) in enumerate(_multi_pairs(list(arrays[:-2]), 3)):
        gg = _prep(g, rescale_grad, clip_gradient) + wds[i] * w
        new_m = momentum * m - lrs[i] * gg.astype(m.dtype)
        outs.append(w + new_m.astype(w.dtype))
        outs.append(new_m)
    return tuple(outs)


@register("preloaded_multi_mp_sgd_update", differentiable=False,
          num_outputs=-1,
          aux_writeback=lambda p: {k: v for i in range(
              int(p.get("num_weights", 1)))
              for k, v in ((2 * i, 3 * i), (2 * i + 1, 3 * i + 2))})
def _preloaded_multi_mp_sgd_update(*arrays, rescale_grad=1.0,
                                   clip_gradient=-1.0, num_weights=1):
    lrs, wds = arrays[-2], arrays[-1]
    outs = []
    for i, (w, g, w32) in enumerate(_multi_pairs(list(arrays[:-2]), 3)):
        gg = _prep(g.astype(jnp.float32), rescale_grad, clip_gradient) \
            + wds[i] * w32
        new_w32 = w32 - lrs[i] * gg
        outs.append(new_w32.astype(w.dtype))
        outs.append(new_w32)
    return tuple(outs)


@register("preloaded_multi_mp_sgd_mom_update", differentiable=False,
          num_outputs=-1,
          aux_writeback=lambda p: {k: v for i in range(
              int(p.get("num_weights", 1)))
              for k, v in ((3 * i, 4 * i), (3 * i + 1, 4 * i + 2),
                           (3 * i + 2, 4 * i + 3))})
def _preloaded_multi_mp_sgd_mom_update(*arrays, momentum=0.0,
                                       rescale_grad=1.0, clip_gradient=-1.0,
                                       num_weights=1):
    lrs, wds = arrays[-2], arrays[-1]
    outs = []
    for i, (w, g, m, w32) in enumerate(_multi_pairs(list(arrays[:-2]), 4)):
        gg = _prep(g.astype(jnp.float32), rescale_grad, clip_gradient) \
            + wds[i] * w32
        new_m = momentum * m - lrs[i] * gg
        new_w32 = w32 + new_m
        outs.append(new_w32.astype(w.dtype))
        outs.append(new_m)
        outs.append(new_w32)
    return tuple(outs)


@register("reset_arrays", differentiable=False, num_outputs=-1,
          aux_writeback=lambda p: {i: i for i in range(
              int(p.get("num_arrays", 1)))})
def _reset_arrays(*arrays, num_arrays=1):
    """Zero every input (reference: reset_arrays — gradient clearing in one
    launch).  Functional: returns the zeroed copies; in-place semantics come
    from the NDArray call layer."""
    return tuple(jnp.zeros_like(a) for a in arrays)


@register("multi_adamw_update", aliases=["_multi_adamw_update"],
          differentiable=False, num_outputs=-1,
          aux_writeback=lambda p: {k: v for i in range(
              int(p.get("num_weights", 1)))
              for k, v in ((3 * i, 4 * i), (3 * i + 1, 4 * i + 2),
                           (3 * i + 2, 4 * i + 3))})
def _multi_adamw_update(*arrays, lrs=None, wds=None, etas=None, beta1=0.9,
                        beta2=0.999, epsilon=1e-8, clip_gradient=-1.0,
                        num_weights=1):
    """Fused AdamW fleet (reference: src/operator/contrib/adamw.cc
    multi_adamw_update).  Inputs (w, g, mean, var)*N + rescale_grad tensor
    last."""
    rescale = arrays[-1].astype(jnp.float32)
    lrs = _scalar_list(lrs, num_weights, 0.001)
    wds = _scalar_list(wds, num_weights, 0.0)
    etas = _scalar_list(etas, num_weights, 1.0)
    outs = []
    for i, (w, g, m, v) in enumerate(_multi_pairs(list(arrays[:-1]), 4)):
        gg = g.astype(jnp.float32) * rescale
        if clip_gradient is not None and clip_gradient > 0:
            gg = jnp.clip(gg, -clip_gradient, clip_gradient)
        new_m = beta1 * m + (1.0 - beta1) * gg
        new_v = beta2 * v + (1.0 - beta2) * gg * gg
        upd = lrs[i] * new_m / (jnp.sqrt(new_v) + epsilon) + wds[i] * w
        outs.extend([(w - etas[i] * upd).astype(w.dtype), new_m, new_v])
    return tuple(outs)


@register("multi_mp_adamw_update", aliases=["_multi_mp_adamw_update"],
          differentiable=False, num_outputs=-1,
          aux_writeback=lambda p: {k: v for i in range(
              int(p.get("num_weights", 1)))
              for k, v in ((4 * i, 5 * i), (4 * i + 1, 5 * i + 2),
                           (4 * i + 2, 5 * i + 3), (4 * i + 3, 5 * i + 4))})
def _multi_mp_adamw_update(*arrays, lrs=None, wds=None, etas=None,
                           beta1=0.9, beta2=0.999, epsilon=1e-8,
                           clip_gradient=-1.0, num_weights=1):
    """Mixed-precision fused AdamW (inputs (w, g, mean, var, w32)*N +
    rescale_grad last)."""
    rescale = arrays[-1].astype(jnp.float32)
    lrs = _scalar_list(lrs, num_weights, 0.001)
    wds = _scalar_list(wds, num_weights, 0.0)
    etas = _scalar_list(etas, num_weights, 1.0)
    outs = []
    for i, (w, g, m, v, w32) in enumerate(_multi_pairs(list(arrays[:-1]),
                                                       5)):
        gg = g.astype(jnp.float32) * rescale
        if clip_gradient is not None and clip_gradient > 0:
            gg = jnp.clip(gg, -clip_gradient, clip_gradient)
        new_m = beta1 * m + (1.0 - beta1) * gg
        new_v = beta2 * v + (1.0 - beta2) * gg * gg
        upd = lrs[i] * new_m / (jnp.sqrt(new_v) + epsilon) + wds[i] * w32
        new_w32 = w32 - etas[i] * upd
        outs.extend([new_w32.astype(w.dtype), new_m, new_v, new_w32])
    return tuple(outs)


@register("multi_lans_update", aliases=["_multi_lans_update"],
          differentiable=False, num_outputs=-1,
          aux_writeback=lambda p: {k: v for i in range(
              int(p.get("num_weights", 1)))
              for k, v in ((3 * i, 4 * i), (3 * i + 1, 4 * i + 2),
                           (3 * i + 2, 4 * i + 3))})
def _multi_lans_update(*arrays, learning_rates=None, wds=None, beta1=0.9,
                       beta2=0.999, epsilon=1e-6, t=1, bias_correction=True,
                       lower_bound=-1.0, upper_bound=-1.0,
                       clip_gradient=-1.0, rescale_grad=1.0, num_weights=1):
    """Fused LANS fleet (reference: src/operator/contrib/multi_lans.cc /
    the LANS paper): per-layer trust ratio applied SEPARATELY to the
    momentum and gradient terms, each INCLUDING the weight-decay
    contribution; gradients are norm-normalized first.  Inputs
    (w, g, mean, var)*N; learning_rates/wds are float tuples."""
    lrs = _scalar_list(learning_rates, num_weights, 0.001)
    wds_l = _scalar_list(wds, num_weights, 0.0)
    outs = []
    for i, (w, g, m, v) in enumerate(_multi_pairs(list(arrays), 4)):
        w32 = w.astype(jnp.float32)
        # rescale accepted for reference-signature parity; it cancels under
        # the LANS norm-normalization below
        g32 = g.astype(jnp.float32) * rescale_grad
        gnorm = jnp.sqrt(jnp.sum(g32 * g32))
        g32 = g32 / jnp.maximum(gnorm, 1e-12)        # LANS grad normalize
        if clip_gradient is not None and clip_gradient > 0:
            g32 = jnp.clip(g32, -clip_gradient, clip_gradient)
        new_m = beta1 * m + (1.0 - beta1) * g32
        new_v = beta2 * v + (1.0 - beta2) * g32 * g32
        mh, vh = new_m, new_v
        if bias_correction:
            mh = mh / (1.0 - beta1 ** t)
            vh = vh / (1.0 - beta2 ** t)
        wnorm = jnp.sqrt(jnp.sum(w32 * w32))

        def trust(upd):
            unorm = jnp.sqrt(jnp.sum(upd * upd))
            ratio = jnp.where((wnorm > 0) & (unorm > 0), wnorm / unorm, 1.0)
            if lower_bound > 0:
                ratio = jnp.maximum(ratio, lower_bound)
            if upper_bound > 0:
                ratio = jnp.minimum(ratio, upper_bound)
            return ratio * upd
        denom = jnp.sqrt(vh) + epsilon
        upd = beta1 * trust(mh / denom + wds_l[i] * w32) +             (1.0 - beta1) * trust(g32 / denom + wds_l[i] * w32)
        outs.extend([(w32 - lrs[i] * upd).astype(w.dtype), new_m, new_v])
    return tuple(outs)


@register("multi_mp_lans_update", aliases=["_multi_mp_lans_update"],
          differentiable=False, num_outputs=-1,
          aux_writeback=lambda p: {k: v for i in range(
              int(p.get("num_weights", 1)))
              for k, v in ((4 * i, 5 * i), (4 * i + 1, 5 * i + 2),
                           (4 * i + 2, 5 * i + 3), (4 * i + 3, 5 * i + 4))})
def _multi_mp_lans_update(*arrays, learning_rates=None, wds=None, beta1=0.9,
                          beta2=0.999, epsilon=1e-6, t=1,
                          bias_correction=True, lower_bound=-1.0,
                          upper_bound=-1.0, clip_gradient=-1.0,
                          rescale_grad=1.0, num_weights=1):
    """Mixed-precision LANS fleet ((w, g, mean, var, w32)*N)."""
    lrs = _scalar_list(learning_rates, num_weights, 0.001)
    wds_l = _scalar_list(wds, num_weights, 0.0)
    outs = []
    for i, (w, g, m, v, w32) in enumerate(_multi_pairs(list(arrays), 5)):
        g32 = g.astype(jnp.float32) * rescale_grad  # cancels post-normalize
        gnorm = jnp.sqrt(jnp.sum(g32 * g32))
        g32 = g32 / jnp.maximum(gnorm, 1e-12)
        if clip_gradient is not None and clip_gradient > 0:
            g32 = jnp.clip(g32, -clip_gradient, clip_gradient)
        new_m = beta1 * m + (1.0 - beta1) * g32
        new_v = beta2 * v + (1.0 - beta2) * g32 * g32
        mh, vh = new_m, new_v
        if bias_correction:
            mh = mh / (1.0 - beta1 ** t)
            vh = vh / (1.0 - beta2 ** t)
        wnorm = jnp.sqrt(jnp.sum(w32 * w32))

        def trust(upd):
            unorm = jnp.sqrt(jnp.sum(upd * upd))
            ratio = jnp.where((wnorm > 0) & (unorm > 0), wnorm / unorm, 1.0)
            if lower_bound > 0:
                ratio = jnp.maximum(ratio, lower_bound)
            if upper_bound > 0:
                ratio = jnp.minimum(ratio, upper_bound)
            return ratio * upd
        denom = jnp.sqrt(vh) + epsilon
        upd = beta1 * trust(mh / denom + wds_l[i] * w32) +             (1.0 - beta1) * trust(g32 / denom + wds_l[i] * w32)
        new_w32 = w32 - lrs[i] * upd
        outs.extend([new_w32.astype(w.dtype), new_m, new_v, new_w32])
    return tuple(outs)

@register("adagrad_update", aliases=["_sparse_adagrad_update"],
          differentiable=False, num_outputs=2, mutates_input=0,
          aux_writeback={1: 2})
def _adagrad_update(weight, grad, history, lr=0.01, epsilon=1e-7, wd=0.0,
                    rescale_grad=1.0, clip_gradient=-1.0):
    """AdaGrad (reference: src/operator/optimizer_op.cc adagrad_update;
    the _sparse_adagrad_update alias covers the rowsparse entry point —
    rowsparse laziness happens at the NDArray layer here, the math is
    identical)."""
    g = _prep(grad, rescale_grad, clip_gradient)
    new_h = history + g * g
    upd = g / jnp.sqrt(new_h + epsilon) + wd * weight
    return (weight - lr * upd).astype(weight.dtype), new_h


# ---------------------------------------------------------------------------
# Tree kernels: fused pytree optimizer apply (ISSUE 3 tentpole a).
#
# The registry ops above are the reference's per-tensor kernels — one
# dispatch per parameter.  The tree kernels below take the WHOLE parameter
# group as pytrees (lists of arrays) and apply the update as ONE jitted XLA
# program: the role of the reference's multi_sgd_update / multi_adamw fleets,
# but without the flat varargs calling convention — lr folds in as a traced
# per-leaf vector (so an LR scheduler never retriggers a compile), wd / clip
# / momentum are static, and the weight/state buffers are donated so XLA
# updates them in place (donation is skipped on the cpu backend, which
# cannot reuse buffers and would warn).
#
# Each leaf's math reuses the per-tensor kernel bodies above, so fused and
# per-param trajectories agree to fp32 tolerance (the equivalence suite in
# tests/test_fused_update.py pins this).  Multi-precision leaves follow
# Optimizer.update_multi_precision's generic master-copy semantics: grad is
# cast to fp32, the fp32 body runs on weight32, and the low-precision weight
# is a cast of the new master.
# ---------------------------------------------------------------------------


def _tree_sgd(weights, grads, weights32, lrs, *, wds=(), rescale_grad=1.0,
              clip_gradient=-1.0, mp=False):
    new_w, new_w32 = [], []
    for i, (w, g) in enumerate(zip(weights, grads)):
        if mp:
            w32 = weights32[i]
            nw32 = _sgd_update(w32, g.astype(jnp.float32), lr=lrs[i],
                               wd=wds[i], rescale_grad=rescale_grad,
                               clip_gradient=clip_gradient)
            new_w.append(nw32.astype(w.dtype))
            new_w32.append(nw32)
        else:
            new_w.append(_sgd_update(w, g, lr=lrs[i], wd=wds[i],
                                     rescale_grad=rescale_grad,
                                     clip_gradient=clip_gradient))
    return tuple(new_w), None, tuple(new_w32) if mp else None


def _tree_sgd_mom(weights, grads, moms, weights32, lrs, *, momentum=0.0,
                  wds=(), rescale_grad=1.0, clip_gradient=-1.0, mp=False):
    new_w, new_m, new_w32 = [], [], []
    for i, (w, g, m) in enumerate(zip(weights, grads, moms)):
        if mp:
            w32 = weights32[i]
            nw32, nm = _sgd_mom_update(w32, g.astype(jnp.float32), m,
                                       lr=lrs[i], momentum=momentum,
                                       wd=wds[i], rescale_grad=rescale_grad,
                                       clip_gradient=clip_gradient)
            new_w.append(nw32.astype(w.dtype))
            new_m.append(nm)
            new_w32.append(nw32)
        else:
            nw, nm = _sgd_mom_update(w, g, m, lr=lrs[i], momentum=momentum,
                                     wd=wds[i], rescale_grad=rescale_grad,
                                     clip_gradient=clip_gradient)
            new_w.append(nw)
            new_m.append(nm)
    return tuple(new_w), (tuple(new_m),), tuple(new_w32) if mp else None


def _tree_nag_mom(weights, grads, moms, weights32, lrs, *, momentum=0.0,
                  wds=(), rescale_grad=1.0, clip_gradient=-1.0, mp=False):
    new_w, new_m, new_w32 = [], [], []
    for i, (w, g, m) in enumerate(zip(weights, grads, moms)):
        if mp:
            w32 = weights32[i]
            nw32, nm = _nag_mom_update(w32, g.astype(jnp.float32), m,
                                       lr=lrs[i], momentum=momentum,
                                       wd=wds[i], rescale_grad=rescale_grad,
                                       clip_gradient=clip_gradient)
            new_w.append(nw32.astype(w.dtype))
            new_m.append(nm)
            new_w32.append(nw32)
        else:
            nw, nm = _nag_mom_update(w, g, m, lr=lrs[i], momentum=momentum,
                                     wd=wds[i], rescale_grad=rescale_grad,
                                     clip_gradient=clip_gradient)
            new_w.append(nw)
            new_m.append(nm)
    return tuple(new_w), (tuple(new_m),), tuple(new_w32) if mp else None


def _tree_adam(weights, grads, means, variances, weights32, lrs, *,
               beta1=0.9, beta2=0.999, epsilon=1e-8, wds=(),
               rescale_grad=1.0, clip_gradient=-1.0, mp=False):
    # lrs arrive bias-corrected per leaf (the class folds sqrt(1-b2^t)/
    # (1-b1^t) in on host, exactly like the per-param path)
    new_w, new_m, new_v, new_w32 = [], [], [], []
    for i, (w, g, m, v) in enumerate(zip(weights, grads, means, variances)):
        tgt = weights32[i] if mp else w
        gg = g.astype(jnp.float32) if mp else g
        nw, nm, nv = _adam_update(tgt, gg, m, v, lr=lrs[i], beta1=beta1,
                                  beta2=beta2, epsilon=epsilon, wd=wds[i],
                                  rescale_grad=rescale_grad,
                                  clip_gradient=clip_gradient)
        new_w.append(nw.astype(w.dtype) if mp else nw)
        new_m.append(nm)
        new_v.append(nv)
        if mp:
            new_w32.append(nw)
    return (tuple(new_w), (tuple(new_m), tuple(new_v)),
            tuple(new_w32) if mp else None)


def _tree_adamw(weights, grads, means, variances, weights32, lrs, decays, *,
                beta1=0.9, beta2=0.999, epsilon=1e-8, wds=(),
                rescale_grad=1.0, clip_gradient=-1.0, mp=False):
    # lrs = bias-corrected step lr; decays = raw_lr * wd per leaf (the
    # class's decoupled `weight -= lr * wd * weight`, fused in)
    new_w, new_m, new_v, new_w32 = [], [], [], []
    for i, (w, g, m, v) in enumerate(zip(weights, grads, means, variances)):
        tgt = weights32[i] if mp else w
        gg = g.astype(jnp.float32) if mp else g
        nw, nm, nv = _adamw_update(tgt, gg, m, v, lr=lrs[i], beta1=beta1,
                                   beta2=beta2, epsilon=epsilon, wd=0.0,
                                   eta=1.0, rescale_grad=rescale_grad,
                                   clip_gradient=clip_gradient)
        if wds[i]:
            nw = nw - decays[i] * nw
        new_w.append(nw.astype(w.dtype) if mp else nw)
        new_m.append(nm)
        new_v.append(nv)
        if mp:
            new_w32.append(nw)
    return (tuple(new_w), (tuple(new_m), tuple(new_v)),
            tuple(new_w32) if mp else None)


# kind -> (body, donatable positional argnums: weight/state buffers only —
# grads and the lr vector must survive the call)
_TREE_BODIES = {
    "sgd": (_tree_sgd, (0, 2)),
    "sgd_mom": (_tree_sgd_mom, (0, 2, 3)),
    "nag_mom": (_tree_nag_mom, (0, 2, 3)),
    "adam": (_tree_adam, (0, 2, 3, 4)),
    "adamw": (_tree_adamw, (0, 2, 3, 4)),
}


def tree_body(kind):
    """The PURE (un-jitted) tree-kernel body for `kind`, or None.

    The whole-step compiled lane (mxnet_tpu.step) inlines these bodies
    into its single-program trace so the fused eager apply and the
    compiled step share one implementation of every optimizer's math —
    signature ``body(weights, grads, *state_cols, weights32, lrs[,
    decays], **static) -> (new_w, new_state_cols_or_None, new_w32_or_
    None)`` exactly as :func:`tree_apply` dispatches it."""
    hit = _TREE_BODIES.get(kind)
    return hit[0] if hit else None


@functools.lru_cache(maxsize=512)
def _tree_jit(kind, statics, donate):
    body, donatable = _TREE_BODIES[kind]
    fn = functools.partial(body, **dict(statics))
    from ..programs import register_program
    return register_program("optimizer.fused_%s" % kind, fn,
                            specializing=True,
                            donate_argnums=donatable if donate else ())


def tree_apply(kind, arrays, lrs, decays=None, **static_params):
    """Apply one fused pytree update: ONE device dispatch for the whole
    (weight, grad, state) group.

    ``arrays`` is the kind's positional pytree lists (weights, grads,
    states..., weights32-or-None); ``lrs`` (and for adamw ``decays``) are
    per-leaf host floats, shipped as a traced fp32 vector so per-step lr
    changes never recompile.  Everything in ``static_params`` (wds tuple,
    momentum, betas, clip, rescale_grad, mp) is static — stable across
    steps.  Returns (new_weights, new_states_tuple_or_None,
    new_weights32_or_None) as tuples of jax arrays.
    """
    import numpy as _onp
    from ..engine import engine as _engine
    donate = jax.default_backend() != "cpu"
    fn = _tree_jit(kind, tuple(sorted(static_params.items())), donate)
    args = [tuple(a) if isinstance(a, list) else a for a in arrays]
    args.append(jnp.asarray(_onp.asarray(lrs, _onp.float32)))
    if kind == "adamw":
        args.append(jnp.asarray(_onp.asarray(decays, _onp.float32)))
    _engine.count_dispatch()
    return fn(*args)


def _lamb_fleet_body(w, g, m, v, w32, lr, wd, beta1, beta2, epsilon, t,
                     bias_correction, lower_bound, upper_bound,
                     clip_gradient, rescale_grad):
    """One LAMB fleet member (reference: src/operator/contrib/multi_lamb.cc):
    adam moments, then ONE per-layer trust ratio on the whole update
    (contrast LANS, which applies separate ratios to the momentum and
    gradient terms)."""
    g32 = g.astype(jnp.float32) * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g32 = jnp.clip(g32, -clip_gradient, clip_gradient)
    new_m = beta1 * m + (1.0 - beta1) * g32
    new_v = beta2 * v + (1.0 - beta2) * g32 * g32
    mh, vh = new_m, new_v
    if bias_correction:
        mh = mh / (1.0 - beta1 ** t)
        vh = vh / (1.0 - beta2 ** t)
    upd = mh / (jnp.sqrt(vh) + epsilon) + wd * w32
    wnorm = jnp.sqrt(jnp.sum(w32 * w32))
    if lower_bound > 0:
        wnorm = jnp.maximum(wnorm, lower_bound)
    if upper_bound > 0:
        wnorm = jnp.minimum(wnorm, upper_bound)
    unorm = jnp.sqrt(jnp.sum(upd * upd))
    ratio = jnp.where((wnorm > 0) & (unorm > 0), wnorm / unorm, 1.0)
    return w32 - lr * ratio * upd, new_m, new_v


@register("multi_lamb_update", aliases=["_contrib_multi_lamb_update"],
          differentiable=False, num_outputs=-1,
          aux_writeback=lambda p: {k: v for i in range(
              int(p.get("num_weights", 1)))
              for k, v in ((3 * i, 4 * i), (3 * i + 1, 4 * i + 2),
                           (3 * i + 2, 4 * i + 3))})
def _multi_lamb_update(*arrays, learning_rates=None, wds=None, beta1=0.9,
                       beta2=0.999, epsilon=1e-6, t=1, bias_correction=True,
                       lower_bound=-1.0, upper_bound=-1.0,
                       clip_gradient=-1.0, rescale_grad=1.0, num_weights=1):
    """Fused multi-tensor LAMB ((w, g, mean, var)*N)."""
    lrs = _scalar_list(learning_rates, num_weights, 0.001)
    wds_l = _scalar_list(wds, num_weights, 0.0)
    outs = []
    for i, (w, g, m, v) in enumerate(_multi_pairs(list(arrays), 4)):
        new_w32, new_m, new_v = _lamb_fleet_body(
            w, g, m, v, w.astype(jnp.float32), lrs[i], wds_l[i], beta1,
            beta2, epsilon, t, bias_correction, lower_bound, upper_bound,
            clip_gradient, rescale_grad)
        outs.extend([new_w32.astype(w.dtype), new_m, new_v])
    return tuple(outs)


@register("multi_mp_lamb_update", aliases=["_contrib_multi_mp_lamb_update"],
          differentiable=False, num_outputs=-1,
          aux_writeback=lambda p: {k: v for i in range(
              int(p.get("num_weights", 1)))
              for k, v in ((4 * i, 5 * i), (4 * i + 1, 5 * i + 2),
                           (4 * i + 2, 5 * i + 3), (4 * i + 3, 5 * i + 4))})
def _multi_mp_lamb_update(*arrays, learning_rates=None, wds=None, beta1=0.9,
                          beta2=0.999, epsilon=1e-6, t=1,
                          bias_correction=True, lower_bound=-1.0,
                          upper_bound=-1.0, clip_gradient=-1.0,
                          rescale_grad=1.0, num_weights=1):
    """Mixed-precision fused LAMB ((w, g, mean, var, w32)*N)."""
    lrs = _scalar_list(learning_rates, num_weights, 0.001)
    wds_l = _scalar_list(wds, num_weights, 0.0)
    outs = []
    for i, (w, g, m, v, w32) in enumerate(_multi_pairs(list(arrays), 5)):
        new_w32, new_m, new_v = _lamb_fleet_body(
            w, g, m, v, w32, lrs[i], wds_l[i], beta1, beta2, epsilon, t,
            bias_correction, lower_bound, upper_bound, clip_gradient,
            rescale_grad)
        outs.extend([new_w32.astype(w.dtype), new_m, new_v, new_w32])
    return tuple(outs)


# ---------------------------------------------------------------------------
# Program contracts (ISSUE 11): the fused tree kernels' declared
# donation/HBM invariants.  Declaration is a dict insert; the builders
# below only run inside the device-free verifier
# (`python -m tools.mxlint --contracts`), which lowers each kernel with
# abstract inputs and proves every donated buffer actually aliases an
# output — the eager path only turns donation ON off-CPU
# (tree_apply's `donate = jax.default_backend() != "cpu"`), so a
# dropped donation would otherwise surface as doubled HBM on the first
# TPU run and nowhere else.
# ---------------------------------------------------------------------------

# per kind: (static params beyond wds/rescale/clip/mp, extra traced args)
_CONTRACT_STATICS = {
    "sgd": {},
    "sgd_mom": {"momentum": 0.9},
    "nag_mom": {"momentum": 0.9},
    "adam": {"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8},
    "adamw": {"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8},
}

# state columns between grads and weights32 in each body's signature
_CONTRACT_N_STATE = {"sgd": 0, "sgd_mom": 1, "nag_mom": 1,
                     "adam": 2, "adamw": 2}


def _fused_contract_cases(kind, mp):
    """ContractCases for one fused kind: 3 leaves of 64 elements — small
    enough to lower instantly, structured enough that every donated
    buffer class (weights, each state column, weights32) is present."""
    from ..programs import ContractCase
    n, leaf = 3, (64,)
    wdtype = jnp.bfloat16 if mp else jnp.float32

    def col(dt=jnp.float32):
        return tuple(jax.ShapeDtypeStruct(leaf, dt) for _ in range(n))

    statics = dict(_CONTRACT_STATICS[kind])
    statics.update(wds=(0.0,) * n, rescale_grad=1.0 / 32,
                   clip_gradient=-1.0, mp=mp)
    fn = _tree_jit(kind, tuple(sorted(statics.items())), True)
    args = [col(wdtype), col(wdtype)]
    args += [col() for _ in range(_CONTRACT_N_STATE[kind])]
    args.append(col() if mp else None)                    # weights32
    args.append(jax.ShapeDtypeStruct((n,), jnp.float32))  # lrs
    if kind == "adamw":
        args.append(jax.ShapeDtypeStruct((n,), jnp.float32))
    return [ContractCase("optimizer.fused_%s" % kind, tuple(args),
                         label="%s%s" % (kind, "_mp" if mp else ""),
                         target=fn)]


def _declare_fused_contracts():
    from ..programs import declare_contract
    for kind, (_body, donatable) in sorted(_TREE_BODIES.items()):
        def build(kind=kind):
            cases = _fused_contract_cases(kind, mp=False)
            if kind in ("adam", "adamw"):
                # the multi-precision layout donates weights32 too —
                # prove that alias on at least one Adam-family kind
                cases += _fused_contract_cases(kind, mp=True)
            return cases
        declare_contract(
            "optimizer.fused_%s" % kind, build,
            donate_argnums=donatable,
            temp_budget_bytes=1 << 20,
            description="fused multi-tensor %s apply: weight/state "
                        "buffers donate in-place; grads and the lr "
                        "vector survive the call" % kind)


_declare_fused_contracts()
