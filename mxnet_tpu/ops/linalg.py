"""Linear-algebra op tail.

Reference: src/operator/tensor/la_op.cc (linalg_gemm/trmm/potri/gelqf/
syevd/makediag/extractdiag/maketrian/extracttrian/sumlogdiag/det/slogdet/
inverse), src/operator/numpy/linalg/*, src/operator/contrib/krprod.cc
(khatri_rao), np einsum.

All lower to jax.numpy.linalg / lax.linalg — XLA's native decompositions
(QR/Cholesky/eigh run on the MXU where block-factorizable).  gemm2/potrf/
syrk/trsm live in matrix.py since round 1; this file adds the tail.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register, alias


@register("linalg_gemm")
def _linalg_gemm(a, b, c, transpose_a=False, transpose_b=False, alpha=1.0,
                 beta=1.0, axis=-2):
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    return alpha * jnp.matmul(a, b) + beta * c


@register("linalg_trmm")
def _linalg_trmm(a, b, transpose=False, rightside=False, lower=True,
                 alpha=1.0):
    tri = jnp.tril(a) if lower else jnp.triu(a)
    if transpose:
        tri = jnp.swapaxes(tri, -1, -2)
    return alpha * (jnp.matmul(b, tri) if rightside else jnp.matmul(tri, b))


@register("linalg_potri")
def _linalg_potri(a):
    """Inverse from a Cholesky factor: (L L^T)^-1 given L."""
    eye = jnp.broadcast_to(jnp.eye(a.shape[-1], dtype=a.dtype), a.shape)
    linv = lax.linalg.triangular_solve(a, eye, left_side=True, lower=True)
    return jnp.matmul(jnp.swapaxes(linv, -1, -2), linv)


@register("linalg_gelqf", num_outputs=2)
def _linalg_gelqf(a):
    """LQ factorization (reference returns (L, Q) with A = L Q)."""
    q, r = jnp.linalg.qr(jnp.swapaxes(a, -1, -2), mode="reduced")
    return jnp.swapaxes(r, -1, -2), jnp.swapaxes(q, -1, -2)


@register("linalg_syevd", num_outputs=2)
def _linalg_syevd(a):
    """Symmetric eigendecomposition: returns (U, lambda) with
    A = U^T diag(lambda) U (the reference's row-eigenvector convention)."""
    w, v = jnp.linalg.eigh(a)
    return jnp.swapaxes(v, -1, -2), w


@register("linalg_makediag")
def _linalg_makediag(a, offset=0):
    return jnp.vectorize(lambda v: jnp.diag(v, k=offset),
                         signature="(n)->(m,m)")(a)


@register("linalg_extractdiag")
def _linalg_extractdiag(a, offset=0):
    return jnp.vectorize(lambda m: jnp.diag(m, k=offset),
                         signature="(m,m)->(n)")(a)


@register("linalg_maketrian")
def _linalg_maketrian(a, offset=0, lower=True):
    """Pack a vector into a (lower/upper) triangular matrix."""
    n_elem = a.shape[-1]
    # n(n+1)/2 = n_elem → n
    n = int((-1 + (1 + 8 * n_elem) ** 0.5) / 2)
    idx = jnp.tril_indices(n) if lower else jnp.triu_indices(n)

    def pack(v):
        m = jnp.zeros((n, n), a.dtype)
        return m.at[idx].set(v)
    return jnp.vectorize(pack, signature="(k)->(m,m)")(a)


@register("linalg_extracttrian")
def _linalg_extracttrian(a, offset=0, lower=True):
    n = a.shape[-1]
    idx = jnp.tril_indices(n) if lower else jnp.triu_indices(n)

    def unpack(m):
        return m[idx]
    return jnp.vectorize(unpack, signature="(m,m)->(k)")(a)


@register("linalg_sumlogdiag")
def _linalg_sumlogdiag(a):
    d = jnp.diagonal(a, axis1=-2, axis2=-1)
    return jnp.sum(jnp.log(d), axis=-1)


@register("linalg_inverse", aliases=["inverse"])
def _linalg_inverse(a):
    return jnp.linalg.inv(a)


@register("linalg_det", aliases=["det"])
def _linalg_det(a):
    return jnp.linalg.det(a)


@register("linalg_slogdet", aliases=["slogdet"], num_outputs=2)
def _linalg_slogdet(a):
    sign, logabs = jnp.linalg.slogdet(a)
    return sign, logabs


@register("khatri_rao", differentiable=True)
def _khatri_rao(*mats):
    """Column-wise Kronecker product (reference: src/operator/contrib/
    krprod.cc)."""
    out = mats[0]
    for m in mats[1:]:
        out = (out[:, None, :] * m[None, :, :]).reshape(-1, out.shape[-1])
    return out


@register("einsum")
def _einsum(*args, subscripts=""):
    return jnp.einsum(subscripts, *args)


alias("einsum", "_npi_einsum")


@register("moments", num_outputs=2)
def _moments(data, axes=None, keepdims=False):
    """Reference: src/operator/nn/moments.cc — returns (mean, var)."""
    ax = tuple(axes) if isinstance(axes, (list, tuple)) else axes
    mean = jnp.mean(data, axis=ax, keepdims=keepdims)
    var = jnp.var(data, axis=ax, keepdims=keepdims)
    return mean, var


@register("batch_take")
def _batch_take(a, indices):
    """Reference: src/operator/tensor/indexing_op.cc (batch_take):
    out[i] = a[i, indices[i]]."""
    return jnp.take_along_axis(
        a, indices.astype(jnp.int32)[..., None], axis=-1)[..., 0]


@register("ravel_multi_index", differentiable=False)
def _ravel_multi_index(data, shape=None):
    """data: (ndim, N) indices → flat indices (reference:
    src/operator/tensor/ravel.cc)."""
    strides = []
    s = 1
    for d in reversed(shape):
        strides.append(s)
        s *= d
    strides = jnp.asarray(list(reversed(strides)), data.dtype)
    return jnp.sum(data * strides[:, None], axis=0)


@register("unravel_index", differentiable=False)
def _unravel_index(data, shape=None):
    out = []
    rem = data.astype(jnp.int64) if data.dtype != jnp.int32 else data
    strides = []
    s = 1
    for d in reversed(shape):
        strides.append(s)
        s *= d
    for st, d in zip(reversed(strides), shape):
        out.append((rem // st) % d)
    return jnp.stack(out, axis=0).astype(data.dtype)
