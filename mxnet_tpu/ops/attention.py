"""Attention ops: Pallas flash attention + MXNet transformer parity ops.

Reference: src/operator/contrib/transformer.cc
(_contrib_interleaved_matmul_selfatt_qk, _contrib_interleaved_matmul_
selfatt_valatt, _contrib_interleaved_matmul_encdec_qk/valatt) — the fused
attention matmuls GluonNLP's BERT uses.

TPU-native: the hot path is a blockwise online-softmax (flash) attention
kernel in Pallas (SURVEY.md §2.1 cuDNN row: "attention → Pallas flash
attention").  Blocks stream K/V through VMEM with running (max, sum)
accumulators so the T×T score matrix never materializes in HBM; the MXU
does the two matmuls per block.  Backward recomputes attention from the
saved inputs (rematerialization — trade FLOPs for HBM, SURVEY.md design
notes).  Non-TPU backends and unaligned shapes fall back to the jnp
composition, which XLA fuses well at moderate sequence length.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

__all__ = ["attention_core", "flash_attention"]

_BLOCK_Q = 256
_BLOCK_K = 256


def _on_tpu() -> bool:
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


# ---------------------------------------------------------------------------
# jnp reference path (always-correct fallback; also the recompute backward)
# ---------------------------------------------------------------------------


def _attention_jnp(q, k, v, scale, causal):
    """q,k,v: (B, H, T, D)."""
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        Tq, Tk = q.shape[2], k.shape[2]
        mask = jnp.tril(jnp.ones((Tq, Tk), bool), Tk - Tq)
        logits = jnp.where(mask, logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


# ---------------------------------------------------------------------------
# Pallas flash kernel (forward)
# ---------------------------------------------------------------------------


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, scale, causal,
                      block_k, seq_k):
    # refs: q (block_q, D), k/v (seq_k, D), o (block_q, D); grid=(BH, Tq/bq)
    import jax.experimental.pallas as pl

    block_q, d = q_ref.shape
    q = q_ref[:].astype(jnp.float32) * scale
    q_idx = pl.program_id(1)

    m = jnp.full((block_q, 1), -jnp.inf, jnp.float32)
    l = jnp.zeros((block_q, 1), jnp.float32)
    acc = jnp.zeros((block_q, d), jnp.float32)

    num_kb = seq_k // block_k

    def body(kb, carry):
        m, l, acc = carry
        k_blk = k_ref[pl.dslice(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.dslice(kb * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)
        if causal:
            q_pos = q_idx * block_q + \
                lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_pos = kb * block_k + \
                lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        # guard fully-masked rows: exp(-inf - -inf) would be nan
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s - m_safe)
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = alpha * acc + jnp.dot(p, v_blk,
                                        preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    if causal:
        # only key blocks at or before this query block contribute
        num_kb_eff = (q_idx + 1) * block_q // block_k
        num_kb_eff = jnp.minimum(num_kb_eff, num_kb)
        m, l, acc = lax.fori_loop(0, num_kb_eff, body, (m, l, acc))
    else:
        m, l, acc = lax.fori_loop(0, num_kb, body, (m, l, acc))

    o_ref[:] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_fwd(q, k, v, scale, causal, block_q=_BLOCK_Q, block_k=_BLOCK_K):
    """q,k,v: (B, H, T, D) with T % block == 0."""
    import jax.experimental.pallas as pl

    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    qr = q.reshape(B * H, Tq, D)
    kr = k.reshape(B * H, Tk, D)
    vr = v.reshape(B * H, Tk, D)
    kernel = functools.partial(_flash_fwd_kernel, scale=scale, causal=causal,
                               block_k=block_k, seq_k=Tk)
    out = pl.pallas_call(
        kernel,
        grid=(B * H, Tq // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, Tk, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, Tk, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, Tq, D), q.dtype),
    )(qr, kr, vr)
    return out.reshape(B, H, Tq, D)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention(q, k, v, scale, causal):
    """Blockwise flash attention, (B, H, T, D) layout."""
    return _flash_fwd(q, k, v, scale, causal)


def _flash_vjp_fwd(q, k, v, scale, causal):
    return _flash_fwd(q, k, v, scale, causal), (q, k, v)


def _flash_vjp_bwd(scale, causal, res, g):
    # rematerialized backward through the jnp composition (correct grads;
    # the dedicated flash backward kernel is a later optimization)
    q, k, v = res
    _, vjp = jax.vjp(lambda q, k, v: _attention_jnp(q, k, v, scale, causal),
                     q, k, v)
    return vjp(g)


flash_attention.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def attention_core(q, k, v, scale=None, causal=False, mask=None):
    """Dispatch: Pallas flash on TPU for aligned mask-free shapes, jnp
    composition otherwise.  q,k,v: (B, H, T, D)."""
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    Tq, Tk, D = q.shape[2], k.shape[2], q.shape[3]
    use_flash = (_on_tpu() and mask is None and
                 Tq % _BLOCK_Q == 0 and Tk % _BLOCK_K == 0 and
                 D % 128 == 0 and (not causal or Tq == Tk))
    if use_flash:
        return flash_attention(q, k, v, float(scale), bool(causal))
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        cm = jnp.tril(jnp.ones((Tq, Tk), bool), Tk - Tq)
        logits = jnp.where(cm, logits, -jnp.inf)
    if mask is not None:
        logits = jnp.where(mask.astype(bool), logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


# ---------------------------------------------------------------------------
# MXNet transformer parity ops (interleaved QKV layout, reference:
# src/operator/contrib/transformer.cc).  Input: (T, N, H*3*D) where the
# projection interleaves [q1..qD, k1..kD, v1..vD] per head.
# ---------------------------------------------------------------------------


def _split_interleaved_qkv(qkv, heads):
    T, N, HC = qkv.shape
    D = HC // (heads * 3)
    x = qkv.reshape(T, N, heads, 3, D)
    # -> (N, heads, T, D)
    q = x[:, :, :, 0].transpose(1, 2, 0, 3)
    k = x[:, :, :, 1].transpose(1, 2, 0, 3)
    v = x[:, :, :, 2].transpose(1, 2, 0, 3)
    return q, k, v


@register("_contrib_interleaved_matmul_selfatt_qk")
def _selfatt_qk(queries_keys_values, heads=1):
    """scores = scaled q @ k^T → (N*heads, T, T)."""
    q, k, _ = _split_interleaved_qkv(queries_keys_values, heads)
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(q.dtype)
    s = jnp.einsum("nhqd,nhkd->nhqk", q * scale, k,
                   preferred_element_type=jnp.float32).astype(q.dtype)
    N, H, T, _ = s.shape
    return s.reshape(N * H, T, T)


@register("_contrib_interleaved_matmul_selfatt_valatt")
def _selfatt_valatt(queries_keys_values, attention, heads=1):
    """out = att @ v → (T, N, H*D)."""
    _, _, v = _split_interleaved_qkv(queries_keys_values, heads)
    N, H, T, D = v.shape
    att = attention.reshape(N, H, T, T)
    out = jnp.einsum("nhqk,nhkd->nhqd", att, v)
    return out.transpose(2, 0, 1, 3).reshape(T, N, H * D)


@register("_contrib_interleaved_matmul_encdec_qk")
def _encdec_qk(queries, keys_values, heads=1):
    Tq, N, HC = queries.shape
    D = HC // heads
    q = queries.reshape(Tq, N, heads, D).transpose(1, 2, 0, 3)
    Tk = keys_values.shape[0]
    kv = keys_values.reshape(Tk, N, heads, 2, D)
    k = kv[:, :, :, 0].transpose(1, 2, 0, 3)
    scale = 1.0 / jnp.sqrt(jnp.asarray(D, q.dtype))
    s = jnp.einsum("nhqd,nhkd->nhqk", q * scale, k,
                   preferred_element_type=jnp.float32).astype(q.dtype)
    return s.reshape(N * heads, Tq, Tk)


@register("_contrib_interleaved_matmul_encdec_valatt")
def _encdec_valatt(keys_values, attention, heads=1):
    Tk, N, HC = keys_values.shape
    D = HC // (heads * 2)
    kv = keys_values.reshape(Tk, N, heads, 2, D)
    v = kv[:, :, :, 1].transpose(1, 2, 0, 3)
    Tq = attention.shape[1]
    att = attention.reshape(N, heads, Tq, Tk)
    out = jnp.einsum("nhqk,nhkd->nhqd", att, v)
    return out.transpose(2, 0, 1, 3).reshape(Tq, N, heads * D)
