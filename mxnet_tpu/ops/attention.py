"""Attention ops: Pallas flash attention + MXNet transformer parity ops.

Reference: src/operator/contrib/transformer.cc
(_contrib_interleaved_matmul_selfatt_qk, _contrib_interleaved_matmul_
selfatt_valatt, _contrib_interleaved_matmul_encdec_qk/valatt) — the fused
attention matmuls GluonNLP's BERT uses.

TPU-native: the hot path is a blockwise online-softmax (flash) attention
kernel in Pallas (SURVEY.md §2.1 cuDNN row: "attention → Pallas flash
attention").  Blocks stream K/V through VMEM with running (max, sum)
accumulators so the T×T score matrix never materializes in HBM; the MXU
does the two matmuls per block.  Backward recomputes attention from the
saved inputs (rematerialization — trade FLOPs for HBM, SURVEY.md design
notes).  Non-TPU backends and unaligned shapes fall back to the jnp
composition, which XLA fuses well at moderate sequence length.
"""
from __future__ import annotations

import functools
import threading

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

__all__ = ["attention_core", "flash_attention", "cached_attention",
           "cached_attention_multi", "paged_attention",
           "paged_attention_multi"]

# kernel block sizes: 256x256 keeps the fp32 accumulators + two operand
# tiles comfortably inside v5e VMEM; overridable via env so a healthy
# TPU window can sweep candidates without code edits
# (tools/tpu_capture.py --child-flash honors these)
from ..base import get_env

_BLOCK_Q = get_env("MX_FLASH_BLOCK_Q", 256, int)
_BLOCK_K = get_env("MX_FLASH_BLOCK_K", 256, int)

# Mosaic requires the last two dims of every block to be (8k, 128k) or
# equal to the full array dims — a rank-2 (BH, T) residual with a
# squeezed-BH block violates that.  The LSE therefore rides with a small
# trailing lane dim (all lanes duplicate the value); 8 = one sublane's
# width, and 8 == the full array dim satisfies the lowering rule while
# costing 8x (not 128x) the compact residual's HBM.
_LSE_LANES = 8


def _on_tpu() -> bool:
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


def _interpret() -> bool:
    """Pallas kernels execute via Mosaic on TPU, interpret elsewhere —
    one code path, testable on CPU, real lowering on hardware."""
    return not _on_tpu()


# Lowering config (reference role: optimize_for(backend) /
# MXNET_SUBGRAPH_BACKEND): None = heuristic dispatch, "pallas" = force the
# flash kernel wherever alignment permits (any backend; CPU interprets),
# "xla" = force the jnp composition.  Two levels:
#   * process-wide default via set_attention_impl (MXNET_SUBGRAPH_BACKEND
#     role);
#   * a thread-local SCOPE (attention_impl_scope) that the subgraph
#     backend-property registry pushes around one block's trace, so
#     per-block optimize_for never leaks into other blocks.
_FORCED_IMPL = None
_IMPL_TLS = threading.local()


def set_attention_impl(impl):
    global _FORCED_IMPL
    if impl not in (None, "pallas", "xla"):
        raise ValueError("attention impl must be None, 'pallas' or 'xla'")
    prev = _FORCED_IMPL
    _FORCED_IMPL = impl
    return prev


def current_attention_impl():
    stack = getattr(_IMPL_TLS, "stack", None)
    if stack:
        return stack[-1]
    return _FORCED_IMPL


class attention_impl_scope:
    """Scoped override: the innermost scope wins over the global."""

    def __init__(self, impl):
        if impl not in (None, "pallas", "xla"):
            raise ValueError("attention impl must be None, 'pallas' or "
                             "'xla'")
        self._impl = impl

    def __enter__(self):
        if not hasattr(_IMPL_TLS, "stack"):
            _IMPL_TLS.stack = []
        _IMPL_TLS.stack.append(self._impl)
        return self

    def __exit__(self, *exc):
        _IMPL_TLS.stack.pop()
        return False


# ---------------------------------------------------------------------------
# jnp reference path (always-correct fallback; also the recompute backward)
# ---------------------------------------------------------------------------


def _attention_jnp(q, k, v, scale, causal):
    """q,k,v: (B, H, T, D)."""
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        Tq, Tk = q.shape[2], k.shape[2]
        mask = jnp.tril(jnp.ones((Tq, Tk), bool), Tk - Tq)
        logits = jnp.where(mask, logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


# ---------------------------------------------------------------------------
# Pallas flash kernel (forward)
# ---------------------------------------------------------------------------



def _sds(shape, dtype, like):
    """ShapeDtypeStruct carrying the input's varying-mesh-axes (vma) so
    pallas_call works INSIDE shard_map(check_vma=True) — ring attention
    runs these kernels per shard."""
    try:
        aval = jax.typeof(like)
        vma = getattr(aval, "vma", None)
        if vma:
            return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    except Exception:
        pass
    return jax.ShapeDtypeStruct(shape, dtype)

def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal,
                      block_k, seq_k):
    # refs: q (block_q, D), k/v (seq_k, D), o (block_q, D),
    # lse (block_q, _LSE_LANES) — lanes duplicate the value; grid=(BH, Tq/bq)
    import jax.experimental.pallas as pl

    block_q, d = q_ref.shape
    q = q_ref[:].astype(jnp.float32) * scale
    q_idx = pl.program_id(1)

    m = jnp.full((block_q, 1), -jnp.inf, jnp.float32)
    l = jnp.zeros((block_q, 1), jnp.float32)
    acc = jnp.zeros((block_q, d), jnp.float32)

    num_kb = seq_k // block_k

    def body(kb, carry):
        m, l, acc = carry
        k_blk = k_ref[pl.dslice(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.dslice(kb * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)
        if causal:
            q_pos = q_idx * block_q + \
                lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_pos = kb * block_k + \
                lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        # guard fully-masked rows: exp(-inf - -inf) would be nan
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s - m_safe)
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = alpha * acc + jnp.dot(p, v_blk,
                                        preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    if causal:
        # only key blocks at or before this query block contribute
        # (ceil-div: correct for any block_q/block_k ratio)
        num_kb_eff = ((q_idx + 1) * block_q + block_k - 1) // block_k
        num_kb_eff = jnp.minimum(num_kb_eff, num_kb)
        m, l, acc = lax.fori_loop(0, num_kb_eff, body, (m, l, acc))
    else:
        m, l, acc = lax.fori_loop(0, num_kb, body, (m, l, acc))

    o_ref[:] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    # logsumexp residual for the flash backward: lse = m + log(l)
    # (softmax prob recomputes as exp(s - lse)); -inf for fully-masked rows
    lse = jnp.where(l > 0,
                    jnp.where(jnp.isfinite(m), m, 0.0)
                    + jnp.log(jnp.maximum(l, 1e-30)),
                    -jnp.inf)
    lse_ref[:] = jnp.broadcast_to(lse, (block_q, _LSE_LANES))


def _flash_fwd_res(q, k, v, scale, causal, block_q=_BLOCK_Q,
                   block_k=_BLOCK_K):
    """q,k,v: (B, H, T, D) with T % block == 0.  Returns (out, lse_lanes)
    with lse_lanes (B*H, Tq, _LSE_LANES) fp32 — the laned residual the
    backward kernels consume directly (no rebroadcast on the bwd path)."""
    import jax.experimental.pallas as pl

    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    qr = q.reshape(B * H, Tq, D)
    kr = k.reshape(B * H, Tk, D)
    vr = v.reshape(B * H, Tk, D)
    kernel = functools.partial(_flash_fwd_kernel, scale=scale, causal=causal,
                               block_k=block_k, seq_k=Tk)
    out, lse_lanes = pl.pallas_call(
        kernel,
        interpret=_interpret(),
        grid=(B * H, Tq // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, Tk, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, Tk, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_q, _LSE_LANES), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            _sds((B * H, Tq, D), q.dtype, qr),
            _sds((B * H, Tq, _LSE_LANES), jnp.float32, qr),
        ],
    )(qr, kr, vr)
    return out.reshape(B, H, Tq, D), lse_lanes


def _lse_from_lanes(lse_lanes, B, H, Tq):
    """(B*H, Tq, _LSE_LANES) laned residual -> public (B, H, Tq)."""
    return lse_lanes[:, :, 0].reshape(B, H, Tq)


def _flash_fwd(q, k, v, scale, causal, block_q=_BLOCK_Q, block_k=_BLOCK_K):
    """Public-shape wrapper: returns (out, lse) with lse (B, H, Tq)."""
    B, H, Tq, _ = q.shape
    out, lse_lanes = _flash_fwd_res(q, k, v, scale, causal, block_q, block_k)
    return out, _lse_from_lanes(lse_lanes, B, H, Tq)


# ---------------------------------------------------------------------------
# Pallas flash backward (FlashAttention-2 recompute-from-LSE formulation):
# O(L) memory — the T×T score matrix is never materialized.  Two kernels:
# dq iterates q-blocks (streaming K/V), dk/dv iterates k-blocks (streaming
# Q/dO).  delta = rowsum(dO * O) is the softmax-jacobian correction term.
# ---------------------------------------------------------------------------


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref,
                         dq_ref, *, scale, causal, block_k, seq_k):
    import jax.experimental.pallas as pl

    block_q, d = q_ref.shape
    q = q_ref[:].astype(jnp.float32)
    do = do_ref[:].astype(jnp.float32)
    # lanes all duplicate the value; a lane-reduce recovers (block_q, 1)
    lse = jnp.max(lse_ref[:], axis=-1, keepdims=True)
    # softmax-jacobian row term, computed in-kernel (saves a (BH, T)
    # residual array + its laned rebroadcast)
    delta = jnp.sum(do * o_ref[:].astype(jnp.float32), axis=-1,
                    keepdims=True)
    q_idx = pl.program_id(1)
    lse_safe = jnp.where(jnp.isfinite(lse), lse, 0.0)

    num_kb = seq_k // block_k

    def body(kb, dq):
        k_blk = k_ref[pl.dslice(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.dslice(kb * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = q_idx * block_q + \
                lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_pos = kb * block_k + \
                lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, -jnp.inf)
        p = jnp.where(jnp.isfinite(s) & jnp.isfinite(lse),
                      jnp.exp(s - lse_safe), 0.0)
        dp = jnp.dot(do, v_blk.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        return dq + jnp.dot(ds, k_blk, preferred_element_type=jnp.float32)

    dq = jnp.zeros((block_q, d), jnp.float32)
    if causal:
        num_kb_eff = jnp.minimum(
            ((q_idx + 1) * block_q + block_k - 1) // block_k, num_kb)
        dq = lax.fori_loop(0, num_kb_eff, body, dq)
    else:
        dq = lax.fori_loop(0, num_kb, body, dq)
    dq_ref[:] = (dq * scale).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref,
                          dk_ref, dv_ref, *, scale, causal, block_q, seq_q):
    import jax.experimental.pallas as pl

    block_k, d = k_ref.shape
    k = k_ref[:].astype(jnp.float32)
    v = v_ref[:].astype(jnp.float32)
    k_idx = pl.program_id(1)

    num_qb = seq_q // block_q

    def body(qb, carry):
        dk, dv = carry
        q_blk = q_ref[pl.dslice(qb * block_q, block_q), :].astype(jnp.float32)
        do_blk = do_ref[pl.dslice(qb * block_q, block_q), :].astype(
            jnp.float32)
        lse = jnp.max(lse_ref[pl.dslice(qb * block_q, block_q), :],
                      axis=-1, keepdims=True)
        delta = jnp.sum(
            do_blk * o_ref[pl.dslice(qb * block_q, block_q), :].astype(
                jnp.float32), axis=-1, keepdims=True)
        lse_safe = jnp.where(jnp.isfinite(lse), lse, 0.0)
        s = jnp.dot(q_blk, k.T, preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = qb * block_q + \
                lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_pos = k_idx * block_k + \
                lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, -jnp.inf)
        p = jnp.where(jnp.isfinite(s) & jnp.isfinite(lse),
                      jnp.exp(s - lse_safe), 0.0)
        dv_new = dv + jnp.dot(p.T, do_blk,
                              preferred_element_type=jnp.float32)
        dp = jnp.dot(do_blk, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk_new = dk + jnp.dot(ds.T, q_blk,
                              preferred_element_type=jnp.float32)
        return dk_new, dv_new

    dk = jnp.zeros((block_k, d), jnp.float32)
    dv = jnp.zeros((block_k, d), jnp.float32)
    if causal:
        # only query blocks at or after this key block contribute
        qb_start = (k_idx * block_k) // block_q
        dk, dv = lax.fori_loop(qb_start, num_qb, body, (dk, dv))
    else:
        dk, dv = lax.fori_loop(0, num_qb, body, (dk, dv))
    dk_ref[:] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[:] = dv.astype(dv_ref.dtype)


def _flash_bwd(q, k, v, o, lse_lanes, g, scale, causal,
               block_q=_BLOCK_Q, block_k=_BLOCK_K):
    """lse_lanes: (B*H, Tq, _LSE_LANES) fp32 as produced by
    _flash_fwd_res; delta is recomputed in-kernel from o/do blocks."""
    import jax.experimental.pallas as pl

    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    qr = q.reshape(B * H, Tq, D)
    kr = k.reshape(B * H, Tk, D)
    vr = v.reshape(B * H, Tk, D)
    outr = o.reshape(B * H, Tq, D)
    gr = g.reshape(B * H, Tq, D)

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, scale=scale, causal=causal,
                          block_k=block_k, seq_k=Tk),
        interpret=_interpret(),
        grid=(B * H, Tq // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, Tk, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, Tk, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_q, _LSE_LANES), lambda b, i: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
        out_shape=_sds((B * H, Tq, D), q.dtype, qr),
    )(qr, kr, vr, outr, gr, lse_lanes)

    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, seq_q=Tq),
        interpret=_interpret(),
        grid=(B * H, Tk // block_k),
        in_specs=[
            pl.BlockSpec((None, Tq, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, block_k, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_k, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, Tq, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, Tq, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, Tq, _LSE_LANES), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_k, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_k, D), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            _sds((B * H, Tk, D), k.dtype, qr),
            _sds((B * H, Tk, D), v.dtype, qr),
        ],
    )(qr, kr, vr, outr, gr, lse_lanes)

    return (dq.reshape(B, H, Tq, D), dk.reshape(B, H, Tk, D),
            dv.reshape(B, H, Tk, D))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention_with_lse(q, k, v, scale, causal):
    """Blockwise flash attention returning (out, lse) — the ring-attention
    building block (partials merge via logsumexp).  Differentiable in BOTH
    outputs: the lse cotangent contributes
        dq += scale * g_lse ⊙ (P K)          (P K = this kernel with v:=k)
        dk += scale * Pᵀ (g_lse ⊙ q)          (the dkv kernel's dv pass)
    so the merge weights backpropagate without materializing P."""
    return _flash_fwd(q, k, v, scale, causal)


def _flash_lse_vjp_fwd(q, k, v, scale, causal):
    # symbolic_zeros=True wraps primals in CustomVJPPrimal
    q, k, v = (x.value if hasattr(x, "value") else x for x in (q, k, v))
    B, H, Tq, _ = q.shape
    out, lse_lanes = _flash_fwd_res(q, k, v, scale, causal)
    return (out, _lse_from_lanes(lse_lanes, B, H, Tq)), (q, k, v, out,
                                                         lse_lanes)


def _flash_lse_vjp_bwd(scale, causal, res, cts):
    from jax.custom_derivatives import SymbolicZero
    g_out, g_lse = cts
    q, k, v, o, lse_lanes = res
    B, H, Tq, _ = q.shape
    if isinstance(g_out, SymbolicZero):
        # out unused downstream: no kernel passes needed for its term
        dq = jnp.zeros(q.shape, q.dtype)
        dk = jnp.zeros(k.shape, k.dtype)
        dv = jnp.zeros(v.shape, v.dtype)
    else:
        dq, dk, dv = _flash_bwd(q, k, v, o, lse_lanes, g_out, scale,
                                causal)
    if not isinstance(g_lse, SymbolicZero):
        # the lse term costs one extra fwd + one bwd kernel pass — the
        # symbolic-zero gate skips it when only `out` was used downstream
        lse = _lse_from_lanes(lse_lanes, B, H, Tq)
        gl = jnp.where(jnp.isfinite(lse), g_lse, 0.0)[..., None]
        pk = _flash_fwd(q, k, k.astype(q.dtype), scale, causal)[0]
        dq = (dq.astype(jnp.float32)
              + scale * gl * pk.astype(jnp.float32)).astype(dq.dtype)
        g2 = (gl * q.astype(jnp.float32)).astype(q.dtype)
        _, _, dk2 = _flash_bwd(q, k, jnp.zeros_like(v), jnp.zeros_like(o),
                               lse_lanes, g2, scale, causal)
        dk = (dk.astype(jnp.float32)
              + scale * dk2.astype(jnp.float32)).astype(dk.dtype)
    return dq, dk, dv


flash_attention_with_lse.defvjp(_flash_lse_vjp_fwd, _flash_lse_vjp_bwd,
                                symbolic_zeros=True)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention(q, k, v, scale, causal):
    """Blockwise flash attention, (B, H, T, D) layout."""
    return _flash_fwd(q, k, v, scale, causal)[0]


def _flash_vjp_fwd(q, k, v, scale, causal):
    out, lse_lanes = _flash_fwd_res(q, k, v, scale, causal)
    return out, (q, k, v, out, lse_lanes)


def _flash_vjp_bwd(scale, causal, res, g):
    # blockwise Pallas backward: O(L) memory (recompute-from-LSE), never
    # building the T×T score matrix the old jnp rematerialization needed
    q, k, v, o, lse_lanes = res
    return _flash_bwd(q, k, v, o, lse_lanes, g, scale, causal)


flash_attention.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def attention_core(q, k, v, scale=None, causal=False, mask=None):
    """Dispatch: Pallas flash on TPU for aligned mask-free shapes, jnp
    composition otherwise.  q,k,v: (B, H, T, D)."""
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    Tq, Tk, D = q.shape[2], k.shape[2], q.shape[3]
    aligned = (mask is None and Tq % _BLOCK_Q == 0 and Tk % _BLOCK_K == 0
               and D % 128 == 0 and (not causal or Tq == Tk))
    impl = current_attention_impl()
    if impl == "xla":
        use_flash = False
    elif impl == "pallas":
        use_flash = aligned          # CPU interprets; TPU lowers via Mosaic
    else:
        use_flash = _on_tpu() and aligned
    if use_flash:
        return flash_attention(q, k, v, float(scale), bool(causal))
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        cm = jnp.tril(jnp.ones((Tq, Tk), bool), Tk - Tq)
        logits = jnp.where(cm, logits, -jnp.inf)
    if mask is not None:
        logits = jnp.where(mask.astype(bool), logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


# ---------------------------------------------------------------------------
# Cached (decode-time) attention: one query token per sequence attending
# over a fixed-capacity KV page buffer under a valid-length mask — the
# autoregressive serving hot path (mxnet_tpu/serve/decode.py).  The page
# buffer is the full pre-allocated slot extent, so the program shape
# never depends on how far a generation has progressed: zero retraces
# across a sequence's whole lifetime, and the pool arrays can be donated
# through every decode step (HBM stays flat).
# ---------------------------------------------------------------------------


def cached_attention(q, k_pages, v_pages, cur_len, scale=None):
    """Single-position attention over per-sequence KV cache pages.

    ``q``: (B, H, D) — the current token's query per sequence;
    ``k_pages``/``v_pages``: (B, P, H, D) — each sequence's KV page
    buffer at its FULL capacity P (positions >= ``cur_len`` hold stale
    or zero entries); ``cur_len``: (B,) int — how many leading positions
    are valid (includes the current token's just-written entry).
    Returns (B, H, D).

    Masked positions get a finite -1e30 (never -inf): ``cur_len`` >= 1
    by contract, so every row has at least one live key and the softmax
    stays NaN-free even for scratch/padded lanes.
    """
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    P = k_pages.shape[1]
    logits = jnp.einsum("bhd,bphd->bhp", q, k_pages,
                        preferred_element_type=jnp.float32) * scale
    valid = jnp.arange(P)[None, None, :] < cur_len[:, None, None]
    logits = jnp.where(valid, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhp,bphd->bhd", probs, v_pages)


def cached_attention_multi(q, k_pages, v_pages, pos, scale=None):
    """Multi-position attention over per-sequence KV cache pages.

    The speculative-verify generalization of :func:`cached_attention`:
    T query rows per sequence, each attending over the prefix ending at
    its OWN absolute position — the causal mask a chunk of in-flight
    draft tokens needs when the target model scores all of them in one
    dispatch.

    ``q``: (B, T, H, D) — T query tokens per sequence; ``k_pages``/
    ``v_pages``: (B, P, H, D) full-capacity page buffers (rows >= a
    query's position hold stale entries); ``pos``: (B, T) int — each
    query row's absolute position (its own KV entry is already written,
    so row t attends keys [0, pos[b, t]]).  Returns (B, T, H, D).
    Masking keeps the finite -1e30 discipline of the single-position
    path so scratch/padded lanes stay NaN-free.
    """
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    P = k_pages.shape[1]
    logits = jnp.einsum("bthd,bphd->bthp", q, k_pages,
                        preferred_element_type=jnp.float32) * scale
    valid = jnp.arange(P)[None, None, :] <= pos[:, :, None]
    logits = jnp.where(valid[:, :, None, :], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bthp,bphd->bthd", probs, v_pages)


def paged_attention_multi(q, k_heap, v_heap, block_tables, pos,
                          scale=None):
    """Multi-position attention over a PAGED KV heap — the speculative
    verify dispatch's core (ISSUE 20).

    Gathers each lane's physical pages into the (B, extent, H, D) view
    :func:`cached_attention_multi` expects and delegates, exactly as
    :func:`paged_attention` does for the single-position step, so the
    verify program shares the flat path's masking/softmax semantics and
    greedy accept/reject stays bit-exact against plain decode.

    ``q``: (B, T, H, D); ``k_heap``/``v_heap``: (n_pages, page_len, H,
    D) one layer's heap slice; ``block_tables``: (B, pages_per_slot)
    int32; ``pos``: (B, T) absolute positions.  Returns (B, T, H, D).
    """
    B = q.shape[0]
    page_len = k_heap.shape[1]
    extent = block_tables.shape[1] * page_len
    k = k_heap[block_tables].reshape((B, extent) + k_heap.shape[2:])
    v = v_heap[block_tables].reshape((B, extent) + v_heap.shape[2:])
    return cached_attention_multi(q, k, v, pos, scale=scale)


def paged_attention(q, k_heap, v_heap, block_tables, cur_len,
                    scale=None):
    """Single-position attention over a PAGED KV heap (ISSUE 18).

    The paged decode engine keeps one shared page heap instead of
    per-slot extents; each sequence's logical key positions map to
    physical pages through its block table.  This gathers every lane's
    pages into the (B, extent, H, D) view :func:`cached_attention`
    expects and delegates — the masking/softmax discipline (finite
    -1e30, ``cur_len`` >= 1) is identical, so flat-vs-paged greedy
    decode parity holds at the token level.

    ``q``: (B, H, D); ``k_heap``/``v_heap``: (n_pages, page_len, H, D)
    — ONE layer's slice of the shared heap; ``block_tables``:
    (B, pages_per_slot) int32 physical page ids (scratch lanes carry
    all-zero rows: page 0 is reserved, masked by ``cur_len``);
    ``cur_len``: (B,) int valid leading positions.  Returns (B, H, D).
    """
    B = q.shape[0]
    page_len = k_heap.shape[1]
    extent = block_tables.shape[1] * page_len
    k = k_heap[block_tables].reshape((B, extent) + k_heap.shape[2:])
    v = v_heap[block_tables].reshape((B, extent) + v_heap.shape[2:])
    return cached_attention(q, k, v, cur_len, scale=scale)


# ---------------------------------------------------------------------------
# MXNet transformer parity ops (interleaved QKV layout, reference:
# src/operator/contrib/transformer.cc).  Input: (T, N, H*3*D) where the
# projection interleaves [q1..qD, k1..kD, v1..vD] per head.
# ---------------------------------------------------------------------------


def _split_interleaved_qkv(qkv, heads):
    T, N, HC = qkv.shape
    D = HC // (heads * 3)
    x = qkv.reshape(T, N, heads, 3, D)
    # -> (N, heads, T, D)
    q = x[:, :, :, 0].transpose(1, 2, 0, 3)
    k = x[:, :, :, 1].transpose(1, 2, 0, 3)
    v = x[:, :, :, 2].transpose(1, 2, 0, 3)
    return q, k, v


@register("_contrib_interleaved_matmul_selfatt_qk")
def _selfatt_qk(queries_keys_values, heads=1):
    """scores = scaled q @ k^T → (N*heads, T, T)."""
    q, k, _ = _split_interleaved_qkv(queries_keys_values, heads)
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(q.dtype)
    s = jnp.einsum("nhqd,nhkd->nhqk", q * scale, k,
                   preferred_element_type=jnp.float32).astype(q.dtype)
    N, H, T, _ = s.shape
    return s.reshape(N * H, T, T)


@register("_contrib_interleaved_matmul_selfatt_valatt")
def _selfatt_valatt(queries_keys_values, attention, heads=1):
    """out = att @ v → (T, N, H*D)."""
    _, _, v = _split_interleaved_qkv(queries_keys_values, heads)
    N, H, T, D = v.shape
    att = attention.reshape(N, H, T, T)
    out = jnp.einsum("nhqk,nhkd->nhqd", att, v)
    return out.transpose(2, 0, 1, 3).reshape(T, N, H * D)


@register("_contrib_interleaved_matmul_encdec_qk")
def _encdec_qk(queries, keys_values, heads=1):
    Tq, N, HC = queries.shape
    D = HC // heads
    q = queries.reshape(Tq, N, heads, D).transpose(1, 2, 0, 3)
    Tk = keys_values.shape[0]
    kv = keys_values.reshape(Tk, N, heads, 2, D)
    k = kv[:, :, :, 0].transpose(1, 2, 0, 3)
    scale = 1.0 / jnp.sqrt(jnp.asarray(D, q.dtype))
    s = jnp.einsum("nhqd,nhkd->nhqk", q * scale, k,
                   preferred_element_type=jnp.float32).astype(q.dtype)
    return s.reshape(N * heads, Tq, Tk)


@register("_contrib_interleaved_matmul_encdec_valatt")
def _encdec_valatt(keys_values, attention, heads=1):
    Tk, N, HC = keys_values.shape
    D = HC // (heads * 2)
    kv = keys_values.reshape(Tk, N, heads, 2, D)
    v = kv[:, :, :, 1].transpose(1, 2, 0, 3)
    Tq = attention.shape[1]
    att = attention.reshape(N, heads, Tq, Tk)
    out = jnp.einsum("nhqk,nhkd->nhqd", att, v)
    return out.transpose(2, 0, 1, 3).reshape(Tq, N, heads * D)


# ---------------------------------------------------------------------------
# sliding-window attention (reference: src/operator/contrib/
# sldwin_atten-inl.h — GluonNLP's Longformer ops).  Banded layout:
# score[b, i, h, j] pairs query i with key i + (j - w)*dilation, j in
# [0, 2w] (symmetric) or [0, w] (causal-left only); out-of-range or
# beyond-valid-length entries are masked.  O(L*w) memory, gather-based —
# the band never materializes the full L×L matrix.
# ---------------------------------------------------------------------------


def _sldwin_offsets(w, symmetric):
    lo = -w
    hi = w if symmetric else 0
    return jnp.arange(lo, hi + 1)


def _sldwin_kidx(L, w, dilation, symmetric):
    offs = _sldwin_offsets(w, symmetric) * dilation       # (J,)
    idx = jnp.arange(L)[:, None] + offs[None, :]          # (L, J)
    valid = (idx >= 0) & (idx < L)
    return jnp.clip(idx, 0, L - 1), valid


@register("_contrib_sldwin_atten_score", aliases=["sldwin_atten_score"],
          no_jit=True)  # per-head dilation tensor must be concrete
def _sldwin_atten_score(query, key, dilation, w=1, symmetric=True):
    """query/key: (B, L, H, D); dilation: (H,) ints → (B, L, H, J)."""
    B, L, H, D = query.shape
    outs = []
    dil = jnp.asarray(dilation).reshape(-1)
    for h in range(H):
        d = int(dil[h]) if dil.shape[0] > 1 else int(dil[0])
        idx, valid = _sldwin_kidx(L, int(w), d, bool(symmetric))
        kg = key[:, :, h, :][:, idx, :]                   # (B, L, J, D)
        s = jnp.einsum("bld,bljd->blj", query[:, :, h, :], kg)
        outs.append(jnp.where(valid[None], s, 0.0))
    return jnp.stack(outs, axis=2)                        # (B, L, H, J)


@register("_contrib_sldwin_atten_mask_like",
          aliases=["sldwin_atten_mask_like"], differentiable=False,
          no_jit=True)
def _sldwin_atten_mask_like(score, dilation, valid_length, w=1,
                            symmetric=True):
    """1.0 where the band entry addresses a real, in-valid-length key."""
    B, L, H, J = score.shape
    dil = jnp.asarray(dilation).reshape(-1)
    vl = jnp.asarray(valid_length).reshape(B, 1, 1)
    masks = []
    for h in range(H):
        d = int(dil[h]) if dil.shape[0] > 1 else int(dil[0])
        idx, valid = _sldwin_kidx(L, int(w), d, bool(symmetric))
        in_len = (idx[None] < vl) & (jnp.arange(L)[None, :, None] < vl)
        masks.append(valid[None] & in_len)
    return jnp.stack(masks, axis=2).astype(score.dtype)


@register("_contrib_sldwin_atten_context",
          aliases=["sldwin_atten_context"], no_jit=True)
def _sldwin_atten_context(score, value, dilation, w=1, symmetric=True):
    """score: (B, L, H, J); value: (B, L, H, D) → (B, L, H, D)."""
    B, L, H, J = score.shape
    dil = jnp.asarray(dilation).reshape(-1)
    outs = []
    for h in range(H):
        d = int(dil[h]) if dil.shape[0] > 1 else int(dil[0])
        idx, valid = _sldwin_kidx(L, int(w), d, bool(symmetric))
        vg = value[:, :, h, :][:, idx, :]                 # (B, L, J, D)
        s = jnp.where(valid[None], score[:, :, h, :], 0.0)
        outs.append(jnp.einsum("blj,bljd->bld", s, vg))
    return jnp.stack(outs, axis=2)
