"""Subgraph backend-property registry — named lowering configs.

Reference: ``src/operator/subgraph/subgraph_property.h`` (SubgraphProperty,
SubgraphPropertyRegistry, MXNET_SUBGRAPH_BACKEND) and
``build_subgraph.cc`` — the mechanism behind ``HybridBlock.optimize_for
(backend)``: a registry of named backend properties, each of which selects
and rewrites parts of the graph for its target.

TPU-native realization: XLA already does the partition/fuse work, so a
property here is a *scoped bundle of lowering overrides* applied around
one block's trace — which kernel an op lowers to (Pallas flash vs XLA
composition for attention), what dtype policy applies (AMP bf16 lists),
etc.  Properties are PER BLOCK: ``net.optimize_for(x, backend='pallas')``
stamps the property on that block, the cached-op plumbing enters the
property's scope for that block's traces/executions only, and the cache
key carries the backend name so different lowerings never share an
executable.  The reference's process-wide ``MXNET_SUBGRAPH_BACKEND``
escape hatch maps to the process-wide defaults (e.g.
``ops.attention.set_attention_impl``).

Adding a backend::

    @register_backend("my_lowering")
    class MyProperty(SubgraphProperty):
        def scope(self):
            return some_context_manager()
"""
from __future__ import annotations

import contextlib
from typing import Dict

__all__ = ["SubgraphProperty", "register_backend", "get_backend",
           "list_backends"]

_REGISTRY: Dict[str, "SubgraphProperty"] = {}


class SubgraphProperty:
    """A named lowering config (reference: class SubgraphProperty).

    Subclasses override :meth:`scope` to return a context manager that
    installs this property's overrides for the duration of one block
    trace/execution."""

    name: str = ""

    def scope(self):
        return contextlib.nullcontext()

    def cache_token(self):
        """Hashable identity mixed into the block's cached-op key — two
        properties whose lowering differs must not share executables."""
        return self.name


def register_backend(name: str):
    """Decorator: register a SubgraphProperty class or instance under
    `name` (reference: MXNET_REGISTER_SUBGRAPH_PROPERTY)."""

    def _do(obj):
        prop = obj() if isinstance(obj, type) else obj
        prop.name = name
        _REGISTRY[name] = prop
        return obj

    return _do


def get_backend(name: str) -> SubgraphProperty:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(
            "unknown subgraph backend %r (registered: %s)"
            % (name, ", ".join(sorted(_REGISTRY)) or "<none>")) from None


def list_backends():
    return sorted(_REGISTRY)


# ---------------------------------------------------------------------------
# built-in properties
# ---------------------------------------------------------------------------


@register_backend("pallas")
class _PallasAttention(SubgraphProperty):
    """Force the Pallas flash-attention kernel wherever block alignment
    permits (the reference's force-a-partitioned-subgraph role)."""

    def scope(self):
        from .ops.attention import attention_impl_scope
        return attention_impl_scope("pallas")


@register_backend("xla")
class _XlaAttention(SubgraphProperty):
    """Force the plain jnp/XLA attention composition."""

    def scope(self):
        from .ops.attention import attention_impl_scope
        return attention_impl_scope("xla")


@register_backend("amp_bf16")
class _AmpBf16(SubgraphProperty):
    """Apply the AMP bfloat16 policy lists (amp/lists.py) to every op
    dispatched inside this block — per-block mixed precision without the
    process-wide amp.init()."""

    def scope(self):
        return _amp_scope("bfloat16")


@register_backend("amp_float16")
class _AmpFp16(SubgraphProperty):
    def scope(self):
        return _amp_scope("float16")


def _amp_scope(dtype):
    # thread-local override: the REQUESTED policy always applies inside the
    # block (even when a different process-wide amp.init is active), and
    # concurrent threads never observe it
    from . import amp as _amp
    return _amp.state_scope(_amp.make_state(target_dtype=dtype))
