"""mx.monitor — executor introspection during training.

Reference: ``python/mxnet/monitor.py`` (class Monitor — installs output
hooks on executors, stat_func over arrays every `interval` batches).

The reference intercepts every op's outputs via MXExecutorSetMonitorCallback;
this rebuild's executor evaluates whole jitted programs, so the observable
surface is the bound arrays: arguments, gradients, aux states, and outputs
— which is what Monitor consumers (debugging exploding grads, dead units)
actually read.  ``monitor_all`` is accepted for parity and widens nothing
further.
"""
from __future__ import annotations

import logging
import re
from typing import Callable, List, Optional, Tuple

from . import ndarray as nd

__all__ = ["Monitor"]


class Monitor:
    def __init__(self, interval: int, stat_func: Optional[Callable] = None,
                 pattern: str = ".*", sort: bool = False,
                 monitor_all: bool = False):
        if stat_func is None:
            def stat_func(x):
                return nd.invoke("norm", x) / (x.size ** 0.5)
        self.stat_func = stat_func
        self.interval = interval
        self.activated = False
        self.queue: List[Tuple[int, str, object]] = []
        self.step = 0
        self.exes = []
        self.re_prog = re.compile(pattern)
        self.sort = sort

    def install(self, exe) -> None:
        # per-node taps need per-node execution — disable the executor's
        # whole-graph-jit inference fast path
        exe._pure_ok = False
        """Attach to an executor (reference: Monitor.install_to_executor)."""
        self.exes.append(exe)

    def tic(self):
        """Start collecting for this batch if due (reference: Monitor.tic)."""
        if self.step % self.interval == 0:
            self.queue = []
            self.activated = True
        self.step += 1

    def toc(self) -> List[Tuple[int, str, str]]:
        """Collect stats from installed executors (reference: Monitor.toc).

        The stat_func values stay on device while they are gathered; the
        whole batch of scalars is then stacked device-side and pulled in
        ONE transfer (the ISSUE-3 metric design), instead of one blocking
        ``asnumpy`` per tensor per callback."""
        if not self.activated:
            return []
        self.activated = False
        for exe in self.exes:
            groups = [("%s" % n, a) for n, a in exe.arg_dict.items()]
            groups += [("%s_grad" % n, a) for n, a in exe.grad_dict.items()
                       if a is not None]
            groups += [("%s" % n, a) for n, a in exe.aux_dict.items()]
            groups += [("output%d" % i, o)
                       for i, o in enumerate(exe.outputs)]
            for name, arr in groups:
                if arr is None or not self.re_prog.match(name):
                    continue
                self.queue.append((self.step, name, self.stat_func(arr)))
        # flatten to per-value slots, device values separated from host
        flat: List[Tuple[int, str, List[object]]] = []
        device_vals = []
        for n, k, v_list in self.queue:
            if not isinstance(v_list, (list, tuple)):
                v_list = [v_list]
            flat.append((n, k, list(v_list)))
            device_vals.extend(v for v in v_list if hasattr(v, "asnumpy"))
        drained = {}
        if device_vals:
            stacked = nd.concat([v.reshape(-1)[0:1] for v in device_vals],
                                dim=0)
            # the single per-toc drain point (everything above is async)
            host = stacked.asnumpy()  # mxlint: disable=host-sync-in-hot-path
            drained = {id(v): host[i] for i, v in enumerate(device_vals)}
        res = []
        for n, k, v_list in flat:
            s = ",".join("%f" % float(drained.get(id(v), v))
                         for v in v_list)
            res.append((n, k, s))
        if self.sort:
            res = sorted(res, key=lambda x: x[1])
        self.queue = []
        return res

    def toc_print(self):
        """Collect and log (reference: Monitor.toc_print)."""
        for n, k, v in self.toc():
            logging.info("Batch: %7d %30s %s", n, k, v)
