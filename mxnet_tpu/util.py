"""mx.util (reference: python/mxnet/util.py): env helpers + numpy-mode
decorators.

One-array-type design note: mx.np.ndarray IS mx.nd.NDArray here, so the
np-mode switches are compatibility recorders (npx.set_np flags), and the
use_np* decorators are transparent wrappers — code written against the
reference API runs unchanged.
"""
from __future__ import annotations

import functools
import os

from .base import get_env, set_env

__all__ = ["getenv", "setenv", "makedirs", "is_np_array", "is_np_shape",
           "np_array", "np_shape", "use_np", "use_np_array",
           "use_np_shape", "get_gpu_count", "get_gpu_memory"]


def getenv(name):
    """Reference: mx.util.getenv."""
    return get_env(name)


def setenv(name, value):
    """Reference: mx.util.setenv."""
    set_env(name, value)


def makedirs(d):
    """Reference: mx.util.makedirs (exist_ok semantics)."""
    os.makedirs(os.path.expanduser(d), exist_ok=True)


def is_np_array() -> bool:
    from . import npx
    return npx.is_np_array()


def is_np_shape() -> bool:
    from . import npx
    return npx.is_np_shape()


class _NpScope:
    """Context manager/decorator setting the npx numpy-mode flags; None
    leaves a flag untouched, and __exit__ restores BOTH flags exactly
    (compat: the flags gate nothing — one array type)."""

    def __init__(self, array=None, shape=None):
        self._array = array
        self._shape = shape

    def __enter__(self):
        from . import npx
        self._saved = (npx.is_np_array(), npx.is_np_shape())
        npx.set_np(
            array=self._saved[0] if self._array is None else self._array,
            shape=self._saved[1] if self._shape is None else self._shape)
        return self

    def __exit__(self, *exc):
        from . import npx
        npx.set_np(array=self._saved[0], shape=self._saved[1])
        return False

    def __call__(self, fn_or_cls):
        if isinstance(fn_or_cls, type):
            return fn_or_cls          # classes pass through (compat)

        @functools.wraps(fn_or_cls)
        def wrapped(*a, **kw):
            with _NpScope(self._array, self._shape):
                return fn_or_cls(*a, **kw)
        return wrapped


def np_array(active=True):
    return _NpScope(array=active)


def np_shape(active=True):
    return _NpScope(shape=active)


def use_np_array(fn):
    return _NpScope(array=True)(fn)


def use_np_shape(fn):
    return _NpScope(shape=True)(fn)


def use_np(fn):
    """Reference: @use_np — activate both numpy semantics."""
    return _NpScope(array=True, shape=True)(fn)


def get_gpu_count() -> int:
    from .device import num_gpus
    return num_gpus()


def get_gpu_memory(dev_id: int = 0):
    from .device import gpu_memory_info
    return gpu_memory_info(dev_id)

def set_np(shape=True, array=True, dtype=False):
    """Reference: util.set_np — npx.set_np's canonical home."""
    from . import npx
    return npx.set_np(shape=shape, array=array, dtype=dtype)


def reset_np():
    from . import npx
    return npx.reset_np()
