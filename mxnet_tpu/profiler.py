"""mx.profiler — profiling API rebuilt over ``jax.profiler``.

Reference parity: ``python/mxnet/profiler.py`` (set_config, set_state,
start/stop/pause/resume, dump, dumps, Task/Frame/Event/Counter/Marker) and
``src/profiler/profiler.cc`` (Profiler::DumpProfile, the aggregate stats
table).

TPU-first design: the reference's engine hooks every op execution and writes
a chrome-trace JSON; here the *device-side* story belongs to XLA — we
delegate hardware tracing to ``jax.profiler.start_trace`` (xplane, viewable
in TensorBoard/Perfetto/XProf) — while the *host-side* per-op statistics the
MXNet API promises (the ``dumps()`` table, the ``dump()`` chrome trace) are
collected in the eager dispatch layer (``ndarray.invoke`` wraps each op in a
span when the profiler is running) and by the user-facing instrumentation
objects below.

Eager dispatch is asynchronous (XLA computations are enqueued, not awaited),
so a span measures *dispatch* latency by default — matching what the host
thread actually does.  Set ``MXNET_PROFILER_SYNC=1`` (or
``set_config(sync=True)``) to block on each op's outputs inside its span,
trading throughput for true per-op execution times, the moral equivalent of
the reference's ``NaiveEngine`` profiling mode.
"""
from __future__ import annotations

import json
import threading
import time
from collections import defaultdict
from typing import Dict, List, Optional

from .base import get_env

__all__ = [
    "set_config", "set_state", "state", "start", "stop", "pause", "resume",
    "dump", "dumps", "dump_profile", "Domain", "Task", "Frame", "Event",
    "Counter", "Marker", "scope", "annotate",
]

# module-level fast flags read by the dispatch hot loop -----------------------
RUNNING = False          # profiler collecting?
IMPERATIVE = False       # collect eager op spans?

_lock = threading.RLock()
_config = {
    "filename": "profile.json",
    "profile_all": False,
    "profile_symbolic": False,
    "profile_imperative": False,
    "profile_memory": False,
    "profile_api": False,
    "aggregate_stats": True,
    "continuous_dump": False,
    "sync": get_env("MXNET_PROFILER_SYNC", dtype=bool),
    # directory for jax.profiler xplane traces; None disables device tracing
    "device_trace_dir": None,
}
_jax_trace_active = False
_paused = False

# chrome-trace events: (name, category, ts_us, dur_us, tid)
_events: List[tuple] = []
# aggregate: name -> [count, total_us, min_us, max_us]
_agg: Dict[str, List[float]] = defaultdict(lambda: [0, 0.0, float("inf"), 0.0])
_counters: List[tuple] = []   # (name, ts_us, value)
_t0 = time.perf_counter()


def _now_us() -> float:
    return (time.perf_counter() - _t0) * 1e6


def set_config(**kwargs):
    """Configure the profiler (reference: profiler.set_config).

    Accepts the reference's kwargs (``filename``, ``profile_all``,
    ``profile_symbolic``, ``profile_imperative``, ``profile_memory``,
    ``profile_api``, ``aggregate_stats``, ``continuous_dump``) plus the
    rebuild's ``sync`` (block per op for exact times) and
    ``device_trace_dir`` (enable jax.profiler xplane capture there).
    """
    with _lock:
        for k, v in kwargs.items():
            if k not in _config:
                raise ValueError("profiler.set_config: unknown option %r" % k)
            _config[k] = v


def set_state(state_: str = "stop"):
    """'run' starts collection, 'stop' ends it (reference: set_state)."""
    global RUNNING, IMPERATIVE, _jax_trace_active, _paused
    if state_ not in ("run", "stop"):
        raise ValueError("profiler state must be 'run' or 'stop'")
    with _lock:
        run = state_ == "run"
        RUNNING = run
        _paused = False
        IMPERATIVE = run and (_config["profile_all"] or _config["profile_imperative"])
        tdir = _config["device_trace_dir"]
        if run and tdir and not _jax_trace_active:
            import jax
            jax.profiler.start_trace(tdir)
            _jax_trace_active = True
        elif not run and _jax_trace_active:
            import jax
            try:
                jax.profiler.stop_trace()
            finally:
                _jax_trace_active = False
        if not run and _config["continuous_dump"]:
            dump()


def state() -> str:
    return "run" if RUNNING else "stop"


def start():
    set_state("run")


def stop():
    set_state("stop")


def pause():
    """Temporarily suspend collection without closing the trace."""
    global IMPERATIVE, _paused
    with _lock:
        _paused = True
        IMPERATIVE = False


def resume():
    global IMPERATIVE, _paused
    with _lock:
        _paused = False
        IMPERATIVE = RUNNING and (_config["profile_all"] or _config["profile_imperative"])


def record_span(name: str, category: str, ts_us: float, dur_us: float):
    """Append one completed span (called from dispatch and Task/Frame/Event)."""
    with _lock:
        _events.append((name, category, ts_us, dur_us, threading.get_ident()))
        if _config["aggregate_stats"]:
            a = _agg[name]
            a[0] += 1
            a[1] += dur_us
            a[2] = min(a[2], dur_us)
            a[3] = max(a[3], dur_us)


class _OpSpan:
    """Context manager wrapped around one eager op dispatch.

    Also annotates the host timeline for jax.profiler so op names show up
    in the xplane trace (jax.profiler.TraceAnnotation).
    """
    __slots__ = ("name", "t0", "ann")

    def __init__(self, name: str):
        self.name = name
        self.ann = None

    def __enter__(self):
        if _jax_trace_active:
            import jax
            self.ann = jax.profiler.TraceAnnotation(self.name)
            self.ann.__enter__()
        self.t0 = _now_us()
        return self

    def __exit__(self, *exc):
        record_span(self.name, "operator", self.t0, _now_us() - self.t0)
        if self.ann is not None:
            self.ann.__exit__(*exc)
        return False


def op_span(name: str) -> _OpSpan:
    return _OpSpan(name)


def want_sync() -> bool:
    return _config["sync"]


# -- user instrumentation objects (reference: profiler.Task/Frame/Event...) ---

class Domain:
    """A named grouping for instrumentation objects."""

    def __init__(self, name: str):
        self.name = name

    def __repr__(self):
        return "Domain(%s)" % self.name


class _DurationObject:
    _category = "task"

    def __init__(self, domain: Optional[Domain] = None, name: str = "task"):
        if isinstance(domain, str) and name == "task":  # Event(name) form
            domain, name = None, domain
        self.domain = domain
        self.name = name
        self._t0 = None

    def start(self):
        self._t0 = _now_us()

    def stop(self):
        if self._t0 is None:
            raise RuntimeError("%s %r stopped before start" %
                               (type(self).__name__, self.name))
        record_span(self.name, self._category, self._t0, _now_us() - self._t0)
        self._t0 = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


class Task(_DurationObject):
    _category = "task"


class Frame(_DurationObject):
    _category = "frame"


class Event(_DurationObject):
    _category = "event"


class Counter:
    """A named monotonic-timestamped counter (reference: profiler.Counter)."""

    def __init__(self, domain: Optional[Domain] = None, name: str = "counter",
                 value: int = 0):
        if isinstance(domain, str) and name == "counter":
            domain, name = None, domain
        self.domain = domain
        self.name = name
        self._value = value
        self._record()

    def _record(self):
        with _lock:
            _counters.append((self.name, _now_us(), self._value))

    def set_value(self, value):
        self._value = value
        self._record()

    def increment(self, delta=1):
        self._value += delta
        self._record()

    def decrement(self, delta=1):
        self._value -= delta
        self._record()

    def __iadd__(self, delta):
        self.increment(delta)
        return self

    def __isub__(self, delta):
        self.decrement(delta)
        return self


class Marker:
    """An instant event (reference: profiler.Marker.mark)."""

    def __init__(self, domain: Optional[Domain] = None, name: str = "marker"):
        if isinstance(domain, str) and name == "marker":
            domain, name = None, domain
        self.domain = domain
        self.name = name

    def mark(self, scope_: str = "process"):
        record_span(self.name, "marker", _now_us(), 0.0)


class scope:
    """Context manager: annotate everything inside with a name prefix.

    Inside jit traces this is ``jax.named_scope`` (names land in the XLA HLO
    and the device profile); eagerly it opens a span.
    """

    def __init__(self, name: str):
        self.name = name
        self._span = _OpSpan(name)
        self._named = None

    def __enter__(self):
        import jax
        self._named = jax.named_scope(self.name)
        self._named.__enter__()
        self._span.__enter__()
        return self

    def __exit__(self, *exc):
        self._span.__exit__(*exc)
        return self._named.__exit__(*exc)


class _NullSpan:
    """Free when the profiler is stopped (annotate's fast path)."""
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


def annotate(name: str):
    """Phase range for the steady-state training step (allreduce / update /
    metric): a full scope() — host span + jax.named_scope so the fused
    blocks show as single ranges in a device trace — when the profiler is
    running, and a shared no-op context otherwise, so the fit hot loop
    pays one global read per phase."""
    return scope(name) if RUNNING else _NULL_SPAN


# -- output -------------------------------------------------------------------

def dump(finished: bool = True, profile_process: str = "worker"):
    """Write collected spans as a chrome-trace JSON to ``filename``.

    Reference: Profiler::DumpProfile writes the same ``traceEvents`` format;
    the file opens in chrome://tracing / Perfetto.  Device-side xplane traces
    (if ``device_trace_dir`` was set) are written by jax.profiler at stop().
    """
    with _lock:
        events = []
        for name, cat, ts, dur, tid in _events:
            events.append({"name": name, "cat": cat, "ph": "X",
                           "ts": ts, "dur": dur, "pid": 0, "tid": tid})
        for name, ts, value in _counters:
            events.append({"name": name, "cat": "counter", "ph": "C",
                           "ts": ts, "pid": 0,
                           "args": {"value": value}})
        payload = {"traceEvents": events, "displayTimeUnit": "ms"}
        with open(_config["filename"], "w") as f:
            json.dump(payload, f)
        if finished:
            _events.clear()
            _counters.clear()


dump_profile = dump  # deprecated reference alias


def dumps(reset: bool = False, format: str = "table") -> str:
    """Aggregate per-op statistics (reference: MXAggregateProfileStatsPrint).

    ``format='table'`` renders the reference-style text table;
    ``format='json'`` returns a JSON object keyed by op name.
    """
    with _lock:
        if format == "json":
            out = json.dumps({
                name: {"count": int(c), "total_us": t, "min_us": mn,
                       "max_us": mx, "avg_us": t / c if c else 0.0}
                for name, (c, t, mn, mx) in sorted(_agg.items())
            })
        else:
            lines = ["Profile Statistics:",
                     "%-40s %-12s %-14s %-12s %-12s %-12s" %
                     ("Name", "Total Count", "Time (us)", "Min (us)",
                      "Max (us)", "Avg (us)")]
            for name, (c, t, mn, mx) in sorted(_agg.items(),
                                               key=lambda kv: -kv[1][1]):
                lines.append("%-40s %-12d %-14.1f %-12.1f %-12.1f %-12.1f" %
                             (name[:40], c, t, mn, mx, t / c if c else 0.0))
            out = "\n".join(lines)
        if reset:
            _agg.clear()
        return out


def reset():
    """Drop all collected data."""
    with _lock:
        _events.clear()
        _counters.clear()
        _agg.clear()
