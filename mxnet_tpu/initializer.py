"""Weight initializers.

Reference: python/mxnet/initializer.py (class Initializer, class Xavier,
class MSRAPrelu, class Orthogonal, class Mixed, InitDesc attr-driven
dispatch, the string/alias registry used by ``init="xavier"``).

TPU-native: initializers produce values with ``jax.random`` under the global
seed plumbing (mx.random.seed) and are materialized straight into HBM via the
NDArray constructor — no host round trip for large params.
"""
from __future__ import annotations

import json
import math
import re
from typing import Optional

import numpy as _np
import jax.numpy as jnp

from .base import MXNetError

__all__ = ["InitDesc", "Initializer", "register", "create", "Zero", "One",
           "Constant", "Uniform", "Normal", "Orthogonal", "Xavier",
           "MSRAPrelu", "Bilinear", "LSTMBias", "Mixed", "Load"]

_INIT_REGISTRY = {}


def register(klass):
    """Register an initializer under its lowercased class name
    (reference: mx.init registry via ``Initializer.register``)."""
    _INIT_REGISTRY[klass.__name__.lower()] = klass
    return klass


class InitDesc(str):
    """Name + attrs descriptor passed to the initializer (reference:
    python/mxnet/initializer.py InitDesc)."""

    def __new__(cls, name, attrs=None, global_init=None):
        ret = super().__new__(cls, name)
        ret.attrs = attrs or {}
        ret.global_init = global_init
        return ret


class Initializer:
    """Base initializer; call with (name, arr) — dispatches on name suffix
    like the reference (`_init_weight`, `_init_bias`, ...)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs
        self._verbose = False
        self._print_func = None

    def set_verbosity(self, verbose=False, print_func=None):
        self._verbose = verbose
        self._print_func = print_func or (lambda x: None)
        return self

    def dumps(self):
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, desc, arr):
        if not isinstance(desc, str):
            raise TypeError("first argument must be a name string/InitDesc")
        if isinstance(desc, InitDesc) and desc.attrs.get("__init__"):
            create(desc.attrs["__init__"])._init_weight(desc, arr)
            return
        name = desc.lower()
        if name.endswith("weight"):
            self._init_weight(desc, arr)
        elif name.endswith("bias"):
            self._init_bias(desc, arr)
        elif name.endswith("gamma"):
            self._init_gamma(desc, arr)
        elif name.endswith("beta"):
            self._init_beta(desc, arr)
        elif name.endswith("running_mean") or name.endswith("moving_mean"):
            self._init_zero(desc, arr)
        elif name.endswith("running_var") or name.endswith("moving_var"):
            self._init_one(desc, arr)
        elif name.endswith("moving_inv_var"):
            self._init_zero(desc, arr)
        elif name.endswith("moving_avg"):
            self._init_zero(desc, arr)
        else:
            self._init_default(desc, arr)

    # -- leaf initializers -------------------------------------------------
    def _init_zero(self, name, arr):
        arr[:] = 0.0

    def _init_one(self, name, arr):
        arr[:] = 1.0

    def _init_bias(self, name, arr):
        arr[:] = 0.0

    def _init_gamma(self, name, arr):
        arr[:] = 1.0

    def _init_beta(self, name, arr):
        arr[:] = 0.0

    def _init_weight(self, name, arr):
        raise NotImplementedError("%s does not define _init_weight"
                                  % type(self).__name__)

    def _init_default(self, name, arr):
        self._init_weight(name, arr)

    def __repr__(self):
        return "%s(%s)" % (self.__class__.__name__, self._kwargs)


def create(init, **kwargs):
    """Resolve an initializer from an instance, a name string, or a JSON
    dump (reference: initializer registry + Initializer.dumps round trip)."""
    if isinstance(init, Initializer):
        return init
    if callable(init) and not isinstance(init, str):
        return init
    if isinstance(init, str):
        if init.startswith("["):  # JSON [name, kwargs]
            name, kw = json.loads(init)
            return _INIT_REGISTRY[name.lower()](**kw)
        key = init.lower()
        # MXNet registry names: 'zeros'/'ones' map to Zero/One
        key = {"zeros": "zero", "ones": "one", "msra": "msraprelu",
               "gaussian": "normal"}.get(key, key)
        if key not in _INIT_REGISTRY:
            raise MXNetError("unknown initializer %r (have: %s)"
                             % (init, sorted(_INIT_REGISTRY)))
        return _INIT_REGISTRY[key](**kwargs)
    raise TypeError("cannot create initializer from %r" % (init,))


@register
class Zero(Initializer):
    def _init_weight(self, name, arr):
        arr[:] = 0.0


@register
class One(Initializer):
    def _init_weight(self, name, arr):
        arr[:] = 1.0


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, name, arr):
        arr[:] = _np.asarray(self.value)


def _draw_uniform(low, high, shape):
    """All initializer randomness rides the mx.random.seed stream (the
    reference seeds initializers through MXNet's RNG, not numpy's): same
    seed => same init on every process — the property multi-host DP relies
    on before the first weight broadcast."""
    import jax
    from .ops.random import next_key
    return jax.random.uniform(next_key(), tuple(shape), minval=low,
                              maxval=high)


def _draw_normal(mean, sigma, shape):
    import jax
    from .ops.random import next_key
    return jax.random.normal(next_key(), tuple(shape)) * sigma + mean


@register
class Uniform(Initializer):
    """U(-scale, scale) (reference default scale 0.07)."""

    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, name, arr):
        arr[:] = _draw_uniform(-self.scale, self.scale, arr.shape)


@register
class Normal(Initializer):
    """N(0, sigma) (reference default sigma 0.01)."""

    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, name, arr):
        arr[:] = _draw_normal(0.0, self.sigma, arr.shape)


@register
class Orthogonal(Initializer):
    """Orthogonal matrix init (reference: Orthogonal, Saxe et al.)."""

    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, name, arr):
        nout = arr.shape[0]
        nin = int(_np.prod(arr.shape[1:])) if len(arr.shape) > 1 else 1
        if self.rand_type == "uniform":
            tmp = _np.asarray(_draw_uniform(-1.0, 1.0, (nout, nin)))
        else:
            tmp = _np.asarray(_draw_normal(0.0, 1.0, (nout, nin)))
        u, _, v = _np.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == tmp.shape else v
        arr[:] = (self.scale * q).reshape(arr.shape)


@register
class Xavier(Initializer):
    """Xavier/Glorot (reference: class Xavier; magnitude default 3)."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, arr):
        shape = arr.shape
        hw_scale = 1.0
        if len(shape) < 2:
            raise ValueError("Xavier requires at least 2D weight, got %s for %s"
                             % (shape, name))
        if len(shape) > 2:
            hw_scale = _np.prod(shape[2:])
        fan_in, fan_out = shape[1] * hw_scale, shape[0] * hw_scale
        factor = 1.0
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        elif self.factor_type == "out":
            factor = fan_out
        else:
            raise ValueError("Incorrect factor type")
        scale = math.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            arr[:] = _draw_uniform(-scale, scale, shape)
        elif self.rnd_type == "gaussian":
            arr[:] = _draw_normal(0, scale, shape)
        else:
            raise ValueError("Unknown random type")


@register
class MSRAPrelu(Xavier):
    """Kaiming/He init (reference: class MSRAPrelu)."""

    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Bilinear(Initializer):
    """Bilinear upsampling kernel (reference: class Bilinear, used by
    Deconvolution-based UpSampling)."""

    def _init_weight(self, name, arr):
        weight = _np.zeros(arr.shape, dtype=_np.float32).reshape(-1)
        shape = arr.shape
        f = _np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(_np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr[:] = weight.reshape(shape)


@register
class LSTMBias(Initializer):
    """Forget-gate bias init (reference: class LSTMBias)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, arr):
        v = _np.zeros(arr.shape)
        num_hidden = arr.shape[0] // 4
        v[num_hidden:2 * num_hidden] = self.forget_bias  # i, f, c, o gate order
        arr[:] = v


@register
class FusedRNN(Initializer):
    """Initialize a fused-RNN packed parameter blob (reference: class
    FusedRNN): the flat cuDNN-layout vector is split into per-layer/
    direction i2h/h2h weight matrices and biases (the layout
    ops/rnn.py._unpack_params reads), each initialized with `init`, with
    the LSTM forget-gate bias set to forget_bias."""

    def __init__(self, init, num_hidden, num_layers, mode,
                 bidirectional=False, forget_bias=1.0):
        if init is not None and not isinstance(init, Initializer):
            init = create(init)
        if init is not None and not isinstance(init, Initializer):
            raise TypeError("FusedRNN needs an Initializer (or its name); "
                            "got %r" % (type(init).__name__,))
        super().__init__(init=init.dumps() if init else None,
                         num_hidden=num_hidden, num_layers=num_layers,
                         mode=mode, bidirectional=bidirectional,
                         forget_bias=forget_bias)
        self._init = init
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._forget_bias = forget_bias

    def _init_weight(self, desc, arr):
        from .ops.rnn import _GATES, rnn_param_size
        gates = _GATES[self._mode]
        H = self._num_hidden
        dirs = 2 if self._bidirectional else 1
        gh = gates * H
        total = arr.shape[0]
        # infer input_size from the blob length (reference does the same
        # via the RNN op's shape inference)
        #   total = dirs*gh*(I + H) + (L-1)*dirs*gh*(H*dirs + H) + L*dirs*2*gh
        rest = total - self._num_layers * dirs * 2 * gh \
            - (self._num_layers - 1) * dirs * gh * (H * dirs + H)
        input_size = rest // (dirs * gh) - H
        assert rnn_param_size(self._num_layers, input_size, H, self._mode,
                              self._bidirectional) == total, \
            "FusedRNN: blob length does not match the declared geometry"
        out = _np.zeros(total, dtype=_np.float64)
        offset = 0
        for layer in range(self._num_layers):
            isz = input_size if layer == 0 else H * dirs
            for _ in range(dirs):
                for cols in (isz, H):
                    w = _np.zeros((gh, cols))
                    if self._init is not None:
                        self._init("%s_weight" % desc, w)
                    out[offset:offset + w.size] = w.reshape(-1)
                    offset += w.size
        for _ in range(self._num_layers * dirs * 2):
            b = _np.zeros(gh)
            if self._mode == "lstm":
                # gate order i, f, g, o (ops/rnn.py _cell_step)
                b[H:2 * H] = self._forget_bias / 2.0
            out[offset:offset + gh] = b
            offset += gh
        arr[:] = out.reshape(arr.shape)


@register
class Mixed(Initializer):
    """Pattern→initializer dispatch (reference: class Mixed)."""

    def __init__(self, patterns=None, initializers=None):
        super().__init__()
        patterns = patterns or []
        initializers = initializers or []
        if len(patterns) != len(initializers):
            raise ValueError("patterns and initializers must pair up")
        self.map = [(re.compile(p), create(i)) for p, i in
                    zip(patterns, initializers)]

    def __call__(self, name, arr):
        for pat, init in self.map:
            if pat.match(str(name)):
                init(name, arr)
                return
        raise ValueError("Parameter %s did not match any pattern; add '.*' "
                         "as a catch-all" % name)


@register
class Load(Initializer):
    """Init from a dict of arrays, falling back to default_init
    (reference: class Load used by model loading paths)."""

    def __init__(self, param, default_init=None, verbose=False):
        super().__init__()
        self.param = {k[4:] if k.startswith("arg:") or k.startswith("aux:")
                      else k: v for k, v in param.items()}
        self.default_init = default_init

    def __call__(self, name, arr):
        if name in self.param:
            src = self.param[name]
            src_np = src.asnumpy() if hasattr(src, "asnumpy") else _np.asarray(src)
            if tuple(src_np.shape) != tuple(arr.shape):
                raise ValueError("Parameter %s shape mismatch: %s vs %s"
                                 % (name, src_np.shape, arr.shape))
            arr[:] = src_np
        else:
            if self.default_init is None:
                raise ValueError("Cannot init %s: not found and no default"
                                 % name)
            self.default_init(name, arr)
