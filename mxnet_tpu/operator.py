"""mx.operator: user-defined operators with numpy callbacks (CustomOp).

Reference: python/mxnet/operator.py (CustomOp, CustomOpProp, register),
src/operator/custom/custom.cc (CustomOperator::Push — the engine bridge
that schedules the python callback on its own thread pool).

TPU-native design: the numpy callback crosses the device boundary through
``jax.pure_callback`` so a Custom op remains *traceable* — it works inside
``hybridize()``/``jit`` (XLA inserts the host transfer at the callback
boundary, playing the role of custom.cc's engine thread + DevCopy).  The
gradient is wired with ``jax.custom_vjp`` whose backward is the user's
``CustomOp.backward`` behind a second pure_callback, so autograd works both
on the eager tape and under the whole-graph vjp a CachedOp takes.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as _np
import jax
import jax.numpy as jnp

from .base import MXNetError

__all__ = ["CustomOp", "CustomOpProp", "register", "get_registered_op",
           "Custom"]

_REGISTRY: Dict[str, type] = {}


class CustomOp:
    """Base class for user ops.  Implement ``forward`` and ``backward``
    with numpy semantics (reference: operator.py class CustomOp)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    def assign(self, dst, req, src):
        """Helper honoring the grad_req the same way the reference does."""
        if req in ("write", "inplace", 1, 2):
            dst[...] = src
        elif req in ("add", 3):
            dst[...] = dst + src
        # 'null'/0: drop


class CustomOpProp:
    """Op metadata provider (reference: operator.py class CustomOpProp).

    Subclass and override ``list_arguments``/``list_outputs``/
    ``infer_shape``/``infer_type``/``create_operator``."""

    def __init__(self, need_top_grad: bool = True):
        self.need_top_grad_ = need_top_grad

    def list_arguments(self) -> List[str]:
        return ["data"]

    def list_outputs(self) -> List[str]:
        return ["output"]

    def list_auxiliary_states(self) -> List[str]:
        return []

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], []

    def infer_type(self, in_type):
        return in_type, [in_type[0]] * len(self.list_outputs()), []

    def need_top_grad(self) -> bool:
        return self.need_top_grad_

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        deps = []
        if self.need_top_grad():
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps

    def create_operator(self, ctx, in_shapes, in_dtypes) -> CustomOp:
        raise NotImplementedError


def register(reg_name: str):
    """Decorator: ``@mx.operator.register("my_op")`` on a CustomOpProp
    subclass (reference: operator.py register)."""

    def _reg(prop_cls):
        if not issubclass(prop_cls, CustomOpProp):
            raise MXNetError("register expects a CustomOpProp subclass")
        _REGISTRY[reg_name] = prop_cls
        return prop_cls

    return _reg


def get_registered_op(name: str) -> type:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise MXNetError("custom op %r is not registered" % (name,))


def _writable(arrs: Sequence[_np.ndarray]) -> List[_np.ndarray]:
    # pure_callback hands read-only views; the CustomOp contract is
    # in-place assignment into out_data/in_grad buffers.
    return [_np.array(a) for a in arrs]


def Custom(*inputs, op_type: Optional[str] = None, **kwargs):
    """Invoke a registered custom op: ``mx.nd.Custom(x, op_type='my_op')``
    (reference: the generated nd.Custom wrapper over custom.cc)."""
    from .ndarray.ndarray import NDArray
    from . import autograd
    from .device import current_context

    if op_type is None:
        raise MXNetError("Custom requires op_type=")
    from .ndarray import ndarray as _ndmod
    if _ndmod._sym_tracer is not None:
        raise MXNetError(
            "Custom ops cannot be traced into symbol.json (the numpy "
            "callback has no graph representation — the reference's "
            "exported Custom nodes need the python process too); exclude "
            "the Custom op from the exported subgraph")
    # standard MXNet call kwargs are not prop parameters
    kwargs.pop("name", None)
    kwargs.pop("ctx", None)
    prop = get_registered_op(op_type)(**{k: str(v) for k, v in kwargs.items()})

    nd_in = [x if isinstance(x, NDArray) else NDArray(jnp.asarray(x))
             for x in inputs]
    ctx = nd_in[0].context if nd_in else current_context()
    n_args = len(prop.list_arguments())
    if len(nd_in) != n_args:
        raise MXNetError("custom op %r expects %d inputs (%s), got %d"
                         % (op_type, n_args, prop.list_arguments(),
                            len(nd_in)))

    in_shapes = [tuple(x.shape) for x in nd_in]
    in_dtypes = [_np.dtype(x.dtype) for x in nd_in]
    if prop.list_auxiliary_states():
        raise MXNetError("custom op %r declares auxiliary states, which the "
                         "TPU bridge does not support yet (keep state on the "
                         "CustomOp instance instead)" % (op_type,))
    in_shapes2, out_shapes, _aux = prop.infer_shape(list(in_shapes))
    _, out_dtypes, _ = prop.infer_type(list(in_dtypes))
    n_out = len(prop.list_outputs())
    op = prop.create_operator(ctx, in_shapes2, in_dtypes)

    out_avals = tuple(jax.ShapeDtypeStruct(tuple(s), _np.dtype(t))
                      for s, t in zip(out_shapes, out_dtypes))
    in_avals = tuple(jax.ShapeDtypeStruct(s, t)
                     for s, t in zip(in_shapes, in_dtypes))
    def _fwd_cb(*xs):
        # is_train is re-derived at CALLBACK time, not closed over at trace
        # time: under hybridize the first trace's value would otherwise be
        # frozen into every later call (the reference passes per-call
        # is_train to CustomOp.forward).  ambient_is_train() (not
        # is_training()) because pure_callback may run on an XLA runtime
        # thread whose thread-local autograd state was never set.
        is_train = autograd.ambient_is_train()
        in_data = _writable(xs)
        out_data = [_np.zeros(s, t) for s, t in zip(out_shapes, out_dtypes)]
        op.forward(is_train, ["write"] * n_out, in_data, out_data, [])
        return tuple(out_data)

    def _bwd_cb(*flat):
        og = _writable(flat[:n_out])
        ind = _writable(flat[n_out:n_out + n_args])
        outd = _writable(flat[n_out + n_args:])
        in_grad = [_np.zeros(s, t) for s, t in zip(in_shapes, in_dtypes)]
        op.backward(["write"] * n_args, og, ind, outd, in_grad, [])
        return tuple(in_grad)

    @jax.custom_vjp
    def run(*xs):
        return jax.pure_callback(_fwd_cb, out_avals, *xs)

    def run_fwd(*xs):
        ys = jax.pure_callback(_fwd_cb, out_avals, *xs)
        return ys, (xs, ys)

    def run_bwd(res, cts):
        xs, ys = res
        gs = jax.pure_callback(_bwd_cb, in_avals, *cts, *xs, *ys)
        return tuple(gs)

    run.defvjp(run_fwd, run_bwd)

    jax_in = [x._jax for x in nd_in]
    traced = any(isinstance(v, jax.core.Tracer) for v in jax_in)
    if traced:
        # inside a hybridize/jit trace: stay traceable via pure_callback
        # (XLA host send/recv plays the role of custom.cc's engine thread).
        # NB the axon PJRT plugin lacks host-callback support; under it a
        # Custom op works eagerly but not inside hybridize() on-device.
        outs = run(*jax_in)
        return ([NDArray(o, ctx=ctx) for o in outs][0] if n_out == 1
                else [NDArray(o, ctx=ctx) for o in outs])
    # eager: execute the numpy callback directly on host values — no
    # callback primitive, so it works on every backend (the reference's
    # CustomOperator also runs the python callback synchronously on host).
    from .ndarray.ndarray import _put
    host_in = [_np.asarray(v) for v in jax_in]
    host_out = _fwd_cb(*host_in)
    outs = tuple(_put(o, ctx) for o in host_out)
    if autograd.is_recording():
        def tape_vjp(cts):
            gs = _bwd_cb(*[_np.asarray(c) for c in cts], *host_in, *host_out)
            return tuple(jnp.asarray(g) for g in gs)
        wrapped = autograd.record_custom(tape_vjp, nd_in, outs, ctx,
                                         name="Custom:%s" % op_type)
        outs_nd = wrapped if isinstance(wrapped, list) else [wrapped]
    else:
        outs_nd = [NDArray(o, ctx=ctx) for o in outs]
    return outs_nd[0] if n_out == 1 else outs_nd
