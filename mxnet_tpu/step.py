"""Whole-program compiled training step (ISSUE 7 tentpole).

Reference: MXNet's defining trick is ``hybridize()`` — run eager, then
cache the whole graph as one CachedOp (src/imperative/cached_op.cc).  The
Julia→TPU full-program compilation work (arxiv 1810.09868) and TF1-style
graph execution (arxiv 1605.08695) make the same argument for the
*training loop*: compile the whole step, not kernels.  PR 3 made the
eager Gluon step O(1) dispatches; this module collapses those remaining
~dozen programs — loss forward, backward, the bucketed (int8/2bit
error-feedback quantized) gradient exchange, the fused multi-tensor
optimizer apply and device-side metric accumulation — into **one donated
``jax.jit``** per step, with a ``lax.scan`` multi-step window
(``MX_STEP_SCAN=N``) that keeps N prefetched batches on device per host
round-trip and folds gradient accumulation into the scanned body.

Semantics mirror hybridize: the first call traces, a shape/dtype change
retraces (the cache key is the input/param avals), ``invalidate()`` is
the ``_clear_cached_op`` equivalent, and parameter values are *read
fresh and written back every dispatch* — external mutation (checkpoint
restore, manual ``set_data``) between steps is picked up automatically
because the NDArray chunks, not device-side captures, remain the source
of truth.  lr/wd (and Adam-family bias correction) arrive as traced
scalars computed on host per step, so LR schedulers never retrigger
compilation.

Eager remains the debug path: configurations the trace cannot express —
the PS/dist_async transport (its exchange crosses a socket mid-step),
multi-process collectives (the SPMD mesh lane ``parallel.TrainStep``
owns those), optimizers without a pure tree kernel, ``grad_req='add'``,
sparse gradients — fall back to the eager pipeline with a one-time
warning, and :meth:`CompiledStep.step` keeps working either way.

State continuity: optimizer slot state lives in the Trainer's Updater
``states`` (donated in, written back out each dispatch), and
error-feedback residuals live in the kvstore's GradientCompression store
— so ``Trainer.save_states``/checkpoint sidecars round-trip the donated
state, and switching compiled↔eager mid-training continues the exact
trajectory.
"""
from __future__ import annotations

import warnings
from typing import Dict, List, Optional

import numpy as _np
import jax
import jax.numpy as jnp
from jax import lax

from .base import MXNetError, get_env
from .device import cpu
from .ndarray.ndarray import NDArray
from . import autograd
from .ops import random as _ops_random
from .ops.optimizer import tree_body
from .gluon.block import _flatten_nds
from .gluon.parameter import (DeferredInitializationError,
                              _ParamOverrideScope)

__all__ = ["CompiledStep", "scan_window", "step_compile_enabled",
           "metric_trace_kernel"]


def step_compile_enabled() -> bool:
    """MX_STEP_COMPILE=1 — the whole-step-compiled lane is on."""
    return bool(get_env("MX_STEP_COMPILE", dtype=bool))


def scan_window() -> int:
    """MX_STEP_SCAN window size (N batches per dispatch); 0/1 = per-step."""
    try:
        n = int(get_env("MX_STEP_SCAN", 0, int) or 0)
    except (TypeError, ValueError):
        n = 0
    return max(n, 0)


def metric_trace_kernel(metric):
    """(kernel, argspec) folding `metric` into a whole-step jit, or None
    (caller accumulates eagerly from the returned outputs instead).
    argspec names the kernel's operand order: 'pred_label', 'label_pred'
    or 'loss' (see EvalMetric._trace_kernel)."""
    if metric is None:
        return None
    get = getattr(metric, "_trace_kernel", None)
    return get() if get is not None else None


def metric_cache_key(metric, metric_info):
    """Trace-identity of a folded metric: class + argspec + the
    kernel-affecting config (axis/eps/ignore_label/...), so two
    same-class metrics with different hyperparameters never share a
    cached executable."""
    if metric_info is None:
        return None
    cfg = tuple(sorted((k, repr(v)) for k, v in
                       getattr(metric, "_kwargs", {}).items()))
    return (type(metric).__name__, metric_info[1], cfg)


def _as_jax(x):
    return x._jax if isinstance(x, NDArray) else jnp.asarray(x)


def _as_nd(x, ctx):
    return x if isinstance(x, NDArray) else NDArray(jnp.asarray(x), ctx=ctx)


def _placed(a, sharding):
    """Place `a` onto `sharding` iff it is not already there (sharded
    lane steady state: an equality check, no transfer)."""
    if a is None or sharding is None:
        return a
    if getattr(a, "sharding", None) == sharding:
        return a
    return jax.device_put(a, sharding)


class CompiledStep:
    """One Gluon training step as a single donated XLA program.

    Built over a live ``gluon.Trainer`` — its parameters, optimizer,
    kvstore (exchange + compression) and updater state are the state the
    compiled program donates and writes back, so eager and compiled
    steps are interchangeable mid-run.

    ``step(data, label)`` is the hybridize-style drop-in for the eager
    record/backward/Trainer.step/metric sequence; ``run_window(data,
    label, accum=k)`` executes a stacked window of micro-batches under
    one ``lax.scan`` dispatch with gradient accumulation folded in.
    """

    def __init__(self, net, loss_fn, trainer, metric=None, layout=None):
        self._net = net
        self._loss_fn = loss_fn
        self._trainer = trainer
        self._metric = metric
        # sharded lane (ISSUE 14): a parallel.SpecLayout turns this step
        # into an SPMD program over the layout's mesh — parameters and
        # optimizer state live sheet-sharded (fsdp) / tensor-split (tp),
        # the batch splits over data×fsdp, gradients reduce-scatter onto
        # the parameter shards and XLA all-gathers updated params just
        # in time inside the SAME one donated jit.  None (+ unset env
        # knobs) keeps the replicated behavior bit-identical.
        if layout is None:
            from .parallel.speclayout import layout_from_env
            layout = layout_from_env()
        self._layout = layout
        self._shard_kv = None   # lazily built exchange store (sharded lane)
        self._cache: Dict = {}
        self._fallback_reason: Optional[str] = None
        self._warned = False
        # donation safety: ONLY buffers this step produced itself (last
        # dispatch's outputs) are donated as-is — a foreign array may be
        # aliased elsewhere (kvstore init/broadcast slots share the
        # initial param buffers; set_data/as_in_context alias on same
        # device+dtype), and donating it would delete every alias's
        # view.  Foreign inputs are copied once before donation; the
        # refs list pins the owned arrays so ids cannot be reused.
        self._owned: set = set()
        self._owned_refs: List = []
        # plan cache: the trace-static view of the trainer (exchange
        # body, bucket specs, mp grouping, slot-state layout) is rebuilt
        # only when its cheap signature changes — not O(n_params) of
        # Python per dispatch on the host hot path
        self._plan_cached = None
        self._plan_sig = None

    # -- cache control (hybridize semantics) -------------------------------
    @property
    def compiled(self) -> bool:
        return self._fallback_reason is None

    @property
    def fallback_reason(self) -> Optional[str]:
        return self._fallback_reason

    def invalidate(self) -> None:
        """Drop every cached executable (the `_clear_cached_op` of this
        lane) — the next call retraces from the current configuration."""
        self._cache.clear()
        self._plan_cached = None
        self._plan_sig = None

    def _fall(self, reason: str):
        self._fallback_reason = reason
        if not self._warned:
            self._warned = True
            warnings.warn("CompiledStep: falling back to the eager "
                          "pipeline (%s)" % reason, stacklevel=3)
        return None

    # -- plan: the trace-static view of the trainer ------------------------
    def _plan_signature(self):
        """What can change the plan between steps: kvstore identity and
        compression config, bucket capacity, grad_req flips, context
        set.  Cheap attribute reads only — checked every dispatch."""
        tr = self._trainer
        # sharded lane: materialize the lazily-created exchange store
        # BEFORE keying on it, or the signature flips between step 1
        # (id(None)) and step 2 (id(store)) and forces a full plan
        # rebuild on the second dispatch
        kv = tr._kvstore if self._layout is None else \
            (tr._kvstore or self._ensure_shard_kv())
        gc = getattr(kv, "_gc", None) if kv is not None else None
        from .kvstore.bucketing import bucket_bytes
        opt = tr._optimizer
        return (id(kv), tr._update_on_kvstore, id(opt),
                None if self._layout is None
                else self._layout.signature(),
                tuple(p._grad_req for p in tr._params),
                tuple(id(c) for c in (tr._contexts or ())),
                None if gc is None
                else (gc.type, gc.block, gc.threshold),
                getattr(kv, "_compress_bf16", False) if kv else False,
                bucket_bytes(),
                # trace-static optimizer hyperparams (the supported
                # kinds'): a mid-run mutation must rebuild spec statics
                opt.clip_gradient, getattr(opt, "momentum", None),
                getattr(opt, "beta1", None), getattr(opt, "beta2", None),
                getattr(opt, "epsilon", None),
                getattr(opt, "correct_bias", None))

    def _plan(self):
        if self._fallback_reason is not None:
            return None
        tr = self._trainer
        if not tr._kv_initialized:
            tr._init_kvstore()
        if tr._params_to_init:
            tr._init_params()
        sig = self._plan_signature()
        if self._plan_cached is not None and sig == self._plan_sig:
            return self._plan_cached
        if tr._update_on_kvstore:
            return self._fall("server-side optimizer (update_on_kvstore)")
        opt = tr._optimizer
        spec = opt._compiled_spec()
        if spec is None:
            return self._fall("optimizer %s has no pure tree kernel"
                              % type(opt).__name__)
        kv = tr._kvstore
        if kv is not None and kv.num_workers > 1:
            return self._fall("multi-process exchange needs the SPMD mesh "
                              "lane (parallel.TrainStep)")
        trainable_idx, frozen_params = [], []
        for i, p in enumerate(tr._params):
            if p._data is None:
                raise DeferredInitializationError(
                    "Parameter %s is not initialized yet" % p.name)
            if p.grad_req == "add":
                return self._fall("grad_req='add' (use run_window(accum=k) "
                                  "for compiled gradient accumulation)")
            if p.grad_req == "null":
                frozen_params.append(p)
            elif p._grad_stype == "row_sparse":
                return self._fall("row_sparse gradients take the per-key "
                                  "gather/scatter path")
            else:
                trainable_idx.append(i)
        ctxs = tr._contexts
        trainable = [tr._params[i] for i in trainable_idx]
        layout = self._layout
        shardings = frozen_shardings = compute_shardings = None
        if layout is not None:
            if len(ctxs) > 1:
                return self._fall(
                    "SpecLayout sharded lane is SPMD over the mesh — "
                    "use ONE Trainer context (the mesh owns the devices)")
            # per-parameter placement: rules > Block.sharding_spec hook >
            # kind defaults > fsdp sheet (speclayout resolution order)
            specs = layout.resolve(self._net)
            by_id = {}
            for name, p in self._net.collect_params().items():
                by_id[id(p)] = specs.get(name)

            def _spec_of(p):
                sp = by_id.get(id(p))
                if sp is None:
                    sp = layout.param_spec(p.name, tuple(p.shape), p.dtype)
                return sp

            shardings = tuple(layout.sharding(_spec_of(p))
                              for p in trainable)
            compute_shardings = tuple(
                layout.sharding(layout.compute_spec(_spec_of(p)))
                for p in trainable)
            # frozen/aux state (BatchNorm stats) mutates inside forward
            # on every chip's shard of the batch: replicate it
            frozen_shardings = tuple(layout.replicated()
                                     for _ in frozen_params)
            # adopt: the parameter (and dormant grad) buffers live
            # SHARDED from here — the NDArray chunks stay the source of
            # truth, but their jax value is the global mesh array, so
            # per-chip HBM drops with the fsdp axis and steady-state
            # gathers are no-ops
            from .parallel.speclayout import place_value as _place
            for p, s in list(zip(trainable, shardings)) + \
                    list(zip(frozen_params, frozen_shardings)):
                nd_ = p._data[ctxs[0]]
                nd_._set_jax(_place(nd_._jax, s))
                if p._grad:
                    g_nd = p._grad.get(ctxs[0])
                    if g_nd is not None:
                        g_nd._set_jax(_place(g_nd._jax, s))
        exchange = None
        if kv is not None and len(ctxs) > 1:
            # the eager exchange set: every trainable param crosses the
            # store when there is more than one device copy to merge
            exchange = kv.build_exchange_body(
                trainable_idx, [p.data(ctxs[0]) for p in trainable])
            if exchange is None:
                return self._fall("kvstore %r exchange is not traceable "
                                  "(host-blocking transport)" % kv.type)
        elif layout is not None:
            # sharded quantized wire: the trainer's compression config
            # rides a process-local exchange store (single-context
            # trainers never build one of their own) whose body becomes
            # the reduce-scatter/all-gather variant
            kvx = self._ensure_shard_kv()
            if kvx is not None:
                exchange = kvx.build_exchange_body(
                    trainable_idx, [p.data(ctxs[0]) for p in trainable],
                    layout=layout)
                if exchange is None:
                    return self._fall(
                        "kvstore %r exchange is not traceable under the "
                        "sharded lane" % kvx.type)
        # optimizer slot state, created through the SAME updater store the
        # eager path uses (and every save_states/checkpoint reads)
        mp_flags = []
        for d, upd in enumerate(tr._updaters):
            for pos, i in enumerate(trainable_idx):
                w = trainable[pos].data(ctxs[d])
                if i not in upd.states:
                    upd.states[i] = \
                        upd.optimizer.create_state_multi_precision(i, w)
                    upd.states_synced[i] = True
                if d == 0:
                    mp_flags.append(bool(opt._is_mp_state(w, upd.states[i])))
        groups: Dict[bool, List[int]] = {}
        for pos, mp in enumerate(mp_flags):
            groups.setdefault(mp, []).append(pos)
        state_shardings = w32_shardings = residual_shardings = None
        if layout is not None:
            # ZeRO: optimizer state lives on its parameter's shards from
            # init — re-place the (just-created) slot NDArrays so the
            # sharded layout IS the stored state, not a per-dispatch copy
            from .parallel.speclayout import place_value as _place
            upd0 = tr._updaters[0]
            state_shardings, w32_shardings = [], []
            for pos, i in enumerate(trainable_idx):
                p = trainable[pos]
                pspec = _spec_of(p)
                inner, w32 = spec["unpack"](upd0.states[i], mp_flags[pos])
                cols = []
                for s_nd in inner:
                    ssh = layout.sharding(
                        layout.state_spec(pspec, tuple(s_nd.shape)))
                    s_nd._set_jax(_place(s_nd._jax, ssh))
                    cols.append(ssh)
                state_shardings.append(tuple(cols))
                if w32 is not None:
                    wsh = layout.sharding(
                        layout.state_spec(pspec, tuple(w32.shape)))
                    w32._set_jax(_place(w32._jax, wsh))
                    w32_shardings.append(wsh)
                else:
                    w32_shardings.append(None)
            state_shardings = tuple(state_shardings)
            w32_shardings = tuple(w32_shardings)
            residual_shardings = tuple(
                sh if sh is not None else layout.replicated()
                for sh in (exchange.residual_shardings
                           if exchange is not None else ()))
        plan = {
            "spec": spec,
            "trainable_idx": trainable_idx,
            "trainable": trainable,
            "frozen": frozen_params,
            "ctxs": ctxs,
            "exchange": exchange,
            "mp_flags": tuple(mp_flags),
            "mp_groups": sorted(groups.items()),
            "clip": -1.0 if opt.clip_gradient is None
                    else float(opt.clip_gradient),
            # sharded lane (ISSUE 14): every donated state group's
            # placement, resolved once per plan
            "layout": layout,
            "shardings": shardings,
            "compute_shardings": compute_shardings,
            "frozen_shardings": frozen_shardings,
            "state_shardings": state_shardings,
            "w32_shardings": w32_shardings,
            "residual_shardings": residual_shardings,
            "replicated": None if layout is None else layout.replicated(),
            "gc": getattr(kv, "_gc", None) if layout is None
                  else getattr(self._shard_kv or kv, "_gc", None),
        }
        self._plan_cached = plan
        self._plan_sig = sig
        return plan

    def _ensure_shard_kv(self):
        """The sharded lane's exchange store: the trainer's own kvstore
        when it has one, else a lazily created process-local 'ici' store
        carrying the trainer's compression config (a single-context
        Trainer never builds a store of its own) — the error-feedback
        residual state lives there exactly like the replicated lane's
        store-resident residuals, so checkpoints and census attribution
        see one consistent owner.  None when no compression is
        configured (plain FSDP: constraint-only exchange)."""
        tr = self._trainer
        if tr._kvstore is not None:
            return tr._kvstore
        if self._shard_kv is None and tr._compression_params:
            from .kvstore import create as _kv_create
            kv = _kv_create("ici")
            kv.set_gradient_compression(tr._compression_params)
            self._shard_kv = kv
        return self._shard_kv

    # -- trace builders ----------------------------------------------------
    def _make_forward(self, plan):
        net, loss_fn = self._net, self._loss_fn
        trainable, frozen = plan["trainable"], plan["frozen"]

        def run_forward(t_vals, f_vals, rng, x_vals, y_val):
            overrides: Dict[int, NDArray] = {}
            fr_nds = []
            for p, v in zip(trainable, t_vals):
                overrides[id(p)] = NDArray(v, ctx=cpu())
            for p, v in zip(frozen, f_vals):
                nd_ = NDArray(v, ctx=cpu())
                overrides[id(p)] = nd_
                fr_nds.append(nd_)
            x_nds = [NDArray(v, ctx=cpu()) for v in x_vals]
            y_nd = NDArray(y_val, ctx=cpu())
            with _ParamOverrideScope(overrides), \
                    _ops_random.trace_key_scope(rng), \
                    autograd._Scope(False, True):
                out = net(*x_nds)
                loss = loss_fn(out, y_nd)
            out_leaves: List[NDArray] = []
            _flatten_nds(out, out_leaves)
            loss_leaves: List[NDArray] = []
            _flatten_nds(loss, loss_leaves)
            # aux state (BatchNorm running stats) mutated during forward:
            # the frozen params' fresh values ride the scan carry
            new_f = tuple(nd_._jax for nd_ in fr_nds)
            return ([l._jax for l in loss_leaves],
                    [o._jax for o in out_leaves], new_f)

        def forward_backward(t_vals, f_vals, rng, x_vals, y_val):
            def loss_of(tv):
                losses, outs, new_f = run_forward(tv, f_vals, rng,
                                                  x_vals, y_val)
                # backward() seeds a ones cotangent on the loss: the
                # gradient of the elementwise SUM is exactly that
                total = losses[0].sum()
                for extra in losses[1:]:
                    total = total + extra.sum()
                out0 = outs[0] if outs else losses[0]
                return total, (losses[0], out0, new_f)

            (_tot, aux), grads = jax.value_and_grad(
                loss_of, has_aux=True)(tuple(t_vals))
            loss0, out0, new_f = aux
            return loss0, out0, grads, new_f

        return forward_backward

    def _build_fn(self, plan, n_steps, accum, rescale, wds, decays_on,
                  metric_info, return_outs):
        spec = plan["spec"]
        body = tree_body(spec["kind"])
        statics = dict(spec["static"])
        n_state = spec["n_state"]
        mp_groups = plan["mp_groups"]
        exchange = plan["exchange"]
        clip = plan["clip"]
        shardings = plan.get("shardings")
        compute_shardings = plan.get("compute_shardings")
        forward_backward = self._make_forward(plan)

        def _traced_step_window(t_vals, f_vals, opt_states, w32s,
                                residuals, mstate, lr_rows, decay_rows,
                                rng, xs, ys):
            # NB every helper below is NESTED in this jitted function on
            # purpose: mxlint's jit-purity rule walks the jitted def's
            # own AST, so the whole step body is machine-checked for
            # host syncs / wall-clock / env reads (ISSUE 7 satellite).
            def apply_optimizer(t_vals, grads, opt_states, w32s, lr_row,
                                decay_row):
                new_t = list(t_vals)
                new_states = list(opt_states)
                new_w32 = list(w32s)
                for mp, poss in mp_groups:
                    ws = tuple(t_vals[p] for p in poss)
                    gs = tuple(grads[p] for p in poss)
                    cols = [tuple(opt_states[p][j] for p in poss)
                            for j in range(n_state)]
                    args = [ws, gs] + cols
                    args.append(tuple(w32s[p] for p in poss)
                                if mp else None)
                    args.append(lr_row[jnp.asarray(poss, jnp.int32)])
                    if decays_on:
                        args.append(decay_row[jnp.asarray(poss,
                                                          jnp.int32)])
                    out_w, out_states, out_w32 = body(
                        *args, wds=tuple(wds[p] for p in poss),
                        rescale_grad=rescale, clip_gradient=clip, mp=mp,
                        **statics)
                    for j, p in enumerate(poss):
                        new_t[p] = out_w[j]
                        if out_states is not None:
                            new_states[p] = tuple(col[j]
                                                  for col in out_states)
                        if mp and out_w32 is not None:
                            new_w32[p] = out_w32[j]
                return tuple(new_t), tuple(new_states), tuple(new_w32)

            def accumulate_metric(mstate, loss0, out0, y_mb):
                if metric_info is None or mstate is None:
                    return mstate
                kernel, order = metric_info
                msum, minst = mstate
                if order == "loss":
                    return tuple(kernel(msum, minst, loss0))
                if order == "label_pred":
                    return tuple(kernel(msum, minst, y_mb, out0))
                return tuple(kernel(msum, minst, out0, y_mb))

            def one_step(carry, inp):
                t_vals, f_vals, opt_states, w32s, residuals, mstate = carry
                lr_row, decay_row, rngs, x_row, y_row = inp
                # FSDP just-in-time all-gather (ISSUE 14): parameters are
                # STORED sheet-sharded over fsdp but COMPUTE whole (tp
                # splits stay); constraining to the compute spec here
                # makes XLA emit the gather right before the forward —
                # and re-emit it inside every scan iteration, so a
                # window never holds gathered copies across steps
                t_use = t_vals if compute_shardings is None else tuple(
                    lax.with_sharding_constraint(v, s)
                    for v, s in zip(t_vals, compute_shardings))

                def micro(mcarry, minp):
                    f_v, g_acc, mst = mcarry
                    key, x_mb, y_mb = minp
                    loss0, out0, grads, new_f = forward_backward(
                        t_use, f_v, key, x_mb, y_mb)
                    mst = accumulate_metric(mst, loss0, out0, y_mb)
                    g_acc = tuple(a + g for a, g in zip(g_acc, grads))
                    return (new_f, g_acc, mst), (loss0, out0)

                init = (f_vals,
                        tuple(jnp.zeros(v.shape, v.dtype)
                              for v in t_vals),
                        mstate)
                if accum == 1:
                    mcarry, (loss0, out0) = micro(
                        init, (rngs[0], tuple(x[0] for x in x_row),
                               y_row[0]))
                    losses = loss0[None]
                    outs = out0[None]
                else:
                    mcarry, (losses, outs) = lax.scan(
                        micro, init, (rngs, x_row, y_row))
                f_vals, g_sum, mstate = mcarry
                if shardings is not None:
                    # the reduce-scatter point (ISSUE 14): the gradient
                    # sum over the data×fsdp-sharded batch lands directly
                    # on each parameter's shards — GSPMD fuses the cross-
                    # chip sum and the scatter into one collective, and
                    # the updated params all-gather just in time at the
                    # next forward's use sites
                    g_sum = tuple(lax.with_sharding_constraint(g, s)
                                  for g, s in zip(g_sum, shardings))
                if exchange is not None:
                    new_g, new_res = exchange(list(g_sum),
                                              list(residuals))
                    g_sum = tuple(new_g)
                    residuals = tuple(new_res)
                t_vals, opt_states, w32s = apply_optimizer(
                    t_vals, g_sum, opt_states, w32s, lr_row, decay_row)
                out_row = (losses, outs) if return_outs else losses
                return (t_vals, f_vals, opt_states, w32s, residuals,
                        mstate), out_row

            # window xs leaves arrive (n_steps*accum, B, ...); the
            # single-step path passes the bare (B, ...) micro-batch.
            # Either way the (window, micro-batch) grid is laid out
            # inside the trace (a reshape — free in XLA).
            if n_steps * accum == 1:
                x_grid = tuple(x[None, None] for x in xs)
                y_grid = ys[None, None]
            else:
                x_grid = tuple(x.reshape((n_steps, accum) + x.shape[1:])
                               for x in xs)
                y_grid = ys.reshape((n_steps, accum) + ys.shape[1:])
            keys = jax.random.split(rng, n_steps * accum).reshape(
                (n_steps, accum) + rng.shape)
            carry = (t_vals, f_vals, opt_states, w32s, residuals, mstate)
            if n_steps == 1:
                # unrolled single step: a length-1 lax.scan would wrap
                # the whole model in a while-loop body, which XLA (CPU
                # especially) optimizes far more conservatively
                carry, row = one_step(
                    carry, (lr_rows[0],
                            None if decay_rows is None else decay_rows[0],
                            keys[0], tuple(x[0] for x in x_grid),
                            y_grid[0]))
                stacked = jax.tree_util.tree_map(lambda a: a[None], row)
            else:
                carry, stacked = lax.scan(
                    one_step, carry,
                    (lr_rows, decay_rows, keys, x_grid, y_grid))
            if return_outs:
                losses, outs = stacked
                outs = outs.reshape((n_steps * accum,) + outs.shape[2:])
            else:
                losses, outs = stacked, None
            losses = losses.reshape((n_steps * accum,) + losses.shape[2:])
            return carry + (losses, outs)

        # AOT census (ISSUE 10): the whole-step program's compile time,
        # memory_analysis footprint and retrace diffs are first-class
        # registry outputs — a CompiledStep invalidation shows up as a
        # `step.*` retrace with the offending arg named
        from .programs import register_program
        pname = "step.step" if n_steps * accum == 1 else "step.window"
        return register_program(pname, _traced_step_window,
                                donate_argnums=(0, 1, 2, 3, 4, 5))

    # -- host-side per-window bookkeeping ----------------------------------
    def _lr_rows(self, plan, n_steps, batch_size):
        tr = self._trainer
        opt = tr._optimizer
        spec = plan["spec"]
        idxs = plan["trainable_idx"]
        rescale = tr._scale / batch_size
        opt.rescale_grad = rescale
        # advance EVERY device copy's update-count table (Updater.__call__
        # keys per-device tables) so an eager<->compiled switch continues
        # one num_update trajectory on all replicas; lr comes off the
        # primary table
        ctx0 = plan["ctxs"][0]
        for c in plan["ctxs"][1:]:
            opt._set_current_context((c.canonical_type, c.device_id))
            for _ in range(n_steps):
                opt._update_count(idxs)
        opt._set_current_context((ctx0.canonical_type, ctx0.device_id))
        lr_rows, decay_rows = [], []
        wds = None
        for _ in range(n_steps):
            opt._update_count(idxs)
            raw = opt._get_lrs(idxs)
            if wds is None:
                wds = tuple(opt._get_wds(idxs))
            if spec.get("decay_fn") is not None:
                decay_rows.append([spec["decay_fn"](i, lr, wd)
                                   for i, lr, wd in zip(idxs, raw, wds)])
            if spec.get("lr_fn") is not None:
                raw = [spec["lr_fn"](i, lr) for i, lr in zip(idxs, raw)]
            lr_rows.append(raw)
        # packing HOST floats (scheduler lr / bias-correction values) into
        # the traced lr matrix — no device buffer is read here
        lrs = jnp.asarray(_np.asarray(lr_rows, _np.float32))  # mxlint: disable=host-sync-in-hot-path
        decays = None
        if decay_rows:
            decays = jnp.asarray(_np.asarray(decay_rows, _np.float32))  # mxlint: disable=host-sync-in-hot-path
        return rescale, wds, lrs, decays

    def _gather_state(self, plan):
        tr = self._trainer
        spec = plan["spec"]
        ctx0 = plan["ctxs"][0]
        t_vals = tuple(p.data(ctx0)._jax for p in plan["trainable"])
        f_vals = tuple(p.data(ctx0)._jax for p in plan["frozen"])
        upd = tr._updaters[0]
        opt_states, w32s = [], []
        for pos, i in enumerate(plan["trainable_idx"]):
            inner, w32 = spec["unpack"](upd.states[i],
                                        plan["mp_flags"][pos])
            opt_states.append(tuple(s._jax for s in inner))
            w32s.append(w32._jax if w32 is not None else None)
        residuals = ()
        if plan["exchange"] is not None:
            gc = plan["gc"]
            if plan["exchange"].residual_specs:
                residuals = tuple(
                    gc.peek_residual(wk, shape, dtype)
                    for wk, shape, dtype in
                    plan["exchange"].residual_specs)
        mstate = None
        if self._metric is not None and \
                metric_trace_kernel(self._metric) is not None:
            ds = getattr(self._metric, "_dev_sum", None)
            if ds is None:
                mstate = (jnp.zeros((), jnp.float32),
                          jnp.zeros((), jnp.int32))
            else:
                mstate = (ds, self._metric._dev_inst)
        opt_states = tuple(opt_states)
        w32s = tuple(w32s)
        if plan.get("layout") is not None:
            # defensive re-placement: steady-state buffers are already
            # mesh-resident (adopted at plan time, written back sharded),
            # so these are == checks; only external mutation (set_data,
            # checkpoint restore) between steps pays a device_put here
            t_vals = tuple(_placed(v, s)
                           for v, s in zip(t_vals, plan["shardings"]))
            f_vals = tuple(_placed(v, s)
                           for v, s in zip(f_vals,
                                           plan["frozen_shardings"]))
            opt_states = tuple(
                tuple(_placed(c, cs) for c, cs in zip(cols, css))
                for cols, css in zip(opt_states, plan["state_shardings"]))
            w32s = tuple(_placed(w, s)
                         for w, s in zip(w32s, plan["w32_shardings"]))
            residuals = tuple(
                _placed(r, s)
                for r, s in zip(residuals, plan["residual_shardings"]))
            if mstate is not None:
                mstate = tuple(_placed(m, plan["replicated"])
                               for m in mstate)
        return t_vals, f_vals, opt_states, w32s, residuals, mstate

    def _write_back(self, plan, new_t, new_f, new_states, new_w32,
                    new_res, new_mstate):
        tr = self._trainer
        spec = plan["spec"]
        ctxs = plan["ctxs"]

        def place(val, ctx, d):
            return val if d == 0 else jax.device_put(val, ctx.jax_device)

        for d, ctx in enumerate(ctxs):
            for pos, p in enumerate(plan["trainable"]):
                p._data[ctx]._set_jax(place(new_t[pos], ctx, d))
            for pos, p in enumerate(plan["frozen"]):
                p._data[ctx]._set_jax(place(new_f[pos], ctx, d))
            upd = tr._updaters[d]
            for pos, i in enumerate(plan["trainable_idx"]):
                inner, w32 = spec["unpack"](upd.states[i],
                                            plan["mp_flags"][pos])
                for s_nd, val in zip(inner, new_states[pos]):
                    s_nd._set_jax(place(val, ctx, d).astype(s_nd.dtype))
                if w32 is not None and new_w32[pos] is not None:
                    w32._set_jax(place(new_w32[pos], ctx, d))
        if plan["exchange"] is not None and new_res:
            gc = plan["gc"]
            for (wk, _shape, _dtype), val in zip(
                    plan["exchange"].residual_specs, new_res):
                gc.put_residual(wk, val)
        if new_mstate is not None:
            self._metric._dev_sum, self._metric._dev_inst = new_mstate

    # -- dispatch ----------------------------------------------------------
    def _run(self, plan, n_steps, accum, xs, ys, batch_size, transfers):
        """One window dispatch: xs/ys leaves shaped (n_steps*accum, B,
        ...).  Returns (losses, outs_or_None) as jax arrays."""
        from .engine import engine as _engine
        from . import telemetry as _telemetry
        rescale, wds, lr_rows, decay_rows = self._lr_rows(
            plan, n_steps, batch_size)
        metric_info = metric_trace_kernel(self._metric)
        return_outs = self._metric is not None and metric_info is None
        layout = plan.get("layout")
        key = (n_steps, accum, rescale, wds, plan["clip"],
               None if layout is None else layout.signature(),
               plan["spec"]["kind"],
               tuple(sorted(plan["spec"]["static"].items())),
               plan["mp_flags"],
               tuple((tuple(x.shape), str(x.dtype)) for x in xs),
               (tuple(ys.shape), str(ys.dtype)),
               tuple((p.shape, str(p.dtype)) for p in plan["trainable"]),
               tuple((p.shape, str(p.dtype)) for p in plan["frozen"]),
               tuple((wk, tuple(s), str(jnp.dtype(dt))) for wk, s, dt in
                     (plan["exchange"].residual_specs
                      if plan["exchange"] is not None else ())),
               metric_cache_key(self._metric, metric_info),
               return_outs)
        fn = self._cache.get(key)
        if fn is None:
            # profiler blind spot fix (ISSUE 8): a retrace is the
            # expensive rare event that used to hide inside the first
            # dispatch — it gets its own phase span so hybridize-style
            # recompiles are visible in dumps() and the flight recorder
            with _telemetry.phase("retrace"):
                fn = self._build_fn(plan, n_steps, accum, rescale, wds,
                                    decay_rows is not None, metric_info,
                                    return_outs)
                self._cache[key] = fn
        state = self._gather_state(plan)

        def donatable(a):
            if a is None or id(a) in self._owned:
                return a
            return jnp.array(a, copy=True)   # foreign: may be aliased

        state = tuple(jax.tree_util.tree_map(donatable, s) for s in state)
        rng = _ops_random.next_key()
        if layout is not None:
            # the batch crosses to the mesh sharded over data×fsdp (axis
            # 0 of each micro-batch; axis 1 of stacked window leaves) —
            # the ONE transfer the dispatch budget charges.  rng is a
            # committed single-device jit output: replicate it onto the
            # mesh or the dispatch mixes incompatible device sets.
            bdim = 0 if n_steps * accum == 1 else 1
            xs = tuple(jax.device_put(
                x, layout.sharding(layout.batch_spec_for(x.shape, bdim)))
                for x in xs)
            ys = jax.device_put(
                ys, layout.sharding(layout.batch_spec_for(ys.shape, bdim)))
            rng = jax.device_put(rng, plan["replicated"])
            transfers = max(transfers, 1)
        # distinct span names so scan windows and single compiled steps
        # aggregate separately in profiler.dumps() (the eager-only
        # blind spot this satellite closes)
        span_name = "compiled_step" if n_steps * accum == 1 \
            else "compiled_window"
        with _telemetry.phase(span_name):
            out = fn(*state, lr_rows, decay_rows, rng, xs, ys)
        (new_t, new_f, new_states, new_w32, new_res, new_mstate,
         losses, outs) = out
        self._write_back(plan, new_t, new_f, new_states, new_w32,
                         new_res, new_mstate)
        self._owned_refs = [
            a for a in jax.tree_util.tree_leaves(
                (new_t, new_f, new_states, new_w32, new_res, new_mstate))
            if a is not None]
        self._owned = {id(a) for a in self._owned_refs}
        _engine.count_step_window(n_steps * accum,
                                  dispatches=1 + transfers)
        if plan["exchange"] is not None:
            _engine.count_wire_bytes(
                plan["exchange"].wire_bytes * n_steps)
        _telemetry.note_step(steps=n_steps * accum, batch_size=batch_size,
                             extra={"compiled": True})
        return losses, outs

    def step(self, data, label, batch_size=None):
        """One training step (forward + backward + exchange + update +
        metric) in ONE dispatch; returns the loss (eager shape)."""
        datas = data if isinstance(data, (list, tuple)) else (data,)
        B = int(_as_jax(datas[0]).shape[0])
        batch_size = batch_size or B
        try:
            plan = self._plan()
        except DeferredInitializationError:
            plan = None   # first call finishes deferred init eagerly
        if plan is None:
            return self._eager_step(datas, label, batch_size)
        ctx0 = plan["ctxs"][0]
        xs = tuple(_as_jax(d) for d in datas)
        y = _as_jax(label)
        losses, outs = self._run(plan, 1, 1, xs, y, batch_size,
                                 transfers=0)
        if outs is not None:
            self._metric.update([_as_nd(y, ctx0)],
                                [NDArray(outs[0], ctx=ctx0)])
        return NDArray(losses.reshape(losses.shape[1:]), ctx=ctx0)

    def run_window(self, data, label, batch_size=None, accum=1):
        """N-step scan window: `data` leaves are (n_micro, B, ...) with
        ``n_micro = n_steps * accum`` — every `accum` consecutive
        micro-batches accumulate into one optimizer step.  The whole
        window is ONE device dispatch (plus the batch transfer); returns
        the per-micro-batch losses, shape (n_micro, ...)."""
        datas = data if isinstance(data, (list, tuple)) else (data,)
        accum = max(1, int(accum))
        xs = tuple(_as_jax(d) for d in datas)
        y = _as_jax(label)
        n_micro = int(xs[0].shape[0])
        if n_micro % accum:
            raise MXNetError("run_window: %d micro-batches do not divide "
                             "into accum=%d groups" % (n_micro, accum))
        n_steps = n_micro // accum
        B = int(xs[0].shape[1])
        batch_size = batch_size or B * accum
        try:
            plan = self._plan()
        except DeferredInitializationError:
            plan = None
        if plan is None:
            if accum > 1:
                raise MXNetError(
                    "run_window(accum=%d) has no eager fallback (%s); use "
                    "grad_req='add' accumulation on the eager path"
                    % (accum, self._fallback_reason))
            losses = [self._eager_step(
                tuple(NDArray(x[t], ctx=self._trainer._contexts[0])
                      for x in xs),
                NDArray(y[t], ctx=self._trainer._contexts[0]),
                batch_size).mean()._jax
                for t in range(n_micro)]
            return NDArray(jnp.stack(losses),
                           ctx=self._trainer._contexts[0])
        ctx0 = plan["ctxs"][0]
        losses, outs = self._run(plan, n_steps, accum, xs, y, batch_size,
                                 transfers=1)
        if outs is not None:
            flat = outs.reshape((-1,) + outs.shape[2:])
            self._metric.update(
                [NDArray(y.reshape((-1,) + y.shape[2:]), ctx=ctx0)],
                [NDArray(flat, ctx=ctx0)])
        return NDArray(losses, ctx=ctx0)

    # -- the debug path ----------------------------------------------------
    def _eager_step(self, datas, label, batch_size):
        ctxs = self._trainer._contexts
        if len(ctxs) > 1:
            # classic DP eager loop: the batch splits across the device
            # copies, each runs its own forward/backward, the Trainer's
            # exchange merges — same math the compiled lane traces
            B = int(_as_jax(datas[0]).shape[0])
            per = B // len(ctxs)
            losses, out0, y0 = [], None, None
            with autograd.record():
                for d, ctx in enumerate(ctxs):
                    sl = slice(d * per, (d + 1) * per if
                               d < len(ctxs) - 1 else B)
                    x_nds = [NDArray(jax.device_put(_as_jax(x)[sl],
                                                    ctx.jax_device),
                                     ctx=ctx) for x in datas]
                    y_nd = NDArray(jax.device_put(_as_jax(label)[sl],
                                                  ctx.jax_device), ctx=ctx)
                    out = self._net(*x_nds)
                    loss = self._loss_fn(out, y_nd)
                    loss.backward()
                    losses.append(loss)
                    if d == 0:
                        out0, y0 = out, y_nd
            self._trainer.step(batch_size)
            if self._metric is not None:
                o = out0[0] if isinstance(out0, (list, tuple)) else out0
                self._metric.update([y0], [o])
            return losses[0]
        ctx = ctxs[0]
        x_nds = [_as_nd(d, ctx) for d in datas]
        y_nd = _as_nd(label, ctx)
        with autograd.record():
            out = self._net(*x_nds)
            loss = self._loss_fn(out, y_nd)
        loss.backward()
        self._trainer.step(batch_size)
        if self._metric is not None:
            out0 = out[0] if isinstance(out, (list, tuple)) else out
            self._metric.update([y_nd], [out0])
        return loss


# ---------------------------------------------------------------------------
# Program contracts (ISSUE 11): the whole-step programs' declared
# donation/HBM invariants and window closure.  The builder assembles a
# small canonical model + Trainer (momentum SGD, so real slot state is
# in the donated tree) and hands the verifier the EXACT traced bodies
# `step.step` / `step.window` the runtime registers, with abstract
# (ShapeDtypeStruct) state/batch trees — `python -m tools.mxlint
# --contracts` lowers them device-free and proves all six donated
# state groups alias outputs, the temp footprint fits the declared
# budget, and the window set is trace-closed.
# ---------------------------------------------------------------------------

_CONTRACT_WINDOWS = (1, 4)      # the single step + one scan window
_CONTRACT_BATCH = 8
_CONTRACT_IN = 16


def _contract_step() -> "CompiledStep":
    import mxnet_tpu as mx
    from .gluon import nn, Trainer
    from .gluon.loss import SoftmaxCrossEntropyLoss
    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(32, in_units=_CONTRACT_IN, activation="relu"))
    net.add(nn.Dense(8, in_units=32))
    net.initialize(mx.init.Xavier())
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": 0.1, "momentum": 0.9})
    return CompiledStep(net, SoftmaxCrossEntropyLoss(), trainer)


def _abstract(tree):
    return jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), tree)


def _step_abstract_args(cs, plan, n_steps):
    """The abstract argument tree a window of `n_steps` dispatches with
    — shared by the cases and the closure's resolve, so the closure
    proof checks the SAME signature construction the cases compiled."""
    state = _abstract(cs._gather_state(plan))
    n_params = len(plan["trainable_idx"])
    lr_rows = jax.ShapeDtypeStruct((n_steps, n_params), jnp.float32)
    key = _ops_random.next_key()
    rng = jax.ShapeDtypeStruct(key.shape, key.dtype)
    if n_steps == 1:
        xs = (jax.ShapeDtypeStruct((_CONTRACT_BATCH, _CONTRACT_IN),
                                   jnp.float32),)
        ys = jax.ShapeDtypeStruct((_CONTRACT_BATCH,), jnp.float32)
    else:
        xs = (jax.ShapeDtypeStruct((n_steps, _CONTRACT_BATCH,
                                    _CONTRACT_IN), jnp.float32),)
        ys = jax.ShapeDtypeStruct((n_steps, _CONTRACT_BATCH), jnp.float32)
    return state + (lr_rows, None, rng, xs, ys)


def _step_contract_case(cs, plan, n_steps):
    from .programs import ContractCase
    rescale, wds, _lr_rows, _decays = cs._lr_rows(plan, n_steps,
                                                  _CONTRACT_BATCH)
    fn = cs._build_fn(plan, n_steps, 1, rescale, wds, decays_on=False,
                      metric_info=None, return_outs=False)
    pname = "step.step" if n_steps == 1 else "step.window"
    return ContractCase(pname, _step_abstract_args(cs, plan, n_steps),
                        label="w%d" % n_steps, target=fn)


import functools as _functools


@_functools.lru_cache(maxsize=4)
def _step_contract_built(configured_window: int):
    """Keyed by the CONFIGURED scan window so a long-lived process that
    changes MX_STEP_SCAN between verifies never reuses a closure built
    for the old window set."""
    from .programs import ContractClosure
    cs = _contract_step()
    plan = cs._plan()
    assert plan is not None, cs.fallback_reason
    cases = [_step_contract_case(cs, plan, n) for n in _CONTRACT_WINDOWS]

    # window-set closure: the windows the step lane can actually
    # dispatch are the single step plus the CONFIGURED scan window
    # (MX_STEP_SCAN at verify time) — each must land on a declared
    # case's signature, so an operator config outside the contracted
    # window set fails the static proof instead of retracing at runtime
    points = sorted({1, configured_window} | set(_CONTRACT_WINDOWS))
    closure = ContractClosure(
        points, lambda n: _step_abstract_args(cs, plan, int(n)))
    return cases, closure


def _declare_step_contracts():
    from .programs import declare_contract

    declare_contract(
        "step.train",
        lambda: _step_contract_built(scan_window() or 1)[0],
        donate_argnums=(0, 1, 2, 3, 4, 5),
        temp_budget_bytes=8 << 20,
        closure=lambda: _step_contract_built(scan_window() or 1)[1],
        description="whole-step compiled train programs: params, frozen "
                    "aux, optimizer slots, fp32 masters, EF residuals "
                    "and metric state all donate and write back; the "
                    "batch, lr matrix and rng key survive; trace "
                    "signatures closed over the configured window set")


_declare_step_contracts()


# ---------------------------------------------------------------------------
# Sharded-step contracts (ISSUE 14): the SpecLayout lane's donation/HBM
# proofs over every supported mesh class.  Each class builds the SAME
# canonical model as the replicated contract, lays it out through a
# SpecLayout over a fake mesh (the verifier forces 8 CPU devices, like
# tests/conftest), and lowers the EXACT `step.step` body the runtime
# would dispatch — with the abstract argument tree carrying the REAL
# NamedShardings, so the aliasing proof covers the sharded donation
# (params, slots, masters all sheet-sharded) and the trace-closure
# proves the {dp, dp×fsdp, dp×fsdp×tp} points land on declared
# signatures instead of retracing at runtime.
# ---------------------------------------------------------------------------

# mesh classes the sharded lane contracts: label -> (axes, shape).
# The dp2/dp3/dp4 rows are elastic-resize coverage (ISSUE 16): every
# data-parallel world size a mid-job resize can land on (within the
# 8-device contract mesh) gets its own declared signature, so a job
# that shrinks 4->3 or grows 2->4 dispatches onto a contracted program
# instead of retracing where the closure proof promised none.
_SHARD_MESH_CLASSES = (
    ("dp", ("data",), (8,)),
    ("dp2", ("data",), (2,)),
    ("dp3", ("data",), (3,)),
    ("dp4", ("data",), (4,)),
    ("dp_fsdp", ("data", "fsdp"), (4, 2)),
    ("dp_fsdp_tp", ("data", "fsdp", "tp"), (2, 2, 2)),
)


def _abstract_sharded(tree):
    """Like :func:`_abstract` but KEEPING each leaf's sharding — the
    sharded cases must lower with the placements the runtime uses, or
    the donation/temp proofs describe a program that never ships."""
    return jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                       sharding=getattr(a, "sharding",
                                                        None)),
        tree)


def _contract_sharded_step(axes, shape) -> "CompiledStep":
    import mxnet_tpu as mx
    from .gluon import Trainer
    from .parallel.mesh import make_mesh
    from .parallel.speclayout import SpecLayout
    need = 1
    for s in shape:
        need *= int(s)
    devs = jax.devices()
    if len(devs) < need:
        raise RuntimeError(
            "sharded step contract needs %d devices (have %d); run under "
            "the contracts CLI or tests/conftest, which force an 8-device "
            "CPU mesh" % (need, len(devs)))
    mesh = make_mesh(axes=axes, shape=shape, devices=devs[:need])
    cs = _contract_step()
    cs._layout = SpecLayout.infer(mesh)
    return cs


def _sharded_case(label, axes, shape):
    from .programs import ContractCase
    cs = _contract_sharded_step(axes, shape)
    plan = cs._plan()
    assert plan is not None, cs.fallback_reason
    rescale, wds, _lr, _dec = cs._lr_rows(plan, 1, _CONTRACT_BATCH)
    fn = cs._build_fn(plan, 1, 1, rescale, wds, decays_on=False,
                      metric_info=None, return_outs=False)
    args = _sharded_abstract_args(cs, plan)
    return ContractCase("step.step", args, label=label, target=fn)


def _sharded_abstract_args(cs, plan):
    layout = plan["layout"]
    state = _abstract_sharded(cs._gather_state(plan))
    n_params = len(plan["trainable_idx"])
    lr_rows = jax.ShapeDtypeStruct((1, n_params), jnp.float32)
    key = _ops_random.next_key()
    rng = jax.ShapeDtypeStruct(key.shape, key.dtype,
                               sharding=plan["replicated"])
    xs_shape = (_CONTRACT_BATCH, _CONTRACT_IN)
    ys_shape = (_CONTRACT_BATCH,)
    xs = (jax.ShapeDtypeStruct(
        xs_shape, jnp.float32,
        sharding=layout.sharding(layout.batch_spec_for(xs_shape, 0))),)
    ys = jax.ShapeDtypeStruct(
        ys_shape, jnp.float32,
        sharding=layout.sharding(layout.batch_spec_for(ys_shape, 0)))
    return state + (lr_rows, None, rng, xs, ys)


@_functools.lru_cache(maxsize=1)
def _sharded_contract_built():
    from .programs import ContractClosure
    cases = {}
    for label, axes, shape in _SHARD_MESH_CLASSES:
        cases[label] = _sharded_case(label, axes, shape)

    def resolve(label):
        # re-derive the dispatch signature from the runtime's own state
        # construction for that mesh class — a drift between what the
        # lane dispatches and what the cases compiled is a closure miss
        for lbl, axes, shape in _SHARD_MESH_CLASSES:
            if lbl == label:
                cs = _contract_sharded_step(axes, shape)
                plan = cs._plan()
                return _sharded_abstract_args(cs, plan)
        return None

    closure = ContractClosure([lbl for lbl, _a, _s in
                               _SHARD_MESH_CLASSES], resolve)
    return list(cases.values()), closure


def _declare_sharded_step_contracts():
    from .programs import declare_contract
    declare_contract(
        "step.train_sharded",
        lambda: _sharded_contract_built()[0],
        donate_argnums=(0, 1, 2, 3, 4, 5),
        # per-mesh-class ceiling: the sharded step's temp footprint must
        # not exceed the replicated budget — reduce-scatter/all-gather
        # staging is transient and bounded by the gathered param bytes
        temp_budget_bytes=8 << 20,
        closure=lambda: _sharded_contract_built()[1],
        description="SpecLayout sharded step programs: the same six "
                    "donated state groups as step.train, sheet-/tensor-"
                    "sharded over the mesh; donation aliasing must "
                    "survive sharding, and the {dp, dp2, dp3, dp4, "
                    "dp×fsdp, dp×fsdp×tp} mesh classes — including "
                    "every data-parallel size an elastic resize can "
                    "reach — are trace-closed")


_declare_sharded_step_contracts()
