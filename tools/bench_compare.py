#!/usr/bin/env python
"""bench_compare.py — the bench-trajectory regression sentinel (ISSUE 10).

Reads one ``bench.py`` JSON report (file argument, or ``-``/stdin for a
pipe: ``python bench.py --eager | python tools/bench_compare.py -``),
appends a compact record — throughput, total compile seconds, peak temp
bytes, retrace count, device — to the rolling history file
(``MX_BENCH_HISTORY``, default ``BENCH_HISTORY.jsonl`` next to bench.py)
and exits non-zero when the run regresses vs the rolling best *for the
same metric on the same device class*:

  * throughput  more than ``--throughput-tol`` (default 10%) below the
    best recorded value, or
  * memory      peak temp bytes more than ``--memory-tol`` (default 15%)
    above the best (smallest) recorded footprint.

The first run of a metric seeds the history and always passes.  Records
whose report carries no census block (e.g. a replayed TPU capture) gate
on throughput only.

``--inject-slowdown F`` divides the measured throughput by F before
gating and skips the history append — the synthetic-regression hook the
acceptance test drives (a 2x injected slowdown must exit non-zero while
the real run passes).

``--check-schema`` validates every history line parses and carries the
required fields (tools/lint.sh runs this), exit 0 on an empty/missing
history.
"""
import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

REQUIRED_FIELDS = ("ts", "metric", "value", "unit")
NUMERIC_FIELDS = ("ts", "value")

# program-contract manifest (ISSUE 11): tools/mxlint/contracts.json,
# written by `python -m tools.mxlint --contracts --write-manifest`.
# Version must track mxnet_tpu.programs.CONTRACT_SCHEMA (this tool
# stays jax-free, so the value is pinned here; tests/test_contracts.py
# asserts the two constants agree).
CONTRACT_SCHEMA = 1
CONTRACT_MANIFEST = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "mxlint",
    "contracts.json")
CONTRACT_FIELDS = ("name", "donate_argnums", "temp_budget_bytes")
# each program row carries a `cases` list (one entry per lowering —
# e.g. fused_adam's plain AND mp cases); every case needs these
CONTRACT_PROGRAM_FIELDS = ("program", "cases")
CONTRACT_CASE_FIELDS = ("program", "label", "donated_expected",
                        "aliased", "temp_bytes", "budget")


def _base_mod():
    """mxnet_tpu.base loaded standalone (it only needs os/threading):
    importing the package would drag jax into a CLI that reads one env
    var — the sentinel must stay instant in CI loops."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "mx_base_bench_compare", os.path.join(REPO, "mxnet_tpu",
                                              "base.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def history_path() -> str:
    p = _base_mod().get_env("MX_BENCH_HISTORY", "") or ""
    return p or os.path.join(REPO, "BENCH_HISTORY.jsonl")


def load_history(path):
    """[(lineno, record)] of parseable lines; ValueError lines reported
    by check_schema, skipped (with a warning) by the gate."""
    if not os.path.exists(path):
        return []
    out = []
    with open(path) as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                out.append((i, json.loads(line)))
            except ValueError:
                out.append((i, None))
    return out


def check_schema(path) -> int:
    bad = []
    for lineno, rec in load_history(path):
        if rec is None:
            bad.append((lineno, "unparseable JSON"))
            continue
        if not isinstance(rec, dict):
            bad.append((lineno, "not an object"))
            continue
        for field in REQUIRED_FIELDS:
            if field not in rec:
                bad.append((lineno, "missing field %r" % field))
        for field in NUMERIC_FIELDS:
            if field in rec and not isinstance(rec[field], (int, float)):
                bad.append((lineno, "field %r not numeric" % field))
    if bad:
        for lineno, why in bad:
            print("bench_compare: %s:%d: %s" % (path, lineno, why),
                  file=sys.stderr)
        return 1
    rc = check_contract_manifest(CONTRACT_MANIFEST)
    if rc:
        return rc
    print("bench_compare: schema OK (%d records in %s)"
          % (len(load_history(path)), path))
    return 0


def check_contract_manifest(path) -> int:
    """Validate the checked-in program-contract manifest (absent is OK —
    the contracts lane may not have been run on this checkout)."""
    if not os.path.exists(path):
        return 0
    try:
        with open(path) as f:
            doc = json.load(f)
    except ValueError as e:
        print("bench_compare: %s: unparseable contract manifest: %s"
              % (path, e), file=sys.stderr)
        return 1
    if not isinstance(doc, dict):
        print("bench_compare: %s: contract manifest is not an object"
              % path, file=sys.stderr)
        return 1
    bad = []
    progs = doc.get("programs")
    if progs is not None and not isinstance(progs, dict):
        bad.append("'programs' is not an object")
        doc = dict(doc, programs={})
    if doc.get("schema") != CONTRACT_SCHEMA:
        bad.append("contract schema %r != expected %d (regenerate with "
                   "python -m tools.mxlint --contracts --write-manifest, "
                   "or bump CONTRACT_SCHEMA in both places)"
                   % (doc.get("schema"), CONTRACT_SCHEMA))
    declared = doc.get("contracts", [])
    if not isinstance(declared, list):
        bad.append("'contracts' is not a list")
        declared = []
    for entry in declared:
        if not isinstance(entry, dict):
            # type corruption must be a finding, not a TypeError
            bad.append("contract entry %r is not an object" % (entry,))
            continue
        for field in CONTRACT_FIELDS:
            if field not in entry:
                bad.append("contract entry %r missing field %r"
                           % (entry.get("name", "?"), field))
    for pname, row in (doc.get("programs") or {}).items():
        if not isinstance(row, dict):
            # type corruption must be a finding, not a TypeError
            bad.append("program row %r is not an object" % pname)
            continue
        for field in CONTRACT_PROGRAM_FIELDS:
            if field not in row:
                bad.append("program row %r missing field %r"
                           % (pname, field))
        cases = row.get("cases") or []
        if not isinstance(cases, list):
            bad.append("program %r 'cases' is not a list" % pname)
            cases = []
        for case in cases:
            if not isinstance(case, dict):
                bad.append("program %r has a non-object case" % pname)
                continue
            for field in CONTRACT_CASE_FIELDS:
                if field not in case:
                    bad.append("program %r case %r missing field %r"
                               % (pname, case.get("label", "?"), field))
    if bad:
        for why in bad:
            print("bench_compare: %s: %s" % (path, why), file=sys.stderr)
        return 1
    print("bench_compare: contract manifest OK (%d contracts, %d "
          "programs, schema %d)"
          % (len(doc.get("contracts", [])),
             len(doc.get("programs") or {}), CONTRACT_SCHEMA))
    return 0


def extract_record(report: dict) -> dict:
    """Compact history record from one bench.py report."""
    import platform
    rec = {
        "ts": time.time(),
        "metric": str(report.get("metric", "unknown")),
        "value": float(report.get("value", 0.0)),
        "unit": str(report.get("unit", "")),
        "device": str(report.get("device", "")),
        # absolute throughput is machine-relative: records gate only
        # against the rolling best measured on the SAME host, so a
        # committed history never fails a slower developer box
        "host": platform.node(),
    }
    census = report.get("census") or {}
    summary = census.get("summary") or {}
    if summary:
        rec["compile_seconds_total"] = summary.get("compile_seconds_total")
        rec["peak_temp_bytes"] = summary.get("peak_temp_bytes")
        rec["retraces"] = summary.get("retraces")
        rec["programs"] = summary.get("programs")
        if summary.get("cache_hits") is not None:
            rec["cache_hits"] = summary.get("cache_hits")
    # ISSUE 13: retrace budget + warm-start + input-pipeline series
    if "retrace_budget" in report:
        rec["retrace_budget"] = report.get("retrace_budget")
        rec["retraces_over_budget"] = bool(
            report.get("retraces_over_budget"))
    if "warm_spawn_seconds" in report:
        rec["warm_spawn_seconds"] = report.get("warm_spawn_seconds")
        rec["cold_spawn_seconds"] = report.get("cold_spawn_seconds")
    prefetch = report.get("prefetch") or {}
    if prefetch:
        rec["data_wait_share_pct"] = prefetch.get("data_wait_share_pct")
        rec["prefetch_enabled"] = bool(prefetch.get("enabled"))
    # ISSUE 15: decode-lane gated series — the continuous-vs-request
    # speedup is an ABSOLUTE acceptance (>= 2x), and flat-KV/zero-
    # retrace are invariants, not trajectories
    dec = report.get("decode") or {}
    if dec:
        rec["decode_speedup"] = dec.get("continuous_speedup")
        rec["decode_speedup_ok"] = bool(dec.get("speedup_ok"))
        rec["decode_kv_pool_flat"] = bool(dec.get("kv_pool_flat"))
        rec["decode_zero_retraces"] = bool(
            dec.get("zero_serve_time_retraces"))
        # ISSUE 18: paged-KV gated series — parity/flat-heap/zero-
        # retrace are invariants, the shared-prefix first-token drop
        # (>= 5x) and equal-HBM admission width (>= 4x) are ABSOLUTE
        # acceptances, not trajectories
        paged = dec.get("paged") or {}
        if paged:
            rec["decode_paged_parity_ok"] = bool(
                paged.get("parity_with_flat"))
            rec["decode_paged_kv_flat"] = bool(paged.get("kv_pool_flat"))
            rec["decode_paged_zero_retraces"] = bool(
                paged.get("zero_retraces"))
        sp = dec.get("shared_prefix") or {}
        if sp:
            rec["decode_shared_prefix_speedup"] = \
                sp.get("first_token_speedup")
            rec["decode_shared_prefix_ok"] = bool(sp.get("speedup_ok"))
        adm = dec.get("admission") or {}
        if adm:
            rec["decode_admission_ratio"] = adm.get("capacity_ratio")
            rec["decode_admission_ok"] = bool(adm.get("ok"))
        # ISSUE 20: speculative-decode gated series — the request-level
        # speedup over the plain paged engine is an ABSOLUTE acceptance
        # (>= 2x on the draft-friendly demo LM), and token parity /
        # flat-heap / zero-retrace are invariants, not trajectories
        spec = dec.get("speculative") or {}
        if spec:
            rec["decode_spec_speedup"] = spec.get("request_speedup")
            rec["decode_spec_ok"] = bool(spec.get("speedup_ok"))
            rec["decode_spec_parity_ok"] = bool(spec.get("parity"))
            rec["decode_spec_kv_flat"] = bool(spec.get("kv_pool_flat"))
            rec["decode_spec_zero_retraces"] = bool(
                spec.get("zero_retraces"))
    # ISSUE 17: routed-lane gated series — the session router's
    # forwarding tax is an ABSOLUTE acceptance (routed p50 AND p99
    # within 10% of direct-to-replica, or the ADDED latency under the
    # probe's flat ms floor), not a trajectory
    routed = report.get("routed") or {}
    if routed:
        rec["routed_p50_overhead_pct"] = routed.get("p50_overhead_pct")
        rec["routed_p99_overhead_pct"] = routed.get("p99_overhead_pct")
        rec["routed_within_gate"] = bool(routed.get("within_gate"))
    # ISSUE 16: hierarchical-exchange gated series — the two-tier
    # cross-slice byte reduction is an ABSOLUTE acceptance (the
    # promoted int8 return leg must move fewer bytes than the flat
    # exchange), not a trajectory
    if report.get("metric") == "kvstore_hierarchical_cross_slice_bytes":
        rec["hier_cross_slice_reduction"] = report.get(
            "cross_slice_reduction")
        rec["hier_fewer_bytes_ok"] = bool(report.get("ok"))
    # ISSUE 14: sharded-lane per-chip state bytes, keyed by mesh class
    # (gating compares only within one mesh topology — a dp,fsdp=2 run
    # must never become the bar a dp,fsdp=4 run is held to)
    sharded = report.get("sharded") or {}
    if sharded:
        rec["params_bytes_per_chip"] = sharded.get("params_bytes_per_chip")
        rec["optimizer_bytes_per_chip"] = \
            sharded.get("optimizer_bytes_per_chip")
        rec["mesh_class"] = sharded.get("mesh_class")
        rec["sharded_within_ideal"] = bool(
            sharded.get("within_15pct_of_ideal"))
    return rec


def gate(rec, history, throughput_tol, memory_tol):
    """(ok, findings): compare `rec` against the rolling best of the
    same (metric, device) records."""
    peers = [r for _, r in history
             if isinstance(r, dict)
             and r.get("metric") == rec["metric"]
             and r.get("device", "") == rec["device"]
             and r.get("host", "") == rec.get("host", "")
             and isinstance(r.get("value"), (int, float))]
    findings = []
    if not peers:
        findings.append(
            "first record for %r on %r@%s: seeding history"
            % (rec["metric"], rec["device"] or "default",
               rec.get("host", "?")))
        # absolute acceptances gate even a seeding record — a first
        # run that violates its invariant must fail, not set the bar
        if "hier_fewer_bytes_ok" in rec and \
                not rec["hier_fewer_bytes_ok"]:
            findings.append(
                "HIERARCHICAL-EXCHANGE REGRESSION: two-tier exchange "
                "moved no fewer cross-slice wire bytes than the flat "
                "int8 exchange (reduction %s <= 1x)"
                % rec.get("hier_cross_slice_reduction"))
            return False, findings
        if "routed_within_gate" in rec and \
                not rec["routed_within_gate"]:
            findings.append(
                "ROUTED-OVERHEAD REGRESSION: p50 %s%% / p99 %s%% "
                "through the session router exceed the 10%% gate over "
                "direct-to-replica (and the added ms floor)"
                % (rec.get("routed_p50_overhead_pct"),
                   rec.get("routed_p99_overhead_pct")))
            return False, findings
        if "decode_spec_speedup" in rec and (
                not rec.get("decode_spec_ok")
                or not rec.get("decode_spec_parity_ok")
                or not rec.get("decode_spec_kv_flat")
                or not rec.get("decode_spec_zero_retraces")):
            findings.append(
                "SPECULATIVE REGRESSION: request-level speedup %s "
                "below the 2x acceptance floor, or token parity / "
                "flat-heap / zero-retrace invariants broken"
                % rec.get("decode_spec_speedup"))
            return False, findings
        return True, findings
    # Throughput gates within the record's own lane CLASS: same input-
    # pipeline mode (a prefetch-off run pays data_wait the prefetched
    # best never did; legacy rows predate the input stream entirely)
    # and same warmth (a cold-cache process absorbs its first-dispatch
    # stragglers inside the timed loop; a warm one does not).  Each
    # class keeps its own rolling best — cross-class comparison would
    # fail honest runs for configuration, not regression.
    def _thr_class(r):
        return (r.get("prefetch_enabled"), bool(r.get("cache_hits")))

    thr_peers = [r for r in peers if _thr_class(r) == _thr_class(rec)]
    if not thr_peers:
        findings.append(
            "first %r record of its pipeline/warmth class: seeding "
            "throughput trajectory" % rec["metric"])
    best_value = max(r["value"] for r in thr_peers) if thr_peers else 0.0
    ok = True
    if best_value > 0:
        floor = best_value * (1.0 - throughput_tol)
        if rec["value"] < floor:
            ok = False
            findings.append(
                "THROUGHPUT REGRESSION: %.4g %s < %.4g (best %.4g "
                "- %d%% tolerance)" % (
                    rec["value"], rec["unit"], floor, best_value,
                    round(throughput_tol * 100)))
        else:
            findings.append(
                "throughput %.4g %s within %d%% of best %.4g"
                % (rec["value"], rec["unit"],
                   round(throughput_tol * 100), best_value))
    mem = rec.get("peak_temp_bytes")
    mem_peers = [r["peak_temp_bytes"] for r in peers
                 if isinstance(r.get("peak_temp_bytes"), (int, float))
                 and r["peak_temp_bytes"] > 0]
    if isinstance(mem, (int, float)) and mem > 0 and mem_peers:
        best_mem = min(mem_peers)
        ceil = best_mem * (1.0 + memory_tol)
        if mem > ceil:
            ok = False
            findings.append(
                "MEMORY REGRESSION: peak temp bytes %d > %d (best %d "
                "+ %d%% tolerance)" % (mem, int(ceil), int(best_mem),
                                       round(memory_tol * 100)))
        else:
            findings.append(
                "peak temp bytes %d within %d%% of best %d"
                % (mem, round(memory_tol * 100), int(best_mem)))
    # ISSUE 15 gated series: the decode lane's acceptance invariants
    if "decode_speedup" in rec:
        if not rec.get("decode_speedup_ok"):
            ok = False
            findings.append(
                "DECODE-BATCHING REGRESSION: continuous-vs-request "
                "speedup %s < the 2x acceptance floor"
                % rec.get("decode_speedup"))
        else:
            findings.append("decode continuous speedup %sx >= 2x"
                            % rec.get("decode_speedup"))
        if not rec.get("decode_kv_pool_flat"):
            ok = False
            findings.append(
                "DECODE KV-POOL LEAK: pool bytes grew across the "
                "bench run (donation broke — HBM would creep on TPU)")
        if not rec.get("decode_zero_retraces"):
            ok = False
            findings.append(
                "DECODE RETRACE REGRESSION: serve-time retraces "
                "after warmup (the bucket tables must be closed)")
    # ISSUE 18 gated series: the paged-KV engine's acceptance invariants
    if "decode_paged_parity_ok" in rec:
        if not rec.get("decode_paged_parity_ok"):
            ok = False
            findings.append(
                "PAGED DECODE PARITY BROKEN: paged tokens diverged "
                "from the flat continuous lane on the same workload")
        if not rec.get("decode_paged_kv_flat"):
            ok = False
            findings.append(
                "PAGED KV-HEAP LEAK: page-heap bytes grew across the "
                "bench run (heap donation broke — HBM would creep)")
        if not rec.get("decode_paged_zero_retraces"):
            ok = False
            findings.append(
                "PAGED RETRACE REGRESSION: serve-time retraces after "
                "warmup (the chunk/step program tables must be closed)")
    if "decode_shared_prefix_speedup" in rec:
        if not rec.get("decode_shared_prefix_ok"):
            ok = False
            findings.append(
                "SHARED-PREFIX REGRESSION: repeat first-token speedup "
                "%s < the 5x acceptance floor (or tokens diverged)"
                % rec.get("decode_shared_prefix_speedup"))
        else:
            findings.append(
                "shared-prefix first-token speedup %sx >= 5x"
                % rec.get("decode_shared_prefix_speedup"))
    if "decode_admission_ratio" in rec:
        if not rec.get("decode_admission_ok"):
            ok = False
            findings.append(
                "PAGED ADMISSION REGRESSION: equal-HBM concurrent "
                "sessions %sx < the 4x acceptance floor (or pools "
                "were not byte-identical)"
                % rec.get("decode_admission_ratio"))
        else:
            findings.append(
                "paged admission %sx wider than flat at equal KV HBM"
                % rec.get("decode_admission_ratio"))
    # ISSUE 20 gated series: speculative decode's acceptance invariants
    if "decode_spec_speedup" in rec:
        if not rec.get("decode_spec_parity_ok"):
            ok = False
            findings.append(
                "SPECULATIVE PARITY BROKEN: speculative tokens "
                "diverged from the plain paged greedy lane (accept/"
                "verify must be bit-exact regardless of draft quality)")
        if not rec.get("decode_spec_ok"):
            ok = False
            findings.append(
                "SPECULATIVE REGRESSION: request-level speedup %s < "
                "the 2x acceptance floor on the draft-friendly demo LM"
                % rec.get("decode_spec_speedup"))
        else:
            findings.append(
                "speculative request-level speedup %sx >= 2x"
                % rec.get("decode_spec_speedup"))
        if not rec.get("decode_spec_kv_flat"):
            ok = False
            findings.append(
                "SPECULATIVE KV LEAK: target heap or draft pool bytes "
                "grew across the bench run (window donation broke)")
        if not rec.get("decode_spec_zero_retraces"):
            ok = False
            findings.append(
                "SPECULATIVE RETRACE REGRESSION: serve-time retraces "
                "after warmup (draft/verify bucket tables must be "
                "closed over k and the slot buckets)")
    # ISSUE 17 gated series: the session router's forwarding tax
    if "routed_within_gate" in rec:
        if not rec["routed_within_gate"]:
            ok = False
            findings.append(
                "ROUTED-OVERHEAD REGRESSION: p50 %s%% / p99 %s%% "
                "through the session router exceed the 10%% gate over "
                "direct-to-replica (and the added ms floor)"
                % (rec.get("routed_p50_overhead_pct"),
                   rec.get("routed_p99_overhead_pct")))
        else:
            findings.append(
                "routed overhead p50 %s%% / p99 %s%% within the gate"
                % (rec.get("routed_p50_overhead_pct"),
                   rec.get("routed_p99_overhead_pct")))
    # ISSUE 16 gated series: the hierarchical exchange's acceptance —
    # two-tier must beat flat dist_async on cross-slice wire bytes
    if "hier_fewer_bytes_ok" in rec:
        if not rec["hier_fewer_bytes_ok"]:
            ok = False
            findings.append(
                "HIERARCHICAL-EXCHANGE REGRESSION: two-tier exchange "
                "moved no fewer cross-slice wire bytes than the flat "
                "int8 exchange (reduction %s <= 1x)"
                % rec.get("hier_cross_slice_reduction"))
        else:
            findings.append(
                "hierarchical exchange cross-slice reduction %sx > 1x"
                % rec.get("hier_cross_slice_reduction"))
    # ISSUE 13 gated series: the retrace budget only ever goes down
    if rec.get("retraces_over_budget"):
        ok = False
        findings.append(
            "RETRACE BUDGET EXCEEDED: %s retraces > budget %s"
            % (rec.get("retraces"), rec.get("retrace_budget")))
    # compile wall-time is its own trajectory: a warm (cache-hit) run's
    # sub-second total must never become the bar a cold run is held to,
    # so records gate only against peers of the same warmth class
    comp = rec.get("compile_seconds_total")
    if isinstance(comp, (int, float)) and comp > 0:
        warm_class = bool(rec.get("cache_hits"))
        comp_peers = [r["compile_seconds_total"] for r in peers
                      if isinstance(r.get("compile_seconds_total"),
                                    (int, float))
                      and r["compile_seconds_total"] > 0
                      and bool(r.get("cache_hits")) == warm_class]
        if comp_peers:
            best_comp = min(comp_peers)
            ceil_c = best_comp * (1.0 + throughput_tol)
            if comp > ceil_c:
                ok = False
                findings.append(
                    "COMPILE-TIME REGRESSION: %.3fs > %.3fs (best "
                    "%s-class %.3fs + %d%% tolerance)"
                    % (comp, ceil_c,
                       "warm" if warm_class else "cold", best_comp,
                       round(throughput_tol * 100)))
            else:
                findings.append(
                    "compile seconds %.3f within %d%% of best %s-class "
                    "%.3f" % (comp, round(throughput_tol * 100),
                              "warm" if warm_class else "cold",
                              best_comp))
    # ISSUE 14: per-chip sharded state bytes — mesh-class-keyed (like
    # the warmth classes): gate against the best (smallest) per-chip
    # footprint recorded for the SAME mesh topology, and fail outright
    # when the lane reports the fsdp drop fell outside 15% of ideal
    pbc = rec.get("params_bytes_per_chip")
    if isinstance(pbc, (int, float)) and pbc > 0:
        if rec.get("sharded_within_ideal") is False:
            ok = False
            findings.append(
                "SHARDED-STATE REGRESSION: per-chip params+optimizer "
                "bytes fell outside 15%% of the ideal 1/fsdp drop "
                "(mesh class %s)" % rec.get("mesh_class"))
        opt_b = rec.get("optimizer_bytes_per_chip") or 0
        total = pbc + (opt_b if isinstance(opt_b, (int, float)) else 0)
        pbc_peers = [
            (r["params_bytes_per_chip"] +
             (r.get("optimizer_bytes_per_chip") or 0))
            for r in peers
            if r.get("mesh_class") == rec.get("mesh_class")
            and isinstance(r.get("params_bytes_per_chip"), (int, float))
            and r["params_bytes_per_chip"] > 0]
        if not pbc_peers:
            findings.append(
                "first sharded record for mesh class %r: seeding "
                "params_bytes_per_chip trajectory" % rec.get("mesh_class"))
        else:
            best_pbc = min(pbc_peers)
            ceil_p = best_pbc * (1.0 + memory_tol)
            if total > ceil_p:
                ok = False
                findings.append(
                    "SHARDED-STATE REGRESSION: per-chip params+optimizer "
                    "bytes %d > %d (best %d + %d%% tolerance, mesh class "
                    "%s)" % (total, int(ceil_p), int(best_pbc),
                             round(memory_tol * 100),
                             rec.get("mesh_class")))
            else:
                findings.append(
                    "per-chip sharded state %d within %d%% of best %d "
                    "(mesh class %s)"
                    % (total, round(memory_tol * 100), int(best_pbc),
                       rec.get("mesh_class")))
    # warm-spawn trajectory: the ready-to-traffic seconds themselves
    # (the speedup ratio already gates as this metric's value)
    wsp = rec.get("warm_spawn_seconds")
    if isinstance(wsp, (int, float)) and wsp > 0:
        wsp_peers = [r["warm_spawn_seconds"] for r in peers
                     if isinstance(r.get("warm_spawn_seconds"),
                                   (int, float))
                     and r["warm_spawn_seconds"] > 0]
        if wsp_peers:
            best_wsp = min(wsp_peers)
            ceil_w = best_wsp * (1.0 + throughput_tol)
            if wsp > ceil_w:
                ok = False
                findings.append(
                    "WARM-SPAWN REGRESSION: %.3fs ready-to-traffic > "
                    "%.3fs (best %.3fs + %d%% tolerance)"
                    % (wsp, ceil_w, best_wsp,
                       round(throughput_tol * 100)))
            else:
                findings.append(
                    "warm spawn %.3fs within %d%% of best %.3fs"
                    % (wsp, round(throughput_tol * 100), best_wsp))
    return ok, findings


def append_record(path, rec) -> None:
    d = os.path.dirname(os.path.abspath(path))
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "a") as f:
        f.write(json.dumps(rec, sort_keys=True) + "\n")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("report", nargs="?", default="-",
                    help="bench.py JSON report file, or '-' for stdin")
    ap.add_argument("--history", default=None,
                    help="history file (default MX_BENCH_HISTORY or "
                         "BENCH_HISTORY.jsonl next to bench.py)")
    ap.add_argument("--throughput-tol", type=float, default=0.10)
    ap.add_argument("--memory-tol", type=float, default=0.15)
    ap.add_argument("--no-append", action="store_true",
                    help="gate only; do not record this run")
    ap.add_argument("--inject-slowdown", type=float, default=None,
                    help="divide throughput by F before gating "
                         "(synthetic-regression self-test; implies "
                         "--no-append)")
    ap.add_argument("--check-schema", action="store_true",
                    help="validate the history file and exit")
    args = ap.parse_args(argv)

    path = args.history or history_path()
    if args.check_schema:
        return check_schema(path)

    if args.report == "-":
        raw = sys.stdin.read()
    else:
        with open(args.report) as f:
            raw = f.read()
    # bench.py children may print diagnostics; the report is the last
    # JSON object line
    report = None
    for line in raw.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                report = json.loads(line)
            except ValueError:
                continue
    if report is None:
        print("bench_compare: no JSON report found in input",
              file=sys.stderr)
        return 2

    rec = extract_record(report)
    injected = None
    if args.inject_slowdown:
        injected = float(args.inject_slowdown)
        rec["value"] = rec["value"] / injected
        rec["injected_slowdown"] = injected

    history = load_history(path)
    bad = sum(1 for _, r in history if r is None)
    if bad:
        print("bench_compare: warning: %d unparseable history line(s) "
              "skipped (run --check-schema)" % bad, file=sys.stderr)
    ok, findings = gate(rec, history, args.throughput_tol,
                        args.memory_tol)
    # EVERY real run lands in the trajectory, regressions included
    # (marked ok=false) — a week of failing runs must be visible in the
    # history, and the gate compares against the rolling BEST, so a
    # failing record can never lower the bar
    rec["ok"] = ok
    if not args.no_append and injected is None:
        append_record(path, rec)
    print(json.dumps({
        "ok": ok,
        "record": rec,
        "history": path,
        "history_records": sum(1 for _, r in history if r is not None),
        "findings": findings,
    }, sort_keys=True))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
