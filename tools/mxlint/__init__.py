"""mxlint — a TPU-invariant static analyzer for this repo.

The last three PRs bought hard-won performance/correctness invariants
(O(1) dispatches per training step, no per-batch host sync, virtual-clock
fault paths, a documented ``MX_*`` env surface); mxlint is the machine
check that keeps them true.  Pure stdlib ``ast`` — no third-party deps,
no imports of the code under analysis — so it runs anywhere the repo
checks out, including inside the tier-1 pytest lane
(``tests/test_mxlint.py``).

Usage::

    python -m tools.mxlint                # mxnet_tpu/ + tools/launch.py
    python -m tools.mxlint --jobs 4       # parallel file parse
    python -m tools.mxlint --format json  # stable schema + lock graph
    python -m tools.mxlint --write-baseline
    python -m tools.mxlint --list-rules

Suppression: append ``# mxlint: disable=<rule-id>[,<rule-id>...]`` to the
flagged line (or ``disable=all``).  Grandfathered violations live in
``tools/mxlint/baseline.json`` (see ``--write-baseline``; concurrency
entries need a ``why`` justification); the tier-1 test fails on any NEW
violation.

Per-file rules (``tools/mxlint/rules.py``; docs/ARCHITECTURE.md
"Enforced invariants"):

  host-sync-in-hot-path    device->host syncs reachable from Trainer.step /
                           Module.update / metric update (ISSUE 3)
  jit-purity               side effects inside jitted / registered kernels
  wall-clock-in-fault-path raw time.* in fault.py / health.py / kvstore/*
                           that must use the injectable clock (ISSUE 1)
  env-var-registry         ad-hoc MX_* env reads bypassing base.get_env or
                           missing from base.ENV_CATALOG / docs/ENV_VARS.md
  donation-after-use       buffers donated to a donate_argnums jit and
                           referenced afterwards

Whole-program concurrency rules (``tools/mxlint/project.py``; ISSUE 6 —
thread roots = Thread targets, socketserver handlers, executor
submit/map targets, ``_grad_hook`` overlap callbacks):

  unguarded-shared-write   attribute written lock-free while another
                           thread root reads/writes it (anchored on the
                           write site; peer may be in another file)
  inconsistent-guard       racing accesses hold disjoint lock sets
  lock-order-cycle         the static lock-acquisition graph has a cycle
  blocking-wait-unbounded  timeout-less Event.wait/Condition.wait/
                           Lock.acquire/proc.wait in fault / kvstore /
                           health / launch paths
  thread-leak              non-daemon thread without join or stop event
"""
from .core import (Diagnostic, FileContext, Rule, RULES, register_rule,
                   lint_source, lint_sources, lint_paths, load_baseline,
                   load_baseline_whys, write_baseline, collect_env_reads,
                   load_catalog_names)
from . import rules as _rules  # noqa: F401  (registers the rule set)
from . import project as _project_rules  # noqa: F401  (concurrency rules)
from .project import ProjectIndex, summarize_source

__all__ = ["Diagnostic", "FileContext", "Rule", "RULES", "register_rule",
           "lint_source", "lint_sources", "lint_paths", "load_baseline",
           "load_baseline_whys", "write_baseline", "collect_env_reads",
           "load_catalog_names", "ProjectIndex", "summarize_source"]

__version__ = "2.0"
