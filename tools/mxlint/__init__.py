"""mxlint — a TPU-invariant static analyzer for this repo.

The last three PRs bought hard-won performance/correctness invariants
(O(1) dispatches per training step, no per-batch host sync, virtual-clock
fault paths, a documented ``MX_*`` env surface); mxlint is the machine
check that keeps them true.  Pure stdlib ``ast`` — no third-party deps,
no imports of the code under analysis — so it runs anywhere the repo
checks out, including inside the tier-1 pytest lane
(``tests/test_mxlint.py``).

Usage::

    python -m tools.mxlint mxnet_tpu/              # lint, exit 1 on hits
    python -m tools.mxlint --format json mxnet_tpu/
    python -m tools.mxlint --write-baseline mxnet_tpu/
    python -m tools.mxlint --list-rules

Suppression: append ``# mxlint: disable=<rule-id>[,<rule-id>...]`` to the
flagged line (or ``disable=all``).  Grandfathered violations live in
``tools/mxlint/baseline.json`` (see ``--write-baseline``); the tier-1
test fails on any NEW violation.

Rules (see ``tools/mxlint/rules.py`` and docs/ARCHITECTURE.md
"Enforced invariants"):

  host-sync-in-hot-path    device->host syncs reachable from Trainer.step /
                           Module.update / metric update (ISSUE 3)
  jit-purity               side effects inside jitted / registered kernels
  wall-clock-in-fault-path raw time.* in fault.py / health.py / kvstore/*
                           that must use the injectable clock (ISSUE 1)
  env-var-registry         ad-hoc MX_* env reads bypassing base.get_env or
                           missing from base.ENV_CATALOG / docs/ENV_VARS.md
  donation-after-use       buffers donated to a donate_argnums jit and
                           referenced afterwards
"""
from .core import (Diagnostic, FileContext, Rule, RULES, register_rule,
                   lint_source, lint_paths, load_baseline, write_baseline,
                   collect_env_reads, load_catalog_names)
from . import rules as _rules  # noqa: F401  (registers the rule set)

__all__ = ["Diagnostic", "FileContext", "Rule", "RULES", "register_rule",
           "lint_source", "lint_paths", "load_baseline", "write_baseline",
           "collect_env_reads", "load_catalog_names"]

__version__ = "1.0"
