"""``python -m tools.mxlint`` — CLI front end.

Exit-code contract (what tools/lint.sh and the tier-1 test key on):
  0  clean (every diagnostic suppressed or baselined)
  1  new violations
  2  usage / internal error
"""
from __future__ import annotations

import argparse
import json
import os
import sys

from .core import (RULES, apply_baseline, lint_paths, load_baseline,
                   load_baseline_whys, repo_root_of, write_baseline)
from . import rules as _rules  # noqa: F401  (registers the rule set)
from . import project as _project  # noqa: F401  (concurrency rules)

DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "baseline.json")


def _default_paths():
    """mxnet_tpu plus the supervisor and the operator-facing tools —
    the launcher is part of the threaded runtime the concurrency rules
    certify, telemetry_dump.py processes trace files (ISSUE 8), and
    fleet_top.py emits the FLEET wire verb the exhaustiveness rule
    pins (ISSUE 12)."""
    out = ["mxnet_tpu"]
    for extra in ("launch.py", "telemetry_dump.py", "bench_compare.py",
                  "fleet_top.py"):
        if os.path.isfile(os.path.join("tools", extra)):
            out.append(os.path.join("tools", extra))
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.mxlint",
        description="TPU-invariant static analyzer for this repo "
                    "(stdlib-ast; see tools/mxlint/__init__.py)")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/trees to lint (default: mxnet_tpu plus "
                    "tools/launch.py — the whole threaded runtime)")
    ap.add_argument("--jobs", type=int, default=1, metavar="N",
                    help="parse/lint files in N worker processes (the "
                    "whole-program pass itself stays in-process)")
    ap.add_argument("--baseline", default=None, metavar="FILE",
                    help="grandfathered-violations file (default: "
                    "tools/mxlint/baseline.json when it exists)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report grandfathered violations too")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline from the current findings "
                    "and exit 0")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--select", default=None, metavar="RULES",
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--contracts", action="store_true",
                    help="run the program-contract lane instead of the "
                    "AST rules: lower every contracted jit program "
                    "device-free (JAX_PLATFORMS=cpu) and prove donation "
                    "aliasing, temp-HBM budgets and trace closure "
                    "(ISSUE 11; see tools/mxlint/contracts.py)")
    ap.add_argument("--write-manifest", nargs="?", const="DEFAULT",
                    default=None, metavar="FILE",
                    help="with --contracts: write the contract manifest "
                    "JSON (default tools/mxlint/contracts.json)")
    ap.add_argument("--protocol", action="store_true",
                    help="run the wire-protocol verifier instead of the "
                    "AST rules: extract per-verb effect summaries from "
                    "every declare_verbs() machine and model-check the "
                    "exactly-once layer under exhaustive bounded fault "
                    "schedules (ISSUE 19; see tools/mxlint/protocol.py). "
                    "No baseline: findings are fix-or-suppress-with-why")
    args = ap.parse_args(argv)

    if args.protocol:
        # pure-stdlib like the AST lanes, but its own pipeline: verb
        # machines + deterministic model checker, never baselined
        from . import protocol as _protocol
        sel = None
        if args.select:
            sel = {r.strip() for r in args.select.split(",") if r.strip()}
            unknown = sel - set(RULES)
            if unknown:
                print("mxlint: unknown rule(s): %s"
                      % ", ".join(sorted(unknown)), file=sys.stderr)
                return 2
        ppaths = list(args.paths) if args.paths else _default_paths()
        for p in ppaths:
            if not os.path.exists(p):
                print("mxlint: no such path: %s" % p, file=sys.stderr)
                return 2
        return _protocol.run_cli(ppaths, fmt=args.format, select=sel)

    if args.contracts:
        # the contract lane imports the runtime (jax + mxnet_tpu) —
        # deliberately isolated from the pure-stdlib AST lanes above
        from . import contracts as _contracts
        out = args.write_manifest
        if out == "DEFAULT":
            out = _contracts.DEFAULT_MANIFEST
        names = None
        if args.select:
            names = [r.strip() for r in args.select.split(",")
                     if r.strip()]
        return _contracts.run_cli(fmt=args.format, write_manifest=out,
                                  contract_names=names)

    if args.list_rules:
        for rid, rule in sorted(RULES.items()):
            print("%-26s %s" % (rid, rule.description))
        return 0

    paths = list(args.paths) if args.paths else _default_paths()
    for p in paths:
        if not os.path.exists(p):
            print("mxlint: no such path: %s" % p, file=sys.stderr)
            return 2
    select = None
    if args.select:
        select = {r.strip() for r in args.select.split(",") if r.strip()}
        unknown = select - set(RULES)
        if unknown:
            print("mxlint: unknown rule(s): %s" % ", ".join(sorted(unknown)),
                  file=sys.stderr)
            return 2

    root = repo_root_of(paths[0]) or os.getcwd()
    # only the json output needs the ProjectIndex back (for the lock
    # graph); a --select run narrowed to file rules then skips the
    # whole-program indexing entirely
    want_graph = args.format == "json"
    try:
        result = lint_paths(paths, root=root, select=select,
                            jobs=args.jobs, return_project=want_graph)
        diags, project = result if want_graph else (result, None)
    except Exception as e:  # internal error must not look like "clean"
        print("mxlint: internal error: %s: %s" % (type(e).__name__, e),
              file=sys.stderr)
        return 2

    baseline_path = args.baseline or (
        DEFAULT_BASELINE if os.path.isfile(DEFAULT_BASELINE) else None)

    if args.write_baseline:
        if select is not None:
            # a rule-narrowed scan sees only a slice of the findings;
            # writing it out would silently drop every other rule's
            # grandfathered entries
            print("mxlint: --write-baseline cannot be combined with "
                  "--select (it would erase the unselected rules' "
                  "entries)", file=sys.stderr)
            return 2
        out = args.baseline or DEFAULT_BASELINE
        # merge: entries for files OUTSIDE the scanned paths are not in
        # `diags` only because they were not looked at — preserve them,
        # and re-attach every surviving entry's `why` justification
        kept = []
        whys = {}
        if os.path.isfile(out):
            rel_scanned = [os.path.relpath(os.path.abspath(p),
                                           root).replace(os.sep, "/")
                           for p in paths]
            prefixes = [r + "/" if os.path.isdir(p) else r
                        for p, r in zip(paths, rel_scanned)]

            def scanned(entry_path):
                return any(entry_path == pre.rstrip("/") or
                           entry_path.startswith(pre) for pre in prefixes)

            try:
                whys = load_baseline_whys(out)
                for key, count in load_baseline(out).items():
                    if not scanned(key[0]):
                        kept.append((key, count))
            except (OSError, ValueError, KeyError) as e:
                print("mxlint: cannot read existing baseline %s: %s"
                      % (out, e), file=sys.stderr)
                return 2
        write_baseline(out, diags, extra_counts=dict(kept), whys=whys)
        n = len(diags) + sum(c for _, c in kept)
        print("mxlint: wrote %d grandfathered entr%s to %s%s"
              % (n, "y" if n == 1 else "ies", out,
                 " (%d preserved from unscanned paths)" % len(kept)
                 if kept else ""))
        return 0

    baseline = {}
    if baseline_path and not args.no_baseline:
        try:
            baseline = load_baseline(baseline_path)
        except (OSError, ValueError, KeyError) as e:
            # a typo'd --baseline must read as a usage error (2), never as
            # "new violations" (1) — scripts key on the exit code
            print("mxlint: cannot read baseline %s: %s"
                  % (baseline_path, e), file=sys.stderr)
            return 2
    new, old, stale = apply_baseline(diags, baseline)

    if args.format == "json":
        # stable machine schema (satellite of ISSUE 6): every finding
        # carries rule id, file:line, a drift-stable fingerprint and the
        # thread roots involved; the static lock graph rides along so CI
        # can assert it stays acyclic
        cycles = project.lock_cycles()
        print(json.dumps({
            "schema": 2,
            "violations": [d.to_json() for d in new],
            "baselined": [d.to_json() for d in old],
            "stale_baseline": ["%s:%s:%s" % k for k in stale],
            "lock_graph": {
                "edges": sorted("%s -> %s" % k
                                for k in project.lock_graph()),
                "acyclic": not cycles,
            },
        }, indent=1))
    else:
        for d in new:
            print("%s:%d:%d: %s: %s" % (d.path, d.line, d.col, d.rule,
                                        d.message))
        if stale:
            print("mxlint: note: %d stale baseline entr%s (fixed or "
                  "reworded) — run --write-baseline to shrink the file"
                  % (len(stale), "y" if len(stale) == 1 else "ies"),
                  file=sys.stderr)
        summary = "mxlint: %d new violation%s" % (
            len(new), "" if len(new) == 1 else "s")
        if old:
            summary += ", %d baselined" % len(old)
        print(summary, file=sys.stderr)
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
