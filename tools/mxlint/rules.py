"""The repo-specific rule set.

Each rule enforces an invariant a prior PR bought (see the
"Enforced invariants" table in docs/ARCHITECTURE.md).  All analysis is
file-local: call graphs do not cross imports, so a sync hidden behind an
imported helper needs a root entry for that helper's own file.  That is a
deliberate trade — file-local analysis is fast, dependency-free and has
no false positives from dynamic dispatch — and the hot-path root table
below covers both sides of every cross-file hot edge (Trainer._update ->
Updater.__call__, Module.update_metric -> metric.update, ...).
"""
from __future__ import annotations

import ast
import fnmatch
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from .core import (Diagnostic, FileContext, Rule, register_rule,
                   _attr_chain)

# ---------------------------------------------------------------------------
# host-sync-in-hot-path
# ---------------------------------------------------------------------------

# (file pattern, [qualname patterns]) — the training-step hot path as rooted
# per file.  Cross-file hot edges are covered by rooting the callee's own
# entry points (file-local analysis never follows imports).
HOT_PATH_ROOTS: List[Tuple[str, List[str]]] = [
    ("mxnet_tpu/gluon/trainer.py",
     ["Trainer.step", "Trainer.update", "Trainer._update",
      "Trainer.allreduce_grads", "Trainer._allreduce_grads"]),
    # the whole-step compiled lane (ISSUE 7): every host-side function on
    # the per-dispatch path is a hot root — one sync here stalls the
    # single-program pipeline exactly like a per-op sync used to.  The
    # traced bodies (_traced_step_window / _traced_fit_step and their
    # closures) are additionally jit-purity targets via their
    # jax.jit(...) sites.
    ("mxnet_tpu/step.py",
     ["CompiledStep.step", "CompiledStep.run_window", "CompiledStep._run",
      "CompiledStep._plan", "CompiledStep._lr_rows",
      "CompiledStep._gather_state", "CompiledStep._write_back"]),
    ("mxnet_tpu/module/*.py", ["*.update", "*.update_metric"]),
    ("mxnet_tpu/model.py", ["*.update", "*.update_metric"]),
    ("mxnet_tpu/metric.py", ["*.update", "*.update_dict"]),
    ("mxnet_tpu/monitor.py", ["Monitor.tic", "Monitor.toc"]),
    ("mxnet_tpu/optimizer/*.py",
     ["Updater.__call__", "*.fused_update", "*._fused_apply", "*.update",
      "*.update_multi_precision"]),
    # telemetry span/record helpers (ISSUE 8) run inside every step
    # phase — Trainer.step, the fit loops, CompiledStep dispatches all
    # cross into this file per batch, so a host sync here stalls the
    # pipeline exactly like one in the trainer would.  Spans are
    # dispatch-time by contract; this root machine-checks it (the
    # tests/test_telemetry.py reinjection test trips this entry).
    ("mxnet_tpu/telemetry.py",
     ["phase", "note_step", "heartbeat_payload", "rpc_span",
      "Span.*", "_PhaseSpan.*", "FlightRecorder.record",
      "Counter.*", "Gauge.*", "Histogram.*"]),
    # the serving batcher's dispatch loop (ISSUE 9): a host sync between
    # dequeue and dispatch serializes the whole fleet's latency — the
    # scatter-side device→host read belongs on the handler threads
    # (_Pending.result/_Batch.host), never in the loop.  The
    # tests/test_mxlint.py reinjection test proves a blocking host read
    # reintroduced into the loop trips this entry.
    ("mxnet_tpu/serve/batcher.py",
     ["Batcher._loop", "Batcher._collect", "Batcher._dispatch",
      "Batcher.submit"]),
    # the servable dispatch path is the other side of the batcher's hot
    # edge (file-local analysis never follows imports)
    ("mxnet_tpu/serve/servable.py",
     ["Servable.dispatch", "Servable.program", "Servable.signature_of",
      "ModelHost.active"]),
    # the decode pump + slot allocator (ISSUE 15): ONE host sync
    # between decode dispatches serializes every active generation's
    # token cadence — sampled tokens stay device-resident between
    # steps, and the device→host read belongs ONLY to the harvester
    # thread (_harvest_once, deliberately NOT rooted).  The
    # tests/test_mxlint.py reinjection test proves a blocking host
    # read between state dequeue and dispatch trips this entry.
    ("mxnet_tpu/serve/decode.py",
     ["DecodeBatcher._loop", "DecodeBatcher._tick",
      "DecodeBatcher._retire", "DecodeBatcher._admit",
      "DecodeBatcher._active", "DecodeBatcher._step",
      "DecodeBatcher._dispatch_prefill", "DecodeBatcher._hq_put",
      "DecodeBatcher.submit", "DecodeServable.dispatch_step",
      "DecodeServable.dispatch_prefill", "DecodeServable.step_program",
      "DecodeServable.prefill_program",
      # the paged engine (ISSUE 18): admission planning (hash lookups,
      # page allocation, chunk layout) and the chunk scheduler run
      # between dequeue and dispatch every tick — pure host
      # bookkeeping by contract, same no-sync rule
      "PagedDecodeBatcher._tick", "PagedDecodeBatcher._retire",
      "PagedDecodeBatcher._admit", "PagedDecodeBatcher._plan",
      "PagedDecodeBatcher._active",
      "PagedDecodeBatcher._next_chunk_slot",
      "PagedDecodeBatcher._dispatch_chunk_for",
      "PagedDecodeBatcher._step",
      "PagedDecodeServable.dispatch_step",
      "PagedDecodeServable.dispatch_chunk",
      "PagedDecodeServable.step_program",
      "PagedDecodeServable.chunk_program"]),
    # the paged KV allocator + prefix hash table (ISSUE 18) sit inside
    # the pump's admission path — every method is per-tick bookkeeping
    # (free lists, refcounts, rolling hashes over host ints) and must
    # never touch the device or block.  The tests/test_mxlint.py
    # reinjection test proves a host sync smuggled into alloc() trips
    # this entry.
    ("mxnet_tpu/serve/paging.py",
     ["PageAllocator.alloc", "PageAllocator.lookup",
      "PageAllocator.publish", "PageAllocator.release",
      "PageAllocator.free_pages", "PageAllocator.shared_extra_refs",
      "chain_hash", "page_hashes"]),
    # the program census (ISSUE 10) wraps EVERY jit dispatch: its call
    # path and record helpers are dispatch-time bookkeeping by contract
    # (shape/aval reads only — never a device sync), and the buffer
    # census walks live-array HANDLES (nbytes metadata, no transfer).
    # The tests/test_mxlint.py reinjection test trips this entry.
    ("mxnet_tpu/programs.py",
     ["Program.__call__", "Program._compile", "ProgramRecord.note_compile",
      "ProgramRecord.note_cache_hit",
      "signature_of", "diff_signatures", "buffer_census",
      "LeakDetector.check"]),
    # the persistent compile cache's KEY helpers (ISSUE 13) run under
    # Program._compile per executable build — pure hash/string work over
    # host metadata by contract (the disk I/O itself lives in
    # load/store, which only the cold path reaches; the open()-in-hot-
    # path check above guards the rest of the runtime).  The
    # tests/test_compile_cache.py reinjection test trips this entry.
    ("mxnet_tpu/compile_cache.py",
     ["cache_key", "signature_token", "function_fingerprint"]),
    # the async input pipeline's consumer handoff (ISSUE 13): __next__
    # runs once per training step between batches — a device sync or
    # host pull here re-serializes exactly the overlap the prefetcher
    # exists to create (the device_put lives on the producer thread by
    # design).  The tests/test_compile_cache.py reinjection test trips
    # this entry.
    ("mxnet_tpu/io/prefetch.py",
     ["DevicePrefetcher.__next__", "DevicePrefetcher._put"]),
    # the fleet collector's scrape/merge loop (ISSUE 12) runs forever
    # NEXT TO the training/serving processes it observes — a host sync
    # (or any device pull) reintroduced here would periodically stall
    # the very fleet it measures.  The merge algebra is dict arithmetic
    # by contract (no numpy, no jax); this root machine-checks it (the
    # tests/test_fleet.py reinjection test trips this entry).
    ("mxnet_tpu/fleet.py",
     ["FleetCollector.scrape_once", "FleetCollector._scrape_member",
      "FleetCollector._scrape_heartbeat", "FleetCollector._fold",
      "FleetCollector._publish", "FleetCollector._rebase_counters",
      "FleetCollector._hist_delta", "merge_snapshots",
      "merge_bucket_maps", "quantile_from_buckets",
      "StragglerDetector.update", "SLOTracker.update"]),
]

_SYNC_ATTRS = {"asnumpy", "asscalar", "item", "wait_to_read", "tolist"}
_NUMPY_PULLS = ("numpy.asarray", "numpy.array", "numpy.frombuffer")


def _is_numpy_pull(ctx: FileContext, func: ast.AST) -> bool:
    return any(ctx.resolves_to(func, d) for d in _NUMPY_PULLS)


def _program_fn_arg(ctx: FileContext, call: ast.AST):
    """The traced-fn argument of a program-census jit site (ISSUE 10):
    ``register_program(name, fn, **jit_kw)`` is the repo's drop-in for
    ``jax.jit(fn, **jit_kw)`` — its second positional arg is the traced
    body, and the same jit kwargs (static_argnums, donate_argnums) apply.
    Returns the fn node, or None when `call` is not such a site."""
    if not isinstance(call, ast.Call) or len(call.args) < 2:
        return None
    f = call.func
    if ctx.resolves_to(f, "mxnet_tpu.programs.register_program") or \
            (isinstance(f, ast.Name) and f.id == "register_program") or \
            (isinstance(f, ast.Attribute) and f.attr == "register_program"):
        return call.args[1]
    return None


@register_rule
class HostSyncInHotPath(Rule):
    id = "host-sync-in-hot-path"
    description = ("device->host syncs (.asnumpy()/.item()/np.asarray/"
                   "waitall) inside functions reachable from the training "
                   "step; each one stalls the XLA pipeline and breaks the "
                   "O(1)-dispatches-per-step budget")
    invariant_from = "ISSUE 3 (single-dispatch training step)"
    path_patterns = tuple(sorted({pat for pat, _ in HOT_PATH_ROOTS}))

    def check(self, ctx: FileContext) -> Iterator[Diagnostic]:
        roots: List[str] = []
        for pat, quals in HOT_PATH_ROOTS:
            if not fnmatch.fnmatch(ctx.path, pat):
                continue
            for qual in ctx.functions:
                if any(fnmatch.fnmatch(qual, qp) for qp in quals):
                    roots.append(qual)
        if not roots:
            return
        # BFS with provenance so the message names the reaching root
        via: Dict[str, str] = {}
        stack = [(r, r) for r in roots]
        while stack:
            qual, root = stack.pop()
            if qual in via:
                continue
            via[qual] = root
            for callee in ctx.call_graph.get(qual, ()):
                stack.append((callee, root))
        for qual, root in sorted(via.items()):
            fn = ctx.functions[qual]
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                what = None
                if isinstance(f, ast.Attribute) and f.attr in _SYNC_ATTRS:
                    what = ".%s()" % f.attr
                elif isinstance(f, ast.Attribute) and f.attr == "waitall":
                    what = "waitall()"
                elif isinstance(f, ast.Name) and f.id == "waitall":
                    what = "waitall()"
                elif _is_numpy_pull(ctx, f):
                    what = "np.%s()" % f.attr if isinstance(f, ast.Attribute)\
                        else "np.asarray()"
                elif isinstance(f, ast.Name) and f.id == "open":
                    # ISSUE 13: the persistent compile cache made disk
                    # I/O a runtime concern — it lives in
                    # Program._compile (cold path) by contract; a file
                    # open reintroduced on a per-dispatch path (the
                    # batcher loop, the prefetch handoff, the trainer
                    # step) stalls the pipeline exactly like a device
                    # sync would
                    yield ctx.diag(
                        self.id, node,
                        "open() in %s (hot path via %s): disk I/O on a "
                        "per-dispatch path; cache/file reads belong on "
                        "the cold (compile/build) path" % (qual, root))
                    continue
                if what:
                    yield ctx.diag(
                        self.id, node,
                        "%s in %s (hot path via %s) forces a device->host "
                        "sync every batch; accumulate device-side and drain "
                        "once outside the step" % (what, qual, root))


# ---------------------------------------------------------------------------
# jit-purity
# ---------------------------------------------------------------------------

_WALL_CLOCK = ("time.time", "time.monotonic", "time.perf_counter",
               "time.process_time", "time.sleep")


def _donate_positions(call: ast.Call) -> Optional[Set[int]]:
    """Literal donate_argnums positions of a jax.jit call; None if absent
    or not statically known.  An `X if flag else ()` conditional takes the
    union — the use-after bug only bites when donation is on."""
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        vals = [kw.value]
        if isinstance(kw.value, ast.IfExp):
            vals = [kw.value.body, kw.value.orelse]
        out: Set[int] = set()
        for v in vals:
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                out.add(v.value)
            elif isinstance(v, (ast.Tuple, ast.List)):
                for el in v.elts:
                    if isinstance(el, ast.Constant) and \
                            isinstance(el.value, int):
                        out.add(el.value)
        return out or None
    return None


def _static_param_names(fn: ast.AST,
                        jit_call: Optional[ast.Call]) -> Set[str]:
    """Parameters a tracer never flows through: static_argnums/argnames at
    the jit site, plus any parameter with a default (registry op `params`
    are static by contract)."""
    static: Set[str] = set()
    args = fn.args
    pos = [a.arg for a in getattr(args, "posonlyargs", [])] + \
          [a.arg for a in args.args]
    if jit_call is not None:
        for kw in jit_call.keywords:
            if kw.arg == "static_argnames":
                v = kw.value
                elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
                for el in elts:
                    if isinstance(el, ast.Constant) and \
                            isinstance(el.value, str):
                        static.add(el.value)
            elif kw.arg == "static_argnums":
                v = kw.value
                elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
                for el in elts:
                    if isinstance(el, ast.Constant) and \
                            isinstance(el.value, int) and \
                            el.value < len(pos):
                        static.add(pos[el.value])
    ndefaults = len(args.defaults)
    if ndefaults:
        static.update(a for a in pos[-ndefaults:])
    static.update(a.arg for a in args.kwonlyargs)
    if args.vararg:
        pass  # *arrays stay traced
    if args.kwarg:
        static.add(args.kwarg.arg)  # **params: static attrs by contract
    return static


def _is_jax_jit(ctx: FileContext, node: ast.AST) -> bool:
    return ctx.resolves_to(node, "jax.jit") or \
        ctx.resolves_to(node, "jax.experimental.pjit.pjit")


def _collect_jit_functions(ctx: FileContext):
    """(fn node -> jit call-or-None) for every function this file jits
    or registers as an op kernel — shared by jit-purity and
    retrace-hazard."""
    # every def in the file, by name (incl. nested), for by-name marks
    defs_by_name: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(node.name, []).append(node)
    marked: Dict[ast.AST, Optional[ast.Call]] = {}
    in_ops = fnmatch.fnmatch(ctx.path, "mxnet_tpu/ops/*.py")

    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                jit_call = None
                hit = False
                if _is_jax_jit(ctx, dec):
                    hit = True
                elif isinstance(dec, ast.Call):
                    if _is_jax_jit(ctx, dec.func):
                        hit, jit_call = True, dec
                    elif ctx.resolves_to(dec.func, "functools.partial") \
                            and dec.args and _is_jax_jit(ctx, dec.args[0]):
                        hit, jit_call = True, dec
                    elif in_ops and ctx.resolves_to(
                            dec.func, "mxnet_tpu.ops.registry.register")\
                            or in_ops and isinstance(dec.func, ast.Name)\
                            and dec.func.id == "register":
                        # no_jit exempts only when truthy (or not a
                        # literal — then be conservative and exempt)
                        if not any(kw.arg == "no_jit" and
                                   (not isinstance(kw.value,
                                                   ast.Constant) or
                                    kw.value.value)
                                   for kw in dec.keywords):
                            hit = True
                if hit:
                    marked[node] = jit_call
        elif isinstance(node, ast.Call):
            fn_arg = None
            jit_call = None
            if _is_jax_jit(ctx, node.func) and node.args:
                fn_arg, jit_call = node.args[0], node
            elif _program_fn_arg(ctx, node) is not None:
                # register_program(name, fn, **jit_kw): fn is traced
                # exactly like jax.jit(fn, **jit_kw)'s arg (ISSUE 10)
                fn_arg, jit_call = _program_fn_arg(ctx, node), node
            elif in_ops and isinstance(node.func, ast.Name) and \
                    node.func.id == "register" and len(node.args) >= 2:
                if not any(kw.arg == "no_jit" and
                           isinstance(kw.value, ast.Constant) and
                           kw.value.value for kw in node.keywords):
                    fn_arg = node.args[1]
            if isinstance(fn_arg, ast.Name):
                for d in defs_by_name.get(fn_arg.id, ()):
                    marked.setdefault(d, jit_call)
    return marked


@register_rule
class JitPurity(Rule):
    id = "jit-purity"
    description = ("side effects (print/open/wall-clock/env reads/python "
                   "RNG/global writes/host syncs) and data-dependent "
                   "python branches inside functions that jax traces — "
                   "they run once at trace time (or crash), not per step")
    invariant_from = "seed (pure-traceable op registry contract)"

    def _jit_functions(self, ctx: FileContext):
        return _collect_jit_functions(ctx)

    def check(self, ctx: FileContext) -> Iterator[Diagnostic]:
        for fn, jit_call in sorted(self._jit_functions(ctx).items(),
                                   key=lambda kv: kv[0].lineno):
            static = _static_param_names(fn, jit_call)
            params = {a.arg for a in fn.args.args} | \
                {a.arg for a in getattr(fn.args, "posonlyargs", [])}
            traced = params - static
            for node in ast.walk(fn):
                if isinstance(node, ast.Global):
                    yield ctx.diag(self.id, node,
                                   "`global` write inside jitted %r runs at "
                                   "trace time, not per call" % fn.name)
                elif isinstance(node, ast.Call):
                    f = node.func
                    if isinstance(f, ast.Name) and f.id in ("print", "open",
                                                            "input"):
                        yield ctx.diag(
                            self.id, node,
                            "%s() inside jitted %r is a trace-time side "
                            "effect (use jax.debug.print / hoist the I/O)"
                            % (f.id, fn.name))
                    elif any(ctx.resolves_to(f, d) for d in _WALL_CLOCK):
                        yield ctx.diag(
                            self.id, node,
                            "wall-clock read inside jitted %r is baked in "
                            "at trace time" % fn.name)
                    elif ctx.resolves_to(f, "os.getenv") or \
                            (isinstance(f, ast.Attribute) and
                             f.attr in ("get_env", "getenv")) or \
                            (isinstance(f, ast.Name) and
                             f.id in ("get_env", "getenv")):
                        yield ctx.diag(
                            self.id, node,
                            "env read inside jitted %r is baked in at trace "
                            "time; pass it as a static argument" % fn.name)
                    elif isinstance(f, ast.Attribute) and \
                            f.attr in ("asnumpy", "item", "asscalar"):
                        yield ctx.diag(
                            self.id, node,
                            ".%s() inside jitted %r forces concretization "
                            "under trace" % (f.attr, fn.name))
                    else:
                        chain = _attr_chain(f)
                        if chain:
                            origin = ctx.import_aliases.get(chain[0],
                                                            chain[0])
                            full = ".".join([origin] + chain[1:])
                            if full.startswith("random.") or \
                                    full.startswith("numpy.random."):
                                yield ctx.diag(
                                    self.id, node,
                                    "python/numpy RNG inside jitted %r is "
                                    "trace-frozen; thread a jax PRNG key "
                                    "instead" % fn.name)
                elif isinstance(node, ast.Attribute) and \
                        _attr_chain(node) is not None:
                    chain = _attr_chain(node)
                    origin = ctx.import_aliases.get(chain[0], chain[0])
                    if ".".join([origin] + chain[1:]).startswith(
                            "os.environ"):
                        yield ctx.diag(
                            self.id, node,
                            "os.environ access inside jitted %r is baked in "
                            "at trace time" % fn.name)
                elif isinstance(node, (ast.If, ast.While)):
                    d = self._data_dep_branch(ctx, node, traced, fn)
                    if d:
                        yield d

    def _data_dep_branch(self, ctx, node, traced: Set[str], fn):
        """`if x > 0:` on a traced array argument — TracerBoolConversionError
        at runtime (or silently trace-frozen).  Shape/dtype attribute
        reads (`x.ndim`, `x.shape[0]`) are static and exempt, as are
        `is None` / isinstance checks."""
        # A traced name only counts when its VALUE flows into the branch
        # decision directly: bare (`if x:`), compared (`if x > 0:`), or
        # indexed (`if x[0]:`).  Excluded subtrees are static or at worst
        # loud at trace time on their own:
        #   - Attribute chains (`x.ndim`, `x.shape[0]`, `x.dtype`)
        #   - Call arguments (`isinstance(x, ...)`, `len(x)`, helper
        #     predicates over shape/dtype)
        #   - `is` / `is not` comparisons (None sentinels)
        real: List[str] = []

        def scan(sub):
            if isinstance(sub, (ast.Attribute, ast.Call)):
                return
            if isinstance(sub, ast.Compare) and \
                    all(isinstance(op, (ast.Is, ast.IsNot))
                        for op in sub.ops):
                return
            if isinstance(sub, ast.Name) and sub.id in traced and \
                    isinstance(sub.ctx, ast.Load):
                real.append(sub.id)
            for child in ast.iter_child_nodes(sub):
                scan(child)

        scan(node.test)
        if real:
            return ctx.diag(
                self.id, node,
                "branch on traced argument%s %s inside jitted %r is "
                "data-dependent python control flow; use lax.cond/jnp.where "
                "or mark the argument static" %
                ("s" if len(real) > 1 else "", ", ".join(sorted(set(real))),
                 fn.name))
        return None


# ---------------------------------------------------------------------------
# wall-clock-in-fault-path
# ---------------------------------------------------------------------------

@register_rule
class WallClockInFaultPath(Rule):
    id = "wall-clock-in-fault-path"
    description = ("raw time.time()/monotonic()/sleep() in retry/timeout/"
                   "liveness code that must use mxnet_tpu.fault's "
                   "injectable clock, so chaos tests can fast-forward it")
    invariant_from = "ISSUE 1 (virtual-clock fault tolerance)"
    path_patterns = ("mxnet_tpu/fault.py", "mxnet_tpu/health.py",
                     "mxnet_tpu/kvstore/*.py")

    def check(self, ctx: FileContext) -> Iterator[Diagnostic]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.Attribute, ast.Name)):
                continue
            if not isinstance(getattr(node, "ctx", None), ast.Load):
                continue
            # a bare module alias ("time") resolves to "time", never to
            # "time.time", so plain imports don't flag
            for dotted in _WALL_CLOCK:
                if ctx.resolves_to(node, dotted):
                    yield ctx.diag(
                        self.id, node,
                        "%s in fault-path code: use mxnet_tpu.fault."
                        "%s() so chaos tests can drive it with a "
                        "virtual clock" %
                        (dotted, "sleep" if dotted.endswith("sleep")
                         else "now"))
                    break


# ---------------------------------------------------------------------------
# env-var-registry
# ---------------------------------------------------------------------------

@register_rule
class EnvVarRegistry(Rule):
    id = "env-var-registry"
    description = ("every MX_*/MXNET_* env read must go through "
                   "mxnet_tpu.base.get_env and be declared in "
                   "base.ENV_CATALOG (docs/ENV_VARS.md regenerates from "
                   "it); ad-hoc os.environ reads dodge overrides, typed "
                   "defaults and the doc")
    invariant_from = "ISSUE 1-3 (documented MX_* env surface)"
    # NB fnmatch '*' crosses '/': this one pattern covers every depth
    path_patterns = ("mxnet_tpu/*.py",)

    _EXEMPT = ("mxnet_tpu/base.py",)  # the accessor itself

    def _is_mx(self, name: str) -> bool:
        return name.startswith("MX_") or name.startswith("MXNET_")

    def check(self, ctx: FileContext) -> Iterator[Diagnostic]:
        if ctx.path in self._EXEMPT:
            return
        for node in ast.walk(ctx.tree):
            name = None
            adhoc = False
            if isinstance(node, ast.Call):
                f = node.func
                chain = _attr_chain(f)
                if chain:
                    origin = ctx.import_aliases.get(chain[0], chain[0])
                    full = ".".join([origin] + chain[1:])
                    lit = (node.args and
                           isinstance(node.args[0], ast.Constant) and
                           isinstance(node.args[0].value, str) and
                           node.args[0].value)
                    if full in ("os.environ.get", "os.getenv"):
                        name, adhoc = lit, True
                    elif full.endswith("get_env") or full == "util.getenv" \
                            or (isinstance(f, ast.Name) and
                                f.id in ("get_env", "getenv")):
                        name = lit
            elif isinstance(node, ast.Subscript) and \
                    isinstance(node.ctx, ast.Load):
                chain = _attr_chain(node.value)
                if chain:
                    origin = ctx.import_aliases.get(chain[0], chain[0])
                    if ".".join([origin] + chain[1:]) == "os.environ":
                        sl = node.slice
                        if isinstance(sl, ast.Constant) and \
                                isinstance(sl.value, str):
                            name, adhoc = sl.value, True
            if not name or not self._is_mx(name):
                continue
            if adhoc:
                yield ctx.diag(
                    self.id, node,
                    "ad-hoc env read of %s: route it through "
                    "mxnet_tpu.base.get_env (typed, override-aware, "
                    "catalog-documented)" % name)
            if ctx.catalog is not None and name not in ctx.catalog:
                yield ctx.diag(
                    self.id, node,
                    "%s is not declared in base.ENV_CATALOG — add it (with "
                    "default + doc line) and regenerate docs/ENV_VARS.md "
                    "via tools/gen_env_docs.py" % name)


# ---------------------------------------------------------------------------
# donation-after-use
# ---------------------------------------------------------------------------

@register_rule
class DonationAfterUse(Rule):
    id = "donation-after-use"
    description = ("an argument passed at a donate_argnums position is "
                   "invalidated by XLA buffer donation; reading it after "
                   "the call returns garbage or errors on hardware (CPU "
                   "silently skips donation, hiding the bug)")
    invariant_from = "ISSUE 3 (donated fused-optimizer buffers)"

    # The INVERSE failure mode — a donation XLA silently DROPS because
    # no output matches the donated leaf's shape+dtype, leaving both
    # generations of the buffer live on TPU — is not statically visible
    # in source and is covered by the contract lane instead:
    # `python -m tools.mxlint --contracts` lowers every contracted
    # program and emits `contract-donation-dropped` when a declared
    # donation fails to appear in the executable's input→output
    # aliasing (with jax's "donated buffers were not usable" warning
    # attached).  A donated-but-value-unused arg (jax prunes it; e.g.
    # the bf16 weights of a multi-precision Adam apply, whose new
    # values derive from the fp32 masters) is a no-op donation — the
    # verifier NOTES it in the budget table (`pruned` column) without
    # flagging.  See docs/TESTING.md §5 and ISSUE 11.

    def check(self, ctx: FileContext) -> Iterator[Diagnostic]:
        # 1. name -> donated positions, for `f = jax.jit(g, donate_argnums=...)`
        #    bindings (local names and self.X attributes, file-wide)
        bound: Dict[str, Set[int]] = {}
        self_bound: Dict[str, Set[int]] = {}
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Assign) or \
                    not isinstance(node.value, ast.Call):
                continue
            call = node.value
            if not (ctx.resolves_to(call.func, "jax.jit") or
                    _program_fn_arg(ctx, call) is not None):
                continue
            donated = _donate_positions(call)
            if not donated:
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    bound[tgt.id] = donated
                elif isinstance(tgt, ast.Attribute) and \
                        isinstance(tgt.value, ast.Name) and \
                        tgt.value.id == "self":
                    self_bound[tgt.attr] = donated
        # 2. scan every function for calls through those bindings (or a
        #    direct jax.jit(...)(...) call) and reads-after of donated args
        for qual, fn in sorted(ctx.functions.items()):
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                donated = None
                f = node.func
                if isinstance(f, ast.Name) and f.id in bound:
                    donated = bound[f.id]
                elif isinstance(f, ast.Attribute) and \
                        isinstance(f.value, ast.Name) and \
                        f.value.id == "self" and f.attr in self_bound:
                    donated = self_bound[f.attr]
                elif isinstance(f, ast.Call) and \
                        (ctx.resolves_to(f.func, "jax.jit") or
                         _program_fn_arg(ctx, f) is not None):
                    donated = _donate_positions(f)
                if not donated:
                    continue
                donated_names = {a.id for i, a in enumerate(node.args)
                                 if i in donated and isinstance(a, ast.Name)}
                if not donated_names:
                    continue
                yield from self._reads_after(ctx, fn, node, donated_names,
                                             qual)

    def _reads_after(self, ctx, fn, call, names: Set[str], qual: str):
        call_line = getattr(call, "end_lineno", call.lineno)
        names = set(names)
        # `a = fn(a, b)` rebinds on the call's own line: the assignment
        # targets of the statement containing the call kill the taint
        for stmt in ast.walk(fn):
            if isinstance(stmt, ast.Assign) and \
                    any(n is call for n in ast.walk(stmt.value)):
                for tgt in stmt.targets:
                    for n in ast.walk(tgt):
                        if isinstance(n, ast.Name):
                            names.discard(n.id)
        if not names:
            return
        events = []   # (lineno, name, is_store)
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and node.id in names and \
                    node.lineno > call_line:
                events.append((node.lineno, node.id,
                               isinstance(node.ctx, ast.Store), node))
        events.sort(key=lambda e: e[0])
        dead = set(names)
        for lineno, name, is_store, node in events:
            if name not in dead:
                continue
            if is_store:
                dead.discard(name)   # rebound: old buffer unreachable
            else:
                yield ctx.diag(
                    self.id, node,
                    "%r is read after being passed at a donated position "
                    "of a donate_argnums-jitted call in %s; its buffer "
                    "belongs to XLA now — rebind the result or drop "
                    "donation" % (name, qual))
                dead.discard(name)   # one report per buffer per call


# ---------------------------------------------------------------------------
# retrace-hazard
# ---------------------------------------------------------------------------

def _literal_static_spec(call: ast.Call) -> Tuple[Set[int], Set[str]]:
    """(static positions, static names) literally declared at a jit
    call site — the single source both halves of the retrace analysis
    (bindings and direct calls) read, so a parsing fix lands once."""
    nums: Set[int] = set()
    names: Set[str] = set()
    for kw in call.keywords:
        if kw.arg not in ("static_argnums", "static_argnames"):
            continue
        v = kw.value
        elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
        for el in elts:
            if not isinstance(el, ast.Constant):
                continue
            if kw.arg == "static_argnums" and isinstance(el.value, int):
                nums.add(el.value)
            elif kw.arg == "static_argnames" and \
                    isinstance(el.value, str):
                names.add(el.value)
    return nums, names


def _jit_call_bindings(ctx: FileContext):
    """Names (locals and ``self.X`` attrs) bound to jax.jit /
    register_program results, with the literal static spec of each
    binding's jit call — the call-site half of the retrace analysis."""
    bound: Dict[str, Tuple[Set[int], Set[str]]] = {}
    self_bound: Dict[str, Tuple[Set[int], Set[str]]] = {}
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Assign) or \
                not isinstance(node.value, ast.Call):
            continue
        call = node.value
        if not (_is_jax_jit(ctx, call.func) or
                _program_fn_arg(ctx, call) is not None):
            continue
        st = _literal_static_spec(call)
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                bound[tgt.id] = st
            elif isinstance(tgt, ast.Attribute) and \
                    isinstance(tgt.value, ast.Name) and \
                    tgt.value.id == "self":
                self_bound[tgt.attr] = st
    return bound, self_bound


def _scalar_literal(node: ast.AST):
    """The python numeric value of a literal operand, through unary
    sign (``-1.0`` parses as UnaryOp(USub, Constant)); None otherwise.
    bools are excluded (two values cannot amplify retraces)."""
    sign = 1
    if isinstance(node, ast.UnaryOp) and \
            isinstance(node.op, (ast.USub, ast.UAdd)):
        sign = -1 if isinstance(node.op, ast.USub) else 1
        node = node.operand
    if isinstance(node, ast.Constant) and \
            isinstance(node.value, (int, float)) and \
            not isinstance(node.value, bool):
        return sign * node.value
    return None


@register_rule
class RetraceHazard(Rule):
    id = "retrace-hazard"
    description = ("per-call-site retrace amplifiers on the hot-path "
                   "surfaces whose zero-retrace behavior is contracted "
                   "(step, serve, batcher, programs): python branches on "
                   "a traced argument's .shape/.ndim inside a jitted "
                   "body (each distinct shape compiles a separate "
                   "executable — close the shape set or hoist the "
                   "branch), and python scalar literals passed as traced "
                   "operands at jit call sites in hot-path roots (the "
                   "program cache keys scalars by VALUE, so every "
                   "distinct scalar is a fresh compile).  Per-op eager "
                   "kernels (mxnet_tpu/ops) are exempt: rank/shape "
                   "specialization is their light-census contract")
    invariant_from = "ISSUE 11 (program contracts: static zero-retrace)"

    # scoped to the files whose dispatch behavior the contracts lane
    # proves — the same surface the host-sync rule roots
    path_patterns = tuple(sorted({pat for pat, _ in HOT_PATH_ROOTS}))

    _SHAPE_ATTRS = ("shape", "ndim", "size")

    def check(self, ctx: FileContext) -> Iterator[Diagnostic]:
        yield from self._shape_branches(ctx)
        yield from self._scalar_call_sites(ctx)

    # -- (a) shape-specializing branches inside traced bodies ---------------
    def _shape_branches(self, ctx: FileContext) -> Iterator[Diagnostic]:
        for fn, jit_call in sorted(_collect_jit_functions(ctx).items(),
                                   key=lambda kv: kv[0].lineno):
            static = _static_param_names(fn, jit_call)
            params = {a.arg for a in fn.args.args} | \
                {a.arg for a in getattr(fn.args, "posonlyargs", [])}
            traced = params - static
            for node in ast.walk(fn):
                if not isinstance(node, (ast.If, ast.While)):
                    continue
                names = self._shape_reads(node.test, traced)
                if names:
                    yield ctx.diag(
                        self.id, node,
                        "branch on %s inside jitted %r specializes the "
                        "executable per input shape — every new shape "
                        "is a silent recompile; bucket the shapes "
                        "(declare a contract closure), mark the "
                        "argument static, or hoist the branch" %
                        (", ".join(sorted(names)), fn.name))

    def _shape_reads(self, test: ast.AST, traced: Set[str]) -> Set[str]:
        """'x.shape...' chains rooted at a traced parameter inside a
        branch test — through subscripts too (``xs[0].shape[0]``: the
        tuple-of-batches layout every window body uses)."""
        out: Set[str] = set()
        for node in ast.walk(test):
            if not isinstance(node, ast.Attribute) or \
                    node.attr not in self._SHAPE_ATTRS:
                continue
            base = node.value
            while isinstance(base, ast.Subscript):
                base = base.value
            if isinstance(base, ast.Name) and base.id in traced:
                out.add("%s.%s" % (base.id, node.attr))
        return out

    # -- (b) python scalars as traced operands in hot-path roots ------------
    def _scalar_call_sites(self, ctx: FileContext) -> Iterator[Diagnostic]:
        roots: List[str] = []
        for pat, quals in HOT_PATH_ROOTS:
            if not fnmatch.fnmatch(ctx.path, pat):
                continue
            for qual in ctx.functions:
                if any(fnmatch.fnmatch(qual, qp) for qp in quals):
                    roots.append(qual)
        if not roots:
            return
        bound, self_bound = _jit_call_bindings(ctx)
        hot = ctx.reachable_from(roots)
        for qual in sorted(hot):
            fn = ctx.functions[qual]
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                st = None
                f = node.func
                if isinstance(f, ast.Name) and f.id in bound:
                    st = bound[f.id]
                elif isinstance(f, ast.Attribute) and \
                        isinstance(f.value, ast.Name) and \
                        f.value.id == "self" and f.attr in self_bound:
                    st = self_bound[f.attr]
                elif isinstance(f, ast.Call) and \
                        (_is_jax_jit(ctx, f.func) or
                         _program_fn_arg(ctx, f) is not None):
                    st = _literal_static_spec(f)
                if st is None:
                    continue
                static_nums, static_names = st
                hits = []
                for pos, arg in enumerate(node.args):
                    if pos in static_nums:
                        continue
                    val = _scalar_literal(arg)
                    if val is not None:
                        hits.append((val, arg))
                for kw in node.keywords:
                    if kw.arg is None or kw.arg in static_names:
                        continue
                    val = _scalar_literal(kw.value)
                    if val is not None:
                        hits.append((val, kw.value))
                for val, anchor in hits:
                    yield ctx.diag(
                        self.id, anchor,
                        "python scalar %r passed as a traced operand "
                        "of a jitted call in %s (hot path): the "
                        "program cache keys scalars by VALUE — each "
                        "distinct value retraces; pass a jnp array "
                        "or mark the position static"
                        % (val, qual))


# ---------------------------------------------------------------------------
# wire-manifest-schema (PR 19 satellite): the four shipped protocol
# machines must declare their WIRE_VERBS through the shared
# declare_verbs() schema helper — a bare dict has no vocabulary
# validation and is invisible to the --protocol verifier.
# ---------------------------------------------------------------------------

@register_rule
class WireManifestSchema(Rule):
    id = "wire-manifest-schema"
    description = ("shipped WIRE_VERBS manifests must go through "
                   "kvstore.wire_verbs.declare_verbs (schema-validated, "
                   "protocol-verifier visible), not a bare dict")
    invariant_from = "PR 19"
    path_patterns = ("mxnet_tpu/kvstore/server.py",
                     "mxnet_tpu/serve/server.py",
                     "mxnet_tpu/serve/router.py",
                     "mxnet_tpu/fleet.py")

    def check(self, ctx: FileContext) -> Iterator[Diagnostic]:
        for node in ast.walk(ctx.tree):
            target = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                target = node.targets[0].id
            elif isinstance(node, ast.AnnAssign) and \
                    isinstance(node.target, ast.Name):
                target = node.target.id
            if target != "WIRE_VERBS":
                continue
            val = getattr(node, "value", None)
            is_declared = (isinstance(val, ast.Call) and
                           _attr_chain(val.func) is not None and
                           _attr_chain(val.func)[-1] == "declare_verbs")
            if not is_declared:
                yield ctx.diag(
                    self.id, node,
                    "WIRE_VERBS here must be built by declare_verbs() "
                    "from mxnet_tpu/kvstore/wire_verbs.py — a bare "
                    "dict skips schema validation and hides this "
                    "machine from `python -m tools.mxlint --protocol`")


# registered last so --list-rules / --select see the --protocol lane's
# rule ids (scope='protocol': skipped by the file and project passes,
# executed only inside tools/mxlint/protocol.py's check_sources)
from . import protocol as _protocol  # noqa: E402,F401
