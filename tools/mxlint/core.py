"""mxlint framework: diagnostics, rule registry, suppressions, baseline.

Everything here is file-local static analysis over stdlib ``ast`` — rules
never import the code under analysis, so a broken tree still lints.  The
deliberately simple analyses (per-file call graph, alias maps, literal
env names) trade soundness for zero-dependency robustness; the baseline
file absorbs the approximation errors that fixing would not pay for.
"""
from __future__ import annotations

import ast
import fnmatch
import hashlib
import json
import os
import re
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set, Tuple

__all__ = ["Diagnostic", "FileContext", "Rule", "RULES", "register_rule",
           "lint_source", "lint_sources", "lint_paths", "load_baseline",
           "write_baseline", "collect_env_reads", "load_catalog_names",
           "repo_root_of"]


# ---------------------------------------------------------------------------
# Diagnostics
# ---------------------------------------------------------------------------

class Diagnostic:
    """One finding: rule id + location + message + the source line.

    The baseline fingerprint is (path, rule, stripped source line) — line
    NUMBERS drift with every edit, line TEXT only changes when the
    violation itself is touched, which is exactly when a grandfathered
    entry should come back up for review.

    A concurrency finding can span TWO sites (a write and a conflicting
    read in another function or file).  It is always ANCHORED on the
    write site — fingerprint, suppression comment and baseline entry key
    on that one line — and names the peer in ``peer``/``message``, so
    line drift at the peer never invalidates the fingerprint.  ``threads``
    carries the thread roots involved (for the JSON schema).
    """

    __slots__ = ("rule", "path", "line", "col", "message", "snippet",
                 "threads", "peer")

    def __init__(self, rule: str, path: str, line: int, col: int,
                 message: str, snippet: str = "",
                 threads: Tuple[str, ...] = (), peer: Optional[str] = None):
        self.rule = rule
        self.path = path.replace(os.sep, "/")
        self.line = line
        self.col = col
        self.message = message
        self.snippet = snippet.strip()
        self.threads = tuple(threads)
        self.peer = peer

    def fingerprint(self) -> Tuple[str, str, str]:
        return (self.path, self.rule, self.snippet)

    def fingerprint_id(self) -> str:
        """Stable machine id of the fingerprint (survives line drift:
        hashes path+rule+source text, never line numbers or the peer)."""
        blob = "\x00".join(self.fingerprint()).encode("utf-8")
        return hashlib.sha1(blob).hexdigest()[:16]

    def to_json(self) -> Dict:
        out = {"rule": self.rule, "path": self.path, "line": self.line,
               "col": self.col, "message": self.message,
               "snippet": self.snippet,
               "fingerprint": self.fingerprint_id(),
               "threads": list(self.threads)}
        if self.peer:
            out["peer"] = self.peer
        return out

    def __repr__(self):
        return "%s:%d:%d: %s: %s" % (self.path, self.line, self.col,
                                     self.rule, self.message)


# ---------------------------------------------------------------------------
# Suppressions:  # mxlint: disable=rule-a,rule-b   (or disable=all)
# ---------------------------------------------------------------------------

_SUPPRESS_RE = re.compile(r"#\s*mxlint:\s*disable=([A-Za-z0-9_,\- ]+)")
_SUPPRESS_FILE_RE = re.compile(r"#\s*mxlint:\s*disable-file=([A-Za-z0-9_,\- ]+)")


def _parse_suppressions(lines: Sequence[str]):
    per_line: Dict[int, Set[str]] = {}
    per_file: Set[str] = set()
    for i, text in enumerate(lines, start=1):
        m = _SUPPRESS_RE.search(text)
        if m:
            per_line[i] = {r.strip() for r in m.group(1).split(",") if r.strip()}
        m = _SUPPRESS_FILE_RE.search(text)
        if m:
            per_file |= {r.strip() for r in m.group(1).split(",") if r.strip()}
    return per_line, per_file


# ---------------------------------------------------------------------------
# Per-file context handed to every rule
# ---------------------------------------------------------------------------

class FileContext:
    """Parsed file + shared lazy analyses (alias maps, function index,
    call graph) so each rule doesn't re-derive them."""

    def __init__(self, path: str, source: str,
                 catalog: Optional[Set[str]] = None):
        self.path = path.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        # known env vars (from base.ENV_CATALOG); None = unknown, skip the
        # registry-membership half of env-var-registry
        self.catalog = catalog
        self._functions = None
        self._call_graph = None
        self._import_aliases = None

    # -- source helpers -----------------------------------------------------
    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def diag(self, rule: str, node: ast.AST, message: str) -> Diagnostic:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Diagnostic(rule, self.path, line, col, message,
                          self.line_text(line))

    # -- import alias map ---------------------------------------------------
    @property
    def import_aliases(self) -> Dict[str, str]:
        """local name -> dotted origin, e.g. {'_time': 'time',
        'np': 'numpy', 'monotonic': 'time.monotonic'}."""
        if self._import_aliases is None:
            aliases: Dict[str, str] = {}
            for node in ast.walk(self.tree):
                if isinstance(node, ast.Import):
                    for a in node.names:
                        if a.asname:
                            aliases[a.asname] = a.name
                        else:
                            # `import os.path` binds the NAME `os` to the
                            # module `os` — mapping it to the full dotted
                            # path would blind every os.environ/time.*
                            # detector in files that import submodules
                            head = a.name.split(".")[0]
                            aliases[head] = head
                elif isinstance(node, ast.ImportFrom) and node.module:
                    for a in node.names:
                        aliases[a.asname or a.name] = \
                            "%s.%s" % (node.module, a.name)
            self._import_aliases = aliases
        return self._import_aliases

    def resolves_to(self, node: ast.AST, dotted: str) -> bool:
        """True if `node` (the func of a Call) names `dotted` (e.g.
        'time.monotonic' or 'os.environ.get') through any import alias."""
        chain = _attr_chain(node)
        if chain is None:
            return False
        head, rest = chain[0], chain[1:]
        origin = self.import_aliases.get(head, head)
        full = ".".join([origin] + rest)
        return full == dotted

    # -- function index / call graph ---------------------------------------
    @property
    def functions(self) -> Dict[str, ast.AST]:
        """qualname ('Class.method' or 'func') -> FunctionDef.  Nested
        defs belong to their enclosing function (their bodies are scanned
        as part of it)."""
        if self._functions is None:
            idx: Dict[str, ast.AST] = {}
            for node in self.tree.body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    idx[node.name] = node
                elif isinstance(node, ast.ClassDef):
                    for sub in node.body:
                        if isinstance(sub, (ast.FunctionDef,
                                            ast.AsyncFunctionDef)):
                            idx["%s.%s" % (node.name, sub.name)] = sub
            self._functions = idx
        return self._functions

    @property
    def call_graph(self) -> Dict[str, Set[str]]:
        """qualname -> set of callee qualnames (same-file resolution:
        ``self.m()``/``cls.m()``/``super().m()`` -> a method m in this
        file, bare ``f()`` -> a module-level f)."""
        if self._call_graph is None:
            methods_by_name: Dict[str, List[str]] = {}
            for qual in self.functions:
                if "." in qual:
                    methods_by_name.setdefault(
                        qual.split(".", 1)[1], []).append(qual)
            graph: Dict[str, Set[str]] = {}
            for qual, fn in self.functions.items():
                callees: Set[str] = set()
                for node in ast.walk(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    f = node.func
                    if isinstance(f, ast.Name) and f.id in self.functions:
                        callees.add(f.id)
                    elif isinstance(f, ast.Attribute):
                        recv = f.value
                        is_selfish = (
                            isinstance(recv, ast.Name)
                            and recv.id in ("self", "cls")) or (
                            isinstance(recv, ast.Call)
                            and isinstance(recv.func, ast.Name)
                            and recv.func.id == "super")
                        if is_selfish:
                            own_class = qual.split(".", 1)[0] \
                                if "." in qual else None
                            own = "%s.%s" % (own_class, f.attr)
                            if own in self.functions:
                                callees.add(own)
                            else:
                                # over-approximate: any class in this file
                                # with a method of that name
                                callees.update(
                                    methods_by_name.get(f.attr, ()))
                graph[qual] = callees
            self._call_graph = graph
        return self._call_graph

    def reachable_from(self, roots: Iterable[str]) -> Set[str]:
        seen: Set[str] = set()
        stack = [r for r in roots if r in self.functions]
        while stack:
            q = stack.pop()
            if q in seen:
                continue
            seen.add(q)
            stack.extend(self.call_graph.get(q, ()))
        return seen


def _attr_chain(node: ast.AST) -> Optional[List[str]]:
    """['os', 'environ', 'get'] for os.environ.get; None if not a plain
    Name/Attribute chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return None


# ---------------------------------------------------------------------------
# Rule registry
# ---------------------------------------------------------------------------

class Rule:
    """Base class: subclasses set `id`/`description`/`invariant_from` and
    implement check(ctx) -> iterator of Diagnostics.

    ``scope`` is ``"file"`` (checked per file against a FileContext),
    ``"project"`` (checked once against the whole-program ProjectIndex —
    see tools/mxlint/project.py; such rules implement
    ``check_project(project)`` instead), or ``"protocol"`` (run only by
    the ``--protocol`` wire-protocol verifier in
    tools/mxlint/protocol.py; registered here so --list-rules/--select
    see the ids, skipped by both the file and project passes, and —
    unlike the other scopes — never baselined)."""

    id: str = ""
    description: str = ""
    scope: str = "file"
    # which PR introduced the invariant this rule enforces (docs table)
    invariant_from: str = ""
    # fnmatch patterns (posix, repo-relative) this rule applies to;
    # empty = every linted file
    path_patterns: Tuple[str, ...] = ()

    def applies_to(self, path: str) -> bool:
        if not self.path_patterns:
            return True
        return any(fnmatch.fnmatch(path, pat) for pat in self.path_patterns)

    def check(self, ctx: FileContext) -> Iterator[Diagnostic]:
        raise NotImplementedError


RULES: Dict[str, Rule] = {}


def register_rule(cls):
    assert cls.id and cls.id not in RULES, cls
    RULES[cls.id] = cls()
    return cls


# ---------------------------------------------------------------------------
# Baseline: grandfathered violations, matched by fingerprint multiset
# ---------------------------------------------------------------------------

def load_baseline(path: str) -> Dict[Tuple[str, str, str], int]:
    with open(path) as f:
        data = json.load(f)
    counts: Dict[Tuple[str, str, str], int] = {}
    for e in data.get("entries", []):
        key = (e["path"], e["rule"], e["snippet"])
        counts[key] = counts.get(key, 0) + int(e.get("count", 1))
    return counts


def load_baseline_whys(path: str) -> Dict[Tuple[str, str, str], str]:
    """The reviewer-written justification (`why`) of each baseline
    entry, keyed like load_baseline().  Baselining policy (docs/TESTING
    §5): every concurrency-rule entry MUST carry one."""
    with open(path) as f:
        data = json.load(f)
    return {(e["path"], e["rule"], e["snippet"]): e["why"]
            for e in data.get("entries", []) if e.get("why")}


def write_baseline(path: str, diags: Sequence[Diagnostic],
                   extra_counts: Optional[Dict[Tuple[str, str, str],
                                               int]] = None,
                   whys: Optional[Dict[Tuple[str, str, str],
                                       str]] = None) -> None:
    """Write `diags` as the baseline; `extra_counts` carries entries to
    preserve verbatim (e.g. for files a narrowed scan never visited) and
    `whys` reattaches per-entry justifications so a regeneration never
    drops the review trail."""
    counts: Dict[Tuple[str, str, str], int] = dict(extra_counts or {})
    for d in diags:
        counts[d.fingerprint()] = counts.get(d.fingerprint(), 0) + 1
    whys = whys or {}
    entries = []
    for (p, r, s), c in sorted(counts.items()):
        e = {"path": p, "rule": r, "snippet": s, "count": c}
        if (p, r, s) in whys:
            e["why"] = whys[(p, r, s)]
        entries.append(e)
    with open(path, "w") as f:
        json.dump({"version": 1, "entries": entries}, f, indent=1,
                  sort_keys=True)
        f.write("\n")


def apply_baseline(diags: Sequence[Diagnostic],
                   baseline: Dict[Tuple[str, str, str], int]):
    """Split diagnostics into (new, grandfathered); also return baseline
    entries that matched nothing (stale — candidates for re-baseline)."""
    budget = dict(baseline)
    new: List[Diagnostic] = []
    old: List[Diagnostic] = []
    for d in diags:
        key = d.fingerprint()
        if budget.get(key, 0) > 0:
            budget[key] -= 1
            old.append(d)
        else:
            new.append(d)
    stale = [k for k, c in budget.items() if c > 0]
    return new, old, stale


# ---------------------------------------------------------------------------
# Catalog extraction (env-var-registry): parse base.py's ENV_CATALOG keys
# without importing it
# ---------------------------------------------------------------------------

def load_catalog_names(root: str) -> Optional[Set[str]]:
    base_py = os.path.join(root, "mxnet_tpu", "base.py")
    if not os.path.isfile(base_py):
        return None
    with open(base_py) as f:
        tree = ast.parse(f.read(), filename=base_py)
    for node in ast.walk(tree):
        target = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            target = node.targets[0].id
        elif isinstance(node, ast.AnnAssign) and \
                isinstance(node.target, ast.Name):
            target = node.target.id
        if target == "ENV_CATALOG" and \
                isinstance(getattr(node, "value", None), ast.Dict):
            names = set()
            for k in node.value.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    names.add(k.value)
            return names
    return None


def repo_root_of(path: str) -> Optional[str]:
    """Nearest ancestor of `path` containing mxnet_tpu/base.py."""
    p = os.path.abspath(path)
    if os.path.isfile(p):
        p = os.path.dirname(p)
    while True:
        if os.path.isfile(os.path.join(p, "mxnet_tpu", "base.py")):
            return p
        parent = os.path.dirname(p)
        if parent == p:
            return None
        p = parent


# ---------------------------------------------------------------------------
# Entry points
# ---------------------------------------------------------------------------

def _suppressed(d: Diagnostic, per_line, per_file) -> bool:
    if d.rule in per_file or "all" in per_file:
        return True
    sup = per_line.get(d.line, ())
    return d.rule in sup or "all" in sup


def _project_wanted(select: Optional[Set[str]]) -> bool:
    if select is None:
        return True
    return any(r.scope == "project" and r.id in select
               for r in RULES.values())


def _lint_one_file(path: str, source: str,
                   catalog: Optional[Set[str]],
                   select: Optional[Set[str]],
                   want_summary: bool = True):
    """File-scope pass over one source: returns (diags, summary,
    per_line_supp, per_file_supp).  `summary` is the picklable
    project-pass extraction (None when the file does not parse, or when
    a --select narrowed the run to file rules only) — this is the unit
    of work ``--jobs N`` farms out to worker processes."""
    try:
        ctx = FileContext(path, source, catalog=catalog)
    except SyntaxError as e:
        return ([Diagnostic("mxlint-parse", path, e.lineno or 1, 0,
                            "file does not parse: %s" % e.msg)],
                None, {}, set())
    per_line, per_file = _parse_suppressions(ctx.lines)
    out: List[Diagnostic] = []
    for rule in RULES.values():
        if rule.scope != "file":
            continue
        if select is not None and rule.id not in select:
            continue
        if not rule.applies_to(ctx.path):
            continue
        for d in rule.check(ctx):
            if not _suppressed(d, per_line, per_file):
                out.append(d)
    summary = None
    if want_summary:
        from . import project as _project
        summary = _project.summarize(ctx.tree, ctx.path, ctx.lines)
    return out, summary, per_line, per_file


def _dedupe_sort(diags: Sequence[Diagnostic]) -> List[Diagnostic]:
    # dedupe: nested Attribute chains can hit one detector twice per line
    seen = set()
    uniq = []
    for d in diags:
        key = (d.rule, d.path, d.line, d.message)
        if key not in seen:
            seen.add(key)
            uniq.append(d)
    uniq.sort(key=lambda d: (d.path, d.line, d.col, d.rule))
    return uniq


def lint_sources(sources: Dict[str, str],
                 catalog: Optional[Set[str]] = None,
                 select: Optional[Set[str]] = None,
                 return_project: bool = False):
    """Lint a {repo-relative path: source} mapping: per-file rules on
    each file, then the whole-program concurrency pass over all of them
    together.  Returns the diagnostics (and the ProjectIndex when
    ``return_project``)."""
    from . import project as _project
    want_project = return_project or _project_wanted(select)
    diags: List[Diagnostic] = []
    summaries = {}
    supp = {}
    for path, source in sources.items():
        path = path.replace(os.sep, "/")
        file_diags, summary, per_line, per_file = _lint_one_file(
            path, source, catalog, select, want_summary=want_project)
        diags.extend(file_diags)
        if summary is not None:
            summaries[path] = summary
        supp[path] = (per_line, per_file)
    index = None
    if want_project:
        index = _project.ProjectIndex(summaries)
        diags.extend(_project_pass(index, supp, select))
    out = _dedupe_sort(diags)
    if return_project:
        return out, index
    return out


def _project_pass(index, supp, select) -> List[Diagnostic]:
    out: List[Diagnostic] = []
    for rule in RULES.values():
        if rule.scope != "project":
            continue
        if select is not None and rule.id not in select:
            continue
        for d in rule.check_project(index):
            per_line, per_file = supp.get(d.path, ({}, set()))
            if not _suppressed(d, per_line, per_file):
                out.append(d)
    return out


def lint_source(source: str, path: str,
                catalog: Optional[Set[str]] = None,
                select: Optional[Set[str]] = None) -> List[Diagnostic]:
    """Lint one source string as repo-relative `path`.  Returns ALL
    diagnostics after suppression comments (baseline is the caller's
    job).  Syntax errors surface as a single mxlint-parse diagnostic —
    a file that doesn't parse can't be certified.  Project-scope rules
    run over the single-file 'program' (thread roots inside this file
    are still discovered)."""
    return lint_sources({path: source}, catalog=catalog, select=select)


_SKIP_DIRS = {"__pycache__", ".git", "node_modules", ".venv", "fixtures"}


def iter_py_files(paths: Sequence[str]) -> Iterator[str]:
    for p in paths:
        if os.path.isfile(p):
            yield p
        else:
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames
                                     if d not in _SKIP_DIRS)
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)


def _parallel_worker(item):
    """Module-level so ProcessPoolExecutor can pickle it.  One file in,
    (rel, diags, summary, per_line_supp, per_file_supp) out."""
    fp, rel, catalog, select, want_summary = item
    with open(fp, encoding="utf-8") as f:
        src = f.read()
    diags, summary, per_line, per_file = _lint_one_file(
        rel, src, catalog, select, want_summary=want_summary)
    return rel, diags, summary, per_line, per_file


def lint_paths(paths: Sequence[str], root: Optional[str] = None,
               select: Optional[Set[str]] = None, jobs: int = 1,
               return_project: bool = False):
    """Lint files/trees: per-file rules on each file (parsed in ``jobs``
    worker processes when > 1), then ONE whole-program concurrency pass
    over everything scanned.  Paths in diagnostics are repo-relative (to
    the detected root containing mxnet_tpu/base.py) so baselines and
    path patterns are machine-independent.

    Note the project pass only sees the files given: linting a single
    file still discovers the thread roots *inside* it, but conflicts
    against unscanned files are invisible — the shipped gate therefore
    always scans the full runtime tree."""
    if root is None:
        root = repo_root_of(paths[0] if paths else ".") or os.getcwd()
    catalog = load_catalog_names(root)
    from . import project as _project
    want_project = return_project or _project_wanted(select)
    items = []
    for fp in iter_py_files(paths):
        rel = os.path.relpath(os.path.abspath(fp), root).replace(os.sep, "/")
        items.append((fp, rel, catalog, select, want_project))
    results = None
    if jobs and jobs > 1 and len(items) > 1:
        try:
            import concurrent.futures as _cf
            with _cf.ProcessPoolExecutor(max_workers=jobs) as pool:
                results = list(pool.map(_parallel_worker, items,
                                        chunksize=8))
        except Exception:
            # sandboxes without process spawning fall back silently —
            # results are identical either way, only slower
            results = None
    if results is None:
        results = [_parallel_worker(it) for it in items]
    diags: List[Diagnostic] = []
    summaries = {}
    supp = {}
    for rel, file_diags, summary, per_line, per_file in results:
        diags.extend(file_diags)
        if summary is not None:
            summaries[rel] = summary
        supp[rel] = (per_line, per_file)
    index = None
    if want_project:
        index = _project.ProjectIndex(summaries)
        diags.extend(_project_pass(index, supp, select))
    diags = _dedupe_sort(diags)
    if return_project:
        return diags, index
    return diags


# ---------------------------------------------------------------------------
# Env-read scanner (shared with tools/gen_env_docs.py --check)
# ---------------------------------------------------------------------------

_ENV_NAME_RE = re.compile(r"^MX(?:NET)?_[A-Z0-9_]+$")


def collect_env_reads(paths: Sequence[str]) -> Dict[str, List[str]]:
    """name -> ['path:line', ...] for every literal MX_*/MXNET_* env read
    (os.environ.get/[]/os.getenv/base.get_env) in the trees."""
    found: Dict[str, List[str]] = {}

    def note(name, rel, lineno):
        if _ENV_NAME_RE.match(name):
            found.setdefault(name, []).append("%s:%d" % (rel, lineno))

    for fp in iter_py_files(paths):
        rel = fp.replace(os.sep, "/")
        try:
            with open(fp, encoding="utf-8") as f:
                tree = ast.parse(f.read(), filename=fp)
        except SyntaxError:
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                chain = _attr_chain(node.func)
                tail = chain[-1] if chain else None
                if tail in ("get_env", "getenv") or \
                        (chain and chain[-2:] == ["environ", "get"]):
                    if node.args and isinstance(node.args[0], ast.Constant) \
                            and isinstance(node.args[0].value, str):
                        note(node.args[0].value, rel, node.lineno)
            elif isinstance(node, ast.Subscript):
                chain = _attr_chain(node.value)
                if chain and chain[-1] == "environ":
                    sl = node.slice
                    if isinstance(sl, ast.Constant) and \
                            isinstance(sl.value, str):
                        note(sl.value, rel, node.lineno)
    return found
