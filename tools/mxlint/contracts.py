"""Program-contract verifier: device-free donation/HBM/retrace proofs
(ISSUE 11 tentpole).

The AST lanes prove what the *source* cannot do; this lane proves what
the *compiled executables* will do — without a TPU.  Every contracted
jit site (see ``mxnet_tpu.programs.declare_contract``; ``step.py``, the
serve bucket table, the fused optimizer kernels, the quantization wire
kernels and the kvstore exchange bodies all declare) is lowered with
abstract ``jax.ShapeDtypeStruct`` inputs under ``JAX_PLATFORMS=cpu``
via ``jit(fn).lower(*abstract).compile()`` and three theorems are
checked:

* **donation-aliasing** — every leaf the contract declares donated
  actually appears in the executable's input→output aliasing
  (``tf.aliasing_output`` in the lowered module).  XLA silently DROPS a
  donation whose shape/dtype matches no output; CPU never exercises
  donation at runtime, so the first symptom used to be doubled HBM on
  TPU.  jax's "Some donated buffers were not usable" lowering warning
  is captured and attached to the finding.  Donated-but-*unused* args
  (jax prunes them; e.g. the bf16 weights of an mp Adam apply, whose
  new values derive from the fp32 masters) are counted separately and
  NOTED, not flagged — a pruned donation is a no-op, not a leak.
* **hbm-budget** — the compiled ``memory_analysis`` temp bytes fit the
  contract's declared ``temp_budget_bytes``: the static HBM-creep gate
  (the dynamic twin is tools/bench_compare.py's peak-temp history
  gate).  Budget bumps are reviewed like baseline entries —
  docs/TESTING.md §5.
* **trace-closure** — for contracts with a closure spec, every
  reachable workload point (each admissible serve batch size, each
  configured step window) resolves to a trace signature inside the
  declared case set; a miss is rendered through the PR-10 retrace
  explainer diff so the offending arg is named.  "Zero serve-time
  retraces" becomes a theorem instead of a bench observation.

Exit contract matches the AST lane: 0 clean, 1 findings, 2 internal
error.  ``--format json`` emits the machine schema
(``contract_schema``); ``--write-manifest`` refreshes the checked-in
``tools/mxlint/contracts.json`` (validated by
``tools/bench_compare.py --check-schema``).
"""
from __future__ import annotations

import json
import os
import re
import time
import warnings
from typing import Any, Dict, List, Optional, Tuple

from .core import Diagnostic

RULE_DONATION = "contract-donation-dropped"
RULE_BUDGET = "contract-hbm-budget"
RULE_CLOSURE = "contract-trace-closure"
RULE_ERROR = "contract-error"

DEFAULT_MANIFEST = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "contracts.json")

# modules whose import declares the shipped tree's contracts (lazy
# builders; importing costs dict inserts, not traces)
DECLARING_MODULES = (
    "mxnet_tpu.step",
    "mxnet_tpu.serve.servable",
    "mxnet_tpu.ops.optimizer",
    "mxnet_tpu.ops.quantization",
    "mxnet_tpu.kvstore.kvstore",
)

_ALIAS_RE = re.compile(r"tf\.aliasing_output")
# sharded lowerings (inputs carrying NamedShardings, ISSUE 14) mark
# donations as `jax.buffer_donor = true` instead: the in/out aliasing
# decision is deferred to XLA (shardings may legally differ), so the
# donor attribute is the strongest device-free witness that the
# declared donation reached the executable — jax's not-usable warning
# still fires at compile when a donor cannot be consumed
_DONOR_RE = re.compile(r"jax\.buffer_donor\s*=\s*true")
_DROP_WARNING = "donated buffers were not usable"


def _ensure_device_free():
    """The proofs must not depend on (or grab) an accelerator: force the
    CPU backend unless the operator explicitly chose a platform.  The
    sharded step contracts (ISSUE 14) lower over {dp, dp×fsdp,
    dp×fsdp×tp} meshes, so the CPU backend is faked out to 8 devices —
    the same flag tests/conftest.py sets — when the operator has not
    already pinned a device count."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = \
            (flags + " --xla_force_host_platform_device_count=8").strip()


def load_contracts(extra_modules: Tuple[str, ...] = ()):
    """Import the declaring modules and return the registered contracts."""
    _ensure_device_free()
    import importlib
    for mod in tuple(DECLARING_MODULES) + tuple(extra_modules):
        importlib.import_module(mod)
    from mxnet_tpu import programs
    return programs.contracts()


def _rel(path: Optional[str], root: str) -> str:
    if not path:
        return "<contracts>"
    try:
        rel = os.path.relpath(os.path.abspath(path), root)
    except ValueError:
        return path.replace(os.sep, "/")
    return rel.replace(os.sep, "/")


def _origin(contract, root: str) -> Tuple[str, int]:
    if contract.origin:
        return _rel(contract.origin[0], root), int(contract.origin[1])
    return "<contracts>", 1


class CaseResult:
    """One lowered case's measured facts (one row of the budget table)."""

    __slots__ = ("contract", "program", "label", "donated_expected",
                 "aliased", "pruned", "dropped", "temp_bytes", "memory",
                 "budget", "compile_seconds")

    def __init__(self, contract: str, program: str, label: str):
        self.contract = contract
        self.program = program
        self.label = label
        self.donated_expected = 0
        self.aliased = 0
        self.pruned = 0
        self.dropped = 0
        self.temp_bytes: Optional[int] = None
        self.memory: Optional[Dict[str, int]] = None
        self.budget: Optional[int] = None
        self.compile_seconds = 0.0

    def to_json(self) -> Dict[str, Any]:
        return {k: getattr(self, k) for k in self.__slots__}


def _donated_leaves(case, donate_argnums) -> int:
    import jax
    return sum(len(jax.tree_util.tree_leaves(case.args[i]))
               for i in donate_argnums if i < len(case.args))


def _verify_case(contract, case, root: str):
    """Lower+compile one case; returns (CaseResult, [Diagnostic])."""
    import jax
    path, line = _origin(contract, root)
    res = CaseResult(contract.name, case.program, case.label)
    res.budget = contract.temp_budget_bytes
    diags: List[Diagnostic] = []
    res.donated_expected = _donated_leaves(case, contract.donate_argnums)

    # declaration/spec cross-check: the alias/prune arithmetic below is
    # only sound when the jit site donates EXACTLY what the contract
    # declares — an undeclared jit donation could otherwise alias and
    # mask a pruned declared one.  Program wrappers expose their jit
    # kwargs; fn-cases carry theirs on the case.
    jit_kw = getattr(case.target, "jit_kw", None) \
        if case.target is not None else case.jit_kw
    if isinstance(jit_kw, dict):
        spec = tuple(sorted(int(i) for i in
                            (jit_kw.get("donate_argnums") or ())))
        if spec != contract.donate_argnums:
            diags.append(Diagnostic(
                RULE_DONATION, path, line, 0,
                "program %r (case %s): the jit site donates argnums %r "
                "but the contract declares %r — align them (the "
                "aliasing proof cannot attribute aliases across a "
                "mismatched spec)"
                % (case.program, case.label, spec,
                   contract.donate_argnums),
                snippet="contract %s" % contract.name))

    t0 = time.perf_counter()
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        lowered = case.lower()
        txt = lowered.as_text()
        compiled = lowered.compile()
    res.compile_seconds = time.perf_counter() - t0

    drop_msgs = [str(w.message) for w in rec
                 if _DROP_WARNING in str(w.message)]
    res.aliased = len(_ALIAS_RE.findall(txt)) + \
        len(_DONOR_RE.findall(txt))
    missing = max(0, res.donated_expected - res.aliased)
    if drop_msgs:
        # jax could not alias a LIVE donated buffer (shape/dtype matched
        # no output): the TPU would carry both generations of it.
        # Count the dropped buffers from the WARNING (it names each
        # aval), not from expected-aliased: an alias from a jit-spec
        # donation the contract does not declare could mask the
        # subtraction to zero while the drop is real.
        warned = sum(m.count("ShapedArray") for m in drop_msgs)
        res.dropped = max(missing, warned, 1)
        diags.append(Diagnostic(
            RULE_DONATION, path, line, 0,
            "program %r (case %s): %d of %d declared donations dropped "
            "at lowering — %s; on TPU the undonated buffer stays live "
            "next to its replacement (CPU hides this).  Make the donated "
            "leaf's shape+dtype match an output, or shrink the declared "
            "donate_argnums" % (case.program, case.label, res.dropped,
                                res.donated_expected,
                                "; ".join(drop_msgs)[:300]),
            snippet="contract %s" % contract.name))
    else:
        # no lowering warning: any shortfall is donated-but-unused args
        # jax pruned from the computation — a no-op donation, noted in
        # the table, not a finding
        res.pruned = missing

    mem = _memory_dict(compiled)
    if mem is not None:
        res.memory = mem
        res.temp_bytes = mem.get("temp_bytes")
    budget = contract.temp_budget_bytes
    if budget is not None and res.temp_bytes is not None and \
            res.temp_bytes > budget:
        diags.append(Diagnostic(
            RULE_BUDGET, path, line, 0,
            "program %r (case %s): compiled temp footprint %d bytes "
            "exceeds the contract's %d-byte budget — HBM creep; shrink "
            "the program or bump the budget WITH review (docs/TESTING.md "
            "§5 budget-bump policy)"
            % (case.program, case.label, res.temp_bytes, budget),
            snippet="contract %s" % contract.name))
    return res, diags


def _memory_dict(compiled) -> Optional[Dict[str, int]]:
    try:
        ma = compiled.memory_analysis()
        if ma is None:
            return None
        return {
            "argument_bytes": int(ma.argument_size_in_bytes),
            "output_bytes": int(ma.output_size_in_bytes),
            "temp_bytes": int(ma.temp_size_in_bytes),
            "alias_bytes": int(ma.alias_size_in_bytes),
        }
    except Exception:
        return None


def _verify_closure(contract, cases, root: str) -> List[Diagnostic]:
    """Prove the declared workload points' signatures all land in the
    compiled case set; render misses through the retrace explainer."""
    from mxnet_tpu import programs
    closure = contract.closure
    if callable(closure) and not isinstance(closure,
                                            programs.ContractClosure):
        closure = closure()
    if closure is None:
        return []
    path, line = _origin(contract, root)
    case_sigs = {}
    for case in cases:
        case_sigs[programs.signature_of(tuple(case.args),
                                        case.kwargs)] = case
    diags: List[Diagnostic] = []
    for point in closure.points:
        args = closure.resolve(point)
        if args is None:
            continue        # provably rejected before any jit
        sig = programs.signature_of(tuple(args), {})
        if sig in case_sigs:
            continue
        # nearest declared case (same tree structure first) for the
        # explainer diff, so the offending arg is NAMED
        near = None
        for csig, case in case_sigs.items():
            if csig[0] == sig[0]:
                near = (csig, case)
                break
        if near is None and case_sigs:
            near = next(iter(case_sigs.items()))
        detail = ""
        if near is not None:
            diff = programs.diff_signatures(near[0], sig)
            if diff is not None:
                detail = " vs case %s: %s" % (
                    near[1].label, programs._format_diff(diff))
        diags.append(Diagnostic(
            RULE_CLOSURE, path, line, 0,
            "contract %r: workload point %r dispatches a trace "
            "signature OUTSIDE the declared case set (a run-time "
            "retrace the zero-retrace proof does not cover)%s"
            % (contract.name, point, detail),
            snippet="contract %s" % contract.name))
    return diags


def verify(contract_names: Optional[List[str]] = None,
           root: Optional[str] = None):
    """Run the whole lane.  Returns (diags, results, verified_names)."""
    root = root or os.getcwd()
    contracts = load_contracts()
    if contract_names:
        wanted = set(contract_names)
        contracts = [c for c in contracts if c.name in wanted]
    diags: List[Diagnostic] = []
    results: List[CaseResult] = []
    verified: List[str] = []
    for contract in contracts:
        path, line = _origin(contract, root)
        try:
            cases = contract.build()
        except Exception as e:
            diags.append(Diagnostic(
                RULE_ERROR, path, line, 0,
                "contract %r failed to build its cases: %s: %s"
                % (contract.name, type(e).__name__, e),
                snippet="contract %s" % contract.name))
            continue
        built = []
        for case in cases:
            try:
                res, case_diags = _verify_case(contract, case, root)
            except Exception as e:
                diags.append(Diagnostic(
                    RULE_ERROR, path, line, 0,
                    "contract %r case %s failed to lower/compile: %s: %s"
                    % (contract.name, case.label, type(e).__name__, e),
                    snippet="contract %s" % contract.name))
                continue
            built.append(case)
            results.append(res)
            diags.extend(case_diags)
            if case.program not in verified:
                verified.append(case.program)
        try:
            diags.extend(_verify_closure(contract, built, root))
        except Exception as e:
            diags.append(Diagnostic(
                RULE_ERROR, path, line, 0,
                "contract %r closure check failed: %s: %s"
                % (contract.name, type(e).__name__, e),
                snippet="contract %s" % contract.name))
    return diags, results, verified


def _fmt_bytes(n: Optional[int]) -> str:
    if n is None:
        return "-"
    return "{:,}".format(n)


def budget_table(results: List[CaseResult]) -> str:
    """The per-program budget table tools/lint.sh prints."""
    header = ("program", "case", "donated", "aliased", "pruned",
              "temp_bytes", "budget", "compile_s")
    rows = [header]
    for r in results:
        rows.append((r.program, r.label,
                     str(r.donated_expected), str(r.aliased),
                     str(r.pruned), _fmt_bytes(r.temp_bytes),
                     _fmt_bytes(r.budget), "%.2f" % r.compile_seconds))
    widths = [max(len(row[i]) for row in rows)
              for i in range(len(header))]
    lines = []
    for i, row in enumerate(rows):
        lines.append("  ".join(c.ljust(w) for c, w in zip(row, widths))
                     .rstrip())
        if i == 0:
            lines.append("  ".join("-" * w for w in widths))
    return "\n".join(lines)


def manifest(results: List[CaseResult]) -> Dict[str, Any]:
    """The contract-manifest document: declared contracts + this run's
    measured table.  ``schema`` is programs.CONTRACT_SCHEMA — what
    bench_compare --check-schema validates.  Each program keeps EVERY
    measured case (optimizer.fused_adam has both the plain and the mp
    lowering) — a flat {program: row} map would silently drop all but
    the last."""
    from mxnet_tpu import programs
    doc = programs.contract_manifest()
    rows: Dict[str, Any] = {}
    for r in results:
        slot = rows.setdefault(r.program, {"program": r.program,
                                           "contract": r.contract,
                                           "cases": []})
        slot["cases"].append(r.to_json())
    doc["programs"] = rows
    return doc


def run_cli(fmt: str = "text",
            write_manifest: Optional[str] = None,
            contract_names: Optional[List[str]] = None) -> int:
    _ensure_device_free()
    root = os.getcwd()
    if write_manifest and contract_names:
        # a narrowed run sees only a slice of the programs; writing it
        # out would silently erase every other program's snapshot rows
        # (and still pass check_contract_manifest — it validates shape,
        # not coverage)
        import sys
        print("mxlint --contracts: --write-manifest cannot be combined "
              "with --select (it would drop the unselected programs' "
              "rows)", file=sys.stderr)
        return 2
    try:
        if contract_names:
            known = {c.name for c in load_contracts()}
            unknown = set(contract_names) - known
            if unknown:
                # a typo'd --select must read as a usage error, never
                # as "0 contracts, clean"
                import sys
                print("mxlint --contracts: unknown contract(s): %s "
                      "(have %s)" % (", ".join(sorted(unknown)),
                                     ", ".join(sorted(known))),
                      file=sys.stderr)
                return 2
        diags, results, verified = verify(contract_names, root=root)
    except Exception as e:    # import errors etc: internal, never "clean"
        import sys
        print("mxlint --contracts: internal error: %s: %s"
              % (type(e).__name__, e), file=sys.stderr)
        return 2
    doc = manifest(results)
    if write_manifest:
        with open(write_manifest, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        print("mxlint --contracts: wrote manifest (%d programs) to %s"
              % (len(doc["programs"]), write_manifest))
    if fmt == "json":
        print(json.dumps({
            "contract_schema": doc["schema"],
            "violations": [d.to_json() for d in diags],
            "verified_programs": verified,
            "programs": doc["programs"],
        }, indent=1, sort_keys=True))
    else:
        import sys
        for d in diags:
            print("%s:%d:%d: %s: %s" % (d.path, d.line, d.col, d.rule,
                                        d.message))
        print(budget_table(results))
        print("mxlint --contracts: %d program%s verified device-free, "
              "%d finding%s"
              % (len(verified), "" if len(verified) == 1 else "s",
                 len(diags), "" if len(diags) == 1 else "s"),
              file=sys.stderr)
    return 1 if diags else 0
