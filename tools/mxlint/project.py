"""mxlint whole-program concurrency analysis (ISSUE 6).

PRs 1/2/5 made the runtime genuinely multi-threaded (kvstore heartbeat,
socketserver handlers, health watchdog, mid-backward ``_grad_hook``
callbacks); the per-file rules in ``rules.py`` cannot see the lock
discipline those features depend on.  This module adds the project-wide
pass they need:

* :func:`summarize` distills one parsed file into a picklable
  :class:`FileSummary` — functions with their ``self.X`` attribute
  accesses (and the locks lexically held at each), lock-acquisition
  events, blocking-wait sites, thread-spawn sites, call edges, plus an
  alias-aware import map — cheap enough to farm out to ``--jobs N``
  worker processes.
* :class:`ProjectIndex` stitches the summaries together: resolves call
  edges across files (import aliases, ``x = Class()`` locals, typed
  ``self._mod = module`` attributes), discovers thread entry points
  (``threading.Thread(target=...)``, socketserver handler classes,
  executor ``submit``/``map`` targets, ``._grad_hook`` assignments),
  computes which functions each thread root reaches, infers the locks
  guaranteed held at every function entry (intersection over call
  sites, a shrinking-set fixpoint), and builds the static
  lock-acquisition graph.
* Five registered project-scope rules consume the index:
  ``unguarded-shared-write``, ``inconsistent-guard``,
  ``lock-order-cycle``, ``blocking-wait-unbounded``, ``thread-leak``.

Soundness posture (same trade as the file rules): no imports of the
code under analysis, best-effort alias/type tracking, and deliberate
happens-before modelling — writes inside ``__init__`` (or helpers only
reachable from it) are pre-publication and never conflict; per-key lock
factories (``with self._lock_of(k):``) collapse to one guard token; a
socketserver handler's *own* attributes are per-connection and not
shared.  What the analysis cannot prove is suppressed inline or
baselined with a ``why`` — never silently ignored.
"""
from __future__ import annotations

import ast
import fnmatch
import re
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from .core import Diagnostic, Rule, register_rule, _attr_chain

__all__ = ["FileSummary", "ProjectIndex", "summarize", "summarize_source"]


# ---------------------------------------------------------------------------
# type tokens
# ---------------------------------------------------------------------------

# attr/local types we track.  SYNC types are excluded from shared-state
# conflicts (the primitives are internally thread-safe and only ever
# rebound pre-publication).
_SYNC_TYPES = {"Lock", "RLock", "Condition", "Event", "Semaphore",
               "Barrier"}
_EXEMPT_TYPES = _SYNC_TYPES | {"Thread", "Executor", "ThreadLocal"}

_CTOR_TYPES = {
    "threading.Lock": "Lock", "threading.RLock": "RLock",
    "threading.Condition": "Condition", "threading.Event": "Event",
    "threading.Semaphore": "Semaphore",
    "threading.BoundedSemaphore": "Semaphore",
    "threading.Barrier": "Barrier", "threading.Thread": "Thread",
    "threading.local": "ThreadLocal",
    "subprocess.Popen": "Popen",
    "concurrent.futures.ThreadPoolExecutor": "Executor",
    "concurrent.futures.ProcessPoolExecutor": "Executor",
    "concurrent.futures.thread.ThreadPoolExecutor": "Executor",
}

_GUARD_NAME_RE = re.compile(r"(lock|mutex|cv|cond\b|condition|sem)", re.I)
_EVENTISH_RE = re.compile(r"(event|stop|done|ready)", re.I)
_LOCKISH_RE = re.compile(r"(lock|mutex|sem)", re.I)
_CONDISH_RE = re.compile(r"(cv|cond)", re.I)
_PROCISH = ("proc", "process", "popen")

# container-method calls that mutate the receiver
_MUTATORS = {"append", "add", "extend", "insert", "remove", "discard",
             "pop", "popitem", "clear", "update", "setdefault", "sort",
             "reverse"}

_HANDLER_BASES = ("BaseRequestHandler", "StreamRequestHandler",
                  "DatagramRequestHandler")


# ---------------------------------------------------------------------------
# picklable summary records (plain __slots__ classes, protocol-2 safe)
# ---------------------------------------------------------------------------

class Access:
    """One ``self.X`` access: r(ead) / w(rite), with the guard tokens
    lexically held."""
    __slots__ = ("attr", "kind", "line", "col", "snippet", "guards")

    def __init__(self, attr, kind, line, col, snippet, guards):
        self.attr, self.kind = attr, kind
        self.line, self.col, self.snippet = line, col, snippet
        self.guards = frozenset(guards)


class CallSite:
    __slots__ = ("ref", "guards", "line")

    def __init__(self, ref, guards, line):
        self.ref, self.guards, self.line = ref, frozenset(guards), line


class Acq:
    """A ``with <lock>:`` entry: the new token + tokens already held."""
    __slots__ = ("token", "held", "line", "snippet")

    def __init__(self, token, held, line, snippet):
        self.token, self.held = token, tuple(held)
        self.line, self.snippet = line, snippet


class WaitSite:
    """A blocking call (wait/acquire/join) with its receiver kind."""
    __slots__ = ("kind", "recv", "has_timeout", "line", "col", "snippet")

    def __init__(self, kind, recv, has_timeout, line, col, snippet):
        self.kind, self.recv, self.has_timeout = kind, recv, has_timeout
        self.line, self.col, self.snippet = line, col, snippet


class Spawn:
    """A thread/pool-worker spawn site."""
    __slots__ = ("kind", "target", "daemon", "binding", "line", "col",
                 "snippet")

    def __init__(self, kind, target, daemon, binding, line, col, snippet):
        self.kind = kind            # 'thread' | 'pool'
        self.target = target        # ref (see _Summarizer._ref) or None
        self.daemon = daemon        # True | False | None (absent/dynamic)
        self.binding = binding      # token for join matching, or None
        self.line, self.col, self.snippet = line, col, snippet


class FuncInfo:
    __slots__ = ("qual", "owner", "accesses", "calls", "acqs", "waits",
                 "spawns", "joins", "daemon_set")

    def __init__(self, qual, owner):
        self.qual = qual
        self.owner = owner          # owning class name or None
        self.accesses: List[Access] = []
        self.calls: List[CallSite] = []
        self.acqs: List[Acq] = []
        self.waits: List[WaitSite] = []
        self.spawns: List[Spawn] = []
        self.joins: Set[str] = set()
        self.daemon_set: Set[str] = set()


class ClassInfo:
    __slots__ = ("name", "qual", "bases", "methods", "attr_types",
                 "is_handler")

    def __init__(self, name, qual, bases):
        self.name, self.qual, self.bases = name, qual, bases
        self.methods: Dict[str, str] = {}     # method name -> func qual
        self.attr_types: Dict[str, object] = {}
        self.is_handler = any(
            str(b).rsplit(".", 1)[-1] in _HANDLER_BASES for b in bases)


class WireInfo:
    """Per-file wire-protocol facts (ISSUE 11 wire-verb-exhaustive):
    client-emitted verbs, server handler comparisons, the literal
    ``WIRE_VERBS`` manifest, replay-cache verb tuples (``_CACHED`` /
    ``_MUTATING``) and ``encode_*``/``decode_*`` codec basenames."""

    __slots__ = ("emits", "handles", "manifest", "manifest_line",
                 "replay_verbs", "codecs", "meta")

    def __init__(self):
        # [(verb, line, snippet)] — calls through _rpc/_send_np, and
        # ("VERB", ...) tuple literals handed to send_msg
        self.emits: List[Tuple[str, int, str]] = []
        self.handles: Dict[str, int] = {}     # verb -> first compare line
        # verb -> {"semantics": ..., "codec": ...} from a literal
        # module/class-level WIRE_VERBS dict — either a bare dict or the
        # dict argument of a declare_verbs(...) call; None when absent
        self.manifest: Optional[Dict[str, Dict[str, object]]] = None
        self.manifest_line = 0
        self.replay_verbs: Set[str] = set()
        self.codecs: Set[Tuple[str, str]] = set()   # ("encode"|"decode", name)
        # declare_verbs(...) call-level facts: protocol name + keyword
        # options (role, durable, handler); empty for bare-dict manifests
        self.meta: Dict[str, object] = {}


class FileSummary:
    __slots__ = ("path", "module", "funcs", "classes", "aliases",
                 "hook_targets", "wire")

    def __init__(self, path, module):
        self.path, self.module = path, module
        self.funcs: Dict[str, FuncInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}
        self.aliases: Dict[str, str] = {}
        # ``X._grad_hook = <callable>`` assignment targets: overlap-
        # exchange callbacks that fire mid-backward (ISSUE 5)
        self.hook_targets: List[Tuple[object, int]] = []
        self.wire = WireInfo()


# ---------------------------------------------------------------------------
# alias map (path-aware: resolves relative imports against the file path)
# ---------------------------------------------------------------------------

def _module_of(path: str) -> str:
    mod = path[:-3] if path.endswith(".py") else path
    mod = mod.replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


def _build_aliases(tree: ast.AST, path: str) -> Dict[str, str]:
    pkg = _module_of(path)
    if not path.endswith("/__init__.py"):
        pkg = pkg.rsplit(".", 1)[0] if "." in pkg else ""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    out[a.asname] = a.name
                else:
                    head = a.name.split(".")[0]
                    out[head] = head
        elif isinstance(node, ast.ImportFrom):
            base = node.module or ""
            if node.level:
                parts = pkg.split(".") if pkg else []
                keep = parts[: max(0, len(parts) - (node.level - 1))]
                base = ".".join(keep + ([node.module] if node.module
                                        else []))
            for a in node.names:
                if a.name == "*":
                    continue
                out[a.asname or a.name] = \
                    ("%s.%s" % (base, a.name)) if base else a.name
    return out


# ---------------------------------------------------------------------------
# the summarizer
# ---------------------------------------------------------------------------

class _Scope:
    """One function (or module) name scope; closure lookups walk up."""
    __slots__ = ("qual", "types", "defs", "parent")

    def __init__(self, qual, parent):
        self.qual = qual
        self.types: Dict[str, object] = {}
        self.defs: Dict[str, str] = {}    # local def name -> func qual
        self.parent = parent

    def lookup(self, name):
        s = self
        while s is not None:
            if name in s.types:
                return s.types[name], s.qual
            s = s.parent
        return None, None

    def lookup_def(self, name):
        s = self
        while s is not None:
            if name in s.defs:
                return s.defs[name]
            s = s.parent
        return None


class _Summarizer:
    def __init__(self, path: str, tree: ast.AST, lines: Sequence[str]):
        self.path = path
        self.tree = tree
        self.lines = lines
        self.summary = FileSummary(path, _module_of(path))
        self.summary.aliases = _build_aliases(tree, path)
        self.class_stack: List[ClassInfo] = []
        self.func_stack: List[FuncInfo] = []
        # module scope is named by its dotted module so module-level
        # lock tokens (`_clock_lock` in fault.py vs `_lock` in two other
        # files) never collide across files in the project lock graph
        self.scope: _Scope = _Scope(self.summary.module, None)
        self.guards: List[str] = []
        self.qual_stack: List[str] = []
        self._container_writes: Set[int] = set()  # Attribute node ids
        self._collect_class_types()
        # a synthetic FuncInfo for module-level statements
        self._module_fn = FuncInfo("<module>", None)
        self.summary.funcs["<module>"] = self._module_fn
        self.func_stack.append(self._module_fn)
        for stmt in tree.body:
            self._visit(stmt)
        self.func_stack.pop()

    # -- helpers ------------------------------------------------------------
    def _line(self, lineno):
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def _dotted(self, node) -> Optional[str]:
        chain = _attr_chain(node)
        if not chain:
            return None
        head = chain[0]
        origin = self.summary.aliases.get(head, head)
        return ".".join([origin] + chain[1:])

    def _ctor_type(self, call: ast.Call):
        """Type token produced by a constructor-style call, or None."""
        dotted = self._dotted(call.func)
        if dotted in _CTOR_TYPES:
            return _CTOR_TYPES[dotted]
        tail = dotted.rsplit(".", 1)[-1] if dotted else None
        if tail in ("ThreadPoolExecutor", "ProcessPoolExecutor"):
            return "Executor"
        if isinstance(call.func, ast.Name) and \
                call.func.id in self.summary.classes:
            return ("class", call.func.id)
        if dotted:
            # `x = mod.Class(...)` for a class defined in this file
            parts = dotted.rsplit(".", 1)
            if len(parts) == 2 and parts[1] in self.summary.classes:
                return ("class", parts[1])
        # list()/sorted()/tuple() over a lock collection stays lockish
        if isinstance(call.func, ast.Name) and \
                call.func.id in ("list", "sorted", "tuple") and call.args:
            if self._expr_type(call.args[0]) in ("LockList", "LockDict"):
                return "LockList"
        return None

    def _owner(self) -> Optional[str]:
        return self.class_stack[-1].name if self.class_stack else None

    def _attr_type(self, attr: str):
        cls = self.class_stack[-1] if self.class_stack else None
        if cls is not None and attr in cls.attr_types:
            return cls.attr_types[attr]
        return None

    def _expr_type(self, node):
        """Best-effort type token of an expression."""
        if isinstance(node, ast.Name):
            t, _scope = self.scope.lookup(node.id)
            return t
        if isinstance(node, ast.Attribute):
            if isinstance(node.value, ast.Name) and node.value.id == "self":
                return self._attr_type(node.attr)
            # module attr through a module-typed self attribute
            return None
        if isinstance(node, ast.Call):
            t = self._ctor_type(node)
            if t is not None:
                return t
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in ("values", "keys"):
                if self._expr_type(f.value) == "LockDict":
                    return "LockList"
            return None
        return None

    # -- guard tokens -------------------------------------------------------
    def _guard_token(self, expr) -> Optional[str]:
        """Token for a with-item that acquires a lock, else None."""
        if isinstance(expr, ast.Call):
            # per-key lock factory: `with self._lock_of(key):`
            f = expr.func
            if isinstance(f, ast.Attribute) and \
                    isinstance(f.value, ast.Name) and f.value.id == "self" \
                    and _GUARD_NAME_RE.search(f.attr):
                owner = self._owner_for_self()
                if owner:
                    return "%s.%s()" % (owner, f.attr)
            return None
        chain = _attr_chain(expr)
        if not chain:
            return None
        if chain[0] == "self" and len(chain) == 2:
            attr = chain[1]
            t = self._attr_type_for_self(attr)
            if t in _SYNC_TYPES or _GUARD_NAME_RE.search(attr):
                owner = self._owner_for_self()
                if owner:
                    return "%s.%s" % (owner, attr)
            return None
        if len(chain) == 1:
            name = chain[0]
            t, scope_qual = self.scope.lookup(name)
            if t in _SYNC_TYPES or (t is None and
                                    _GUARD_NAME_RE.search(name)):
                # local/closure lock: qualify with module + defining
                # scope so same-named locals in two files (or an
                # untyped parameter named `lock`) never collapse into
                # one graph node and fabricate cross-file cycles
                if scope_qual is None:
                    scope_qual = self.scope.qual
                if scope_qual == self.summary.module:
                    return "%s.%s" % (scope_qual, name)
                return "%s.%s.%s" % (self.summary.module, scope_qual,
                                     name)
        return None

    def _owner_for_self(self) -> Optional[str]:
        """Nearest enclosing class — `self` in a nested def is a closure
        over the method's self (same instance)."""
        return self.class_stack[-1].name if self.class_stack else None

    def _attr_type_for_self(self, attr):
        cls = self.class_stack[-1] if self.class_stack else None
        if cls is not None:
            return cls.attr_types.get(attr)
        return None

    # -- pass A: class attr types ------------------------------------------
    def _collect_class_types(self):
        def scan_class(cnode: ast.ClassDef, qual: str):
            bases = [self._dotted(b) or "" for b in cnode.bases]
            info = ClassInfo(cnode.name, qual, bases)
            self.summary.classes[cnode.name] = info
            for sub in ast.walk(cnode):
                if isinstance(sub, ast.Assign):
                    val_t = None
                    if isinstance(sub.value, ast.Call):
                        val_t = self._ctor_type_early(sub.value)
                    elif isinstance(sub.value, ast.Name) and \
                            sub.value.id in self.summary.aliases:
                        dotted = self.summary.aliases[sub.value.id]
                        # `self._srv_mod = _srv` (module alias): lets
                        # `self._srv_mod.send_msg(...)` resolve cross-file
                        val_t = ("module", dotted)
                    for tgt in sub.targets:
                        if isinstance(tgt, ast.Attribute) and \
                                isinstance(tgt.value, ast.Name) and \
                                tgt.value.id == "self" and val_t:
                            info.attr_types.setdefault(tgt.attr, val_t)
                elif isinstance(sub, ast.AnnAssign) and \
                        isinstance(sub.target, ast.Attribute) and \
                        isinstance(sub.target.value, ast.Name) and \
                        sub.target.value.id == "self" and \
                        isinstance(sub.value, ast.Call):
                    val_t = self._ctor_type_early(sub.value)
                    if val_t:
                        info.attr_types.setdefault(sub.target.attr, val_t)
                elif isinstance(sub, ast.Call):
                    # `self._locks.setdefault(k, threading.Lock())` marks
                    # _locks as a lock collection
                    f = sub.func
                    if isinstance(f, ast.Attribute) and \
                            f.attr == "setdefault" and \
                            isinstance(f.value, ast.Attribute) and \
                            isinstance(f.value.value, ast.Name) and \
                            f.value.value.id == "self" and \
                            len(sub.args) == 2 and \
                            isinstance(sub.args[1], ast.Call) and \
                            self._ctor_type_early(sub.args[1]) in \
                            _SYNC_TYPES:
                        info.attr_types.setdefault(f.value.attr, "LockDict")

        def walk(node, quals):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    scan_class(child, ".".join(quals + [child.name]))
                    walk(child, quals + [child.name])
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    walk(child, quals + [child.name])
                else:
                    walk(child, quals)
        walk(self.tree, [])
        # module-level lock names
        for stmt in self.tree.body:
            if isinstance(stmt, ast.Assign) and \
                    isinstance(stmt.value, ast.Call):
                t = self._ctor_type_early(stmt.value)
                if t:
                    for tgt in stmt.targets:
                        if isinstance(tgt, ast.Name):
                            self.scope.types[tgt.id] = t

    def _ctor_type_early(self, call: ast.Call):
        dotted = self._dotted(call.func)
        if dotted in _CTOR_TYPES:
            return _CTOR_TYPES[dotted]
        tail = dotted.rsplit(".", 1)[-1] if dotted else None
        if tail in ("ThreadPoolExecutor", "ProcessPoolExecutor"):
            return "Executor"
        return None

    # -- refs ---------------------------------------------------------------
    def _ref(self, node) -> Optional[tuple]:
        """Portable reference to a callable for cross-file resolution."""
        if isinstance(node, ast.Name):
            qual = self.scope.lookup_def(node.id)
            if qual is not None:
                return ("local", qual)
            dotted = self.summary.aliases.get(node.id)
            if dotted:
                return ("dotted", dotted)
            return None
        if isinstance(node, ast.Attribute):
            recv = node.value
            if isinstance(recv, ast.Name) and recv.id in ("self", "cls"):
                owner = self._owner_for_self()
                if owner:
                    return ("method", owner, node.attr)
                return None
            if isinstance(recv, ast.Call) and \
                    isinstance(recv.func, ast.Name) and \
                    recv.func.id == "super":
                owner = self._owner_for_self()
                if owner:
                    return ("method", owner, node.attr)
                return None
            t = self._expr_type(recv)
            if isinstance(t, tuple) and t[0] == "class":
                return ("method", t[1], node.attr)
            # self.<module-typed attr>.func  /  alias.func
            if isinstance(recv, ast.Attribute) and \
                    isinstance(recv.value, ast.Name) and \
                    recv.value.id == "self":
                at = self._attr_type_for_self(recv.attr)
                if isinstance(at, tuple) and at[0] == "module":
                    return ("dotted", "%s.%s" % (at[1], node.attr))
            dotted = self._dotted(node)
            if dotted and dotted != ".".join(_attr_chain(node) or []):
                # head resolved through an import alias: cross-module
                return ("dotted", dotted)
            return None
        if isinstance(node, ast.Call):
            # functools.partial(f, ...) -> f
            dotted = self._dotted(node.func)
            if dotted in ("functools.partial", "partial") and node.args:
                return self._ref(node.args[0])
            return None
        return None

    # -- main walk ----------------------------------------------------------
    def _record_access(self, attr, kind, node):
        fn = self.func_stack[-1]
        fn.accesses.append(Access(
            attr, kind, node.lineno, node.col_offset,
            self._line(node.lineno), self.guards))

    def _visit(self, node):
        meth = getattr(self, "_visit_%s" % type(node).__name__, None)
        if meth is not None:
            meth(node)
            return
        for child in ast.iter_child_nodes(node):
            self._visit(child)

    def _visit_ClassDef(self, node: ast.ClassDef):
        info = self.summary.classes.get(node.name)
        self.qual_stack.append(node.name)
        if info is not None:
            self.class_stack.append(info)
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    info.methods[sub.name] = \
                        ".".join(self.qual_stack + [sub.name])
        for sub in node.body:
            self._visit(sub)
        if info is not None:
            self.class_stack.pop()
        self.qual_stack.pop()

    def _visit_FunctionDef(self, node):
        qual = ".".join(self.qual_stack + [node.name])
        owner = self._owner()
        fn = FuncInfo(qual, owner)
        self.summary.funcs[qual] = fn
        self.scope.defs[node.name] = qual
        for dec in node.decorator_list:
            self._visit(dec)
        self.qual_stack.append(node.name)
        self.func_stack.append(fn)
        self.scope = _Scope(qual, self.scope)
        saved_guards, self.guards = self.guards, []
        for stmt in node.body:
            self._visit(stmt)
        self.guards = saved_guards
        self.scope = self.scope.parent
        self.func_stack.pop()
        self.qual_stack.pop()

    _visit_AsyncFunctionDef = _visit_FunctionDef

    def _visit_Lambda(self, node: ast.Lambda):
        # lambdas passed to e.g. fault.fire(on_close=...) run at the call
        # site; keep the lexical guard context
        self._visit(node.body)

    def _visit_With(self, node: ast.With):
        pushed = []
        for item in node.items:
            self._visit(item.context_expr)
            tok = self._guard_token(item.context_expr)
            if tok is not None:
                fn = self.func_stack[-1]
                fn.acqs.append(Acq(tok, self.guards, node.lineno,
                                   self._line(node.lineno)))
                self.guards.append(tok)
                pushed.append(tok)
        for stmt in node.body:
            self._visit(stmt)
        for tok in pushed:
            self.guards.pop()

    _visit_AsyncWith = _visit_With

    def _visit_Assign(self, node: ast.Assign):
        # hook targets / daemon flags / type bindings, then accesses
        val_type = self._expr_type(node.value)
        if val_type is None and isinstance(node.value, ast.Name) and \
                node.value.id in self.summary.aliases:
            # bare module alias: makes `x = mod; x.f()` resolvable
            val_type = ("module", self.summary.aliases[node.value.id])
        # value FIRST: a `t = threading.Thread(...)` records its spawn
        # during the value visit, and the target handler then attaches
        # the binding name to that spawn for join matching
        self._visit(node.value)
        for tgt in node.targets:
            self._assign_target(tgt, node, val_type)

    def _assign_target(self, tgt, node, val_type):
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._assign_target(el, node, None)
            return
        if isinstance(tgt, ast.Name):
            if val_type is not None:
                self.scope.types[tgt.id] = val_type
            if isinstance(node.value, ast.Call) and \
                    self._dotted(node.value.func) == "threading.Thread":
                self._bind_last_spawn(tgt.id)
            return
        if isinstance(tgt, ast.Attribute):
            if tgt.attr == "_grad_hook":
                ref = self._ref(node.value)
                if ref is not None:
                    self.summary.hook_targets.append((ref, node.lineno))
                self._maybe_self_access(tgt, "w")
                return
            if tgt.attr == "daemon" and \
                    isinstance(node.value, ast.Constant) and \
                    node.value.value is True:
                self.func_stack[-1].daemon_set.add(
                    self._binding_token(tgt.value))
            if isinstance(tgt.value, ast.Name) and tgt.value.id == "self":
                if val_type is not None and self.class_stack:
                    self.class_stack[-1].attr_types.setdefault(
                        tgt.attr, val_type)
                self._record_self_attr(tgt, "w")
                if isinstance(node.value, ast.Call) and \
                        self._dotted(node.value.func) == "threading.Thread":
                    owner = self._owner_for_self()
                    self._bind_last_spawn(
                        "%s.%s" % (owner, tgt.attr) if owner else tgt.attr)
            else:
                self._visit(tgt.value)
            return
        if isinstance(tgt, ast.Subscript):
            base = tgt.value
            if isinstance(base, ast.Attribute) and \
                    isinstance(base.value, ast.Name) and \
                    base.value.id == "self":
                self._record_self_attr(base, "w")
            else:
                self._visit(base)
            self._visit(tgt.slice)
            return
        self._visit(tgt)

    def _bind_last_spawn(self, token):
        fn = self.func_stack[-1]
        if fn.spawns:
            fn.spawns[-1].binding = token

    def _binding_token(self, recv) -> str:
        if isinstance(recv, ast.Attribute) and \
                isinstance(recv.value, ast.Name) and recv.value.id == "self":
            owner = self._owner_for_self()
            return "%s.%s" % (owner, recv.attr) if owner else recv.attr
        if isinstance(recv, ast.Name):
            return recv.id
        chain = _attr_chain(recv)
        if chain:
            return ".".join(chain)
        return "?"

    def _visit_AugAssign(self, node: ast.AugAssign):
        tgt = node.target
        if isinstance(tgt, ast.Attribute) and \
                isinstance(tgt.value, ast.Name) and tgt.value.id == "self":
            self._record_self_attr(tgt, "w")
            self._record_self_attr(tgt, "r")
        elif isinstance(tgt, ast.Subscript) and \
                isinstance(tgt.value, ast.Attribute) and \
                isinstance(tgt.value.value, ast.Name) and \
                tgt.value.value.id == "self":
            self._record_self_attr(tgt.value, "w")
            self._visit(tgt.slice)
        else:
            self._visit(tgt)
        self._visit(node.value)

    def _visit_AnnAssign(self, node: ast.AnnAssign):
        if node.value is None:
            return
        fake = ast.Assign(targets=[node.target], value=node.value)
        ast.copy_location(fake, node)
        self._visit_Assign(fake)

    def _visit_For(self, node: ast.For):
        it_t = self._expr_type(node.iter)
        if it_t in ("LockList", "LockDict") and \
                isinstance(node.target, ast.Name):
            self.scope.types[node.target.id] = "Lock"
        self._visit(node.iter)
        for stmt in node.body + node.orelse:
            self._visit(stmt)

    def _maybe_self_access(self, attr_node: ast.Attribute, kind):
        if isinstance(attr_node.value, ast.Name) and \
                attr_node.value.id == "self":
            self._record_self_attr(attr_node, kind)
        else:
            self._visit(attr_node.value)

    def _record_self_attr(self, attr_node: ast.Attribute, kind):
        if self._owner_for_self() is None:
            return
        t = self._attr_type_for_self(attr_node.attr)
        if t in _EXEMPT_TYPES or t == "LockDict" or \
                (isinstance(t, tuple) and t[0] == "module"):
            return
        self._record_access(attr_node.attr, kind, attr_node)

    def _visit_Attribute(self, node: ast.Attribute):
        if isinstance(node.value, ast.Name) and node.value.id == "self":
            kind = "w" if isinstance(node.ctx, (ast.Store, ast.Del)) \
                else "r"
            self._record_self_attr(node, kind)
            return
        self._visit(node.value)

    def _visit_Call(self, node: ast.Call):
        f = node.func
        dotted = self._dotted(f)
        # thread spawn
        if dotted == "threading.Thread":
            target = None
            daemon = None
            for kw in node.keywords:
                if kw.arg == "target":
                    target = self._ref(kw.value)
                elif kw.arg == "daemon":
                    daemon = kw.value.value \
                        if isinstance(kw.value, ast.Constant) else None
            self.func_stack[-1].spawns.append(Spawn(
                "thread", target, daemon, None, node.lineno,
                node.col_offset, self._line(node.lineno)))
        elif isinstance(f, ast.Attribute) and f.attr in ("submit", "map") \
                and self._expr_type(f.value) == "Executor" and node.args:
            target = self._ref(node.args[0])
            self.func_stack[-1].spawns.append(Spawn(
                "pool", target, True, None, node.lineno,
                node.col_offset, self._line(node.lineno)))
        # blocking waits
        if isinstance(f, ast.Attribute) and \
                f.attr in ("wait", "wait_for", "acquire", "join"):
            self._classify_wait(node, f)
        # container mutators on self attrs
        if isinstance(f, ast.Attribute) and f.attr in _MUTATORS and \
                isinstance(f.value, ast.Attribute) and \
                isinstance(f.value.value, ast.Name) and \
                f.value.value.id == "self":
            self._record_self_attr(f.value, "w")
        # join bookkeeping for thread-leak
        if isinstance(f, ast.Attribute) and f.attr == "join":
            self.func_stack[-1].joins.add(self._binding_token(f.value))
        # call edge
        ref = self._ref(f)
        if ref is not None:
            self.func_stack[-1].calls.append(
                CallSite(ref, self.guards, node.lineno))
        # recurse
        self._visit(f)
        for a in node.args:
            self._visit(a)
        for kw in node.keywords:
            self._visit(kw.value)

    def _classify_wait(self, node: ast.Call, f: ast.Attribute):
        has_timeout = self._wait_is_bounded(node, f.attr)
        recv = f.value
        t = self._expr_type(recv)
        name = recv.attr if isinstance(recv, ast.Attribute) else (
            recv.id if isinstance(recv, ast.Name) else None)
        kind = None
        if f.attr in ("wait", "wait_for"):
            if t == "Event" or t == "Condition":
                kind = "%s.%s" % (t, f.attr)
            elif t == "Popen" or (name and name.lower() in _PROCISH):
                kind = "Popen.wait"
            elif t is None and name and _CONDISH_RE.search(name):
                kind = "Condition.wait"
            elif t is None and name and _EVENTISH_RE.search(name):
                kind = "Event.wait"
        elif f.attr == "acquire":
            if t in ("Lock", "RLock", "Semaphore") or \
                    (t is None and name and _LOCKISH_RE.search(name)):
                kind = "%s.acquire" % (t or "Lock")
        elif f.attr == "join":
            if t == "Thread" or (t is None and name and
                                 "thread" in name.lower()):
                kind = "Thread.join"
        if kind is None:
            return
        recv_tok = self._binding_token(recv) if isinstance(
            recv, (ast.Name, ast.Attribute)) else "?"
        self.func_stack[-1].waits.append(WaitSite(
            kind, recv_tok, has_timeout, node.lineno, node.col_offset,
            self._line(node.lineno)))

    @staticmethod
    def _wait_is_bounded(node: ast.Call, meth: str) -> bool:
        """Per-method timeout semantics — a positional arg is NOT
        always a timeout: ``wait_for(pred)`` still parks forever and
        ``acquire(True)`` is explicitly unbounded."""
        kw = {k.arg: k.value for k in node.keywords}
        if "timeout" in kw:
            return True
        if meth == "wait_for":
            # signature (predicate, timeout=None): only a SECOND
            # positional bounds the wait
            return len(node.args) >= 2
        if meth == "acquire":
            # (blocking=True, timeout=-1): bounded iff a timeout is
            # given or the acquire is non-blocking
            if len(node.args) >= 2:
                return True
            blocking = kw.get("blocking") or (node.args[0]
                                              if node.args else None)
            return isinstance(blocking, ast.Constant) and \
                blocking.value is False
        # wait()/join()/proc.wait(): the first positional is the timeout
        return bool(node.args)

    def _visit_Subscript(self, node: ast.Subscript):
        base = node.value
        if isinstance(node.ctx, (ast.Store, ast.Del)) and \
                isinstance(base, ast.Attribute) and \
                isinstance(base.value, ast.Name) and \
                base.value.id == "self":
            self._record_self_attr(base, "w")
        else:
            self._visit(base)
        self._visit(node.slice)


_VERB_RE = re.compile(r"^[A-Z][A-Z_]{2,}$")
# the SEQ envelope wraps verbs, it is not one; PONG is a reply payload
_NON_VERBS = {"SEQ", "PONG"}


def _verb_const(node) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str) and \
            _VERB_RE.match(node.value) and node.value not in _NON_VERBS:
        return node.value
    return None


def _wire_summary(tree: ast.AST, lines: Sequence[str]) -> WireInfo:
    """Extract the file's wire-protocol facts (see WireInfo)."""
    w = WireInfo()

    def snippet(n):
        ln = getattr(n, "lineno", 1)
        return lines[ln - 1].strip() if 1 <= ln <= len(lines) else ""

    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            f = node.func
            tail = f.attr if isinstance(f, ast.Attribute) else \
                (f.id if isinstance(f, ast.Name) else None)
            if tail in ("_rpc", "_send_np") and node.args:
                verb = _verb_const(node.args[0])
                if verb:
                    w.emits.append((verb, node.lineno, snippet(node)))
            elif tail == "send_msg":
                for a in node.args:
                    if isinstance(a, ast.Tuple) and a.elts:
                        verb = _verb_const(a.elts[0])
                        if verb:
                            w.emits.append((verb, node.lineno,
                                            snippet(node)))
        elif isinstance(node, ast.Compare):
            left_ok = isinstance(node.left, (ast.Name, ast.Subscript))
            if not left_ok:
                continue
            for op, comp in zip(node.ops, node.comparators):
                if isinstance(op, ast.Eq):
                    verb = _verb_const(comp)
                    if verb:
                        w.handles.setdefault(verb, node.lineno)
                elif isinstance(op, ast.In) and \
                        isinstance(comp, (ast.Tuple, ast.List, ast.Set)):
                    for el in comp.elts:
                        verb = _verb_const(el)
                        if verb:
                            w.handles.setdefault(verb, node.lineno)
        elif isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            tname = node.targets[0].id
            val = node.value
            if tname == "WIRE_VERBS" and isinstance(val, ast.Call):
                # declare_verbs("name", {...literal...}, role=..., ...)
                # (ISSUE 19): unwrap to the literal dict argument and
                # keep the call-level options as manifest metadata
                cf = val.func
                ctail = cf.attr if isinstance(cf, ast.Attribute) else \
                    (cf.id if isinstance(cf, ast.Name) else None)
                if ctail == "declare_verbs":
                    for pos, a in enumerate(val.args):
                        if pos == 0 and isinstance(a, ast.Constant):
                            w.meta["protocol"] = a.value
                        elif isinstance(a, ast.Dict):
                            val = a
                    for kw in node.value.keywords:
                        if kw.arg and isinstance(kw.value, ast.Constant):
                            w.meta[kw.arg] = kw.value.value
            if tname == "WIRE_VERBS" and isinstance(val, ast.Dict):
                manifest: Dict[str, Dict[str, object]] = {}
                for k, v in zip(val.keys, val.values):
                    verb = _verb_const(k)
                    if not verb or not isinstance(v, ast.Dict):
                        continue
                    entry: Dict[str, object] = {}
                    for ek, ev in zip(v.keys, v.values):
                        if not isinstance(ek, ast.Constant):
                            continue
                        if isinstance(ev, ast.Constant):
                            entry[str(ek.value)] = ev.value
                        elif isinstance(ev, (ast.Tuple, ast.List)) and \
                                all(isinstance(el, ast.Constant)
                                    for el in ev.elts):
                            entry[str(ek.value)] = tuple(
                                el.value for el in ev.elts)
                    manifest[verb] = entry
                w.manifest = manifest
                w.manifest_line = node.lineno
            elif tname in ("_CACHED", "_MUTATING") and \
                    isinstance(node.value, (ast.Tuple, ast.List)):
                for el in node.value.elts:
                    verb = _verb_const(el)
                    if verb:
                        w.replay_verbs.add(verb)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for kind in ("encode", "decode"):
                if node.name.startswith(kind + "_"):
                    w.codecs.add((kind, node.name[len(kind) + 1:]))
    return w


def summarize(tree: ast.AST, path: str,
              lines: Sequence[str]) -> FileSummary:
    summary = _Summarizer(path, tree, lines).summary
    summary.wire = _wire_summary(tree, lines)
    return summary


def summarize_source(source: str, path: str) -> Optional[FileSummary]:
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError:
        return None
    return summarize(tree, path, source.splitlines())


# ---------------------------------------------------------------------------
# the project index
# ---------------------------------------------------------------------------

class Root:
    __slots__ = ("kind", "display", "entries", "multi")

    def __init__(self, kind, display, entries, multi):
        self.kind = kind          # 'thread' | 'handler' | 'pool' | 'hook'
        self.display = display    # e.g. 'thread:KVStore._start_heartbeat.run'
        self.entries = tuple(entries)
        self.multi = multi        # may run in >1 thread concurrently


class ProjectIndex:
    """Cross-file resolution + reachability + guard inference over a set
    of :class:`FileSummary` objects (key: repo-relative path)."""

    def __init__(self, summaries: Dict[str, FileSummary]):
        self.summaries = summaries
        self.funcs: Dict[str, Tuple[str, FuncInfo]] = {}
        self.class_reg: Dict[str, List[Tuple[str, ClassInfo]]] = {}
        self.path_of_module: Dict[str, str] = {}
        for path, s in summaries.items():
            self.path_of_module[s.module] = path
            for qual, fn in s.funcs.items():
                self.funcs[self._fid(path, qual)] = (path, fn)
            for cname, cinfo in s.classes.items():
                self.class_reg.setdefault(cname, []).append((path, cinfo))
        self.edges: Dict[str, List[Tuple[str, frozenset]]] = {}
        self._resolve_edges()
        self.family = self._class_families()
        self.roots: List[Root] = []
        self._discover_roots()
        self.reach: List[Set[str]] = [self._closure(r.entries)
                                      for r in self.roots]
        spawn_reach_all: Set[str] = set()
        for s in self.reach:
            spawn_reach_all |= s
        all_fids = set(self.funcs)
        self.main_entries = all_fids - spawn_reach_all
        self.main_reach = self._closure(self.main_entries)
        self.init_only = self._compute_init_only(all_fids)
        # guard checking wants the locks GUARANTEED held at entry
        # (intersection over call sites); the deadlock graph wants every
        # lock POSSIBLY held (union) — an edge on any one path is real
        self.entry_guards = self._infer_entry_guards()
        self.entry_guards_any = self._infer_entry_guards_union()

    def _compute_init_only(self, all_fids) -> Set[str]:
        """Pre-publication functions: ``__init__`` plus every PRIVATE
        helper whose callers are ALL init-only (construction
        happens-before thread start, so their writes can never race).
        Public methods are never init-only — the analysis cannot see
        their external callers — and neither are thread entry points,
        even when spawned from __init__."""
        callers: Dict[str, Set[str]] = {}
        for caller, outs in self.edges.items():
            for callee, _g in outs:
                callers.setdefault(callee, set()).add(caller)
        root_entries = {e for r in self.roots for e in r.entries}
        init_only = {f for f in all_fids
                     if f.rsplit(".", 1)[-1] == "__init__"} - root_entries

        def private(fid):
            name = fid.rsplit(".", 1)[-1]
            return name.startswith("_") and not name.startswith("__")

        changed = True
        while changed:
            changed = False
            for f in all_fids:
                if f in init_only or f in root_entries or not private(f):
                    continue
                cs = callers.get(f)
                if cs and cs <= init_only:
                    init_only.add(f)
                    changed = True
        return init_only

    # -- plumbing -----------------------------------------------------------
    @staticmethod
    def _fid(path, qual):
        return "%s::%s" % (path, qual)

    def _resolve_ref(self, path: str, ref) -> Optional[str]:
        if ref is None:
            return None
        kind = ref[0]
        if kind == "local":
            fid = self._fid(path, ref[1])
            return fid if fid in self.funcs else None
        if kind == "method":
            cname, meth = ref[1], ref[2]
            cands = self.class_reg.get(cname, ())
            same = [(p, c) for p, c in cands if p == path]
            for p, c in (same or list(cands)[:1]):
                qual = c.methods.get(meth)
                if qual:
                    fid = self._fid(p, qual)
                    if fid in self.funcs:
                        return fid
            return None
        if kind == "dotted":
            dotted = ref[1]
            # longest module prefix match, remainder = func or Class.meth
            parts = dotted.split(".")
            for i in range(len(parts) - 1, 0, -1):
                mod = ".".join(parts[:i])
                p = self.path_of_module.get(mod)
                if p is None:
                    continue
                rest = parts[i:]
                fid = self._fid(p, ".".join(rest))
                if fid in self.funcs:
                    return fid
                return None
            return None
        return None

    def _resolve_edges(self):
        for fid, (path, fn) in self.funcs.items():
            out = []
            for cs in fn.calls:
                callee = self._resolve_ref(path, cs.ref)
                if callee is not None and callee != fid:
                    out.append((callee, cs.guards))
            self.edges[fid] = out

    def _class_families(self) -> Dict[Tuple[str, str], Tuple[str, str]]:
        """Union-find over subclass relations: a subclass shares its
        base's attribute namespace, so a write in the base file and a
        read in the subclass file are the SAME shared state — this is
        what lets one diagnostic span two files."""
        parent: Dict[Tuple[str, str], Tuple[str, str]] = {}

        def find(k):
            while parent.get(k, k) != k:
                parent[k] = parent.get(parent[k], parent[k])
                k = parent[k]
            return k

        def union(a, b):
            ra, rb = find(a), find(b)
            if ra != rb:
                parent[max(ra, rb)] = min(ra, rb)

        for path, s in self.summaries.items():
            for cname, cinfo in s.classes.items():
                key = (path, cname)
                parent.setdefault(key, key)
                for base in cinfo.bases:
                    tail = str(base).rsplit(".", 1)[-1]
                    cands = self.class_reg.get(tail, ())
                    same = [(p, c) for p, c in cands if p == path]
                    pick = same or (list(cands) if len(cands) == 1 else [])
                    for p, c in pick[:1]:
                        parent.setdefault((p, c.name), (p, c.name))
                        union(key, (p, c.name))
        return {k: find(k) for k in parent}

    def _closure(self, entries) -> Set[str]:
        seen: Set[str] = set()
        stack = [e for e in entries if e in self.funcs]
        while stack:
            f = stack.pop()
            if f in seen:
                continue
            seen.add(f)
            for callee, _g in self.edges.get(f, ()):
                if callee not in seen:
                    stack.append(callee)
        return seen

    def _discover_roots(self):
        seen_entries = set()

        def add(kind, display, entries, multi):
            entries = tuple(e for e in entries if e in self.funcs)
            if not entries:
                return
            key = (kind, entries)
            if key in seen_entries:
                return
            seen_entries.add(key)
            self.roots.append(Root(kind, display, entries, multi))

        for path, s in self.summaries.items():
            for qual, fn in s.funcs.items():
                for sp in fn.spawns:
                    fid = self._resolve_ref(path, sp.target)
                    if fid is None:
                        continue
                    disp = "%s:%s" % (sp.kind, fid.split("::", 1)[1])
                    add(sp.kind, disp, [fid], sp.kind == "pool")
            for cname, cinfo in s.classes.items():
                if cinfo.is_handler:
                    entries = [self._fid(path, q)
                               for q in cinfo.methods.values()]
                    add("handler", "handler:%s" % cname, entries, True)
            for ref, _line in s.hook_targets:
                fid = self._resolve_ref(path, ref)
                if fid is not None:
                    add("hook", "hook:%s" % fid.split("::", 1)[1],
                        [fid], False)

    def _infer_entry_guards(self) -> Dict[str, frozenset]:
        # a function's entry-held set is the INTERSECTION over its call
        # sites.  Forced to empty: thread entry points, and any function
        # the analysis cannot see every caller of — public API, or no
        # static caller at all.  A PRIVATE function whose callers are
        # all visible keeps whatever they guarantee (this is how
        # `_try_release_barrier`-style called-with-lock-held helpers
        # avoid false positives).
        callers: Set[str] = set()
        for _caller, outs in self.edges.items():
            for callee, _g in outs:
                callers.add(callee)

        def public(fid):
            name = fid.rsplit(".", 1)[-1]
            return not name.startswith("_") or name.startswith("__")

        forced = {e for r in self.roots for e in r.entries} | {
            f for f in self.main_entries
            if public(f) or f not in callers}
        entry: Dict[str, Optional[frozenset]] = {f: None for f in self.funcs}
        for e in forced:
            entry[e] = frozenset()
        changed = True
        while changed:
            changed = False
            for caller, outs in self.edges.items():
                held = entry.get(caller)
                if held is None or caller in self.init_only:
                    continue
                for callee, g in outs:
                    if callee in forced:
                        continue
                    eff = held | g
                    cur = entry.get(callee)
                    new = eff if cur is None else (cur & eff)
                    if new != cur:
                        entry[callee] = new
                        changed = True
        return {f: (g if g is not None else frozenset())
                for f, g in entry.items()}

    def _infer_entry_guards_union(self) -> Dict[str, frozenset]:
        entry: Dict[str, frozenset] = {f: frozenset() for f in self.funcs}
        changed = True
        while changed:
            changed = False
            for caller, outs in self.edges.items():
                if caller in self.init_only:
                    continue
                held = entry[caller]
                for callee, g in outs:
                    new = entry[callee] | held | g
                    if new != entry[callee]:
                        entry[callee] = new
                        changed = True
        return entry

    # -- public queries ------------------------------------------------------
    def roots_of(self, fid: str) -> List[Tuple[str, bool]]:
        """(display, multi) of every root that reaches `fid` — plus the
        implicit main thread when main-reachable."""
        out = [(r.display, r.multi)
               for r, reach in zip(self.roots, self.reach) if fid in reach]
        if fid in self.main_reach:
            out.append(("main", False))
        return out

    def effective_guards(self, fid: str, site_guards) -> frozenset:
        return frozenset(site_guards) | self.entry_guards.get(
            fid, frozenset())

    def lock_graph(self):
        """edges: {(held, acquired): [site, ...]} from every non-init
        acquisition; a cycle here is a potential deadlock."""
        edges: Dict[Tuple[str, str], List[str]] = {}
        for fid, (path, fn) in self.funcs.items():
            if fid in self.init_only:
                continue
            entry = self.entry_guards_any.get(fid, frozenset())
            for acq in fn.acqs:
                held = set(acq.held) | entry
                for h in held:
                    if h == acq.token:
                        continue
                    edges.setdefault((h, acq.token), []).append(
                        "%s:%d" % (path, acq.line))
        return edges

    def lock_cycles(self):
        """List of cycles, each a list of (held, acquired, site)."""
        edges = self.lock_graph()
        adj: Dict[str, List[str]] = {}
        for (a, b), _sites in edges.items():
            adj.setdefault(a, []).append(b)
        cycles = []
        seen_cycles = set()
        state: Dict[str, int] = {}   # 0 unvisited, 1 in-stack, 2 done

        def dfs(n, stack):
            state[n] = 1
            stack.append(n)
            for m in sorted(adj.get(n, ())):
                if state.get(m, 0) == 0:
                    dfs(m, stack)
                elif state.get(m) == 1:
                    i = stack.index(m)
                    cyc = stack[i:] + [m]
                    norm = tuple(sorted(set(cyc)))
                    if norm not in seen_cycles:
                        seen_cycles.add(norm)
                        steps = []
                        for a, b in zip(cyc, cyc[1:]):
                            site = edges.get((a, b), ["?"])[0]
                            steps.append((a, b, site))
                        cycles.append(steps)
            stack.pop()
            state[n] = 2

        for n in sorted(adj):
            if state.get(n, 0) == 0:
                dfs(n, [])
        return cycles

    # -- shared-state conflict scan -----------------------------------------
    def shared_conflicts(self):
        """Yield (attr_key, anchor_site, peer_site, kind).  ``kind`` is
        'unguarded' (a write holds nothing — anchored on that write) or
        'inconsistent' (some guard exists but the racing pair shares no
        lock — anchored on the less-guarded side).  A site is
        (path, fid, Access, roots, guards); each anchor line is reported
        at most once per attribute, so the two rules never double-report
        one underlying race."""
        # group accesses per (class FAMILY, attr): subclasses share the
        # base's attribute namespace, so the write and the conflicting
        # read may live in different files
        handler_fams = set()
        for (path, cname), fam in self.family.items():
            cinfo = self.summaries[path].classes.get(cname)
            if cinfo is not None and cinfo.is_handler:
                handler_fams.add(fam)
        grouped: Dict[Tuple[str, str, str], List] = {}
        for fid, (path, fn) in self.funcs.items():
            if fn.owner is None or fid in self.init_only:
                continue
            key0 = (path, fn.owner)
            fam = self.family.get(key0, key0)
            if fam in handler_fams or \
                    self.summaries[path].classes.get(fn.owner) is None:
                # a handler's own attrs are per-connection, not shared
                continue
            roots = self.roots_of(fid)
            if not roots:
                continue
            for acc in fn.accesses:
                key = (fam[0], fam[1], acc.attr)
                guards = self.effective_guards(fid, acc.guards)
                grouped.setdefault(key, []).append(
                    (path, fid, acc, roots, guards))
        for key, sites in sorted(grouped.items()):
            writes = [s for s in sites if s[2].kind == "w"]
            if not writes:
                continue
            anchored: Set[Tuple[str, int]] = set()
            for w in writes:
                for a in sites:
                    if not _roots_conflict(w[3], a[3]):
                        continue
                    if w[4] & a[4]:
                        continue
                    if not w[4]:
                        anchor, other, kind = w, a, "unguarded"
                    elif not a[4]:
                        anchor, other, kind = a, w, "inconsistent"
                    else:
                        anchor, other, kind = w, a, "inconsistent"
                    mark = (anchor[0], anchor[2].line)
                    if mark in anchored:
                        continue
                    anchored.add(mark)
                    yield key, anchor, other, kind
                    break   # one peer per write site is enough


def _roots_conflict(r1, r2):
    """Can the two sites execute concurrently?  Yes when they are
    reachable from two distinct thread roots, or from one root that
    runs in several threads at once (socketserver handlers, pools)."""
    union = {n for n, _m in list(r1) + list(r2)}
    if len(union) > 1:
        return True
    return any(m for _n, m in list(r1) + list(r2))


# ---------------------------------------------------------------------------
# the project-scope rules
# ---------------------------------------------------------------------------

class ProjectRule(Rule):
    scope = "project"

    def check(self, ctx):          # file-scope entry point unused
        return iter(())

    def check_project(self, project: ProjectIndex) -> Iterator[Diagnostic]:
        raise NotImplementedError

    def _emit(self, rule_id, path, line, col, message, snippet,
              threads=(), peer=None):
        if self.path_patterns and not any(
                fnmatch.fnmatch(path, p) for p in self.path_patterns):
            return None
        return Diagnostic(rule_id, path, line, col, message, snippet,
                          threads=tuple(threads), peer=peer)


def _thread_names(*rootlists):
    names = set()
    for rl in rootlists:
        for n, _m in rl:
            names.add(n)
    return sorted(names)


@register_rule
class UnguardedSharedWrite(ProjectRule):
    id = "unguarded-shared-write"
    description = ("an object attribute written with NO lock held while "
                   "another thread root reads or writes it; interleaved "
                   "steps corrupt training state silently.  Anchored on "
                   "the write site; the conflicting peer site is named "
                   "in the message (it may be in another file)")
    invariant_from = "ISSUE 6 (whole-program lock discipline)"

    def check_project(self, project):
        for key, anchor, other, kind in project.shared_conflicts():
            if kind != "unguarded":
                continue
            path, cls, attr = key
            threads = _thread_names(anchor[3], other[3])
            peer = "%s:%d" % (other[0], other[2].line)
            if other is anchor:
                what = ("this write site itself runs concurrently in "
                        "several threads of root(s) %s"
                        % ", ".join(threads))
            else:
                what = ("it is also %s at %s from thread root(s) %s"
                        % ("written" if other[2].kind == "w" else "read",
                           peer, ", ".join(threads)))
            d = self._emit(
                self.id, anchor[0], anchor[2].line, anchor[2].col,
                "%s.%s is written here with no lock held and %s; guard "
                "both sides with a common lock" % (cls, attr, what),
                anchor[2].snippet, threads=threads, peer=peer)
            if d:
                yield d


@register_rule
class InconsistentGuard(ProjectRule):
    id = "inconsistent-guard"
    description = ("a shared attribute is guarded at some sites but a "
                   "conflicting access holds a DISJOINT lock set — the "
                   "guard only works if every racing site shares a lock")
    invariant_from = "ISSUE 6 (whole-program lock discipline)"

    def check_project(self, project):
        for key, anchor, other, kind in project.shared_conflicts():
            if kind != "inconsistent":
                continue
            path, cls, attr = key
            threads = _thread_names(anchor[3], other[3])
            peer = "%s:%d" % (other[0], other[2].line)
            d = self._emit(
                self.id, anchor[0], anchor[2].line, anchor[2].col,
                "%s.%s accessed here under {%s} but a conflicting %s at "
                "%s holds {%s}; no common lock protects this pair "
                "(thread roots %s)"
                % (cls, attr, ", ".join(sorted(anchor[4])) or "no lock",
                   "write" if other[2].kind == "w" else "access", peer,
                   ", ".join(sorted(other[4])) or "no lock",
                   ", ".join(threads)),
                anchor[2].snippet, threads=threads, peer=peer)
            if d:
                yield d


@register_rule
class LockOrderCycle(ProjectRule):
    id = "lock-order-cycle"
    description = ("the static lock-acquisition graph has a cycle: two "
                   "thread roots taking the same locks in opposite "
                   "order deadlock under contention")
    invariant_from = "ISSUE 6 (lock hierarchy)"

    def check_project(self, project):
        for cyc in project.lock_cycles():
            a, b, site = cyc[0]
            path, _, line = site.rpartition(":")
            chain = " -> ".join([s[0] for s in cyc] + [cyc[0][0]])
            sites = "; ".join("%s->%s at %s" % s for s in cyc)
            try:
                lineno = int(line)
            except ValueError:
                path, lineno = site, 1
            snippet = ""
            s = project.summaries.get(path)
            if s is not None:
                for f in s.funcs.values():
                    for acq in f.acqs:
                        if acq.line == lineno:
                            snippet = acq.snippet
            d = self._emit(
                self.id, path or site, lineno, 0,
                "lock-acquisition cycle %s (%s): threads taking these "
                "locks in opposite order deadlock; pick one hierarchy "
                "and reorder" % (chain, sites), snippet)
            if d:
                yield d


@register_rule
class BlockingWaitUnbounded(ProjectRule):
    id = "blocking-wait-unbounded"
    description = ("Event.wait()/Condition.wait()/Lock.acquire()/"
                   "join()/proc.wait() without a timeout in fault/"
                   "kvstore/health/supervisor code: a wedged peer parks "
                   "this thread forever — pass a timeout or budget the "
                   "wait with fault.Deadline")
    invariant_from = "ISSUE 6 (bounded waits in recovery paths)"
    path_patterns = ("mxnet_tpu/fault.py", "mxnet_tpu/health.py",
                     "mxnet_tpu/kvstore/*.py", "tools/launch.py")

    def check_project(self, project):
        for fid, (path, fn) in sorted(project.funcs.items()):
            for ws in fn.waits:
                if ws.has_timeout:
                    continue
                d = self._emit(
                    self.id, path, ws.line, ws.col,
                    "%s() on %r without a timeout blocks this thread "
                    "forever if the peer is wedged; pass a timeout (or "
                    "drive the budget through fault.Deadline)"
                    % (ws.kind, ws.recv), ws.snippet)
                if d:
                    yield d


@register_rule
class ThreadLeak(ProjectRule):
    id = "thread-leak"
    description = ("a non-daemon Thread is started without a matching "
                   "join()/stop-event: it outlives its owner and blocks "
                   "interpreter shutdown")
    invariant_from = "ISSUE 6 (thread lifecycle hygiene)"

    def check_project(self, project):
        for fid, (path, fn) in sorted(project.funcs.items()):
            for sp in fn.spawns:
                if sp.kind != "thread" or sp.daemon is True:
                    continue
                binding = sp.binding
                if binding is not None and self._handled(
                        project, path, binding):
                    continue
                if self._target_has_stop_event(project, path, sp):
                    continue
                d = self._emit(
                    self.id, path, sp.line, sp.col,
                    "non-daemon Thread started here has no join() or "
                    "stop event anywhere in this project; it outlives "
                    "its owner — set daemon=True, join it on close(), "
                    "or loop it on a stop Event", sp.snippet)
                if d:
                    yield d

    @staticmethod
    def _handled(project, path, binding):
        # a bare local name ('t') only matches joins in the SPAWNING
        # file — an unrelated `t.join()` elsewhere must not silence the
        # leak; class-qualified bindings ('KVStore._hb_thread') are
        # unambiguous and match project-wide (close() may live in a
        # subclass file)
        for fid, (p, fn) in project.funcs.items():
            if "." not in binding and p != path:
                continue
            if binding in fn.joins or binding in fn.daemon_set:
                return True
        return False

    @staticmethod
    def _target_has_stop_event(project, path, sp):
        fid = project._resolve_ref(path, sp.target)
        if fid is None:
            return False
        for f in project._closure([fid]):
            _p, fn = project.funcs[f]
            for ws in fn.waits:
                if ws.kind.startswith("Event."):
                    return True
        return False


@register_rule
class WireVerbExhaustive(ProjectRule):
    id = "wire-verb-exhaustive"
    description = ("every client-emitted wire verb (kvstore CMDs, serve "
                   "PREDICT/HEALTH/METRICS/SWAP/STOP, the coming "
                   "JOIN/LEAVE/ROUTE) must be fully wired: declared in a "
                   "server-side WIRE_VERBS manifest with an explicit "
                   "replayable-or-idempotent semantics, handled by a "
                   "comparison in the declaring file, consistent with "
                   "that file's exactly-once replay set, and — when it "
                   "ships tensors — backed by an encode_*/decode_* "
                   "codec pair somewhere in the scanned tree")
    invariant_from = "ISSUE 11 (wire-protocol exhaustiveness)"

    _SEMANTICS = ("replayable", "idempotent")

    def check_project(self, project: ProjectIndex) -> Iterator[Diagnostic]:
        manifests = []       # (path, WireInfo)
        codecs: Set[Tuple[str, str]] = set()
        for path, s in sorted(project.summaries.items()):
            wire = getattr(s, "wire", None)
            if wire is None:
                continue
            codecs |= wire.codecs
            if wire.manifest is not None:
                manifests.append((path, wire))
        declared: Dict[str, List[str]] = {}
        for path, wire in manifests:
            for verb in wire.manifest:
                declared.setdefault(verb, []).append(path)

        def declares_for(client_path: str, verb: str) -> bool:
            """Protocol scoping: a client's verbs must be declared by a
            manifest in the SAME package directory when one exists
            there (serve/client.py binds to serve/server.py's manifest
            — kvstore's STOP must not mask a serve STOP dropped from
            the serve manifest).  Files in manifest-less directories
            (tools/launch.py driving the PS) fall back to any
            manifest."""
            holders = declared.get(verb)
            if not holders:
                return False
            client_dir = client_path.rsplit("/", 1)[0]
            local = [p for p, _w in manifests
                     if p.rsplit("/", 1)[0] == client_dir]
            if not local:
                return True
            return any(h.rsplit("/", 1)[0] == client_dir
                       for h in holders)

        # 1. manifest-side checks: semantics, handler, replay set, codec
        for path, wire in manifests:
            line = wire.manifest_line
            for verb, entry in sorted(wire.manifest.items()):
                sem = entry.get("semantics")
                if sem not in self._SEMANTICS:
                    d = self._emit(
                        self.id, path, line, 0,
                        "WIRE_VERBS entry %r declares semantics %r — "
                        "every verb must state 'replayable' (exactly-"
                        "once via the SEQ replay cache) or 'idempotent' "
                        "(safe to re-execute on retry)" % (verb, sem),
                        "WIRE_VERBS[%r]" % verb)
                    if d:
                        yield d
                if verb not in wire.handles:
                    d = self._emit(
                        self.id, path, line, 0,
                        "WIRE_VERBS declares %r but this file has no "
                        "handler comparison for it — the verb is "
                        "half-wired (a client can emit what no server "
                        "dispatches)" % verb,
                        "WIRE_VERBS[%r]" % verb)
                    if d:
                        yield d
                if wire.replay_verbs:
                    if sem == "replayable" and \
                            verb not in wire.replay_verbs:
                        d = self._emit(
                            self.id, path, line, 0,
                            "%r is declared replayable but is missing "
                            "from this file's replay-cache verb tuple "
                            "(_CACHED/_MUTATING) — a retried request "
                            "would re-execute instead of replaying"
                            % verb, "WIRE_VERBS[%r]" % verb)
                        if d:
                            yield d
                    elif sem == "idempotent" and \
                            verb in wire.replay_verbs:
                        d = self._emit(
                            self.id, path, line, 0,
                            "%r is declared idempotent but sits in this "
                            "file's replay-cache verb tuple — pick one: "
                            "exactly-once (declare replayable) or "
                            "re-executable (drop it from the cache set)"
                            % verb, "WIRE_VERBS[%r]" % verb)
                        if d:
                            yield d
                codec = entry.get("codec")
                if codec is not None:
                    for kind in ("encode", "decode"):
                        if (kind, str(codec)) not in codecs:
                            d = self._emit(
                                self.id, path, line, 0,
                                "verb %r names wire codec %r but no "
                                "%s_%s() exists in the scanned tree — "
                                "the payload cannot cross the wire"
                                % (verb, codec, kind, codec),
                                "WIRE_VERBS[%r]" % verb)
                            if d:
                                yield d
            # 2. reverse exhaustiveness: a handled verb missing from the
            # manifest means its contract (semantics, codec) is undeclared
            for verb, hline in sorted(wire.handles.items()):
                if verb not in wire.manifest:
                    d = self._emit(
                        self.id, path, hline, 0,
                        "this file handles wire verb %r but its "
                        "WIRE_VERBS manifest does not declare it — add "
                        "the entry (semantics + codec) so the protocol "
                        "surface stays exhaustive" % verb,
                        "handles %r" % verb)
                    if d:
                        yield d

        # 3. client side: every emitted verb must be declared somewhere
        for path, s in sorted(project.summaries.items()):
            wire = getattr(s, "wire", None)
            if wire is None:
                continue
            for verb, line, snip in wire.emits:
                if not declares_for(path, verb):
                    d = self._emit(
                        self.id, path, line, 0,
                        "client-emitted wire verb %r has no WIRE_VERBS "
                        "declaration in %s — the verb would ship "
                        "half-wired (no declared semantics, no "
                        "guaranteed handler)"
                        % (verb,
                           "this protocol's server module"
                           if verb in declared
                           else "any scanned server module"), snip)
                    if d:
                        yield d
