"""mxlint altitude 4 — the wire-protocol verifier (``--protocol``).

Two halves, both pure stdlib-``ast`` static analysis (no sockets, no
imports of the code under check, virtual clock only):

1. **Per-verb effect extraction.**  Every ``WIRE_VERBS`` manifest built
   through :func:`mxnet_tpu.kvstore.wire_verbs.declare_verbs` names a
   protocol machine; for each declared verb the extractor walks the
   handler branch (depth-bounded method inlining) and summarizes which
   state categories it mutates, whether each mutation sits behind an
   *invalidating guard* (the test that made it run becomes false once
   it ran — the shape that makes a handler idempotent), where the SEQ
   replay layer resolves/persists, and whether a router re-mints the
   client's ``(cid, seq)`` identity.

2. **Fault-schedule model checking.**  The summaries plus the declared
   contracts compile into tiny per-verb state machines; a deterministic
   enumerator drives every bounded schedule of drop / duplicate /
   reply-loss / stale-reorder / crash-restart-from-snapshot / router
   failover and asserts the declared property on each terminal state
   (``replayable``: applied exactly once per request; ``idempotent``:
   N deliveries ≡ 1; stateless: no visible delta).  The schedule count
   is deterministic and pinned by the test suite.

Findings from this lane are NEVER baselinable — a broken exactly-once
invariant is not technical debt.  Per-line ``# mxlint: disable=...``
suppressions are honored (for documented-by-design exceptions only).
"""
from __future__ import annotations

import ast
import json
import os
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from .core import (Diagnostic, Rule, register_rule, _parse_suppressions,
                   _suppressed, repo_root_of, iter_py_files)
from .project import _wire_summary

__all__ = ["check_sources", "check_paths", "run_cli", "PROTOCOL_RULES"]

RULE_REPLAY = "protocol-replay-class"
RULE_EPOCH = "protocol-idempotent-epoch"
RULE_ORDER = "protocol-reply-order"
RULE_STREAM = "protocol-stream-dedupe"
RULE_VERBATIM = "protocol-router-verbatim"
RULE_EFFECTS = "protocol-effects-drift"
RULE_MODEL = "protocol-model"
RULE_ERROR = "protocol-error"


class _ProtocolRule(Rule):
    """Registry stub: protocol-lane rules run inside check_sources(),
    not the per-file/project passes — scope='protocol' is skipped by
    both.  Registering them keeps --list-rules/--select truthful."""
    scope = "protocol"
    invariant_from = "PR 19"

    def check(self, ctx):                       # pragma: no cover
        return iter(())


@register_rule
class _ReplayClassRule(_ProtocolRule):
    id = RULE_REPLAY
    description = ("declared replay class must match the SEQ layer: a "
                   "mutating replayable verb outside the replay cache "
                   "re-executes on reconnect replay")


@register_rule
class _IdempotentEpochRule(_ProtocolRule):
    id = RULE_EPOCH
    description = ("a declared-idempotent verb must not bump the "
                   "membership epoch on its no-op path (PR-16 "
                   "contract: retried JOIN/LEAVE are epoch-silent)")


@register_rule
class _ReplyOrderRule(_ProtocolRule):
    id = RULE_ORDER
    description = ("the SEQ layer must resolve a mutating verb's cache "
                   "entry BEFORE persisting: a snapshot carrying the "
                   "effect but not the resolved entry double-applies "
                   "on crash-replay")


@register_rule
class _StreamDedupeRule(_ProtocolRule):
    id = RULE_STREAM
    description = ("a stream verb's client on_stream callback must "
                   "dedupe by frame offset — replayed connections "
                   "resend frames")


@register_rule
class _RouterVerbatimRule(_ProtocolRule):
    id = RULE_VERBATIM
    description = ("a router must forward the client envelope verbatim, "
                   "never mint its own (cid, seq): fresh identities "
                   "defeat every replica's replay cache")


@register_rule
class _EffectsDriftRule(_ProtocolRule):
    id = RULE_EFFECTS
    description = ("the manifest's declared mutates set must match the "
                   "handler's extracted effect summary")


@register_rule
class _ModelRule(_ProtocolRule):
    id = RULE_MODEL
    description = ("exhaustive bounded fault schedules must uphold the "
                   "declared per-verb property (exactly-once / "
                   "idempotent / stateless)")


@register_rule
class _ProtocolErrorRule(_ProtocolRule):
    id = RULE_ERROR
    description = ("protocol lane infrastructure error: unparseable "
                   "machine, undeclared handler branch, missing SEQ "
                   "layer — the machine cannot be certified")


PROTOCOL_RULES = (RULE_REPLAY, RULE_EPOCH, RULE_ORDER, RULE_STREAM,
                  RULE_VERBATIM, RULE_EFFECTS, RULE_MODEL, RULE_ERROR)


# ---------------------------------------------------------------------------
# State-category tables: attribute name -> protocol state category.
# Categories in _BENIGN never carry protocol meaning (caches, telemetry,
# liveness stamps, lock plumbing) — mutating them is always allowed.
# ---------------------------------------------------------------------------

ATTR_EXACT = {
    "_store": "kv",
    "_opt_blob": "optimizer",
    "_updater": "optimizer",
    "_members": "membership",
    "_membership_epoch": "epoch",
    "_replay": "replaycache",
    "_pins": "routing",
    "_replicas": "routing",
    "_signals": "routing",
    "_rr": "routing",
    "_draining": "lifecycle",
    "_drain_deadline": "lifecycle",
    "host": "model",
    "batcher": "engine",
    "decode": "engine",
    "_locks": "locking",
    "_lock": "locking",
    "_last_seen": "liveness",
    "_seen_regime": "liveness",
    "_vclock_pumper": "liveness",
    "_mutations": "durability",
}

ATTR_PREFIX = (
    ("_barrier", "barrier"),
    ("_snapshot", "durability"),
    ("_replay", "replaycache"),
    ("_c_", "telemetry"),
    ("_g_", "telemetry"),
    ("_seen", "liveness"),
)

_BENIGN = frozenset(("replaycache", "routing", "locking", "liveness",
                     "durability", "telemetry"))

# mutator method names, by the kind of state transition they make
MUT_SET = frozenset(("add", "set", "update", "setdefault"))
MUT_DEL = frozenset(("discard", "remove", "clear", "pop", "popitem"))
MUT_AUG = frozenset(("append", "appendleft", "inc", "insert", "extend",
                     "submit", "deploy", "put", "observe"))

# handler-function search order when locating a verb's dispatch branch
_BRANCH_PRIORITY = ("_dispatch", "handle", "handle_local", "_serve")

_INLINE_DEPTH = 4
# methods recorded as persistence points, never inlined (their bodies
# write files, not protocol state)
_PERSIST_METHODS = frozenset(("snapshot", "_note_mutation"))


def _attr_category(name: str) -> str:
    if name in ATTR_EXACT:
        return ATTR_EXACT[name]
    for pre, cat in ATTR_PREFIX:
        if name.startswith(pre):
            return cat
    return "other:" + name


def _chain(node) -> Optional[List[str]]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return None


class _Effect:
    __slots__ = ("category", "kind", "guarded", "line", "via")

    def __init__(self, category, kind, guarded, line, via=""):
        self.category = category
        self.kind = kind            # "set" | "del" | "aug"
        self.guarded = guarded
        self.line = line
        self.via = via              # inlined callee, for messages

    def key(self):
        return (self.category, self.kind, self.guarded, self.line)


class _VerbFacts:
    __slots__ = ("verb", "line", "func", "effects", "persists",
                 "calls_forward", "calls_fanout")

    def __init__(self, verb, line, func):
        self.verb = verb
        self.line = line            # dispatch-compare line
        self.func = func            # qualname of the dispatch function
        self.effects: List[_Effect] = []
        self.persists: List[Tuple[int, bool]] = []   # (line, guarded)
        self.calls_forward = False
        self.calls_fanout = False


class _SeqFacts:
    __slots__ = ("present", "line", "bypass", "cached", "resolve_line",
                 "persist_line", "persist_verbs", "has_stale")

    def __init__(self):
        self.present = False
        self.line = 0
        self.bypass: Set[str] = set()
        self.cached: Optional[Set[str]] = None
        self.resolve_line = 0
        self.persist_line = 0
        self.persist_verbs: Set[str] = set()
        self.has_stale = False


class _Machine:
    """One protocol machine: a file whose WIRE_VERBS went through
    declare_verbs()."""

    __slots__ = ("path", "lines", "tree", "protocol", "role", "durable",
                 "manifest", "manifest_line", "verbs", "seq",
                 "minted_sites", "errors")

    def __init__(self, path, lines, tree, wire):
        self.path = path
        self.lines = lines
        self.tree = tree
        self.protocol = wire.meta.get("protocol")
        self.role = wire.meta.get("role", "server")
        self.durable = bool(wire.meta.get("durable"))
        self.manifest = wire.manifest or {}
        self.manifest_line = wire.manifest_line
        self.verbs: Dict[str, _VerbFacts] = {}
        self.seq = _SeqFacts()
        self.minted_sites: List[int] = []
        self.errors: List[Tuple[int, str]] = []

    def line_text(self, lineno):
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""


class _FileCtx:
    """Class/method index of one machine file, for branch lookup and
    depth-bounded inlining."""

    def __init__(self, tree):
        self.classes: Dict[str, ast.ClassDef] = {}
        # (class, method) -> FunctionDef;  method -> [class, ...]
        self.methods: Dict[Tuple[str, str], ast.FunctionDef] = {}
        self.method_classes: Dict[str, List[str]] = {}
        self.functions: List[Tuple[str, Optional[str], ast.FunctionDef]] = []
        stack: List[Tuple[ast.AST, Optional[str]]] = [(tree, None)]
        while stack:
            node, cls = stack.pop()
            for sub in ast.iter_child_nodes(node):
                if isinstance(sub, ast.ClassDef):
                    self.classes[sub.name] = sub
                    stack.append((sub, sub.name))
                elif isinstance(sub, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    if cls is not None:
                        self.methods[(cls, sub.name)] = sub
                        self.method_classes.setdefault(
                            sub.name, []).append(cls)
                    self.functions.append((sub.name, cls, sub))
                    stack.append((sub, cls))
        self.functions.sort(key=lambda t: t[2].lineno)

    def resolve_name_method(self, meth: str):
        """``rt.forward(...)`` — a Name receiver resolves iff exactly
        one class in the file defines the method."""
        owners = self.method_classes.get(meth, [])
        if len(set(owners)) == 1:
            cls = owners[0]
            return cls, self.methods[(cls, meth)]
        return None, None


# ---------------------------------------------------------------------------
# local-alias / taint pre-pass (per function)
# ---------------------------------------------------------------------------

def _state_cats_in(expr, aliases, tainted) -> Set[str]:
    """Every state category an expression touches (self attrs through
    the category tables, plus category-aliased locals)."""
    cats: Set[str] = set()
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute):
            ch = _chain(node)
            if ch and ch[0] == "self" and len(ch) >= 2:
                cats.add(_attr_category(ch[1]))
        elif isinstance(node, ast.Name):
            if node.id in aliases:
                cats.add(aliases[node.id])
    return cats


def _is_tainted_test(expr, aliases, tainted) -> bool:
    return any(isinstance(n, ast.Name) and n.id in tainted
               for n in ast.walk(expr))


def _scan_locals(fn: ast.FunctionDef):
    """aliases: local name -> state category it references (``stored =
    self._store.get(k)``); tainted: locals whose value is derived from
    state (directly, or assigned/mutated under a state-dependent test
    or loop) — a bare ``if changed:`` over such a name is an
    invalidating guard."""
    aliases: Dict[str, str] = {}
    tainted: Set[str] = set()

    def first_cat(expr):
        for node in ast.walk(expr):
            if isinstance(node, ast.Attribute):
                ch = _chain(node)
                if ch and ch[0] == "self" and len(ch) >= 2:
                    cat = _attr_category(ch[1])
                    if cat not in _BENIGN:
                        return cat
        return None

    def scan(stmts, ctx):
        for st in stmts:
            if isinstance(st, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                targets = (st.targets if isinstance(st, ast.Assign)
                           else [st.target])
                value = getattr(st, "value", None)
                for t in targets:
                    if isinstance(t, ast.Name) and value is not None:
                        cat = first_cat(value)
                        if cat:
                            aliases.setdefault(t.id, cat)
                            tainted.add(t.id)
                        elif ctx:
                            tainted.add(t.id)
            if isinstance(st, ast.Expr) and isinstance(st.value, ast.Call):
                f = st.value.func
                if isinstance(f, ast.Attribute) and \
                        isinstance(f.value, ast.Name) and ctx:
                    tainted.add(f.value.id)
            sub_ctx = ctx
            if isinstance(st, ast.For):
                sub_ctx = ctx or bool(
                    _state_cats_in(st.iter, aliases, tainted))
            elif isinstance(st, (ast.If, ast.While)):
                sub_ctx = ctx or bool(
                    _state_cats_in(st.test, aliases, tainted)) or \
                    _is_tainted_test(st.test, aliases, tainted)
            for field in ("body", "orelse", "finalbody"):
                inner = getattr(st, field, None)
                if inner:
                    scan(inner, sub_ctx)
            for h in getattr(st, "handlers", ()) or ():
                scan(h.body, sub_ctx)
            if isinstance(st, ast.With):
                pass    # body already covered above
    scan(fn.body, False)
    return aliases, tainted


# ---------------------------------------------------------------------------
# guard polarity: does running the guarded body make the guard false?
# ---------------------------------------------------------------------------

def _guards_of(test, aliases, tainted):
    """[(cats, polarity)] — polarity 'absent' (test says the state is
    missing), 'present', or 'taint' (bare state-derived flag)."""
    out = []
    if isinstance(test, ast.BoolOp):
        for v in test.values:
            out.extend(_guards_of(v, aliases, tainted))
        return out
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        inner = _guards_of(test.operand, aliases, tainted)
        flip = {"absent": "present", "present": "absent",
                "taint": "taint"}
        return [(c, flip[p]) for c, p in inner]
    cats = _state_cats_in(test, aliases, tainted)
    if isinstance(test, ast.Compare) and len(test.ops) == 1:
        op = test.ops[0]
        comp = test.comparators[0]
        none_cmp = isinstance(comp, ast.Constant) and comp.value is None
        if isinstance(op, ast.NotIn):
            pol = "absent"
        elif isinstance(op, ast.In):
            pol = "present"
        elif isinstance(op, ast.Is):
            pol = "absent" if none_cmp else "present"
        elif isinstance(op, ast.IsNot):
            pol = "present" if none_cmp else "absent"
        elif isinstance(op, ast.Eq):
            pol = "absent" if none_cmp else "present"
        elif isinstance(op, ast.NotEq):
            pol = "absent"
        else:
            pol = "present"
        if cats:
            out.append((cats, pol))
        elif _is_tainted_test(test, aliases, tainted):
            out.append((set(), "taint"))
        return out
    if cats:
        out.append((cats, "present"))
    elif _is_tainted_test(test, aliases, tainted):
        out.append((set(), "taint"))
    return out


def _quick_muts(stmts, aliases) -> Set[Tuple[str, str]]:
    """(category, kind) pairs mutated anywhere under `stmts`, without
    inlining — enough to decide guard invalidation."""
    muts: Set[Tuple[str, str]] = set()

    def note_target(t, kind):
        if isinstance(t, ast.Attribute):
            ch = _chain(t)
            if ch and ch[0] == "self" and len(ch) >= 2:
                muts.add((_attr_category(ch[1]), kind))
        elif isinstance(t, ast.Subscript):
            note_target(t.value, kind)
        elif isinstance(t, ast.Name) and t.id in aliases:
            muts.add((aliases[t.id], kind))

    for st in stmts:
        for node in ast.walk(st):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    note_target(t, "set")
            elif isinstance(node, ast.AugAssign):
                note_target(node.target, "aug")
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    note_target(t, "del")
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute):
                meth = node.func.attr
                kind = ("set" if meth in MUT_SET else
                        "del" if meth in MUT_DEL else
                        "aug" if meth in MUT_AUG else None)
                if kind:
                    ch = _chain(node.func)
                    if ch and ch[0] == "self" and len(ch) >= 3:
                        muts.add((_attr_category(ch[1]), kind))
                    elif ch and len(ch) == 2 and ch[0] in aliases:
                        muts.add((aliases[ch[0]], kind))
    return muts


def _guard_invalidates(guards, muts) -> bool:
    """An 'invalidating' guard is one the body's own mutation turns
    false: absent-polarity + a set of the tested category (JOIN adds
    the missing member), or present-polarity + a del of it (LEAVE
    discards the present member).  Bare tainted flags count — they
    exist only to gate re-application."""
    for cats, pol in guards:
        if pol == "taint":
            return True
        if pol == "absent" and any(c in cats and k == "set"
                                   for c, k in muts):
            return True
        if pol == "present" and any(c in cats and k == "del"
                                    for c, k in muts):
            return True
    return False


def _ends_in_exit(stmts) -> bool:
    return bool(stmts) and isinstance(stmts[-1], (ast.Return, ast.Raise,
                                                  ast.Continue))


# ---------------------------------------------------------------------------
# effect walker: one verb branch -> [_Effect], with method inlining
# ---------------------------------------------------------------------------

class _Walker:
    def __init__(self, fctx: _FileCtx, facts: _VerbFacts):
        self.fctx = fctx
        self.facts = facts
        self.stack: List[Tuple[str, str]] = []   # (class, method) cycle guard

    def walk_stmts(self, stmts, cls, aliases, tainted, guarded,
                   scoped_cats, depth, line_override=None):
        scoped = set(scoped_cats)
        for st in stmts:
            self._walk_stmt(st, cls, aliases, tainted, guarded, scoped,
                            depth, line_override)
            # sibling terminator: `if <present state test>: return` makes
            # every LATER same-category "set" effectively run-once
            if isinstance(st, ast.If) and _ends_in_exit(st.body) \
                    and not st.orelse:
                for cats, pol in _guards_of(st.test, aliases, tainted):
                    if pol == "present":
                        scoped |= cats

    def _effect(self, cat, kind, guarded, line, via=""):
        self.facts.effects.append(_Effect(cat, kind, guarded, line, via))

    def _note_calls(self, expr, cls, aliases, guarded, scoped, depth,
                    line_override):
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            line = line_override or node.lineno
            if isinstance(f, ast.Attribute):
                ch = _chain(f)
                if ch is None:
                    continue
                meth = ch[-1]
                if ch[0] == "self" and len(ch) == 2:
                    # self.meth(...) — persistence point or inline
                    if meth in _PERSIST_METHODS:
                        self.facts.persists.append((line, guarded))
                        continue
                    if meth in ("forward",):
                        self.facts.calls_forward = True
                    if meth in ("fan_out",):
                        self.facts.calls_fanout = True
                    self._inline(cls, meth, node, guarded, scoped,
                                 depth, line)
                elif ch[0] == "self" and len(ch) >= 3:
                    cat = _attr_category(ch[1])
                    kind = ("set" if meth in MUT_SET else
                            "del" if meth in MUT_DEL else
                            "aug" if meth in MUT_AUG else None)
                    if kind:
                        self._effect(cat, kind,
                                     guarded or cat in scoped, line)
                elif len(ch) == 2:
                    recv, = ch[:1]
                    if recv in aliases and (meth in MUT_SET or
                                            meth in MUT_DEL or
                                            meth in MUT_AUG):
                        kind = ("set" if meth in MUT_SET else
                                "del" if meth in MUT_DEL else "aug")
                        cat = aliases[recv]
                        self._effect(cat, kind,
                                     guarded or cat in scoped, line)
                    elif meth in _PERSIST_METHODS:
                        continue
                    else:
                        owner, fn = self.fctx.resolve_name_method(meth)
                        if fn is not None:
                            if meth == "forward":
                                self.facts.calls_forward = True
                            if meth == "fan_out":
                                self.facts.calls_fanout = True
                            self._inline(owner, meth, node, guarded,
                                         scoped, depth, line,
                                         fn_known=fn)
            elif isinstance(f, ast.Name):
                if f.id in aliases:
                    # calling a state-derived callable (the installed
                    # updater) applies it: an in-place aug of both its
                    # source category and any state-aliased args
                    cat = aliases[f.id]
                    self._effect(cat, "aug", guarded or cat in scoped,
                                 line, via=f.id)
                    for a in node.args:
                        if isinstance(a, ast.Name) and a.id in aliases:
                            acat = aliases[a.id]
                            self._effect(acat, "aug",
                                         guarded or acat in scoped,
                                         line, via=f.id)

    def _inline(self, cls, meth, call, guarded, scoped, depth, line,
                fn_known=None):
        if depth <= 0 or cls is None:
            return
        fn = fn_known or self.fctx.methods.get((cls, meth))
        if fn is None or (cls, meth) in self.stack:
            return
        self.stack.append((cls, meth))
        try:
            aliases, tainted = _scan_locals(fn)
            self.walk_stmts(fn.body, cls, aliases, tainted, guarded,
                            scoped, depth - 1, line_override=line)
        finally:
            self.stack.pop()

    def _note_target(self, t, kind, cls, aliases, guarded, scoped, line):
        if isinstance(t, ast.Attribute):
            ch = _chain(t)
            if ch and ch[0] == "self" and len(ch) >= 2:
                cat = _attr_category(ch[1])
                self._effect(cat, kind, guarded or cat in scoped, line)
        elif isinstance(t, ast.Subscript):
            self._note_target(t.value, kind, cls, aliases, guarded,
                              scoped, line)
        elif isinstance(t, ast.Name) and kind != "set" and \
                t.id in aliases:
            cat = aliases[t.id]
            self._effect(cat, kind, guarded or cat in scoped, line)

    def _walk_stmt(self, st, cls, aliases, tainted, guarded, scoped,
                   depth, line_override):
        line = line_override or getattr(st, "lineno", 0)
        if isinstance(st, ast.Assign):
            for t in st.targets:
                self._note_target(t, "set", cls, aliases, guarded,
                                  scoped, line)
            self._note_calls(st.value, cls, aliases, guarded, scoped,
                             depth, line_override)
        elif isinstance(st, ast.AugAssign):
            self._note_target(st.target, "aug", cls, aliases, guarded,
                              scoped, line)
            self._note_calls(st.value, cls, aliases, guarded, scoped,
                             depth, line_override)
        elif isinstance(st, ast.AnnAssign) and st.value is not None:
            self._note_target(st.target, "set", cls, aliases, guarded,
                              scoped, line)
            self._note_calls(st.value, cls, aliases, guarded, scoped,
                             depth, line_override)
        elif isinstance(st, ast.Delete):
            for t in st.targets:
                self._note_target(t, "del", cls, aliases, guarded,
                                  scoped, line)
        elif isinstance(st, (ast.Expr, ast.Return)):
            if getattr(st, "value", None) is not None:
                self._note_calls(st.value, cls, aliases, guarded,
                                 scoped, depth, line_override)
        elif isinstance(st, ast.If):
            self._note_calls(st.test, cls, aliases, guarded, scoped,
                             depth, line_override)
            guards = _guards_of(st.test, aliases, tainted)
            body_muts = _quick_muts(st.body, aliases)
            g_body = guarded or _guard_invalidates(guards, body_muts)
            self.walk_stmts(st.body, cls, aliases, tainted, g_body,
                            scoped, depth, line_override)
            if st.orelse:
                flip = {"absent": "present", "present": "absent",
                        "taint": "taint"}
                inv = [(c, flip[p]) for c, p in guards]
                or_muts = _quick_muts(st.orelse, aliases)
                g_or = guarded or _guard_invalidates(inv, or_muts)
                self.walk_stmts(st.orelse, cls, aliases, tainted, g_or,
                                scoped, depth, line_override)
        elif isinstance(st, (ast.While, ast.For)):
            if isinstance(st, ast.While):
                self._note_calls(st.test, cls, aliases, guarded, scoped,
                                 depth, line_override)
            else:
                self._note_calls(st.iter, cls, aliases, guarded, scoped,
                                 depth, line_override)
            self.walk_stmts(st.body, cls, aliases, tainted, guarded,
                            scoped, depth, line_override)
            if st.orelse:
                self.walk_stmts(st.orelse, cls, aliases, tainted,
                                guarded, scoped, depth, line_override)
        elif isinstance(st, ast.With):
            for item in st.items:
                self._note_calls(item.context_expr, cls, aliases,
                                 guarded, scoped, depth, line_override)
            self.walk_stmts(st.body, cls, aliases, tainted, guarded,
                            scoped, depth, line_override)
        elif isinstance(st, ast.Try):
            for block in (st.body, st.orelse, st.finalbody):
                if block:
                    self.walk_stmts(block, cls, aliases, tainted,
                                    guarded, scoped, depth,
                                    line_override)
            for h in st.handlers:
                self.walk_stmts(h.body, cls, aliases, tainted, guarded,
                                scoped, depth, line_override)
        elif isinstance(st, ast.Raise):
            if st.exc is not None:
                self._note_calls(st.exc, cls, aliases, guarded, scoped,
                                 depth, line_override)


# ---------------------------------------------------------------------------
# branch finder + SEQ-layer facts + minted-envelope scan
# ---------------------------------------------------------------------------

def _verbs_of_test(test, manifest) -> Set[str]:
    """Verbs this If-test dispatches on: `cmd == "VERB"` or
    `cmd in ("A", "B")` (constants only — attribute tuples like
    self._MUTATING are replay metadata, not dispatch)."""
    out: Set[str] = set()
    if not (isinstance(test, ast.Compare) and len(test.ops) == 1):
        return out
    op = test.ops[0]
    comp = test.comparators[0]
    if isinstance(op, ast.Eq):
        for side in (test.left, comp):
            if isinstance(side, ast.Constant) and \
                    isinstance(side.value, str) and side.value in manifest:
                out.add(side.value)
    elif isinstance(op, ast.In) and isinstance(comp, (ast.Tuple, ast.List)):
        for e in comp.elts:
            if isinstance(e, ast.Constant) and \
                    isinstance(e.value, str) and e.value in manifest:
                out.add(e.value)
    return out


def _find_branches(fctx: _FileCtx, manifest):
    """verb -> (rank, line, body, class, fn) — best dispatch branch per
    verb across every function in the file (priority order, then file
    order)."""
    best: Dict[str, Tuple[int, int, list, Optional[str],
                          ast.FunctionDef]] = {}
    for name, cls, fn in fctx.functions:
        rank = (_BRANCH_PRIORITY.index(name)
                if name in _BRANCH_PRIORITY else len(_BRANCH_PRIORITY))
        for node in ast.walk(fn):
            if not isinstance(node, ast.If):
                continue
            for verb in _verbs_of_test(node.test, manifest):
                cand = (rank, node.lineno, node.body, cls, fn)
                if verb not in best or cand[:2] < best[verb][:2]:
                    best[verb] = cand
    return best


def _const_tuple_attr(fctx: _FileCtx, cls: Optional[str], attr: str):
    """Resolve a class-level `ATTR = ("A", "B")` tuple of constants."""
    cands = [cls] if cls else []
    cands += [c for c in fctx.classes if c not in cands]
    for cname in cands:
        cdef = fctx.classes.get(cname)
        if cdef is None:
            continue
        for st in cdef.body:
            if isinstance(st, ast.Assign) and len(st.targets) == 1 and \
                    isinstance(st.targets[0], ast.Name) and \
                    st.targets[0].id == attr and \
                    isinstance(st.value, (ast.Tuple, ast.List)):
                vals = [e.value for e in st.value.elts
                        if isinstance(e, ast.Constant)]
                return set(vals)
    return None


def _seq_facts(fctx: _FileCtx, manifest) -> _SeqFacts:
    sf = _SeqFacts()
    for name, cls, fn in fctx.functions:
        if name != "_handle_seq":
            continue
        sf.present = True
        sf.line = fn.lineno
        resolve_lines: List[int] = []
        for node in ast.walk(fn):
            if isinstance(node, ast.If) and \
                    isinstance(node.test, ast.Compare) and \
                    len(node.test.ops) == 1:
                op = node.test.ops[0]
                comp = node.test.comparators[0]
                returns_handle = any(
                    isinstance(s, ast.Return) and
                    isinstance(s.value, ast.Call) and
                    isinstance(s.value.func, ast.Attribute) and
                    s.value.func.attr == "handle"
                    for s in node.body)
                if isinstance(op, ast.In) and \
                        isinstance(comp, (ast.Tuple, ast.List)) and \
                        returns_handle:
                    sf.bypass |= {e.value for e in comp.elts
                                  if isinstance(e, ast.Constant)}
                elif isinstance(op, ast.NotIn) and \
                        isinstance(comp, ast.Attribute) and \
                        returns_handle:
                    tup = _const_tuple_attr(fctx, cls, comp.attr)
                    if tup is not None:
                        sf.cached = set(tup) & set(manifest)
                        sf.bypass |= set(manifest) - sf.cached
            elif isinstance(node, ast.Compare) and len(node.ops) == 1 \
                    and isinstance(node.ops[0], (ast.Lt, ast.LtE)):
                names = {n.id for n in ast.walk(node)
                         if isinstance(n, ast.Name)}
                if "seq" in names:
                    sf.has_stale = True
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute):
                if node.func.attr == "set" and \
                        isinstance(node.func.value, ast.Subscript):
                    resolve_lines.append(node.lineno)
                elif node.func.attr in _PERSIST_METHODS:
                    sf.persist_line = node.lineno
        if resolve_lines:
            sf.resolve_line = max(resolve_lines)
        if sf.persist_line:
            # the persist is gated on `cmd in self._MUTATING` (or
            # similar): resolve which verbs actually persist here
            for node in ast.walk(fn):
                if isinstance(node, ast.If) and \
                        isinstance(node.test, ast.Compare) and \
                        len(node.test.ops) == 1 and \
                        isinstance(node.test.ops[0], ast.In) and \
                        isinstance(node.test.comparators[0],
                                   ast.Attribute) and \
                        any(isinstance(c, ast.Call) and
                            isinstance(c.func, ast.Attribute) and
                            c.func.attr in _PERSIST_METHODS
                            for s in node.body
                            for c in ast.walk(s)):
                    tup = _const_tuple_attr(
                        fctx, cls, node.test.comparators[0].attr)
                    if tup is not None:
                        sf.persist_verbs = set(tup) & set(manifest)
            if not sf.persist_verbs and sf.cached is not None:
                sf.persist_verbs = set(sf.cached)
        if sf.cached is None:
            sf.cached = set(manifest) - sf.bypass
        break
    return sf


def _minted_seq_sites(tree) -> List[int]:
    """Lines where a router builds a fresh ("SEQ", ...) tuple literal
    and hands it to send_msg — minting its own request identity."""
    sites: List[int] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        ch = _chain(node.func)
        if not ch or ch[-1] != "send_msg":
            continue
        for a in node.args:
            if isinstance(a, ast.Tuple) and a.elts and \
                    isinstance(a.elts[0], ast.Constant) and \
                    a.elts[0].value == "SEQ":
                sites.append(node.lineno)
    return sites


def _extract_machine(path, source) -> Optional[_Machine]:
    """Parse one file; a _Machine when it carries a declare_verbs()
    manifest, None otherwise.  Raises SyntaxError upward (the caller
    turns it into a protocol-error diagnostic)."""
    lines = source.splitlines()
    tree = ast.parse(source, filename=path)
    wire = _wire_summary(tree, lines)
    if not wire.manifest or "protocol" not in wire.meta:
        return None
    m = _Machine(path, lines, tree, wire)
    fctx = _FileCtx(tree)
    branches = _find_branches(fctx, m.manifest)
    for verb in sorted(m.manifest):
        if verb not in branches:
            m.errors.append(
                (m.manifest_line,
                 "verb %s declared in the %s manifest has no dispatch "
                 "branch in this file" % (verb, m.protocol)))
            continue
        rank, line, body, cls, fn = branches[verb]
        vf = _VerbFacts(verb, line, fn.name)
        aliases, tainted = _scan_locals(fn)
        w = _Walker(fctx, vf)
        w.walk_stmts(body, cls, aliases, tainted, False, set(),
                     _INLINE_DEPTH)
        # dedupe (an inlined helper shared by two paths reports once)
        seen: Set[tuple] = set()
        vf.effects = [e for e in vf.effects
                      if not (e.key() in seen or seen.add(e.key()))]
        m.verbs[verb] = vf
    m.seq = _seq_facts(fctx, m.manifest)
    if m.role == "router":
        m.minted_sites = _minted_seq_sites(tree)
    return m


# ---------------------------------------------------------------------------
# client-side stream emits (for protocol-stream-dedupe)
# ---------------------------------------------------------------------------

class _StreamEmit:
    __slots__ = ("path", "line", "verb", "capable", "snippet")

    def __init__(self, path, line, verb, capable, snippet):
        self.path, self.line, self.verb = path, line, verb
        self.capable, self.snippet = capable, snippet


def _first_param(fn) -> Optional[str]:
    args = [a.arg for a in fn.args.args if a.arg not in ("self", "cls")]
    return args[0] if args else None


def _offset_dedupe_capable(fn) -> bool:
    """The callback dedupes iff its frame-offset (first) parameter
    participates in the arithmetic that selects fresh tokens — a
    compare against the high-water mark or an offset subtraction."""
    p = _first_param(fn)
    if p is None:
        return False
    for node in ast.walk(fn if isinstance(fn, ast.Lambda) else
                         ast.Module(body=fn.body, type_ignores=[])):
        if isinstance(node, ast.Compare):
            if any(isinstance(n, ast.Name) and n.id == p
                   for n in ast.walk(node)):
                return True
        elif isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub):
            if any(isinstance(n, ast.Name) and n.id == p
                   for n in ast.walk(node)):
                return True
    return False


def _resolve_stream_callable(value, tree):
    """on_stream=<value> -> the FunctionDef/Lambda it names (through an
    IfExp's truthy arm), or None when unresolvable."""
    if isinstance(value, ast.IfExp):
        value = value.body
    if isinstance(value, ast.Lambda):
        return value
    name = None
    if isinstance(value, ast.Name):
        name = value.id
    elif isinstance(value, ast.Attribute):
        name = value.attr
    if name is None:
        return None
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == name:
            return node
    return None


def _scan_stream_emits(path, tree, lines, stream_verbs) -> List[_StreamEmit]:
    out: List[_StreamEmit] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        a0 = node.args[0]
        if not (isinstance(a0, ast.Constant) and a0.value in stream_verbs):
            continue
        for kw in node.keywords:
            if kw.arg != "on_stream":
                continue
            if isinstance(kw.value, ast.Constant) and \
                    kw.value.value is None:
                continue
            fn = _resolve_stream_callable(kw.value, tree)
            capable = fn is not None and _offset_dedupe_capable(fn)
            snippet = (lines[node.lineno - 1]
                       if 1 <= node.lineno <= len(lines) else "")
            out.append(_StreamEmit(path, node.lineno, a0.value,
                                   capable, snippet))
    return out


# ---------------------------------------------------------------------------
# static rules over extracted machines
# ---------------------------------------------------------------------------

def _diag(rule, m: _Machine, line, message) -> Diagnostic:
    return Diagnostic(rule, m.path, line or m.manifest_line or 1, 0,
                      message, m.line_text(line or m.manifest_line))


def _nonbenign_cats(vf: _VerbFacts) -> Set[str]:
    return {e.category for e in vf.effects if e.category not in _BENIGN}


def _static_checks(m: _Machine) -> Iterator[Diagnostic]:
    for line, msg in m.errors:
        yield _diag(RULE_ERROR, m, line, msg)
    for verb in sorted(m.manifest):
        row = m.manifest[verb]
        vf = m.verbs.get(verb)
        declared_replay = row.get("replay")
        semantics = row.get("semantics")
        declared_mutates = tuple(row.get("mutates") or ())
        vline = vf.line if vf else m.manifest_line

        # -- protocol-replay-class ------------------------------------------
        if m.role in ("server", "collector"):
            if m.seq.present:
                extracted = ("bypass" if verb in m.seq.bypass
                             else "cached")
                if declared_replay == "cached" and extracted == "bypass":
                    yield _diag(
                        RULE_REPLAY, m, m.seq.line,
                        "%s.%s is declared replay=cached but the SEQ "
                        "layer bypasses the replay cache for it — a "
                        "reconnect replay re-executes the request"
                        % (m.protocol, verb))
                elif declared_replay == "bypass" and \
                        extracted == "cached":
                    yield _diag(
                        RULE_REPLAY, m, m.seq.line,
                        "%s.%s is declared replay=bypass but the SEQ "
                        "layer caches it — the manifest misdescribes "
                        "the machine" % (m.protocol, verb))
            elif declared_replay == "cached":
                yield _diag(
                    RULE_REPLAY, m, vline,
                    "%s.%s is declared replay=cached but this machine "
                    "has no _handle_seq replay layer at all"
                    % (m.protocol, verb))
            if semantics == "replayable" and declared_mutates and \
                    declared_replay != "cached":
                yield _diag(
                    RULE_REPLAY, m, vline,
                    "%s.%s mutates %s and is replayable but sits "
                    "outside the replay cache (replay=%s): retried "
                    "mutations double-apply"
                    % (m.protocol, verb, ",".join(declared_mutates),
                       declared_replay))
        elif m.role == "router" and vf is not None:
            routed = vf.calls_forward or vf.calls_fanout
            if declared_replay == "forward" and not routed:
                yield _diag(
                    RULE_REPLAY, m, vline,
                    "%s.%s is declared replay=forward but its dispatch "
                    "branch never forwards/fans-out the envelope"
                    % (m.protocol, verb))
            if declared_replay == "local" and routed:
                yield _diag(
                    RULE_REPLAY, m, vline,
                    "%s.%s is declared replay=local but its branch "
                    "forwards upstream" % (m.protocol, verb))

        # -- protocol-idempotent-epoch --------------------------------------
        if vf is not None and semantics == "idempotent":
            for e in vf.effects:
                if e.category == "epoch" and e.kind == "aug" and \
                        not e.guarded:
                    yield _diag(
                        RULE_EPOCH, m, e.line,
                        "%s.%s is declared idempotent but bumps the "
                        "membership epoch unconditionally — its no-op "
                        "path must leave the epoch alone (PR-16 "
                        "membership contract)" % (m.protocol, verb))

        # -- protocol-effects-drift -----------------------------------------
        if vf is not None:
            extracted_cats = _nonbenign_cats(vf)
            for cat in sorted(extracted_cats):
                if cat not in declared_mutates:
                    where = min(e.line for e in vf.effects
                                if e.category == cat)
                    yield _diag(
                        RULE_EFFECTS, m, where,
                        "%s.%s handler mutates state category %r not "
                        "declared in its manifest mutates tuple"
                        % (m.protocol, verb, cat))
            for cat in declared_mutates:
                if cat not in extracted_cats:
                    yield _diag(
                        RULE_EFFECTS, m, vline,
                        "%s.%s declares mutates=%r but the handler "
                        "branch never touches that category"
                        % (m.protocol, verb, cat))

    # -- protocol-reply-order ----------------------------------------------
    sf = m.seq
    if sf.present and sf.persist_line and sf.resolve_line and \
            sf.persist_line < sf.resolve_line:
        risky = sorted(
            v for v in (sf.persist_verbs or set(m.manifest))
            if v in m.verbs and any(
                e.kind == "aug" and not e.guarded and
                e.category not in _BENIGN
                for e in m.verbs[v].effects))
        if risky:
            yield _diag(
                RULE_ORDER, m, sf.persist_line,
                "%s SEQ layer persists (line %d) BEFORE resolving the "
                "replay entry (line %d): a crash between the two "
                "snapshots the applied effect without its cache entry, "
                "so reconnect replay double-applies %s"
                % (m.protocol, sf.persist_line, sf.resolve_line,
                   ",".join(risky)))

    # -- protocol-router-verbatim -------------------------------------------
    if m.role == "router":
        for line in sorted(m.minted_sites):
            yield _diag(
                RULE_VERBATIM, m, line,
                "%s router builds its own (\"SEQ\", ...) envelope "
                "instead of forwarding the client's verbatim — a "
                "minted (cid, seq) defeats every replica's replay "
                "cache" % m.protocol)


# ---------------------------------------------------------------------------
# model checker: exhaustive bounded fault schedules on a virtual clock
# ---------------------------------------------------------------------------
#
# The simulated server holds per-(request, category) application counts;
# one handler execution applies each category's delta once (an unguarded
# aug adds 1 per execution, anything guarded or set-like lands at 1 no
# matter how often it re-runs).  The declared property is asserted on
# every terminal state:
#   replayable / idempotent : every category count <= 1, == 1 after a
#                             delivered success (crash schedules allow
#                             the documented bounded-loss 0)
#   stateless (mutates=())  : no non-benign category ever counts > 0
# Everything iterates over sorted/static structures — the schedule count
# is a pure function of the shipped tree and is pinned by the tests.

_CLIENT_PREFIX = ("drop", "replydrop", "dup")
_CLIENT_FINAL = ("ok", "dupok")


class _VerbDelta:
    """Per-execution state delta of one verb, in model terms."""

    __slots__ = ("aug_cats", "set_cats")

    def __init__(self, vf: Optional[_VerbFacts]):
        self.aug_cats: Set[str] = set()
        self.set_cats: Set[str] = set()
        for e in (vf.effects if vf else ()):
            if e.category in _BENIGN or e.category.startswith("other:"):
                continue
            if e.kind == "aug" and not e.guarded:
                self.aug_cats.add(e.category)
            else:
                self.set_cats.add(e.category)
        self.aug_cats -= set()
        self.set_cats -= self.aug_cats

    @property
    def cats(self):
        return self.aug_cats | self.set_cats


class _ServerSim:
    """One simulated server: replay cache (latest seq per client, like
    the real single-entry-per-cid caches) + per-(seq, cat) counts +
    optional snapshot durability."""

    def __init__(self, cached: bool, durable: bool, has_stale: bool):
        self.cached = cached
        self.durable = durable
        self.has_stale = has_stale
        self.counts: Dict[Tuple[int, str], int] = {}
        self.entry: Optional[List] = None       # [seq, resolved]
        self.snap = ({}, None)                  # (counts, resolved entry)
        self.execs = 0

    def _apply(self, seq: int, delta: _VerbDelta):
        self.execs += 1
        for c in sorted(delta.aug_cats):
            self.counts[(seq, c)] = self.counts.get((seq, c), 0) + 1
        for c in sorted(delta.set_cats):
            self.counts[(seq, c)] = 1

    def persist(self):
        ent = None
        if self.entry is not None and self.entry[1]:
            ent = list(self.entry)
        self.snap = (dict(self.counts), ent)

    def crash_restore(self):
        counts, ent = self.snap
        self.counts = dict(counts)
        self.entry = list(ent) if ent is not None else None

    def deliver(self, seq: int, delta: _VerbDelta,
                steps: Sequence[str], crash_after: int = -1) -> bool:
        """One request delivery; returns True when it replied (from
        cache or fresh execution).  ``crash_after`` crashes (and
        restores from snapshot) after that many micro-steps."""
        if self.cached:
            if self.entry is not None and self.entry[0] == seq:
                if self.entry[1]:
                    return True                 # replayed from cache
            elif self.entry is not None and seq < self.entry[0] \
                    and self.has_stale:
                return True                     # stale-rejected (error reply)
            else:
                self.entry = [seq, False]
        done = 0
        for step in steps:
            if crash_after >= 0 and done >= crash_after:
                self.crash_restore()
                return False
            if step == "apply":
                self._apply(seq, delta)
            elif step == "resolve":
                if self.cached and self.entry is not None and \
                        self.entry[0] == seq:
                    self.entry[1] = True
            elif step == "persist":
                if self.durable:
                    self.persist()
            done += 1
        if crash_after >= 0 and done >= crash_after:
            self.crash_restore()
            return False
        return True


def _micro_steps(m: _Machine, verb: str) -> List[str]:
    """Ordered micro-steps of one fresh execution: the branch's apply
    and any in-branch persist (by line), then the SEQ layer's resolve /
    persist in their extracted order."""
    vf = m.verbs.get(verb)
    branch_events: List[Tuple[int, str]] = []
    if vf is not None and vf.effects:
        branch_events.append(
            (min(e.line for e in vf.effects), "apply"))
    for line, _guarded in (vf.persists if vf else ()):
        branch_events.append((line, "persist"))
    seq_events: List[Tuple[int, str]] = []
    if m.seq.present and m.seq.resolve_line:
        seq_events.append((m.seq.resolve_line, "resolve"))
    if m.seq.present and m.seq.persist_line and \
            verb in (m.seq.persist_verbs or set()):
        seq_events.append((m.seq.persist_line, "persist"))
    steps = [ev for _l, ev in sorted(branch_events)] + \
            [ev for _l, ev in sorted(seq_events)]
    if "resolve" not in steps:
        steps.append("resolve")
    return steps


def _check_counts(m, verb, row, sim: _ServerSim, schedule,
                  delivered: bool, crashed: bool):
    """Assert the declared property on one terminal state; yields
    violation messages."""
    semantics = row.get("semantics")
    stateless = not tuple(row.get("mutates") or ())
    for (seq, cat), n in sorted(sim.counts.items()):
        if n > 1:
            yield ("%s.%s (%s): request seq=%d applied %dx to %r "
                   "under schedule %s — %s requires exactly-once"
                   % (m.protocol, verb, semantics, seq, n, cat,
                      "/".join(schedule), semantics))
        elif stateless and n > 0:
            yield ("%s.%s declares no mutations but schedule %s left "
                   "%r mutated" % (m.protocol, verb,
                                   "/".join(schedule), cat))
    if delivered and not crashed and not stateless:
        delta = _VerbDelta(m.verbs.get(verb))
        for cat in sorted(delta.cats):
            if sim.counts.get((1, cat), 0) != 1:
                yield ("%s.%s: delivered success under schedule %s "
                       "left %r un-applied (lost effect)"
                       % (m.protocol, verb, "/".join(schedule), cat))


def _client_schedules():
    """All bounded single-client retry schedules: up to two failed
    attempts, then a final delivered one."""
    prefixes = [()]
    for a in _CLIENT_PREFIX:
        prefixes.append((a,))
        for b in _CLIENT_PREFIX:
            prefixes.append((a, b))
    for pre in prefixes:
        for fin in _CLIENT_FINAL:
            yield pre + (fin,)


def _run_single_client(m, verb, row, cached) -> Iterator[Tuple]:
    """(schedule, sim, delivered, crashed) per terminal state."""
    delta = _VerbDelta(m.verbs.get(verb))
    steps = _micro_steps(m, verb)
    for sched in _client_schedules():
        sim = _ServerSim(cached, m.durable, m.seq.has_stale)
        delivered = False
        for act in sched:
            if act == "drop":
                continue
            if act in ("replydrop", "ok"):
                replied = sim.deliver(1, delta, steps)
                delivered = replied and act == "ok"
            elif act in ("dup", "dupok"):
                sim.deliver(1, delta, steps)
                replied = sim.deliver(1, delta, steps)
                delivered = replied and act == "dupok"
        yield (sched, sim, delivered, False)


def _run_crash(m, verb, row, cached) -> Iterator[Tuple]:
    """Crash-restart schedules (durable machines only): attempt 1
    crashes after each micro-step boundary, the server restores from
    its last snapshot, and the client replays the same seq."""
    delta = _VerbDelta(m.verbs.get(verb))
    steps = _micro_steps(m, verb)
    for point in range(len(steps) + 1):
        sim = _ServerSim(cached, True, m.seq.has_stale)
        sim.deliver(1, delta, steps, crash_after=point)
        sim.deliver(1, delta, steps)
        label = ("crash@%d" % point, "retry")
        yield (label, sim, True, True)


def _run_stale(m, verb, row) -> Iterator[Tuple]:
    """An old connection's duplicate of an ALREADY superseded request
    arrives after a newer one executed: it must be rejected as stale,
    never re-executed (the cache only remembers the newest seq)."""
    delta = _VerbDelta(m.verbs.get(verb))
    steps = _micro_steps(m, verb)
    for variant in ("dup-after-newer", "dup-after-newer-replydrop"):
        sim = _ServerSim(True, m.durable, m.seq.has_stale)
        sim.deliver(1, delta, steps)            # request 1 executes
        sim.deliver(2, delta, steps)            # request 2 supersedes it
        sim.deliver(1, delta, steps)            # late duplicate of 1
        yield ((variant,), sim, True, False)


def _run_router(m, verb, row, fanout: bool) -> Iterator[Tuple]:
    """Forward/fan-out schedules over two replicas, each with its own
    replay cache.  verbatim => every hop carries the client's (cid,
    seq); minted => the router stamps a fresh seq per send, so no
    replica can ever dedupe."""
    minted = bool(m.minted_sites)
    delta = _VerbDelta(m.verbs.get(verb))
    # remote execution delta: the forwarded verb's effect lands on the
    # replica; model it as one opaque unguarded application per fresh seq
    remote = _VerbDelta(None)
    remote.aug_cats = {"remote"}

    def fresh_seq(counter):
        counter[0] += 1
        return counter[0] + 100

    if fanout:
        plans = [("once",), ("once", "client-retry")]
    else:
        plans = [("A:ok",), ("A:dup",),
                 ("A:connfail-pre", "B:ok"), ("A:connfail-pre", "B:dup"),
                 ("A:connfail-post", "B:ok"),
                 ("A:connfail-post", "B:dup")]
    for plan in plans:
        reps = {"A": _ServerSim(True, False, True),
                "B": _ServerSim(True, False, True)}
        counter = [0]
        if fanout:
            for hop in plan:
                for name in sorted(reps):
                    seq = fresh_seq(counter) if minted else 1
                    reps[name].deliver(seq, remote, ["apply", "resolve"])
        else:
            for hop in plan:
                name, outcome = hop.split(":")
                seq = fresh_seq(counter) if minted else 1
                if outcome == "connfail-pre":
                    continue
                reps[name].deliver(seq, remote, ["apply", "resolve"])
                if outcome == "dup":
                    seq2 = fresh_seq(counter) if minted else 1
                    reps[name].deliver(seq2, remote,
                                       ["apply", "resolve"])
        for name in sorted(reps):
            if reps[name].execs > 1:
                yield (plan, name, reps[name].execs)


def _model_check(m: _Machine) -> Tuple[List[Diagnostic], int]:
    diags: List[Diagnostic] = []
    schedules = 0
    for verb in sorted(m.manifest):
        if verb not in m.verbs:
            continue                    # protocol-error already raised
        row = m.manifest[verb]
        vline = m.verbs[verb].line
        msgs: List[str] = []
        if m.role in ("server", "collector"):
            cached = m.seq.present and verb not in m.seq.bypass
            for sched, sim, delivered, crashed in \
                    _run_single_client(m, verb, row, cached):
                schedules += 1
                msgs.extend(_check_counts(m, verb, row, sim, sched,
                                          delivered, crashed))
            if cached and m.seq.present:
                for sched, sim, delivered, crashed in \
                        _run_stale(m, verb, row):
                    schedules += 1
                    msgs.extend(_check_counts(m, verb, row, sim, sched,
                                              delivered, crashed))
            if cached and m.durable:
                for sched, sim, delivered, crashed in \
                        _run_crash(m, verb, row, cached):
                    schedules += 1
                    msgs.extend(_check_counts(m, verb, row, sim, sched,
                                              delivered, crashed))
        elif m.role == "router":
            vf = m.verbs[verb]
            if vf.calls_forward or vf.calls_fanout:
                plans = 2 if vf.calls_fanout and not vf.calls_forward \
                    else 6
                schedules += plans
                for plan, rep, execs in _run_router(
                        m, verb, row, fanout=vf.calls_fanout and
                        not vf.calls_forward):
                    msgs.append(
                        "%s.%s: replica %s executed one client request "
                        "%dx under schedule %s — the router must "
                        "forward (cid, seq) verbatim so replica replay "
                        "caches dedupe"
                        % (m.protocol, verb, rep, execs,
                           "/".join(plan)))
            else:
                for sched, sim, delivered, crashed in \
                        _run_single_client(m, verb, row, False):
                    schedules += 1
                    msgs.extend(_check_counts(m, verb, row, sim, sched,
                                              delivered, crashed))
        # one diagnostic per distinct violation message, anchored on the
        # verb's dispatch line (distinct snippet => distinct fingerprint)
        for msg in sorted(set(msgs)):
            diags.append(_diag(RULE_MODEL, m, vline, msg))
    return diags, schedules


# ---------------------------------------------------------------------------
# lane driver
# ---------------------------------------------------------------------------

def check_sources(sources: Dict[str, str],
                  select: Optional[Set[str]] = None):
    """Run the protocol lane over a {repo-relative path: source} map.

    Returns ``(diags, stats)``: suppression-filtered diagnostics (this
    lane has NO baseline — findings are fix-or-suppress-with-why) and
    a stats dict with machine/verb/schedule counts.  Files without a
    declare_verbs() manifest only contribute client-side emit facts.
    """
    machines: List[_Machine] = []
    diags: List[Diagnostic] = []
    supp: Dict[str, Tuple[dict, set]] = {}
    parsed: Dict[str, Tuple[ast.AST, List[str]]] = {}
    for path in sorted(sources):
        src = sources[path]
        path = path.replace(os.sep, "/")
        lines = src.splitlines()
        supp[path] = _parse_suppressions(lines)
        try:
            m = _extract_machine(path, src)
        except SyntaxError as e:
            diags.append(Diagnostic(
                RULE_ERROR, path, e.lineno or 1, 0,
                "file does not parse: %s" % e.msg))
            continue
        parsed[path] = (ast.parse(src, filename=path)
                        if m is None else m.tree, lines)
        if m is not None:
            machines.append(m)
    schedules = 0
    for m in machines:
        diags.extend(_static_checks(m))
        model_diags, n = _model_check(m)
        diags.extend(model_diags)
        schedules += n
    # stream verbs come from the manifests; their emit sites can live in
    # ANY scanned file (the serve client) — check each site once
    stream_verbs: Set[str] = set()
    for m in machines:
        for verb, row in m.manifest.items():
            if row.get("stream"):
                stream_verbs.add(verb)
    if stream_verbs:
        seen_sites: Set[Tuple[str, int]] = set()
        for path in sorted(parsed):
            tree, lines = parsed[path]
            for em in _scan_stream_emits(path, tree, lines,
                                         stream_verbs):
                if (em.path, em.line) in seen_sites:
                    continue
                seen_sites.add((em.path, em.line))
                if not em.capable:
                    diags.append(Diagnostic(
                        RULE_STREAM, em.path, em.line, 0,
                        "%s is a stream verb but this on_stream "
                        "callback never consults its frame offset — "
                        "replayed connections resend STREAM frames and "
                        "the client would apply tokens twice" % em.verb,
                        em.snippet))
    if select is not None:
        diags = [d for d in diags if d.rule in select]
    out = []
    for d in diags:
        per_line, per_file = supp.get(d.path, ({}, set()))
        if not _suppressed(d, per_line, per_file):
            out.append(d)
    out.sort(key=lambda d: (d.path, d.line, d.rule, d.message))
    stats = {
        "machines": [
            {"protocol": m.protocol, "path": m.path, "role": m.role,
             "durable": m.durable, "verbs": len(m.manifest)}
            for m in machines],
        "verbs": sum(len(m.manifest) for m in machines),
        "schedules": schedules,
    }
    return out, stats


def check_paths(paths: Sequence[str], root: Optional[str] = None,
                select: Optional[Set[str]] = None):
    if root is None:
        root = repo_root_of(paths[0] if paths else ".") or os.getcwd()
    sources: Dict[str, str] = {}
    for fp in iter_py_files(paths):
        rel = os.path.relpath(os.path.abspath(fp),
                              root).replace(os.sep, "/")
        with open(fp, encoding="utf-8") as f:
            sources[rel] = f.read()
    return check_sources(sources, select=select)


def run_cli(paths: Sequence[str], fmt: str = "text",
            select: Optional[Set[str]] = None, out=None) -> int:
    """--protocol entry point.  Exit 0 clean, 1 findings, 2 lane
    errors (unparseable machine / undeclared branch).  No baseline:
    every finding is fix-now or suppress-with-why."""
    import sys
    out = out or sys.stdout
    diags, stats = check_paths(list(paths), select=select)
    errors = [d for d in diags if d.rule == RULE_ERROR]
    if fmt == "json":
        json.dump({
            "protocol_schema": 1,
            "machines": stats["machines"],
            "verbs": stats["verbs"],
            "schedules": stats["schedules"],
            "violations": [d.to_json() for d in diags],
        }, out, indent=1, sort_keys=True)
        out.write("\n")
    else:
        for d in diags:
            out.write("%s\n" % d)
        for mrow in stats["machines"]:
            out.write("protocol: %-8s %-28s role=%-9s durable=%-5s "
                      "%2d verbs\n"
                      % (mrow["protocol"], mrow["path"], mrow["role"],
                         mrow["durable"], mrow["verbs"]))
        out.write("protocol: %d machine(s), %d verb(s), %d fault "
                  "schedule(s) checked, %d violation(s)\n"
                  % (len(stats["machines"]), stats["verbs"],
                     stats["schedules"], len(diags)))
    if errors:
        return 2
    return 1 if diags else 0
