"""kvstore push/pull bandwidth harness.

Reference: ``tools/bandwidth/measure.py`` — times repeated
``push``+``pull`` of large arrays through a kvstore and reports GB/s per
store type.  Here the interesting axes are the collective stores (one
jitted reduce; ICI on real hardware, host RAM on the fake mesh) and the
dist_async TCP parameter server.

Run:  python tools/bandwidth.py [--store local|device|ici] [--mb 64]
      [--iters 10] [--compress 2bit|bf16]
(dist_async needs `tools/launch.py -n W -s 1 -- python tools/bandwidth.py
 --store dist_async`.)
"""
import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--store", default="local")
    p.add_argument("--mb", type=float, default=64.0)
    p.add_argument("--iters", type=int, default=10)
    p.add_argument("--compress", default=None)
    p.add_argument("--cpu", action="store_true",
                   help="pin the CPU backend (no TPU probe)")
    args = p.parse_args()
    if args.cpu:
        os.environ.setdefault("MX_FORCE_CPU", "1")
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import nd, kvstore

    kv = kvstore.create(args.store)
    if args.compress:
        kv.set_gradient_compression({"type": args.compress,
                                     "threshold": 0.5})
    n = int(args.mb * (1 << 20) / 4)
    payload = nd.array(np.random.RandomState(0).rand(n).astype(np.float32))
    out = nd.zeros((n,))
    kv.init("x", nd.zeros((n,)))
    kv.pushpull("x", payload, out=out)          # warm (compile/connect)
    out.wait_to_read()
    t0 = time.perf_counter()
    for _ in range(args.iters):
        kv.pushpull("x", payload, out=out)
    out.wait_to_read()
    dt = time.perf_counter() - t0
    moved = 2 * args.mb * args.iters / 1024.0    # push + pull, GiB
    print(json.dumps({
        "metric": "kvstore_pushpull_bandwidth_gb_per_sec",
        "value": round(moved / dt, 3), "unit": "GiB/s",
        "store": kv.type, "mb_per_tensor": args.mb, "iters": args.iters,
        "compression": args.compress,
        "num_workers": kv.num_workers,
    }))


if __name__ == "__main__":
    main()
