"""kvstore push/pull bandwidth harness.

Reference: ``tools/bandwidth/measure.py`` — times repeated
``push``+``pull`` of large arrays through a kvstore and reports GB/s per
store type.  Here the interesting axes are the collective stores (one
jitted reduce; ICI on real hardware, host RAM on the fake mesh) and the
dist_async TCP parameter server.

ISSUE 5 adds *wire accounting*: every exchange notes the bytes its payload
occupies in its wire representation (compressed int8/2-bit codes+scales,
bf16 cast, or full width) on ``engine.wire_bytes``; this harness reports
the measured bytes-per-step and — with ``--compare-compress`` — the
reduction factor vs an uncompressed fp32 baseline run in the same process
(the ISSUE 5 acceptance gate: int8 must move >= 3.5x fewer bytes).

ISSUE 16 adds ``--hierarchical``: a self-contained flat-vs-two-tier
comparison of the dist_async CROSS-SLICE leg.  It spawns an in-process
parameter server, then measures the same int8-pushed payload twice —
flat (int8 push + full-width fp32 pull, the one-tier exchange's return
leg) and two-tier (int8 push + PULLQ int8 pull, the promoted
cross-slice leg of the hierarchical exchange) — and asserts the
two-tier run moves fewer wire bytes per step.  Pull-leg bytes come from
the ``kvstore.pull_wire_bytes`` telemetry counter; push-leg bytes stay
on ``engine.wire_bytes`` as before.

Run:  python tools/bandwidth.py [--store local|device|ici] [--mb 64]
      [--iters 10] [--compress 2bit|int8|bf16] [--compare-compress]
      [--hierarchical]
(dist_async needs `tools/launch.py -n W -s 1 -- python tools/bandwidth.py
 --store dist_async`; --hierarchical brings its own server.)
"""
import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _measure(store, compress, mb, iters, key="x"):
    """One timed pushpull loop; returns
    (kv, GiB/s, push wire bytes per step, pull wire bytes per step)."""
    import numpy as np
    from mxnet_tpu import nd, kvstore
    from mxnet_tpu import telemetry
    from mxnet_tpu.engine import engine

    kv = kvstore.create(store)
    if compress:
        params = {"type": compress}
        if compress == "2bit":
            params["threshold"] = 0.5
        kv.set_gradient_compression(params)
    n = int(mb * (1 << 20) / 4)
    payload = nd.array(np.random.RandomState(0).rand(n).astype(np.float32))
    out = nd.zeros((n,))
    kv.init(key, nd.zeros((n,)))
    kv.pushpull(key, payload, out=out)          # warm (compile/connect)
    out.wait_to_read()
    w0 = engine.snapshot()["wire_bytes"]        # one consistent read
    p0 = telemetry.registry.value("kvstore.pull_wire_bytes")
    t0 = time.perf_counter()
    for _ in range(iters):
        kv.pushpull(key, payload, out=out)
    out.wait_to_read()
    dt = time.perf_counter() - t0
    wire_per_step = (engine.snapshot()["wire_bytes"] - w0) / iters
    pull_per_step = (telemetry.registry.value("kvstore.pull_wire_bytes")
                     - p0) / iters
    moved = 2 * mb * iters / 1024.0              # push + pull, GiB
    return kv, round(moved / dt, 3), int(wire_per_step), int(pull_per_step)


def _hierarchical_main(args):
    """--hierarchical (ISSUE 16): flat vs two-tier dist_async exchange,
    self-contained — spawns an in-process parameter server (the
    cross-slice tier), runs the same int8-pushed payload through the
    flat return leg (full-width fp32 pull) and the two-tier one (PULLQ
    int8 pull), and asserts the two-tier run moves fewer cross-slice
    wire bytes per step.  Exits nonzero when it does not — the
    bench_compare gate."""
    import socket as _socket
    import threading

    os.environ.setdefault("MX_FORCE_CPU", "1")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import mxnet_tpu as mx   # noqa: F401  (backend init)
    from mxnet_tpu.kvstore import server as ps_server

    s = _socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    threading.Thread(target=ps_server.serve_forever,
                     kwargs=dict(port=port, num_workers=1),
                     daemon=True).start()
    addr = "127.0.0.1:%d" % port
    os.environ["MX_PS_ROOT"] = addr
    os.environ["MX_PS_ROOTS"] = addr
    os.environ["DMLC_NUM_SERVER"] = "1"
    os.environ["DMLC_NUM_WORKER"] = "1"
    deadline = time.time() + 10.0
    while time.time() < deadline:
        try:
            _socket.create_connection(("127.0.0.1", port),
                                      timeout=0.2).close()
            break
        except OSError:
            time.sleep(0.05)

    mb = 8.0 if args.mb == 64.0 else args.mb   # a single-server compare
                                               # needs no 64 MB payload
    os.environ["MX_EXCHANGE_HIERARCHICAL"] = "0"
    kv_f, flat_gbps, flat_push, flat_pull = _measure(
        "dist_async", "int8", mb, args.iters, key="h_flat")
    os.environ["MX_EXCHANGE_HIERARCHICAL"] = "1"
    kv_h, hier_gbps, hier_push, hier_pull = _measure(
        "dist_async", "int8", mb, args.iters, key="h_tier")
    kv_h.close()
    kv_f.close()
    flat_total = flat_push + flat_pull
    hier_total = hier_push + hier_pull
    report = {
        "metric": "kvstore_hierarchical_cross_slice_bytes",
        "store": "dist_async", "mb_per_tensor": mb, "iters": args.iters,
        "compression": "int8",
        "flat": {"push_wire_bytes": flat_push,
                 "pull_wire_bytes": flat_pull,
                 "total_wire_bytes": flat_total,
                 "gb_per_sec": flat_gbps},
        "hierarchical": {"push_wire_bytes": hier_push,
                         "pull_wire_bytes": hier_pull,
                         "total_wire_bytes": hier_total,
                         "gb_per_sec": hier_gbps},
        "cross_slice_reduction": round(flat_total / max(1, hier_total), 3),
        "ok": hier_total < flat_total,
    }
    print(json.dumps(report))
    if not report["ok"]:
        print("bandwidth.py: FAIL - hierarchical exchange moved %d "
              "wire bytes/step, flat moved %d (expected fewer)"
              % (hier_total, flat_total), file=sys.stderr)
        return 1
    return 0


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--store", default="local")
    p.add_argument("--mb", type=float, default=64.0)
    p.add_argument("--iters", type=int, default=10)
    p.add_argument("--compress", default=None)
    p.add_argument("--compare-compress", action="store_true",
                   help="also run an uncompressed fp32 baseline and "
                   "report the measured wire-bytes reduction factor")
    p.add_argument("--hierarchical", action="store_true",
                   help="self-contained flat-vs-two-tier dist_async "
                   "comparison (in-process server); asserts the "
                   "two-tier exchange moves fewer cross-slice wire "
                   "bytes per step than the flat int8 exchange")
    p.add_argument("--cpu", action="store_true",
                   help="pin the CPU backend (no TPU probe)")
    args = p.parse_args()
    if args.cpu:
        os.environ.setdefault("MX_FORCE_CPU", "1")
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if args.hierarchical:
        sys.exit(_hierarchical_main(args))
    import mxnet_tpu as mx   # noqa: F401  (backend init)

    kv, gbps, wire, _pull = _measure(args.store, args.compress, args.mb,
                                     args.iters)
    report = {
        "metric": "kvstore_pushpull_bandwidth_gb_per_sec",
        "value": gbps, "unit": "GiB/s",
        "store": kv.type, "mb_per_tensor": args.mb, "iters": args.iters,
        "compression": args.compress,
        "wire_bytes_per_step": wire,
        "num_workers": kv.num_workers,
    }
    if args.compare_compress:
        # fresh store + key: independent residual state, same payload
        _, base_gbps, base_wire, _bp = _measure(args.store, None, args.mb,
                                                args.iters, key="x_fp32")
        report["fp32_wire_bytes_per_step"] = base_wire
        report["fp32_gb_per_sec"] = base_gbps
        report["wire_reduction_vs_fp32"] = round(
            base_wire / max(1, wire), 3)
    print(json.dumps(report))


if __name__ == "__main__":
    main()
