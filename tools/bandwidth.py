"""kvstore push/pull bandwidth harness.

Reference: ``tools/bandwidth/measure.py`` — times repeated
``push``+``pull`` of large arrays through a kvstore and reports GB/s per
store type.  Here the interesting axes are the collective stores (one
jitted reduce; ICI on real hardware, host RAM on the fake mesh) and the
dist_async TCP parameter server.

ISSUE 5 adds *wire accounting*: every exchange notes the bytes its payload
occupies in its wire representation (compressed int8/2-bit codes+scales,
bf16 cast, or full width) on ``engine.wire_bytes``; this harness reports
the measured bytes-per-step and — with ``--compare-compress`` — the
reduction factor vs an uncompressed fp32 baseline run in the same process
(the ISSUE 5 acceptance gate: int8 must move >= 3.5x fewer bytes).

Run:  python tools/bandwidth.py [--store local|device|ici] [--mb 64]
      [--iters 10] [--compress 2bit|int8|bf16] [--compare-compress]
(dist_async needs `tools/launch.py -n W -s 1 -- python tools/bandwidth.py
 --store dist_async`.)
"""
import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _measure(store, compress, mb, iters, key="x"):
    """One timed pushpull loop; returns (GiB/s, wire bytes per step)."""
    import numpy as np
    from mxnet_tpu import nd, kvstore
    from mxnet_tpu.engine import engine

    kv = kvstore.create(store)
    if compress:
        params = {"type": compress}
        if compress == "2bit":
            params["threshold"] = 0.5
        kv.set_gradient_compression(params)
    n = int(mb * (1 << 20) / 4)
    payload = nd.array(np.random.RandomState(0).rand(n).astype(np.float32))
    out = nd.zeros((n,))
    kv.init(key, nd.zeros((n,)))
    kv.pushpull(key, payload, out=out)          # warm (compile/connect)
    out.wait_to_read()
    w0 = engine.snapshot()["wire_bytes"]        # one consistent read
    t0 = time.perf_counter()
    for _ in range(iters):
        kv.pushpull(key, payload, out=out)
    out.wait_to_read()
    dt = time.perf_counter() - t0
    wire_per_step = (engine.snapshot()["wire_bytes"] - w0) / iters
    moved = 2 * mb * iters / 1024.0              # push + pull, GiB
    return kv, round(moved / dt, 3), int(wire_per_step)


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--store", default="local")
    p.add_argument("--mb", type=float, default=64.0)
    p.add_argument("--iters", type=int, default=10)
    p.add_argument("--compress", default=None)
    p.add_argument("--compare-compress", action="store_true",
                   help="also run an uncompressed fp32 baseline and "
                   "report the measured wire-bytes reduction factor")
    p.add_argument("--cpu", action="store_true",
                   help="pin the CPU backend (no TPU probe)")
    args = p.parse_args()
    if args.cpu:
        os.environ.setdefault("MX_FORCE_CPU", "1")
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import mxnet_tpu as mx   # noqa: F401  (backend init)

    kv, gbps, wire = _measure(args.store, args.compress, args.mb, args.iters)
    report = {
        "metric": "kvstore_pushpull_bandwidth_gb_per_sec",
        "value": gbps, "unit": "GiB/s",
        "store": kv.type, "mb_per_tensor": args.mb, "iters": args.iters,
        "compression": args.compress,
        "wire_bytes_per_step": wire,
        "num_workers": kv.num_workers,
    }
    if args.compare_compress:
        # fresh store + key: independent residual state, same payload
        _, base_gbps, base_wire = _measure(args.store, None, args.mb,
                                           args.iters, key="x_fp32")
        report["fp32_wire_bytes_per_step"] = base_wire
        report["fp32_gb_per_sec"] = base_gbps
        report["wire_reduction_vs_fp32"] = round(
            base_wire / max(1, wire), 3)
    print(json.dumps(report))


if __name__ == "__main__":
    main()
