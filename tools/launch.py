#!/usr/bin/env python
"""launch.py — start (and supervise) a multi-process / multi-host training job.

Reference: ``tools/launch.py`` + ``3rdparty/ps-lite/tracker``
(dmlc_tracker.local/ssh — spawn workers+servers with DMLC_* envs).

TPU-native contract: there are no parameter servers — every process is a
jax.distributed worker; ``mxnet_tpu.parallel.init_process_group()``
(called by the training script, or implicitly via MX_DIST_AUTO_INIT) reads
the env this launcher sets:

  MX_COORDINATOR    host:port of process 0
  MX_NUM_PROCESSES  world size
  MX_PROCESS_ID     this process's rank

Modes:
  -n N --launcher local  : N processes on this host (separate CPU backends;
                           for pipeline/io testing — real multi-chip needs
                           one process per host)
  -n N --launcher ssh -H hostfile : one process per hostfile line via ssh
  --launcher manual      : print the per-rank environment + command

Supervision (the restart-and-resume layer over ISSUE 1's recovery
primitives): with ``--restart on-failure`` (or ``--restart N``) the
launcher keeps watching every spawned rank and parameter server.  A
process that exits nonzero is restarted with its ORIGINAL environment —
same rank, same MX_COORDINATOR (rank 0 re-binds its own coordinator
port, so a dead rank 0 regenerates the coordinator for the job), same
MX_PS_SNAPSHOT path — so ``fit(checkpoint_dir=..., auto_resume)`` and
the durable PS pick up from the last step instead of from scratch.
Restart delays follow ``mxnet_tpu.fault.RetryPolicy`` exponential
backoff; a rank that exceeds ``--max-restarts`` escalates to whole-job
teardown (every surviving process is killed, the job exits nonzero).
``--hang-timeout S`` additionally arms heartbeat-file liveness: each
rank gets MX_HEARTBEAT_FILE, the fit loop touches it every batch, and a
rank whose file goes stale for S seconds is killed and restarted —
distinguishing *wedged* from merely *slow* (a slow rank keeps beating).
In-process, ``MX_STEP_TIMEOUT`` (mxnet_tpu.health watchdog) converts a
hung step into exit code 86 the supervisor sees like any other crash.

Serving fleet tier (ISSUE 17): ``--serve-port-base B`` tells the
supervisor its command is a serving replica bound at ``B + rank``, so
each process is registered with the embedded fleet collector as a
wire-scraped ``serve`` member (queue depth, decode occupancy, KV
headroom — the router's routing signals).  ``--route PORT``
additionally fronts the replicas with the session router
(``python -m mxnet_tpu.serve.router``) reading an authoritative
replicas file this supervisor rewrites, and ``--autoscale MIN:MAX``
arms the SLO-burn autoscaler: when any fleet SLO burn (from the merged
snapshot; targets via MX_FLEET_SLO_*) holds >= MX_AUTOSCALE_UP_BURN
for MX_AUTOSCALE_HOLD scrape rounds, a warm replica is spawned into
the spike (compile-cache makes that seconds); when every burn holds <=
MX_AUTOSCALE_DOWN_BURN the newest replica is retired DRAIN-not-kill —
dropped from the replicas file first (the router stops admitting),
then the wire DRAIN verb lets its in-flight generations finish against
a bounded deadline; the clean exit 0 is expected, not a failure.
Post-action cooldowns back off exponentially (MX_AUTOSCALE_COOLDOWN)
so the fleet never flaps.  A crashed replica is an involuntary retire:
the router fails its pinned sessions over immediately, the supervisor
restarts it (or, past the restart budget, shrinks the serve tier and
continues, like --elastic does for workers).

Elastic membership (ISSUE 16): ``--elastic`` spawns every worker with
MX_ELASTIC=1, so each rank JOINs the parameter-server membership table
at store init, and changes two supervisor behaviours.  Involuntary: a
worker that exhausts its restart budget is given up — the supervisor
sends LEAVE on its behalf to every server (barriers re-quorum on the
survivors), retires it from the fleet plane, and the job CONTINUES on
the remaining ranks instead of tearing down (teardown only when the
last worker dies).  Voluntary: ``--resize-file PATH`` polls PATH for a
target worker count; when it differs from the live world the supervisor
drains every rank at its next epoch boundary (SIGTERM → the elastic fit
handler checkpoints and exits 0), LEAVEs removed ranks out of the
membership, and respawns ranks ``0..N_new-1`` with the new world size
and a bumped MX_ELASTIC_EPOCH — the epoch salts the fusion-bucket CRC
names, so the resized job replans its exchange layout with zero
coordination and can never misread a pre-resize server accumulator.

Example:
  python tools/launch.py -n 2 --restart on-failure \\
      --fault 'worker.step:crash:after=5' -- python train.py --kv dist
"""
import argparse
import json
import os
import pickle
import shlex
import shutil
import socket
import struct
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# keep in sync with mxnet_tpu.health.WATCHDOG_EXIT_CODE (launch.py stays
# import-light: mxnet_tpu loads lazily, only when a restart is needed)
WATCHDOG_EXIT_CODE = 86


def _free_port() -> int:
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _compat_env(rank: int, coordinator: str, n: int):
    """The launcher contract: MX_* plus the reference-era DMLC_* names,
    for scripts that read either.  launch_manual prints exactly this."""
    return {
        "MX_COORDINATOR": coordinator,
        "MX_NUM_PROCESSES": str(n),
        "MX_PROCESS_ID": str(rank),
        "DMLC_NUM_WORKER": str(n),
        "DMLC_WORKER_ID": str(rank),
        "DMLC_ROLE": "worker",
    }


def _env_for(rank: int, coordinator: str, n: int):
    env = dict(os.environ)
    env.update(_compat_env(rank, coordinator, n))
    return env


# ---------------------------------------------------------------------------
# Supervisor
# ---------------------------------------------------------------------------

class SupervisedProc:
    """One supervised process: argv + frozen env + restart accounting."""

    def __init__(self, name, argv, env, role="worker", addr=None,
                 heartbeat=None):
        self.name = name
        self.argv = list(argv)
        self.env = dict(env)          # frozen: restarts reuse it verbatim
        self.role = role              # "worker"|"server"|"serve"|"router"
        self.addr = addr              # host:port (servers, for STOP)
        self.draining = False         # serve tier: retirement in flight
        self.heartbeat = heartbeat    # liveness file path or None
        self.fleet_key = None         # this proc's fleet-member id
        self.proc = None
        self.restarts = 0
        self.restart_at = None        # backoff deadline for the respawn
        self.spawned_wall = None      # wall clock of the last spawn
        self.rc = None                # final status once permanently done
        self.we_killed = False        # we tore it down: rc not a failure

    @property
    def done(self):
        return self.rc is not None

    def alive(self):
        return self.proc is not None and self.proc.poll() is None


class Supervisor:
    """Restart-and-resume process supervisor (tentpole of ISSUE 2).

    Policy ``never`` reproduces the old launcher: spawn once, wait for
    every worker, fold return codes.  Policy ``on-failure`` restarts a
    crashed process with its original env after a
    ``mxnet_tpu.fault.RetryPolicy`` backoff delay (so restart storms
    decorrelate), up to ``max_restarts`` per process; past the budget
    the whole job is torn down nonzero.  Heartbeat-file staleness
    (``hang_timeout``) counts as a crash: the wedged process is killed
    first, then the restart path runs.

    Backoff is DEADLINE-scheduled, not slept inline: a rank awaiting its
    restart window never blocks reaping, hang detection, or restarts of
    the other processes (a correlated failure restarts every rank after
    ONE backoff, not a serialized sum of them).  All backoff timing goes
    through ``mxnet_tpu.fault``'s module clock — under
    ``fault.use_virtual_time()`` chaos tests drive the full schedule
    with zero real sleeping.
    """

    def __init__(self, restart="never", max_restarts=3, backoff=None,
                 hang_timeout=None, startup_grace=None, poll=0.05,
                 log=None, status_interval=None, elastic=False,
                 resize_file=None, drain_timeout=60.0):
        if restart not in ("never", "on-failure"):
            raise ValueError("restart must be 'never' or 'on-failure'")
        self.restart = restart
        self.max_restarts = int(max_restarts)
        # elastic membership (ISSUE 16): shrink-and-continue past the
        # restart budget, plus resize-file-driven voluntary resize
        self.elastic = bool(elastic)
        self.resize_file = resize_file
        self.drain_timeout = float(drain_timeout)
        self.ps_addrs = []            # server addrs for LEAVE-on-behalf
        self.worker_factory = None    # (rank, n, generation) -> spec
        self.generation = 0           # membership generation: bumped per
                                      # resize, rides MX_ELASTIC_EPOCH
        self._resize_applied = None   # last target honoured (an
                                      # involuntary shrink must not be
                                      # "healed" by a stale resize file)
        self._backoff = backoff       # lazy: RetryPolicy needs mxnet_tpu
        self.hang_timeout = hang_timeout
        # fleet status table (ISSUE 8): every status_interval wall
        # seconds — and on every failure — print one line per process
        # from the heartbeat files' telemetry JSON payload (step,
        # throughput, last-exchange bytes); 0 = failures only, None
        # (default) = no tables at all
        self.status_interval = status_interval
        self._last_status = time.time()
        self._crash_seq = 0
        # before the FIRST beat (no heartbeat file yet) a process gets a
        # generous startup window — jax import + first-batch compile are
        # legitimately slow — but not forever: a (re)spawn that wedges
        # during startup must still be detected or the job hangs for
        # good.  Default: 20x the hang timeout, at least 120s.
        self.startup_grace = startup_grace if startup_grace is not None \
            else (max(120.0, 20.0 * hang_timeout) if hang_timeout
                  else None)
        self.poll = poll
        self.log = log or (lambda msg: print("launch.py: %s" % msg,
                                             file=sys.stderr, flush=True))
        self.procs = []
        self.job_rc = 0
        self._fault = None            # mxnet_tpu.fault, loaded lazily
        self.fleet = None             # embedded FleetCollector (ISSUE 12)
        # serving fleet tier (ISSUE 17): --route/--autoscale wiring
        self.replicas_file = None     # router's authoritative addr list
        self.fleet_port = None        # FLEET wire port (router signals)
        self.autoscale = None         # (min, max) replica bounds or None
        self.serve_factory = None     # index -> (name, argv, env, addr,
                                      #           heartbeat)
        self._as_next_index = 0       # next spawned replica's rank
        self._as_up_hold = 0          # consecutive rounds burn >= up
        self._as_down_hold = 0        # consecutive rounds burn <= down
        self._as_last_round = None    # last scrape round evaluated
        self._as_last_dir = None      # last action direction
        self._as_streak = 0           # consecutive same-direction acts
        self._as_cooldown_until = 0.0
        self._as_policy = None        # RetryPolicy-shaped cooldown

    # -- registration -------------------------------------------------------
    def add(self, name, argv, env, role="worker", addr=None,
            heartbeat=None):
        sp = SupervisedProc(name, argv, env, role=role, addr=addr,
                            heartbeat=heartbeat)
        self.procs.append(sp)
        return sp

    # -- plumbing -----------------------------------------------------------
    def _fault_mod(self):
        """mxnet_tpu.fault, imported on first use only — a job that
        never crashes never pays the framework import in the launcher."""
        if self._fault is None:
            if REPO not in sys.path:
                sys.path.insert(0, REPO)
            from mxnet_tpu import fault
            self._fault = fault
        return self._fault

    def _now(self):
        return self._fault.now() if self._fault is not None \
            else time.monotonic()

    def _sleep_poll(self):
        # once the fault clock is loaded (first failure), poll ticks go
        # through it too, so virtual-time tests advance restart deadlines
        if self._fault is not None:
            self._fault.sleep(self.poll)
        else:
            time.sleep(self.poll)

    def _backoff_delay(self, attempt):
        fault = self._fault_mod()
        if self._backoff is None:
            # deadline is irrelevant (only .delay() is used); jitter
            # decorrelates simultaneous rank restarts after a correlated
            # failure (e.g. the coordinator died under all of them)
            self._backoff = fault.RetryPolicy(
                deadline=float("inf"), base=1.0, max_delay=30.0,
                jitter=0.1)
        return self._backoff.delay(attempt)

    def _spawn(self, sp):
        if sp.heartbeat:
            # drop the previous incarnation's beats: liveness
            # enforcement (re)starts at this process's FIRST beat, so
            # neither a stale leftover file nor a slow startup (jax
            # import, first-batch compile) can get a healthy process
            # killed before its first batch
            try:
                os.remove(sp.heartbeat)
            except OSError:
                pass
        sp.spawned_wall = time.time()
        sp.proc = subprocess.Popen(sp.argv, env=sp.env)

    def _kill(self, sp):
        if not sp.alive():
            return
        sp.we_killed = True
        sp.proc.terminate()
        try:
            sp.proc.wait(timeout=5.0)
        except subprocess.TimeoutExpired:
            sp.proc.kill()
            try:
                # bounded even after SIGKILL: an unkillable (D-state)
                # child must not wedge the whole supervisor loop — the
                # zombie is reaped by a later poll() instead
                sp.proc.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                self.log("%s ignored SIGKILL (uninterruptible?); "
                         "leaving it to a later poll" % sp.name)

    def _fold(self, rc):
        if rc:
            self.job_rc = self.job_rc or (rc if rc > 0 else 1)

    # -- fleet status (ISSUE 8) --------------------------------------------

    # malformed heartbeat JSON lines seen by _read_beat, tolerated and
    # COUNTED (ISSUE 12 satellite): a half-written payload line must
    # not drop the whole beat — the head line still proves liveness.
    # Class-level because _read_beat is a staticmethod.
    malformed_beats = 0

    @staticmethod
    def _read_beat(sp):
        """(age_seconds_or_None, head_line, telemetry_payload_dict) from
        a rank's heartbeat file.  Line 1 is the classic
        ``<unix-time> <epoch> <batch>`` / ``... done`` beat; line 2, when
        present, is the flight recorder's latest step record as compact
        JSON (mxnet_tpu.telemetry.heartbeat_payload, ``schema``-tagged).

        Age normally compares wall time against the file mtime; when
        this process runs under mxnet_tpu.fault's VIRTUAL clock (chaos
        tests) that compare races — the payload's ``ts`` field was
        stamped by fault.now() in the beating process, so the age is
        computed on that same injectable clock instead."""
        if not sp.heartbeat:
            return None, "", {}
        try:
            age = time.time() - os.stat(sp.heartbeat).st_mtime
            with open(sp.heartbeat) as f:
                lines = f.read().splitlines()
        except OSError:
            return None, "", {}
        # import-light inline copy of mxnet_tpu.telemetry.parse_heartbeat
        # (the launcher must not import the framework on its happy
        # path) — keep the two in sync
        head = lines[0] if lines else ""
        payload = {}
        if len(lines) > 1 and lines[1].strip():
            try:
                payload = json.loads(lines[1])
                if not isinstance(payload, dict):
                    raise ValueError("payload is not a JSON object")
            except ValueError:
                payload = {}
                Supervisor.malformed_beats += 1
        try:
            # schema gate: a beat stamped by a NEWER framework version
            # is ignored, not mis-rendered (1 = the schema this copy
            # understands; mxnet_tpu.telemetry.HEARTBEAT_SCHEMA)
            if payload.get("schema", 1) > 1:
                payload = {}
        except TypeError:
            payload = {}
            Supervisor.malformed_beats += 1
        # only consulted when the framework is already loaded — the
        # launcher stays import-light on the happy path
        _f = sys.modules.get("mxnet_tpu.fault")
        if _f is not None and _f.is_virtual() and \
                isinstance(payload.get("ts"), (int, float)):
            age = max(0.0, _f.now() - float(payload["ts"]))
        return age, head, payload

    @staticmethod
    def _state_of(sp):
        if sp.done:
            return "done(rc=%s)" % sp.rc
        if sp.restart_at is not None:
            return "restarting"
        return "running" if sp.alive() else "spawning"

    # -- embedded fleet collector (ISSUE 12) --------------------------------
    def _start_collector(self):
        """Embed a fleet collector so every supervised job gets the
        fleet plane for free: workers scrape via their heartbeat files,
        parameter servers over the METRICS wire verb.  The collector
        thread runs the scrape/merge/detect loop; the status table and
        crash dumps read its merged snapshot.  Lazy-imports the
        framework (same posture as _fault_mod); any failure degrades to
        the old heartbeat-only table, never to a dead supervisor."""
        if self.fleet is not None:
            return
        candidates = [sp for sp in self.procs
                      if sp.heartbeat or (sp.role in ("server", "serve",
                                                      "router") and
                                          sp.addr)]
        if not candidates:
            return
        try:
            if REPO not in sys.path:
                sys.path.insert(0, REPO)
            from mxnet_tpu import fleet as _fleet
            from mxnet_tpu.base import get_env as _get_env
            interval = _get_env("MX_FLEET_INTERVAL", 2.0, float)
            if not interval or interval <= 0:
                return      # MX_FLEET_INTERVAL=0 opts the embed out
            members = []
            nsrv = 0
            for sp in candidates:
                if sp.role in ("serve", "router") and sp.addr:
                    # serve tier (ISSUE 17): wire-scraped with the
                    # member row carrying its addr, so the merged
                    # snapshot is directly router/autoscaler-consumable
                    # (fleet.replica_signals)
                    rank = sp.env.get("MX_PROCESS_ID",
                                      "0" if sp.role == "router"
                                      else len(members))
                    m = _fleet.FleetMember(sp.role, rank, addr=sp.addr)
                elif sp.heartbeat:
                    rank = sp.env.get("MX_PROCESS_ID", len(members))
                    m = _fleet.FleetMember("worker", rank,
                                           heartbeat=sp.heartbeat)
                else:
                    m = _fleet.FleetMember("server", nsrv, addr=sp.addr)
                    nsrv += 1
                sp.fleet_key = m.key
                members.append(m)
            self.fleet = _fleet.FleetCollector(members).start(
                port=self.fleet_port)
        except Exception as e:
            self.log("fleet collector unavailable (%s); falling back "
                     "to heartbeat-only status" % e)
            self.fleet = None

    def _stop_collector(self):
        if self.fleet is not None:
            try:
                self.fleet.stop()
            except Exception:
                pass

    def status_table(self):
        """Live fleet status as a rendered text table — one row per
        supervised process.  Row data comes from the heartbeat
        telemetry payloads; presence, straggler and SLO flags come from
        the embedded collector's merged fleet snapshot when it runs
        (ISSUE 12 — the table IS the fleet snapshot's view of the job).
        What a human tailing the supervisor log (and chaos_smoke.sh)
        reads to see where the fleet is."""
        snap = self.fleet.snapshot() if self.fleet is not None else None
        fleet_members = (snap or {}).get("members") or {}
        stragglers = {f.get("member"): f
                      for f in (snap or {}).get("stragglers") or []}
        cols = ("proc", "state", "restarts", "step", "epoch",
                "steps/s", "img/s", "wire KB", "beat age", "flags")
        rows = [cols]
        for sp in self.procs:
            age, _head, p = self._read_beat(sp)
            flags = []
            meta = fleet_members.get(sp.fleet_key)
            if meta is not None and not meta.get("present") and \
                    not sp.done:
                flags.append("ABSENT")
            f = stragglers.get(sp.fleet_key)
            if f:
                flags.append("STRAGGLER(%.3gx %s)"
                             % (f.get("ratio", 0),
                                f.get("dominant_phase") or "?"))
            rows.append((
                sp.name, self._state_of(sp), str(sp.restarts),
                str(p.get("step", "-")), str(p.get("epoch", "-")),
                "%.3g" % p["steps_per_sec"] if "steps_per_sec" in p
                else "-",
                "%.4g" % p["throughput"] if "throughput" in p else "-",
                "%.1f" % (p["wire_bytes"] / 1024.0)
                if "wire_bytes" in p else "-",
                "%.1fs" % age if age is not None else "-",
                " ".join(flags) or "-"))
        widths = [max(len(r[i]) for r in rows) for i in range(len(cols))]
        lines = ["  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip()
                 for r in rows]
        sep = "-" * len(lines[0])
        out = ["fleet status:", sep] + lines + [sep]
        slo = (snap or {}).get("slo") or {}
        breached = sorted((slo.get("breached") or {}))
        if breached:
            out.append("SLO BREACH (latched): %s" % ", ".join(breached))
        return "\n".join(out)

    def _maybe_status(self):
        if not self.status_interval:
            return
        now = time.time()
        if now - self._last_status >= self.status_interval:
            self._last_status = now
            self.log("\n" + self.status_table())

    def _crash_dump(self, sp, rc, kind):
        """Supervisor-side crash record into MX_CRASH_DIR: what the
        supervisor observed of a failed process (exit code, restart
        budget, last heartbeat payload).  The worker's own in-process
        dump (flight-recorder ring) lands next to it; together they say
        what the rank was doing and how it died."""
        d = os.environ.get("MX_CRASH_DIR")
        if not d:
            return None
        try:
            os.makedirs(d, exist_ok=True)
            self._crash_seq += 1
            age, head, payload = self._read_beat(sp)
            safe = "".join(c if c.isalnum() else "_" for c in sp.name)
            path = os.path.join(d, "supervisor-%s-%d.json"
                                % (safe, self._crash_seq))
            blob = {"reason": kind, "proc": sp.name, "role": sp.role,
                    "rc": rc, "restarts": sp.restarts,
                    "wall_time": time.time(),
                    "heartbeat_age": age, "heartbeat_head": head,
                    "heartbeat": payload}
            if self.fleet is not None:
                # the last merged fleet snapshot (ISSUE 12): the
                # post-mortem shows what the REST of the job was doing
                # when this rank died, not just the dead rank's story
                try:
                    blob["fleet"] = self.fleet.snapshot()
                except Exception:
                    blob["fleet"] = None
            tmp = "%s.tmp.%d" % (path, os.getpid())
            with open(tmp, "w") as f:
                json.dump(blob, f, indent=1)
            os.replace(tmp, path)
            return path
        except OSError:
            return None

    # -- failure handling ---------------------------------------------------
    def _describe(self, rc):
        if rc == WATCHDOG_EXIT_CODE:
            return ("exit %d (MX_STEP_TIMEOUT watchdog: hung step)"
                    % rc)
        if rc < 0:
            return "signal %d" % -rc
        return "exit %d" % rc

    def _on_failure(self, sp, rc):
        """Crashed (or was hang-killed).  Returns True to keep running,
        False when the budget is exhausted → caller tears the job down."""
        self._crash_dump(sp, rc, self._describe(rc))
        if self.status_interval is not None:
            # a failure is always worth a fleet snapshot, whatever the
            # interval cadence says
            self.log("\n" + self.status_table())
        if self.restart != "on-failure":
            sp.rc = rc
            self._fold(rc)
            return True                       # old posture: wait the rest
        if sp.restarts >= self.max_restarts:
            if self.elastic and sp.role == "worker":
                survivors = [w for w in self.procs
                             if w is not sp and w.role == "worker"
                             and not w.done]
                if survivors:
                    # shrink-and-continue (ISSUE 16): an elastic job
                    # gives the rank up instead of tearing everyone
                    # down.  LEAVE on its behalf evicts it from the PS
                    # membership (barriers re-quorum on the survivors
                    # at the current membership epoch) and the fleet
                    # plane retires it immediately — a departed member
                    # is gone by protocol, not merely silent, so it
                    # must never linger as ABSENT/STRAGGLER.
                    self.log("%s failed (%s) past its restart budget "
                             "(%d) - elastic shrink: continuing with "
                             "%d worker(s)"
                             % (sp.name, self._describe(rc),
                                self.max_restarts, len(survivors)))
                    sp.rc = rc        # done; NOT folded — the job's
                                      # exit code belongs to survivors
                    try:
                        rank = int(sp.env.get("MX_PROCESS_ID", -1))
                    except (TypeError, ValueError):
                        rank = -1
                    if rank >= 0:
                        for addr in self.ps_addrs:
                            try:
                                _send_leave(addr, rank)
                            except OSError as e:
                                self.log("LEAVE r%d -> %s failed (%s); "
                                         "liveness eviction will catch "
                                         "up" % (rank, addr, e))
                    if self.fleet is not None and sp.fleet_key:
                        try:
                            self.fleet.retire(sp.fleet_key)
                        except Exception:
                            pass
                    return True
            if sp.role == "serve":
                survivors = [w for w in self.procs
                             if w is not sp and w.role == "serve"
                             and not w.done]
                if survivors:
                    # involuntary retire (ISSUE 17): the serve tier
                    # shrinks and continues — the router already failed
                    # this replica's pinned sessions over on the first
                    # dead forward; here the supervisor just stops
                    # paying for restarts and retires it from the
                    # signal plane + the replicas file
                    self.log("%s failed (%s) past its restart budget "
                             "(%d) - involuntary retire: serving "
                             "continues on %d replica(s)"
                             % (sp.name, self._describe(rc),
                                self.max_restarts, len(survivors)))
                    sp.rc = rc        # done; NOT folded — the tier's
                                      # exit code belongs to survivors
                    self._write_replicas_file()
                    if self.fleet is not None and sp.fleet_key:
                        try:
                            self.fleet.retire(sp.fleet_key)
                        except Exception:
                            pass
                    return True
            self.log("%s failed (%s) and exhausted its restart budget "
                     "(%d) - tearing the job down"
                     % (sp.name, self._describe(rc), self.max_restarts))
            sp.rc = rc
            self._fold(rc)
            return False
        delay = self._backoff_delay(sp.restarts)
        sp.restarts += 1
        sp.restart_at = self._now() + delay    # deadline, not a sleep:
        extra = ""                             # supervision stays live
        if sp.role == "worker" and sp.env.get("MX_PROCESS_ID") == "0":
            extra = " (rank 0: regenerating the coordinator on %s)" \
                % sp.env.get("MX_COORDINATOR", "?")
        self.log("%s failed (%s) - restart %d/%d in %.3gs with original "
                 "env%s" % (sp.name, self._describe(rc), sp.restarts,
                            self.max_restarts, delay, extra))
        return True

    def _check_hang(self, sp):
        """Heartbeat-file liveness: slow ranks keep the file fresh;
        a file stale past hang_timeout means wedged → kill (the exit
        then routes through the normal failure/restart path)."""
        if not (sp.heartbeat and self.hang_timeout) or not sp.alive():
            return
        try:
            age = time.time() - os.stat(sp.heartbeat).st_mtime
            limit, phase = self.hang_timeout, "--hang-timeout"
            try:
                with open(sp.heartbeat) as f:
                    if f.read().strip().endswith("done"):
                        return     # fit finished its beats: post-fit
                                   # work may be legitimately silent
            except OSError:
                pass
        except OSError:
            # no beat yet: startup.  Slow is allowed (import + compile);
            # wedged-before-the-first-batch is bounded by the grace
            if self.startup_grace is None or sp.spawned_wall is None:
                return
            age = time.time() - sp.spawned_wall
            limit, phase = self.startup_grace, "startup grace"
        if age > limit:
            self.log("%s heartbeat stale for %.3gs (> %s %.3g) - "
                     "killing the wedged process"
                     % (sp.name, age, phase, limit))
            sp.proc.kill()
            try:
                # bounded: a D-state child must not stall hang checks
                # for every OTHER rank; poll() reaps it later
                sp.proc.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                self.log("%s ignored SIGKILL (uninterruptible?); "
                         "leaving it to a later poll" % sp.name)

    # -- elastic resize (ISSUE 16) ------------------------------------------
    def _check_resize(self):
        """Poll the resize file for a target worker count; a target that
        differs from the last one honoured triggers a live resize."""
        if not (self.elastic and self.resize_file and self.worker_factory):
            return
        try:
            with open(self.resize_file) as f:
                txt = f.read().strip()
        except OSError:
            return
        if not txt:
            return
        try:
            n_new = int(txt)
        except ValueError:
            self.log("resize file %r holds %r (not an integer); ignored"
                     % (self.resize_file, txt))
            return
        if n_new <= 0 or n_new == self._resize_applied:
            return
        self._resize_applied = n_new
        self._do_resize(n_new)

    def _do_resize(self, n_new):
        """Voluntary elastic resize: quiesce every worker at its next
        epoch boundary (SIGTERM → the elastic fit drain handler saves a
        checkpoint and exits 0), LEAVE the removed ranks out of the PS
        membership, then respawn ranks 0..n_new-1 under the new world
        size with a bumped membership generation.  MX_ELASTIC_EPOCH
        carries the generation into every worker, where it salts the
        fusion-bucket CRC names — the resized world's exchange layout
        is replanned deterministically and can never collide with a
        pre-resize server accumulator."""
        old = [sp for sp in self.procs
               if sp.role == "worker" and not sp.done]
        self.generation += 1
        self.log("elastic resize: %d -> %d worker(s) (generation %d); "
                 "draining at the epoch boundary"
                 % (len(old), n_new, self.generation))
        for sp in old:
            if sp.alive():
                sp.proc.terminate()   # drain: checkpoint, then exit 0
        deadline = time.time() + self.drain_timeout
        for sp in old:
            if sp.proc is not None:
                try:
                    sp.proc.wait(timeout=max(0.1,
                                             deadline - time.time()))
                except subprocess.TimeoutExpired:
                    self.log("%s did not drain within %.3gs - killing "
                             "it (auto-resume picks up from its last "
                             "checkpoint)" % (sp.name, self.drain_timeout))
                    sp.we_killed = True
                    sp.proc.kill()
                    try:
                        sp.proc.wait(timeout=5.0)
                    except subprocess.TimeoutExpired:
                        pass
            sp.rc = 0                 # drained by request, not a failure
        # ranks above the new world size leave the membership NOW;
        # continuing/new ranks re-register themselves (JOIN is
        # idempotent) when they come up under the new generation
        for sp in old:
            try:
                rank = int(sp.env.get("MX_PROCESS_ID", -1))
            except (TypeError, ValueError):
                rank = -1
            if rank >= n_new:
                for addr in self.ps_addrs:
                    try:
                        _send_leave(addr, rank)
                    except OSError as e:
                        self.log("LEAVE r%d -> %s failed (%s); liveness "
                                 "eviction will catch up" % (rank, addr, e))
        self.procs = [sp for sp in self.procs if sp.role != "worker"]
        for rank in range(n_new):
            name, argv, env, heartbeat = self.worker_factory(
                rank, n_new, self.generation)
            sp = self.add(name, argv, env, role="worker",
                          heartbeat=heartbeat)
            self._spawn(sp)
        if self.fleet is not None:
            # the collector's member set is frozen at start(): rebuild
            # it over the new world (removed ranks drop out of
            # presence/straggler tracking with it)
            self._stop_collector()
            self.fleet = None
            self._start_collector()

    # -- serving autoscaler (ISSUE 17) --------------------------------------
    def _serve_procs(self, live_only=True):
        return [sp for sp in self.procs
                if sp.role == "serve" and not sp.done
                and not (live_only and sp.draining)]

    def _write_replicas_file(self):
        """Atomically rewrite the router's authoritative replica list:
        live, non-draining replicas only.  Dropping an addr here is the
        FIRST retirement step — the router stops admitting new sessions
        to it before the replica itself is asked to DRAIN."""
        if not self.replicas_file:
            return
        addrs = [sp.addr for sp in self._serve_procs() if sp.addr]
        tmp = "%s.tmp.%d" % (self.replicas_file, os.getpid())
        with open(tmp, "w") as f:
            f.write("".join(a + "\n" for a in addrs))
        os.replace(tmp, self.replicas_file)

    def _as_env(self, name, default):
        from mxnet_tpu.base import get_env as _get_env
        try:
            v = _get_env(name, default, float)
            return float(default if v is None else v)
        except (TypeError, ValueError):
            return float(default)

    def _check_autoscale(self):
        """One autoscale evaluation per fleet scrape round: SLO burn
        (observed/target, from the merged snapshot) must HOLD past the
        hysteresis band for MX_AUTOSCALE_HOLD consecutive rounds before
        an action fires, and every action arms an exponentially
        backed-off cooldown — a spike absorbs with a burst of spawns,
        but up/down flapping gets slower each flip."""
        if not (self.autoscale and self.serve_factory
                and self.fleet is not None):
            return
        snap = None
        try:
            snap = self.fleet.snapshot()
        except Exception:
            return
        if not snap:
            return
        round_id = snap.get("scrape")
        if round_id is None or round_id == self._as_last_round:
            return                      # same round: nothing new to read
        self._as_last_round = round_id
        burn = ((snap.get("slo") or {}).get("burn") or {})
        vals = [float(v) for v in burn.values()
                if isinstance(v, (int, float))]
        worst = max(vals, default=0.0)
        up_t = self._as_env("MX_AUTOSCALE_UP_BURN", 1.0)
        down_t = self._as_env("MX_AUTOSCALE_DOWN_BURN", 0.5)
        hold = max(1, int(self._as_env("MX_AUTOSCALE_HOLD", 3)))
        if worst >= up_t:
            self._as_up_hold += 1
            self._as_down_hold = 0
        elif worst <= down_t:
            self._as_down_hold += 1
            self._as_up_hold = 0
        else:
            # inside the hysteresis band: hold steady both ways
            self._as_up_hold = self._as_down_hold = 0
        if self._now() < self._as_cooldown_until:
            return
        mn, mx = self.autoscale
        n_live = len(self._serve_procs())
        if self._as_up_hold >= hold and n_live < mx:
            self._scale_up(worst, up_t, n_live)
        elif self._as_down_hold >= hold and n_live > mn:
            self._scale_down(worst, down_t, n_live)

    def _as_arm_cooldown(self, direction):
        fault = self._fault_mod()
        if self._as_last_dir == direction:
            self._as_streak += 1
        else:
            self._as_streak = 0
            self._as_last_dir = direction
        base = max(0.1, self._as_env("MX_AUTOSCALE_COOLDOWN", 10.0))
        if self._as_policy is None or self._as_policy.base != base:
            self._as_policy = fault.RetryPolicy(
                deadline=float("inf"), base=base, max_delay=8.0 * base,
                jitter=0.1)
        self._as_cooldown_until = self._now() + \
            self._as_policy.delay(min(self._as_streak, 3))
        self._as_up_hold = self._as_down_hold = 0

    def _scale_up(self, worst, up_t, n_live):
        idx = self._as_next_index
        self._as_next_index += 1
        name, argv, env, addr, heartbeat = self.serve_factory(idx)
        sp = self.add(name, argv, env, role="serve", addr=addr,
                      heartbeat=heartbeat)
        self._spawn(sp)
        self._write_replicas_file()
        self.log("autoscale: burn %.3g >= %.3g held - spawning %s at "
                 "%s (%d -> %d replicas)"
                 % (worst, up_t, name, addr, n_live, n_live + 1))
        if self.fleet is not None:
            try:
                from mxnet_tpu import fleet as _fleet
                m = _fleet.FleetMember("serve", idx, addr=addr)
                sp.fleet_key = m.key
                self.fleet.add_member(m)
            except Exception:
                pass
        self._as_arm_cooldown("up")

    def _scale_down(self, worst, down_t, n_live):
        victims = self._serve_procs()
        if not victims:
            return
        sp = victims[-1]                # newest replica retires first
        sp.draining = True
        self._write_replicas_file()     # router admission closes FIRST
        self.log("autoscale: burn %.3g <= %.3g held - retiring %s "
                 "drain-not-kill (%d -> %d replicas)"
                 % (worst, down_t, sp.name, n_live, n_live - 1))
        try:
            _send_drain(sp.addr)
        except OSError as e:
            # already dead or wedged: the DRAIN courtesy failed, fall
            # back to the supervisor's kill (clients failover-replay)
            self.log("%s: DRAIN failed (%s); killing it" % (sp.name, e))
            self._kill(sp)
        if self.fleet is not None:
            if sp.fleet_key:
                try:
                    self.fleet.retire(sp.fleet_key)
                except Exception:
                    pass
            try:
                # the spike this retirement answers is over: un-latch
                # the breach records so the NEXT breach is a fresh
                # signal, not a stale latch blocking/false-arming scale
                # decisions
                self.fleet.slo.reset()
            except Exception:
                pass
        self._as_arm_cooldown("down")

    def _teardown(self):
        for sp in self.procs:
            self._kill(sp)
            if sp.rc is None:
                sp.rc = 0 if sp.proc is None else (sp.proc.poll() or 0)

    # -- run ----------------------------------------------------------------
    def run(self):
        """Spawn everything, supervise until every worker is done, then
        stop the servers gracefully.  Returns the job return code."""
        for sp in self.procs:
            self._spawn(sp)
        if self.status_interval is not None or self.hang_timeout \
                or self.replicas_file or self.autoscale:
            # the fleet plane rides the same provisioning as the status
            # table / hang detection (heartbeat files, server addrs);
            # the serve router/autoscaler REQUIRE it (load signals)
            self._start_collector()
        try:
            while True:
                # elastic: the resize file can swap the whole worker set
                # out from under this loop, so the membership is read
                # fresh each tick rather than captured once up front
                self._check_resize()
                self._check_autoscale()
                for sp in list(self.procs):
                    if sp.done or sp.proc is None:
                        continue
                    if sp.restart_at is not None:
                        if self._now() >= sp.restart_at:
                            sp.restart_at = None
                            self._spawn(sp)
                        continue           # awaiting its backoff window
                    self._check_hang(sp)
                    rc = sp.proc.poll()
                    if rc is None:
                        continue
                    if rc == 0:
                        # a server exiting 0 early means a worker sent
                        # STOP (its own shutdown path) — that's done too
                        sp.rc = 0
                        continue
                    if not self._on_failure(sp, rc):
                        self._teardown()
                        return self.job_rc
                # serve replicas and the router count as workers for
                # job lifetime: the job ends when every non-server
                # process is done (serve: a STOP through the client or
                # router stops the whole tier)
                workers = [sp for sp in self.procs
                           if sp.role != "server"]
                if all(w.done for w in workers):
                    break
                self._maybe_status()
                self._sleep_poll()
        except BaseException:
            # ^C or any supervisor bug (e.g. a respawn Popen failing):
            # never exit leaving ranks/servers running unsupervised
            self._teardown()
            raise
        finally:
            self._stop_collector()
        self.stop_servers()
        return self.job_rc

    # -- graceful server shutdown ------------------------------------------
    def stop_servers(self, timeout=10.0):
        """Workers are done: drain each surviving parameter server with
        the wire-protocol STOP (ISSUE 1's graceful drain — in-flight
        requests finish, the snapshot lands) instead of SIGTERM, and
        fold server exit codes into the job's return code.  SIGTERM is
        the fallback for a server that won't take the hint; a kill WE
        sent is not treated as a server failure."""
        for sp in self.procs:
            if sp.role != "server" or sp.done:
                continue
            if sp.restart_at is not None and not sp.alive():
                # its crash was already forgiven by the restart policy
                # and the workers finished before the backoff window —
                # nothing left to restart, and folding the stale rc
                # would make the job's exit code a race
                sp.rc = 0
                continue
            stop_sent = False
            if sp.alive() and sp.addr:
                try:
                    _send_stop(sp.addr)
                    stop_sent = True
                except OSError as e:
                    self.log("%s: graceful STOP failed (%s); falling "
                             "back to terminate" % (sp.name, e))
            if not stop_sent:
                # no drain was requested — waiting for one is pointless
                self._kill(sp)
            try:
                sp.proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                self._kill(sp)
            rc = sp.proc.poll()
            sp.rc = rc if rc is not None else 0
            if not sp.we_killed:
                self._fold(sp.rc)


def _send_stop(addr, timeout=5.0):
    """Send the kvstore wire-protocol STOP (length-prefixed pickle; see
    mxnet_tpu/kvstore/server.py) and await the ack.  Inlined rather than
    imported so the launcher never has to load the framework."""
    host, _, port = addr.rpartition(":")
    with socket.create_connection((host or "127.0.0.1", int(port)),
                                  timeout=timeout) as s:
        s.settimeout(timeout)
        payload = pickle.dumps(("STOP", None), protocol=4)
        s.sendall(struct.pack("<Q", len(payload)) + payload)
        head = b""
        while len(head) < 8:                  # ack: (True, "stopping")
            chunk = s.recv(8 - len(head))
            if not chunk:
                return
            head += chunk
        (n,) = struct.unpack("<Q", head)
        body = b""
        while len(body) < n:
            chunk = s.recv(min(1 << 16, n - len(body)))
            if not chunk:
                return
            body += chunk


def _send_leave(addr, rank, timeout=5.0):
    """Send the kvstore wire-protocol LEAVE for rank ``rank`` (elastic
    membership, ISSUE 16) — the supervisor departs a dead or removed
    worker on its behalf so barriers re-quorum on the survivors
    immediately instead of waiting out liveness eviction.  Same inlined
    length-prefixed-pickle framing as _send_stop: the launcher never
    loads the framework for it.  LEAVE is idempotent server-side, so
    racing the worker's own voluntary leave() is harmless."""
    host, _, port = addr.rpartition(":")
    with socket.create_connection((host or "127.0.0.1", int(port)),
                                  timeout=timeout) as s:
        s.settimeout(timeout)
        payload = pickle.dumps(("LEAVE", "r%d" % int(rank)), protocol=4)
        s.sendall(struct.pack("<Q", len(payload)) + payload)
        head = b""
        while len(head) < 8:                  # ack: (True, (epoch, ...))
            chunk = s.recv(8 - len(head))
            if not chunk:
                return
            head += chunk
        (n,) = struct.unpack("<Q", head)
        body = b""
        while len(body) < n:
            chunk = s.recv(min(1 << 16, n - len(body)))
            if not chunk:
                return
            body += chunk


def _send_drain(addr, drain_timeout=None, timeout=5.0):
    """Send the serve wire-protocol DRAIN (drain-not-kill retirement,
    ISSUE 17) and await the status ack.  Same inlined length-prefixed-
    pickle framing as _send_stop — the launcher never loads the
    framework for it.  ``drain_timeout=None`` lets the replica's own
    MX_SERVE_DRAIN_TIMEOUT bound the retirement; DRAIN is idempotent
    (a retry keeps the replica's FIRST deadline)."""
    host, _, port = addr.rpartition(":")
    with socket.create_connection((host or "127.0.0.1", int(port)),
                                  timeout=timeout) as s:
        s.settimeout(timeout)
        msg = ("DRAIN",) if drain_timeout is None \
            else ("DRAIN", float(drain_timeout))
        payload = pickle.dumps(msg, protocol=4)
        s.sendall(struct.pack("<Q", len(payload)) + payload)
        head = b""
        while len(head) < 8:              # ack: (True, {status dict})
            chunk = s.recv(8 - len(head))
            if not chunk:
                return
            head += chunk
        (n,) = struct.unpack("<Q", head)
        body = b""
        while len(body) < n:
            chunk = s.recv(min(1 << 16, n - len(body)))
            if not chunk:
                return
            body += chunk


def _make_supervisor(args):
    restart = getattr(args, "restart", "never")
    max_restarts = getattr(args, "max_restarts", 3)
    if restart not in ("never", "on-failure"):
        try:
            max_restarts = int(restart)
        except ValueError:
            raise SystemExit("--restart must be never, on-failure, or an "
                             "integer budget (got %r)" % restart)
        if max_restarts < 0:
            raise SystemExit("--restart N needs N >= 0")
        restart = "on-failure"
    return Supervisor(restart=restart, max_restarts=max_restarts,
                      hang_timeout=getattr(args, "hang_timeout", None),
                      status_interval=getattr(args, "status_interval",
                                              None),
                      elastic=getattr(args, "elastic", False),
                      resize_file=getattr(args, "resize_file", None),
                      drain_timeout=getattr(args, "drain_timeout", None)
                      or 60.0)


# ---------------------------------------------------------------------------
# Launch modes
# ---------------------------------------------------------------------------

def launch_local(args, command):
    coordinator = "127.0.0.1:%d" % _free_port()
    sup = _make_supervisor(args)
    hb_dir = None
    if sup.hang_timeout or sup.status_interval:
        # status tables read the same per-rank heartbeat files hang
        # detection uses — either feature provisions them
        hb_dir = tempfile.mkdtemp(prefix="mx-heartbeat-")
    # warm respawn (ISSUE 13): one resolved cache dir frozen into EVERY
    # rank's env — workers and PS servers alike, and every RESTART of
    # them (the supervisor respawns with the original env) — so a
    # chaos-killed process deserializes its executables instead of
    # re-paying the cold-start compile bill
    compile_cache_dir = getattr(args, "compile_cache", None)
    if compile_cache_dir:
        compile_cache_dir = os.path.abspath(compile_cache_dir)
        os.makedirs(compile_cache_dir, exist_ok=True)
    ps_roots = []
    if getattr(args, "num_servers", 0) > 0:
        # dist_async parameter server(s) (reference: tracker starting
        # DMLC_ROLE=server processes); with -s N keys shard across the N
        # servers by hash (kvstore_dist.h key->server assignment role)
        snap_dir = getattr(args, "ps_snapshot_dir", None)
        if snap_dir:
            os.makedirs(snap_dir, exist_ok=True)
        for s in range(args.num_servers):
            port = _free_port()
            addr = "127.0.0.1:%d" % port
            ps_roots.append(addr)
            env = dict(os.environ)
            env.update({"DMLC_ROLE": "server",
                        "DMLC_NUM_WORKER": str(args.num_workers),
                        "MX_PS_PORT": str(port),
                        "MX_FORCE_CPU": "1", "JAX_PLATFORMS": "cpu",
                        "PYTHONPATH": REPO + os.pathsep +
                        env.get("PYTHONPATH", "")})
            if compile_cache_dir:
                env["MX_COMPILE_CACHE"] = compile_cache_dir
            if snap_dir:
                # durable PS: a restarted server (same snapshot path,
                # same port via the frozen env) resumes with no data
                # loss — the client side's reconnect-and-replay then
                # rides straight through
                env["MX_PS_SNAPSHOT"] = os.path.join(
                    snap_dir, "server_%d.pkl" % s)
            if getattr(args, "fault", None):
                env["MX_FAULT_INJECT"] = args.fault
            sup.add("server %d" % s,
                    [sys.executable, "-m", "mxnet_tpu.kvstore.server"],
                    env, role="server", addr=addr)
    elastic = bool(getattr(args, "elastic", False))

    def make_worker(rank, n, generation):
        """(name, argv, env, heartbeat) for one worker — used for the
        initial spawn AND stored as the supervisor's worker_factory so
        an elastic resize can respawn the world at any size."""
        env = _env_for(rank, coordinator, n)
        if compile_cache_dir:
            env["MX_COMPILE_CACHE"] = compile_cache_dir
        if getattr(args, "fault", None):
            # arm the chaos spec in every worker (mxnet_tpu.fault reads
            # MX_FAULT_INJECT at import) — a restarted rank re-arms the
            # same spec, keeping chaos runs deterministic per process
            env["MX_FAULT_INJECT"] = args.fault
        heartbeat = None
        if hb_dir:
            heartbeat = os.path.join(hb_dir, "rank_%d" % rank)
            env["MX_HEARTBEAT_FILE"] = heartbeat
        if ps_roots:
            env["MX_PS_ROOT"] = ps_roots[0]
            env["MX_PS_ROOTS"] = ",".join(ps_roots)
            env["DMLC_PS_ROOT_URI"] = ps_roots[0].split(":")[0]
            env["DMLC_PS_ROOT_PORT"] = ps_roots[0].split(":")[1]
            env["DMLC_NUM_SERVER"] = str(len(ps_roots))
        if elastic:
            # MX_ELASTIC: the dist store JOINs the membership at init
            # and fit arms the SIGTERM epoch-boundary drain.
            # MX_ELASTIC_EPOCH: supervisor-assigned membership
            # generation — salts the fusion-bucket names so each
            # incarnation's exchange layout is distinct and agreed
            # (every worker of a generation gets the SAME value; a
            # racily-observed server epoch could disagree mid-join)
            env["MX_ELASTIC"] = "1"
            env["MX_ELASTIC_EPOCH"] = str(int(generation))
        return "rank %d" % rank, list(command), env, heartbeat

    # serving fleet tier (ISSUE 17): replicas get wire addrs on the
    # fleet plane; --route adds the session router; --autoscale arms
    # the SLO-burn resize loop
    serve_base = getattr(args, "serve_port_base", None)
    route_port = getattr(args, "route", None)
    autoscale = getattr(args, "autoscale", None)
    if (route_port is not None or autoscale) and serve_base is None:
        raise SystemExit("launch.py: --route/--autoscale need "
                         "--serve-port-base B (the replicas' "
                         "--port-base, so the supervisor knows their "
                         "addrs)")

    def make_replica(index):
        """serve_factory face of make_worker: (name, argv, env, addr,
        heartbeat) for replica ``index`` at serve-port-base + index —
        used for the initial spawn AND every autoscaler scale-up."""
        name, argv, env, heartbeat = make_worker(index,
                                                 args.num_workers, 0)
        return (name, argv, env,
                "127.0.0.1:%d" % (serve_base + index), heartbeat)

    rt_dir = None
    for rank in range(args.num_workers):
        if serve_base is not None:
            name, argv, env, addr, heartbeat = make_replica(rank)
            sup.add(name, argv, env, role="serve", addr=addr,
                    heartbeat=heartbeat)
        else:
            name, argv, env, heartbeat = make_worker(
                rank, args.num_workers, 0)
            sup.add(name, argv, env, role="worker", heartbeat=heartbeat)
    if route_port is not None:
        rt_dir = tempfile.mkdtemp(prefix="mx-router-")
        sup.replicas_file = os.path.join(rt_dir, "replicas.txt")
        sup.fleet_port = _free_port()
        sup._write_replicas_file()
        env = dict(os.environ)
        env.update({"MX_FORCE_CPU": "1", "JAX_PLATFORMS": "cpu",
                    "PYTHONPATH": REPO + os.pathsep +
                    env.get("PYTHONPATH", "")})
        if getattr(args, "fault", None):
            # the router has its own chaos sites (router.request /
            # router.forward) — arm the same spec everywhere
            env["MX_FAULT_INJECT"] = args.fault
        heartbeat = None
        if hb_dir:
            heartbeat = os.path.join(hb_dir, "router")
            env["MX_HEARTBEAT_FILE"] = heartbeat
        sup.add("router",
                [sys.executable, "-m", "mxnet_tpu.serve.router",
                 "--port", str(route_port),
                 "--replicas-file", sup.replicas_file,
                 "--fleet", "127.0.0.1:%d" % sup.fleet_port],
                env, role="router",
                addr="127.0.0.1:%d" % route_port, heartbeat=heartbeat)
    if autoscale:
        try:
            mn, mx = (int(x) for x in str(autoscale).split(":", 1))
        except ValueError:
            raise SystemExit("launch.py: --autoscale wants MIN:MAX "
                             "(got %r)" % autoscale)
        if not (1 <= mn <= mx):
            raise SystemExit("launch.py: --autoscale needs "
                             "1 <= MIN <= MAX")
        sup.autoscale = (mn, mx)
        sup.serve_factory = make_replica
        sup._as_next_index = args.num_workers
    sup.ps_addrs = list(ps_roots)
    if elastic:
        sup.worker_factory = make_worker
        sup._resize_applied = args.num_workers
    try:
        return sup.run()
    finally:
        if hb_dir:
            shutil.rmtree(hb_dir, ignore_errors=True)
        if rt_dir:
            shutil.rmtree(rt_dir, ignore_errors=True)


def launch_ssh(args, command):
    if getattr(args, "hang_timeout", None):
        raise SystemExit(
            "launch.py: --hang-timeout reads a LOCAL heartbeat file and "
            "cannot observe remote ranks; it is only supported with "
            "--launcher local (use MX_STEP_TIMEOUT for in-process "
            "hang detection on remote ranks)")
    if getattr(args, "restart", "never") != "never":
        # an ssh CLIENT exiting nonzero does not mean the REMOTE rank
        # died (a transport blip orphans it alive); respawning would
        # start a duplicate rank k against the same PS/checkpoints, and
        # teardown could only kill the local clients.  Restart
        # supervision therefore stays a local-launcher feature.
        raise SystemExit(
            "launch.py: --restart is only supported with --launcher "
            "local (an ssh client's exit cannot be distinguished from "
            "the remote rank's death; restarting on it risks duplicate "
            "ranks)")
    if getattr(args, "elastic", False) or getattr(args, "resize_file",
                                                  None):
        # same reasoning as --restart: elastic respawn/drain needs
        # authoritative process lifecycle, which ssh clients cannot give
        raise SystemExit(
            "launch.py: --elastic/--resize-file are only supported "
            "with --launcher local")
    if getattr(args, "num_servers", 0) > 0:
        raise SystemExit(
            "launch.py: -s/--num-servers is only implemented for the "
            "local launcher; start `python -m mxnet_tpu.kvstore.server` "
            "on a host manually and export MX_PS_ROOT=host:port")
    if getattr(args, "route", None) is not None or \
            getattr(args, "serve_port_base", None) is not None or \
            getattr(args, "autoscale", None):
        # the serve tier needs authoritative local process lifecycle
        # (replicas file, DRAIN-then-reap, fleet wire scrapes) — same
        # reasoning as --restart/--elastic
        raise SystemExit(
            "launch.py: --route/--serve-port-base/--autoscale are only "
            "supported with --launcher local")
    hosts = []
    with open(args.hostfile) as f:
        for line in f:
            line = line.strip()
            if line and not line.startswith("#"):
                hosts.append(line.split()[0])
    if len(hosts) < args.num_workers:
        raise SystemExit("hostfile has %d hosts < -n %d"
                         % (len(hosts), args.num_workers))
    coordinator = "%s:%d" % (hosts[0], 43117)
    sup = _make_supervisor(args)   # restart=never (guarded above): the
                                   # supervisor just waits + folds rcs
    for rank in range(args.num_workers):
        env = _env_for(rank, coordinator, args.num_workers)
        exports = " ".join("%s=%s" % (k, shlex.quote(v))
                           for k, v in env.items()
                           if k.startswith(("MX_", "DMLC_", "JAX_")))
        remote = "cd %s && env %s %s" % (
            shlex.quote(os.getcwd()), exports,
            " ".join(shlex.quote(c) for c in command))
        sup.add("rank %d (%s)" % (rank, hosts[rank]),
                ["ssh", "-o", "StrictHostKeyChecking=no", hosts[rank],
                 remote],
                dict(os.environ), role="worker")
    return sup.run()


def launch_manual(args, command):
    coordinator = "<host0>:43117"
    for rank in range(args.num_workers):
        # exactly the contract _env_for gives spawned workers — MX_*
        # plus the DMLC_* compat names, so a manually-started process
        # behaves identically to a launched one
        env = _compat_env(rank, coordinator, args.num_workers)
        exports = " ".join("%s=%s" % kv for kv in env.items())
        print("rank %d:  env %s %s" % (rank, exports, " ".join(command)))
    return 0


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("-n", "--num-workers", type=int, required=True)
    p.add_argument("-s", "--num-servers", type=int, default=0)
    p.add_argument("--launcher", default="local",
                   choices=["local", "ssh", "manual"])
    p.add_argument("-H", "--hostfile", default=None)
    p.add_argument("--restart", default="never", metavar="POLICY",
                   help="never (default) | on-failure | N (shorthand for "
                        "on-failure with --max-restarts N).  on-failure "
                        "restarts a crashed rank/server with its original "
                        "env (RetryPolicy backoff) so checkpoint "
                        "auto-resume and MX_PS_SNAPSHOT pick up from the "
                        "last step; past the budget the whole job is "
                        "torn down nonzero.  Local launcher only")
    p.add_argument("--max-restarts", type=int, default=3, metavar="N",
                   help="per-process restart budget under --restart "
                        "on-failure (default 3)")
    p.add_argument("--hang-timeout", type=float, default=None,
                   metavar="SECS",
                   help="supervisor-side wedge detection: each rank gets "
                        "MX_HEARTBEAT_FILE (touched every batch by the "
                        "fit loop); a rank whose file goes stale this "
                        "many seconds is killed and handled like a "
                        "crash.  Set it well above your slowest "
                        "batch+eval gap — slow is fine, wedged is not.  "
                        "Before a rank's first beat a startup grace of "
                        "max(120s, 20x this) applies (import + compile)")
    p.add_argument("--status-interval", type=float, default=None,
                   metavar="SECS",
                   help="print a live fleet status table every SECS "
                        "seconds (and on every failure): per-rank step, "
                        "throughput and last-exchange bytes read from "
                        "the heartbeat files' telemetry JSON payload "
                        "(implies per-rank heartbeat files, like "
                        "--hang-timeout).  Unset = no tables")
    p.add_argument("--elastic", action="store_true",
                   help="elastic membership (preemption tolerance): "
                        "workers JOIN the parameter-server membership "
                        "at startup (MX_ELASTIC=1); a rank that "
                        "exhausts its restart budget is LEAVEd out and "
                        "the job continues on the survivors "
                        "(shrink-and-continue) instead of tearing "
                        "down.  Local launcher only")
    p.add_argument("--resize-file", default=None, metavar="PATH",
                   help="poll PATH for a target worker count (an "
                        "integer); when it changes the supervisor "
                        "drains every rank at its next epoch boundary "
                        "(SIGTERM -> checkpoint -> exit 0), LEAVEs "
                        "removed ranks from the PS membership, and "
                        "respawns the new world with a bumped "
                        "MX_ELASTIC_EPOCH (bucket-layout salt).  "
                        "Requires --elastic")
    p.add_argument("--drain-timeout", type=float, default=None,
                   metavar="SECS",
                   help="how long a resize waits for workers to reach "
                        "their epoch-boundary drain before killing "
                        "them (default 60; auto-resume then picks up "
                        "from the last checkpoint)")
    p.add_argument("--serve-port-base", type=int, default=None,
                   metavar="PORT",
                   help="the command is a serving replica bound at "
                        "PORT + rank (its own --port-base): each "
                        "replica is registered on the fleet plane as a "
                        "wire-scraped 'serve' member whose merged "
                        "signals (queue depth, decode occupancy, KV "
                        "headroom) feed the router and autoscaler.  "
                        "Local launcher only")
    p.add_argument("--route", type=int, default=None, metavar="PORT",
                   help="front the replicas with the session router "
                        "(python -m mxnet_tpu.serve.router) on PORT: "
                        "clients speak to ONE addr, sessions pin to "
                        "replicas, retirement is drain-not-kill.  The "
                        "supervisor owns the router's replicas file "
                        "and an embedded fleet collector wire port "
                        "for its load signals.  Needs "
                        "--serve-port-base")
    p.add_argument("--autoscale", default=None, metavar="MIN:MAX",
                   help="SLO-burn autoscaler over the serve tier: "
                        "spawn a warm replica when any fleet SLO burn "
                        "(MX_FLEET_SLO_* targets) holds >= "
                        "MX_AUTOSCALE_UP_BURN, retire-and-DRAIN the "
                        "newest when every burn holds <= "
                        "MX_AUTOSCALE_DOWN_BURN; hysteresis hold + "
                        "exponentially backed-off cooldowns stop "
                        "flapping.  Needs --serve-port-base (and "
                        "usually --route)")
    p.add_argument("--fault", default=None, metavar="SPEC",
                   help="arm fault injection in every spawned process "
                        "(MX_FAULT_INJECT spec, e.g. "
                        "'worker.step:crash:after=5' or "
                        "'kvstore.send:close:after=3'); chaos testing "
                        "only")
    p.add_argument("--compile-cache", default=None, metavar="DIR",
                   help="persistent compiled-program cache directory "
                        "(sets MX_COMPILE_CACHE in every rank): a "
                        "respawned/restarted rank deserializes its XLA "
                        "executables from here instead of recompiling "
                        "them — warm restart compiles ~0 programs")
    p.add_argument("--ps-snapshot-dir", default=None, metavar="DIR",
                   help="persist each parameter server's store under "
                        "DIR (atomic pickles) so a restarted server "
                        "loses no data")
    p.add_argument("command", nargs=argparse.REMAINDER)
    args = p.parse_args()
    command = args.command
    if command and command[0] == "--":   # strip only the leading separator
        command = command[1:]
    if not command:
        raise SystemExit("no command given")
    if args.resize_file and not args.elastic:
        raise SystemExit("--resize-file requires --elastic")
    if args.launcher == "local":
        sys.exit(launch_local(args, command))
    elif args.launcher == "ssh":
        if not args.hostfile:
            raise SystemExit("--launcher ssh needs -H hostfile")
        sys.exit(launch_ssh(args, command))
    else:
        sys.exit(launch_manual(args, command))


if __name__ == "__main__":
    main()
