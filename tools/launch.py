#!/usr/bin/env python
"""launch.py — start a multi-process / multi-host training job.

Reference: ``tools/launch.py`` + ``3rdparty/ps-lite/tracker``
(dmlc_tracker.local/ssh — spawn workers+servers with DMLC_* envs).

TPU-native contract: there are no parameter servers — every process is a
jax.distributed worker; ``mxnet_tpu.parallel.init_process_group()``
(called by the training script, or implicitly via MX_DIST_AUTO_INIT) reads
the env this launcher sets:

  MX_COORDINATOR    host:port of process 0
  MX_NUM_PROCESSES  world size
  MX_PROCESS_ID     this process's rank

Modes:
  -n N --launcher local  : N processes on this host (separate CPU backends;
                           for pipeline/io testing — real multi-chip needs
                           one process per host)
  -n N --launcher ssh -H hostfile : one process per hostfile line via ssh
  --launcher manual      : print the per-rank environment + command

Example:
  python tools/launch.py -n 2 --launcher local -- python train.py --kv dist
"""
import argparse
import os
import shlex
import socket
import subprocess
import sys


def _free_port() -> int:
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _env_for(rank: int, coordinator: str, n: int):
    env = dict(os.environ)
    env.update({
        "MX_COORDINATOR": coordinator,
        "MX_NUM_PROCESSES": str(n),
        "MX_PROCESS_ID": str(rank),
        # reference-era names, for scripts that read DMLC_*:
        "DMLC_NUM_WORKER": str(n),
        "DMLC_WORKER_ID": str(rank),
        "DMLC_ROLE": "worker",
    })
    return env


def launch_local(args, command):
    coordinator = "127.0.0.1:%d" % _free_port()
    server_procs = []
    ps_roots = []
    if getattr(args, "num_servers", 0) > 0:
        # dist_async parameter server(s) (reference: tracker starting
        # DMLC_ROLE=server processes); with -s N keys shard across the N
        # servers by hash (kvstore_dist.h key->server assignment role)
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        snap_dir = getattr(args, "ps_snapshot_dir", None)
        if snap_dir:
            os.makedirs(snap_dir, exist_ok=True)
        for s in range(args.num_servers):
            port = _free_port()
            ps_roots.append("127.0.0.1:%d" % port)
            env = dict(os.environ)
            env.update({"DMLC_ROLE": "server",
                        "DMLC_NUM_WORKER": str(args.num_workers),
                        "MX_PS_PORT": str(port),
                        "MX_FORCE_CPU": "1", "JAX_PLATFORMS": "cpu",
                        "PYTHONPATH": repo + os.pathsep +
                        env.get("PYTHONPATH", "")})
            if snap_dir:
                # durable PS: a restarted server (same snapshot path)
                # resumes with no data loss — the client side's
                # reconnect-and-replay then rides straight through
                env["MX_PS_SNAPSHOT"] = os.path.join(
                    snap_dir, "server_%d.pkl" % s)
            if getattr(args, "fault", None):
                env["MX_FAULT_INJECT"] = args.fault
            server_procs.append(subprocess.Popen(
                [sys.executable, "-m", "mxnet_tpu.kvstore.server"],
                env=env))
    procs = []
    for rank in range(args.num_workers):
        env = _env_for(rank, coordinator, args.num_workers)
        if getattr(args, "fault", None):
            # arm the chaos spec in every worker (mxnet_tpu.fault reads
            # MX_FAULT_INJECT at import)
            env["MX_FAULT_INJECT"] = args.fault
        if ps_roots:
            env["MX_PS_ROOT"] = ps_roots[0]
            env["MX_PS_ROOTS"] = ",".join(ps_roots)
            env["DMLC_PS_ROOT_URI"] = ps_roots[0].split(":")[0]
            env["DMLC_PS_ROOT_PORT"] = ps_roots[0].split(":")[1]
            env["DMLC_NUM_SERVER"] = str(len(ps_roots))
        procs.append(subprocess.Popen(command, env=env))
    rc = 0
    for p in procs:
        rc = p.wait() or rc
    for p in server_procs:       # workers done: stop the PS
        p.terminate()
        p.wait()
    return rc


def launch_ssh(args, command):
    if getattr(args, "num_servers", 0) > 0:
        raise SystemExit(
            "launch.py: -s/--num-servers is only implemented for the "
            "local launcher; start `python -m mxnet_tpu.kvstore.server` "
            "on a host manually and export MX_PS_ROOT=host:port")
    hosts = []
    with open(args.hostfile) as f:
        for line in f:
            line = line.strip()
            if line and not line.startswith("#"):
                hosts.append(line.split()[0])
    if len(hosts) < args.num_workers:
        raise SystemExit("hostfile has %d hosts < -n %d"
                         % (len(hosts), args.num_workers))
    coordinator = "%s:%d" % (hosts[0], 43117)
    procs = []
    for rank in range(args.num_workers):
        env = _env_for(rank, coordinator, args.num_workers)
        exports = " ".join("%s=%s" % (k, shlex.quote(v))
                           for k, v in env.items()
                           if k.startswith(("MX_", "DMLC_", "JAX_")))
        remote = "cd %s && env %s %s" % (
            shlex.quote(os.getcwd()), exports,
            " ".join(shlex.quote(c) for c in command))
        procs.append(subprocess.Popen(["ssh", "-o",
                                       "StrictHostKeyChecking=no",
                                       hosts[rank], remote]))
    rc = 0
    for p in procs:
        rc = p.wait() or rc
    return rc


def launch_manual(args, command):
    coordinator = "<host0>:43117"
    for rank in range(args.num_workers):
        env = {"MX_COORDINATOR": coordinator,
               "MX_NUM_PROCESSES": args.num_workers,
               "MX_PROCESS_ID": rank}
        exports = " ".join("%s=%s" % kv for kv in env.items())
        print("rank %d:  env %s %s" % (rank, exports, " ".join(command)))
    return 0


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("-n", "--num-workers", type=int, required=True)
    p.add_argument("-s", "--num-servers", type=int, default=0)
    p.add_argument("--launcher", default="local",
                   choices=["local", "ssh", "manual"])
    p.add_argument("-H", "--hostfile", default=None)
    p.add_argument("--fault", default=None, metavar="SPEC",
                   help="arm fault injection in every spawned process "
                        "(MX_FAULT_INJECT spec, e.g. "
                        "'kvstore.send:close:after=3'); chaos testing "
                        "only")
    p.add_argument("--ps-snapshot-dir", default=None, metavar="DIR",
                   help="persist each parameter server's store under "
                        "DIR (atomic pickles) so a restarted server "
                        "loses no data")
    p.add_argument("command", nargs=argparse.REMAINDER)
    args = p.parse_args()
    command = args.command
    if command and command[0] == "--":   # strip only the leading separator
        command = command[1:]
    if not command:
        raise SystemExit("no command given")
    if args.launcher == "local":
        sys.exit(launch_local(args, command))
    elif args.launcher == "ssh":
        if not args.hostfile:
            raise SystemExit("--launcher ssh needs -H hostfile")
        sys.exit(launch_ssh(args, command))
    else:
        sys.exit(launch_manual(args, command))


if __name__ == "__main__":
    main()
