"""Standing TPU-bench capture loop.

Role: the reference measures its headline numbers with always-available
GPUs (`example/image-classification/benchmark_score.py`); here the one
real TPU chip sits behind a tunnel that can be wedged for hours and heal
mid-round.  A one-shot probe at bench time therefore misses healthy
windows.  This loop runs in the background for the whole round:

  1. re-probes the accelerator on a fixed cadence (subprocess + hard
     timeout, same hangs-don't-flake machinery as base.probe_accelerator),
     appending every attempt to TPU_CAPTURE.log;
  2. on the first healthy window, runs the full capture suite —
     ResNet-50 train bench (bench.py), a flash-attention fwd+bwd
     microbench, and a real-Mosaic (interpret=False) Pallas kernel
     smoke — and persists the JSON results to TPU_CAPTURE.json;
  3. bench.py consults TPU_CAPTURE.json when its own live probe fails,
     so the driver's end-of-round run reports the captured TPU number
     instead of the CPU fallback.

Run:  nohup python tools/tpu_capture.py > /dev/null 2>&1 &
"""
import importlib.util
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Load base.py standalone (NOT via the mxnet_tpu package __init__, which
# imports jax — the parent loop must stay jax-free or a wedged axon tunnel
# can hang the loop itself).  base.py only imports os/threading/typing.
_spec = importlib.util.spec_from_file_location(
    "_mx_base_standalone", os.path.join(REPO, "mxnet_tpu", "base.py"))
_mx_base = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_mx_base)
LOG = os.path.join(REPO, "TPU_CAPTURE.log")
OUT = os.path.join(REPO, "TPU_CAPTURE.json")
PROBE_TIMEOUT_S = 120
# Round-4 post-mortem: a single healthy window was burned by 1800s child
# timeouts on a tunnel that wedged mid-suite.  Children now get a 300s
# budget — the two exceptions (block sweep 1500s, pytest lane 1800s) are
# ordered LAST — and the tunnel is re-probed before EVERY child so a
# mid-suite wedge aborts the pass instead of serially timing out.
CHILD_TIMEOUT_S = 300
SWEEP_TIMEOUT_S = 1500          # 5 x (60s probe + 180s config) + startup
PYTEST_TIMEOUT_S = 1800         # the longest child; always ordered last
PROBE_INTERVAL_S = 300          # 5 min cadence: ~144 probes over a 12h round
MAX_HOURS = 13


def _ts():
    return time.strftime("%Y-%m-%dT%H:%M:%S%z")


def _current_round():
    """Round number from the driver's PROGRESS.jsonl (last line), or None
    when unavailable — the primary same-round identity for captures."""
    try:
        with open(os.path.join(REPO, "PROGRESS.jsonl")) as f:
            lines = [l for l in f.read().splitlines() if l.strip()]
        return json.loads(lines[-1]).get("round")
    except Exception:
        return None


def _log(msg):
    line = "%s %s" % (_ts(), msg)
    with open(LOG, "a") as f:
        f.write(line + "\n")


def _probe():
    """One un-memoized subprocess probe (shared helper in base.py)."""
    return _mx_base.probe_accelerator_once(PROBE_TIMEOUT_S)


def _run_json_child(argv, tag, timeout=None):
    """Run a child that prints one JSON line; return the parsed dict or None."""
    timeout = timeout or CHILD_TIMEOUT_S
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("MX_FORCE_CPU", None)
    # The bench.py child must MEASURE, not replay a prior capture — otherwise
    # a stale result could be re-stamped with a fresh captured_at forever.
    env["MX_NO_CAPTURE_FALLBACK"] = "1"
    # ...and must not re-probe the tunnel we just probed (150s of a 300s
    # budget) — bench.py honors this by skipping its own probe
    env["MX_ASSUME_LIVE"] = "1"
    try:
        r = subprocess.run(argv, env=env, timeout=timeout, cwd=REPO,
                           stdout=subprocess.PIPE, stderr=subprocess.PIPE)
    except subprocess.TimeoutExpired:
        _log("%s: TIMEOUT after %ss" % (tag, timeout))
        return None
    lines = [l for l in r.stdout.decode(errors="replace").splitlines()
             if l.startswith("{")]
    if r.returncode != 0 or not lines:
        _log("%s: rc=%s no-json; stderr tail: %s"
             % (tag, r.returncode, r.stderr.decode(errors="replace")[-1500:]))
        return None
    try:
        return json.loads(lines[-1])
    except ValueError:
        _log("%s: unparseable json: %r" % (tag, lines[-1][:200]))
        return None


def flash_block_sweep():
    """Child mode: sweep MX_FLASH_BLOCK_Q/K candidates on the live chip and
    report TFLOP/s per config — the block-size tuning that interpret-mode
    CPU runs cannot do (VMEM limits/Mosaic tiling only exist on hardware).
    Each config runs in a SUBPROCESS because the env is read at import."""
    import subprocess
    results = {}
    for bq, bk in ((128, 128), (128, 256), (256, 256), (256, 512),
                   (512, 512)):
        # the tunnel can wedge mid-sweep: re-probe before each config so a
        # dead backend costs one 60s probe, not 5 serial config timeouts
        if not _mx_base.probe_accelerator_once(60):
            results["%dx%d" % (bq, bk)] = {"err": "tunnel wedged, skipped"}
            break  # dead backend: stop probing, report what we have
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        env.pop("MX_FORCE_CPU", None)
        env["MX_FLASH_BLOCK_Q"] = str(bq)
        env["MX_FLASH_BLOCK_K"] = str(bk)
        try:
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--child-flash"],
                env=env, timeout=180, cwd=REPO,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE)
            lines = [l for l in r.stdout.decode(errors="replace")
                     .splitlines() if l.startswith("{")]
            results["%dx%d" % (bq, bk)] = json.loads(lines[-1]) if lines                 else {"rc": r.returncode,
                      "err": r.stderr.decode(errors="replace")[-400:]}
        except subprocess.TimeoutExpired:
            results["%dx%d" % (bq, bk)] = {"err": "timeout"}
    print(json.dumps({"metric": "flash_block_sweep", "configs": results,
                      "value": 0.0, "unit": "sweep"}))


def flash_microbench():
    """Child mode: flash-attention fwd+bwd throughput on the live backend."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    sys.path.insert(0, REPO)
    from mxnet_tpu.ops.attention import flash_attention

    B, H, L, D = 4, 12, 2048, 64
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, H, L, D), jnp.bfloat16)
    k = jnp.asarray(rng.randn(B, H, L, D), jnp.bfloat16)
    v = jnp.asarray(rng.randn(B, H, L, D), jnp.bfloat16)

    def loss(q, k, v):
        out = flash_attention(q, k, v, 1.0 / np.sqrt(D), False)
        return jnp.sum(out.astype(jnp.float32))

    step = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
    out = step(q, k, v)
    jax.block_until_ready(out)
    iters = 20
    t0 = time.perf_counter()
    for _ in range(iters):
        out = step(q, k, v)
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0
    # fwd 2*2*B*H*L^2*D FLOPs (QK^T + PV), bwd ~2.5x fwd
    flops = 3.5 * 2 * 2 * B * H * L * L * D
    print(json.dumps({
        "metric": "flash_attention_fwd_bwd_tflops",
        "value": round(flops * iters / dt / 1e12, 2), "unit": "TFLOP/s",
        "device": jax.default_backend(),
        "shape": [B, H, L, D],
        "ms_per_step": round(dt / iters * 1e3, 2),
    }))


def mosaic_smoke():
    """Child mode: execute a Pallas kernel with interpret=False (real Mosaic
    lowering) and check numerics vs jnp — proves block specs + VMEM budgets
    on hardware, which interpret-mode tests cannot.  Covers forward AND the
    custom-vjp backward (the bwd kernel has its own block specs)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    sys.path.insert(0, REPO)
    from mxnet_tpu.ops.attention import flash_attention

    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(2, 4, 512, 64), jnp.float32)
    k = jnp.asarray(rng.randn(2, 4, 512, 64), jnp.float32)
    v = jnp.asarray(rng.randn(2, 4, 512, 64), jnp.float32)
    out = jax.jit(lambda q, k, v: flash_attention(q, k, v, 1.0 / np.sqrt(64),
                                                  False))(q, k, v)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(64)
    ref = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, axis=-1), v)
    err = float(jnp.max(jnp.abs(out.astype(jnp.float32) - ref)))
    assert err < 2e-2, err

    # Backward through the Pallas custom_vjp vs jnp autodiff of the ref.
    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, 1.0 / np.sqrt(64), False)
                       * jnp.cos(jnp.arange(64, dtype=jnp.float32)))

    def loss_ref(q, k, v):
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(64)
        o = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, axis=-1), v)
        return jnp.sum(o * jnp.cos(jnp.arange(64, dtype=jnp.float32)))

    g_flash = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
    bwd_err = max(float(jnp.max(jnp.abs(a - b)))
                  for a, b in zip(g_flash, g_ref))
    assert bwd_err < 5e-2, bwd_err
    print(json.dumps({
        "metric": "pallas_mosaic_flash_max_abs_err", "value": round(err, 6),
        "unit": "abs", "device": jax.default_backend(), "ok": True,
        "bwd_max_abs_err": round(bwd_err, 6),
    }))


def _run_tpu_test_lane():
    """Run the MX_TEST_CTX=tpu pytest lane (op battery + gluon) on the live
    chip; returns a summary dict parsed from pytest's last line."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("MX_FORCE_CPU", None)
    env["MX_TEST_CTX"] = "tpu"
    argv = [sys.executable, "-m", "pytest", "-q", "--no-header", "-p",
            "no:cacheprovider", "tests/test_operator.py",
            "tests/test_gluon.py", "tests/test_transformer.py",
            "tests/test_torch_parity.py"]
    try:
        r = subprocess.run(argv, env=env, timeout=PYTEST_TIMEOUT_S, cwd=REPO,
                           stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    except subprocess.TimeoutExpired:
        _log("tpu_test_lane: TIMEOUT after %ss" % PYTEST_TIMEOUT_S)
        return None
    tail = r.stdout.decode(errors="replace").strip().splitlines()
    # pytest's "N passed in Xs" line may be followed by TPU-runtime
    # shutdown chatter: take the last line that looks like a summary
    summary = ""
    for line in reversed(tail):
        if " passed" in line or " failed" in line or " error" in line \
                or " skipped" in line:
            summary = line
            break
    if not summary and tail:
        summary = tail[-1]
    _log("tpu_test_lane: rc=%s %s" % (r.returncode, summary[:200]))
    return {"rc": r.returncode, "summary": summary[:500]}


# The capture suite: tag -> (child argv, timeout).  None argv = the pytest
# lane, which has its own runner.  bench.py --real-data synthesizes its own
# .rec pack, so no data drop is needed.  ONE table drives capture(), the
# missing-list, the ok-counter, and the completion check.
#
# ORDER = information-per-second, highest first (round-4 lesson: the one
# healthy window died before the highest-value child even started):
#   1. mosaic_smoke      — "does the Pallas flash kernel lower through
#                          Mosaic at all?"  The single most valuable bit;
#                          nothing else answers it.  ~2 compiles, <300s.
#   2. flash_microbench  — kernel TFLOP/s, the headline Pallas number.
#   3. resnet50_bench    — the BASELINE headline img/s.
#   4. bert_bench / score_bench — the other BASELINE configs.
#   5. flash_block_sweep — tuning, only meaningful after 1-2 land.
#   6. tpu_test_lane     — breadth; the longest child.
# (real_data_bench is host-side ingest — it needs NO chip, so it is a
# committed round artifact produced on CPU, not a capture child.)
TAGS = (
    ("mosaic_smoke", [os.path.abspath(__file__), "--child-mosaic"],
     CHILD_TIMEOUT_S),
    ("flash_microbench", [os.path.abspath(__file__), "--child-flash"],
     CHILD_TIMEOUT_S),
    ("resnet50_bench", [os.path.join(REPO, "bench.py")], CHILD_TIMEOUT_S),
    ("bert_bench", [os.path.join(REPO, "bench.py"), "--bert"],
     CHILD_TIMEOUT_S),
    ("score_bench", [os.path.join(REPO, "bench.py"), "--score"],
     CHILD_TIMEOUT_S),
    ("flash_block_sweep", [os.path.abspath(__file__), "--child-sweep"],
     SWEEP_TIMEOUT_S),
    ("tpu_test_lane", None, PYTEST_TIMEOUT_S),
)
TAG_NAMES = tuple(t[0] for t in TAGS)
MAX_ATTEMPTS = 3   # a deterministically-failing child must not hog the
                   # chip all round: give up after this many tries


def _ok(res):
    """A child result counts as captured only with POSITIVE evidence of an
    accelerator run: a real device field (or, for the sweep, at least one
    config that ran on one; for the test lane, rc==0).  Error payloads,
    device-less records and bench.py's value-0 last-resort record all
    count as failures so the resume loop retries them."""
    if not isinstance(res, dict):
        return False
    if "rc" in res and "metric" not in res:
        # all-skipped pytest lane (chip unavailable at collection) is NOT
        # a capture: require at least one test to have actually passed
        return (int(res.get("rc", 1)) == 0
                and " passed" in str(res.get("summary", "")))
    if "error" in res:
        return False
    if "configs" in res:
        return any(_ok(c) for c in res["configs"].values()
                   if isinstance(c, dict))
    dev = res.get("device")
    return dev is not None and dev != "cpu"


def _persist(results, probes):
    """Write TPU_CAPTURE.json atomically.  Called the moment any child
    lands (round-4 lesson: a wedge later in the pass must never cost
    artifacts already captured)."""
    import glob
    payload = {"captured_at": _ts(), "probes": probes,
               "round": _current_round(),
               # secondary round identity: the driver writes BENCH_r{N}.json
               # at each round's END, so any BENCH file appearing after this
               # capture marks it stale when PROGRESS.jsonl is unavailable
               "bench_files_at_capture": sorted(
                   os.path.basename(p) for p in
                   glob.glob(os.path.join(REPO, "BENCH_r*.json"))),
               "results": results}
    tmp = OUT + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=1)
    os.replace(tmp, OUT)  # atomic: bench.py may read concurrently


def capture(prev=None, attempts=None, probes=0, already_probed=False):
    """Run the capture suite; with `prev`, only re-run children whose
    earlier attempt failed (tunnel wedged mid-suite) and merge.
    `attempts` (tag -> count) is updated in place; tags over MAX_ATTEMPTS
    are skipped for good.

    The tunnel is RE-PROBED before every child: a mid-suite wedge aborts
    the pass immediately (cost: one 120s probe) instead of letting each
    remaining child burn its timeout on a dead backend.  Every captured
    child is persisted the moment it lands.  `already_probed` skips the
    probe for the FIRST child only (the caller just saw a healthy probe)."""
    results = dict(prev or {})
    attempts = attempts if attempts is not None else {}
    for tag, argv, timeout in TAGS:
        if _ok(results.get(tag)):
            continue
        if attempts.get(tag, 0) >= MAX_ATTEMPTS:
            continue
        if already_probed:
            already_probed = False
        elif not _probe():
            _log("capture pass ABORTED before %s: tunnel wedged" % tag)
            return results
        attempts[tag] = attempts.get(tag, 0) + 1
        if argv is None:
            results[tag] = _run_tpu_test_lane()
        else:
            results[tag] = _run_json_child([sys.executable] + argv, tag,
                                           timeout)
        if results[tag] is not None:
            # persist even non-ok payloads: failure diagnostics are round
            # evidence too, and a wedge later in the pass must never cost
            # what already landed
            _persist(results, probes)
            if _ok(results[tag]):
                _log("captured %s -> TPU_CAPTURE.json" % tag)
    return results


def main():
    if "--child-flash" in sys.argv:
        flash_microbench()
        return
    if "--child-sweep" in sys.argv:
        flash_block_sweep()
        return
    if "--child-mosaic" in sys.argv:
        mosaic_smoke()
        return
    once = "--once" in sys.argv
    deadline = time.time() + MAX_HOURS * 3600
    n = 0
    results = {}
    if os.path.exists(OUT):
        # Same-round capture (its BENCH_r* snapshot matches the repo's):
        # seed from it and only fill the missing children.  Otherwise it is
        # a previous round's file — remove it so a stale number can never
        # masquerade as this round's.
        import glob
        try:
            with open(OUT) as f:
                prior = json.load(f)
        except ValueError:
            prior = {}
        now_bench = sorted(os.path.basename(p) for p in
                           glob.glob(os.path.join(REPO, "BENCH_r*.json")))
        rnd = _current_round()
        same_round = (prior.get("round") == rnd if rnd is not None
                      and prior.get("round") is not None
                      else prior.get("bench_files_at_capture") == now_bench)
        if same_round:
            results = prior.get("results") or {}
            _log("seeding from same-round TPU_CAPTURE.json (%d children ok)"
                 % sum(_ok(v) for v in results.values()))
        else:
            os.remove(OUT)
            _log("removed stale TPU_CAPTURE.json from a previous round")
    _log("capture loop started (interval=%ss)" % PROBE_INTERVAL_S)
    attempts = {}
    while time.time() < deadline:
        n += 1
        healthy = _probe()
        _log("probe %d: %s" % (n, "HEALTHY" if healthy else "wedged"))
        if healthy:
            todo = [t for t in TAG_NAMES
                    if not _ok(results.get(t))
                    and attempts.get(t, 0) < MAX_ATTEMPTS]
            if not todo:
                _log("nothing left to capture (rest exhausted %d attempts)"
                     % MAX_ATTEMPTS)
                return
            _log("running capture suite (missing: %s)" % ",".join(todo))
            before_ok = sum(_ok(results.get(t)) for t in TAG_NAMES)
            # capture() persists each child as it lands and aborts the pass
            # if a pre-child re-probe finds the tunnel wedged
            results = capture(results, attempts, n, already_probed=True)
            n_ok = sum(_ok(results.get(t)) for t in TAG_NAMES)
            if n_ok > before_ok:
                _log("window yielded %d new children (%d/%d total ok)"
                     % (n_ok - before_ok, n_ok, len(TAG_NAMES)))
            else:
                _log("no new children captured this window")
            if all(_ok(results.get(t)) for t in TAG_NAMES):
                _log("capture COMPLETE — all children captured")
                return
        if once:
            return
        time.sleep(PROBE_INTERVAL_S)
    _log("capture loop ended without a healthy window (%d probes)" % n)


if __name__ == "__main__":
    main()
