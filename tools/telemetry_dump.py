#!/usr/bin/env python
"""telemetry_dump.py — merge per-worker telemetry traces into ONE
chrome-trace file.

Each process in a distributed job buffers its spans (client RPCs,
server handling, step phases — see mxnet_tpu/telemetry.py) and flushes
them to ``MX_TELEMETRY_TRACE/trace-<role>-r<rank>-p<pid>.trace.json``
at exit.  This tool stitches those per-process files into a single
timeline viewable in chrome://tracing / Perfetto: every source file
becomes one named process row (``process_name`` metadata), span
timestamps are already wall-epoch microseconds so rows align, and the
``trace_id``/``span_id``/``parent_id`` args let the viewer (and the
tests) follow one RPC from a worker's push through the server's handler
and back — retries and replay-cache hits ride along as instant events.

Usage:
  python tools/telemetry_dump.py --out merged.json trace1.json trace2.json
  python tools/telemetry_dump.py --out merged.json --dir $MX_TELEMETRY_TRACE

Prints a JSON summary (files, events, distinct trace ids) to stdout.
"""
import argparse
import glob
import json
import os
import sys


def load_trace(path):
    """One per-process trace file -> (label, events list)."""
    with open(path) as f:
        payload = json.load(f)
    if isinstance(payload, list):          # bare event list tolerated
        payload = {"traceEvents": payload}
    meta = payload.get("metadata") or {}
    label = "%s r%s (pid %s)" % (meta.get("role", "proc"),
                                 meta.get("rank", "?"),
                                 meta.get("pid", "?"))
    return label, list(payload.get("traceEvents") or [])


def merge(paths):
    """Merge trace files into one chrome-trace payload + summary."""
    events = []
    trace_ids = set()
    per_file = {}
    for i, path in enumerate(sorted(paths)):
        label, evs = load_trace(path)
        # one synthetic pid per source file: two processes on one host
        # can share an OS pid across time, and the viewer needs stable
        # distinct rows anyway
        pid = i + 1
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "args": {"name": label}})
        for ev in evs:
            ev = dict(ev)
            ev["pid"] = pid
            events.append(ev)
            tid = (ev.get("args") or {}).get("trace_id")
            if tid:
                trace_ids.add(tid)
        per_file[os.path.basename(path)] = len(evs)
    payload = {"traceEvents": events, "displayTimeUnit": "ms"}
    summary = {"files": per_file, "events": len(events),
               "distinct_trace_ids": len(trace_ids)}
    return payload, summary


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("inputs", nargs="*", help="per-process trace files")
    ap.add_argument("--dir", default=None,
                    help="merge every *.trace.json under this directory "
                         "(what MX_TELEMETRY_TRACE processes flush into)")
    ap.add_argument("--out", required=True, help="merged chrome-trace path")
    args = ap.parse_args(argv)
    paths = list(args.inputs)
    if args.dir:
        paths.extend(glob.glob(os.path.join(args.dir, "*.trace.json")))
    if not paths:
        print("telemetry_dump: no input traces", file=sys.stderr)
        return 1
    payload, summary = merge(paths)
    tmp = "%s.tmp.%d" % (args.out, os.getpid())
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, args.out)
    summary["out"] = args.out
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
