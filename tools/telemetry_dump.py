#!/usr/bin/env python
"""telemetry_dump.py — merge per-worker telemetry traces into ONE
chrome-trace file.

Each process in a distributed job buffers its spans (client RPCs,
server handling, step phases — see mxnet_tpu/telemetry.py) and flushes
them to ``MX_TELEMETRY_TRACE/trace-<role>-r<rank>-p<pid>.trace.json``
at exit.  The fleet collector (mxnet_tpu/fleet.py) flushes its scrape
spans the same way under role ``fleet``, so a merged job trace shows
the scraping cadence as its own row next to the workers/servers it
observed.  This tool stitches those per-process files into a single
timeline viewable in chrome://tracing / Perfetto: every source file
becomes one named process row (``process_name`` metadata), span
timestamps are already wall-epoch microseconds so rows align, and the
``trace_id``/``span_id``/``parent_id`` args let the viewer (and the
tests) follow one RPC from a worker's push through the server's handler
and back — retries and replay-cache hits ride along as instant events.

Partial jobs are NORMAL (a killed rank's file may never flush): a
missing or unreadable input is warned about and skipped, a directory
with zero trace files still produces an (empty) merged file, and
``--expect-roles`` lists which roles were expected — absent ones are
named in a warning.  All of that exits 0; only a genuinely unwritable
--out fails the merge.

Usage:
  python tools/telemetry_dump.py --out merged.json trace1.json trace2.json
  python tools/telemetry_dump.py --out merged.json --dir $MX_TELEMETRY_TRACE \\
      --expect-roles worker,server,fleet

Prints a JSON summary (files, events, distinct trace ids, roles,
skipped inputs, absent roles) to stdout.
"""
import argparse
import glob
import json
import os
import sys


def load_trace(path):
    """One per-process trace file -> (label, role, events list)."""
    with open(path) as f:
        payload = json.load(f)
    if isinstance(payload, list):          # bare event list tolerated
        payload = {"traceEvents": payload}
    meta = payload.get("metadata") or {}
    role = meta.get("role") or _role_from_name(path) or "proc"
    label = "%s r%s (pid %s)" % (role, meta.get("rank", "?"),
                                 meta.get("pid", "?"))
    return label, role, list(payload.get("traceEvents") or [])


def _role_from_name(path):
    """``trace-<role>-r<rank>-p<pid>.trace.json`` -> role (or None)."""
    base = os.path.basename(path)
    if base.startswith("trace-"):
        rest = base[len("trace-"):]
        head = rest.split("-r", 1)[0]
        return head or None
    return None


def merge(paths):
    """Merge trace files into one chrome-trace payload + summary.
    Missing/unreadable/corrupt inputs are skipped with a warning (a
    crashed rank legitimately never flushed its trace)."""
    events = []
    trace_ids = set()
    per_file = {}
    roles = set()
    skipped = {}
    pid = 0
    for path in sorted(paths):
        try:
            label, role, evs = load_trace(path)
        except (OSError, ValueError) as e:
            skipped[os.path.basename(path)] = str(e)
            print("telemetry_dump: skipping %s (%s)" % (path, e),
                  file=sys.stderr)
            continue
        # one synthetic pid per source file: two processes on one host
        # can share an OS pid across time, and the viewer needs stable
        # distinct rows anyway
        pid += 1
        roles.add(role)
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "args": {"name": label}})
        for ev in evs:
            ev = dict(ev)
            ev["pid"] = pid
            events.append(ev)
            tid = (ev.get("args") or {}).get("trace_id")
            if tid:
                trace_ids.add(tid)
        per_file[os.path.basename(path)] = len(evs)
    payload = {"traceEvents": events, "displayTimeUnit": "ms"}
    summary = {"files": per_file, "events": len(events),
               "distinct_trace_ids": len(trace_ids),
               "roles": sorted(roles), "skipped": skipped}
    return payload, summary


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("inputs", nargs="*", help="per-process trace files")
    ap.add_argument("--dir", default=None,
                    help="merge every *.trace.json under this directory "
                         "(what MX_TELEMETRY_TRACE processes flush into)")
    ap.add_argument("--out", required=True, help="merged chrome-trace path")
    ap.add_argument("--expect-roles", default=None, metavar="ROLES",
                    help="comma-separated roles that SHOULD appear "
                         "(e.g. worker,server,fleet); absent ones are "
                         "listed in a warning — still exit 0 (a killed "
                         "rank's trace legitimately never flushed)")
    args = ap.parse_args(argv)
    paths = list(args.inputs)
    if args.dir:
        paths.extend(glob.glob(os.path.join(args.dir, "*.trace.json")))
    if not paths:
        print("telemetry_dump: warning - no input traces (merging an "
              "empty timeline)", file=sys.stderr)
    payload, summary = merge(paths)
    expected = [r.strip() for r in (args.expect_roles or "").split(",")
                if r.strip()]
    absent = sorted(set(expected) - set(summary["roles"]))
    summary["absent_roles"] = absent
    if absent:
        print("telemetry_dump: warning - expected role(s) with no "
              "trace file: %s" % ", ".join(absent), file=sys.stderr)
    tmp = "%s.tmp.%d" % (args.out, os.getpid())
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, args.out)
    summary["out"] = args.out
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
