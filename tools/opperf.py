"""Per-operator benchmark harness.

Reference: benchmark/opperf/opperf.py (run_op_benchmarks — per-op fwd/bwd
latency over standard shapes) and benchmark/python/ffi/benchmark_ffi.py
(per-call eager-dispatch overhead, SURVEY hard part 2).

Reuses the test battery's per-op input specs (tests/test_operator.py
SPECS) so every benchmarked op runs on the same shapes its correctness
test pins.  Two numbers per op:
  * ``eager_us``  — wall time through the FULL eager dispatch path
    (NDArray wrap, registry lookup, per-op jit cache) — the FFI-overhead
    benchmark's role;
  * ``fwd_us``    — wall time of the cached XLA executable alone.
Plus ``dispatch_overhead_us`` = eager - fwd aggregated at the end.

Usage:  python tools/opperf.py [--ops op1,op2] [--runs 50] [-o out.json]
"""
import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))


def bench_op(opname, spec, runs):
    import jax
    from mxnet_tpu import nd
    from mxnet_tpu.ndarray.ndarray import invoke
    from mxnet_tpu.ops import registry

    np_inputs = spec.inputs()
    nd_inputs = [nd.array(x) for x in np_inputs]
    op = registry.get_op(opname)

    def once():
        return invoke(opname, *nd_inputs, **spec.params)

    def sync(res):
        outs = res if isinstance(res, (list, tuple)) else [res]
        for o in outs:
            if hasattr(o, "_jax"):
                jax.block_until_ready(o._jax)

    try:
        sync(once())  # compile + warm
        sync(once())
    except Exception as e:  # keep the sweep going: record the failure
        return {"op": opname, "error": "%s: %s" % (type(e).__name__, e)}

    t0 = time.perf_counter()
    for _ in range(runs):
        res = once()
    sync(res)
    eager_us = (time.perf_counter() - t0) / runs * 1e6

    rec = {"op": opname, "eager_us": round(eager_us, 2),
           "shapes": [list(x.shape) for x in np_inputs]}
    if not op.no_jit and not op.needs_rng:
        # time the cached executable alone (no dispatch wrapping)
        from mxnet_tpu.ops.registry import cached_jit
        fn = cached_jit(op.name, spec.params)
        jax_in = [x._jax for x in nd_inputs]
        jax.block_until_ready(jax.tree_util.tree_leaves(fn(*jax_in)))
        t0 = time.perf_counter()
        for _ in range(runs):
            out = fn(*jax_in)
        jax.block_until_ready(jax.tree_util.tree_leaves(out))
        fwd_us = (time.perf_counter() - t0) / runs * 1e6
        rec["fwd_us"] = round(fwd_us, 2)
        rec["dispatch_overhead_us"] = round(eager_us - fwd_us, 2)
    return rec


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ops", default=None,
                    help="comma-separated op subset (default: all specs)")
    ap.add_argument("--runs", type=int, default=50)
    ap.add_argument("-o", "--output", default=None)
    args = ap.parse_args()

    from mxnet_tpu.base import ensure_live_backend
    backend = ensure_live_backend()
    import jax
    import test_operator as batt  # tests/ on sys.path

    ops = sorted(batt.SPECS)
    if args.ops:
        ops = [o for o in args.ops.split(",") if o in batt.SPECS]
    results = []
    for opname in ops:
        rec = bench_op(opname, batt.SPECS[opname], args.runs)
        results.append(rec)
        sys.stderr.write("%-40s %s\n" % (
            opname, rec.get("eager_us", rec.get("error"))))
    ok = [r for r in results if "eager_us" in r]
    overhead = [r["dispatch_overhead_us"] for r in ok
                if "dispatch_overhead_us" in r]
    summary = {
        "device": jax.default_backend() if backend != "cpu" else "cpu",
        "num_ops": len(ok),
        "num_errors": len(results) - len(ok),
        "median_eager_us": round(sorted(
            r["eager_us"] for r in ok)[len(ok) // 2], 2) if ok else None,
        "median_dispatch_overhead_us": round(sorted(overhead)[
            len(overhead) // 2], 2) if overhead else None,
        "results": results,
    }
    out = json.dumps(summary)
    if args.output:
        with open(args.output, "w") as f:
            f.write(out)
    # one-line summary on stdout (driver-friendly), full payload in -o
    print(json.dumps({k: v for k, v in summary.items() if k != "results"}))


if __name__ == "__main__":
    main()
