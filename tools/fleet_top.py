#!/usr/bin/env python
"""fleet_top.py — live terminal dashboard over the fleet telemetry
plane (ISSUE 12).

Replaces ad-hoc reading of N heartbeat files: one table, one row per
fleet member, with per-member throughput, phase breakdown, queue /
occupancy, and the straggler / SLO flags the collector's detectors
raise.  Three source modes:

  --fleet HOST:PORT       read a running collector's FLEET wire verb
                          (the supervisor embeds one; MX_FLEET_PORT)
  --serve a:p,b:p [...]   build a local collector over serve replicas
  --kv a:p,b:p            ... and/or parameter servers (METRICS verb)
  --heartbeat-dir DIR     ... and/or training workers' heartbeat files
                          (rank_* files, the launch.py layout)

Examples::

  python tools/fleet_top.py --fleet 127.0.0.1:9800 --once
  python tools/fleet_top.py --serve 127.0.0.1:9700,127.0.0.1:9701 \\
      --heartbeat-dir /tmp/mx-heartbeat-XXXX --interval 2

``--once`` renders a single snapshot and exits 0 (CI smoke);
``--json`` dumps the merged snapshot instead of the table.
"""
import argparse
import glob
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def _fmt(v, spec="%.3g"):
    if v is None:
        return "-"
    try:
        return spec % v
    except (TypeError, ValueError):
        return str(v)


def _member_row(mid, meta, snap):
    """One table row from the merged snapshot's member entry."""
    counters = snap.get("counters") or {}
    gauges = snap.get("gauges") or {}

    def cval(name):
        return (counters.get(name) or {}).get("per_member", {}).get(mid)

    def gval(name):
        return (gauges.get(name) or {}).get("per_member", {}).get(mid)

    role = meta.get("role", "?")
    state = "up" if meta.get("present") else \
        "ABSENT(%d)" % meta.get("absent_scrapes", 0)
    if role == "serve":
        work = _fmt(cval("serve.requests"), "%d")
        rate = "-"
        queue = _fmt(gval("serve.queue_rows"), "%g")
        # decode-aware replicas (ISSUE 17): slot occupancy + KV-pool
        # headroom ride the flags column so one table answers "can this
        # replica take another session?"
        occ = gval("serve.decode.slot_occupancy")
        if occ is not None:
            flags_extra = ["slots=%.0f%%" % (100.0 * occ)]
            head = gval("serve.decode.kv_headroom_bytes")
            if head is not None:
                flags_extra.append("kv_free=%s" % _fmt(head, "%.3g"))
            # paged replicas (ISSUE 18): page-level headroom + what
            # prefix sharing is saving right now
            pages = gval("serve.decode.kv_free_pages")
            if pages is not None:
                flags_extra.append("pages=%s" % _fmt(pages, "%g"))
            saved = gval("serve.decode.kv_shared_saved_bytes")
            if saved:
                flags_extra.append("shared=%s" % _fmt(saved, "%.3g"))
        else:
            flags_extra = []
    elif role == "router":
        # the fleet front-tier (ISSUE 17): forwarded requests, pinned
        # sessions as "queue", failovers/spills as flags
        work = _fmt(cval("router.requests"), "%d")
        rate = "-"
        queue = _fmt(gval("router.sessions"), "%g")
        flags_extra = []
        up = gval("router.replicas_up")
        if up is not None:
            flags_extra.append("up=%s" % _fmt(up, "%g"))
        for cname, label in (("router.failovers", "failover"),
                             ("router.spills", "spill")):
            v = cval(cname)
            if v:
                flags_extra.append("%s=%d" % (label, v))
    else:
        work = _fmt(cval("worker.steps"), "%d")
        rate = _fmt(gval("worker.steps_per_sec"))
        queue = "-"
        flags_extra = []
    # dominant phase: largest per-phase gauge for this member
    dom = "-"
    best = 0.0
    for key, slot in gauges.items():
        if not key.startswith("worker.phase_seconds{"):
            continue
        v = slot.get("per_member", {}).get(mid)
        if v is not None and v > best:
            best = v
            dom = key.split("phase=", 1)[1].rstrip("}")
    flags = list(flags_extra)
    for f in snap.get("stragglers") or []:
        if f.get("member") == mid:
            flags.append("STRAGGLER(%.3gx %s)"
                         % (f.get("ratio", 0),
                            f.get("dominant_phase") or "?"))
    return (mid, state, meta.get("source") or "-",
            meta.get("model") or "-", work, rate, queue, dom,
            " ".join(flags) or "-")


def render(snap):
    """The fleet table + SLO footer as one printable string."""
    cols = ("member", "state", "source", "model", "work", "rate",
            "queue", "top phase", "flags")
    rows = [cols]
    for mid in sorted(snap.get("members") or {}):
        rows.append(_member_row(mid, snap["members"][mid], snap))
    widths = [max(len(str(r[i])) for r in rows) for i in range(len(cols))]
    lines = ["  ".join(str(c).ljust(w)
                       for c, w in zip(r, widths)).rstrip()
             for r in rows]
    sep = "-" * max(len(ln) for ln in lines)
    out = ["fleet @ scrape %s (%s member(s))"
           % (snap.get("scrape", "?"), len(snap.get("members") or {})),
           sep] + lines + [sep]
    # per-model traffic rows (ISSUE 20, schema 3): one line per
    # co-hosted model from the merged model-labeled counter rollup
    for mdl in sorted(snap.get("models") or {}):
        row = snap["models"][mdl]
        parts = []
        for cname, label in (("serve.requests", "req"),
                             ("serve.rows", "rows"),
                             ("serve.batches", "batches"),
                             ("serve.decode.requests", "gen"),
                             ("serve.decode.tokens", "tok"),
                             ("serve.decode.sequences", "seqs")):
            v = row.get(cname)
            if v:
                parts.append("%s=%s" % (label, _fmt(v, "%d")))
        out.append("model %-16s %s" % (mdl, " ".join(parts) or "-"))
    slo = snap.get("slo") or {}
    out.append("slo: p50=%.4gms p99=%.4gms reject=%.3g%% queue=%.3g"
               % (slo.get("p50_ms", 0), slo.get("p99_ms", 0),
                  100 * slo.get("rejection_rate", 0),
                  slo.get("queue_depth", 0)))
    for name, b in (slo.get("burn") or {}).items():
        mark = " BREACH" if name in (slo.get("breached") or {}) else ""
        out.append("slo burn %s: %.3gx%s" % (name, b, mark))
    stragglers = snap.get("stragglers") or []
    if stragglers:
        out.append("stragglers: " + ", ".join(
            "%s (%.3gx, %s)" % (f["member"], f.get("ratio", 0),
                                f.get("dominant_phase") or "?")
            for f in stragglers))
    return "\n".join(out)


def _build_collector(args):
    from mxnet_tpu import fleet
    members = []
    for i, addr in enumerate(a for a in (args.serve or "").split(",")
                             if a.strip()):
        members.append(fleet.FleetMember("serve", i, addr=addr.strip()))
    for i, addr in enumerate(a for a in (args.kv or "").split(",")
                             if a.strip()):
        members.append(fleet.FleetMember("server", i, addr=addr.strip()))
    for i, addr in enumerate(a for a in (args.router or "").split(",")
                             if a.strip()):
        members.append(fleet.FleetMember("router", i, addr=addr.strip()))
    if args.heartbeat_dir:
        for path in sorted(glob.glob(
                os.path.join(args.heartbeat_dir, "rank_*"))):
            if path.endswith(".tmp") or ".tmp." in path:
                continue
            rank = os.path.basename(path).split("_", 1)[1]
            members.append(fleet.FleetMember("worker", rank,
                                             heartbeat=path))
    if not members:
        raise SystemExit("fleet_top: no members (need --fleet, --serve, "
                         "--kv, or --heartbeat-dir)")
    return fleet.FleetCollector(members,
                                interval=args.interval,
                                stale_after=args.stale_after)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python tools/fleet_top.py", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--fleet", default=None, metavar="HOST:PORT",
                    help="read a running collector's FLEET wire verb")
    ap.add_argument("--serve", default=None, metavar="ADDRS",
                    help="comma-separated serve replica addresses to "
                         "scrape directly (builds a local collector)")
    ap.add_argument("--kv", default=None, metavar="ADDRS",
                    help="comma-separated parameter-server addresses")
    ap.add_argument("--router", default=None, metavar="ADDRS",
                    help="comma-separated session-router addresses "
                         "(the serve tier's front, ISSUE 17)")
    ap.add_argument("--heartbeat-dir", default=None, metavar="DIR",
                    help="directory of rank_* heartbeat files (the "
                         "launch.py layout) for training workers")
    ap.add_argument("--interval", type=float, default=None,
                    help="refresh/scrape seconds (default "
                         "MX_FLEET_INTERVAL)")
    ap.add_argument("--stale-after", type=float, default=None,
                    help="heartbeat staleness bound (default "
                         "MX_FLEET_STALE / auto)")
    ap.add_argument("--once", action="store_true",
                    help="render one snapshot and exit")
    ap.add_argument("--json", action="store_true",
                    help="print the merged snapshot as JSON instead of "
                         "the table")
    args = ap.parse_args(argv)

    from mxnet_tpu import fleet
    collector = None
    if args.fleet:
        def snap_fn():
            return fleet.fetch_fleet(args.fleet)
    else:
        collector = _build_collector(args)

        def snap_fn():
            return collector.scrape_once()

    interval = args.interval
    if interval is None:
        from mxnet_tpu.base import get_env
        interval = get_env("MX_FLEET_INTERVAL", 2.0, float) or 2.0
    try:
        while True:
            snap = snap_fn()
            if args.json:
                print(json.dumps(snap, indent=1, default=str))
            else:
                if not args.once and sys.stdout.isatty():
                    print("\033[2J\033[H", end="")
                print(render(snap))
            if args.once:
                return 0
            sys.stdout.flush()
            time.sleep(interval)
    except KeyboardInterrupt:
        return 0
    finally:
        if collector is not None:
            collector.stop()


if __name__ == "__main__":
    sys.exit(main())
