#!/usr/bin/env python
"""serve_load.py — drive a serving fleet and verify every answer.

The client side of the serving chaos lane (tools/chaos_smoke.sh) and a
handy manual load CLI.  Sends N PREDICT requests at the fleet through
one sticky :class:`mxnet_tpu.serve.ServeClient` (failover exercises the
SEQ retry + replica rotation), checks every response against a LOCAL
eager forward of the deterministic demo model — correctness, not just
arrival — and reports a JSON summary.

``--chaos`` additionally asserts the kill-one-replica story end to end:

  * every request got a (correct) response — zero lost in-flight
    requests across the crash;
  * at least one client failover happened (the fault actually fired);
  * after the load, EVERY replica answers a pinned HEALTH probe — i.e.
    the supervisor restarted the crashed one and it is serving again.

``--stop`` sends the wire STOP to every replica at the end so the
supervised job (launch.py) drains and exits 0.  ``--metrics`` prints
every replica's live Prometheus snapshot via the METRICS verb after the
load (``--requests 0 --metrics`` is a pure scrape).

``--routed`` declares ``--addrs`` to be the session ROUTER's one
address (ISSUE 17) instead of the replica list: the load and the
verification are unchanged (the router forwards envelopes verbatim, so
answers must still match the local oracle bit-for-bit), but the
``--chaos`` assertions move to the fleet tier — zero lost requests,
at least one failover SOMEWHERE (client-side when the router itself is
killed, router-side when a replica dies under it), and afterwards the
router reports every replica ``up`` again.  ``--poisson RATE`` opens
the closed loop into Poisson arrivals at RATE req/s (exponential
inter-arrival gaps) — the autoscaler chaos lane drives a baseline and
a 4x spike with it.

``--decode`` switches the load to GENERATE requests against the
continuous-batching decode engine (ISSUE 15): every generated token
sequence is checked against a LOCAL greedy decode of the same
deterministic demo LM (``serve.decode.reference_generate``), so a
failover that re-prefills on the survivor must reproduce the sequence
EXACTLY — completed sequences are never lost, replayed at most once,
and never silently wrong.

With ``MX_SERVE_DRAFT`` set (the replicas run the SPECULATIVE engine,
ISSUE 20) the local oracle mirrors the replica's model construction —
the spec pair's TARGET params — because speculative decoding is
bit-identical to the target's own greedy decode; the verification
itself is byte-for-byte the same.

``--shared-prefix K`` (with ``--decode``, ISSUE 18) reshapes the load
into the paged engine's headline workload: the N sessions cycle over K
distinct full-bucket prompts, so a prefix-sharing replica answers every
repeat from its hash table (CoW fork + one replay chunk) instead of
re-prefilling.  Each request STREAMS (on_token) and the time to the
FIRST token is recorded per lane — ``cold`` (first sight of a prompt)
vs ``shared`` (repeats) — reported as p50/p99 ms.  Token verification
against the local oracle is unchanged: sharing must be invisible to
correctness, whichever engine is behind the socket.
"""
import argparse
import json
import os
import socket
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("MX_FORCE_CPU", "1")


def wait_up(addrs, timeout=90.0):
    deadline = time.monotonic() + timeout
    pending = list(addrs)
    while pending and time.monotonic() < deadline:
        addr = pending[0]
        host, port = addr.rsplit(":", 1)
        try:
            socket.create_connection((host, int(port)),
                                     timeout=0.5).close()
            pending.pop(0)
        except OSError:
            time.sleep(0.2)
    if pending:
        raise SystemExit("serve_load: replicas never came up: %s"
                         % pending)


def decode_oracle():
    """(cfg, params) for the LOCAL reference decode — mirrors the
    replica's own model construction.  Under MX_SERVE_DRAFT the
    replica's GENERATE lane is the speculative pair's TARGET
    (``demo_spec_pair`` damps the deep layers so a shallow draft stays
    plausible), and speculative decoding is bit-identical to that
    target's greedy decode, so the oracle must be built the same way."""
    from mxnet_tpu.base import get_env
    from mxnet_tpu.serve.decode import (DecodeConfig, demo_lm_params,
                                        demo_spec_pair)
    cfg = DecodeConfig()
    draft_layers = int(get_env("MX_SERVE_DRAFT", 0, int) or 0)
    if draft_layers > 0:
        params, _dcfg, _dparams = demo_spec_pair(
            cfg, draft_layers=draft_layers)
    else:
        params = demo_lm_params(cfg)
    return cfg, params


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--addrs", required=True,
                    help="comma-separated replica addresses host:port")
    ap.add_argument("--requests", type=int, default=120)
    ap.add_argument("--rows", type=int, default=2,
                    help="rows per request")
    ap.add_argument("--decode", action="store_true",
                    help="drive GENERATE (autoregressive decode) "
                         "instead of PREDICT; every token sequence is "
                         "verified against a local reference decode")
    ap.add_argument("--max-tokens", type=int, default=12,
                    help="--decode: generated tokens per request "
                         "(short/long mix alternates 2 and this)")
    ap.add_argument("--shared-prefix", type=int, default=0, metavar="K",
                    help="--decode: cycle the sessions over K distinct "
                         "full-bucket prompts (the prefix-reuse "
                         "workload, ISSUE 18) and report first-token "
                         "p50/p99 ms per lane (cold vs shared)")
    ap.add_argument("--routed", action="store_true",
                    help="--addrs is the session router's address: "
                         "chaos assertions move to the fleet tier "
                         "(router health + per-replica 'up' states "
                         "instead of pinned per-replica probes)")
    ap.add_argument("--poisson", type=float, default=None,
                    metavar="RATE",
                    help="Poisson arrivals at RATE requests/s "
                         "(exponential inter-arrival gaps) instead of "
                         "closed-loop back-to-back")
    ap.add_argument("--chaos", action="store_true",
                    help="assert failover happened and every replica "
                         "serves again afterwards")
    ap.add_argument("--stop", action="store_true",
                    help="send STOP to every replica at the end")
    ap.add_argument("--metrics", action="store_true",
                    help="after the load, print every replica's live "
                         "Prometheus snapshot via the METRICS verb "
                         "(use --requests 0 for a pure scrape)")
    ap.add_argument("--timeout", type=float, default=20.0)
    args = ap.parse_args()

    import numpy as np
    from mxnet_tpu import telemetry
    from mxnet_tpu.serve import ServeClient
    from mxnet_tpu.serve.demo import demo_block, demo_expected

    addrs = [a.strip() for a in args.addrs.split(",") if a.strip()]
    wait_up(addrs)
    cli = ServeClient(addrs, timeout=args.timeout)
    rng = np.random.RandomState(0)

    def pace():
        # open-loop Poisson arrivals: exponential inter-arrival gaps at
        # --poisson req/s (closed-loop back-to-back when unset)
        if args.poisson:
            time.sleep(float(rng.exponential(1.0 / args.poisson)))

    ok, t0 = 0, time.perf_counter()
    first_token_ms = None
    if args.decode and args.shared_prefix:
        # the prefix-reuse workload: N sessions over K full-bucket
        # prompts, first-token latency split cold (first sight) vs
        # shared (repeats a paged replica answers from its hash table)
        from mxnet_tpu.serve.decode import reference_generate
        cfg, params = decode_oracle()
        plen = cfg.prompt_buckets[-1]
        max_new = min(args.max_tokens, cfg.max_tokens)
        bases = [[int(t) for t in rng.randint(2, cfg.vocab, size=plen)]
                 for _ in range(max(1, args.shared_prefix))]
        expect = [reference_generate(p, max_new, params=params,
                                     config=cfg) for p in bases]
        lanes = {"cold": [], "shared": []}
        seen = set()
        for i in range(args.requests):
            k = i % len(bases)
            lane = "shared" if k in seen else "cold"
            seen.add(k)
            stamp = {}

            def first_token(_chunk, _stamp=stamp):
                _stamp.setdefault("t", time.perf_counter())

            pace()
            t_req = time.perf_counter()
            version, toks = cli.generate(bases[k], max_tokens=max_new,
                                         on_token=first_token)
            assert toks == expect[k], \
                ("request %d (decode v%d, prompt %d) answered WRONG "
                 "tokens: %r != %r" % (i, version, k, toks, expect[k]))
            lanes[lane].append(
                (stamp.get("t", time.perf_counter()) - t_req) * 1000.0)
            ok += 1
        first_token_ms = {
            lane: {"p50": round(float(np.percentile(v, 50)), 3),
                   "p99": round(float(np.percentile(v, 99)), 3),
                   "n": len(v)}
            for lane, v in lanes.items() if v}
    elif args.decode:
        # local truth: the reference greedy decode of the same seeded
        # demo LM — a replica (or a failover re-prefill on the
        # survivor) must answer these tokens EXACTLY
        from mxnet_tpu.serve.decode import reference_generate
        cfg, params = decode_oracle()
        # mirror the server's silent clamp (submit caps max_new at
        # MX_SERVE_DECODE_MAX_TOKENS) or the local oracle would expect
        # more tokens than a CORRECT replica may return
        long_new = min(args.max_tokens, cfg.max_tokens)
        expect_cache = {}
        for i in range(args.requests):
            prompt = [int(t) for t in
                      rng.randint(2, cfg.vocab, size=2 + (i % 3))]
            max_new = 2 if i % 2 else long_new
            key = (tuple(prompt), max_new)
            if key not in expect_cache:
                expect_cache[key] = reference_generate(
                    prompt, max_new, params=params, config=cfg)
            pace()
            version, toks = cli.generate(prompt, max_tokens=max_new)
            assert toks == expect_cache[key], \
                ("request %d (decode v%d) answered WRONG tokens: "
                 "%r != %r" % (i, version, toks, expect_cache[key]))
            ok += 1
    else:
        net = demo_block()                  # local truth for verification
        for i in range(args.requests):
            x = rng.randn(args.rows, 16).astype(np.float32)
            pace()
            version, outs = cli.predict([x])
            np.testing.assert_allclose(
                outs[0], demo_expected(x, net=net), rtol=1e-4,
                atol=1e-5,
                err_msg="request %d (servable v%d) answered WRONG "
                        "values" % (i, version))
            ok += 1
    wall = time.perf_counter() - t0
    failovers = telemetry.registry.value("serve.client_failovers")

    restarted = []
    if args.chaos and args.routed:
        assert ok == args.requests, \
            "lost requests: %d/%d answered" % (ok, args.requests)
        # through a router the failover can land on EITHER side of it:
        # a replica killed under the router is absorbed ROUTER-side
        # (the client never sees it), a killed router is a CLIENT-side
        # failover (reconnect + SEQ replay).  Require at least one
        # somewhere, then wait for the router to report every replica
        # it fronts 'up' again (the supervisor restarted the victim and
        # a refresh-tick probe revived it).
        wait_up(addrs, timeout=120.0)
        h = cli.health()
        assert h.get("status") in ("routing", "draining"), h
        total = failovers + int(h.get("failovers", 0))
        assert total >= 1, \
            "no failover happened anywhere - did the chaos fault fire?"
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            h = cli.health()
            states = h.get("replicas", {})
            if states and all(s == "up" for s in states.values()):
                break
            time.sleep(0.5)
        else:
            raise SystemExit("serve_load: router never saw the fleet "
                             "whole again: %r" % (h,))
        restarted.append(h.get("pid"))
    elif args.chaos:
        assert ok == args.requests, \
            "lost requests: %d/%d answered" % (ok, args.requests)
        assert failovers >= 1, \
            "no failover happened - did the chaos fault fire?"
        # the supervisor must have brought the dead replica back: every
        # replica answers a PINNED health probe.  A replica killed near
        # the END of the load may still be re-warming its program
        # tables (the decode demo compiles ~7 bucket programs before it
        # binds), which outlives the pinned probe's 5s fail-fast clamp
        # — so first wait for every port to accept again (a respawned
        # replica binds only once warm), THEN probe.
        wait_up(addrs, timeout=120.0)
        for i in range(len(addrs)):
            h = cli.health(idx=i)
            assert h.get("status") == "serving", (i, h)
            restarted.append(h.get("pid"))
    if args.metrics:
        for i, addr in enumerate(addrs):
            print("# ==== metrics: replica %d (%s) ====" % (i, addr))
            print(cli.metrics(idx=i))
    if args.stop:
        cli.stop()
    cli.close()
    report = {
        "requests": args.requests,
        "mode": "decode" if args.decode else "predict",
        "routed": bool(args.routed),
        "answered": ok,
        "failovers": failovers,
        "requests_per_sec": round(ok / wall, 2),
        "replica_pids": restarted,
    }
    if first_token_ms is not None:
        report["shared_prefix_prompts"] = args.shared_prefix
        report["first_token_ms"] = first_token_ms
    print(json.dumps(report))
    print("SERVE_LOAD_OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
