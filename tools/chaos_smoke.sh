#!/usr/bin/env bash
# chaos_smoke.sh — exercise the supervised-elastic-launch resilience path
# end-to-end through the CLI, outside the unit suite (CI smoke).
#
# Runs tools/chaos_fit.py under `launch.py -n 2 --restart on-failure` with
# an armed `worker.step:crash:after=5` spec: each rank is killed
# mid-epoch-1, restarted by the supervisor with its original env, and
# auto-resumed from its epoch-0 checkpoint.  Asserts exit 0, both ranks
# finishing, and the resumed ranks' final params matching an
# uninterrupted single-rank reference run.
set -euo pipefail

REPO="$(cd "$(dirname "$0")/.." && pwd)"
WORK="$(mktemp -d /tmp/mx-chaos-smoke.XXXXXX)"
trap 'rm -rf "$WORK"' EXIT

export JAX_PLATFORMS=cpu MX_FORCE_CPU=1
unset XLA_FLAGS || true
PY="${PYTHON:-python3}"

echo "== chaos_smoke: uninterrupted reference run (-n 1)"
"$PY" "$REPO/tools/launch.py" -n 1 --launcher local -- \
    "$PY" "$REPO/tools/chaos_fit.py" \
    --ckpt-dir "$WORK/ref" --out "$WORK/ref" > "$WORK/ref.log" 2>&1

echo "== chaos_smoke: -n 2 --restart on-failure --fault worker.step:crash:after=5"
rc=0
MX_CRASH_DIR="$WORK/crash" \
"$PY" "$REPO/tools/launch.py" -n 2 --launcher local \
    --restart on-failure --max-restarts 2 --status-interval 2 \
    --fault 'worker.step:crash:after=5' -- \
    "$PY" "$REPO/tools/chaos_fit.py" \
    --ckpt-dir "$WORK/chaos" --out "$WORK/chaos" 2>&1 \
    | tee "$WORK/chaos.log" || rc=$?
if [ "$rc" -ne 0 ]; then
    echo "chaos_smoke: FAIL - launch.py exited $rc" >&2
    exit 1
fi
grep -q 'restart 1/' "$WORK/chaos.log" || {
    echo "chaos_smoke: FAIL - no restart happened (fault spec not armed?)" >&2
    exit 1
}
DONE=$(grep -c 'CHAOS_FIT_DONE' "$WORK/chaos.log" || true)
if [ "$DONE" -ne 2 ]; then
    echo "chaos_smoke: FAIL - expected 2 completed ranks, saw $DONE" >&2
    exit 1
fi

echo "== chaos_smoke: flight-recorder crash dumps + supervisor status table (ISSUE 8)"
# the kill-mid-fit above must leave BOTH sides of the observability
# story in MX_CRASH_DIR: each crashed rank's in-process flight-recorder
# dump (>= 1 structured step record) and the supervisor's own record of
# what it saw; the supervisor log must render the fleet status table
grep -q 'fleet status:' "$WORK/chaos.log" || {
    echo "chaos_smoke: FAIL - supervisor never printed a fleet status table" >&2
    exit 1
}
"$PY" - "$WORK/crash" <<'EOF'
import glob, json, sys
d = sys.argv[1]
worker = sorted(glob.glob("%s/crash-rank*.json" % d))
sup = sorted(glob.glob("%s/supervisor-*.json" % d))
assert worker, "no worker flight-recorder crash dumps in %s" % d
assert sup, "no supervisor crash records in %s" % d
blob = json.load(open(worker[0]))
assert len(blob.get("records") or []) >= 1, \
    "crash dump %s has no step records: %s" % (worker[0], blob.keys())
rec = blob["records"][-1]
for field in ("step", "phases", "dispatches", "wire_bytes"):
    assert field in rec, (field, rec)
# ISSUE 10: crash dumps carry the device-buffer census and the program
# registry — a dead rank's memory story and compiled-program set are
# part of the flight recording
census = blob.get("buffer_census")
assert census and census.get("total_bytes", 0) > 0, \
    "crash dump %s has no buffer census: %r" % (worker[0], census)
assert census.get("params", {}).get("count", 0) >= 1, \
    "census attributed no parameter buffers: %r" % (census,)
progs = blob.get("programs")
assert progs and len(progs) >= 1, \
    "crash dump %s has no registered programs" % worker[0]
assert any(t.get("compile_seconds", {}).get("total", 0) > 0
           for t in progs.values()), \
    "no program carries compile time: %r" % (list(progs),)
sblob = json.load(open(sup[0]))
assert sblob["rc"] != 0 and "heartbeat" in sblob, sblob
print("chaos_smoke: %d worker crash dump(s) with step records + %d "
      "supervisor record(s)" % (len(worker), len(sup)))
EOF

echo "== chaos_smoke: comparing resumed params to the uninterrupted run"
"$PY" - "$WORK" <<'EOF'
import sys
import numpy as np
work = sys.argv[1]
ref = np.load("%s/ref.rank0.npz" % work)
for rank in (0, 1):
    got = np.load("%s/chaos.rank%d.npz" % (work, rank))
    assert set(got.files) == set(ref.files), (got.files, ref.files)
    for k in ref.files:
        np.testing.assert_allclose(got[k], ref[k], rtol=1e-5, atol=1e-6,
                                   err_msg="rank %d param %s" % (rank, k))
print("chaos_smoke: resumed params match the uninterrupted run")
EOF

echo "== chaos_smoke: 3-step int8-compressed overlap-scheduled fit"
XLA_FLAGS="--xla_force_host_platform_device_count=2" \
MX_GRAD_COMPRESS=int8 MX_EXCHANGE_OVERLAP=1 \
"$PY" - "$REPO" <<'EOF'
import sys
sys.path.insert(0, sys.argv[1])
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.engine import engine

# 2-device DP fit through the int8-quantized, overlap-scheduled exchange:
# grad hooks fire during backward, bucket collectives launch early, drain
# commits before the fused update — 3 steps must train (loss drops) and
# the wire must carry compressed bytes.
mx.random.seed(0)
ctxs = [mx.cpu(0), mx.cpu(1)]
net = gluon.nn.Dense(4, in_units=8)
net.initialize(mx.init.Xavier(), ctx=ctxs)
trainer = gluon.Trainer(net.collect_params(), "sgd",
                        {"learning_rate": 0.1}, kvstore="device")
loss_fn = gluon.loss.L2Loss()
rng = np.random.RandomState(0)
X = rng.randn(16, 8).astype(np.float32)
W = rng.randn(8, 4).astype(np.float32)
Y = X.dot(W)
losses = []
w0 = engine.wire_bytes
for step in range(3):
    half = len(X) // 2
    tot = 0.0
    with autograd.record():
        for ctx, sl in zip(ctxs, (slice(0, half), slice(half, None))):
            loss = loss_fn(net(nd.array(X[sl], ctx=ctx)),
                           nd.array(Y[sl], ctx=ctx))
            loss.backward()
            tot += float(loss.mean().asnumpy())
    trainer.step(batch_size=len(X))
    losses.append(tot)
wire = engine.wire_bytes - w0
assert all(np.isfinite(l) for l in losses), losses
assert losses[-1] < losses[0], losses
assert trainer._kvstore is not None and trainer._kvstore._gc.type == "int8"
assert 0 < wire, wire
print("compressed_fit_smoke: PASS losses=%s wire_bytes=%d"
      % (["%.4f" % l for l in losses], wire))
EOF

echo "== chaos_smoke: compiled-mode fit (MX_STEP_COMPILE=1) + crash->restart->resume"
# reference run under the whole-step-compiled lane; its params must ALSO
# match the eager reference (compiled == eager parity through the CLI)
MX_STEP_COMPILE=1 "$PY" "$REPO/tools/launch.py" -n 1 --launcher local -- \
    "$PY" "$REPO/tools/chaos_fit.py" \
    --ckpt-dir "$WORK/cref" --out "$WORK/cref" > "$WORK/cref.log" 2>&1
rc=0
MX_STEP_COMPILE=1 "$PY" "$REPO/tools/launch.py" -n 2 --launcher local \
    --restart on-failure --max-restarts 2 \
    --fault 'worker.step:crash:after=5' -- \
    "$PY" "$REPO/tools/chaos_fit.py" \
    --ckpt-dir "$WORK/cchaos" --out "$WORK/cchaos" 2>&1 \
    | tee "$WORK/cchaos.log" || rc=$?
if [ "$rc" -ne 0 ]; then
    echo "chaos_smoke: FAIL - compiled-mode launch.py exited $rc" >&2
    exit 1
fi
grep -q 'restart 1/' "$WORK/cchaos.log" || {
    echo "chaos_smoke: FAIL - no compiled-mode restart happened" >&2
    exit 1
}
"$PY" - "$WORK" <<'EOF'
import sys
import numpy as np
work = sys.argv[1]
eager = np.load("%s/ref.rank0.npz" % work)
cref = np.load("%s/cref.rank0.npz" % work)
# compiled fit == eager fit (same trajectory, one dispatch per batch)
for k in eager.files:
    np.testing.assert_allclose(cref[k], eager[k], rtol=1e-5, atol=1e-6,
                               err_msg="compiled-vs-eager %s" % k)
# crash->restart->resume round-trips the DONATED optimizer state: the
# resumed compiled ranks land on the uninterrupted compiled run's params
for rank in (0, 1):
    got = np.load("%s/cchaos.rank%d.npz" % (work, rank))
    for k in cref.files:
        np.testing.assert_allclose(got[k], cref[k], rtol=1e-5, atol=1e-6,
                                   err_msg="rank %d param %s" % (rank, k))
print("chaos_smoke: compiled-mode fit matches eager; resume round-trips "
      "donated optimizer state")
EOF

echo "== chaos_smoke: 3-step compiled int8 fit (CompiledStep, EF residuals donated)"
XLA_FLAGS="--xla_force_host_platform_device_count=2" \
"$PY" - "$REPO" <<'EOF'
import sys
sys.path.insert(0, sys.argv[1])
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import gluon, nd
from mxnet_tpu.engine import engine

# single-program steps through the int8-compressed ICI exchange body on
# a 2-device store: loss drops, the EF residual store fills, EVERY step
# is one dispatch and the 4-step scan window costs 2 dispatches total
mx.random.seed(0)
ctxs = [mx.cpu(0), mx.cpu(1)]
net = gluon.nn.Dense(4, in_units=8)
net.initialize(mx.init.Xavier(), ctx=ctxs)
trainer = gluon.Trainer(net.collect_params(), "sgd",
                        {"learning_rate": 0.1}, kvstore="ici",
                        compression_params={"type": "int8"})
step = trainer.make_compiled_step(net, gluon.loss.L2Loss())
rng = np.random.RandomState(0)
X = rng.randn(16, 8).astype(np.float32)
Y = X.dot(rng.randn(8, 4)).astype(np.float32)
x_nd = nd.array(X, ctx=ctxs[0])
y_nd = nd.array(Y, ctx=ctxs[0])
losses = []
for _ in range(3):
    losses.append(float(step.step(x_nd, y_nd).mean().asnumpy()))
c0 = engine.dispatch_count
step.step(x_nd, y_nd)
per_step = engine.dispatch_count - c0
assert step.compiled, step.fallback_reason
assert losses[-1] < losses[0], losses
assert per_step <= 2, per_step
assert trainer._kvstore._gc._residuals, "EF residual store never filled"
Xw, Yw = np.stack([X] * 4), np.stack([Y] * 4)
step.run_window(Xw, Yw)           # warm: the trace itself runs eager ops
snap0 = engine.snapshot()         # ONE consistent counter-group read
step.run_window(Xw, Yw)
snap1 = engine.snapshot()
assert snap1["dispatches"] - snap0["dispatches"] <= 2, snap1
assert snap1["compiled_steps"] - snap0["compiled_steps"] == 4, snap1
print("compiled_step_smoke: PASS losses=%s dispatches/step=%d"
      % (["%.4f" % l for l in losses], per_step))
EOF

echo "== chaos_smoke: two-replica serving - kill one mid-load (ISSUE 9)"
# two supervised serving replicas (health-gated via --hang-timeout +
# heartbeat beats from the batcher loop); the serve.request fault kills
# replica 0 mid-request ~45, the sticky client fails over to replica 1,
# the supervisor restarts replica 0, and the driver asserts: every one
# of its 100 requests got a CORRECT answer (zero lost in-flight), >=1
# failover happened, and both replicas serve again at the end.
SERVE_BASE=$("$PY" - <<'EOF'
import socket
while True:
    s1 = socket.socket(); s1.bind(("", 0)); p = s1.getsockname()[1]
    s2 = socket.socket()
    try:
        s2.bind(("", p + 1))
    except OSError:
        s1.close(); s2.close(); continue
    s1.close(); s2.close(); print(p); break
EOF
)
rc=0
# 100 requests with a crash every ~45 handled → at most 2 crashes
# fleet-wide, comfortably inside a 3-per-replica restart budget (the
# failed-over survivor can crash too — rolling chaos is the point)
PYTHONPATH="$REPO${PYTHONPATH:+:$PYTHONPATH}" \
"$PY" "$REPO/tools/launch.py" -n 2 --launcher local \
    --restart on-failure --max-restarts 3 --hang-timeout 30 \
    --fault 'serve.request:crash:after=45' -- \
    "$PY" -m mxnet_tpu.serve --demo --port-base "$SERVE_BASE" \
    > "$WORK/serve.log" 2>&1 &
LAUNCH_PID=$!
"$PY" "$REPO/tools/serve_load.py" \
    --addrs "127.0.0.1:$SERVE_BASE,127.0.0.1:$((SERVE_BASE+1))" \
    --requests 100 --chaos --stop 2>&1 \
    | tee "$WORK/serve_load.log" || rc=$?
if [ "$rc" -ne 0 ]; then
    echo "chaos_smoke: FAIL - serve load driver exited $rc" >&2
    kill "$LAUNCH_PID" 2>/dev/null || true
    cat "$WORK/serve.log" >&2 || true
    exit 1
fi
wait "$LAUNCH_PID" || rc=$?
if [ "$rc" -ne 0 ]; then
    echo "chaos_smoke: FAIL - serve launch.py exited $rc" >&2
    cat "$WORK/serve.log" >&2 || true
    exit 1
fi
grep -q 'restart 1/' "$WORK/serve.log" || {
    echo "chaos_smoke: FAIL - no serving replica was restarted" >&2
    exit 1
}
grep -q 'SERVE_LOAD_OK' "$WORK/serve_load.log" || {
    echo "chaos_smoke: FAIL - serve load driver never reported OK" >&2
    exit 1
}
echo "chaos_smoke: serving chaos PASS (failover + restart, zero lost)"

echo "== chaos_smoke: decode serving - kill a replica mid-generation (ISSUE 15/18)"
# two supervised PAGED decode replicas (GENERATE verb, continuous
# batching, shared page heap + hash-shared prefixes + chunked
# prefill); the serve.request fault kills a replica mid-load under the
# shared-prefix workload, in-flight generations fail over and
# RE-PREFILL on the survivor — as chunk trains, against the survivor's
# OWN hash table — and completed sequences replay from the
# exactly-once cache.  The driver verifies every sequence against a
# local reference decode of the same seeded demo LM — deterministic
# greedy decode means a re-prefilled generation must reproduce its
# tokens EXACTLY, so correctness (not just arrival) survives the crash
# whether the survivor answered from a CoW fork or a cold chunk train.
DECODE_BASE=$("$PY" - <<'EOF'
import socket
while True:
    s1 = socket.socket(); s1.bind(("", 0)); p = s1.getsockname()[1]
    s2 = socket.socket()
    try:
        s2.bind(("", p + 1))
    except OSError:
        s1.close(); s2.close(); continue
    s1.close(); s2.close(); print(p); break
EOF
)
rc=0
# 80 generations with a crash every ~50 handled requests: the first
# crash lands mid-load, and end-of-load per-replica counters stay well
# below the NEXT trip point so the driver's closing health probes and
# STOPs cannot themselves crash a replica into the assertion window
MX_SERVE_KV_PAGES=64 MX_SERVE_KV_PAGE_LEN=16 \
MX_SERVE_PREFIX_SHARE=1 MX_SERVE_PREFILL_CHUNK=16 \
PYTHONPATH="$REPO${PYTHONPATH:+:$PYTHONPATH}" \
"$PY" "$REPO/tools/launch.py" -n 2 --launcher local \
    --restart on-failure --max-restarts 3 --hang-timeout 60 \
    --fault 'serve.request:crash:after=50' -- \
    "$PY" -m mxnet_tpu.serve --decode --port-base "$DECODE_BASE" \
    > "$WORK/decode.log" 2>&1 &
DECODE_LAUNCH_PID=$!
"$PY" "$REPO/tools/serve_load.py" \
    --addrs "127.0.0.1:$DECODE_BASE,127.0.0.1:$((DECODE_BASE+1))" \
    --decode --requests 80 --shared-prefix 3 --chaos --stop 2>&1 \
    | tee "$WORK/decode_load.log" || rc=$?
if [ "$rc" -ne 0 ]; then
    echo "chaos_smoke: FAIL - decode load driver exited $rc" >&2
    kill "$DECODE_LAUNCH_PID" 2>/dev/null || true
    cat "$WORK/decode.log" >&2 || true
    exit 1
fi
wait "$DECODE_LAUNCH_PID" || rc=$?
if [ "$rc" -ne 0 ]; then
    echo "chaos_smoke: FAIL - decode launch.py exited $rc" >&2
    cat "$WORK/decode.log" >&2 || true
    exit 1
fi
grep -q 'restart 1/' "$WORK/decode.log" || {
    echo "chaos_smoke: FAIL - no decode replica was restarted" >&2
    exit 1
}
grep -q 'SERVE_LOAD_OK' "$WORK/decode_load.log" || {
    echo "chaos_smoke: FAIL - decode load driver never reported OK" >&2
    exit 1
}
grep -q 'paged: 64 pages' "$WORK/decode.log" || {
    echo "chaos_smoke: FAIL - decode replicas did not come up PAGED" >&2
    exit 1
}
echo "chaos_smoke: decode chaos PASS (paged failover + chunked" \
     "re-prefill under shared prefixes, sequences exact)"

echo "== chaos_smoke: speculative decode - kill a replica mid-window (ISSUE 20)"
# two supervised SPECULATIVE replicas (MX_SERVE_DRAFT spawns the
# draft/verify pair co-hosted on the paged heap); the serve.request
# fault kills one mid-load under the shared-prefix workload, so
# in-flight generations die between a draft tick and its verify and
# must fail over — the survivor re-prefills BOTH models (chunk train +
# draft-prefill sentinel) and resumes windowed decode.  The driver's
# oracle is the spec pair's TARGET (serve_load honors MX_SERVE_DRAFT),
# and speculative output is bit-identical to target greedy decode, so
# every recovered sequence must still match token for token.
SPEC_BASE=$("$PY" - <<'EOF'
import socket
while True:
    s1 = socket.socket(); s1.bind(("", 0)); p = s1.getsockname()[1]
    s2 = socket.socket()
    try:
        s2.bind(("", p + 1))
    except OSError:
        s1.close(); s2.close(); continue
    s1.close(); s2.close(); print(p); break
EOF
)
rc=0
MX_SERVE_DRAFT=1 MX_SERVE_SPEC_K=4 \
MX_SERVE_KV_PAGES=64 MX_SERVE_KV_PAGE_LEN=16 \
MX_SERVE_PREFIX_SHARE=1 MX_SERVE_PREFILL_CHUNK=16 \
PYTHONPATH="$REPO${PYTHONPATH:+:$PYTHONPATH}" \
"$PY" "$REPO/tools/launch.py" -n 2 --launcher local \
    --restart on-failure --max-restarts 3 --hang-timeout 60 \
    --fault 'serve.request:crash:after=50' -- \
    "$PY" -m mxnet_tpu.serve --decode --port-base "$SPEC_BASE" \
    > "$WORK/spec_decode.log" 2>&1 &
SPEC_LAUNCH_PID=$!
MX_SERVE_DRAFT=1 MX_SERVE_SPEC_K=4 \
"$PY" "$REPO/tools/serve_load.py" \
    --addrs "127.0.0.1:$SPEC_BASE,127.0.0.1:$((SPEC_BASE+1))" \
    --decode --requests 80 --shared-prefix 3 --chaos --stop 2>&1 \
    | tee "$WORK/spec_load.log" || rc=$?
if [ "$rc" -ne 0 ]; then
    echo "chaos_smoke: FAIL - speculative load driver exited $rc" >&2
    kill "$SPEC_LAUNCH_PID" 2>/dev/null || true
    cat "$WORK/spec_decode.log" >&2 || true
    exit 1
fi
wait "$SPEC_LAUNCH_PID" || rc=$?
if [ "$rc" -ne 0 ]; then
    echo "chaos_smoke: FAIL - speculative launch.py exited $rc" >&2
    cat "$WORK/spec_decode.log" >&2 || true
    exit 1
fi
grep -q 'restart 1/' "$WORK/spec_decode.log" || {
    echo "chaos_smoke: FAIL - no speculative replica was restarted" >&2
    exit 1
}
grep -q 'SERVE_LOAD_OK' "$WORK/spec_load.log" || {
    echo "chaos_smoke: FAIL - speculative load driver never reported OK" >&2
    exit 1
}
grep -q 'speculative: k=4 draft=demo-lm-draft' "$WORK/spec_decode.log" || {
    echo "chaos_smoke: FAIL - replicas did not come up SPECULATIVE" >&2
    exit 1
}
echo "chaos_smoke: speculative chaos PASS (draft+target failover" \
     "re-prefill, windowed sequences bit-exact)"

echo "== chaos_smoke: session router - kill a replica UNDER the router (ISSUE 17)"
# the fleet front-tier: one router address fronting two supervised
# decode replicas.  The serve.request fault kills a replica mid-load;
# the ROUTER absorbs the failover (re-pins the dead replica's sessions,
# re-prefills stragglers on the survivor) while the client keeps
# talking to the one address it knows.  Every GENERATE answer is
# verified against the local reference decode THROUGH the router —
# exactly-once end to end: a retry through the router must replay from
# the replica's cache, never burn a second prefill with different
# tokens.
ROUTER_BASE=$("$PY" - <<'EOF'
import socket
while True:
    s1 = socket.socket(); s1.bind(("", 0)); p = s1.getsockname()[1]
    s2 = socket.socket()
    try:
        s2.bind(("", p + 1))
    except OSError:
        s1.close(); s2.close(); continue
    s1.close(); s2.close(); print(p); break
EOF
)
ROUTER_PORT=$("$PY" - <<'EOF'
import socket
s = socket.socket(); s.bind(("", 0)); print(s.getsockname()[1]); s.close()
EOF
)
rc=0
PYTHONPATH="$REPO${PYTHONPATH:+:$PYTHONPATH}" \
"$PY" "$REPO/tools/launch.py" -n 2 --launcher local \
    --restart on-failure --max-restarts 3 --hang-timeout 60 \
    --serve-port-base "$ROUTER_BASE" --route "$ROUTER_PORT" \
    --fault 'serve.request:crash:after=50' -- \
    "$PY" -m mxnet_tpu.serve --decode --port-base "$ROUTER_BASE" \
    > "$WORK/router.log" 2>&1 &
ROUTER_LAUNCH_PID=$!
# the router binds instantly but decode replicas bind only once warm —
# wait for the REPLICA ports too, or the first routed request spends
# its whole retry deadline probing a fleet that isn't up yet
"$PY" - "$ROUTER_BASE" <<'EOF'
import socket, sys, time
base = int(sys.argv[1])
for port in (base, base + 1):
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        try:
            socket.create_connection(("127.0.0.1", port),
                                     timeout=0.5).close()
            break
        except OSError:
            time.sleep(0.2)
    else:
        raise SystemExit("replica on %d never came up" % port)
EOF
"$PY" "$REPO/tools/serve_load.py" \
    --addrs "127.0.0.1:$ROUTER_PORT" --routed \
    --decode --requests 100 --chaos --stop 2>&1 \
    | tee "$WORK/router_load.log" || rc=$?
if [ "$rc" -ne 0 ]; then
    echo "chaos_smoke: FAIL - routed load driver exited $rc" >&2
    kill "$ROUTER_LAUNCH_PID" 2>/dev/null || true
    cat "$WORK/router.log" >&2 || true
    exit 1
fi
wait "$ROUTER_LAUNCH_PID" || rc=$?
if [ "$rc" -ne 0 ]; then
    echo "chaos_smoke: FAIL - routed launch.py exited $rc" >&2
    cat "$WORK/router.log" >&2 || true
    exit 1
fi
grep -q 'restart 1/' "$WORK/router.log" || {
    echo "chaos_smoke: FAIL - no replica was restarted under the router" >&2
    exit 1
}
grep -q 'SERVE_LOAD_OK' "$WORK/router_load.log" || {
    echo "chaos_smoke: FAIL - routed load driver never reported OK" >&2
    exit 1
}
echo "chaos_smoke: router chaos PASS (replica killed, router absorbed it, 100/100 exact)"

echo "== chaos_smoke: session router - kill the ROUTER itself mid-load (ISSUE 17)"
# router-targeted fault burst: the router.request crash site kills the
# front tier mid-request.  The supervisor restarts it; the client fails
# over (reconnect + SEQ replay through the fresh router), the replicas'
# replay caches dedupe anything already dispatched — 100/100 verified
# answers with zero double-dispatches.
RB2=$("$PY" - <<'EOF'
import socket
while True:
    s1 = socket.socket(); s1.bind(("", 0)); p = s1.getsockname()[1]
    s2 = socket.socket()
    try:
        s2.bind(("", p + 1))
    except OSError:
        s1.close(); s2.close(); continue
    s1.close(); s2.close(); print(p); break
EOF
)
RP2=$("$PY" - <<'EOF'
import socket
s = socket.socket(); s.bind(("", 0)); print(s.getsockname()[1]); s.close()
EOF
)
rc=0
PYTHONPATH="$REPO${PYTHONPATH:+:$PYTHONPATH}" \
"$PY" "$REPO/tools/launch.py" -n 2 --launcher local \
    --restart on-failure --max-restarts 3 --hang-timeout 60 \
    --serve-port-base "$RB2" --route "$RP2" \
    --fault 'router.request:crash:after=60' -- \
    "$PY" -m mxnet_tpu.serve --demo --port-base "$RB2" \
    > "$WORK/router2.log" 2>&1 &
ROUTER2_LAUNCH_PID=$!
"$PY" - "$RB2" <<'EOF'
import socket, sys, time
base = int(sys.argv[1])
for port in (base, base + 1):
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        try:
            socket.create_connection(("127.0.0.1", port),
                                     timeout=0.5).close()
            break
        except OSError:
            time.sleep(0.2)
    else:
        raise SystemExit("replica on %d never came up" % port)
EOF
"$PY" "$REPO/tools/serve_load.py" \
    --addrs "127.0.0.1:$RP2" --routed \
    --requests 100 --chaos --stop 2>&1 \
    | tee "$WORK/router2_load.log" || rc=$?
if [ "$rc" -ne 0 ]; then
    echo "chaos_smoke: FAIL - router-kill load driver exited $rc" >&2
    kill "$ROUTER2_LAUNCH_PID" 2>/dev/null || true
    cat "$WORK/router2.log" >&2 || true
    exit 1
fi
wait "$ROUTER2_LAUNCH_PID" || rc=$?
if [ "$rc" -ne 0 ]; then
    echo "chaos_smoke: FAIL - router-kill launch.py exited $rc" >&2
    cat "$WORK/router2.log" >&2 || true
    exit 1
fi
grep -q 'restart 1/' "$WORK/router2.log" || {
    echo "chaos_smoke: FAIL - the router was never restarted" >&2
    exit 1
}
grep -q 'SERVE_LOAD_OK' "$WORK/router2_load.log" || {
    echo "chaos_smoke: FAIL - router-kill load never reported OK" >&2
    exit 1
}
echo "chaos_smoke: router-kill chaos PASS (front tier restarted, 100/100 exact)"

echo "== chaos_smoke: autoscaler - 4x Poisson spike absorbed, drains back (ISSUE 17)"
# SLO-burn autoscaler: 1-3 replicas behind the router, a 1ms p99 target
# any sustained traffic breaches.  The Poisson spike must burn the SLO
# -> spawn(s) observed while EVERY answer stays verified-correct; once
# the spike ends the rolling window ages out, burn drops under the
# scale-down band, and the newest replica retires DRAIN-not-kill.
AS_BASE=$("$PY" - <<'EOF'
import socket
while True:
    s1 = socket.socket(); s1.bind(("", 0)); p = s1.getsockname()[1]
    ss = []
    try:
        for off in (1, 2):
            s = socket.socket(); s.bind(("", p + off)); ss.append(s)
    except OSError:
        s1.close(); [s.close() for s in ss]; continue
    s1.close(); [s.close() for s in ss]; print(p); break
EOF
)
AS_PORT=$("$PY" - <<'EOF'
import socket
s = socket.socket(); s.bind(("", 0)); print(s.getsockname()[1]); s.close()
EOF
)
rc=0
PYTHONPATH="$REPO${PYTHONPATH:+:$PYTHONPATH}" \
MX_FLEET_INTERVAL=0.5 MX_FLEET_SLO_P99_MS=1 \
MX_AUTOSCALE_HOLD=2 MX_AUTOSCALE_COOLDOWN=1 \
"$PY" "$REPO/tools/launch.py" -n 1 --launcher local \
    --restart on-failure --hang-timeout 60 \
    --serve-port-base "$AS_BASE" --route "$AS_PORT" --autoscale 1:3 -- \
    "$PY" -m mxnet_tpu.serve --demo --port-base "$AS_BASE" \
    > "$WORK/autoscale.log" 2>&1 &
AS_LAUNCH_PID=$!
"$PY" - "$AS_BASE" <<'EOF'
import socket, sys, time
port = int(sys.argv[1])
deadline = time.monotonic() + 120
while time.monotonic() < deadline:
    try:
        socket.create_connection(("127.0.0.1", port), timeout=0.5).close()
        break
    except OSError:
        time.sleep(0.2)
else:
    raise SystemExit("replica on %d never came up" % port)
EOF
# the 4x spike: open-loop Poisson arrivals at 40/s vs the 10/s baseline
# trickle, all through the router, every answer verified
"$PY" "$REPO/tools/serve_load.py" \
    --addrs "127.0.0.1:$AS_PORT" --routed \
    --requests 30 --poisson 10 > "$WORK/as_baseline.log" 2>&1 || rc=$?
if [ "$rc" -ne 0 ]; then
    echo "chaos_smoke: FAIL - autoscaler baseline load exited $rc" >&2
    kill "$AS_LAUNCH_PID" 2>/dev/null || true
    cat "$WORK/autoscale.log" >&2 || true
    exit 1
fi
"$PY" "$REPO/tools/serve_load.py" \
    --addrs "127.0.0.1:$AS_PORT" --routed \
    --requests 240 --poisson 40 2>&1 \
    | tee "$WORK/as_spike.log" || rc=$?
if [ "$rc" -ne 0 ]; then
    echo "chaos_smoke: FAIL - autoscaler spike load exited $rc" >&2
    kill "$AS_LAUNCH_PID" 2>/dev/null || true
    cat "$WORK/autoscale.log" >&2 || true
    exit 1
fi
# spike over: wait for the scale-down (window ages out -> burn ~0 ->
# hold -> drain-not-kill retire), then stop the fleet
for _i in $(seq 1 120); do
    grep -q 'drain-not-kill' "$WORK/autoscale.log" && break
    sleep 0.5
done
"$PY" "$REPO/tools/serve_load.py" \
    --addrs "127.0.0.1:$AS_PORT" --routed \
    --requests 0 --stop > "$WORK/as_stop.log" 2>&1 || true
wait "$AS_LAUNCH_PID" || rc=$?
if [ "$rc" -ne 0 ]; then
    echo "chaos_smoke: FAIL - autoscaler launch.py exited $rc" >&2
    cat "$WORK/autoscale.log" >&2 || true
    exit 1
fi
grep -q 'autoscale: .* spawning' "$WORK/autoscale.log" || {
    echo "chaos_smoke: FAIL - the spike never spawned a replica" >&2
    cat "$WORK/autoscale.log" >&2 || true
    exit 1
}
grep -q 'drain-not-kill' "$WORK/autoscale.log" || {
    echo "chaos_smoke: FAIL - the fleet never drained back down" >&2
    cat "$WORK/autoscale.log" >&2 || true
    exit 1
}
grep -q 'SERVE_LOAD_OK' "$WORK/as_spike.log" || {
    echo "chaos_smoke: FAIL - spike load never reported OK" >&2
    exit 1
}
echo "chaos_smoke: autoscaler PASS (spike spawned, drained back, all answers exact)"

echo "== chaos_smoke: serve dispatch budgets (1/batch, 1/decode step, +0 routed, spec window k+1)"
"$PY" "$REPO/tools/dispatch_count.py" --serve --decode --routed \
    --speculative > "$WORK/serve_budget.json"
"$PY" - "$WORK/serve_budget.json" <<'EOF'
import json, sys
r = json.load(open(sys.argv[1]))
assert r["serve"]["ok"], r["serve"]
assert r["decode"]["ok"], r["decode"]
assert r["routed"]["ok"], r["routed"]
assert r["speculative"]["ok"], r["speculative"]
print("serve budget: %(dispatches)d dispatches / %(batches)d batches, "
      "%(retraces)d retraces" % r["serve"])
print("decode budget: %(dispatches)d dispatches = %(prefill_dispatches)d "
      "prefills + %(decode_steps)d steps, %(retraces)d retraces"
      % r["decode"])
print("routed budget: %(routed_dispatches)d dispatches routed == "
      "%(direct_dispatches)d direct (+%(extra_dispatches)d), "
      "%(routed_retraces)d retraces" % r["routed"])
print("speculative budget: %(sequential_dispatches)d dispatches == "
      "%(expected_sequential)d planned (k=%(spec_k)d windows exact), "
      "%(retraces)d retraces" % r["speculative"])
EOF

echo "== chaos_smoke: fleet telemetry plane - kill a replica + a worker mid-load (ISSUE 12)"
"$PY" - "$REPO" "$WORK" <<'EOF'
import json, os, socket, subprocess, sys, threading, time
sys.path.insert(0, sys.argv[1])
WORK = sys.argv[2]
import numpy as np
from mxnet_tpu import fleet, telemetry
from mxnet_tpu.serve import ServeClient, ServeServer, Servable, serve_forever
from mxnet_tpu.serve.demo import DEMO_IN, demo_block, demo_example

def free_port():
    s = socket.socket(); s.bind(("", 0)); p = s.getsockname()[1]; s.close()
    return p

# two in-process serve replicas (separate ports; the abort_event on
# replica 0 is the in-process stand-in for a kill)
replicas = []
for i in range(2):
    port = free_port()
    state = ServeServer()
    state.host.deploy(Servable(demo_block(), name="demo-mlp", version=1),
                      example=demo_example())
    stop_ev, abort_ev = threading.Event(), threading.Event()
    threading.Thread(target=serve_forever,
                     kwargs=dict(port=port, state=state, stop_event=stop_ev,
                                 abort_event=abort_ev), daemon=True).start()
    replicas.append(("127.0.0.1:%d" % port, stop_ev, abort_ev))
for addr, _s, _a in replicas:
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        try:
            socket.create_connection(tuple([addr.split(":")[0],
                                            int(addr.split(":")[1])]),
                                     timeout=0.2).close()
            break
        except OSError:
            time.sleep(0.05)

# two fake training workers beating heartbeat files; rank 1 is 3x slow
hb_dir = os.path.join(WORK, "fleet-hb"); os.makedirs(hb_dir, exist_ok=True)
def beat(rank, step, sps, data_wait):
    path = os.path.join(hb_dir, "rank_%d" % rank)
    payload = {"schema": 1, "step": step, "steps_per_sec": sps,
               "phases": {"forward": 0.05, "data_wait": data_wait}}
    with open(path, "w") as f:
        f.write("%f 0 %d\n%s\n" % (time.time(), step, json.dumps(payload)))
beat(0, 10, 10.0, 0.01); beat(1, 4, 3.3, 0.22)

members = [fleet.FleetMember("serve", i, addr=a)
           for i, (a, _s, _ab) in enumerate(replicas)]
members += [fleet.FleetMember("worker", r,
                              heartbeat=os.path.join(hb_dir, "rank_%d" % r))
            for r in (0, 1)]
coll = fleet.FleetCollector(members, interval=0.2, stale_after=0.5,
                            scrape_timeout=1.0,
                            slo_targets={"rejection_rate": 0.01})

# drive some load so the serve histograms have mass
cli = ServeClient([replicas[0][0]], timeout=10)
x = np.zeros((1, DEMO_IN), np.float32)
for _ in range(10): cli.predict([x])
cli.close()
m = coll.scrape_once()
assert all(mm["present"] for mm in m["members"].values()), m["members"]

# straggler: the 3x-slow rank is named within 2 windows
for _ in range(2):
    m = coll.scrape_once()
names = [f["member"] for f in m["stragglers"]]
assert names == ["worker:1"], m["stragglers"]
assert m["stragglers"][0]["dominant_phase"] == "data_wait"

# fleet_top --once renders off the FLEET wire verb (straggler visible)
srv = fleet.serve_fleet(coll, 0)
addr = "127.0.0.1:%d" % srv.server_address[1]
out = subprocess.run(
    [sys.executable, os.path.join(sys.argv[1], "tools", "fleet_top.py"),
     "--fleet", addr, "--once"],
    capture_output=True, text=True, timeout=60)
assert out.returncode == 0, out.stderr
assert "serve:1" in out.stdout and "STRAGGLER" in out.stdout, out.stdout
srv.shutdown(); srv.server_close()

# forced rejection spike trips the SLO burn + latch
telemetry.registry.counter("serve.rejected").inc(50)
m = coll.scrape_once()
assert m["slo"]["burn"]["rejection_rate"] > 1.0, m["slo"]
assert "rejection_rate" in m["slo"]["breached"], m["slo"]

# kill replica 0 and silence worker 1: both absent within one interval
before = m["counters"]["serve.requests"]["total"]
replicas[0][2].set()          # sever the replica's listener + conns
time.sleep(0.6)               # > stale_after: worker 1's beat goes stale
beat(0, 20, 10.0, 0.01)       # survivor keeps beating
m = coll.scrape_once()
assert not m["members"]["serve:0"]["present"], m["members"]["serve:0"]
assert not m["members"]["worker:1"]["present"], m["members"]["worker:1"]
assert m["members"]["serve:1"]["present"] and m["members"]["worker:0"]["present"]

# survivors' crash-free rollups keep advancing
cli = ServeClient([replicas[1][0]], timeout=10)
for _ in range(5): cli.predict([x])
cli.close()
m = coll.scrape_once()
assert m["counters"]["serve.requests"]["total"] > before, \
    (m["counters"]["serve.requests"], before)
# the latched breach survives the healthy rounds
assert "rejection_rate" in m["slo"]["breached"], m["slo"]
for _a, stop_ev, ab in replicas:
    stop_ev.set()
print("fleet_smoke: PASS (absent within one scrape, straggler named, "
      "SLO latched, survivors advancing, fleet_top renders)")
EOF

echo "== chaos_smoke: warm respawn — persistent compile cache (ISSUE 13)"
# kill-and-respawn with MX_COMPILE_CACHE (via launch.py --compile-cache):
# the respawned worker must deserialize its step programs — the DONE
# receipt line carries cache_hits and the compile wall-time actually
# paid — and a respawned serve replica must warm its whole bucket table
# from hits while serving correct answers.
CACHE="$WORK/ccache"
rc=0
MX_STEP_COMPILE=1 "$PY" "$REPO/tools/launch.py" -n 1 --launcher local \
    --restart on-failure --max-restarts 2 --compile-cache "$CACHE" \
    --fault 'worker.step:crash:after=5' -- \
    "$PY" "$REPO/tools/chaos_fit.py" \
    --ckpt-dir "$WORK/warm-ckpt" --out "$WORK/warm" 2>&1 \
    | tee "$WORK/warm.log" || rc=$?
if [ "$rc" -ne 0 ]; then
    echo "chaos_smoke: FAIL - warm-respawn launch.py exited $rc" >&2
    exit 1
fi
grep -q 'restart 1/' "$WORK/warm.log" || {
    echo "chaos_smoke: FAIL - warm-respawn: no restart happened" >&2
    exit 1
}
"$PY" - "$WORK/warm.log" <<'EOF'
import re, sys
log = open(sys.argv[1]).read()
done = re.findall(r"CHAOS_FIT_DONE rank \S+ cache_hits=(\d+) "
                  r"cache_misses=(\d+) compile_seconds=([\d.]+)", log)
assert done, "no warm-respawn DONE receipt in log"
hits, _misses, comp = done[-1]
# the crashed first incarnation populated the store; the incarnation
# that FINISHED (the respawn) must have warm-started from it
assert int(hits) >= 1, "respawned worker reported no cache hits: %s" % (done,)
assert float(comp) < 1.0, \
    "respawned worker compile_seconds=%s >= 1s" % comp
print("warm respawn worker: PASS (hits=%s, compile %ss < 1s)" % (hits, comp))
EOF

# serve replica warm respawn: same cache flag, crash mid-load; the
# respawn banner itself carries the receipts, and every answer the
# driver got must still be CORRECT
WARM_BASE=$("$PY" - <<'EOF'
import socket
while True:
    s1 = socket.socket(); s1.bind(("", 0)); p = s1.getsockname()[1]
    s2 = socket.socket()
    try:
        s2.bind(("", p + 1))
    except OSError:
        s1.close(); s2.close(); continue
    s1.close(); s2.close(); print(p); break
EOF
)
rc=0
PYTHONPATH="$REPO${PYTHONPATH:+:$PYTHONPATH}" \
"$PY" "$REPO/tools/launch.py" -n 2 --launcher local \
    --restart on-failure --max-restarts 3 --hang-timeout 30 \
    --compile-cache "$CACHE" \
    --fault 'serve.request:crash:after=45' -- \
    "$PY" -m mxnet_tpu.serve --demo --port-base "$WARM_BASE" \
    > "$WORK/warm_serve.log" 2>&1 &
WARM_LAUNCH_PID=$!
"$PY" "$REPO/tools/serve_load.py" \
    --addrs "127.0.0.1:$WARM_BASE,127.0.0.1:$((WARM_BASE+1))" \
    --requests 100 --chaos --stop 2>&1 \
    | tee "$WORK/warm_serve_load.log" || rc=$?
if [ "$rc" -ne 0 ]; then
    echo "chaos_smoke: FAIL - warm serve load driver exited $rc" >&2
    kill "$WARM_LAUNCH_PID" 2>/dev/null || true
    cat "$WORK/warm_serve.log" >&2 || true
    exit 1
fi
wait "$WARM_LAUNCH_PID" || rc=$?
if [ "$rc" -ne 0 ]; then
    echo "chaos_smoke: FAIL - warm serve launch.py exited $rc" >&2
    cat "$WORK/warm_serve.log" >&2 || true
    exit 1
fi
grep -q 'SERVE_LOAD_OK' "$WORK/warm_serve_load.log" || {
    echo "chaos_smoke: FAIL - warm serve load never reported OK" >&2
    exit 1
}
"$PY" - "$WORK/warm_serve.log" <<'EOF'
import re, sys
log = open(sys.argv[1]).read()
banners = re.findall(r"warm on (\d+) bucket\(s\).* in ([\d.]+)s "
                     r"\(compile-cache hits=(\d+) misses=(\d+)\)", log)
assert len(banners) >= 3, \
    "expected 2 cold + >=1 respawn banner, got %r" % (banners,)
buckets = int(banners[0][0])
warm = [b for b in banners if int(b[2]) >= buckets]
assert warm, "no respawned replica warmed from cache hits: %r" % (banners,)
assert any(float(b[1]) < 1.0 for b in warm), \
    "no warm respawn deployed in <1s: %r" % (warm,)
print("warm respawn serve: PASS (%d respawn banner(s) with hits>=%d, "
      "fastest warm deploy %.2fs)"
      % (len(warm), buckets, min(float(b[1]) for b in warm)))
EOF
echo "chaos_smoke: warm respawn PASS (worker + serve replica came back warm)"

echo "== chaos_smoke: sharded dryrun — 3-step dp×fsdp SpecLayout fit (ISSUE 14)"
# The FSDP lane end-to-end on a fake 8-device mesh: a SpecLayout-sharded
# CompiledStep must (a) run 3 steps as one-donated-jit dispatches within
# the <=2/step budget, (b) match the replicated trajectory, and (c) cut
# per-chip params+optimizer bytes ~linearly with the fsdp axis.
PYTHONPATH="$REPO${PYTHONPATH:+:$PYTHONPATH}" \
XLA_FLAGS="--xla_force_host_platform_device_count=8" "$PY" - <<'EOF'
import gc
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import gluon, nd, programs
from mxnet_tpu.engine import engine
from mxnet_tpu.gluon import nn
from mxnet_tpu.parallel import SpecLayout, make_mesh

rng = np.random.RandomState(0)
X = rng.randn(16, 8).astype(np.float32)
Y = rng.randn(16, 4).astype(np.float32)
LOSS = gluon.loss.L2Loss()

def run(layout, ctxs=None):
    gc.collect()
    before = programs.buffer_census()
    mx.random.seed(0)
    net = nn.Sequential()
    net.add(nn.Dense(32, in_units=8, activation="relu"),
            nn.Dense(4, in_units=32))
    net.initialize(mx.init.Xavier(), ctx=ctxs)
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05, "momentum": 0.9},
                       kvstore="ici",
                       compression_params={"type": "int8"})
    step = tr.make_compiled_step(net, LOSS, layout=layout)
    losses = []
    dispatches = []
    for _ in range(3):
        c0 = engine.snapshot()["dispatches"]
        loss = step.step(nd.array(X), nd.array(Y), batch_size=16)
        dispatches.append(engine.snapshot()["dispatches"] - c0)
        losses.append(float(np.mean(loss.asnumpy())))   # host-side mean
    assert step.compiled, step.fallback_reason
    gc.collect()
    after = programs.buffer_census()
    chip = sum(max(0, after[o]["bytes_per_chip"]
                   - before[o]["bytes_per_chip"])
               for o in ("params", "optimizer_state"))
    return losses, dispatches, chip

# replicated twin: the classic 2-device-copy trainer with the SAME
# quantized ici exchange — the sharded reduce-scatter lane must match
# its trajectory exactly
ref, _d, repl_bytes = run(None, ctxs=[mx.cpu(0), mx.cpu(1)])
mesh = make_mesh(axes=("data", "fsdp"), shape=(-1, 2))
got, disp, chip_bytes = run(SpecLayout.infer(mesh))
assert all(np.isfinite(ref)) and got[-1] < got[0], (ref, got)
np.testing.assert_allclose(ref, got, rtol=2e-4)
assert max(disp[1:]) <= 2, "sharded step over dispatch budget: %s" % disp
# the replicated twin keeps TWO full device copies of params+state; the
# fsdp=2 lane keeps one half-sheet per chip -> ideal 2*2=4x per chip
ratio = repl_bytes / max(1, chip_bytes)
assert ratio >= 0.85 * 4, \
    "per-chip state drop %.2fx outside 15%% of ideal 4x" % ratio
print("sharded_dryrun: PASS (int8 dp*fsdp loss %.4f -> %.4f == "
      "replicated 2-copy trajectory, %d dispatches/step, per-chip "
      "state %.2fx smaller)" % (got[0], got[-1], max(disp[1:]), ratio))
EOF

echo "== chaos_smoke: elastic membership - resize mid-fit + budget shrink (ISSUE 16)"
# The canonical elastic-resize chaos tests live in tests/test_elastic.py:
# grow 2->4 and shrink 4->3 mid-fit through `launch.py --elastic
# --resize-file` with final-param parity against an uninterrupted run,
# plus a rank SIGKILLed past --max-restarts retiring (shrink-and-continue,
# exit 0) instead of failing the job.  Run the WHOLE file here — the
# slow-marked CLI acceptance tests included (tier-1 only runs the fast
# in-process ones).
"$PY" -m pytest "$REPO/tests/test_elastic.py" -q \
    -p no:cacheprovider -p no:randomly
echo "chaos_smoke: elastic PASS (grow 2->4, shrink 4->3, SIGKILL shrink-and-continue)"

echo "== chaos_smoke: wire-protocol verifier has teeth (ISSUE 19)"
# The --protocol lane must (a) pass on the shipped tree — lint.sh below
# runs the real CLI with the pinned schedule count — and (b) actually
# trip when a protocol fault is injected.  Reinject the classic one
# in-memory (drop GENERATE from the serve replay cache: a retried
# generation would re-decode instead of replaying) and assert the lane
# catches it; the full quad lives in tests/test_protocol.py.
"$PY" - <<'EOF'
import os
from tools.mxlint import protocol

repo = os.getcwd()
sources = {}
for fp in protocol.iter_py_files([os.path.join(repo, "mxnet_tpu")]):
    rel = os.path.relpath(fp, repo).replace(os.sep, "/")
    sources[rel] = open(fp, encoding="utf-8").read()
diags, stats = protocol.check_sources(sources)
assert not diags, "shipped tree must be clean: %r" % [
    (d.rule, d.path, d.line) for d in diags]

mut = sources["mxnet_tpu/serve/server.py"].replace(
    '_CACHED = ("PREDICT", "SWAP", "GENERATE")',
    '_CACHED = ("PREDICT", "SWAP")')
assert mut != sources["mxnet_tpu/serve/server.py"], "anchor drifted"
sources["mxnet_tpu/serve/server.py"] = mut
diags, _ = protocol.check_sources(sources)
rules = sorted({d.rule for d in diags})
assert "protocol-replay-class" in rules, rules
print("chaos_smoke: protocol verifier PASS (clean tree certifies; "
      "injected replay-set hole trips %s)" % rules)
EOF

echo "== chaos_smoke: static-analysis lane (tools/lint.sh)"
bash "$REPO/tools/lint.sh"

echo "chaos_smoke: PASS"
