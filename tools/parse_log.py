"""Training-log parser: epoch/accuracy/speed table from fit-style logs.

Reference: ``tools/parse_log.py`` — scrapes `Epoch[N] ... accuracy=X` and
`Speed: Y samples/sec` lines (the Speedometer/fit logging format this
rebuild's mx.callback.Speedometer and Module.fit emit) into a summary
table/CSV.

Run:  python tools/parse_log.py train.log [--format csv|table]
"""
import argparse
import re
import sys
from collections import defaultdict

_EPOCH = re.compile(r"Epoch\[(\d+)\]")
_METRIC = re.compile(r"(\w[\w-]*)=([0-9.eE+-]+)")
_SPEED = re.compile(r"Speed[:=]\s*([0-9.]+)\s*samples/sec")
_TIME = re.compile(r"Time cost[:=]\s*([0-9.]+)")


def parse(lines):
    epochs = defaultdict(dict)
    for line in lines:
        m = _EPOCH.search(line)
        if not m:
            continue
        e = int(m.group(1))
        rec = epochs[e]
        sp = _SPEED.search(line)
        if sp:
            rec.setdefault("speeds", []).append(float(sp.group(1)))
        tc = _TIME.search(line)
        if tc:
            rec["time"] = float(tc.group(1))
        is_val = "Validation" in line
        for name, val in _METRIC.findall(line):
            if name in ("Speed", "Time", "cost"):
                continue
            # fit logs write Train-accuracy=/Validation-accuracy=;
            # Speedometer batch lines write bare accuracy=
            if name.startswith("Validation-"):
                key = "val-" + name[len("Validation-"):]
            elif name.startswith("Train-"):
                key = "train-" + name[len("Train-"):]
            else:
                key = ("val-" if is_val else "train-") + name
            try:
                rec[key] = float(val)
            except ValueError:
                pass
    return dict(epochs)


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("logfile")
    p.add_argument("--format", default="table", choices=["table", "csv"])
    args = p.parse_args()
    with open(args.logfile) as f:
        epochs = parse(f)
    if not epochs:
        print("no Epoch[N] lines found", file=sys.stderr)
        return 1
    cols = sorted({k for rec in epochs.values() for k in rec
                   if k != "speeds"})
    header = ["epoch"] + cols + ["avg-speed"]
    rows = []
    for e in sorted(epochs):
        rec = epochs[e]
        speeds = rec.get("speeds", [])
        avg = sum(speeds) / len(speeds) if speeds else ""
        rows.append([e] + [rec.get(c, "") for c in cols] +
                    [round(avg, 2) if avg else ""])
    if args.format == "csv":
        print(",".join(str(h) for h in header))
        for r in rows:
            print(",".join(str(x) for x in r))
    else:
        widths = [max(len(str(h)), *(len(str(r[i])) for r in rows))
                  for i, h in enumerate(header)]
        print("  ".join(str(h).ljust(w) for h, w in zip(header, widths)))
        for r in rows:
            print("  ".join(str(x).ljust(w) for x, w in zip(r, widths)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
