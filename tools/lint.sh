#!/usr/bin/env bash
# lint.sh — the static-analysis lane as one CLI smoke (chaos_smoke.sh's
# sibling; the builder loop runs the same checks inside tier-1 via
# tests/test_mxlint.py).
#
#   1. mxlint over mxnet_tpu/ — the TPU-invariant rule set (host syncs in
#      the hot path, jit purity, wall clocks in fault paths, the MX_* env
#      registry, donation-after-use) with the checked-in baseline.
#   2. gen_env_docs --check — docs/ENV_VARS.md must match base.ENV_CATALOG
#      and every MX_* read in mxnet_tpu/ + tools/ must be cataloged.
#
# Exit nonzero on any new violation.  To suppress a justified hit, append
# `# mxlint: disable=<rule-id>` to the line; to re-baseline after review,
# run `python -m tools.mxlint --write-baseline mxnet_tpu/`.
set -euo pipefail

REPO="$(cd "$(dirname "$0")/.." && pwd)"
cd "$REPO"
PY="${PYTHON:-python3}"

echo "== lint: mxlint (tools/mxlint, baseline $(
    "$PY" -c 'import json;print(len(json.load(open("tools/mxlint/baseline.json"))["entries"]))' 2>/dev/null || echo 0) entries)"
"$PY" -m tools.mxlint mxnet_tpu/

echo "== lint: env-var doc consistency (tools/gen_env_docs.py --check)"
"$PY" tools/gen_env_docs.py --check

echo "lint: PASS"
