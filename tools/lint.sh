#!/usr/bin/env bash
# lint.sh — the static-analysis lane as one CLI smoke (chaos_smoke.sh's
# sibling; the builder loop runs the same checks inside tier-1 via
# tests/test_mxlint.py).
#
#   1. mxlint over mxnet_tpu/ (incl. telemetry.py — span helpers are
#      hot-path roots) + tools/launch.py + tools/telemetry_dump.py —
#      the per-file TPU-invariant rules (host syncs in the hot path, jit
#      purity, wall clocks in fault paths, the MX_* env registry,
#      donation-after-use)
#      PLUS the whole-program concurrency rules (unguarded-shared-write,
#      inconsistent-guard, lock-order-cycle, blocking-wait-unbounded,
#      thread-leak) with the checked-in baseline; also asserts the
#      runtime's static lock-acquisition graph stays acyclic.
#   2. gen_env_docs --check — docs/ENV_VARS.md must match base.ENV_CATALOG
#      and every MX_* read in mxnet_tpu/ + tools/ must be cataloged.
#
# Exit nonzero on any new violation.  To suppress a justified hit, append
# `# mxlint: disable=<rule-id>` to the line (for a two-site concurrency
# finding: on the WRITE site, where it anchors); to re-baseline after
# review, run `python -m tools.mxlint --write-baseline` (every
# concurrency entry needs a `why` justification — docs/TESTING.md §5).
set -euo pipefail

REPO="$(cd "$(dirname "$0")/.." && pwd)"
cd "$REPO"
PY="${PYTHON:-python3}"

echo "== lint: mxlint (tools/mxlint, baseline $(
    "$PY" -c 'import json;print(len(json.load(open("tools/mxlint/baseline.json"))["entries"]))' 2>/dev/null || echo 0) entries)"
# ONE json run carries both the violation exit contract and the lock
# graph; the checker re-prints violations textually and fails on a
# cyclic graph
rc=0
out="$("$PY" -m tools.mxlint --format json --jobs 4)" || rc=$?
if [ "$rc" -ge 2 ] || [ -z "$out" ]; then
    echo "lint: mxlint internal/usage error (exit $rc)" >&2
    exit 2
fi
MXLINT_JSON="$out" "$PY" - "$rc" <<'PYEOF'
import json, os, sys
rc = int(sys.argv[1])
payload = json.loads(os.environ["MXLINT_JSON"])
for v in payload["violations"]:
    print("%(path)s:%(line)d: %(rule)s: %(message)s" % v)
g = payload["lock_graph"]
print("lock-acquisition graph (%s):" %
      ("acyclic" if g["acyclic"] else "CYCLIC"))
for e in g["edges"]:
    print("   " + e)
sys.exit(rc or (0 if g["acyclic"] else 1))
PYEOF

echo "== lint: env-var doc consistency (tools/gen_env_docs.py --check)"
"$PY" tools/gen_env_docs.py --check

echo "== lint: wire-protocol verifier (python -m tools.mxlint --protocol)"
# altitude 4 (ISSUE 19): per-verb effect summaries + exhaustive bounded
# fault-schedule model checking of the exactly-once layer.  Never
# baselined — a finding here is fix-now or suppress-at-line-with-why.
# The schedule count is pinned: the checker is deterministic (virtual
# clock, no sockets, sorted enumeration), so a drift in the count means
# a machine/verb/SEQ-shape change that must be reviewed (and the doc
# regenerated).  Wall budget <60s like the contracts lane (measured ~4s).
proto_out="$(timeout -k 10 60 "$PY" -m tools.mxlint --protocol)"
echo "$proto_out"
echo "$proto_out" | grep -q "737 fault schedule(s) checked" || {
    echo "lint: protocol fault-schedule count drifted from the pinned 737" \
         "— review the machine change, then repin here and in" \
         "tests/test_protocol.py" >&2
    exit 1
}

echo "== lint: wire-protocol doc consistency (tools/gen_wire_docs.py --check)"
"$PY" tools/gen_wire_docs.py --check

echo "== lint: bench-history schema (tools/bench_compare.py --check-schema)"
"$PY" tools/bench_compare.py --check-schema

echo "== lint: program contracts (python -m tools.mxlint --contracts)"
# device-free donation/HBM/trace-closure proofs (ISSUE 11): lowers every
# contracted jit program under JAX_PLATFORMS=cpu and prints the
# per-program budget table.  Wall-time budget: the lane must stay a
# CI-speed check (<60s CPU; measured ~4s), so a hung lowering fails
# loudly instead of stalling the pipeline.
timeout -k 10 60 env JAX_PLATFORMS=cpu "$PY" -m tools.mxlint --contracts

echo "lint: PASS"
