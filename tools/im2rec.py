#!/usr/bin/env python
"""im2rec: build .rec/.idx packs from an image folder or a .lst file.

Reference: ``tools/im2rec.py`` (list generation + multiprocess pack) —
same .lst format (``index\\tlabel[\\tlabels...]\\tpath``), same record
layout (IRHeader + encoded image via recordio.pack_img), so packs made
here are interchangeable with reference ones.

Usage:
  python tools/im2rec.py --make-list PREFIX ROOT      # write PREFIX.lst
  python tools/im2rec.py PREFIX ROOT                  # pack PREFIX.lst -> .rec/.idx
"""
import argparse
import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

EXTS = (".jpg", ".jpeg", ".png", ".bmp")


def list_images(root):
    cat = {}
    items = []
    for folder in sorted(os.listdir(root)):
        path = os.path.join(root, folder)
        if not os.path.isdir(path):
            continue
        cat[folder] = len(cat)
        for fname in sorted(os.listdir(path)):
            if os.path.splitext(fname)[1].lower() in EXTS:
                items.append((os.path.join(folder, fname), cat[folder]))
    return items


def make_list(args):
    items = list_images(args.root)
    if args.shuffle:
        random.seed(100)
        random.shuffle(items)
    with open(args.prefix + ".lst", "w") as f:
        for i, (path, label) in enumerate(items):
            f.write("%d\t%f\t%s\n" % (i, label, path))
    print("wrote %s.lst (%d items)" % (args.prefix, len(items)))


def read_list(path):
    with open(path) as f:
        for line in f:
            parts = line.strip().split("\t")
            if len(parts) < 3:
                continue
            yield int(parts[0]), [float(x) for x in parts[1:-1]], parts[-1]


def pack(args):
    from mxnet_tpu import recordio, image
    lst = args.prefix + ".lst"
    if not os.path.isfile(lst):
        make_list(args)
    writer = recordio.MXIndexedRecordIO(args.prefix + ".idx",
                                        args.prefix + ".rec", "w")
    count = 0
    for idx, labels, rel in read_list(lst):
        fpath = os.path.join(args.root, rel)
        label = labels[0] if len(labels) == 1 else labels
        if args.pass_through:
            with open(fpath, "rb") as f:
                payload = recordio.pack(
                    recordio.IRHeader(0, label, idx, 0), f.read())
        else:
            img = image.imread(fpath).asnumpy()
            if args.resize:
                img = image.resize_short(img, args.resize).asnumpy()
            payload = recordio.pack_img(
                recordio.IRHeader(0, label, idx, 0), img,
                quality=args.quality,
                img_fmt=".png" if args.encoding == ".png" else ".jpg")
        writer.write_idx(idx, payload)
        count += 1
    writer.close()
    print("packed %d records -> %s.rec/.idx" % (count, args.prefix))


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("prefix")
    p.add_argument("root")
    p.add_argument("--make-list", action="store_true", dest="make_list_only")
    p.add_argument("--shuffle", type=int, default=1)
    p.add_argument("--resize", type=int, default=0)
    p.add_argument("--quality", type=int, default=95)
    p.add_argument("--encoding", default=".jpg", choices=[".jpg", ".png"])
    p.add_argument("--pass-through", action="store_true",
                   help="pack raw file bytes without re-encoding")
    args = p.parse_args()
    if args.make_list_only:
        make_list(args)
    else:
        pack(args)


if __name__ == "__main__":
    main()
