"""Regenerate the golden ONNX wire-format fixtures (tests/fixtures/).

The byte-exact fixtures pin the exporter's output format offline —
conformance testing without onnxruntime (see
tests/test_onnx.py::test_golden_fixture_bytes).  Run after INTENTIONAL
exporter changes and commit the updated .onnx files."""
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("MX_FORCE_CPU", "1")


def main():
    sys.path.insert(0, os.path.join(REPO, "tests"))
    import test_onnx
    out_dir = os.path.join(REPO, "tests", "fixtures")
    os.makedirs(out_dir, exist_ok=True)
    test_onnx._golden_lstm(os.path.join(out_dir, "golden_lstm.onnx"))
    test_onnx._golden_encoder(os.path.join(out_dir, "golden_encoder.onnx"))
    print("wrote", sorted(os.listdir(out_dir)))


if __name__ == "__main__":
    main()
