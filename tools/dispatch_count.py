"""Dispatch-count smoke: the ISSUE 3 acceptance harness.

Runs a short eager Gluon-Trainer fit on CPU, counts device-program
dispatches per training-step phase via ``engine.dispatch_count``, prints a
JSON report and exits nonzero if the step exceeds its budget.

The contract being locked: ``Trainer.step`` (allreduce + optimizer apply)
and the metric update together issue **O(#buckets)** dispatches per step —
a handful, independent of the parameter count — instead of the pre-fusion
O(#params).  Forward/backward stay eager per-op here on purpose (that is
the workload the Gluon path serves); the whole-graph-jitted paths
(Module fast path, parallel.TrainStep) are already single-dispatch.

Usage: python tools/dispatch_count.py [--steps N] [--params N]
Wired as a fast non-slow test in tests/test_fused_update.py.
"""
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("MX_FORCE_CPU", "1")

# a step phase may legitimately cost a few fixed dispatches (fused update
# chunk, bucket exchange, metric accumulate) — but never O(#params)
STEP_BUDGET = 4
METRIC_BUDGET = 2


def run(steps=3, hidden_layers=6, hidden=16):
    """Measured eager fit; returns the report dict (no printing)."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd
    from mxnet_tpu.engine import engine
    from mxnet_tpu.gluon import nn

    mx.random.seed(0)
    np.random.seed(0)
    net = nn.Sequential()
    in_units = 8
    for _ in range(hidden_layers):
        net.add(nn.Dense(hidden, in_units=in_units, activation="relu"))
        in_units = hidden
    net.add(nn.Dense(4, in_units=in_units))
    net.initialize(mx.init.Xavier())
    params = list(net.collect_params().values())
    trainer = gluon.Trainer(params, "sgd",
                            {"learning_rate": 0.05, "momentum": 0.9})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    metric = mx.metric.Accuracy()
    x = nd.array(np.random.randn(16, 8).astype(np.float32))
    y = nd.array(np.random.randint(0, 4, 16).astype(np.float32))

    def one_step():
        with autograd.record():
            out = net(x)
            loss = loss_fn(out, y)
        loss.backward()
        c0 = engine.dispatch_count
        trainer.step(batch_size=16)
        step_d = engine.dispatch_count - c0
        c1 = engine.dispatch_count
        metric.update([y], [out])
        metric_d = engine.dispatch_count - c1
        return step_d, metric_d

    one_step()                      # warmup: state creation dispatches
    per_step = [one_step() for _ in range(steps)]
    step_d = max(d for d, _ in per_step)
    metric_d = max(d for _, d in per_step)
    n_params = len(params)
    return {
        "metric": "eager_step_dispatches",
        "params": n_params,
        "steps": steps,
        "trainer_step_dispatches": step_d,
        "metric_update_dispatches": metric_d,
        "step_budget": STEP_BUDGET,
        "metric_budget": METRIC_BUDGET,
        "ok": bool(step_d <= STEP_BUDGET and metric_d <= METRIC_BUDGET
                   and step_d < n_params),
    }


def main():
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--layers", type=int, default=6)
    args = ap.parse_args()
    report = run(steps=args.steps, hidden_layers=args.layers)
    print(json.dumps(report, indent=2))
    sys.exit(0 if report["ok"] else 1)


if __name__ == "__main__":
    main()
