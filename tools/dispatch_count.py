"""Dispatch-count smoke: the ISSUE 3 acceptance harness.

Runs a short eager Gluon-Trainer fit on CPU, counts device-program
dispatches per training-step phase via ``engine.dispatch_count``, prints a
JSON report and exits nonzero if the step exceeds its budget.

The contract being locked: ``Trainer.step`` (allreduce + optimizer apply)
and the metric update together issue **O(#buckets)** dispatches per step —
a handful, independent of the parameter count — instead of the pre-fusion
O(#params).  Forward/backward stay eager per-op here on purpose (that is
the workload the Gluon path serves); the whole-graph-jitted paths
(Module fast path, parallel.TrainStep) are already single-dispatch.

Usage: python tools/dispatch_count.py [--steps N] [--params N]
Wired as a fast non-slow test in tests/test_fused_update.py.
"""
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("MX_FORCE_CPU", "1")

# a step phase may legitimately cost a few fixed dispatches (fused update
# chunk, bucket exchange, metric accumulate) — but never O(#params)
STEP_BUDGET = 4
METRIC_BUDGET = 2
# one overlap-scheduled, int8-compressed bucket exchange: concat + fused
# quantize-allreduce-dequantize per bucket — never a per-key quantize
EXCHANGE_BUDGET = 4
# ISSUE 7: a compiled N-step scan window is data transfer + ONE window
# launch, regardless of N — and a single compiled step is one launch
COMPILED_WINDOW_BUDGET = 2
COMPILED_STEP_BUDGET = 2
# ISSUE 9: the serving micro-batcher launches exactly ONE device program
# per dispatched batch (pad on host, jit launch, async scatter) — and
# after warmup every launch must hit the AOT bucket table (0 retraces)
SERVE_BATCH_BUDGET = 1
# ISSUE 15: one decode step = ONE device dispatch regardless of how many
# sequences are active (the pump packs them into a slot bucket), one
# prefill = one dispatch per admitted sequence, and after warmup every
# launch hits a pre-built bucket program (0 serve-time retraces)
DECODE_STEP_BUDGET = 1
# ISSUE 18: the paged engine keeps the same envelope with chunked
# prefill — every pump tick issues AT MOST one device program (a
# prefill chunk OR a decode step, never both), every dispatch is
# accounted as exactly one of the two, and retraces stay zero
PAGED_TICK_BUDGET = 1
# ISSUE 20: the speculative engine's plan is exact — each admission is
# its chunk train + ONE draft prefill (the sentinel ending the train),
# then every window is spec_k draft dispatches + ONE verify dispatch
# (committing 1..spec_k tokens), still at most one program per pump
# tick, and retraces stay zero on BOTH models
SPEC_TICK_BUDGET = 1


def run_exchange(n_keys=40):
    """ISSUE 5 acceptance: a batched exchange with int8 compression AND
    overlap scheduling dispatches O(#buckets), not O(#keys) — compression
    must ride inside the fused bucket dispatch (per-bucket residual), and
    the overlap session's unit launches are the same dispatches the
    serialized path would make, just earlier."""
    import numpy as np
    from mxnet_tpu import kvstore, nd
    from mxnet_tpu.engine import engine

    kv = kvstore.create("ici")   # single-process: collective is a no-op,
    kv.set_gradient_compression({"type": "int8"})   # quantize path isn't
    keys = list(range(n_keys))
    grads = [nd.array(np.random.RandomState(k).randn(64).astype("f4"))
             for k in keys]
    for k, g in zip(keys, grads):
        kv.init(k, nd.zeros_like(g))

    # serialized batched push/pull (what Trainer does without overlap)
    kv.push(keys, [[g] for g in grads])
    c0 = engine.snapshot()["dispatches"]
    kv.push(keys, [[g] for g in grads])
    kv.pull(keys, [[g] for g in grads])
    batched_d = engine.snapshot()["dispatches"] - c0

    # overlap session: notify every key, drain (what backward's hooks do)
    sess = kv.begin_exchange(keys, [[g] for g in grads])
    for k in keys:
        sess.notify_key(k)
    sess.drain()
    sess = kv.begin_exchange(keys, [[g] for g in grads])
    c1 = engine.snapshot()["dispatches"]
    for k in keys:
        sess.notify_key(k)
    sess.drain()
    overlap_d = engine.snapshot()["dispatches"] - c1
    return {
        "keys": n_keys,
        "batched_exchange_dispatches": batched_d,
        "overlap_exchange_dispatches": overlap_d,
        "exchange_budget": EXCHANGE_BUDGET,
        "ok": bool(batched_d <= EXCHANGE_BUDGET
                   and overlap_d <= EXCHANGE_BUDGET
                   and batched_d < n_keys and overlap_d < n_keys),
    }


def run_compiled(n_steps=4, hidden_layers=6, hidden=16, mesh=None):
    """ISSUE 7 acceptance: the whole-step-compiled lane dispatches 1-2
    device programs per N-step scan window (the batch transfer + the
    window launch) — NOT N — and a single compiled step is one launch.
    engine.compiled_steps must attribute all N optimizer steps to that
    one window, so dispatches-per-step is 2/N in steady state.

    ``mesh`` (ISSUE 14, e.g. ``"data,fsdp"`` or ``"data,fsdp=2,tp=2"``)
    runs the SAME budget through the SpecLayout-sharded step: the
    sharded one-donated-jit must fit the identical ≤2 dispatches/step
    envelope — proving FSDP adds no hidden host-side gathers."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd
    from mxnet_tpu.engine import engine
    from mxnet_tpu.gluon import nn

    layout = None
    if mesh:
        import jax
        from mxnet_tpu.parallel import SpecLayout, make_mesh
        from mxnet_tpu.parallel.speclayout import parse_mesh_axes
        axes, sizes = parse_mesh_axes(mesh)
        layout = SpecLayout.infer(
            make_mesh(axes=axes, shape=sizes, devices=jax.devices()))

    mx.random.seed(0)
    np.random.seed(0)
    net = nn.Sequential()
    in_units = 8
    for _ in range(hidden_layers):
        net.add(nn.Dense(hidden, in_units=in_units, activation="relu"))
        in_units = hidden
    net.add(nn.Dense(4, in_units=in_units))
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05, "momentum": 0.9})
    loss_fn = gluon.loss.L2Loss()
    metric = mx.metric.MSE()
    step = trainer.make_compiled_step(net, loss_fn, metric=metric,
                                      layout=layout)
    rng = np.random.RandomState(0)
    Xw = rng.randn(n_steps, 16, 8).astype(np.float32)
    Yw = rng.randn(n_steps, 16, 4).astype(np.float32)
    step.run_window(Xw, Yw)                   # warm (trace + compile)
    # ISSUE 10: dispatch_count and compiled_steps must be ONE consistent
    # read — count_step_window bumps both, and reading them as two
    # properties could split a mid-flight bump
    snap0 = engine.snapshot()
    step.run_window(Xw, Yw)
    snap1 = engine.snapshot()
    window_d = snap1["dispatches"] - snap0["dispatches"]
    window_steps = snap1["compiled_steps"] - snap0["compiled_steps"]
    x1 = nd.array(Xw[0])
    y1 = nd.array(Yw[0])
    step.step(x1, y1)                          # warm the 1-step entry
    c1 = engine.snapshot()["dispatches"]
    step.step(x1, y1)
    single_d = engine.snapshot()["dispatches"] - c1
    return {
        "compiled": bool(step.compiled),
        "mesh": mesh or None,
        "scan_steps": n_steps,
        "window_dispatches": window_d,
        "window_steps_accounted": window_steps,
        "single_step_dispatches": single_d,
        "window_budget": COMPILED_WINDOW_BUDGET,
        "step_budget": COMPILED_STEP_BUDGET,
        "ok": bool(step.compiled
                   and window_d <= COMPILED_WINDOW_BUDGET
                   and window_steps == n_steps
                   and single_d <= COMPILED_STEP_BUDGET),
    }


def run_serve(n_requests=24, rows_per_request=2, max_batch=8):
    """ISSUE 9 acceptance: a coalesced serving batch costs ONE device
    dispatch regardless of how many requests ride it, every dispatch
    hits the pre-warmed AOT bucket table (bucket_hits == batches), and
    serve time pays ZERO retraces.  The batcher starts AFTER the burst
    is queued so the coalescing plan — ceil(rows/max_batch) batches —
    is deterministic, not a race against submission speed."""
    import numpy as np
    from mxnet_tpu import telemetry
    from mxnet_tpu.engine import engine
    from mxnet_tpu.serve import Batcher, BucketTable, ModelHost, Servable
    from mxnet_tpu.serve.demo import DEMO_IN, demo_block, demo_example

    host = ModelHost()
    sv = Servable(demo_block(), version=1,
                  buckets=BucketTable([1, 2, 4, max_batch]))
    host.deploy(sv, example=demo_example())
    batcher = Batcher(host, max_batch=max_batch, max_delay_us=0,
                      queue_cap=n_requests * rows_per_request,
                      autostart=False)
    rng = np.random.RandomState(0)
    retraces0, hits0 = sv.retraces, sv.bucket_hits
    batches0 = telemetry.registry.value("serve.batches")
    c0 = engine.snapshot()["dispatches"]
    pendings = [batcher.submit(
        [rng.randn(rows_per_request, DEMO_IN).astype(np.float32)])
        for _ in range(n_requests)]
    batcher.start()
    for p in pendings:
        p.result(timeout=60)
    batcher.close()
    dispatches = engine.snapshot()["dispatches"] - c0
    batches = telemetry.registry.value("serve.batches") - batches0
    total_rows = n_requests * rows_per_request
    want_batches = -(-total_rows // max_batch)     # ceil
    return {
        "requests": n_requests,
        "rows": total_rows,
        "batches": batches,
        "expected_batches": want_batches,
        "dispatches": dispatches,
        "dispatches_per_batch": round(dispatches / max(1, batches), 2),
        "bucket_hits": sv.bucket_hits - hits0,
        "retraces": sv.retraces - retraces0,
        "batch_budget": SERVE_BATCH_BUDGET,
        "ok": bool(batches == want_batches
                   and dispatches == batches * SERVE_BATCH_BUDGET
                   and sv.bucket_hits - hits0 == batches
                   and sv.retraces == retraces0),
    }


def run_decode(n_gens=6, prompt_len=3, max_new=5, slots=8):
    """ISSUE 15 acceptance: the continuous-batching decode engine's
    dispatch budget, driven SYNCHRONOUSLY (autostart=False: no pipeline
    lag, so the plan is exact arithmetic, not a race).  All ``n_gens``
    same-length generations admit at the first boundary (one prefill
    dispatch each), then run in lockstep: ``max_new - 1`` decode steps
    of exactly ONE dispatch each regardless of the active count.  Every
    dispatch must be accounted (dispatches == prefills + steps), and
    serve time pays ZERO retraces after the deploy-time warm."""
    import numpy as np
    from mxnet_tpu import telemetry
    from mxnet_tpu.engine import engine
    from mxnet_tpu.serve.decode import (DecodeBatcher, DecodeConfig,
                                        DecodeServable)

    assert n_gens <= slots, "budget plan needs one admission boundary"
    cfg = DecodeConfig(slots=slots, max_tokens=max(8, max_new),
                       prompt_buckets=(4, 8))
    sv = DecodeServable(config=cfg)
    eng = DecodeBatcher(sv, autostart=False)     # warm() paid here
    reg = telemetry.registry
    retraces0 = sv.retraces
    pre0 = reg.value("serve.decode.prefills")
    steps0 = reg.value("serve.decode.steps")
    c0 = engine.snapshot()["dispatches"]
    gens = [eng.submit(list(range(1, prompt_len + 1)), max_new=max_new)
            for _ in range(n_gens)]
    eng.drain_sync()
    dispatches = engine.snapshot()["dispatches"] - c0
    prefills = reg.value("serve.decode.prefills") - pre0
    steps = reg.value("serve.decode.steps") - steps0
    want_steps = max_new - 1        # token 1 comes out of the prefill
    done = all(len(g.tokens_so_far()) == max_new and g.done()
               for g in gens)
    return {
        "generations": n_gens,
        "tokens": sum(len(g.tokens_so_far()) for g in gens),
        "prefill_dispatches": prefills,
        "decode_steps": steps,
        "expected_steps": want_steps,
        "dispatches": dispatches,
        "dispatches_per_step": DECODE_STEP_BUDGET,
        "retraces": sv.retraces - retraces0,
        "step_budget": DECODE_STEP_BUDGET,
        "ok": bool(done
                   and prefills == n_gens
                   and steps == want_steps
                   and dispatches == prefills
                   + steps * DECODE_STEP_BUDGET
                   and sv.retraces == retraces0),
    }


def run_paged_decode(n_gens=6, prompt_len=8, max_new=5, slots=8):
    """ISSUE 18 acceptance: the paged engine's dispatch arithmetic,
    driven tick by tick.  Each admitted prompt prefills as a train of
    page-aligned chunks (``prompt_len / prefill_chunk`` dispatches; the
    last chunk emits token 1), chunks interleave with decode steps at
    AT MOST one device program per pump tick, every dispatch is
    accounted as a chunk or a step, and serve time pays ZERO retraces
    after the deploy-time warm."""
    from mxnet_tpu import telemetry
    from mxnet_tpu.engine import engine
    from mxnet_tpu.serve.decode import (DecodeConfig, PagedDecodeBatcher,
                                        PagedDecodeServable)

    assert n_gens <= slots, "budget plan needs one admission boundary"
    chunk = 4
    cfg = DecodeConfig(slots=slots, max_tokens=max(8, max_new),
                       prompt_buckets=(4, 8), kv_page_len=4,
                       prefill_chunk=chunk)
    sv = PagedDecodeServable(config=cfg)
    eng = PagedDecodeBatcher(sv, autostart=False)    # warm() paid here
    reg = telemetry.registry
    retraces0 = sv.retraces
    pre0 = reg.value("serve.decode.prefills")
    ch0 = reg.value("serve.decode.prefill_chunks")
    steps0 = reg.value("serve.decode.steps")
    c0 = engine.snapshot()["dispatches"]
    # distinct first pages -> no prefix sharing; the chunk plan is
    # exact arithmetic, not a cache race
    gens = [eng.submit([(i + j) % 7 + 1 for j in range(prompt_len)],
                       max_new=max_new) for i in range(n_gens)]
    max_per_tick = 0
    busy, ticks = True, 0
    while busy and ticks < 10000:
        t0 = engine.snapshot()["dispatches"]
        busy = eng.step_sync()
        max_per_tick = max(max_per_tick,
                           engine.snapshot()["dispatches"] - t0)
        ticks += 1
    dispatches = engine.snapshot()["dispatches"] - c0
    prefills = reg.value("serve.decode.prefills") - pre0
    chunks = reg.value("serve.decode.prefill_chunks") - ch0
    steps = reg.value("serve.decode.steps") - steps0
    want_chunks = n_gens * (-(-prompt_len // chunk))
    done = all(len(g.tokens_so_far()) == max_new and g.done()
               for g in gens)
    return {
        "generations": n_gens,
        "tokens": sum(len(g.tokens_so_far()) for g in gens),
        "prefill_chunk_dispatches": chunks,
        "expected_chunks": want_chunks,
        "prefill_trains": prefills,
        "decode_steps": steps,
        "dispatches": dispatches,
        "max_dispatches_per_tick": max_per_tick,
        "tick_budget": PAGED_TICK_BUDGET,
        "retraces": sv.retraces - retraces0,
        "ok": bool(done
                   and chunks == want_chunks
                   and prefills == n_gens
                   and dispatches == chunks + steps
                   and max_per_tick <= PAGED_TICK_BUDGET
                   and sv.retraces == retraces0),
    }


def run_speculative(n_gens=4, prompt_len=8, max_new=9, slots=8,
                    spec_k=4):
    """ISSUE 20 acceptance: the speculative engine's dispatch
    arithmetic, driven tick by tick.

    Sequential lane (one generation at a time, a FULL-acceptance
    draft == target): the plan is closed-form — per generation,
    ``ceil(prompt/chunk)`` chunk dispatches + 1 draft prefill (the
    train's sentinel) + ``ceil((max_new-1)/k)`` windows of exactly
    ``k`` draft dispatches + 1 verify dispatch.  Concurrent lane (all
    generations at once): the exact count depends on admission overlap,
    so the pinned invariants are the accounting identity (dispatches ==
    chunks + draft prefills + draft steps + verifies), the <=1
    program-per-tick budget, and ZERO retraces on both models."""
    from mxnet_tpu import telemetry
    from mxnet_tpu.engine import engine
    from mxnet_tpu.serve.decode import (DecodeConfig,
                                        DraftDecodeServable,
                                        PagedDecodeServable,
                                        SpeculativeDecodeBatcher,
                                        demo_spec_pair)

    assert n_gens <= slots, "budget plan needs one admission boundary"
    chunk = 4
    cfg = DecodeConfig(slots=slots, max_tokens=prompt_len + max_new + 1,
                       prompt_buckets=(4, 8), kv_page_len=4,
                       prefill_chunk=chunk, spec_k=spec_k)
    k = cfg.spec_k
    # draft_layers == layers: the draft IS the target, so every window
    # fully accepts and the sequential plan is exact arithmetic
    tparams, dcfg, dparams = demo_spec_pair(cfg,
                                            draft_layers=cfg.layers)
    sv = PagedDecodeServable(params=tparams, config=cfg)
    draft = DraftDecodeServable(params=dparams, config=dcfg,
                                name="demo-lm-draft")
    eng = SpeculativeDecodeBatcher(sv, draft, autostart=False)
    reg = telemetry.registry

    def counters():
        return {
            "chunks": reg.value("serve.decode.prefill_chunks"),
            "dp": reg.value("serve.decode.draft_prefills"),
            "ds": reg.value("serve.decode.draft_steps"),
            "verify": reg.value("serve.decode.spec_windows"),
        }

    def drive():
        max_per_tick, busy, ticks = 0, True, 0
        while busy and ticks < 20000:
            t0 = engine.snapshot()["dispatches"]
            busy = eng.step_sync()
            max_per_tick = max(max_per_tick,
                               engine.snapshot()["dispatches"] - t0)
            ticks += 1
        return max_per_tick

    retraces0 = sv.retraces + draft.retraces
    # -- sequential lane: closed-form plan ----------------------------------
    c0, k0 = engine.snapshot()["dispatches"], counters()
    seq_gens = []
    for i in range(n_gens):
        g = eng.submit([(i + j) % 7 + 1 for j in range(prompt_len)],
                       max_new=max_new)
        drive()
        seq_gens.append(g)
    seq_d = engine.snapshot()["dispatches"] - c0
    k1 = counters()
    seq = {key: k1[key] - k0[key] for key in k1}
    chunks_per = -(-prompt_len // chunk)
    windows_per = -(-(max_new - 1) // k)
    want_seq = n_gens * (chunks_per + 1 + windows_per * (k + 1))
    seq_ok = (all(len(g.tokens_so_far()) == max_new and g.done()
                  for g in seq_gens)
              and seq["chunks"] == n_gens * chunks_per
              and seq["dp"] == n_gens
              and seq["ds"] == n_gens * windows_per * k
              and seq["verify"] == n_gens * windows_per
              and seq_d == want_seq)
    # -- concurrent lane: accounting identity + tick budget -----------------
    c0, k0 = engine.snapshot()["dispatches"], counters()
    gens = [eng.submit([(i + j) % 7 + 1 for j in range(prompt_len)],
                       max_new=max_new) for i in range(n_gens)]
    max_per_tick = drive()
    conc_d = engine.snapshot()["dispatches"] - c0
    k1 = counters()
    conc = {key: k1[key] - k0[key] for key in k1}
    accounted = (conc["chunks"] + conc["dp"] + conc["ds"]
                 + conc["verify"])
    conc_ok = (all(len(g.tokens_so_far()) == max_new and g.done()
                   for g in gens)
               and conc_d == accounted
               and max_per_tick <= SPEC_TICK_BUDGET)
    retraces = (sv.retraces + draft.retraces) - retraces0
    return {
        "generations": n_gens,
        "spec_k": k,
        "sequential_dispatches": seq_d,
        "expected_sequential": want_seq,
        "sequential_plan": seq,
        "concurrent_dispatches": conc_d,
        "concurrent_accounted": accounted,
        "concurrent_plan": conc,
        "max_dispatches_per_tick": max_per_tick,
        "tick_budget": SPEC_TICK_BUDGET,
        "retraces": retraces,
        "ok": bool(seq_ok and conc_ok and retraces == 0),
    }


def run_routed(n_requests=24, rows_per_request=2, max_batch=8):
    """ISSUE 17 acceptance: the session router is a PURE host-side
    forwarder — the same PREDICT burst driven through it costs exactly
    the device dispatches the direct-to-replica burst costs (zero
    extra), and zero retraces either way (every launch still hits the
    replica's pre-warmed AOT bucket table; the router never touches a
    tensor).  One in-process replica + one in-process router share this
    process's dispatch counter, so the comparison is exact arithmetic:
    sequential unit-row requests with max_delay_us=0 coalesce 1:1, so
    both lanes must count exactly ``n_requests`` dispatches."""
    import socket
    import threading
    import time
    import numpy as np
    from mxnet_tpu.engine import engine
    from mxnet_tpu.serve import (BucketTable, Servable, ServeClient,
                                 ServeRouter, serve_router_forever)
    from mxnet_tpu.serve.server import ServeServer, serve_forever
    from mxnet_tpu.serve.demo import DEMO_IN, demo_block, demo_example

    def _free_port():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    def _wait_up(port, timeout=30.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                socket.create_connection(("127.0.0.1", port),
                                         timeout=0.2).close()
                return
            except OSError:
                time.sleep(0.05)
        raise RuntimeError("port %d never came up" % port)

    rport, xport = _free_port(), _free_port()
    sv = Servable(demo_block(), version=1,
                  buckets=BucketTable([1, rows_per_request, max_batch]))
    state = ServeServer(max_delay_us=0, queue_cap=256)
    state.host.deploy(sv, example=demo_example())
    stop_replica = threading.Event()
    threading.Thread(target=serve_forever,
                     kwargs=dict(port=rport, state=state,
                                 stop_event=stop_replica),
                     daemon=True).start()
    _wait_up(rport)
    rt = ServeRouter(replicas=["127.0.0.1:%d" % rport], refresh=30.0)
    stop_router = threading.Event()
    threading.Thread(target=serve_router_forever,
                     kwargs=dict(port=xport, router=rt,
                                 stop_event=stop_router),
                     daemon=True).start()
    _wait_up(xport)

    rng = np.random.RandomState(0)

    def burst(port):
        cli = ServeClient(["127.0.0.1:%d" % port], timeout=30.0)
        try:
            c0 = engine.snapshot()["dispatches"]
            r0 = sv.retraces
            for _ in range(n_requests):
                x = rng.randn(rows_per_request,
                              DEMO_IN).astype(np.float32)
                cli.predict([x])
            return (engine.snapshot()["dispatches"] - c0,
                    sv.retraces - r0)
        finally:
            cli.close()

    try:
        direct_d, direct_r = burst(rport)
        routed_d, routed_r = burst(xport)
    finally:
        stop_router.set()
        stop_replica.set()
    return {
        "requests": n_requests,
        "direct_dispatches": direct_d,
        "routed_dispatches": routed_d,
        "extra_dispatches": routed_d - direct_d,
        "direct_retraces": direct_r,
        "routed_retraces": routed_r,
        "ok": bool(direct_d == n_requests
                   and routed_d == direct_d
                   and direct_r == 0 and routed_r == 0),
    }


def run(steps=3, hidden_layers=6, hidden=16):
    """Measured eager fit; returns the report dict (no printing)."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd
    from mxnet_tpu.engine import engine
    from mxnet_tpu.gluon import nn

    mx.random.seed(0)
    np.random.seed(0)
    net = nn.Sequential()
    in_units = 8
    for _ in range(hidden_layers):
        net.add(nn.Dense(hidden, in_units=in_units, activation="relu"))
        in_units = hidden
    net.add(nn.Dense(4, in_units=in_units))
    net.initialize(mx.init.Xavier())
    params = list(net.collect_params().values())
    trainer = gluon.Trainer(params, "sgd",
                            {"learning_rate": 0.05, "momentum": 0.9})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    metric = mx.metric.Accuracy()
    x = nd.array(np.random.randn(16, 8).astype(np.float32))
    y = nd.array(np.random.randint(0, 4, 16).astype(np.float32))

    def one_step():
        with autograd.record():
            out = net(x)
            loss = loss_fn(out, y)
        loss.backward()
        c0 = engine.snapshot()["dispatches"]
        trainer.step(batch_size=16)
        step_d = engine.snapshot()["dispatches"] - c0
        c1 = engine.snapshot()["dispatches"]
        metric.update([y], [out])
        metric_d = engine.snapshot()["dispatches"] - c1
        return step_d, metric_d

    one_step()                      # warmup: state creation dispatches
    per_step = [one_step() for _ in range(steps)]
    step_d = max(d for d, _ in per_step)
    metric_d = max(d for _, d in per_step)
    n_params = len(params)
    return {
        "metric": "eager_step_dispatches",
        "params": n_params,
        "steps": steps,
        "trainer_step_dispatches": step_d,
        "metric_update_dispatches": metric_d,
        "step_budget": STEP_BUDGET,
        "metric_budget": METRIC_BUDGET,
        "ok": bool(step_d <= STEP_BUDGET and metric_d <= METRIC_BUDGET
                   and step_d < n_params),
    }


def main():
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--layers", type=int, default=6)
    ap.add_argument("--compress", default=None,
                    help="run the trainer fit under MX_GRAD_COMPRESS")
    ap.add_argument("--overlap", action="store_true",
                    help="run the trainer fit under MX_EXCHANGE_OVERLAP=1")
    ap.add_argument("--compiled", action="store_true",
                    help="also pin the ISSUE 7 compiled-step budget: 1-2 "
                         "dispatches per N-step scan window")
    ap.add_argument("--serve", action="store_true",
                    help="also pin the ISSUE 9 serving budget: 1 device "
                         "dispatch per coalesced micro-batch, all "
                         "bucket-table hits, 0 serve-time retraces")
    ap.add_argument("--decode", action="store_true",
                    help="with --serve: also pin the ISSUE 15 decode "
                         "budget (exactly 1 dispatch per decode step "
                         "regardless of active-sequence count, 1 per "
                         "prefill, 0 serve-time retraces after warmup) "
                         "AND the ISSUE 18 paged budget (chunked "
                         "prefill = at most 1 dispatch per pump tick, "
                         "chunks counted as steps, 0 retraces)")
    ap.add_argument("--speculative", action="store_true",
                    help="with --serve --decode: also pin the ISSUE 20 "
                         "speculative budget (per window: exactly "
                         "spec_k draft dispatches + 1 verify dispatch "
                         "committing 1..k tokens; chunk trains end in "
                         "one draft-prefill sentinel; <=1 program per "
                         "pump tick; 0 retraces on either model)")
    ap.add_argument("--routed", action="store_true",
                    help="with --serve: also pin the ISSUE 17 router "
                         "budget: the same burst through the session "
                         "router costs ZERO extra device dispatches "
                         "and zero retraces vs direct-to-replica")
    ap.add_argument("--scan", type=int, default=0,
                    help="scan window size for --compiled "
                         "(default: MX_STEP_SCAN, else 4)")
    ap.add_argument("--mesh", default=None,
                    help="with --compiled: ALSO run the SpecLayout-"
                         "sharded step (ISSUE 14) over this mesh "
                         "(e.g. 'data,fsdp' or 'data,fsdp=2,tp=2') and "
                         "pin the same <=2 dispatches/step budget")
    args = ap.parse_args()
    if args.mesh and "xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        # a CPU box needs a fake multi-device mesh; set BEFORE jax init
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                                   " --xla_force_host_platform_device"
                                   "_count=8").strip()
    if args.compress:
        os.environ["MX_GRAD_COMPRESS"] = args.compress
    if args.overlap:
        os.environ["MX_EXCHANGE_OVERLAP"] = "1"
    report = run(steps=args.steps, hidden_layers=args.layers)
    report["compress"] = args.compress
    report["overlap"] = bool(args.overlap)
    report["exchange"] = run_exchange()
    report["ok"] = bool(report["ok"] and report["exchange"]["ok"])
    if args.compiled:
        from mxnet_tpu.step import scan_window
        n_steps = args.scan or scan_window() or 4
        report["compiled"] = run_compiled(n_steps=max(1, n_steps))
        report["ok"] = bool(report["ok"] and report["compiled"]["ok"])
        if args.mesh:
            report["compiled_sharded"] = run_compiled(
                n_steps=max(1, n_steps), mesh=args.mesh)
            report["ok"] = bool(report["ok"] and
                                report["compiled_sharded"]["ok"])
    if args.serve:
        report["serve"] = run_serve()
        report["ok"] = bool(report["ok"] and report["serve"]["ok"])
    if args.decode:
        report["decode"] = run_decode()
        report["ok"] = bool(report["ok"] and report["decode"]["ok"])
        report["paged_decode"] = run_paged_decode()
        report["ok"] = bool(report["ok"]
                            and report["paged_decode"]["ok"])
    if args.speculative:
        report["speculative"] = run_speculative()
        report["ok"] = bool(report["ok"]
                            and report["speculative"]["ok"])
    if args.routed:
        report["routed"] = run_routed()
        report["ok"] = bool(report["ok"] and report["routed"]["ok"])
    print(json.dumps(report, indent=2))
    sys.exit(0 if report["ok"] else 1)


if __name__ == "__main__":
    main()
