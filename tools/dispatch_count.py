"""Dispatch-count smoke: the ISSUE 3 acceptance harness.

Runs a short eager Gluon-Trainer fit on CPU, counts device-program
dispatches per training-step phase via ``engine.dispatch_count``, prints a
JSON report and exits nonzero if the step exceeds its budget.

The contract being locked: ``Trainer.step`` (allreduce + optimizer apply)
and the metric update together issue **O(#buckets)** dispatches per step —
a handful, independent of the parameter count — instead of the pre-fusion
O(#params).  Forward/backward stay eager per-op here on purpose (that is
the workload the Gluon path serves); the whole-graph-jitted paths
(Module fast path, parallel.TrainStep) are already single-dispatch.

Usage: python tools/dispatch_count.py [--steps N] [--params N]
Wired as a fast non-slow test in tests/test_fused_update.py.
"""
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("MX_FORCE_CPU", "1")

# a step phase may legitimately cost a few fixed dispatches (fused update
# chunk, bucket exchange, metric accumulate) — but never O(#params)
STEP_BUDGET = 4
METRIC_BUDGET = 2
# one overlap-scheduled, int8-compressed bucket exchange: concat + fused
# quantize-allreduce-dequantize per bucket — never a per-key quantize
EXCHANGE_BUDGET = 4
# ISSUE 7: a compiled N-step scan window is data transfer + ONE window
# launch, regardless of N — and a single compiled step is one launch
COMPILED_WINDOW_BUDGET = 2
COMPILED_STEP_BUDGET = 2


def run_exchange(n_keys=40):
    """ISSUE 5 acceptance: a batched exchange with int8 compression AND
    overlap scheduling dispatches O(#buckets), not O(#keys) — compression
    must ride inside the fused bucket dispatch (per-bucket residual), and
    the overlap session's unit launches are the same dispatches the
    serialized path would make, just earlier."""
    import numpy as np
    from mxnet_tpu import kvstore, nd
    from mxnet_tpu.engine import engine

    kv = kvstore.create("ici")   # single-process: collective is a no-op,
    kv.set_gradient_compression({"type": "int8"})   # quantize path isn't
    keys = list(range(n_keys))
    grads = [nd.array(np.random.RandomState(k).randn(64).astype("f4"))
             for k in keys]
    for k, g in zip(keys, grads):
        kv.init(k, nd.zeros_like(g))

    # serialized batched push/pull (what Trainer does without overlap)
    kv.push(keys, [[g] for g in grads])
    c0 = engine.dispatch_count
    kv.push(keys, [[g] for g in grads])
    kv.pull(keys, [[g] for g in grads])
    batched_d = engine.dispatch_count - c0

    # overlap session: notify every key, drain (what backward's hooks do)
    sess = kv.begin_exchange(keys, [[g] for g in grads])
    for k in keys:
        sess.notify_key(k)
    sess.drain()
    sess = kv.begin_exchange(keys, [[g] for g in grads])
    c1 = engine.dispatch_count
    for k in keys:
        sess.notify_key(k)
    sess.drain()
    overlap_d = engine.dispatch_count - c1
    return {
        "keys": n_keys,
        "batched_exchange_dispatches": batched_d,
        "overlap_exchange_dispatches": overlap_d,
        "exchange_budget": EXCHANGE_BUDGET,
        "ok": bool(batched_d <= EXCHANGE_BUDGET
                   and overlap_d <= EXCHANGE_BUDGET
                   and batched_d < n_keys and overlap_d < n_keys),
    }


def run_compiled(n_steps=4, hidden_layers=6, hidden=16):
    """ISSUE 7 acceptance: the whole-step-compiled lane dispatches 1-2
    device programs per N-step scan window (the batch transfer + the
    window launch) — NOT N — and a single compiled step is one launch.
    engine.compiled_steps must attribute all N optimizer steps to that
    one window, so dispatches-per-step is 2/N in steady state."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd
    from mxnet_tpu.engine import engine
    from mxnet_tpu.gluon import nn

    mx.random.seed(0)
    np.random.seed(0)
    net = nn.Sequential()
    in_units = 8
    for _ in range(hidden_layers):
        net.add(nn.Dense(hidden, in_units=in_units, activation="relu"))
        in_units = hidden
    net.add(nn.Dense(4, in_units=in_units))
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05, "momentum": 0.9})
    loss_fn = gluon.loss.L2Loss()
    metric = mx.metric.MSE()
    step = trainer.make_compiled_step(net, loss_fn, metric=metric)
    rng = np.random.RandomState(0)
    Xw = rng.randn(n_steps, 16, 8).astype(np.float32)
    Yw = rng.randn(n_steps, 16, 4).astype(np.float32)
    step.run_window(Xw, Yw)                   # warm (trace + compile)
    c0, s0 = engine.dispatch_count, engine.compiled_steps
    step.run_window(Xw, Yw)
    window_d = engine.dispatch_count - c0
    window_steps = engine.compiled_steps - s0
    x1 = nd.array(Xw[0])
    y1 = nd.array(Yw[0])
    step.step(x1, y1)                          # warm the 1-step entry
    c1 = engine.dispatch_count
    step.step(x1, y1)
    single_d = engine.dispatch_count - c1
    return {
        "compiled": bool(step.compiled),
        "scan_steps": n_steps,
        "window_dispatches": window_d,
        "window_steps_accounted": window_steps,
        "single_step_dispatches": single_d,
        "window_budget": COMPILED_WINDOW_BUDGET,
        "step_budget": COMPILED_STEP_BUDGET,
        "ok": bool(step.compiled
                   and window_d <= COMPILED_WINDOW_BUDGET
                   and window_steps == n_steps
                   and single_d <= COMPILED_STEP_BUDGET),
    }


def run(steps=3, hidden_layers=6, hidden=16):
    """Measured eager fit; returns the report dict (no printing)."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd
    from mxnet_tpu.engine import engine
    from mxnet_tpu.gluon import nn

    mx.random.seed(0)
    np.random.seed(0)
    net = nn.Sequential()
    in_units = 8
    for _ in range(hidden_layers):
        net.add(nn.Dense(hidden, in_units=in_units, activation="relu"))
        in_units = hidden
    net.add(nn.Dense(4, in_units=in_units))
    net.initialize(mx.init.Xavier())
    params = list(net.collect_params().values())
    trainer = gluon.Trainer(params, "sgd",
                            {"learning_rate": 0.05, "momentum": 0.9})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    metric = mx.metric.Accuracy()
    x = nd.array(np.random.randn(16, 8).astype(np.float32))
    y = nd.array(np.random.randint(0, 4, 16).astype(np.float32))

    def one_step():
        with autograd.record():
            out = net(x)
            loss = loss_fn(out, y)
        loss.backward()
        c0 = engine.dispatch_count
        trainer.step(batch_size=16)
        step_d = engine.dispatch_count - c0
        c1 = engine.dispatch_count
        metric.update([y], [out])
        metric_d = engine.dispatch_count - c1
        return step_d, metric_d

    one_step()                      # warmup: state creation dispatches
    per_step = [one_step() for _ in range(steps)]
    step_d = max(d for d, _ in per_step)
    metric_d = max(d for _, d in per_step)
    n_params = len(params)
    return {
        "metric": "eager_step_dispatches",
        "params": n_params,
        "steps": steps,
        "trainer_step_dispatches": step_d,
        "metric_update_dispatches": metric_d,
        "step_budget": STEP_BUDGET,
        "metric_budget": METRIC_BUDGET,
        "ok": bool(step_d <= STEP_BUDGET and metric_d <= METRIC_BUDGET
                   and step_d < n_params),
    }


def main():
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--layers", type=int, default=6)
    ap.add_argument("--compress", default=None,
                    help="run the trainer fit under MX_GRAD_COMPRESS")
    ap.add_argument("--overlap", action="store_true",
                    help="run the trainer fit under MX_EXCHANGE_OVERLAP=1")
    ap.add_argument("--compiled", action="store_true",
                    help="also pin the ISSUE 7 compiled-step budget: 1-2 "
                         "dispatches per N-step scan window")
    ap.add_argument("--scan", type=int, default=0,
                    help="scan window size for --compiled "
                         "(default: MX_STEP_SCAN, else 4)")
    args = ap.parse_args()
    if args.compress:
        os.environ["MX_GRAD_COMPRESS"] = args.compress
    if args.overlap:
        os.environ["MX_EXCHANGE_OVERLAP"] = "1"
    report = run(steps=args.steps, hidden_layers=args.layers)
    report["compress"] = args.compress
    report["overlap"] = bool(args.overlap)
    report["exchange"] = run_exchange()
    report["ok"] = bool(report["ok"] and report["exchange"]["ok"])
    if args.compiled:
        from mxnet_tpu.step import scan_window
        n_steps = args.scan or scan_window() or 4
        report["compiled"] = run_compiled(n_steps=max(1, n_steps))
        report["ok"] = bool(report["ok"] and report["compiled"]["ok"])
    print(json.dumps(report, indent=2))
    sys.exit(0 if report["ok"] else 1)


if __name__ == "__main__":
    main()
