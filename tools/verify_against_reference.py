"""Reference-mount readiness check (SURVEY.md §7.2 item 8).

`/root/reference/` has been EMPTY every round so far; SURVEY.md therefore
cites upstream anchors (`path (Symbol)`) instead of `file:line`.  The
moment the mount materializes, this script turns those anchors into
verifiable facts:

  1. anchor conversion — grep each SURVEY anchor's symbol inside its
     cited path under /root/reference and print `file:line`;
  2. op-name diff — enumerate the reference's registered op names
     (NNVM_REGISTER_OP / MXNET_OPERATOR_REGISTER_* in src/operator/**)
     and diff against this repo's registry (mxnet_tpu.ops.registry);
  3. serialization probe — if the mount carries *.params / *-symbol.json
     fixtures (or the reference's own test data), byte-check our
     reader/writer against them.

On an empty mount it reports that state and exits 0 — a standing no-op
until the environment fault is fixed.

Run:  python tools/verify_against_reference.py [--json out.json]
"""
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REF = "/root/reference"
SURVEY = os.path.join(REPO, "SURVEY.md")

# `path (Symbol)` anchors as SURVEY.md writes them, e.g.
#   src/engine/threaded_engine.cc (`ThreadedEngine::PushAsync`, ...)
_ANCHOR_RE = re.compile(
    r"`((?:[\w.-]+/)+[\w.-]+\.(?:cc|cu|h|py|hpp))`?\s*\(`([^`]+)`")


def mount_state():
    try:
        entries = os.listdir(REF)
    except OSError:
        return "missing"
    return "populated" if entries else "empty"


def collect_anchors():
    anchors = []
    with open(SURVEY) as f:
        text = f.read()
    for m in _ANCHOR_RE.finditer(text):
        path, syms = m.group(1), m.group(2)
        first_sym = syms.split(",")[0].strip().strip("`")
        anchors.append((path, first_sym))
    # de-dup, keep order
    seen, out = set(), []
    for a in anchors:
        if a not in seen:
            seen.add(a)
            out.append(a)
    return out


def resolve_anchor(path, symbol):
    """Return 'file:line' for symbol inside path under the mount, else why."""
    # the fork may root files at / or under a top-level dir; try both
    cands = [os.path.join(REF, path)]
    for top in os.listdir(REF):
        cands.append(os.path.join(REF, top, path))
    # symbols like Class::Method: grep the method name too
    needles = [symbol]
    if "::" in symbol:
        needles.append(symbol.split("::")[-1])
    for cand in cands:
        if not os.path.isfile(cand):
            continue
        try:
            with open(cand, errors="replace") as f:
                lines = f.readlines()
        except OSError:
            continue
        for needle in needles:
            for i, line in enumerate(lines, 1):
                if needle in line:
                    return {"resolved": "%s:%d" % (os.path.relpath(cand, REF),
                                                   i)}
        return {"error": "file found but symbol %r absent" % symbol,
                "file": os.path.relpath(cand, REF)}
    return {"error": "path not in mount"}


_REG_RE = re.compile(
    r"(?:NNVM_REGISTER_OP|MXNET_OPERATOR_REGISTER_\w+)\(\s*([\w.]+)\s*[),]")


def reference_op_names():
    names = set()
    for root, _dirs, files in os.walk(os.path.join(REF)):
        for fn in files:
            if not fn.endswith((".cc", ".cu", ".h")):
                continue
            p = os.path.join(root, fn)
            if "operator" not in p:
                continue
            try:
                with open(p, errors="replace") as f:
                    for m in _REG_RE.finditer(f.read()):
                        names.add(m.group(1))
            except OSError:
                pass
    return names


def onnx_like_fixture_paths():
    hits = []
    for root, _dirs, files in os.walk(REF):
        for fn in files:
            if fn.endswith((".params", "-symbol.json")):
                hits.append(os.path.join(root, fn))
    return hits


def main():
    state = mount_state()
    report = {"mount": state}
    if state != "populated":
        print("reference mount is %s — nothing to verify (this is the "
              "standing environment fault; see SURVEY.md caveat)" % state)
        print(json.dumps(report))
        return 0

    # 1. anchors
    anchors = collect_anchors()
    resolved, failed = {}, {}
    for path, sym in anchors:
        r = resolve_anchor(path, sym)
        (resolved if "resolved" in r else failed)["%s (%s)" % (path, sym)] = r
    report["anchors_total"] = len(anchors)
    report["anchors_resolved"] = len(resolved)
    report["anchors_failed"] = failed

    # 2. op-name diff
    ref_ops = reference_op_names()
    sys.path.insert(0, REPO)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("MX_FORCE_CPU", "1")
    from mxnet_tpu.ops import registry
    ours = set(registry.list_ops())
    report["ref_op_count"] = len(ref_ops)
    report["our_op_count"] = len(ours)
    report["ops_missing_here"] = sorted(ref_ops - ours)[:500]
    report["ops_extra_here"] = sorted(ours - ref_ops)[:500]

    # 3. serialization fixtures
    fixtures = onnx_like_fixture_paths()
    report["serialization_fixtures_found"] = len(fixtures)
    ser_ok, ser_bad = [], []
    for p in fixtures[:20]:
        try:
            if p.endswith(".params"):
                import mxnet_tpu as mx
                mx.nd.load(p)
            else:
                import mxnet_tpu as mx
                mx.sym.load(p)
            ser_ok.append(p)
        except Exception as e:  # noqa: BLE001 - report, don't crash
            ser_bad.append({"file": p, "error": str(e)[:200]})
    report["serialization_ok"] = ser_ok
    report["serialization_failed"] = ser_bad

    out = None
    if "--json" in sys.argv:
        out = sys.argv[sys.argv.index("--json") + 1]
        with open(out, "w") as f:
            json.dump(report, f, indent=1)
    print(json.dumps({k: (v if not isinstance(v, (list, dict)) or k in
                          ("anchors_failed",) else
                          (len(v) if isinstance(v, list) else v))
                      for k, v in report.items()}, default=str)[:4000])
    return 0


if __name__ == "__main__":
    sys.exit(main())
