"""Validate/convert a dataset drop into the layout the parity gates and
benches expect (VERDICT r4 next-item #4; the activation contract of
tests/test_real_data.py and bench.py --real-data).

One command turns "I have the files somewhere" into "the gates run":

    python tools/prepare_data.py --check  /data       # validate only
    python tools/prepare_data.py /downloads /data      # convert + layout

Expected layout under the target MX_DATA_DIR (documented in
tests/test_real_data.py):

  mnist/train-images-idx3-ubyte(.gz)   + train-labels / t10k images+labels
  ptb/ptb.train.txt + ptb.valid.txt
  voc/VOC2007/Annotations/*.xml                 (SSD config 4)
  voc/VOC2007/JPEGImages/*.jpg
  voc/VOC2007/ImageSets/Main/trainval.txt + test.txt
  imagenet/train.rec (+ train.idx)              (optional: bench configs)

Conversions performed (source dir searched recursively):
  - idx/ptb/voc files found anywhere are hard-linked/copied into place;
  - a directory of class-subdirectory images is packed into train.rec
    via tools/im2rec.py (the reference's im2rec flow);
  - .gz idx files are accepted as-is (the readers decompress).
"""
import argparse
import glob
import gzip
import os
import shutil
import struct
import sys

MNIST_FILES = ("train-images-idx3-ubyte", "train-labels-idx1-ubyte",
               "t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte")
PTB_FILES = ("ptb.train.txt", "ptb.valid.txt")


def _find(root, name):
    hits = glob.glob(os.path.join(root, "**", name), recursive=True) + \
        glob.glob(os.path.join(root, "**", name + ".gz"), recursive=True)
    return hits[0] if hits else None


def _place(src, dst):
    os.makedirs(os.path.dirname(dst), exist_ok=True)
    if os.path.abspath(src) == os.path.abspath(dst):
        return
    try:
        os.link(src, dst)
    except OSError:
        shutil.copy2(src, dst)


def _check_idx_magic(path, want_dims):
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
    dims = magic & 0xFF
    if dims != want_dims:
        return "bad idx magic in %s: %d dims, want %d" % (path, dims,
                                                          want_dims)
    return None


def check(target):
    """Validate the layout; returns a list of problems (empty = ready)."""
    problems = []
    mnist_ok = True
    for name in MNIST_FILES:
        p = os.path.join(target, "mnist", name)
        hit = p if os.path.exists(p) else (
            p + ".gz" if os.path.exists(p + ".gz") else None)
        if hit is None:
            problems.append("mnist: missing %s(.gz)" % name)
            mnist_ok = False
        else:
            err = _check_idx_magic(hit, 3 if "images" in name else 1)
            if err:
                problems.append(err)
                mnist_ok = False
    if mnist_ok:
        print("mnist: OK (config 0 accuracy gate will run)")
    ptb_ok = True
    for name in PTB_FILES:
        p = os.path.join(target, "ptb", name)
        if not os.path.exists(p):
            problems.append("ptb: missing %s" % name)
            ptb_ok = False
        elif os.path.getsize(p) < 1000:
            problems.append("ptb: %s is suspiciously small" % name)
            ptb_ok = False
    if ptb_ok:
        print("ptb: OK (config 3 perplexity gate will run)")
    voc = os.path.join(target, "voc", "VOC2007")
    if os.path.isdir(voc):
        voc_ok = True
        for sub in ("Annotations", "JPEGImages"):
            d = os.path.join(voc, sub)
            if not os.path.isdir(d) or not os.listdir(d):
                problems.append("voc: %s/ empty or missing" % sub)
                voc_ok = False
        for split in ("trainval.txt", "test.txt"):
            if not os.path.exists(os.path.join(voc, "ImageSets", "Main",
                                               split)):
                problems.append("voc: ImageSets/Main/%s missing" % split)
                voc_ok = False
        if voc_ok:
            n = len(os.listdir(os.path.join(voc, "JPEGImages")))
            print("voc: OK, %d images (config 4 SSD mAP gate will run)"
                  % n)
    else:
        print("voc: absent (config 4 SSD gate stays skipped)")
    rec = os.path.join(target, "imagenet", "train.rec")
    if os.path.exists(rec):
        print("imagenet: train.rec present (%d MB)"
              % (os.path.getsize(rec) >> 20))
    else:
        print("imagenet: absent (resnet bench keeps its synthetic pack)")
    return problems


def convert(source, target):
    """Pull recognizable files out of `source` into the target layout."""
    for name in MNIST_FILES:
        hit = _find(source, name)
        if hit:
            base = os.path.basename(hit)
            _place(hit, os.path.join(target, "mnist", base))
    for name in PTB_FILES:
        hit = _find(source, name)
        if hit:
            _place(hit, os.path.join(target, "ptb", name))
    # VOC: find an Annotations dir with its VOC2007 parent structure
    for anns in glob.glob(os.path.join(source, "**", "Annotations"),
                          recursive=True):
        vocroot = os.path.dirname(anns)
        for sub in ("Annotations", "JPEGImages", "ImageSets"):
            s = os.path.join(vocroot, sub)
            if os.path.isdir(s):
                d = os.path.join(target, "voc", "VOC2007", sub)
                if not os.path.isdir(d):
                    shutil.copytree(s, d)
        break
    # class-subdirectory image tree -> train.rec via im2rec
    rec_dst = os.path.join(target, "imagenet", "train.rec")
    if not os.path.exists(rec_dst):
        for cand in sorted(glob.glob(os.path.join(source, "*"))):
            if not os.path.isdir(cand):
                continue
            subdirs = [d for d in sorted(glob.glob(os.path.join(cand, "*")))
                       if os.path.isdir(d)]
            have_imgs = subdirs and any(
                glob.glob(os.path.join(subdirs[0], "*.jpg")) +
                glob.glob(os.path.join(subdirs[0], "*.jpeg")) +
                glob.glob(os.path.join(subdirs[0], "*.png")))
            if not have_imgs:
                continue
            os.makedirs(os.path.dirname(rec_dst), exist_ok=True)
            prefix = rec_dst[:-len(".rec")]
            import subprocess
            print("packing %s -> %s via im2rec" % (cand, rec_dst))
            subprocess.run(
                [sys.executable,
                 os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "im2rec.py"),
                 prefix, cand, "--quality", "90"],
                check=True)
            break


def main():
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("source", nargs="?",
                    help="directory to scan for raw downloads "
                         "(omit with --check)")
    ap.add_argument("target", nargs="?",
                    help="MX_DATA_DIR layout root to create/validate")
    ap.add_argument("--check", metavar="DIR",
                    help="validate an existing layout and exit")
    args = ap.parse_args()
    if args.check:
        problems = check(args.check)
        for p in problems:
            print("PROBLEM:", p)
        print("\nactivation: MX_DATA_DIR=%s python -m pytest "
              "tests/test_real_data.py" % args.check)
        return 1 if problems else 0
    if not (args.source and args.target):
        ap.error("need SOURCE TARGET (or --check DIR)")
    convert(args.source, args.target)
    problems = check(args.target)
    for p in problems:
        print("PROBLEM:", p)
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
