"""Op-inventory audit: what "N registered ops" means on each side.

The reference's ~1000 `NNVM_REGISTER_OP` entries are NOT ~1000 public
operators: the registry also carries `_backward_*` nodes (the hand-written
gradients this rebuild replaces with `jax.vjp`), cuDNN/oneDNN-internal
variants, and quantization glue.  This tool prints this repo's registry
grouped by family, and — when `/root/reference` is mounted — greps the
reference's registrations and classifies them, so the coverage claim is a
measured statement instead of a raw-count comparison.

Run:  python tools/op_inventory.py [--json out.json]
"""
import json
import os
import re
import sys
from collections import Counter

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REF = "/root/reference"
sys.path.insert(0, REPO)


def classify(name: str) -> str:
    if name.startswith("_backward"):
        return "backward (autodiff here)"
    if name.startswith(("_contrib_quantized_", "quantized_")) or \
            name.startswith(("_contrib_intgemm", "intgemm")):
        return "quantized/intgemm"
    if "mkldnn" in name or "cudnn" in name or name.startswith("_sg_"):
        return "cudnn/onednn internal (XLA here)"
    if name.startswith(("_np", "_npi", "_npx")):
        return "numpy internal"
    if name.startswith(("_random_", "_sample_", "sample_", "random_")):
        return "random"
    if name.startswith("_image") or name.startswith("image_") or \
            name.startswith("_cv"):
        return "image"
    if name.startswith("_contrib_"):
        return "contrib"
    if name.endswith("_update") or name.startswith(
            ("multi_", "preloaded_", "mp_", "_sparse_")):
        return "optimizer/fused"
    if name.startswith(("linalg_", "_linalg")):
        return "linalg"
    if name.startswith(("broadcast_", "elemwise_", "_plus", "_minus",
                        "_mul", "_div", "_mod", "_power", "_maximum",
                        "_minimum")) or name.endswith("_scalar"):
        return "elemwise/broadcast/scalar"
    return "nn/tensor/other"


def our_inventory():
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("MX_FORCE_CPU", "1")
    from mxnet_tpu.ops import registry
    names = registry.list_ops()
    uniq = {}
    for n in names:
        uniq.setdefault(id(registry.get_op(n)), registry.get_op(n).name)
    groups = Counter(classify(n) for n in uniq.values())
    return {"registered_names": len(names), "unique_impls": len(uniq),
            "by_family": dict(groups.most_common())}


_REG_RE = re.compile(
    r"(?:NNVM_REGISTER_OP|MXNET_OPERATOR_REGISTER_\w+)\(\s*([\w.]+)\s*[),]")


def reference_inventory():
    try:
        entries = os.listdir(REF)
    except OSError:
        entries = []
    if not entries:
        return {"mount": "empty"}
    names = set()
    for root, _dirs, files in os.walk(REF):
        if "operator" not in root:
            continue
        for fn in files:
            if fn.endswith((".cc", ".cu", ".h")):
                try:
                    with open(os.path.join(root, fn),
                              errors="replace") as f:
                        for m in _REG_RE.finditer(f.read()):
                            names.add(m.group(1))
                except OSError:
                    pass
    groups = Counter(classify(n) for n in names)
    public = [n for n in names
              if classify(n) != "backward (autodiff here)"]
    return {"mount": "populated", "registered": len(names),
            "public_forward": len(public),
            "by_family": dict(groups.most_common())}


def main():
    report = {"ours": our_inventory(), "reference": reference_inventory()}
    print(json.dumps(report, indent=1))
    if "--json" in sys.argv:
        with open(sys.argv[sys.argv.index("--json") + 1], "w") as f:
            json.dump(report, f, indent=1)


if __name__ == "__main__":
    main()
