"""Definitive op-surface census (VERDICT r4 next-item #2).

The reference mount has been empty every round, so the expected-op list
below is VENDORED: it is the documented public v1.x operator surface,
assembled from the reference's published `mx.nd`/`mx.sym` API docs
(python/mxnet/ndarray/*.py + src/operator/** registrations as indexed by
SURVEY.md §2.1 "Dense op kernels") — every name a v1.x user could call.
When the mount materializes, `tools/verify_against_reference.py` diffs
this same registry against the real `NNVM_REGISTER_OP` set in minutes.

Classification per expected name:
  implemented        — resolvable in this repo's registry (exact name or
                       the registry's own alias convention)
  implemented-via    — not a registry kernel, but the feature exists at
                       the documented API level (cited)
  n/a-backward       — `_backward_*` graph nodes: replaced wholesale by
                       jax.vjp (SURVEY §2.1 maps these to autodiff)
  n/a-engine         — engine/FFI-internal registrations with no user
                       semantics on an XLA substrate
  MISSING            — a user-visible op with no counterpart: a real gap

Run:  python tools/op_census.py [--json OP_CENSUS.json]
Exit status 1 if any name classifies as MISSING.
"""
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# ---------------------------------------------------------------------------
# The vendored expected surface.  Grouped exactly as the v1.x docs group
# them; names are the reference's registration names (CamelCase for the
# layer-ops, lowercase for the tensor ops, _contrib_/_image_/_linalg_
# prefixes as registered).
# ---------------------------------------------------------------------------
EXPECTED = {
    "neural-network": [
        "Activation", "BatchNorm", "Convolution", "Convolution_v1",
        "Correlation", "Crop", "Deconvolution", "Dropout", "Embedding",
        "Flatten", "FullyConnected", "GridGenerator", "GroupNorm",
        "IdentityAttachKLSparseReg", "InstanceNorm", "L2Normalization",
        "LRN", "LayerNorm", "LeakyReLU", "LinearRegressionOutput",
        "LogisticRegressionOutput", "MAERegressionOutput", "MakeLoss",
        "Pad", "Pooling", "Pooling_v1", "RNN", "ROIPooling", "Reshape",
        "SVMOutput", "SequenceLast", "SequenceMask", "SequenceReverse",
        "SliceChannel", "Softmax", "SoftmaxActivation", "SoftmaxOutput",
        "SpatialTransformer", "SwapAxis", "UpSampling", "BilinearSampler",
        "BlockGrad", "CTCLoss", "Cast", "Concat", "ElementWiseSum",
        "Custom",
        "softmax", "log_softmax", "softmin", "masked_softmax",
        "masked_log_softmax", "softmax_cross_entropy", "smooth_l1",
        "make_loss", "stop_gradient", "ctc_loss", "moments", "hard_sigmoid",
    ],
    "basic-math": [
        "abs", "sign", "round", "rint", "ceil", "floor", "trunc", "fix",
        "square", "sqrt", "rsqrt", "cbrt", "rcbrt", "exp", "expm1", "log",
        "log10", "log2", "log1p", "erf", "erfinv", "gamma", "gammaln",
        "logical_not", "reciprocal", "negative", "degrees", "radians",
        "sin", "cos", "tan", "arcsin", "arccos", "arctan", "sinh", "cosh",
        "tanh", "arcsinh", "arccosh", "arctanh", "relu", "sigmoid",
        "log_sigmoid", "mish", "softsign", "clip", "gelu", "erfc",
    ],
    "reduce": [
        "sum", "sum_axis", "mean", "prod", "nansum", "nanprod", "max",
        "max_axis", "min", "min_axis", "norm", "argmax", "argmin",
        "argmax_channel", "logsumexp",
    ],
    "broadcast-elemwise": [
        "broadcast_add", "broadcast_sub", "broadcast_mul", "broadcast_div",
        "broadcast_mod", "broadcast_power", "broadcast_maximum",
        "broadcast_minimum", "broadcast_hypot", "broadcast_equal",
        "broadcast_not_equal", "broadcast_greater", "broadcast_greater_equal",
        "broadcast_lesser", "broadcast_lesser_equal", "broadcast_logical_and",
        "broadcast_logical_or", "broadcast_logical_xor", "broadcast_axes",
        "broadcast_axis", "broadcast_to", "broadcast_like",
        "elemwise_add", "elemwise_sub", "elemwise_mul", "elemwise_div",
        "add_n", "maximum", "minimum", "hypot", "equal", "not_equal",
        "greater", "greater_equal", "lesser", "lesser_equal",
        "logical_and", "logical_or", "logical_xor",
    ],
    "scalar-arith": [
        "_plus_scalar", "_minus_scalar", "_rminus_scalar", "_mul_scalar",
        "_div_scalar", "_rdiv_scalar", "_mod_scalar", "_rmod_scalar",
        "_power_scalar", "_rpower_scalar", "_maximum_scalar",
        "_minimum_scalar", "_hypot_scalar", "_equal_scalar",
        "_not_equal_scalar", "_greater_scalar", "_greater_equal_scalar",
        "_lesser_scalar", "_lesser_equal_scalar", "_logical_and_scalar",
        "_logical_or_scalar", "_logical_xor_scalar", "_smooth_l1",
        "_add", "_sub", "_minus", "_mul", "_div", "_mod", "_power",
        "_maximum", "_minimum",
    ],
    "array-manipulation": [
        "cast", "reshape", "reshape_like", "flatten", "expand_dims",
        "split", "split_v2", "concat", "stack", "transpose", "swapaxes",
        "flip", "reverse", "depth_to_space", "space_to_depth", "diag",
        "tile", "repeat", "pad", "where", "gather_nd", "scatter_nd",
        "one_hot", "pick", "take", "batch_take", "slice", "slice_axis",
        "slice_like", "squeeze", "shape_array", "size_array", "sort",
        "argsort", "topk", "unravel_index", "ravel_multi_index",
        "fill_element_0index", "khatri_rao", "batch_dot", "dot", "shuffle",
        "searchsorted", "im2col", "col2im", "embedding",
        "sequence_mask", "sequence_last", "sequence_reverse", "roll",
    ],
    "creation": [
        "zeros_like", "ones_like", "_zeros", "_ones", "_full", "_eye",
        "_arange", "_linspace", "_histogram", "diag", "_copy", "_copyto",
        "_identity_with_attr_like_rhs",
    ],
    "random": [
        "_random_uniform", "_random_normal", "_random_gamma",
        "_random_exponential", "_random_poisson", "_random_negative_binomial",
        "_random_generalized_negative_binomial", "_random_randint",
        "_random_uniform_like", "_random_normal_like", "_random_gamma_like",
        "_random_exponential_like", "_random_poisson_like",
        "_random_negative_binomial_like",
        "_random_generalized_negative_binomial_like",
        "_sample_uniform", "_sample_normal", "_sample_gamma",
        "_sample_exponential", "_sample_poisson", "_sample_negative_binomial",
        "_sample_generalized_negative_binomial", "_sample_multinomial",
        "_sample_unique_zipfian", "_shuffle", "sample_multinomial",
        "multinomial",
    ],
    "sparse": [
        "cast_storage", "sparse_retain", "_sparse_dot",
        "_scatter_set_nd", "_scatter_elemwise_div", "_scatter_plus_scalar",
        "_scatter_minus_scalar",
    ],
    "optimizer-update": [
        "sgd_update", "sgd_mom_update", "mp_sgd_update", "mp_sgd_mom_update",
        "nag_mom_update", "mp_nag_mom_update", "ftml_update", "ftrl_update",
        "adam_update", "adamw_update", "mp_adamw_update",
        "lamb_update_phase1", "lamb_update_phase2", "mp_lamb_update_phase1",
        "mp_lamb_update_phase2", "rmsprop_update", "rmspropalex_update",
        "adagrad_update", "signsgd_update", "signum_update",
        "multi_sgd_update", "multi_sgd_mom_update", "multi_mp_sgd_update",
        "multi_mp_sgd_mom_update", "multi_all_finite", "multi_sum_sq",
        "multi_lars", "preloaded_multi_sgd_update",
        "preloaded_multi_sgd_mom_update", "preloaded_multi_mp_sgd_update",
        "preloaded_multi_mp_sgd_mom_update", "all_finite", "reset_arrays",
        "lars_update" ,
    ],
    "linalg": [
        "_linalg_gemm", "_linalg_gemm2", "_linalg_potrf", "_linalg_potri",
        "_linalg_trmm", "_linalg_trsm", "_linalg_sumlogdiag",
        "_linalg_syrk", "_linalg_gelqf", "_linalg_syevd", "_linalg_slogdet",
        "_linalg_det", "_linalg_inverse", "_linalg_extractdiag",
        "_linalg_extracttrian", "_linalg_makediag", "_linalg_maketrian",
    ],
    "image": [
        "_image_adjust_lighting", "_image_crop", "_image_flip_left_right",
        "_image_flip_top_bottom", "_image_normalize",
        "_image_random_brightness", "_image_random_color_jitter",
        "_image_random_contrast", "_image_random_flip_left_right",
        "_image_random_flip_top_bottom", "_image_random_hue",
        "_image_random_lighting", "_image_random_saturation",
        "_image_resize", "_image_to_tensor", "_cvimdecode", "_cvimread",
        "_cvimresize", "_cvcopyMakeBorder",
    ],
    "contrib": [
        "_contrib_AdaptiveAvgPooling2D", "_contrib_BilinearResize2D",
        "_contrib_BatchNormWithReLU", "_contrib_SyncBatchNorm",
        "_contrib_CTCLoss", "_contrib_DeformableConvolution",
        "_contrib_DeformablePSROIPooling",
        "_contrib_ModulatedDeformableConvolution", "_contrib_MultiBoxPrior",
        "_contrib_MultiBoxTarget", "_contrib_MultiBoxDetection",
        "_contrib_MultiProposal", "_contrib_PSROIPooling",
        "_contrib_Proposal", "_contrib_ROIAlign", "_contrib_RROIAlign",
        "_contrib_boolean_mask", "_contrib_box_iou", "_contrib_box_nms",
        "_contrib_box_encode", "_contrib_box_decode",
        "_contrib_bipartite_matching", "_contrib_allclose",
        "_contrib_arange_like", "_contrib_count_sketch", "_contrib_fft",
        "_contrib_ifft", "_contrib_dgl_adjacency",
        "_contrib_dgl_csr_neighbor_non_uniform_sample",
        "_contrib_dgl_csr_neighbor_uniform_sample",
        "_contrib_dgl_graph_compact", "_contrib_dgl_subgraph",
        "_contrib_div_sqrt_dim", "_contrib_dynamic_reshape",
        "_contrib_edge_id", "_contrib_getnnz", "_contrib_gradientmultiplier",
        "_contrib_group_adagrad_update", "_contrib_hawkesll",
        "_contrib_index_array", "_contrib_index_copy",
        "_contrib_interleaved_matmul_encdec_qk",
        "_contrib_interleaved_matmul_encdec_valatt",
        "_contrib_interleaved_matmul_selfatt_qk",
        "_contrib_interleaved_matmul_selfatt_valatt",
        "_contrib_intgemm_fully_connected", "_contrib_intgemm_maxabsolute",
        "_contrib_intgemm_prepare_data", "_contrib_intgemm_prepare_weight",
        "_contrib_intgemm_take_weight", "_contrib_mrcnn_mask_target",
        "_contrib_quadratic", "_contrib_quantize", "_contrib_quantize_v2",
        "_contrib_quantized_act", "_contrib_quantized_batch_norm",
        "_contrib_quantized_concat", "_contrib_quantized_conv",
        "_contrib_quantized_elemwise_add", "_contrib_quantized_elemwise_mul",
        "_contrib_quantized_embedding", "_contrib_quantized_flatten",
        "_contrib_quantized_fully_connected", "_contrib_quantized_pooling",
        "_contrib_requantize", "_contrib_round_ste", "_contrib_sign_ste",
        "_contrib_sldwin_atten_context", "_contrib_sldwin_atten_mask_like",
        "_contrib_sldwin_atten_score", "_contrib_calibrate_entropy",
        "_contrib_adamw_update", "_contrib_mp_adamw_update",
        "_contrib_multi_adamw_update", "_contrib_multi_mp_adamw_update",
        "_contrib_multi_lamb_update", "_contrib_multi_mp_lamb_update",
        "_contrib_multi_lans_update", "_contrib_multi_mp_lans_update",
    ],
    "control-flow": ["_foreach", "_while_loop", "_cond"],
    "amp": ["amp_cast", "amp_multicast"],
    "misc": [
        "_histogram", "bincount", "digitize", "interp", "diff", "cumsum",
        "cumprod", "cummax", "cummin", "cross", "trace", "tril", "triu",
        "nan_to_num", "isnan", "isinf", "isfinite", "copysign", "ldexp",
        "nextafter", "logaddexp", "heaviside", "i0", "sinc", "polygamma",
        "digamma", "gammainc", "gammaincc",
    ],
}

# `_backward_*` and engine-internal registrations: pattern-classified,
# mirroring the reference's internal buckets (SURVEY §2.1 maps the
# backward graph nodes to jax.vjp and the FFI/engine nodes to PJRT).
NA_BACKWARD_PREFIXES = ("_backward_",)
NA_ENGINE = {
    "_NDArray", "_Native", "_CachedOp", "_NoGradient", "_copyto",
    "_crossdevice_copy", "_cvcopyMakeBorder", "_set_value", "_onehot_encode",
    "_imdecode", "_broadcast_backward",
}

# Features that live at the documented API level rather than as registry
# kernels — each entry cites where the behavior lives in this repo.
IMPLEMENTED_VIA = {
    "Custom": "operator.py Custom — mx.nd.Custom(x, op_type=...) over "
              "pure_callback + custom_vjp (not a registry kernel: its "
              "dispatch is by op_type, not attrs)",
    "_foreach": "ops/control_flow.py foreach (mx.contrib.nd.foreach)",
    "_while_loop": "ops/control_flow.py while_loop",
    "_cond": "ops/control_flow.py cond",
    "sequence_last": "SequenceLast registry op",
    "_sparse_dot": "ndarray/sparse.py dot (CSR kernels)",
    "_scatter_set_nd": "NDArray.__setitem__ index writeback",
    "_scatter_elemwise_div": "rowsparse lazy-update path ("
                             "optimizer/optimizer.py sparse updates)",
    "_scatter_plus_scalar": "rowsparse lazy-update path",
    "_scatter_minus_scalar": "rowsparse lazy-update path",
    "lars_update": "multi_lars + sgd_mom_update composition "
                   "(optimizer/optimizer.py LARS)",
    "sample_multinomial": "_sample_multinomial alias",
    "_imdecode": "src/imdecode.cc + image/__init__.py imdecode",
}


def build_alias_candidates(name):
    """Registry resolution candidates for a reference name, following the
    registry's own alias conventions."""
    cands = [name]
    if name.startswith("_contrib_"):
        cands.append(name[len("_contrib_"):])
    if name.startswith("_image_"):
        cands.append(name[1:])                      # image_*
    if name.startswith("_linalg_"):
        cands.append(name[1:])                      # linalg_*
    if name.startswith("_random_"):
        cands.extend([name[1:], "random_" + name[len("_random_"):]])
    if name.startswith("_sample_"):
        cands.append("sample_" + name[len("_sample_"):])
    if name.startswith("_cv"):
        cands.extend([name[1:], name[1:] + "_op", name[3:]])
    if name.startswith("_") and not name.startswith("_np"):
        cands.append(name[1:])
    # CamelCase layer name -> snake registry kernel
    if name[:1].isupper():
        snake = "".join(("_" + c.lower() if c.isupper() else c)
                        for c in name).lstrip("_")
        cands.extend([snake, snake.replace("__", "_")])
    else:
        # ...and snake doc name -> CamelCase layer registration
        cands.append("".join(p.capitalize() for p in name.split("_")))
    # creation/copy ops carry an _op suffix in this registry (np shadowing)
    cands.extend([c + "_op" for c in list(cands) if not c.endswith("_op")])
    # scalar arith: _plus_scalar <-> plus_scalar etc
    return cands


def census():
    from mxnet_tpu.ops import registry as reg
    names = set(reg._REGISTRY.keys())

    rows = []
    missing = []
    for group, ops in EXPECTED.items():
        for op in ops:
            if any(op.startswith(p) for p in NA_BACKWARD_PREFIXES):
                rows.append((op, group, "n/a-backward", "jax.vjp"))
                continue
            if op in NA_ENGINE:
                rows.append((op, group, "n/a-engine", "PJRT/XLA substrate"))
                continue
            hit = next((c for c in build_alias_candidates(op)
                        if c in names), None)
            if hit is not None:
                rows.append((op, group, "implemented",
                             hit if hit != op else ""))
            elif op in IMPLEMENTED_VIA:
                rows.append((op, group, "implemented-via",
                             IMPLEMENTED_VIA[op]))
            else:
                rows.append((op, group, "MISSING", ""))
                missing.append(op)

    # registry-side stats
    uniq = {}
    for n, spec in reg._REGISTRY.items():
        fn = getattr(spec, "fn", None) or spec
        uniq.setdefault(id(fn), []).append(n)
    return rows, missing, len(names), len(uniq)


def main():
    rows, missing, n_names, n_unique = census()
    from collections import Counter
    by_status = Counter(r[2] for r in rows)
    out = {
        "expected_total": len(rows),
        "by_status": dict(by_status),
        "registry_names": n_names,
        "registry_unique_kernels": n_unique,
        "missing": missing,
        "rows": [{"op": r[0], "group": r[1], "status": r[2], "note": r[3]}
                 for r in rows],
    }
    if "--json" in sys.argv:
        path = sys.argv[sys.argv.index("--json") + 1]
        with open(path, "w") as f:
            json.dump(out, f, indent=1)
        print("wrote", path)
    print("expected surface: %d ops | %s" % (len(rows), dict(by_status)))
    print("registry: %d names / %d unique kernels"
          % (n_names, n_unique))
    if missing:
        print("MISSING (%d): %s" % (len(missing), " ".join(missing)))
        return 1
    print("MISSING: none")
    return 0


if __name__ == "__main__":
    sys.exit(main())
