#!/usr/bin/env python
"""chaos_fit.py — a tiny deterministic Module.fit job for supervisor chaos
runs (tests/test_supervisor.py, tools/chaos_smoke.sh).

Each rank trains the same seeded MLP on the same synthetic data with a
momentum optimizer and per-epoch checkpointing into a per-rank directory,
then dumps its final parameters to ``--out``.  Because everything is
seeded and the optimizer slot state rides the checkpoint sidecar, a rank
that is crashed (``--fault 'worker.step:crash:after=N'``), restarted by
``launch.py --restart on-failure`` and auto-resumed must land on exactly
the parameters of an uninterrupted run — which is what the callers
assert.
"""
import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("MX_FORCE_CPU", "1")

import numpy as np                                          # noqa: E402

import mxnet_tpu as mx                                      # noqa: E402
from mxnet_tpu import io as mio                             # noqa: E402
from mxnet_tpu.module import Module                         # noqa: E402


def _mlp():
    from mxnet_tpu import symbol as sym
    data = sym.Variable("data")
    h = sym.FullyConnected(data, sym.Variable("fc1_weight"),
                           sym.Variable("fc1_bias"), num_hidden=16)
    h = sym.Activation(h, act_type="relu")
    out = sym.FullyConnected(h, sym.Variable("fc2_weight"),
                             sym.Variable("fc2_bias"), num_hidden=3)
    return sym.SoftmaxOutput(out, sym.Variable("softmax_label"),
                             normalization="batch", name="softmax")


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--ckpt-dir", required=True,
                    help="checkpoint root; each rank uses <dir>/rank<r>")
    ap.add_argument("--out", default=None,
                    help="write final params to <out>.rank<r>.npz")
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batches", type=int, default=4)
    ap.add_argument("--batch-size", type=int, default=24)
    args = ap.parse_args()

    rank = os.environ.get("MX_PROCESS_ID", "0")
    rng = np.random.RandomState(0)
    n = args.batches * args.batch_size
    X = rng.randn(n, 8).astype(np.float32)
    Y = X[:, :3].argmax(axis=1).astype(np.float32)

    mx.random.seed(42)               # identical init across (re)starts
    mod = Module(_mlp(), context=mx.cpu())
    mod.fit(mio.NDArrayIter(X, Y, batch_size=args.batch_size),
            optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
            num_epoch=args.epochs,
            checkpoint_dir=os.path.join(args.ckpt_dir, "rank%s" % rank))

    if args.out:
        arg, _aux = mod.get_params()
        np.savez("%s.rank%s.npz" % (args.out, rank),
                 **{k: v.asnumpy() for k, v in arg.items()})
    # warm-respawn receipts (ISSUE 13): the supervisor's chaos smoke
    # greps these — a rank respawned with MX_COMPILE_CACHE must report
    # cache hits and near-zero compile wall-time
    from mxnet_tpu import compile_cache, programs
    cs = compile_cache.stats()
    summary = programs.program_summary()
    print("CHAOS_FIT_DONE rank %s cache_hits=%d cache_misses=%d "
          "compile_seconds=%.3f"
          % (rank, cs["hits"], cs["misses"],
             summary["compile_seconds_total"]), flush=True)


if __name__ == "__main__":
    main()
