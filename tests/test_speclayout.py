"""ISSUE 14: first-class sharded training through the SpecLayout API.

Pins the tentpole contracts:
  * SpecLayout resolution order — rules > Block.sharding_spec hook >
    kind defaults (embedding/linear on tp) > fsdp sheet-sharding, with
    divisibility degradation to replication;
  * the sharded CompiledStep is ONE donated jit whose loss trajectory
    EQUALS the replicated step's across mesh classes {dp×fsdp,
    dp×fsdp×tp} and optimizers (sgd-mom, adam) — sharding never changes
    results;
  * the int8 quantized exchange under fsdp (reduce-scatter grain,
    shard_map kernel, per-chip EF residuals) matches the replicated
    2-copy quantized trajectory exactly;
  * buffer_census() per-chip params+optimizer bytes drop ~linearly with
    the fsdp axis (within 15% of ideal at fsdp=2 and fsdp=4);
  * zero retraces after step 1 and the ≤2 dispatches/step budget (no
    hidden host-side gathers);
  * sharded↔replicated checkpoint portability via the per-leaf spec
    sidecar (save on dp×fsdp, resume on plain dp and vice versa, same
    parameter trajectory);
  * shard_params_tp stays a thin alias over the speclayout layer.
"""
import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd
from mxnet_tpu.engine import engine
from mxnet_tpu.gluon import nn
from mxnet_tpu.parallel import (SpecLayout, make_mesh, shard_params,
                                shard_params_tp, tp_alternation_specs)
from mxnet_tpu.parallel.speclayout import layout_from_env, parse_mesh_axes

RNG = np.random.RandomState(7)
X = RNG.randn(16, 8).astype(np.float32)
Y = RNG.randn(16, 4).astype(np.float32)
LOSS = gluon.loss.L2Loss()


def _devices(n=8):
    devs = jax.devices("cpu")
    if len(devs) < n:
        pytest.skip("needs %d fake devices" % n)
    return devs[:n]


def _layout(axes=("data", "fsdp"), shape=(-1, 2), rules=None):
    return SpecLayout.infer(
        make_mesh(axes=axes, shape=shape, devices=_devices()), rules=rules)


def _build(seed=0, opt="sgd", optp=None, compress=None, ctxs=None,
           kvstore="ici"):
    mx.random.seed(seed)
    net = nn.Sequential()
    net.add(nn.Dense(16, in_units=8, activation="relu"))
    net.add(nn.Dense(4, in_units=16))
    net.initialize(mx.init.Xavier(), ctx=ctxs)
    tr = gluon.Trainer(net.collect_params(), opt,
                       dict(optp or {"learning_rate": 0.05,
                                     "momentum": 0.9}),
                       kvstore=kvstore, compression_params=compress)
    return net, tr


def _traj(step, steps=4):
    out = []
    for _ in range(steps):
        loss = step.step(nd.array(X), nd.array(Y), batch_size=16)
        out.append(float(np.mean(loss.asnumpy())))
    assert step.compiled, step.fallback_reason
    return out


# -- resolution order ---------------------------------------------------------

def test_spec_defaults_linear_embedding_sheet():
    lay = _layout(axes=("data", "fsdp", "tp"), shape=(2, 2, 2))
    # Dense (out, in) weights: column-parallel tp × fsdp input shards
    assert tuple(lay.linear_spec((16, 8))) == ("tp", "fsdp")
    # embeddings: vocab axis carved by fsdp×tp
    assert tuple(lay.embedding_spec((32, 6))) == (("fsdp", "tp"),)
    # everything else sheet-shards its largest divisible dim on fsdp
    assert tuple(lay.sheet_spec((16,))) == ("fsdp",)
    assert tuple(lay.sheet_spec((7,))) == ()          # indivisible
    assert tuple(lay.batch_spec()) == (("data", "fsdp"),)
    # compute spec: fsdp dropped (the JIT all-gather), tp kept
    assert tuple(lay.compute_spec(P("tp", "fsdp"))) == ("tp",)
    assert tuple(lay.compute_spec(P(("fsdp", "tp")))) == ("tp",)


def test_spec_degrades_on_missing_axes():
    lay = _layout(axes=("data",), shape=(8,))
    assert tuple(lay.linear_spec((16, 8))) == ()
    assert tuple(lay.sheet_spec((16,))) == ()
    assert tuple(lay.batch_spec()) == ("data",)


def test_resolve_kind_defaults_from_block_tree():
    lay = _layout(axes=("data", "fsdp", "tp"), shape=(2, 2, 2))
    net = nn.Sequential()
    net.add(nn.Embedding(32, 16))
    net.add(nn.Dense(16, in_units=16))
    net.initialize(mx.init.Xavier())
    specs = lay.resolve(net)
    assert tuple(specs["0.weight"]) == (("fsdp", "tp"),)   # embedding
    assert tuple(specs["1.weight"]) == ("tp", "fsdp")      # linear
    assert tuple(specs["1.bias"]) == ("fsdp",)             # sheet


def test_block_sharding_spec_hook_overrides_defaults():
    lay = _layout(axes=("data", "fsdp", "tp"), shape=(2, 2, 2))

    class PinnedDense(nn.Dense):
        def sharding_spec(self, layout):
            return {"weight": P(None, "tp")}    # row-parallel, pinned

    net = nn.Sequential()
    net.add(PinnedDense(16, in_units=8))
    net.initialize(mx.init.Xavier())
    specs = lay.resolve(net)
    assert tuple(specs["0.weight"]) == (None, "tp")
    # bias untouched by the hook: default sheet
    assert tuple(specs["0.bias"]) == ("fsdp",)


def test_rules_beat_hook_and_defaults():
    lay = _layout(axes=("data", "fsdp", "tp"), shape=(2, 2, 2),
                  rules={"0.weight": P("fsdp", None)})

    class PinnedDense(nn.Dense):
        def sharding_spec(self, layout):
            return {"weight": P(None, "tp")}

    net = nn.Sequential()
    net.add(PinnedDense(16, in_units=8))
    net.initialize(mx.init.Xavier())
    specs = lay.resolve(net)
    # trailing Nones trim: P('fsdp') == P('fsdp', None) semantically
    assert tuple(specs["0.weight"]) == ("fsdp",)


def test_shard_params_tp_alias_is_speclayout():
    """The deprecated mesh.shard_params_tp entry point delegates to the
    speclayout layer (one source of truth) with the exact legacy
    semantics: col/row alternation, explicit-rule replication."""
    from mxnet_tpu.parallel import mesh as mesh_mod
    mesh = make_mesh(axes=("dp", "tp"), shape=(4, 2), devices=_devices())
    params = {"0.weight": jnp.zeros((8, 4)), "0.bias": jnp.zeros((8,)),
              "1.weight": jnp.zeros((4, 8))}
    specs = tp_alternation_specs(params, mesh)
    assert tuple(specs["0.weight"]) == ("tp", None)
    assert tuple(specs["1.weight"]) == (None, "tp")
    out = mesh_mod.shard_params_tp(params, mesh)
    for name, v in out.items():
        assert tuple(v.sharding.spec) == tuple(specs[name]), name
    src = mesh_mod.shard_params_tp.__doc__ or ""
    assert "Deprecated" in src


def test_shard_params_places_resolved_specs():
    lay = _layout(axes=("data", "fsdp"), shape=(-1, 2))
    params = {"emb.weight": jnp.zeros((32, 8)), "b": jnp.zeros((7,))}
    out = shard_params(params, lay)
    assert tuple(out["emb.weight"].sharding.spec) in (("fsdp",),
                                                      ("fsdp", None))
    assert tuple(out["b"].sharding.spec) == ()


# -- sharded step parity ------------------------------------------------------

_REF_TRAJ = {}


def _ref_traj(opt, optp):
    """One replicated-compiled reference trajectory per optimizer,
    shared across the mesh-class parametrizations (suite wall-time)."""
    key = opt
    if key not in _REF_TRAJ:
        net_r, tr_r = _build(opt=opt, optp=optp)
        _REF_TRAJ[key] = _traj(tr_r.make_compiled_step(net_r, LOSS))
    return _REF_TRAJ[key]


@pytest.mark.parametrize("opt,optp", [
    ("sgd", {"learning_rate": 0.05, "momentum": 0.9}),
    ("adam", {"learning_rate": 0.01}),
])
@pytest.mark.parametrize("axes,shape", [
    (("data", "fsdp"), (-1, 2)),
    (("data", "fsdp", "tp"), (2, 2, 2)),
])
def test_sharded_matches_replicated(opt, optp, axes, shape):
    ref = _ref_traj(opt, optp)
    net_s, tr_s = _build(opt=opt, optp=optp)
    got = _traj(tr_s.make_compiled_step(
        net_s, LOSS, layout=_layout(axes=axes, shape=shape)))
    np.testing.assert_allclose(ref, got, rtol=2e-4)
    # the parameters really live sharded (fsdp in at least one spec)
    shards = {k: getattr(p.data()._jax.sharding, "spec", None)
              for k, p in net_s.collect_params().items()}
    assert any("fsdp" in str(s) for s in shards.values()), shards


def test_sharded_int8_matches_replicated_quantized():
    """The reduce-scatter int8 exchange (shard_map grain, sharded EF
    residuals) must reproduce the replicated 2-copy quantized
    trajectory exactly — same bucket layout, same residual keys."""
    net_r, tr_r = _build(compress={"type": "int8"},
                         ctxs=[mx.cpu(0), mx.cpu(1)])
    ref = _traj(tr_r.make_compiled_step(net_r, LOSS), steps=5)
    for axes, shape in [(("data", "fsdp"), (-1, 2))]:
        net_s, tr_s = _build(compress={"type": "int8"})
        step = tr_s.make_compiled_step(
            net_s, LOSS, layout=_layout(axes=axes, shape=shape))
        got = _traj(step, steps=5)
        np.testing.assert_allclose(ref, got, rtol=2e-4)
        # EF residuals live SHARDED per chip at the padded rs grain
        plan = step._plan()
        assert plan["exchange"] is not None
        assert plan["residual_shardings"], "no residual shardings"
        gc_store = plan["gc"]
        wk, shp, _dt = plan["exchange"].residual_specs[0]
        res = gc_store.peek_residual(wk, shp)
        spec = tuple(res.sharding.spec)
        assert spec == ("fsdp",), spec
        assert shp[0] % (256 * dict(step._layout.mesh.shape)["fsdp"]) == 0


def test_sharded_window_matches_per_step():
    lay = _layout()
    net_w, tr_w = _build()
    step_w = tr_w.make_compiled_step(net_w, LOSS, layout=lay)
    Xw = np.stack([X] * 3)
    Yw = np.stack([Y] * 3)
    win = step_w.run_window(nd.array(Xw), nd.array(Yw))
    win_losses = np.mean(np.asarray(win.asnumpy()).reshape(3, -1), axis=1)
    net_p, tr_p = _build()
    per = _traj(tr_p.make_compiled_step(net_p, LOSS, layout=lay), steps=3)
    np.testing.assert_allclose(win_losses, per, rtol=2e-4)


def test_metric_folds_into_sharded_step():
    lay = _layout()
    net, tr = _build()
    metric = mx.metric.MSE()
    step = tr.make_compiled_step(net, LOSS, metric=metric, layout=lay)
    for _ in range(3):
        step.step(nd.array(X), nd.array(Y), batch_size=16)
    name, val = metric.get()
    assert np.isfinite(val) and val > 0


# -- budgets: dispatches, retraces, per-chip bytes ---------------------------

def test_dispatch_budget_and_zero_retraces_after_step1():
    from mxnet_tpu import programs
    lay = _layout()
    net, tr = _build(compress={"type": "int8"})
    step = tr.make_compiled_step(net, LOSS, layout=lay)
    step.step(nd.array(X), nd.array(Y), batch_size=16)     # trace
    rec = programs.find_record("step.step")
    retr0 = rec.retraces if rec is not None else 0
    for _ in range(3):
        c0 = engine.snapshot()["dispatches"]
        step.step(nd.array(X), nd.array(Y), batch_size=16)
        d = engine.snapshot()["dispatches"] - c0
        assert d <= 2, "sharded step took %d dispatches (budget 2)" % d
    rec = programs.find_record("step.step")
    retr1 = rec.retraces if rec is not None else 0
    assert retr1 == retr0, "sharded step retraced after step 1"


def test_census_per_chip_drops_linearly_with_fsdp():
    """ISSUE 14 acceptance: buffer_census() per-chip params+optimizer
    bytes within 15% of the ideal 1/fsdp drop at fsdp=2 and fsdp=4."""
    import gc as _gc
    from mxnet_tpu import programs

    def run(fsdp):
        _gc.collect()
        before = programs.buffer_census()
        net, tr = _build()
        lay = None if fsdp == 1 else _layout(shape=(-1, fsdp))
        step = tr.make_compiled_step(net, LOSS, layout=lay)
        step.step(nd.array(X), nd.array(Y), batch_size=16)
        _gc.collect()
        after = programs.buffer_census()
        chip = sum(max(0, after[o]["bytes_per_chip"]
                       - before[o]["bytes_per_chip"])
                   for o in ("params", "optimizer_state"))
        return chip, net, tr, step      # keep alive until measured

    base, *_k1 = run(1)
    del _k1
    for fsdp in (2, 4):
        chip, *_k = run(fsdp)
        del _k
        ratio = base / max(1, chip)
        assert ratio >= 0.85 * fsdp, \
            "fsdp=%d: per-chip %d vs replicated %d is %.2fx " \
            "(ideal %dx, 15%% band)" % (fsdp, chip, base, ratio, fsdp)
        # and not mysteriously MORE than ideal (would mean lost buffers)
        assert ratio <= 1.15 * fsdp, (ratio, fsdp)


def test_external_mutation_picked_up_sharded():
    """set_data between sharded steps is re-placed and used (NDArray
    chunks stay the source of truth, same as the replicated lane)."""
    lay = _layout()
    net, tr = _build()
    step = tr.make_compiled_step(net, LOSS, layout=lay)
    step.step(nd.array(X), nd.array(Y), batch_size=16)
    p = list(net.collect_params().values())[0]
    p.set_data(nd.zeros(p.shape))
    step.step(nd.array(X), nd.array(Y), batch_size=16)
    # the zeroed weight moved off zero again (it was actually consumed)
    assert float(np.abs(p.data().asnumpy()).sum()) > 0


# -- checkpoint portability ---------------------------------------------------

def _state_of(net, tr):
    params = {k: p.data()._jax for k, p in net.collect_params().items()}
    upd = tr._updaters[0]
    states = {str(i): jax.tree_util.tree_map(
        lambda s: s._jax, upd.states[i],
        is_leaf=lambda s: isinstance(s, nd.NDArray))
        for i in upd.states}
    return {"params": params, "opt": states}


def _write_state(net, tr, state):
    ctx = tr._contexts[0]
    for k, p in net.collect_params().items():
        p._data[ctx]._set_jax(state["params"][k])
    upd = tr._updaters[0]
    for i in upd.states:
        new = state["opt"][str(i)]
        leaves_new = jax.tree_util.tree_leaves(new)
        leaves_old = jax.tree_util.tree_leaves(
            upd.states[i],
            is_leaf=lambda s: isinstance(s, nd.NDArray))
        for o, v in zip(leaves_old, leaves_new):
            o._set_jax(v)


@pytest.mark.parametrize("first", ["sharded", "replicated"])
def test_checkpoint_portability_sharded_vs_replicated(first, tmp_path):
    """Train 2 steps in one layout, save_sharded, resume in the OTHER
    layout, train 2 more: final params equal the uninterrupted 4-step
    replicated run within existing tolerances — and the restore
    re-shards by NAME from the saved sidecar."""
    from mxnet_tpu.checkpoint import (restore_sharded, save_sharded,
                                      saved_specs)
    lay = _layout()
    # "plain dp": each data-parallel worker holds the FULL value on its
    # one device — a 1-device mesh is that worker's view
    mesh_dp = make_mesh(axes=("dp",), devices=_devices()[:1])

    # uninterrupted reference
    net_u, tr_u = _build()
    step_u = tr_u.make_compiled_step(net_u, LOSS)
    _traj(step_u, steps=4)
    want = {k: p.data().asnumpy() for k, p in
            net_u.collect_params().items()}

    # phase 1
    net_a, tr_a = _build()
    step_a = tr_a.make_compiled_step(
        net_a, LOSS, layout=lay if first == "sharded" else None)
    _traj(step_a, steps=2)
    ck = os.path.join(str(tmp_path), "ck")
    save_sharded(ck, _state_of(net_a, tr_a))
    doc = saved_specs(ck)
    assert doc is not None and doc["schema"] == 1
    if first == "sharded":
        assert any(s for s in doc["leaf_specs"]), doc   # sharded leaves

    # phase 2 on the OTHER layout
    net_b, tr_b = _build(seed=1)    # different init: must be overwritten
    step_b = tr_b.make_compiled_step(
        net_b, LOSS, layout=None if first == "sharded" else lay)
    step_b._plan()                  # materialize state slots
    template = _state_of(net_b, tr_b)
    restore_mesh = mesh_dp if first == "sharded" else lay.mesh
    state = restore_sharded(ck, template=template, mesh=restore_mesh)
    if first == "replicated":
        # sidecar had replicated leaves -> restored replicated; the
        # sharded step re-places them on first dispatch
        pass
    _write_state(net_b, tr_b, state)
    _traj(step_b, steps=2)
    for k, p in net_b.collect_params().items():
        np.testing.assert_allclose(p.data().asnumpy(), want[k],
                                   rtol=2e-4, atol=1e-5)


def test_resume_or_init_mesh_kwarg(tmp_path):
    from mxnet_tpu.checkpoint import resume_or_init
    lay = _layout()
    sh = lay.sharding(P("fsdp"))
    direct = os.path.join(str(tmp_path), "mgr")

    def init_fn():
        return {"w": jnp.zeros((16,))}

    state, start, mgr = resume_or_init(direct, init_fn)
    assert start == 0
    mgr.save(0, {"w": jax.device_put(jnp.arange(16.0), sh)})
    state2, start2, _ = resume_or_init(direct, init_fn, mesh=lay.mesh,
                                       manager=mgr)
    assert start2 == 1
    np.testing.assert_array_equal(np.asarray(state2["w"]),
                                  np.arange(16.0))
    assert tuple(state2["w"].sharding.spec) == ("fsdp",)
    mgr.close()


# -- exchange body / contracts / env / tools ---------------------------------

def test_ici_exchange_body_layout_variant():
    from mxnet_tpu import kvstore as kvs
    lay = _layout(shape=(-1, 2))
    kv = kvs.create("ici")
    kv.set_gradient_compression({"type": "int8"})
    shapes = [(32,), (32, 8), (4,), (4, 32)]
    templates = [nd.array(np.zeros(s, np.float32)) for s in shapes]
    ex = kv.build_exchange_body(list(range(4)), templates, layout=lay)
    assert ex is not None
    # padded to the block×fsdp grain, residuals fsdp-sharded
    total = sum(int(np.prod(s)) for s in shapes)
    (wk, shp, _dt), = ex.residual_specs
    assert shp[0] >= total and shp[0] % (256 * 2) == 0
    (sh,) = ex.residual_shardings
    assert tuple(sh.spec) == ("fsdp",)
    # the body is pure and EXACT vs the replicated body on zero residual
    kv2 = kvs.create("ici")
    kv2.set_gradient_compression({"type": "int8"})
    ex2 = kv2.build_exchange_body(list(range(4)), templates)
    grads = [jnp.asarray(RNG.randn(*s).astype(np.float32))
             for s in shapes]
    o1, _r1 = jax.jit(lambda g, r: ex(g, r))(
        grads, [jnp.zeros(s, d) for _, s, d in ex.residual_specs])
    o2, _r2 = jax.jit(lambda g, r: ex2(g, r))(
        grads, [jnp.zeros(s, d) for _, s, d in ex2.residual_specs])
    for a, b in zip(o1, o2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-6)


def test_sharded_step_contract_declared():
    from mxnet_tpu import programs
    import mxnet_tpu.step  # noqa: F401  (declaring module)
    names = [c.name for c in programs.contracts()]
    assert "step.train_sharded" in names
    c = [c for c in programs.contracts()
         if c.name == "step.train_sharded"][0]
    assert c.donate_argnums == (0, 1, 2, 3, 4, 5)
    cases = c.build()
    # dp2/dp3/dp4 are elastic-resize coverage (ISSUE 16): every
    # data-parallel world a mid-job resize can land on is contracted
    assert sorted(case.label for case in cases) == \
        ["dp", "dp2", "dp3", "dp4", "dp_fsdp", "dp_fsdp_tp"]
    closure = c.closure()
    assert list(closure.points) == \
        ["dp", "dp2", "dp3", "dp4", "dp_fsdp", "dp_fsdp_tp"]


def test_parse_mesh_axes_and_layout_from_env(monkeypatch):
    assert parse_mesh_axes("data,fsdp=2,tp=2") == \
        (("data", "fsdp", "tp"), (-1, 2, 2))
    assert parse_mesh_axes("data,fsdp", fsdp_override=4) == \
        (("data", "fsdp"), (-1, 4))
    with pytest.raises(ValueError):
        parse_mesh_axes("")
    monkeypatch.delenv("MX_MESH_AXES", raising=False)
    monkeypatch.delenv("MX_FSDP", raising=False)
    assert layout_from_env() is None
    monkeypatch.setenv("MX_FSDP", "2")
    lay = layout_from_env()
    assert lay is not None and lay.fsdp == 2
    assert dict(lay.mesh.shape)["fsdp"] == 2
    monkeypatch.setenv("MX_MESH_AXES", "data,fsdp=2,tp=2")
    lay = layout_from_env()
    assert lay.tp == 2 and lay.fsdp == 2


def test_env_catalog_has_mesh_knobs():
    from mxnet_tpu.base import ENV_CATALOG
    assert "MX_MESH_AXES" in ENV_CATALOG
    assert "MX_FSDP" in ENV_CATALOG


def test_dispatch_count_mesh_budget():
    import importlib
    import tools.dispatch_count as dc
    importlib.reload(dc)
    report = dc.run_compiled(n_steps=2, mesh="data,fsdp")
    assert report["ok"], report
    assert report["mesh"] == "data,fsdp"
    assert report["single_step_dispatches"] <= 2


def test_census_reports_bytes_per_chip_fields():
    from mxnet_tpu import programs
    c = programs.buffer_census()
    assert "total_bytes_per_chip" in c
    for owner in ("params", "optimizer_state", "other"):
        assert "bytes_per_chip" in c[owner]
        assert c[owner]["bytes_per_chip"] <= max(c[owner]["bytes"], 1)


def test_sharded_checkpoint_sidecar_json_shape(tmp_path):
    from mxnet_tpu.checkpoint import save_sharded, _sidecar_path
    lay = _layout()
    state = {"w": jax.device_put(jnp.zeros((16, 4)),
                                 lay.sharding(P(None, "fsdp")))}
    p = os.path.join(str(tmp_path), "ck")
    save_sharded(p, state)
    with open(_sidecar_path(p)) as f:
        doc = json.load(f)
    assert doc["schema"] == 1
    assert doc["mesh_axes"] == {"data": 4, "fsdp": 2}
    assert doc["leaf_specs"] == [[None, "fsdp"]]
