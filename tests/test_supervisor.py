"""Supervised elastic launch (ISSUE 2): process supervisor + health guards.

Chaos-marked, tier-1 resident.  The ladder, bottom-up:

  * health.Watchdog expiry math on the fault module's VIRTUAL clock
    (zero real sleeps — the acceptance's detection-latency bound)
  * health.dump_all_stacks / Heartbeat / GradientGuard / StepGuard units
  * MX_NAN_POLICY wired through Module.fit: skip_batch keeps params
    finite over a poisoned batch, raise fails fast, default propagates
  * launch.Supervisor (imported from tools/launch.py): restart with the
    original env, RetryPolicy backoff schedule under virtual time,
    budget exhaustion → whole-job teardown, restart=never back-compat,
    heartbeat-staleness kill+restart, graceful server STOP + exit-code
    folding
  * end-to-end through the CLI: `launch.py -n 2 --restart on-failure`
    with an armed `worker.step:crash:after=N` spec finishes exit 0 and
    the resumed ranks' params match an uninterrupted run; an injected
    hang (delay spec) is converted into a restart by the
    MX_STEP_TIMEOUT watchdog (exit 86)

The subprocess scripts that don't need the framework (markers, hangs,
fake PS) stay framework-free so the supervisor unit tests run in
milliseconds; only the two acceptance tests pay real jax startup.
"""
import importlib.util
import io as _stringio
import os
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import fault, health
from mxnet_tpu.base import MXNetError

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _clean_faults():
    fault.clear()
    yield
    fault.clear()


def _load_launch():
    spec = importlib.util.spec_from_file_location(
        "mx_launch_under_test", os.path.join(REPO, "tools", "launch.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


launch = _load_launch()


def _no_jitter_backoff(base=0.5):
    return fault.RetryPolicy(deadline=float("inf"), base=base,
                             max_delay=8.0, jitter=0.0)


# ---------------------------------------------------------------------------
# Watchdog (virtual clock — no real sleeps)
# ---------------------------------------------------------------------------

def test_watchdog_expiry_on_virtual_clock():
    """Expiry math runs on fault.now(): petted at t, expired strictly
    after t+timeout; detection poll defaults to <= timeout so the
    in-process detection latency stays within 2x MX_STEP_TIMEOUT."""
    fired = []
    with fault.use_virtual_time() as clk:
        wd = health.Watchdog(2.0, on_timeout=lambda: fired.append(True))
        assert not wd.expired()            # never petted: disarmed
        wd.pet()
        clk.advance(1.9)
        assert not wd.check() and not fired
        wd.pet()                           # progress resets the window
        clk.advance(1.9)
        assert not wd.expired()
        clk.advance(0.2)                   # 2.1s since last pet
        assert wd.expired()
    assert wd.poll <= wd.timeout           # poll tick bounds detection
    assert wd.timeout + wd.poll <= 2 * wd.timeout


def test_watchdog_fires_once_and_dumps_stacks(capsys):
    fired = []
    with fault.use_virtual_time() as clk:
        wd = health.Watchdog(1.0, on_timeout=lambda: fired.append(True))
        wd.pet()
        clk.advance(1.5)
        assert wd.check() is True
        assert wd.check() is False         # latched: fires exactly once
    assert fired == [True]
    err = capsys.readouterr().err
    assert "MX_STEP_TIMEOUT" in err
    assert "MainThread" in err             # all-threads stack dump


def test_watchdog_suspend_disarms_between_epochs():
    with fault.use_virtual_time() as clk:
        wd = health.Watchdog(1.0, on_timeout=lambda: None)
        wd.pet()
        wd.suspend()                       # eval/checkpoint phase
        clk.advance(100.0)
        assert not wd.expired()


def test_watchdog_rejects_nonpositive_timeout():
    with pytest.raises(ValueError):
        health.Watchdog(0.0)


def test_dump_all_stacks_names_live_threads():
    ready = threading.Event()
    release = threading.Event()

    def parked():
        ready.set()
        release.wait(timeout=10)

    t = threading.Thread(target=parked, name="parked-thread")
    t.start()
    ready.wait(timeout=10)
    buf = _stringio.StringIO()
    try:
        health.dump_all_stacks(buf)
    finally:
        release.set()
        t.join()
    out = buf.getvalue()
    assert "parked-thread" in out and "MainThread" in out
    assert "release.wait" in out           # the parked frame is visible


# ---------------------------------------------------------------------------
# GradientGuard / Heartbeat / StepGuard
# ---------------------------------------------------------------------------

def _grads(**named):
    return [(k, None if v is None else mx.nd.array(np.asarray(v)))
            for k, v in named.items()]


def test_nonfinite_grads_finds_nan_and_inf():
    bad = health.nonfinite_grads(_grads(
        a=[1.0, 2.0], b=[np.nan, 1.0], c=[np.inf], fixed=None))
    assert bad == ["b", "c"]


def test_gradient_guard_policies():
    ok = _grads(w=[1.0])
    poisoned = _grads(w=[np.nan])
    g = health.GradientGuard("warn")
    assert g.allow_update(poisoned) is True        # warn: apply anyway
    assert g.nan_events == 1
    g = health.GradientGuard("skip_batch")
    assert g.allow_update(ok) is True
    assert g.allow_update(poisoned) is False
    assert (g.nan_events, g.skipped_batches) == (1, 1)
    g = health.GradientGuard("raise")
    with pytest.raises(MXNetError) as ei:
        g.allow_update(poisoned)
    assert "MX_NAN_POLICY" in str(ei.value)
    assert health.GradientGuard("").allow_update(poisoned) is True
    with pytest.raises(ValueError):
        health.GradientGuard("bogus")


def test_heartbeat_beats_atomically(tmp_path):
    hb = health.Heartbeat(str(tmp_path / "sub" / "rank_0"))
    hb.beat(epoch=3, nbatch=7)
    # line 1 keeps the classic `<unix-time> <epoch> <batch>` beat; line
    # 2, when telemetry has recorded a step in this process, is the
    # flight recorder's latest record as JSON (ISSUE 8)
    with open(hb.path) as f:
        lines = f.read().splitlines()
    ts, epoch, nbatch = lines[0].split()
    assert abs(float(ts) - time.time()) < 60
    assert (epoch, nbatch) == ("3", "7")
    if len(lines) > 1:
        import json
        assert "step" in json.loads(lines[1])
    hb.beat(epoch=3, nbatch=8)                     # rewrite, not append
    with open(hb.path) as f:
        assert len(f.read().splitlines()) <= 2
    hb.remove()
    assert not os.path.exists(hb.path)


def test_step_guard_from_env(monkeypatch, tmp_path):
    monkeypatch.setenv("MX_NAN_POLICY", "skip_batch")
    monkeypatch.setenv("MX_HEARTBEAT_FILE", str(tmp_path / "hb"))
    monkeypatch.delenv("MX_STEP_TIMEOUT", raising=False)
    guard = health.StepGuard.from_env()
    try:
        assert guard.armed
        assert guard.grad_guard.policy == "skip_batch"
        assert guard.watchdog is None
        guard.batch_end(0, 0)
        assert os.path.exists(str(tmp_path / "hb"))
    finally:
        guard.close()
    for var in ("MX_NAN_POLICY", "MX_HEARTBEAT_FILE"):
        monkeypatch.delenv(var)
    unarmed = health.StepGuard.from_env()
    assert not unarmed.armed
    unarmed.close()


# ---------------------------------------------------------------------------
# MX_NAN_POLICY through Module.fit
# ---------------------------------------------------------------------------

def _poisoned_data():
    rng = np.random.RandomState(0)
    X = rng.randn(48, 8).astype(np.float32)
    X[24:30] = np.nan                    # batch 1 (of batch_size 24)
    Y = np.zeros(48, np.float32)
    return X, Y


def _mlp():
    from mxnet_tpu import symbol as sym
    data = sym.Variable("data")
    h = sym.FullyConnected(data, sym.Variable("fc1_weight"),
                           sym.Variable("fc1_bias"), num_hidden=16)
    h = sym.Activation(h, act_type="relu")
    out = sym.FullyConnected(h, sym.Variable("fc2_weight"),
                             sym.Variable("fc2_bias"), num_hidden=3)
    return sym.SoftmaxOutput(out, sym.Variable("softmax_label"),
                             normalization="batch", name="softmax")


def _fit_poisoned(monkeypatch, policy):
    from mxnet_tpu import io as mio
    from mxnet_tpu.module import Module
    if policy is None:
        monkeypatch.delenv("MX_NAN_POLICY", raising=False)
    else:
        monkeypatch.setenv("MX_NAN_POLICY", policy)
    X, Y = _poisoned_data()
    mod = Module(_mlp(), context=mx.cpu())
    mod.fit(mio.NDArrayIter(X, Y, batch_size=24), optimizer="sgd",
            optimizer_params={"learning_rate": 0.1}, num_epoch=2)
    arg, _ = mod.get_params()
    return {k: v.asnumpy() for k, v in arg.items()}


def test_nan_policy_skip_batch_keeps_params_finite(monkeypatch):
    """Acceptance: one NaN-poisoned batch per epoch; skip_batch drops
    exactly those updates and the parameters stay finite, while the
    unguarded default lets the NaNs take the weights."""
    params = _fit_poisoned(monkeypatch, "skip_batch")
    for k, v in params.items():
        assert np.isfinite(v).all(), k

    unguarded = _fit_poisoned(monkeypatch, None)
    assert any(not np.isfinite(v).all() for v in unguarded.values())


def test_nan_policy_skip_batch_clears_add_accumulators(monkeypatch,
                                                       caplog):
    """grad_req='add' accumulates into the executor's grad buffers; a
    skipped poisoned batch must purge its NaN sums or every later
    backward's += would stay non-finite and freeze training silently.
    Exactly one skip per epoch proves the clean batches recovered."""
    import logging as _logging
    from mxnet_tpu import io as mio
    from mxnet_tpu.module import Module
    monkeypatch.setenv("MX_NAN_POLICY", "skip_batch")
    X, Y = _poisoned_data()                # batch 1 of 2 is poisoned
    it = mio.NDArrayIter(X, Y, batch_size=24)
    mod = Module(_mlp(), context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
             for_training=True, grad_req="add")
    with caplog.at_level(_logging.WARNING):
        mod.fit(it, optimizer="sgd",
                optimizer_params={"learning_rate": 0.1}, num_epoch=3)
    arg, _ = mod.get_params()
    for k, v in arg.items():
        assert np.isfinite(v.asnumpy()).all(), k
    # 3 epochs x 1 poisoned batch — a dirty accumulator would have
    # dragged every subsequent batch into the skip count (5, not 3)
    assert "skipped 3 poisoned batch update(s)" in caplog.text


def test_nan_policy_raise_fails_the_rank_fast(monkeypatch):
    with pytest.raises(MXNetError) as ei:
        _fit_poisoned(monkeypatch, "raise")
    assert "non-finite gradient" in str(ei.value)


# ---------------------------------------------------------------------------
# Supervisor units (framework-free subprocess scripts: milliseconds each)
# ---------------------------------------------------------------------------

_MARKER_SCRIPT = textwrap.dedent("""
    import os, sys
    m = os.environ["MX_TEST_MARKER"]
    if os.path.exists(m):
        print("SECOND_RUN_OK", flush=True)
        sys.exit(0)
    open(m, "w").close()
    sys.exit(9)
""")


def _marker_env(path):
    env = dict(os.environ)
    env["MX_TEST_MARKER"] = str(path)
    return env


def test_supervisor_restarts_with_original_env_and_backoff(tmp_path):
    """First run crashes (exit 9), the restart reuses the frozen env and
    succeeds; the RetryPolicy backoff window elapsed on the VIRTUAL
    clock (deadline-scheduled — zero real sleeping, and the supervise
    loop stayed live throughout the window)."""
    sup = launch.Supervisor(restart="on-failure", max_restarts=3,
                            backoff=_no_jitter_backoff(base=2.0))
    sp = sup.add("rank 0", [sys.executable, "-c", _MARKER_SCRIPT],
                 _marker_env(tmp_path / "marker"))
    t0 = time.monotonic()
    with fault.use_virtual_time() as clk:
        rc = sup.run()
    assert rc == 0
    assert sp.restarts == 1 and sp.rc == 0
    assert sum(clk.sleeps) >= 2.0          # full backoff window honored
    assert time.monotonic() - t0 < 10      # ...without real sleeping it


def test_supervisor_budget_exhaustion_tears_down_whole_job(tmp_path):
    """A rank burning its budget escalates: the healthy long-running
    rank is killed too and the job exits with the failing rank's code."""
    sup = launch.Supervisor(restart="on-failure", max_restarts=1,
                            backoff=_no_jitter_backoff(base=0.01))
    bad = sup.add("rank 0", [sys.executable, "-c",
                             "import sys; sys.exit(5)"], dict(os.environ))
    slow = sup.add("rank 1", [sys.executable, "-c",
                              "import time; time.sleep(60)"],
                   dict(os.environ))
    t0 = time.monotonic()
    with fault.use_virtual_time():
        rc = sup.run()
    assert rc == 5
    assert bad.restarts == 1               # budget spent, then teardown
    assert not slow.alive()                # healthy rank reaped
    assert time.monotonic() - t0 < 30      # nowhere near the sleep(60)


def test_supervisor_restart_never_preserves_old_contract(tmp_path):
    """Default policy: no restarts, wait every worker, fold nonzero."""
    sup = launch.Supervisor(restart="never")
    bad = sup.add("rank 0", [sys.executable, "-c",
                             "import sys; sys.exit(2)"], dict(os.environ))
    ok = sup.add("rank 1", [sys.executable, "-c",
                            "print('fine')"], dict(os.environ))
    rc = sup.run()
    assert rc == 2
    assert bad.restarts == 0 and ok.rc == 0


def test_supervisor_hang_timeout_kills_and_restarts(tmp_path):
    """Heartbeat-file liveness: enforcement starts at the process's
    FIRST beat (a slow startup is never killed); the wedged first run
    beats once then stalls, is killed when the file goes stale past
    --hang-timeout, and the restart completes."""
    script = textwrap.dedent("""
        import os, sys, time
        m = os.environ["MX_TEST_MARKER"]
        if os.path.exists(m):
            sys.exit(0)
        open(m, "w").close()
        open(os.environ["MX_HEARTBEAT_FILE"], "w").close()  # one beat
        time.sleep(60)                     # ...then wedged
    """)
    hb = tmp_path / "hb_rank0"
    sup = launch.Supervisor(restart="on-failure", max_restarts=2,
                            hang_timeout=0.3, poll=0.05,
                            backoff=_no_jitter_backoff(base=0.01))
    env = _marker_env(tmp_path / "marker")
    env["MX_HEARTBEAT_FILE"] = str(hb)
    sp = sup.add("rank 0", [sys.executable, "-c", script], env,
                 heartbeat=str(hb))
    t0 = time.monotonic()
    with fault.use_virtual_time():         # backoff virtual; mtime real
        rc = sup.run()
    assert rc == 0
    assert sp.restarts == 1
    assert time.monotonic() - t0 < 30


def test_heartbeat_done_sentinel_disarms_hang_enforcement(tmp_path):
    """StepGuard.close() writes a final 'done' beat; the supervisor
    sees it and stops hang enforcement — a rank doing >hang-timeout of
    post-fit work (export, final eval) must not be killed healthy."""
    hb = tmp_path / "hb"
    guard = health.StepGuard(heartbeat_path=str(hb))
    guard.batch_end(0, 0)
    guard.close()
    assert open(str(hb)).read().strip().endswith("done")

    sup = launch.Supervisor(restart="on-failure", max_restarts=1,
                            hang_timeout=0.1, startup_grace=0.1)
    sp = sup.add("rank 0", [sys.executable, "-c",
                            "import time; time.sleep(30)"],
                 dict(os.environ), heartbeat=str(hb))
    sp.spawned_wall = time.time() - 100    # far past every window
    sp.proc = subprocess.Popen(sp.argv, env=sp.env)
    try:
        os.utime(str(hb), (time.time() - 100, time.time() - 100))
        sup._check_hang(sp)                # stale mtime, but 'done'
        assert sp.proc.poll() is None      # ...so it was NOT killed
    finally:
        sp.proc.kill()
        sp.proc.wait()


def test_step_guard_first_batch_compile_grace():
    """The watchdog arms only after the FIRST completed batch — batch
    0's jit compile (arbitrarily long) must not read as a hang, exactly
    like the supervisor's startup grace for the heartbeat file."""
    with fault.use_virtual_time() as clk:
        g = health.StepGuard(step_timeout=1.0, on_timeout=lambda: None)
        try:
            g.batch_start()                # batch 0: compiling
            clk.advance(100.0)
            assert not g.watchdog.expired()
            g.batch_end(0, 0)              # first batch landed: armed
            g.batch_start()
            clk.advance(1.5)
            assert g.watchdog.expired()
        finally:
            g.close()


def test_supervisor_startup_grace_bounds_wedged_spawn(tmp_path):
    """A (re)spawn that wedges BEFORE its first beat (no heartbeat file
    at all) is still detected — bounded by startup_grace, not never."""
    script = textwrap.dedent("""
        import os, sys, time
        m = os.environ["MX_TEST_MARKER"]
        if os.path.exists(m):
            sys.exit(0)
        open(m, "w").close()
        time.sleep(60)                     # wedged in startup: no beat
    """)
    sup = launch.Supervisor(restart="on-failure", max_restarts=2,
                            hang_timeout=0.2, startup_grace=0.5,
                            poll=0.05,
                            backoff=_no_jitter_backoff(base=0.01))
    sp = sup.add("rank 0", [sys.executable, "-c", script],
                 _marker_env(tmp_path / "marker"),
                 heartbeat=str(tmp_path / "hb"))
    t0 = time.monotonic()
    with fault.use_virtual_time():
        rc = sup.run()
    assert rc == 0
    assert sp.restarts == 1
    assert time.monotonic() - t0 < 30


_FAKE_PS = textwrap.dedent("""
    import os, pickle, socket, struct, sys
    srv = socket.socket()
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", int(os.environ["FAKE_PS_PORT"])))
    srv.listen(4)
    while True:
        c, _ = srv.accept()
        head = b""
        while len(head) < 8:
            chunk = c.recv(8 - len(head))
            if not chunk:
                break
            head += chunk
        if len(head) < 8:
            c.close()
            continue
        (n,) = struct.unpack("<Q", head)
        body = b""
        while len(body) < n:
            body += c.recv(n - len(body))
        msg = pickle.loads(body)
        payload = pickle.dumps((True, "stopping"))
        c.sendall(struct.pack("<Q", len(payload)) + payload)
        c.close()
        if msg[0] == "STOP":
            sys.exit(0)
""")


def test_supervisor_stops_servers_gracefully_and_folds_exit_codes():
    """Satellite: after the workers finish, servers get the
    wire-protocol STOP (not SIGTERM) and exit 0 — folded, not ignored."""
    port = launch._free_port()
    env = dict(os.environ)
    env["FAKE_PS_PORT"] = str(port)
    sup = launch.Supervisor(restart="never")
    server = sup.add("server 0", [sys.executable, "-c", _FAKE_PS], env,
                     role="server", addr="127.0.0.1:%d" % port)
    sup.add("rank 0", [sys.executable, "-c", "import time; time.sleep(0.5)"],
            dict(os.environ))
    rc = sup.run()
    assert rc == 0
    assert server.rc == 0 and not server.we_killed   # STOP, not SIGTERM


def test_supervisor_forgiven_server_crash_does_not_fail_job():
    """A server crash the restart policy accepted (respawn pending in
    its backoff window) must not resurface as the job's exit code when
    the workers finish first — success/failure can't be a race."""
    huge = fault.RetryPolicy(deadline=float("inf"), base=1e9,
                             max_delay=1e9, jitter=0.0)
    sup = launch.Supervisor(restart="on-failure", max_restarts=2,
                            backoff=huge)  # window outlasts the workers
    server = sup.add("server 0", [sys.executable, "-c",
                                  "import sys; sys.exit(17)"],
                     dict(os.environ), role="server", addr=None)
    sup.add("rank 0", [sys.executable, "-c", "import time; time.sleep(0.4)"],
            dict(os.environ))
    rc = sup.run()
    assert rc == 0
    assert server.rc == 0                  # forgiven, not folded


def test_supervisor_folds_server_crash_into_job_rc():
    """A server that dies nonzero mid-job fails the job under
    restart=never (the old launcher silently ignored server deaths)."""
    sup = launch.Supervisor(restart="never")
    sup.add("server 0", [sys.executable, "-c", "import sys; sys.exit(17)"],
            dict(os.environ), role="server", addr=None)
    sup.add("rank 0", [sys.executable, "-c", "import time; time.sleep(0.4)"],
            dict(os.environ))
    rc = sup.run()
    assert rc == 17


def test_launch_ssh_rejects_supervision_flags():
    """--hang-timeout reads a local heartbeat file, and --restart on an
    ssh client's exit could duplicate a still-live remote rank — both
    are local-launcher features; accepting and silently dropping them
    would fake protection."""
    class A:
        num_servers, num_workers, hostfile = 0, 1, None
        restart, max_restarts, hang_timeout = "never", 3, 5.0
    with pytest.raises(SystemExit, match="hang-timeout"):
        launch.launch_ssh(A(), ["true"])
    A.hang_timeout = None
    A.restart = "on-failure"
    with pytest.raises(SystemExit, match="restart"):
        launch.launch_ssh(A(), ["true"])


def test_restart_flag_parsing():
    class A:
        restart, max_restarts, hang_timeout = "2", 3, None
    sup = launch._make_supervisor(A())
    assert sup.restart == "on-failure" and sup.max_restarts == 2
    A.restart = "on-failure"
    assert launch._make_supervisor(A()).max_restarts == 3
    A.restart = "sometimes"
    with pytest.raises(SystemExit):
        launch._make_supervisor(A())


# ---------------------------------------------------------------------------
# End-to-end through the CLI (the acceptance demos; real jax startup)
# ---------------------------------------------------------------------------

def _clean_env(**extra):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)      # conftest's 8-dev count: workers pick own
    env.pop("MX_FAULT_INJECT", None)
    env.update(extra)
    return env


def _launch(argv, env, timeout=300):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py")] + argv,
        capture_output=True, text=True, timeout=timeout, env=env)


def test_launch_crash_restart_resumes_to_matching_params(tmp_path):
    """Acceptance: `launch.py -n 2 --restart on-failure` with an armed
    `worker.step:crash:after=5` spec — every rank dies mid-epoch-1, is
    restarted with its original env, auto-resumes from its epoch-0
    checkpoint (momentum sidecar included) and finishes exit 0 with
    final params IDENTICAL to an uninterrupted run."""
    fit = os.path.join(REPO, "tools", "chaos_fit.py")
    ref = _launch(["-n", "1", "--launcher", "local", "--",
                   sys.executable, fit,
                   "--ckpt-dir", str(tmp_path / "ref"),
                   "--out", str(tmp_path / "ref")], _clean_env())
    assert ref.returncode == 0, (ref.stdout, ref.stderr)

    r = _launch(["-n", "2", "--launcher", "local",
                 "--restart", "on-failure", "--max-restarts", "2",
                 "--fault", "worker.step:crash:after=5", "--",
                 sys.executable, fit,
                 "--ckpt-dir", str(tmp_path / "chaos"),
                 "--out", str(tmp_path / "chaos")], _clean_env())
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert "restart 1/" in r.stderr, r.stderr       # the crash really hit
    assert r.stdout.count("CHAOS_FIT_DONE") == 2

    want = np.load(str(tmp_path / "ref.rank0.npz"))
    for rank in (0, 1):
        got = np.load(str(tmp_path / ("chaos.rank%d.npz" % rank)))
        assert set(got.files) == set(want.files)
        for k in want.files:
            np.testing.assert_allclose(got[k], want[k], rtol=1e-5,
                                       atol=1e-6,
                                       err_msg="rank %d %s" % (rank, k))


def test_launch_watchdog_converts_hang_into_restart(tmp_path):
    """Acceptance: an injected hang (`worker.step:delay:delay=60`) is
    detected by the MX_STEP_TIMEOUT watchdog (stack dump + exit 86) and
    the supervisor restarts the rank, which resumes and completes."""
    fit = os.path.join(REPO, "tools", "chaos_fit.py")
    r = _launch(["-n", "1", "--launcher", "local",
                 "--restart", "on-failure", "--max-restarts", "2",
                 "--fault", "worker.step:delay:delay=60,after=5", "--",
                 sys.executable, fit,
                 "--ckpt-dir", str(tmp_path / "hang"),
                 "--out", str(tmp_path / "hang")],
                _clean_env(MX_STEP_TIMEOUT="1.0"))
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert "watchdog" in r.stderr                   # in-process detection
    assert "exit 86" in r.stderr                    # supervisor names it
    assert "MX_STEP_TIMEOUT watchdog" in r.stderr
    assert "CHAOS_FIT_DONE" in r.stdout
    got = np.load(str(tmp_path / "hang.rank0.npz"))
    assert all(np.isfinite(got[k]).all() for k in got.files)
